"""ZeRO sharding memory verification (VERDICT r1 item 6).

Asserts per-device live-buffer sizes actually drop ~1/sharding_degree at
each level ('os', 'os_g', 'p_g_os'), that stage-3 params remain usable
eagerly (gather-on-use), and loss parity vs unsharded training.
Reference: fleet/meta_optimizers/sharding_optimizer.py:43,118-138,
distributed/sharding/group_sharded.py.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu.distributed import fleet, topology
from paddle_tpu.distributed.fleet import DistributedStrategy
from paddle_tpu.distributed.sharding import group_sharded_parallel

DEG = 4


@pytest.fixture(autouse=True)
def _mesh():
    strategy = DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": 2, "mp_degree": 1,
                               "sharding_degree": DEG, "pp_degree": 1}
    fleet.init(is_collective=True, strategy=strategy)
    yield
    topology._HYBRID = None


def _model():
    paddle.seed(0)
    return nn.Sequential(nn.Linear(64, 128), nn.ReLU(),
                         nn.Linear(128, 64), nn.ReLU(),
                         nn.Linear(64, 8))


def _per_device_bytes(t):
    """Actual bytes held on ONE device for this tensor's array."""
    v = t._value
    return v.addressable_shards[0].data.nbytes


def _train(model, opt, steps=4):
    loss_fn = nn.CrossEntropyLoss()

    @paddle.jit.to_static
    def step(x, y):
        loss = loss_fn(model(x), y)
        loss.backward()
        opt.step()
        opt.clear_grad()
        return loss

    rng = np.random.RandomState(0)
    x = paddle.to_tensor(rng.randn(16, 64).astype("float32"))
    y = paddle.to_tensor(rng.randint(0, 8, (16,)).astype("int64"))
    return [float(step(x, y).numpy()) for _ in range(steps)]


def _state_tensors(opt):
    return [t for store in opt._accumulators.values()
            for t in store.values() if t.aval_shape()]


def _sharded_fraction(tensors):
    """sum(per-device bytes) / sum(full bytes) over tensors with >=DEG
    elements on their shardable dim."""
    full = sum(t._value.nbytes for t in tensors)
    per_dev = sum(_per_device_bytes(t) for t in tensors)
    return per_dev / full


def test_zero1_os_shards_optimizer_state():
    model = _model()
    opt = paddle.optimizer.Adam(1e-3, parameters=model.parameters())
    model, opt, _ = group_sharded_parallel(model, opt, level="os")
    losses = _train(model, opt)
    assert np.isfinite(losses).all() and losses[-1] < losses[0]
    moments = _state_tensors(opt)
    assert moments, "optimizer accumulated no state"
    frac = _sharded_fraction(moments)
    assert frac <= 1.2 / DEG, (
        f"optimizer state not sharded: per-device fraction {frac:.3f}, "
        f"expected ~{1 / DEG:.3f}")
    # params stay replicated at ZeRO-1
    p_frac = _sharded_fraction(list(model.parameters()))
    assert p_frac > 0.9


def test_zero2_os_g_shards_state_and_keeps_parity():
    # parity: identical init/data, sharded vs unsharded
    topology._HYBRID = None
    strategy = DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": 2, "mp_degree": 1,
                               "sharding_degree": DEG, "pp_degree": 1}
    fleet.init(is_collective=True, strategy=strategy)

    base = _model()
    base_opt = paddle.optimizer.Adam(1e-3, parameters=base.parameters())
    base_losses = _train(base, base_opt)

    model = _model()
    opt = paddle.optimizer.Adam(1e-3, parameters=model.parameters())
    model, opt, _ = group_sharded_parallel(model, opt, level="os_g")
    losses = _train(model, opt)

    np.testing.assert_allclose(losses, base_losses, rtol=2e-4, atol=2e-5)
    frac = _sharded_fraction(_state_tensors(opt))
    assert frac <= 1.2 / DEG


def test_zero3_p_g_os_shards_params_and_gathers_on_use():
    model = _model()
    opt = paddle.optimizer.Adam(1e-3, parameters=model.parameters())
    model, opt, _ = group_sharded_parallel(model, opt, level="p_g_os")

    # params are physically sharded immediately (before any step)
    p_frac = _sharded_fraction(list(model.parameters()))
    assert p_frac <= 1.2 / DEG, (
        f"stage-3 params not sharded: per-device fraction {p_frac:.3f}")

    losses = _train(model, opt)
    assert np.isfinite(losses).all() and losses[-1] < losses[0]

    # still sharded after compiled steps (outputs keep the placement)
    p_frac = _sharded_fraction(list(model.parameters()))
    assert p_frac <= 1.2 / DEG
    frac = _sharded_fraction(_state_tensors(opt))
    assert frac <= 1.2 / DEG

    # gather-on-use: eager forward on sharded params works and matches
    # itself deterministically
    model.eval()
    x = paddle.to_tensor(
        np.random.RandomState(3).randn(4, 64).astype("float32"))
    out1 = model(x).numpy()
    out2 = model(x).numpy()
    np.testing.assert_allclose(out1, out2, rtol=1e-6)
    assert np.isfinite(out1).all()


def test_invalid_level_rejected():
    model = _model()
    opt = paddle.optimizer.Adam(1e-3, parameters=model.parameters())
    with pytest.raises(ValueError):
        group_sharded_parallel(model, opt, level="zero9")
