"""Round-2 op additions (closing the 211-vs-707 registered-op gap):
linalg (lu, cholesky_solve, householder_product, eig, corrcoef, cov),
math (renorm, vander, logcumsumexp, trapezoid, cumulative_trapezoid,
polygamma, igamma), manipulation (moveaxis, index_add, index_fill,
tensordot, as_real/as_complex), search/stat (bincount, bucketize,
nanmedian, nanquantile). Reference: python/paddle/tensor/*.py +
operators/{lu,cholesky_solve,renorm,bincount,...}_op.
"""
import numpy as np
import pytest

import paddle_tpu as paddle


def T(a, dtype=None):
    return paddle.to_tensor(np.asarray(a, dtype=dtype))


def test_lu_reconstructs():
    rs = np.random.RandomState(0)
    a = rs.randn(4, 4).astype("float32")
    lu, piv = paddle.lu(T(a))
    lu_np, piv_np = np.asarray(lu.numpy()), np.asarray(piv.numpy())
    L = np.tril(lu_np, -1) + np.eye(4, dtype="float32")
    U = np.triu(lu_np)
    # apply recorded row swaps (1-based pivots)
    P = np.eye(4, dtype="float32")
    for i, p in enumerate(piv_np):
        P[[i, p - 1]] = P[[p - 1, i]]
    np.testing.assert_allclose(P @ a, L @ U, rtol=1e-4, atol=1e-5)


def test_lu_get_infos():
    a = np.eye(3, dtype="float32")
    lu, piv, info = paddle.lu(T(a), get_infos=True)
    assert np.asarray(info.numpy()).sum() == 0


def test_cholesky_solve():
    rs = np.random.RandomState(1)
    m = rs.randn(3, 3).astype("float32")
    a = m @ m.T + 3 * np.eye(3, dtype="float32")
    b = rs.randn(3, 2).astype("float32")
    L = np.linalg.cholesky(a).astype("float32")
    out = paddle.cholesky_solve(T(b), T(L), upper=False)
    np.testing.assert_allclose(np.asarray(out.numpy()),
                               np.linalg.solve(a, b), rtol=1e-3, atol=1e-4)


def test_eig_eigenvalues():
    a = np.diag([1.0, 2.0, 3.0]).astype("float32")
    w, v = paddle.eig(T(a))
    np.testing.assert_allclose(sorted(np.asarray(w.numpy()).real),
                               [1, 2, 3], rtol=1e-5)


def test_corrcoef_cov():
    rs = np.random.RandomState(2)
    x = rs.randn(3, 50).astype("float32")
    np.testing.assert_allclose(np.asarray(paddle.corrcoef(T(x)).numpy()),
                               np.corrcoef(x), rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(np.asarray(paddle.cov(T(x)).numpy()),
                               np.cov(x), rtol=1e-4, atol=1e-5)


def test_renorm_clamps_slices():
    x = np.asarray([[[3.0, 4.0]], [[0.3, 0.4]]], "float32")  # norms 5, .5
    out = np.asarray(paddle.renorm(T(x), p=2.0, axis=0,
                                   max_norm=1.0).numpy())
    np.testing.assert_allclose(np.sqrt((out[0] ** 2).sum()), 1.0, rtol=1e-5)
    np.testing.assert_allclose(out[1], x[1], rtol=1e-6)  # under the cap


def test_vander_logcumsumexp():
    x = np.asarray([1.0, 2.0, 3.0], "float32")
    np.testing.assert_allclose(np.asarray(paddle.vander(T(x)).numpy()),
                               np.vander(x), rtol=1e-6)
    v = np.asarray([0.1, 0.5, 2.0], "float32")
    out = np.asarray(paddle.logcumsumexp(T(v), axis=0).numpy())
    ref = np.log(np.cumsum(np.exp(v)))
    np.testing.assert_allclose(out, ref, rtol=1e-5)


def test_trapezoid_family():
    y = np.asarray([1.0, 2.0, 3.0, 4.0], "float32")
    np.testing.assert_allclose(
        float(paddle.trapezoid(T(y)).numpy()), np.trapezoid(y), rtol=1e-6)
    out = np.asarray(paddle.cumulative_trapezoid(T(y)).numpy())
    np.testing.assert_allclose(out, [1.5, 4.0, 7.5], rtol=1e-6)


def test_special_functions():
    x = np.asarray([0.5, 1.5], "float32")
    out = np.asarray(paddle.polygamma(T(x), 1).numpy())
    assert np.all(out > 0)  # trigamma positive
    ig = np.asarray(paddle.igamma(T(x), T([1.0, 1.0], "float32")).numpy())
    np.testing.assert_allclose(ig, 1 - np.exp(-x), rtol=1e-4)


def test_moveaxis_tensordot():
    x = np.arange(24, dtype="float32").reshape(2, 3, 4)
    np.testing.assert_array_equal(
        np.asarray(paddle.moveaxis(T(x), 0, 2).numpy()),
        np.moveaxis(x, 0, 2))
    a = np.random.RandomState(3).randn(2, 3).astype("float32")
    b = np.random.RandomState(4).randn(3, 4).astype("float32")
    np.testing.assert_allclose(
        np.asarray(paddle.tensordot(T(a), T(b), axes=1).numpy()),
        np.tensordot(a, b, axes=1), rtol=1e-5)


def test_index_add_fill():
    x = np.zeros((4, 2), "float32")
    idx = np.asarray([1, 3, 1])
    val = np.ones((3, 2), "float32")
    out = np.asarray(paddle.index_add(T(x), T(idx), 0, T(val)).numpy())
    np.testing.assert_allclose(out[1], [2, 2])  # duplicate accumulates
    np.testing.assert_allclose(out[3], [1, 1])
    np.testing.assert_allclose(out[0], [0, 0])
    out2 = np.asarray(paddle.index_fill(T(x), T(np.asarray([0, 2])), 0,
                                        5.0).numpy())
    np.testing.assert_allclose(out2[0], [5, 5])
    np.testing.assert_allclose(out2[1], [0, 0])


def test_as_real_complex_roundtrip():
    c = np.asarray([1 + 2j, 3 - 1j], "complex64")
    r = paddle.as_real(T(c))
    assert list(r.shape) == [2, 2]
    back = paddle.as_complex(r)
    np.testing.assert_allclose(np.asarray(back.numpy()), c)


def test_bincount_bucketize():
    x = np.asarray([1, 2, 2, 5])
    out = np.asarray(paddle.bincount(T(x)).numpy())
    np.testing.assert_array_equal(out, [0, 1, 2, 0, 0, 1])
    out2 = np.asarray(paddle.bincount(T(x), minlength=8).numpy())
    assert out2.shape[0] == 8
    edges = np.asarray([1.0, 2.0, 3.0], "float32")
    vals = np.asarray([0.5, 1.5, 2.5, 3.5], "float32")
    bk = np.asarray(paddle.bucketize(T(vals), T(edges)).numpy())
    np.testing.assert_array_equal(bk, [0, 1, 2, 3])


def test_nan_reductions():
    x = np.asarray([[1.0, np.nan, 3.0], [4.0, 5.0, np.nan]], "float32")
    assert float(paddle.nanmedian(T(x)).numpy()) == 3.5
    np.testing.assert_allclose(
        float(paddle.nanquantile(T(x), 0.5).numpy()), 3.5)


def test_renorm_grad_flows():
    x = paddle.to_tensor(np.ones((2, 3), "float32") * 2, stop_gradient=False)
    out = paddle.renorm(x, p=2.0, axis=0, max_norm=1.0)
    out.sum().backward()
    assert x.grad is not None and np.isfinite(x.grad.numpy()).all()


def test_floor_divide_truncates_toward_zero():
    """Reference FloorDivFunctor = std::trunc(a/b)
    (elementwise_floordiv_op.h:42) — NOT python floor division."""
    a = paddle.to_tensor(np.asarray([-7, 7, -7, 7], "int64"))
    b = paddle.to_tensor(np.asarray([2, 2, -2, -2], "int64"))
    out = paddle.floor_divide(a, b).numpy()
    assert list(out) == [-3, 3, 3, -3], out  # trunc, not floor (-4...)
    f = paddle.floor_divide(
        paddle.to_tensor(np.asarray([-7.0], "float32")),
        paddle.to_tensor(np.asarray([2.0], "float32"))).numpy()
    assert float(f[0]) == -3.0
    # INT_MIN must not overflow through an abs()
    m = paddle.floor_divide(
        paddle.to_tensor(np.asarray([-2 ** 31], "int32")),
        paddle.to_tensor(np.asarray([2], "int32"))).numpy()
    assert int(m[0]) == -2 ** 30, m


def test_divide_int_is_integer_division():
    """Reference DivFunctor: C a/b per dtype — int tensors divide to
    ints (test_elementwise_div_op.py:203)."""
    a = paddle.to_tensor(np.asarray([7, -7, 9], "int64"))
    b = paddle.to_tensor(np.asarray([2, 2, 3], "int64"))
    out = paddle.divide(a, b)
    assert "int" in str(out.numpy().dtype)
    assert list(out.numpy()) == [3, -3, 3]
    f = paddle.divide(paddle.to_tensor(np.asarray([7.0], "float32")),
                      paddle.to_tensor(np.asarray([2.0], "float32")))
    np.testing.assert_allclose(f.numpy(), [3.5])


def test_round_half_away_from_zero():
    """Eigen/std::round semantics, not banker's rounding."""
    x = paddle.to_tensor(np.asarray([0.5, 1.5, 2.5, -0.5, -2.5],
                                    "float32"))
    out = paddle.round(x).numpy()
    assert list(out) == [1.0, 2.0, 3.0, -1.0, -3.0], out


def test_truediv_operator_casts_ints_to_float():
    """Reference math_op_patch.py:190: `/` casts int tensors to float32
    (true division) — only the divide() API keeps integer division."""
    a = paddle.to_tensor(np.asarray([7], "int64"))
    b = paddle.to_tensor(np.asarray([2], "int64"))
    out = (a / b).numpy()
    assert "float" in str(out.dtype)
    np.testing.assert_allclose(out, [3.5])
    out2 = (7 / b).numpy()
    np.testing.assert_allclose(out2, [3.5])


def test_round_edge_values_exact():
    # near-half value below 0.5 must NOT round up; large exact ints
    # must pass through unchanged
    x = paddle.to_tensor(np.asarray([0.49999997, 8388609.0], "float32"))
    out = paddle.round(x).numpy()
    assert list(out) == [0.0, 8388609.0], out


def test_index_output_dtypes_are_int64():
    """Reference index-emitting ops (top_k_v2, kthvalue, argsort,
    arg_max, where_index) all output int64 indices."""
    x = paddle.to_tensor(np.random.RandomState(0)
                         .randn(3, 5).astype("float32"))
    _, idx = paddle.topk(x, 2)
    assert str(idx.numpy().dtype) == "int64"
    _, kidx = paddle.kthvalue(x, 2)
    assert str(kidx.numpy().dtype) == "int64"
    assert str(paddle.argsort(x).numpy().dtype) == "int64"
    assert str(paddle.argmax(x).numpy().dtype) == "int64"
    nz = paddle.nonzero(paddle.to_tensor(np.asarray([0, 3, 0, 5])))
    assert str(nz.numpy().dtype) == "int64"
    assert str(paddle.shape(x).numpy().dtype) == "int32"  # shape op: i32


def test_index_dtype_args_honored():
    x = paddle.to_tensor(np.random.RandomState(1)
                         .randn(3, 4).astype("float32"))
    assert str(paddle.argmax(x, dtype="int32").numpy().dtype) == "int32"
    assert str(paddle.argmin(x, axis=1, dtype="int32")
               .numpy().dtype) == "int32"
    seq = paddle.to_tensor(np.asarray([1.0, 3.0, 5.0], "float32"))
    v = paddle.to_tensor(np.asarray([2.0], "float32"))
    assert str(paddle.searchsorted(seq, v, out_int32=True)
               .numpy().dtype) == "int32"


def test_reshape_zero_copies_input_dim():
    """Reference reshape_op: shape entry 0 copies the corresponding
    input dimension."""
    x = paddle.to_tensor(np.zeros((2, 3, 4), np.float32))
    assert paddle.reshape(x, [0, 12]).numpy().shape == (2, 12)
    assert paddle.reshape(x, [0, 0, 4]).numpy().shape == (2, 3, 4)
    assert paddle.reshape(x, [0, -1]).numpy().shape == (2, 12)
    with pytest.raises(Exception):
        paddle.reshape(x, [1, 1, 1, 0])  # 0 beyond input rank


def test_manipulation_edge_semantics_pinned():
    """Reference edge semantics that silently regress easily: expand -1
    keeps the input dim, squeeze ignores non-1 axes, split -1 infers."""
    x = paddle.to_tensor(np.zeros((2, 1, 4), np.float32))
    assert paddle.expand(x, [-1, 3, -1]).numpy().shape == (2, 3, 4)
    assert paddle.squeeze(x, axis=0).numpy().shape == (2, 1, 4)
    parts = paddle.split(paddle.to_tensor(np.zeros((6, 2), np.float32)),
                         [2, -1], axis=0)
    assert [p.numpy().shape for p in parts] == [(2, 2), (4, 2)]
