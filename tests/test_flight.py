"""Request-level observability (PR 4): the flight recorder's
lifecycle traces flow-linked through the chrome trace (validated by a
mini chrome-trace validator, not eyeballed), SLO/goodput accounting
with sliding-window percentiles, bounded completed-request retention,
the /debug endpoints, the cleanly-stoppable metrics server handle, and
device cost telemetry on watchdog compile records.

Acceptance criteria pinned here: a real engine run dumps a chrome
trace where each request's admit -> prefill -> first-token -> retire
path is flow-linked (matched s/f ids, every flow point inside an
existing span) and per-request lifecycle timestamps are monotone;
/metrics exposes SLO attainment, goodput tokens and window
percentiles; cost_analysis appears in watchdog records with graceful
fallback.
"""
import json
import urllib.error
import urllib.request

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import observability as obs
from paddle_tpu.observability import (
    FlightRecorder, HostSpanRecorder, MetricsRegistry, SLOTracker,
    WindowedReservoir, device_memory_stats, executable_cost,
    start_metrics_server,
)
from paddle_tpu.observability.flight import (
    ADMITTED, ENQUEUED, FIRST_TOKEN, PREFILL_DISPATCHED, RETIRED,
)
from paddle_tpu.serving import ServingEngine
from paddle_tpu.text.models import GPTForCausalLM, TransformerLMConfig


def _model(seed=7):
    paddle.seed(seed)
    cfg = TransformerLMConfig(vocab_size=97, hidden_size=32,
                              num_layers=2, num_heads=4,
                              max_seq_len=64, dropout=0.0)
    m = GPTForCausalLM(cfg)
    m.eval()
    return m


def _drive(eng, rs, specs):
    reqs = [eng.add_request(rs.randint(0, 97, (n,)).astype(np.int64),
                            max_new_tokens=k) for n, k in specs]
    eng.run()
    return reqs


# ------------------------------------------- mini chrome-trace validator

_EPS = 0.51  # us rounding slack (ts rounded to 3 decimals in export)


def validate_chrome_flows(trace, expect_finished=True):
    """Assert the flow events in a chrome trace dict are well-formed:
    every chain has exactly one "s" (and, when ``expect_finished``,
    exactly one terminal "f"), phases are time-ordered, and EVERY flow
    point lies inside an existing "X" span on the same pid/tid (the
    slice a viewer binds the arrow to). Returns {flow_id: chain}."""
    events = trace["traceEvents"]
    xs = [e for e in events if e["ph"] == "X"]
    flows = [e for e in events if e["ph"] in ("s", "t", "f")]
    assert flows, "no flow events in trace"
    by_id = {}
    for f in flows:
        for field in ("name", "id", "ts", "pid", "tid", "cat"):
            assert field in f, f"flow event missing {field}: {f}"
        by_id.setdefault(f["id"], []).append(f)
    for fid, chain in by_id.items():
        chain.sort(key=lambda e: e["ts"])
        phases = [e["ph"] for e in chain]
        assert phases[0] == "s", f"flow {fid} doesn't start with s"
        assert phases.count("s") == 1, f"flow {fid} has multiple starts"
        if expect_finished:
            assert phases[-1] == "f", f"flow {fid} never finishes"
            assert phases.count("f") == 1
            assert all(p == "t" for p in phases[1:-1])
        for f in chain:
            assert any(
                x["pid"] == f["pid"] and x["tid"] == f["tid"]
                and x["ts"] - _EPS <= f["ts"] <= x["ts"] + x["dur"] + _EPS
                for x in xs), \
                f"flow point binds to no span: {f}"
    return by_id


# ----------------------------------------------------- windowed reservoir

def test_windowed_reservoir_slides_and_bounds():
    clock = [0.0]
    res = WindowedReservoir(window_s=10.0, capacity=4,
                            clock=lambda: clock[0])
    for i in range(4):
        clock[0] = float(i)
        res.add(float(i))
    assert res.count() == 4 and res.seen == 4
    # capacity bound: a 5th point inside the window drops the OLDEST
    clock[0] = 8.0
    res.add(100.0)
    assert res.count() == 4
    assert 0.0 not in res.values()
    # the window slides: 12s later only the recent points remain
    clock[0] = 15.0
    assert res.values() == [100.0]
    assert res.percentile(50) == 100.0
    # and empties entirely once everything ages out
    clock[0] = 100.0
    assert res.count() == 0 and res.percentile(99) is None
    # seen is lifetime, not window
    assert res.seen == 5


def test_gauge_set_function_pulls_at_exposition():
    reg = MetricsRegistry()
    state = {"v": 1.0}
    reg.gauge("pull_g", "pull gauge").set_function(lambda: state["v"])
    assert reg.get("pull_g").value == 1.0
    state["v"] = 42.0            # no set() call — pulled at read
    assert reg.get("pull_g").value == 42.0
    assert "pull_g 42" in reg.prometheus_text()
    assert reg.snapshot()["pull_g"]["values"][""] == 42.0


# ------------------------------------------------- metrics server handle

def test_metrics_server_handle_close_idempotent_and_ctx():
    reg = MetricsRegistry()
    reg.counter("hits_total").inc(3)
    handle = start_metrics_server(reg, port=0)
    port = handle.port
    assert handle.server_address[1] == port      # legacy surface
    body = urllib.request.urlopen(
        f"http://127.0.0.1:{port}/metrics", timeout=10).read().decode()
    assert "hits_total 3" in body
    handle.close()
    handle.close()                               # idempotent
    assert handle.closed
    with pytest.raises(urllib.error.URLError):
        urllib.request.urlopen(f"http://127.0.0.1:{port}/metrics",
                               timeout=2)
    # context-manager form, with an extra JSON route mounted
    with start_metrics_server(
            reg, port=0,
            extra_routes={"/debug/x": lambda: {"ok": True}}) as h:
        js = json.loads(urllib.request.urlopen(
            f"http://127.0.0.1:{h.port}/debug/x", timeout=10).read())
        assert js == {"ok": True}
    assert h.closed


# ------------------------------------------------------------ SLOTracker

def test_slo_tracker_verdicts_and_goodput():
    reg = MetricsRegistry()
    slo = SLOTracker(reg, slo_ttft_ms=100.0, slo_tpot_ms=10.0)
    # attained: ttft 50ms, 11 tokens over 150ms -> tpot 10ms exactly
    assert slo.observe_request(0.05, 0.15, 11) == []
    # ttft violation only (tpot 8ms, under the 10ms target)
    assert slo.observe_request(0.5, 0.54, 6) == ["ttft"]
    # both dimensions violated
    assert slo.observe_request(0.2, 2.2, 11) == ["ttft", "tpot"]
    # single-token request: TPOT not judged (no inter-token interval)
    assert slo.observe_request(0.05, 0.05, 1) == []
    rep = slo.report()
    assert rep["requests"] == 4 and rep["attained"] == 2
    assert rep["attainment"] == 0.5
    assert rep["violations"] == {"ttft": 2, "tpot": 1}
    assert rep["goodput_tokens"] == 12          # 11 + 1
    assert rep["total_tokens"] == 29
    assert rep["goodput_fraction"] == round(12 / 29, 4)
    assert rep["window"]["ttft"]["count"] == 4
    assert rep["window"]["tpot"]["count"] == 3  # 1-token req excluded
    # registry counters back the same numbers (the /metrics view)
    assert reg.get("serving_goodput_tokens_total").value == 12
    assert reg.get("serving_slo_violations_total") \
        .labels("ttft").value == 2


def test_slo_tracker_untargeted_attains_everything():
    reg = MetricsRegistry()
    slo = SLOTracker(reg)                       # no SLOs configured
    assert slo.observe_request(5.0, 50.0, 10) == []
    rep = slo.report()
    assert rep["attainment"] == 1.0 and rep["goodput_fraction"] == 1.0
    assert rep["config"]["slo_ttft_ms"] is None


# -------------------------------------------------- flight recorder unit

class _FakeReq:
    def __init__(self, rid, prompt_len=4, max_new_tokens=8):
        self.rid = rid
        self.prompt = list(range(prompt_len))
        self.max_new_tokens = max_new_tokens
        self.generated = []


def test_flight_recorder_ring_bounded_and_lookup():
    rec = HostSpanRecorder(capacity=1024)
    fl = FlightRecorder(recorder=rec, keep_last=3, decode_window=2)
    reqs = [_FakeReq(i) for i in range(5)]
    for r in reqs:
        fl.enqueued(r)
        fl.admitted(r, slot=0, bucket=8, group_size=1)
        fl.prefill_dispatched(r, bucket=8, group_size=1)
        r.generated = [1]
        fl.token_emitted(r, 1)
        r.generated = [1, 2]
        fl.token_emitted(r, 2)        # decode_window event (n=2)
        fl.retired(r, "eos")
    st = fl.state()
    assert st["completed_kept"] == 3 and st["completed_dropped"] == 2
    assert st["active"] == 0
    assert fl.trace(0) is None        # evicted from the ring
    tr = fl.trace(4)
    assert tr.reason == "eos"
    names = [e["event"] for e in tr.events]
    assert names == [ENQUEUED, ADMITTED, PREFILL_DISPATCHED,
                     FIRST_TOKEN, "decode_window", RETIRED]
    ts = [e["t"] for e in tr.events]
    assert ts == sorted(ts)
    d = tr.as_dict()
    assert d["events"][0]["t_rel_ms"] == 0.0
    json.dumps(fl.debug_requests())   # JSON-safe end to end
    # marker spans + flow chain landed in the host recorder
    assert any(s.name == "request/enqueued" for s in rec.spans())
    flows = [f for f in rec.flows() if f.fid == 4]
    assert [f.phase for f in flows] == ["s", "t", "t", "t", "t", "f"]


# -------------------------------- acceptance: flow-linked engine traces

def test_engine_chrome_trace_flow_links_requests(tmp_path):
    """A REAL engine run dumps a chrome trace where each request's
    enqueue -> admit -> prefill -> first-token -> retire path is a
    well-formed flow chain bound to existing spans, and each
    RequestTrace's lifecycle timestamps are monotone."""
    rec = obs.default_recorder()
    rec.clear()
    m = _model()
    eng = ServingEngine(m, num_slots=2, bucket_min=8)
    rs = np.random.RandomState(0)
    reqs = _drive(eng, rs, [(5, 4), (9, 5), (12, 3), (6, 4)])
    path = str(tmp_path / "flight_trace.json")
    rec.dump_chrome_trace(path)
    with open(path) as fh:
        trace = json.load(fh)
    chains = validate_chrome_flows(trace)
    # one flow chain per request, id == rid
    assert set(chains) == {r.rid for r in reqs}
    for r in reqs:
        chain = chains[r.rid]
        events = [e["args"]["event"] for e in chain]
        assert events[0] == ENQUEUED and events[-1] == RETIRED
        assert ADMITTED in events and FIRST_TOKEN in events
        assert PREFILL_DISPATCHED in events
        # the engine-side record agrees and is monotone
        tr = eng.request_trace(r.rid)
        t_seq = [tr.t_of(ENQUEUED), tr.t_of(ADMITTED),
                 tr.t_of(FIRST_TOKEN), tr.t_of(RETIRED)]
        assert all(t is not None for t in t_seq)
        assert t_seq == sorted(t_seq)
        assert tr.reason == "max_tokens"
        assert tr.as_dict()["events"][-1]["slo_violations"] == []


def test_engine_flow_chains_span_multiple_steps(tmp_path):
    """The flow chain of a long request crosses SEVERAL serving/step
    spans — the 'follow one request across steps' property that makes
    the Perfetto view useful, asserted by timestamp containment."""
    rec = obs.default_recorder()
    rec.clear()
    m = _model()
    eng = ServingEngine(m, num_slots=2, bucket_min=8)
    rs = np.random.RandomState(1)
    (req,) = _drive(eng, rs, [(5, 10)])
    trace = rec.chrome_trace()
    steps = [e for e in trace["traceEvents"]
             if e["ph"] == "X" and e["name"] == "serving/step"]
    chain = validate_chrome_flows(trace)[req.rid]

    def step_of(f):
        for i, s in enumerate(steps):
            if s["ts"] - _EPS <= f["ts"] <= s["ts"] + s["dur"] + _EPS:
                return i
        return None

    hit_steps = {step_of(f) for f in chain} - {None}
    assert len(hit_steps) >= 2, \
        "flow chain never crossed an engine step boundary"


# --------------------------------------------- engine SLO + /metrics

def test_engine_slo_exposed_on_metrics_and_snapshot():
    m = _model()
    eng = ServingEngine(m, num_slots=2, bucket_min=8,
                        slo_ttft_ms=60000.0, slo_tpot_ms=60000.0)
    rs = np.random.RandomState(2)
    _drive(eng, rs, [(5, 3), (9, 4), (7, 3)])
    snap = eng.metrics.snapshot()
    slo = snap["slo"]
    assert slo["requests"] == 3 and slo["attainment"] == 1.0
    assert slo["goodput_tokens"] == slo["total_tokens"] == 10
    assert slo["window"]["ttft"]["count"] == 3
    assert slo["window"]["ttft"]["p50_ms"] > 0
    text = eng.metrics.prometheus_text()
    assert "serving_slo_attained_total 3" in text
    assert "serving_goodput_tokens_total 10" in text
    assert 'serving_window_ttft_ms{quantile="p50"}' in text


def test_engine_slo_violations_zero_goodput():
    m = _model()
    # impossible SLOs: every request violates, goodput is zero
    eng = ServingEngine(m, num_slots=2, bucket_min=8,
                        slo_ttft_ms=0.0001, slo_tpot_ms=0.0001)
    rs = np.random.RandomState(3)
    reqs = _drive(eng, rs, [(5, 3), (9, 4)])
    slo = eng.metrics.snapshot()["slo"]
    assert slo["attained"] == 0 and slo["goodput_tokens"] == 0
    assert slo["violations"]["ttft"] == 2
    assert slo["goodput_fraction"] == 0.0
    # the flight recorder stamped the verdict on the retirement event
    tr = eng.request_trace(reqs[0].rid)
    assert "ttft" in tr.as_dict()["events"][-1]["slo_violations"]


# ---------------------------------------------------- bounded retention

def test_completed_retention_bounded():
    m = _model()
    eng = ServingEngine(m, num_slots=2, bucket_min=8,
                        completed_keep=4, trace_keep=3)
    rs = np.random.RandomState(4)
    specs = [(int(n), 2) for n in rs.randint(2, 12, 10)]
    _drive(eng, rs, specs)
    assert eng.metrics.requests_completed == 10   # accounting is exact
    assert len(eng.scheduler.completed) == 4      # retention is bounded
    st = eng.flight.state()
    assert st["completed_kept"] == 3
    assert st["completed_dropped"] == 7


# ------------------------------------------------------ debug endpoints

def test_engine_debug_endpoints_and_close():
    m = _model()
    eng = ServingEngine(m, num_slots=2, bucket_min=8)
    rs = np.random.RandomState(5)
    reqs = _drive(eng, rs, [(5, 3), (9, 4)])
    handle = eng.serve_metrics()
    port = handle.port
    req_js = json.loads(urllib.request.urlopen(
        f"http://127.0.0.1:{port}/debug/requests", timeout=10).read())
    assert {t["rid"] for t in req_js["completed"]} == \
        {r.rid for r in reqs}
    assert req_js["state"]["active"] == 0
    state = json.loads(urllib.request.urlopen(
        f"http://127.0.0.1:{port}/debug/state", timeout=10).read())
    assert state["queue_depth"] == 0 and state["active_slots"] == {}
    assert state["compiles"] == eng.metrics.compiles
    assert state["watchdog"]["steady_state_compiles"] == 0
    assert state["slo"]["requests"] == 2
    # the engine shuts its servers down with itself
    eng.close()
    assert handle.closed
    with pytest.raises(urllib.error.URLError):
        urllib.request.urlopen(f"http://127.0.0.1:{port}/debug/state",
                               timeout=2)
    eng.close()                                   # idempotent


# ------------------------------------------------- device cost telemetry

def test_watchdog_compile_records_carry_cost():
    m = _model()
    eng = ServingEngine(m, num_slots=2, bucket_min=8, peak_flops=1e12)
    rs = np.random.RandomState(6)
    _drive(eng, rs, [(5, 3), (9, 4)])
    events = eng.watchdog.events()
    assert events
    for e in events:
        assert "cost" in e and "memory" in e      # keys always present
    # CPU's XLA reports cost_analysis: the decode executable's record
    # carries real flops/bytes
    decode = [e for e in events if e["key"] == "('decode',)"]
    assert decode and decode[0]["cost"]["flops"] > 0
    assert decode[0]["cost"]["bytes_accessed"] > 0
    # memory_stats is None on CPU — the graceful-fallback contract
    assert decode[0]["memory"] is None

    cm = eng.cost_model()
    assert cm["decode_flops_per_step"] == decode[0]["cost"]["flops"]
    assert cm["executables_with_cost"] == len(events)
    assert cm["peak_flops"] == 1e12
    assert cm["estimated_mfu"] > 0                # peak known -> estimate
    assert cm["device_memory"] is None            # CPU
    json.dumps(cm)                                # artifact-embeddable
    # per-step gauges feed /metrics
    text = eng.metrics.prometheus_text()
    assert "serving_decode_flops_per_step" in text
    assert "serving_estimated_mfu" in text


def test_cost_helpers_graceful_on_nonreporting_backends():
    class _NoCost:
        def cost_analysis(self):
            raise NotImplementedError

    class _WeirdCost:
        def cost_analysis(self):
            return "not-a-dict"

    class _ListCost:
        def cost_analysis(self):
            return [{"flops": 12.0, "bytes accessed": 34.0,
                     "utilization0{}": 1.0}]

    assert executable_cost(_NoCost()) is None
    assert executable_cost(_WeirdCost()) is None
    assert executable_cost(_ListCost()) == \
        {"flops": 12.0, "bytes_accessed": 34.0}

    class _NoMem:
        def memory_stats(self):
            return None

    class _Mem:
        def memory_stats(self):
            return {"bytes_in_use": 10, "bytes_limit": 110,
                    "weird": object()}

    assert device_memory_stats(_NoMem()) is None
    stats = device_memory_stats(_Mem())
    assert stats["bytes_free"] == 100
    assert "weird" not in stats
