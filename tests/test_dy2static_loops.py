"""dy2static loop family: for-range/for-iter -> lax loops,
break/continue transformation, list append rewriting (reference:
loop_transformer.py:486 LoopTransformer, break_continue_transformer.py:87
BreakContinueTransformer, list_transformer.py)."""
import numpy as np
import pytest

import paddle_tpu as paddle


def _trace_count(fn):
    """Number of compiled entries a to_static fn accumulated."""
    return len(fn.entries)


def test_for_range_tensor_bound_single_program():
    """Data-dependent trip count: for over a Tensor bound must compile
    to ONE lax.while_loop program, not a python unroll — the SAME
    compiled program must then serve a different bound value."""
    @paddle.jit.to_static
    def f(x, n):
        s = x * 0.0
        for i in range(n):
            s = s + x
        return s

    xp = np.full((3,), 2.0, np.float32)
    for _ in range(3):  # eager -> record -> compiled
        out = f(paddle.to_tensor(xp), paddle.to_tensor(np.int64(5)))
    np.testing.assert_allclose(out.numpy(), xp * 5)
    # different trip count through the SAME executable (no retrace for
    # a data-dependent bound: the loop is inside the program)
    n_entries = _trace_count(f)
    out = f(paddle.to_tensor(xp), paddle.to_tensor(np.int64(9)))
    np.testing.assert_allclose(out.numpy(), xp * 9)
    assert _trace_count(f) == n_entries


def test_for_range_python_bounds_keep_python_semantics():
    @paddle.jit.to_static
    def f(x):
        s = x * 0.0
        for i in range(4):
            s = s + x * float(i)
        return s

    xp = np.ones((2,), np.float32)
    for _ in range(3):
        out = f(paddle.to_tensor(xp))
    np.testing.assert_allclose(out.numpy(), xp * 6.0)  # 0+1+2+3


def test_for_range_start_stop_step_tensor():
    @paddle.jit.to_static
    def f(x, start, stop):
        s = x * 0.0
        for i in range(start, stop, 2):
            s = s + x
        return s

    xp = np.ones((2,), np.float32)
    for _ in range(3):
        out = f(paddle.to_tensor(xp), paddle.to_tensor(np.int64(1)),
                paddle.to_tensor(np.int64(8)))
    np.testing.assert_allclose(out.numpy(), xp * 4)  # 1,3,5,7


def test_break_with_tensor_predicate_in_tensor_loop():
    """The reference's BreakContinueTransformer flag scheme: a Tensor
    break predicate folds into the traced loop condition."""
    @paddle.jit.to_static
    def f(x, n, cap):
        s = x * 0.0
        for i in range(n):
            if s.sum() >= cap:
                break
            s = s + x
        return s

    xp = np.ones((2,), np.float32)
    for _ in range(3):
        out = f(paddle.to_tensor(xp), paddle.to_tensor(np.int64(100)),
                paddle.to_tensor(np.float32(6.0)))
    np.testing.assert_allclose(out.numpy(), xp * 3)  # sum hits 6 at s=3x
    # same program, different cap
    out = f(paddle.to_tensor(xp), paddle.to_tensor(np.int64(100)),
            paddle.to_tensor(np.float32(10.0)))
    np.testing.assert_allclose(out.numpy(), xp * 5)


def test_continue_with_tensor_predicate():
    @paddle.jit.to_static
    def f(x, n):
        s = x * 0.0
        t = paddle.to_tensor(np.float32(0.0))
        for i in range(n):
            t = t + 1.0
            if paddle.sum(t % 2.0) > 0.5:  # odd ticks skipped
                continue
            s = s + x
        return s

    xp = np.ones((2,), np.float32)
    for _ in range(3):
        out = f(paddle.to_tensor(xp), paddle.to_tensor(np.int64(10)))
    np.testing.assert_allclose(out.numpy(), xp * 5)  # even ticks only


def test_break_continue_python_loop_python_preds():
    """Pure-python loops keep exact python semantics (incl. early exit)."""
    @paddle.jit.to_static
    def f(x, n):
        s = x * 0.0
        for i in range(n):
            if i == 2:
                continue
            if i >= 5:
                break
            s = s + x * float(i)
        return s

    xp = np.ones((2,), np.float32)
    out = f(paddle.to_tensor(xp), 100)
    np.testing.assert_allclose(out.numpy(), xp * 8.0)  # 0+1+3+4


def test_break_tensor_pred_in_python_bounded_loop():
    """Python bounds + Tensor break predicate: the loop unrolls but the
    guards mask post-break statements — still compiles to one program."""
    @paddle.jit.to_static
    def f(x, cap):
        s = x * 0.0
        for i in range(10):
            if s.sum() >= cap:
                break
            s = s + x
        return s

    xp = np.ones((2,), np.float32)
    for _ in range(3):
        out = f(paddle.to_tensor(xp), paddle.to_tensor(np.float32(4.0)))
    np.testing.assert_allclose(out.numpy(), xp * 2)
    out = f(paddle.to_tensor(xp), paddle.to_tensor(np.float32(12.0)))
    np.testing.assert_allclose(out.numpy(), xp * 6)


def test_while_with_break_flags():
    @paddle.jit.to_static
    def f(x, cap):
        s = x * 0.0
        i = paddle.to_tensor(np.float32(0.0))
        while i < 100.0:
            if s.sum() >= cap:
                break
            s = s + x
            i = i + 1.0
        return s

    xp = np.ones((2,), np.float32)
    for _ in range(3):
        out = f(paddle.to_tensor(xp), paddle.to_tensor(np.float32(6.0)))
    np.testing.assert_allclose(out.numpy(), xp * 3)


def test_for_iter_over_tensor_rows():
    """for x in tensor iterates rows through ONE dynamic-gather loop."""
    @paddle.jit.to_static
    def f(m):
        s = m[0] * 0.0
        for row in m:
            s = s + row * 2.0
        return s

    mp = np.arange(12, dtype=np.float32).reshape(4, 3)
    for _ in range(3):
        out = f(paddle.to_tensor(mp))
    np.testing.assert_allclose(out.numpy(), mp.sum(0) * 2.0)


def test_for_iter_python_list_unchanged():
    @paddle.jit.to_static
    def f(x, ks):
        s = x * 0.0
        for k in ks:
            s = s + x * float(k)
        return s

    xp = np.ones((2,), np.float32)
    out = f(paddle.to_tensor(xp), [1, 2, 3])
    np.testing.assert_allclose(out.numpy(), xp * 6.0)


def test_list_append_in_python_loop():
    """list_transformer slice: appends become carried rebindings, so
    they survive conversion and stack afterwards."""
    @paddle.jit.to_static
    def f(x):
        outs = []
        for i in range(3):
            outs.append(x * float(i + 1))
        return paddle.stack(outs).sum(0)

    xp = np.ones((2,), np.float32)
    for _ in range(3):
        out = f(paddle.to_tensor(xp))
    np.testing.assert_allclose(out.numpy(), xp * 6.0)


def test_list_append_under_tensor_trip_count_raises():
    from paddle_tpu.jit.dy2static import convert_to_static

    def f(x, n):
        outs = []
        for i in range(n):
            outs.append(x)
        return outs

    g = convert_to_static(f)
    with pytest.raises(TypeError, match="static shapes"):
        g(paddle.to_tensor(np.ones(2, np.float32)),
          paddle.to_tensor(np.int64(3)))


def test_nested_loops_inner_break_stays_inner():
    @paddle.jit.to_static
    def f(x):
        s = x * 0.0
        for i in range(3):
            for j in range(5):
                if j >= 2:
                    break
                s = s + x
        return s

    xp = np.ones((2,), np.float32)
    out = f(paddle.to_tensor(xp))
    np.testing.assert_allclose(out.numpy(), xp * 6.0)  # 3 outer x 2 inner


def test_loop_eager_matches_compiled():
    """The converted function must produce identical results eagerly
    (flag machinery dispatches on python values there)."""
    from paddle_tpu.jit.dy2static import convert_to_static

    def f(x, n, cap):
        s = x * 0.0
        for i in range(n):
            if s.sum() >= cap:
                break
            s = s + x
        return s

    g = convert_to_static(f)
    xp = np.ones((2,), np.float32)
    out = g(paddle.to_tensor(xp), paddle.to_tensor(np.int64(50)),
            paddle.to_tensor(np.float32(7.0)))
    np.testing.assert_allclose(out.numpy(), xp * 4)


def test_break_tensor_pred_accumulate_before_check():
    """Review finding: statements BEFORE the break check must also be
    masked on iterations after a Tensor break fires (python break
    semantics: the accumulate on the breaking iteration runs, later
    iterations run nothing)."""
    @paddle.jit.to_static
    def f(x, cap):
        s = x * 0.0
        for i in range(10):
            s = s + x
            if s.sum() >= cap:
                break
        return s

    xp = np.ones((2,), np.float32)
    for _ in range(3):
        out = f(paddle.to_tensor(xp), paddle.to_tensor(np.float32(4.0)))
    np.testing.assert_allclose(out.numpy(), xp * 2)  # sum hits 4 at s=2x
    out = f(paddle.to_tensor(xp), paddle.to_tensor(np.float32(100.0)))
    np.testing.assert_allclose(out.numpy(), xp * 10)  # never breaks


def test_while_tensor_cond_append_raises_friendly():
    """Review finding: append in a Tensor-cond while (no break) must hit
    the friendly static-shapes error, not leak a tracer into a list."""
    from paddle_tpu.jit.dy2static import convert_to_static

    def f(x, n):
        lst = []
        i = paddle.to_tensor(np.float32(0.0))
        while i < n:
            lst.append(x)
            i = i + 1.0
        return lst

    g = convert_to_static(f)
    with pytest.raises(TypeError, match="static shapes"):
        g(paddle.to_tensor(np.ones(2, np.float32)),
          paddle.to_tensor(np.float32(3.0)))


def test_cross_iteration_undefined_carry_names_variable():
    """Review finding: a variable carried across iterations of a
    Tensor-bounded loop but first assigned inside it raises an
    UnboundLocalError NAMING it and the fix."""
    from paddle_tpu.jit.dy2static import convert_to_static

    def f(x, n):
        s = x * 0.0
        i = paddle.to_tensor(np.float32(0.0))
        while i < n:
            if i > 0.5:
                s = s + prev
            prev = s + x
            i = i + 1.0
        return s

    g = convert_to_static(f)
    with pytest.raises(UnboundLocalError, match="prev"):
        g(paddle.to_tensor(np.ones(2, np.float32)),
          paddle.to_tensor(np.float32(3.0)))


def test_python_break_does_not_leak_loop_variable():
    """Review finding: after a python break, the loop variable must hold
    the breaking iteration's value (no extra header run) and a shared
    iterator must not lose an element."""
    from paddle_tpu.jit.dy2static import convert_to_static

    def f(x):
        last = -1
        for i in range(10):
            if i >= 5:
                break
            last = i
        return x + float(i), last

    g = convert_to_static(f)
    out, last = g(paddle.to_tensor(np.zeros(1, np.float32)))
    assert float(out.numpy()[0]) == 5.0  # i stopped AT the break point
    assert last == 4

    def h(x, it):
        for v in it:
            if v >= 3:
                break
        return x

    it = iter([1, 2, 3, 4, 5])
    # a python iterator is not converted (no tensor), but must also not
    # have an extra element consumed by the rewrite
    g2 = convert_to_static(h)
    g2(paddle.to_tensor(np.zeros(1, np.float32)), it)
    assert list(it) == [4, 5]
