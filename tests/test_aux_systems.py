"""Aux subsystems: distribution, elastic, auto-checkpoint, flags, profiler
(SURVEY §5 parity)."""
import os
import time

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn


class TestDistribution:
    def test_normal(self):
        from paddle_tpu.distribution import Normal
        paddle.seed(0)
        d = Normal(0.0, 1.0)
        s = d.sample([2000])
        assert abs(float(s.numpy().mean())) < 0.1
        lp = d.log_prob(paddle.to_tensor(0.0))
        assert float(lp.numpy()) == pytest.approx(-0.9189, rel=1e-3)
        assert float(d.entropy().numpy()) == pytest.approx(1.4189, rel=1e-3)
        kl = d.kl_divergence(Normal(1.0, 1.0))
        assert float(kl.numpy()) == pytest.approx(0.5, rel=1e-4)

    def test_uniform(self):
        from paddle_tpu.distribution import Uniform
        paddle.seed(0)
        d = Uniform(2.0, 4.0)
        s = d.sample([500])
        assert 2.0 <= float(s.numpy().min()) and float(s.numpy().max()) < 4.0
        lp = d.log_prob(paddle.to_tensor(3.0))
        assert float(lp.numpy()) == pytest.approx(-np.log(2), rel=1e-4)
        outside = d.log_prob(paddle.to_tensor(5.0))
        assert np.isneginf(outside.numpy())

    def test_categorical(self):
        from paddle_tpu.distribution import Categorical
        paddle.seed(0)
        d = Categorical(paddle.to_tensor(np.log([0.7, 0.2, 0.1])
                                         .astype("float32")))
        s = d.sample([2000]).numpy()
        assert (s == 0).mean() > 0.55
        lp = d.log_prob(paddle.to_tensor(np.array([0])))
        assert float(lp.numpy()) == pytest.approx(np.log(0.7), rel=1e-3)
        assert float(d.entropy().numpy()) > 0


class TestElastic:
    def test_membership_watch(self, tmp_path):
        from paddle_tpu.distributed.fleet.elastic import (ElasticManager,
                                                          FileStore)
        store = FileStore(str(tmp_path), ttl=5.0)
        changes = []
        m1 = ElasticManager("n1", store=store, heartbeat_interval=0.05,
                            on_membership_change=lambda o, n: changes.append(n))
        m1.start()
        m2 = ElasticManager("n2", store=store, heartbeat_interval=0.05)
        m2.start()
        deadline = time.time() + 10
        while time.time() < deadline and "n2" not in m1.world():
            time.sleep(0.05)
        assert "n2" in m1.world()
        m2.stop()
        m1.stop()
        assert any("n2" in c for c in changes)

    def test_child_supervision(self, tmp_path):
        from paddle_tpu.distributed.fleet.elastic import (ElasticManager,
                                                          FileStore)
        m = ElasticManager("sup", store=FileStore(str(tmp_path)))
        m.launch(["python", "-c", "import sys; sys.exit(0)"])
        m.launch(["python", "-c", "import sys; sys.exit(3)"])
        deadline = time.time() + 20
        while time.time() < deadline:
            done, failed = m.check_procs()
            if done:
                break
            time.sleep(0.1)
        assert done
        assert len(failed) == 1 and failed[0][1] == 3


class TestAutoCheckpoint:
    def test_resume_skips_completed_epochs(self, tmp_path):
        from paddle_tpu.incubate.checkpoint import auto_checkpoint as ac
        ac.set_checkpoint_dir(str(tmp_path))
        net = nn.Linear(2, 2)
        r = ac.TrainEpochRange(5, "job_a")
        r.add("model", net)
        seen = []
        for epoch in r.get():
            seen.append(epoch)
            net.weight.set_value(np.full((2, 2), epoch, np.float32))
            if epoch == 2:
                break  # simulate crash after completing epochs 0..1 (+2 saved)
        assert seen == [0, 1, 2]
        # restart
        net2 = nn.Linear(2, 2)
        r2 = ac.TrainEpochRange(5, "job_a")
        r2.add("model", net2)
        resumed = list(r2.get())
        assert resumed[0] == 2 or resumed[0] == 3  # resumes after last snap
        # weights restored from snapshot
        assert net2.weight.numpy()[0, 0] in (1.0, 2.0)


class TestProfiler:
    def test_record_event_and_profiler(self):
        from paddle_tpu.profiler import RecordEvent, Profiler
        p = Profiler(timer_only=True)
        p.start()
        with RecordEvent("train_step"):
            paddle.ones([4]).sum().numpy()
        p.step()
        p.step()
        info = p.step_info()
        assert "avg step" in info
        p.stop()


class TestFlags:
    def test_set_get(self):
        paddle.set_flags({"FLAGS_check_nan_inf": True})
        assert paddle.get_flags("FLAGS_check_nan_inf")["FLAGS_check_nan_inf"]
        paddle.set_flags({"FLAGS_check_nan_inf": False})


def test_fleet_localfs():
    import os
    import tempfile
    from paddle_tpu.distributed.fleet.utils_fs import (LocalFS,
                                                       FSFileExistsError)
    fs = LocalFS()
    with tempfile.TemporaryDirectory() as d:
        sub = os.path.join(d, "a", "b")
        fs.mkdirs(sub)
        assert fs.is_dir(sub) and fs.is_exist(sub)
        f = os.path.join(sub, "x.txt")
        fs.touch(f)
        assert fs.is_file(f)
        with open(f, "w") as fh:
            fh.write("hello")
        assert fs.cat(f) == "hello"
        dirs, files = fs.ls_dir(sub)
        assert files == ["x.txt"]
        fs.rename(f, f + ".2")
        assert fs.is_file(f + ".2")
        try:
            fs.touch(f + ".2", exist_ok=False)
            raise AssertionError("expected FSFileExistsError")
        except FSFileExistsError:
            pass
        fs.delete(sub)
        assert not fs.is_exist(sub)
    assert not fs.need_upload_download()


def test_hdfs_client_gated():
    import pytest
    from paddle_tpu.distributed.fleet.utils_fs import HDFSClient, ExecuteError
    import shutil as _sh
    if _sh.which("hadoop"):
        pytest.skip("hadoop present")
    with pytest.raises(ExecuteError):
        HDFSClient()


_HADOOP_SHIM = r'''#!/usr/bin/env python3
"""Minimal `hadoop fs` emulation over the local filesystem, mimicking
HDFS shell output formats, so HDFSClient's command construction and
-ls parsing are exercised without a cluster."""
import os, shutil, sys

argv = sys.argv[1:]
assert argv and argv[0] == "fs", argv
argv = argv[1:]
while argv and argv[0] == "-D":      # -D k=v config pairs
    argv = argv[2:]
op, args = argv[0], argv[1:]

if op == "-ls":
    p = args[0]
    if not os.path.exists(p):
        sys.stderr.write(f"ls: `{p}': No such file or directory\n")
        sys.exit(1)
    names = sorted(os.listdir(p)) if os.path.isdir(p) else [p]
    print(f"Found {len(names)} items")
    for n in names:
        full = os.path.join(p, n) if os.path.isdir(p) else n
        kind = "d" if os.path.isdir(full) else "-"
        sz = os.path.getsize(full) if os.path.isfile(full) else 0
        print(f"{kind}rwxr-xr-x   - u g {sz:>10} 2026-01-01 00:00 {full}")
elif op == "-test":
    flag, p = args
    ok = {"-e": os.path.exists, "-f": os.path.isfile,
          "-d": os.path.isdir}[flag](p)
    sys.exit(0 if ok else 1)
elif op == "-mkdir":
    os.makedirs(args[-1], exist_ok=True)
elif op == "-rm":
    p = args[-1]
    if os.path.isdir(p):
        shutil.rmtree(p)
    elif os.path.exists(p):
        os.remove(p)
elif op == "-mv":
    shutil.move(args[0], args[1])
elif op == "-touchz":
    open(args[0], "w").close()
elif op == "-cat":
    sys.stdout.write(open(args[0]).read())
elif op == "-put":
    shutil.copy(args[0], args[1])
elif op == "-get":
    shutil.copy(args[0], args[1])
else:
    sys.stderr.write(f"unknown op {op}\n")
    sys.exit(2)
'''


def test_hdfs_client_against_shim(tmp_path):
    """Behavioral HDFS coverage (VERDICT r2 weak #8): run HDFSClient
    against a hadoop-shell emulator so every subprocess path (command
    assembly, -D config injection, -ls output parsing, -test exit
    codes) is executed. Reference: fleet/utils/fs.py:423 HDFSClient."""
    from paddle_tpu.distributed.fleet.utils_fs import (HDFSClient,
                                                       FSFileExistsError)

    home = tmp_path / "hadoop_home"
    (home / "bin").mkdir(parents=True)
    shim = home / "bin" / "hadoop"
    shim.write_text(_HADOOP_SHIM)
    shim.chmod(0o755)

    root = tmp_path / "dfs"
    root.mkdir()
    fs = HDFSClient(hadoop_home=str(home),
                    configs={"fs.default.name": "hdfs://local:9000"})
    assert fs.need_upload_download()

    d = str(root / "ckpt")
    fs.mkdirs(d)
    assert fs.is_exist(d) and fs.is_dir(d) and not fs.is_file(d)

    # upload / cat / download round-trip
    src = tmp_path / "local.txt"
    src.write_text("hello-dfs")
    fs.upload(str(src), d + "/a.txt")
    assert fs.is_file(d + "/a.txt")
    assert fs.cat(d + "/a.txt") == "hello-dfs"
    back = tmp_path / "back.txt"
    fs.download(d + "/a.txt", str(back))
    assert back.read_text() == "hello-dfs"

    # ls_dir separates dirs and files, strips the listing header
    fs.mkdirs(d + "/sub")
    dirs, files = fs.ls_dir(d)
    assert dirs == ["sub"] and files == ["a.txt"]

    # touch semantics: exist_ok honored, -touchz only for new files
    fs.touch(d + "/a.txt", exist_ok=True)
    assert fs.cat(d + "/a.txt") == "hello-dfs"  # not truncated
    import pytest
    with pytest.raises(FSFileExistsError):
        fs.touch(d + "/a.txt", exist_ok=False)
    fs.touch(d + "/b.txt")
    assert fs.is_file(d + "/b.txt")

    fs.rename(d + "/b.txt", d + "/c.txt")
    assert not fs.is_exist(d + "/b.txt") and fs.is_file(d + "/c.txt")

    fs.delete(d)
    assert not fs.is_exist(d)


def test_elastic_kill_relaunch_resume(tmp_path):
    """VERDICT r1 item 8: launch 2 workers, kill one, the manager
    detects the death (check_procs + heartbeat expiry), relaunches it,
    and training resumes from the checkpoint instead of restarting.
    Reference: fleet/elastic.py:101,173-206."""
    import json
    import signal
    import subprocess
    import sys
    import time as _t
    from paddle_tpu.distributed.fleet.elastic import (ElasticManager,
                                                      FileStore)

    ckpt = tmp_path / "ckpt"
    store_root = str(tmp_path / "store")
    ckpt.mkdir()
    logs = {r: str(tmp_path / f"w{r}.log") for r in (0, 1)}
    total = 8
    worker = os.path.join(os.path.dirname(__file__), "elastic_worker.py")

    def read_log(rank):
        try:
            with open(logs[rank]) as f:
                return [json.loads(ln) for ln in f if ln.strip()]
        except FileNotFoundError:
            return []

    def cmd(rank):
        return [sys.executable, worker, str(rank), str(ckpt), store_root,
                str(total), logs[rank]]

    mgr = ElasticManager(node_id="supervisor",
                         store=FileStore(store_root, ttl=1.5),
                         heartbeat_interval=0.3)
    p0 = mgr.launch(cmd(0))
    p1 = mgr.launch(cmd(1))
    try:
        # wait until worker 1 has made real progress
        deadline = _t.time() + 120
        while _t.time() < deadline:
            steps = [e["step"] for e in read_log(1) if e["event"] == "step"]
            if steps and steps[-1] >= 3:
                break
            _t.sleep(0.3)
        else:
            raise AssertionError(f"worker1 made no progress: {read_log(1)}")

        p1.send_signal(signal.SIGKILL)  # simulate node failure
        p1.wait(timeout=30)

        # supervisor notices the dead child...
        done, failed = mgr.check_procs()
        assert failed and failed[0][0] == p1.pid
        # ...and the heartbeat registry drops the node after ttl
        deadline = _t.time() + 30
        while _t.time() < deadline:
            if "w1" not in mgr.store.alive_nodes():
                break
            _t.sleep(0.3)
        assert "w1" not in mgr.store.alive_nodes()

        # relaunch the failed worker: it must RESUME, not restart
        p1b = mgr.launch(cmd(1))
        deadline = _t.time() + 180
        while _t.time() < deadline:
            if any(e["event"] == "done" for e in read_log(1)):
                break
            _t.sleep(0.5)
        events = read_log(1)
        assert any(e["event"] == "done" for e in events), events[-3:]
        starts = [e for e in events if e["event"] == "start"]
        assert len(starts) == 2
        assert starts[0]["resumed_from"] == 0
        assert starts[1]["resumed_from"] >= 3, starts
        steps = [e["step"] for e in events if e["event"] == "step"]
        assert steps[-1] == total
        # no step ran twice after the resume point
        resumed = starts[1]["resumed_from"]
        post = steps[steps.index(resumed + 1):]
        assert post == list(range(resumed + 1, total + 1))

        # worker 0 was never disturbed and finishes too
        deadline = _t.time() + 180
        while _t.time() < deadline:
            if any(e["event"] == "done" for e in read_log(0)):
                break
            _t.sleep(0.5)
        assert any(e["event"] == "done" for e in read_log(0))
        p0.wait(timeout=30)
        p1b.wait(timeout=30)
    finally:
        mgr.kill_children()
        mgr.stop()


def test_error_taxonomy():
    """Reference: platform/enforce.h:427 + error_codes.proto — typed
    error classes that also subclass the natural builtin so existing
    except-clauses keep working."""
    import pytest
    import numpy as np
    import paddle_tpu as paddle
    from paddle_tpu import errors

    # enforce helpers
    errors.enforce(True, "fine")
    with pytest.raises(errors.InvalidArgumentError):
        errors.enforce(False, "bad")
    with pytest.raises(errors.InvalidArgumentError):
        errors.enforce_eq(1, 2)
    errors.enforce_ge(2, 2)
    with pytest.raises(errors.NotFoundError):
        errors.enforce_not_none(None)
    assert errors.error_for_code("OUT_OF_RANGE") is errors.OutOfRangeError

    # builtin-compatibility contract
    assert issubclass(errors.InvalidArgumentError, ValueError)
    assert issubclass(errors.ResourceExhaustedError, MemoryError)
    assert issubclass(errors.UnimplementedError, NotImplementedError)

    # used at real API edges
    t = paddle.to_tensor(np.zeros((2, 2), np.float32))
    with pytest.raises(errors.InvalidArgumentError):
        t.set_value(np.zeros((3, 3), np.float32))
    with pytest.raises(ValueError):  # old-style handler still catches
        t.set_value(np.zeros((3, 3), np.float32))
    from paddle_tpu.distributed import collective
    with pytest.raises(errors.InvalidArgumentError):
        collective.get_group(99999)
