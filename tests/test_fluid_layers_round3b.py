"""fluid.layers submodule surfaces beyond nn.py (reference:
fluid/layers/{tensor,control_flow,loss,sequence_lod,detection,rnn,
metric_op}.py — now name-complete; audited here). Numerics checks for
the newly implemented families."""
import ast
import os

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.fluid import layers


def setup_function(_):
    layers.clear_layer_cache()


def test_all_submodules_name_complete():
    have = set(dir(layers))
    missing = []
    for mod in ("nn", "tensor", "control_flow", "loss", "sequence_lod",
                "detection", "metric_op", "rnn",
                "learning_rate_scheduler", "io", "device", "collective",
                "distributions"):
        path = f"/root/reference/python/paddle/fluid/layers/{mod}.py"
        if not os.path.exists(path):
            continue
        names = []
        tree = ast.parse(open(path).read())
        for node in ast.walk(tree):
            if isinstance(node, ast.Assign):
                for t in node.targets:
                    if getattr(t, "id", "") == "__all__":
                        try:
                            names = ast.literal_eval(node.value)
                        except Exception:
                            pass
        missing += [f"{mod}.{n}" for n in names if n not in have]
    assert not missing, missing


class TestTensorLayer:
    def test_creation_and_comparisons(self):
        t = layers.fill_constant_batch_size_like(
            paddle.to_tensor(np.zeros((5, 2), np.float32)),
            [-1, 3], "float32", 7.0)
        assert t.numpy().shape == (5, 3) and float(t.numpy()[0, 0]) == 7.0
        a = paddle.to_tensor(np.asarray([1.0, 5.0], np.float32))
        b = paddle.to_tensor(np.asarray([2.0, 2.0], np.float32))
        assert list(layers.less_than(a, b).numpy()) == [True, False]
        assert bool(layers.isfinite(a).numpy())
        assert not bool(layers.isfinite(
            paddle.to_tensor(np.asarray([np.inf], np.float32))).numpy())
        vals, idx = layers.argsort(
            paddle.to_tensor(np.asarray([3.0, 1.0, 2.0], np.float32)))
        assert list(idx.numpy()) == [1, 2, 0]

    def test_create_parameter_reuse(self):
        p1 = layers.create_parameter([3, 4], "float32", name="w0")
        p2 = layers.create_parameter([3, 4], "float32", name="w0")
        assert p1 is p2


class TestControlFlow:
    def test_increment_and_arrays(self):
        c = paddle.to_tensor(np.asarray([0.0], np.float32))
        layers.increment(c)
        layers.increment(c)
        assert float(c.numpy()[0]) == 2.0
        arr = layers.create_array("float32")
        i = paddle.to_tensor(np.asarray([0], "int64"))
        layers.array_write(c, i, arr)
        got = layers.array_read(arr, i)
        assert float(got.numpy()[0]) == 2.0
        assert int(layers.array_length(arr).numpy()[0]) == 1


class TestLosses:
    def test_huber_matches_manual(self):
        x = paddle.to_tensor(np.asarray([0.0, 2.0], np.float32))
        y = paddle.to_tensor(np.asarray([0.5, 0.0], np.float32))
        out = layers.huber_loss(x, y, delta=1.0).numpy()
        np.testing.assert_allclose(out, [0.125, 1.5], rtol=1e-6)

    def test_rank_loss_gradient_and_value(self):
        label = paddle.to_tensor(np.asarray([[1.0]], np.float32))
        left = paddle.to_tensor(np.asarray([[2.0]], np.float32))
        right = paddle.to_tensor(np.asarray([[0.0]], np.float32))
        out = layers.rank_loss(label, left, right)
        want = np.log1p(np.exp(2.0)) - 2.0
        np.testing.assert_allclose(float(out.numpy()), want, rtol=1e-5)

    def test_bpr_loss_prefers_correct_item(self):
        logits = paddle.to_tensor(
            np.asarray([[4.0, 0.0, 0.0]], np.float32))
        good = layers.bpr_loss(logits,
                               paddle.to_tensor(np.asarray([[0]], "int64")))
        bad = layers.bpr_loss(logits,
                              paddle.to_tensor(np.asarray([[1]], "int64")))
        assert good.numpy().item() < bad.numpy().item()

    def test_edit_distance(self):
        a = paddle.to_tensor(np.asarray([[1, 2, 3, 0]], "int64"))
        b = paddle.to_tensor(np.asarray([[1, 3, 3, 0]], "int64"))
        la = paddle.to_tensor(np.asarray([3], "int64"))
        lb = paddle.to_tensor(np.asarray([3], "int64"))
        d, n = layers.edit_distance(a, b, normalized=False,
                                    input_length=la, label_length=lb)
        assert float(d.numpy()[0, 0]) == 1.0

    def test_center_loss_moves_centers_and_grads_input(self):
        x = paddle.to_tensor(np.ones((4, 3), np.float32))
        x.stop_gradient = False
        lab = paddle.to_tensor(np.zeros((4,), "int64"))
        loss = layers.center_loss(x, lab, num_classes=2, alpha=0.5)
        loss.sum().backward()
        assert x.grad is not None
        centers = layers._layer_cache[("center_loss_centers", 2, 3)]
        assert float(np.abs(centers.numpy()).sum()) > 0  # moved


class TestSequenceLod:
    def test_mask_pool_steps(self):
        lens = paddle.to_tensor(np.asarray([2, 3], "int64"))
        m = layers.sequence_mask(lens, maxlen=4)
        np.testing.assert_array_equal(
            m.numpy(), [[1, 1, 0, 0], [1, 1, 1, 0]])
        x = paddle.to_tensor(
            np.arange(24, dtype=np.float32).reshape(2, 4, 3))
        first = layers.sequence_first_step(x)
        np.testing.assert_allclose(first.numpy(), x.numpy()[:, 0])
        from paddle_tpu.core.lod import create_lod_tensor
        lt = create_lod_tensor(np.arange(10, dtype=np.float32)
                               .reshape(5, 2), [[2, 3]])
        pooled = layers.sequence_pool(lt, "sum")
        np.testing.assert_allclose(pooled.numpy()[0],
                                   [0 + 2, 1 + 3])

    def test_sequence_enumerate(self):
        x = paddle.to_tensor(np.asarray([[1, 2, 3]], "int64"))
        out = layers.sequence_enumerate(x, 2, pad_value=0).numpy()
        np.testing.assert_array_equal(out[0],
                                      [[1, 2], [2, 3], [3, 0]])


class TestDetection:
    def test_iou_similarity(self):
        a = paddle.to_tensor(np.asarray([[0, 0, 2, 2]], np.float32))
        b = paddle.to_tensor(np.asarray(
            [[0, 0, 2, 2], [1, 1, 3, 3], [4, 4, 5, 5]], np.float32))
        iou = layers.iou_similarity(a, b).numpy()
        np.testing.assert_allclose(iou[0], [1.0, 1.0 / 7.0, 0.0],
                                   rtol=1e-5)

    def test_box_coder_roundtrip(self):
        priors = paddle.to_tensor(np.asarray(
            [[0, 0, 2, 2], [1, 1, 4, 5]], np.float32))
        var = paddle.to_tensor(np.asarray([0.1, 0.1, 0.2, 0.2],
                                          np.float32))
        targets = paddle.to_tensor(np.asarray(
            [[0.5, 0.5, 2.5, 3.0]], np.float32))
        enc = layers.box_coder(priors, var, targets,
                               code_type="encode_center_size")
        dec = layers.box_coder(priors, var, enc,
                               code_type="decode_center_size", axis=0)
        np.testing.assert_allclose(
            dec.numpy()[0, 0], targets.numpy()[0], rtol=1e-4, atol=1e-4)

    def test_prior_box_grid(self):
        feat = paddle.to_tensor(np.zeros((1, 8, 2, 2), np.float32))
        img = paddle.to_tensor(np.zeros((1, 3, 64, 64), np.float32))
        boxes, var = layers.prior_box(feat, img, min_sizes=[16.0],
                                      aspect_ratios=[1.0, 2.0],
                                      clip=True)
        assert boxes.numpy().shape == (2, 2, 2, 4)
        assert (boxes.numpy() >= 0).all() and (boxes.numpy() <= 1).all()

    def test_multiclass_nms_shapes(self):
        boxes = paddle.to_tensor(np.asarray(
            [[[0, 0, 1, 1], [0, 0, 1.01, 1.01], [3, 3, 4, 4]]],
            np.float32))
        scores = paddle.to_tensor(np.asarray(
            [[[0.0, 0.0, 0.0], [0.9, 0.85, 0.1], [0.0, 0.0, 0.8]]],
            np.float32)).transpose((0, 2, 1))
        out, lens = layers.multiclass_nms(boxes, scores,
                                          score_threshold=0.5,
                                          nms_top_k=10, keep_top_k=10,
                                          background_label=-1)
        # overlapping boxes suppressed per class; two survivors expected
        assert int(lens.numpy()[0]) >= 2
        assert out.numpy().shape[1] == 6


class TestRNNSurface:
    def test_lstm_and_units(self):
        paddle.seed(0)
        x = paddle.to_tensor(
            np.random.RandomState(0).randn(2, 5, 8).astype("float32"))
        h0 = paddle.zeros([1, 2, 16])
        c0 = paddle.zeros([1, 2, 16])
        out, h, c = layers.lstm(x, h0, c0, max_len=5, hidden_size=16,
                                num_layers=1)
        assert out.numpy().shape == (2, 5, 16)
        ht, ct = layers.lstm_unit(
            paddle.to_tensor(np.ones((2, 8), np.float32)),
            paddle.zeros([2, 16]), paddle.zeros([2, 16]))
        assert ht.numpy().shape == (2, 16)

    def test_rnn_functional(self):
        paddle.seed(0)
        import paddle_tpu.nn as nn
        cell = nn.SimpleRNNCell(4, 6)
        x = paddle.to_tensor(
            np.random.RandomState(1).randn(3, 7, 4).astype("float32"))
        out, state = layers.rnn(cell, x)
        assert out.numpy().shape == (3, 7, 6)


def test_auc_single_shot():
    scores = paddle.to_tensor(np.asarray(
        [[0.2, 0.8], [0.9, 0.1], [0.3, 0.7], [0.6, 0.4]], np.float32))
    labels = paddle.to_tensor(np.asarray([[1], [0], [1], [0]], "int64"))
    val, _, _ = layers.auc(scores, labels)
    assert float(val.numpy()) == 1.0  # perfectly separable


def test_argsort_returns_values_then_indices():
    vals, idx = layers.argsort(
        paddle.to_tensor(np.asarray([3.0, 1.0, 2.0], np.float32)))
    assert list(vals.numpy()) == [1.0, 2.0, 3.0]
    assert list(idx.numpy()) == [1, 2, 0]


def test_rnncell_and_decoder_are_subclassable():
    class MyCell(layers.RNNCell):
        pass

    class MyDecoder(layers.Decoder):
        pass

    assert issubclass(MyCell, layers.RNNCell)


def test_prior_box_rectangular_steps():
    feat = paddle.to_tensor(np.zeros((1, 8, 2, 2), np.float32))
    img = paddle.to_tensor(np.zeros((1, 3, 32, 64), np.float32))
    boxes, _ = layers.prior_box(feat, img, min_sizes=[8.0],
                                steps=(32.0, 16.0), offset=0.5)
    b = boxes.numpy()[0, 0, 0]     # first cell center: (16, 8) px
    cx = (b[0] + b[2]) / 2 * 64    # denormalize by image width
    cy = (b[1] + b[3]) / 2 * 32
    np.testing.assert_allclose([cx, cy], [16.0, 8.0], atol=1e-4)


def test_box_coder_decode_axis1():
    priors = paddle.to_tensor(np.asarray(
        [[0, 0, 2, 2], [1, 1, 4, 5]], np.float32))
    var = np.asarray([[0.1, 0.1, 0.2, 0.2],
                      [0.1, 0.1, 0.2, 0.2]], np.float32)
    deltas = paddle.to_tensor(
        np.zeros((2, 3, 4), np.float32))   # [N_prior, M, 4]
    dec = layers.box_coder(priors, paddle.to_tensor(var), deltas,
                           code_type="decode_center_size", axis=1)
    # zero deltas decode back to the priors, broadcast along axis 1
    np.testing.assert_allclose(dec.numpy()[0, 0], [0, 0, 2, 2],
                               atol=1e-5)
    np.testing.assert_allclose(dec.numpy()[1, 2], [1, 1, 4, 5],
                               atol=1e-5)


class TestLRSchedulers:
    def test_decay_math(self):
        layers._step_counters.clear()
        # step 0
        lr = layers.exponential_decay(0.1, 10, 0.5)
        np.testing.assert_allclose(float(lr.numpy()), 0.1, rtol=1e-6)
        layers._step_counters["@LR_DECAY_COUNTER@"].value = \
            paddle.to_tensor(np.asarray([10], "int64")).value
        np.testing.assert_allclose(
            float(layers.exponential_decay(0.1, 10, 0.5).numpy()),
            0.05, rtol=1e-6)
        np.testing.assert_allclose(
            float(layers.inverse_time_decay(0.1, 10, 1.0).numpy()),
            0.05, rtol=1e-6)
        np.testing.assert_allclose(
            float(layers.piecewise_decay([5, 20], [0.1, 0.01, 0.001])
                  .numpy()), 0.01, rtol=1e-6)
        noam = float(layers.noam_decay(512, 4000).numpy())
        want = 512 ** -0.5 * min(11 ** -0.5, 11 * 4000 ** -1.5)
        np.testing.assert_allclose(noam, want, rtol=1e-5)
        layers._step_counters.clear()

    def test_warmup_switches(self):
        layers._step_counters.clear()
        lr = layers.linear_lr_warmup(0.1, warmup_steps=100,
                                     start_lr=0.0, end_lr=0.1)
        np.testing.assert_allclose(float(lr.numpy()), 0.0, atol=1e-7)
        layers._step_counters["@LR_DECAY_COUNTER@"].value = \
            paddle.to_tensor(np.asarray([200], "int64")).value
        np.testing.assert_allclose(
            float(layers.linear_lr_warmup(0.1, 100, 0.0, 0.1).numpy()),
            0.1, rtol=1e-6)
        layers._step_counters.clear()


def test_fluid_net_under_to_static():
    """fluid-style imperative code (implicit params via call-site reuse)
    compiles through to_static: losses decrease continuously across the
    eager -> record -> compiled transitions."""
    paddle.seed(0)
    layers.clear_layer_cache()
    x_np = np.random.RandomState(0).randn(8, 3, 8, 8).astype("float32")
    y_np = np.random.RandomState(0).randint(0, 4, (8,)).astype("int64")

    def net(x):
        h = layers.conv2d(x, 8, 3, padding=1, act="relu", name="c1")
        h = layers.pool2d(h, 2, "max", 2)
        h = layers.flatten(h, axis=1)
        return layers.fc(h, 4, name="out")

    state = {"opt": None}

    def step(x, y):
        logits = net(x)
        loss = layers.reduce_mean(
            layers.softmax_with_cross_entropy(logits, y.unsqueeze(-1)))
        if state["opt"] is None:
            params = []
            for item in layers._layer_cache.values():
                params.extend(item.parameters()
                              if hasattr(item, "parameters") else [item])
            state["opt"] = paddle.optimizer.Adam(5e-3, parameters=params)
        loss.backward()
        state["opt"].step()
        state["opt"].clear_grad()
        return loss

    compiled = paddle.jit.to_static(step)
    losses = [float(compiled(paddle.to_tensor(x_np),
                             paddle.to_tensor(y_np)).numpy())
              for _ in range(6)]
    assert losses[-1] < losses[0], losses
    assert all(b < a for a, b in zip(losses, losses[1:])), losses
