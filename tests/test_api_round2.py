"""Round-2 API-surface completion: 3D/1D pools, conv transposes, extra
losses (CTC/dice/focal/hsigmoid/...), RNN cell infra + BeamSearchDecoder,
grid_sample/affine_grid, inplace tensor methods. After these, paddle.nn,
paddle.nn.functional, paddle.io and the Tensor method list match the
reference __all__ name-for-name (audited against
/root/reference/python/paddle/*/__init__.py).
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.core.jax_compat import shard_map as _shard_map
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F


def T(a, dtype="float32"):
    return paddle.to_tensor(np.asarray(a, dtype=dtype))


def test_pool3d_matches_manual():
    rs = np.random.RandomState(0)
    x = rs.randn(2, 3, 4, 4, 4).astype("float32")
    out = np.asarray(F.max_pool3d(T(x), 2).numpy())
    ref = x.reshape(2, 3, 2, 2, 2, 2, 2, 2).max(axis=(3, 5, 7))
    np.testing.assert_allclose(out, ref, rtol=1e-6)
    out2 = np.asarray(F.avg_pool3d(T(x), 2).numpy())
    ref2 = x.reshape(2, 3, 2, 2, 2, 2, 2, 2).mean(axis=(3, 5, 7))
    np.testing.assert_allclose(out2, ref2, rtol=1e-5)
    np.testing.assert_allclose(
        np.asarray(F.adaptive_avg_pool3d(T(x), 2).numpy()), ref2,
        rtol=1e-5)
    # layer wrappers
    assert nn.MaxPool3D(2)(T(x)).shape == [2, 3, 2, 2, 2]
    assert nn.AdaptiveMaxPool1D(2)(
        T(rs.randn(2, 3, 8).astype("float32"))).shape == [2, 3, 2]


def test_conv1d_transpose_upsamples():
    paddle.seed(0)
    layer = nn.Conv1DTranspose(3, 5, kernel_size=4, stride=2, padding=1)
    x = T(np.random.RandomState(1).randn(2, 3, 8))
    out = layer(x)
    assert out.shape == [2, 5, 16]
    # grads flow
    out.sum().backward()
    assert layer.weight.grad is not None


def test_conv3d_transpose_shape():
    paddle.seed(0)
    layer = nn.Conv3DTranspose(2, 4, kernel_size=2, stride=2)
    x = T(np.random.RandomState(1).randn(1, 2, 3, 3, 3))
    assert layer(x).shape == [1, 4, 6, 6, 6]


def test_ctc_loss_matches_optax():
    import optax
    import jax.numpy as jnp
    rs = np.random.RandomState(0)
    Tn, B, C, L = 10, 2, 6, 3
    lp = rs.randn(Tn, B, C).astype("float32")
    labels = rs.randint(1, C, (B, L)).astype("int32")
    il = np.asarray([10, 8], "int64")
    ll = np.asarray([3, 2], "int64")
    out = F.ctc_loss(T(lp), T(labels, "int32"), T(il, "int64"),
                     T(ll, "int64"), reduction="none")
    t_idx = np.arange(Tn)[None, :]
    lpad = (t_idx >= il[:, None]).astype("float32")
    l_idx = np.arange(L)[None, :]
    labpad = (l_idx >= ll[:, None]).astype("float32")
    ref = optax.ctc_loss(jnp.transpose(jnp.asarray(lp), (1, 0, 2)),
                         jnp.asarray(lpad), jnp.asarray(labels),
                         jnp.asarray(labpad))
    np.testing.assert_allclose(np.asarray(out.numpy()), np.asarray(ref),
                               rtol=1e-5)
    # layer + mean reduction is finite and positive
    layer = nn.CTCLoss()
    val = float(layer(T(lp), T(labels, "int32"), T(il, "int64"),
                      T(ll, "int64")).numpy())
    assert np.isfinite(val) and val > 0


def test_small_losses():
    p = T([[0.8, 0.2]]); lab01 = T([[1.0, 0.0]])
    ll = np.asarray(F.log_loss(p, lab01).numpy())
    np.testing.assert_allclose(
        ll, [[-np.log(0.8 + 1e-4), -np.log(0.8 + 1e-4)]], rtol=1e-4)

    logits = T(np.random.RandomState(0).randn(4, 3))
    lab = T(np.random.RandomState(1).randint(0, 3, (4,)), "int64")
    probs = F.softmax(logits)
    d = float(F.dice_loss(probs, lab).numpy())
    assert 0 <= d <= 1

    fl = F.sigmoid_focal_loss(T(np.zeros((2, 3))),
                              T(np.ones((2, 3))), reduction="mean")
    assert float(fl.numpy()) > 0

    a = T(np.random.RandomState(2).randn(4, 8))
    pos = T(np.random.RandomState(3).randn(4, 8))
    labels = T([0, 0, 1, 1], "int64")
    assert np.isfinite(float(F.npair_loss(a, pos, labels).numpy()))


def test_hsigmoid_loss_trains():
    paddle.seed(0)
    layer = nn.HSigmoidLoss(8, num_classes=6)
    opt = paddle.optimizer.SGD(0.1, parameters=layer.parameters())
    x = T(np.random.RandomState(0).randn(16, 8))
    y = T(np.random.RandomState(1).randint(0, 6, (16,)), "int64")
    losses = []
    for _ in range(5):
        loss = layer(x, y).mean()
        loss.backward()
        opt.step()
        opt.clear_grad()
        losses.append(float(loss.numpy()))
    assert losses[-1] < losses[0]


def test_maxout_bilinear():
    x = T(np.arange(8, dtype="float32").reshape(1, 8, 1, 1))
    out = np.asarray(F.maxout(x, groups=2).numpy())
    # pairs (0..3, 4..7) grouped as [c//groups, groups] -> max over groups
    assert out.shape == (1, 4, 1, 1)
    b = nn.Bilinear(3, 4, 2)
    o = b(T(np.ones((5, 3))), T(np.ones((5, 4))))
    assert o.shape == [5, 2]
    assert np.isfinite(
        float(F.bilinear(T(np.ones((5, 3))), T(np.ones((5, 4))),
                         b.weight, None).numpy().sum()))


def test_grid_sample_identity_and_affine_grid():
    x = np.arange(16, dtype="float32").reshape(1, 1, 4, 4)
    theta = T(np.asarray([[[1.0, 0, 0], [0, 1.0, 0]]], "float32"))
    grid = F.affine_grid(theta, (1, 1, 4, 4), align_corners=True)
    assert grid.shape == [1, 4, 4, 2]
    out = F.grid_sample(T(x), grid, align_corners=True)
    np.testing.assert_allclose(np.asarray(out.numpy()), x, atol=1e-4)


def test_simple_rnn_cell_and_rnn_wrappers():
    paddle.seed(0)
    cell = nn.SimpleRNNCell(4, 8)
    x = T(np.random.RandomState(0).randn(2, 4))
    y, h = cell(x)
    assert y.shape == [2, 8]
    rnn = nn.RNN(cell)
    seq = T(np.random.RandomState(1).randn(2, 5, 4))
    out, last = rnn(seq)
    assert out.shape == [2, 5, 8]
    np.testing.assert_allclose(np.asarray(out.numpy()[:, -1]),
                               np.asarray(last.numpy()), rtol=1e-6)
    bi = nn.BiRNN(nn.SimpleRNNCell(4, 8), nn.SimpleRNNCell(4, 8))
    out2, _ = bi(seq)
    assert out2.shape == [2, 5, 16]
    # LSTMCell works through RNN too
    lc = nn.LSTMCell(4, 6)
    out3, (h3, c3) = nn.RNN(lc)(seq)
    assert out3.shape == [2, 5, 6] and c3.shape == [2, 6]


def test_beam_search_decode():
    paddle.seed(0)
    cell = nn.SimpleRNNCell(3, 8)
    proj = nn.Linear(8, 5)
    emb = nn.Embedding(5, 3)
    dec = nn.BeamSearchDecoder(cell, start_token=0, end_token=4,
                               beam_size=2, embedding_fn=emb,
                               output_fn=proj)
    inits = cell.get_initial_states(paddle.to_tensor(
        np.zeros((3, 3), "float32")))
    ids, _ = nn.dynamic_decode(dec, inits=inits, max_step_num=6)
    assert ids.shape == [3, 6, 2]
    v = np.asarray(ids.numpy())
    assert v.min() >= 0 and v.max() < 5


def test_inplace_tensor_methods():
    t = T([[4.0, 9.0]])
    r = t.sqrt_()
    assert r is t
    np.testing.assert_allclose(np.asarray(t.numpy()), [[2.0, 3.0]])
    t2 = T([1.0, 2.0])
    t2.add_(T([1.0, 1.0]))
    np.testing.assert_allclose(np.asarray(t2.numpy()), [2.0, 3.0])
    t3 = T([[1.0, 2.0]])
    t3.squeeze_()
    assert t3.shape == [2]
    t4 = T([-0.5, 0.5])
    t4.clip_(0.0, 1.0)
    np.testing.assert_allclose(np.asarray(t4.numpy()), [0.0, 0.5])
    # F inplace activations
    t5 = T([-1.0, 1.0])
    F.relu_(t5)
    np.testing.assert_allclose(np.asarray(t5.numpy()), [0.0, 1.0])


def test_new_tensor_method_bindings():
    t = T([[1.0, 2.0], [3.0, 4.0]])
    assert t.t().shape == [2, 2]
    np.testing.assert_allclose(
        np.asarray(t.concat([t, t], axis=0)[0].numpy())
        if False else np.asarray(paddle.concat([t, t], axis=0).numpy()),
        np.concatenate([t.numpy(), t.numpy()], 0))
    assert int(t.rank().numpy()) == 2
    assert t.digamma().shape == [2, 2]
    h = T([1, 2, 2, 3], "int64").bincount()
    np.testing.assert_array_equal(np.asarray(h.numpy()), [0, 1, 2, 1])
    assert not bool(t.is_empty().numpy())


def test_dropout_variants_shapes():
    x = T(np.ones((2, 3, 4, 4, 4)))
    net = nn.Dropout3D(0.5)
    net.train()
    out = net(x)
    assert out.shape == [2, 3, 4, 4, 4]
    net.eval()
    np.testing.assert_allclose(np.asarray(net(x).numpy()), x.numpy())
    ad = nn.AlphaDropout(0.3)
    ad.train()
    assert ad(T(np.ones((4, 4)))).shape == [4, 4]
    ad.eval()
    np.testing.assert_allclose(
        np.asarray(ad(T(np.ones((4, 4)))).numpy()), np.ones((4, 4)))


def test_pad_and_distance_layers():
    x = T(np.ones((1, 2, 4)))
    assert nn.Pad1D([1, 2])(x).shape == [1, 2, 7]
    x3 = T(np.ones((1, 1, 2, 2, 2)))
    assert nn.Pad3D(1)(x3).shape == [1, 1, 4, 4, 4]
    d = nn.PairwiseDistance()
    out = d(T(np.zeros((3, 4))), T(np.ones((3, 4))))
    np.testing.assert_allclose(np.asarray(out.numpy()), [2.0, 2.0, 2.0])
    u = nn.Unfold(2)
    assert u(T(np.ones((1, 1, 4, 4)))).shape[0] == 1


def test_module_surface_completion_smoke():
    """The remaining reference names added in the surface audit: static
    helpers, distributed send/recv/split, incubate LookAhead/ModelAverage,
    distribution MultivariateNormalDiag, jit/vision/utils shims."""
    from paddle_tpu import static, distributed, incubate, distribution

    # static helpers
    paddle.enable_static()
    try:
        prog = static.Program()
        with static.program_guard(prog):
            x = static.data("x", [None, 4], "float32")
            pred = static.nn.fc(x, 1, name="sfc")
            loss = paddle.mean(paddle.square(pred))
            grads = static.gradients(loss, prog.all_parameters())
            assert all(g.name.endswith("@GRAD") for g in grads)
        data = static.serialize_program([x], [pred], program=prog)
        prog2 = static.deserialize_program(data)
        assert len(prog2.global_block().ops) > 0
        pb = static.serialize_persistables([x], [pred], program=prog)
        static.deserialize_persistables(prog2, pb)
        st = static.save_program_state(prog)
        static.set_program_state(prog2, st)
        assert static.BuildStrategy().memory_optimize
        assert static.ExecutionStrategy().num_threads == 1
        assert static.cpu_places(2) and static.cuda_places([0])
        with static.name_scope("blk"), static.device_guard("cpu"):
            pass
        assert static.global_scope() is not None
    finally:
        paddle.disable_static()

    # incubate optimizers
    paddle.seed(0)
    net = nn.Linear(4, 2)
    inner = paddle.optimizer.SGD(0.1, parameters=net.parameters())
    la = incubate.LookAhead(inner, alpha=0.5, k=2)
    x = T(np.ones((4, 4)))
    for _ in range(4):
        loss = (net(x) ** 2).mean()
        loss.backward()
        la.step()
        la.clear_grad()
    ma = incubate.ModelAverage(parameters=net.parameters())
    w_before = np.asarray(net.weight.numpy())
    for _ in range(3):
        ma.step()
    ma.apply()
    np.testing.assert_allclose(np.asarray(net.weight.numpy()), w_before,
                               rtol=1e-6)  # average of constant = itself
    ma.restore()

    out = incubate.softmax_mask_fuse_upper_triangle(
        T(np.zeros((1, 1, 4, 4))))
    v = np.asarray(out.numpy())[0, 0]
    np.testing.assert_allclose(v[0], [1, 0, 0, 0], atol=1e-6)

    # distribution
    d = distribution.MultivariateNormalDiag(
        T(np.zeros(3)), T(np.diag(np.ones(3, "float32"))))
    assert d.sample((2,)).shape == [2, 3]
    assert np.isfinite(float(d.entropy().numpy()))

    # distributed split factory (single-device: plain layers)
    h = distributed.split(T(np.ones((2, 4))), (4, 6), "linear", axis=1)
    assert h.shape == [2, 6]
    emb = distributed.split(T([0, 1], "int64"), (10, 4), "embedding")
    assert emb.shape == [2, 4]
    assert distributed.InMemoryDataset is not None
    assert distributed.ProbabilityEntry(0.5).probability == 0.5

    # jit / vision / utils shims
    pt = paddle.jit.ProgramTranslator.get_instance()
    pt.enable(True)
    paddle.utils.require_version("0.0.1")
    assert paddle.vision.get_image_backend() in ("pil", "cv2")


def test_conv_transpose_groups_and_output_padding():
    paddle.seed(0)
    layer = nn.Conv1DTranspose(4, 6, kernel_size=3, stride=2, groups=2)
    x = T(np.random.RandomState(0).randn(1, 4, 10))
    out = layer(x)
    assert out.shape == [1, 6, 21]
    out.sum().backward()
    # output_padding extends the right edge
    out2 = F.conv1d_transpose(x, layer.weight, None, stride=2,
                              output_padding=1, groups=2)
    assert out2.shape == [1, 6, 22]


def test_avg_pool3d_exclusive_padding():
    x = T(np.ones((1, 1, 2, 2, 2)))
    out = np.asarray(F.avg_pool3d(x, 2, stride=2, padding=1).numpy())
    # paddle default exclusive=True: padded cells excluded -> corners 1.0
    np.testing.assert_allclose(out, np.ones_like(out), rtol=1e-6)
    out_inc = np.asarray(F.avg_pool3d(x, 2, stride=2, padding=1,
                                      exclusive=False).numpy())
    np.testing.assert_allclose(out_inc, 0.125 * np.ones_like(out_inc),
                               rtol=1e-6)


def test_pool3d_ceil_mode():
    x = T(np.random.RandomState(0).randn(1, 1, 6, 6, 6))
    # (6-3)/2 is fractional: ceil adds the partial window
    assert F.max_pool3d(x, 3, stride=2, ceil_mode=True).shape \
        == [1, 1, 3, 3, 3]
    assert F.max_pool3d(x, 3, stride=2, ceil_mode=False).shape \
        == [1, 1, 2, 2, 2]
    # NDHWC supported since r3 (transposed around the NCDHW kernel)
    x_c_last = T(np.random.RandomState(0).randn(1, 6, 6, 6, 2))
    assert F.max_pool3d(x_c_last, 2, data_format="NDHWC").shape \
        == [1, 3, 3, 3, 2]


def test_grid_sample_border_padding():
    x = np.arange(4, dtype="float32").reshape(1, 1, 2, 2)
    # grid far out of range: border clamps to edge values, zeros gives 0
    grid = T(np.full((1, 1, 1, 2), 5.0, "float32"))
    z = float(F.grid_sample(T(x), grid, padding_mode="zeros").numpy())
    b = float(F.grid_sample(T(x), grid, padding_mode="border").numpy())
    assert z == 0.0
    assert b == 3.0  # bottom-right value


def test_beam_search_beams_diverge_and_freeze():
    paddle.seed(0)
    cell = nn.SimpleRNNCell(3, 8)
    proj = nn.Linear(8, 5)
    emb = nn.Embedding(5, 3)
    dec = nn.BeamSearchDecoder(cell, start_token=0, end_token=4,
                               beam_size=3, embedding_fn=emb,
                               output_fn=proj)
    inits = cell.get_initial_states(paddle.to_tensor(
        np.zeros((2, 3), "float32")))
    ids, _ = nn.dynamic_decode(dec, inits=inits, max_step_num=8)
    v = np.asarray(ids.numpy())  # [B, T, beam]
    # beams must NOT be identical copies (the old all-zeros init bug)
    assert not (np.array_equal(v[:, :, 0], v[:, :, 1])
                and np.array_equal(v[:, :, 1], v[:, :, 2])), v
    # once a beam hits end_token, it only re-emits end_token
    for bi in range(v.shape[0]):
        for k in range(v.shape[2]):
            seq = v[bi, :, k]
            hits = np.nonzero(seq == 4)[0]
            if len(hits):
                assert np.all(seq[hits[0]:] == 4), seq


def test_send_recv_spmd_edge():
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P
    from paddle_tpu.distributed import topology, fleet, collective
    from paddle_tpu.distributed.fleet import DistributedStrategy
    strategy = DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": 8}
    fleet.init(is_collective=True, strategy=strategy)
    mesh = fleet.get_hybrid_communicate_group().mesh
    g = collective.get_group(0)

    def body(v):
        from paddle_tpu.core.tensor import Tensor
        t = Tensor(v)
        out = collective.send(t, dst=3, group=g, src=1)
        return out.value

    x = jnp.arange(8, dtype=jnp.float32).reshape(8, 1)
    out = _shard_map(body, mesh=mesh, in_specs=(P("dp"),),
                        out_specs=P("dp"))(x)
    res = np.asarray(out).reshape(-1)
    assert res[3] == 1.0          # rank 3 received rank 1's value
    assert res[1] == 0.0          # non-destination ranks zeroed
    with pytest.raises(Exception):
        _shard_map(
            lambda v: collective.recv(
                __import__("paddle_tpu").core.tensor.Tensor(v),
                src=1, group=g).value,
            mesh=mesh, in_specs=(P("dp"),), out_specs=P("dp"))(x)
    topology._HYBRID = None
