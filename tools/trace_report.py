#!/usr/bin/env python
"""trace_report — assemble per-replica span rings into end-to-end
request traces and render the TTFT critical path.

Inputs are trace surfaces, mixed freely:

  * URLs — a replica's ``host:port`` (scrapes ``/debug/traces``) or a
    full path like ``http://host:port/router/trace`` (the router's
    ring). Scrapes stamp the round trip, so cross-host clock skew is
    bounded and skew-ambiguous orderings are flagged in the timeline.
  * Files — saved ``/debug/traces`` JSON bodies (``-`` reads one from
    stdin), offset-free (same-host clocks).

Renders, per assembled trace, the end-to-end timeline (one row per
span: relative start, duration, replica, name) and, over the whole
cohort, the nine-segment TTFT decomposition (median/p99 ms per
segment + the unattributed gap). ``--chrome OUT.json`` additionally
writes the cross-process chrome://tracing export (one pid per
replica, flow arrows linking the hops).

Exit code: 0 — every requested trace assembled complete (all nine
canonical segments present); 1 — a requested trace is missing or
incomplete; 2 — unreadable input / nothing to assemble. Tier-1
self-runs this against a live 1P+1D in-process handoff
(tests/test_trace.py), the same discipline as incident_report /
cache_report / fleet_top.

Usage: python tools/trace_report.py SOURCE [SOURCE...]
           [--trace ID]... [--breakdown-only] [--chrome OUT.json]
           [--json] [--timeout S]

Zero heavy imports (no jax, no paddle_tpu package import): the
assembler modules load by file path, so this starts in milliseconds
against a live fleet.
"""
import argparse
import importlib.util
import json
import os
import sys
import types

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_trace_modules():
    """Load observability/trace/{context,spans,assembler} as a
    synthetic package by file path — the assembler without the
    paddle_tpu package import (which would pull jax)."""
    pkgdir = os.path.join(_REPO, "paddle_tpu", "observability",
                          "trace")
    pkg = types.ModuleType("_pt_trace")
    pkg.__path__ = [pkgdir]
    sys.modules["_pt_trace"] = pkg
    mods = {}
    for name in ("context", "spans", "assembler"):
        spec = importlib.util.spec_from_file_location(
            f"_pt_trace.{name}", os.path.join(pkgdir, name + ".py"))
        mod = importlib.util.module_from_spec(spec)
        sys.modules[f"_pt_trace.{name}"] = mod
        spec.loader.exec_module(mod)
        mods[name] = mod
    return mods


def _table(headers, rows, out):
    widths = [max(len(h), *(len(r[i]) for r in rows)) if rows
              else len(h) for i, h in enumerate(headers)]
    print("  ".join(h.ljust(w) for h, w in zip(headers, widths)),
          file=out)
    print("  ".join("-" * w for w in widths), file=out)
    for r in rows:
        print("  ".join(c.ljust(w) for c, w in zip(r, widths)),
              file=out)


def _fmt_ms(v):
    return "-" if v is None else f"{v:.3f}"


def render_trace(trace, out=sys.stdout):
    """One trace's header + timeline table."""
    flags = []
    if not trace.complete:
        flags.append("INCOMPLETE: missing "
                     + ", ".join(trace.missing_segments()))
    gap = trace.unattributed_ms()
    frac = trace.unattributed_frac()
    print(f"trace {trace.trace_id}  replicas="
          f"{','.join(trace.replicas)}  "
          f"window={_fmt_ms(trace.window_ms())}ms  "
          f"unattributed={_fmt_ms(gap)}ms"
          + (f" ({frac:.1%})" if frac is not None else ""),
          file=out)
    for f in flags:
        print(f"  {f}", file=out)
    rows = []
    for r in trace.timeline():
        rows.append((
            f"{r['t_rel_ms']:.3f}", f"{r['dur_ms']:.3f}",
            r["replica"][:20], r["name"],
            "skew?" if r["skew_ambiguous"] else "",
        ))
    _table(("T_REL_MS", "DUR_MS", "REPLICA", "SPAN", "FLAGS"), rows,
           out)


def render_breakdown(breakdown, out=sys.stdout):
    """The cohort TTFT decomposition table."""
    print(f"ttft breakdown over {breakdown['count']} trace(s) "
          f"({breakdown['complete']} complete): "
          f"window median={_fmt_ms(breakdown['ttft']['median_ms'])}ms "
          f"p99={_fmt_ms(breakdown['ttft']['p99_ms'])}ms", file=out)
    rows = []
    for name, s in breakdown["segments"].items():
        rows.append((name, _fmt_ms(s["median_ms"]),
                     _fmt_ms(s["p99_ms"]), str(s["count"])))
    un = breakdown["unattributed"]
    frac = un.get("median_frac")
    rows.append(("(unattributed)", _fmt_ms(un["median_ms"]),
                 _fmt_ms(un["p99_ms"]),
                 "-" if frac is None else f"{frac:.1%}"))
    _table(("SEGMENT", "MEDIAN_MS", "P99_MS", "COUNT"), rows, out)


def main(argv=None):
    parser = argparse.ArgumentParser(
        description="assemble /debug/traces rings into end-to-end "
                    "request traces; exit 0 iff every requested "
                    "trace is complete")
    parser.add_argument("sources", nargs="+",
                        help="trace surfaces: URLs (host:port or "
                             "http://.../router/trace) and/or saved "
                             "/debug/traces JSON files ('-' = stdin)")
    parser.add_argument("--trace", action="append", default=None,
                        metavar="ID",
                        help="render only this trace id (repeatable; "
                             "default: every assembled trace)")
    parser.add_argument("--breakdown-only", action="store_true",
                        help="skip per-trace timelines, print only "
                             "the cohort segment decomposition")
    parser.add_argument("--chrome", default=None, metavar="OUT.json",
                        help="also write the cross-process "
                             "chrome://tracing export")
    parser.add_argument("--json", action="store_true",
                        help="dump assembled traces + breakdown as "
                             "JSON instead of tables")
    parser.add_argument("--timeout", type=float, default=5.0,
                        help="per-URL scrape timeout seconds")
    args = parser.parse_args(argv)

    mods = _load_trace_modules()
    asm = mods["assembler"].TraceAssembler()
    for src in args.sources:
        try:
            if src == "-":
                asm.add_body(json.load(sys.stdin))
            elif os.path.exists(src):
                with open(src, encoding="utf-8") as fh:
                    asm.add_body(json.load(fh))
            else:
                asm.scrape(src, timeout=args.timeout)
        except Exception as e:   # noqa: BLE001 - CLI verdict, exit 2
            print(f"ERROR: cannot read {src}: "
                  f"{type(e).__name__}: {e}", file=sys.stderr)
            return 2

    wanted = args.trace
    traces = []
    missing_ids = []
    if wanted:
        for tid in wanted:
            t = asm.assemble(tid)
            if t is None:
                missing_ids.append(tid)
            else:
                traces.append(t)
    else:
        traces = asm.assemble_all()
    if not traces and not missing_ids:
        print("ERROR: no traces assembled from "
              f"{len(args.sources)} source(s)", file=sys.stderr)
        return 2

    breakdown = mods["assembler"].ttft_breakdown(traces)
    if args.json:
        print(json.dumps({
            "traces": [t.as_dict() for t in traces],
            "ttft_breakdown": breakdown,
            "missing_trace_ids": missing_ids,
        }, indent=1, sort_keys=True))
    else:
        if not args.breakdown_only:
            for t in traces:
                render_trace(t)
                print()
        render_breakdown(breakdown)

    if args.chrome:
        with open(args.chrome, "w", encoding="utf-8") as fh:
            json.dump(mods["assembler"].chrome_trace(traces), fh)
        print(f"chrome trace written: {args.chrome}",
              file=sys.stderr)

    rc = 0
    for tid in missing_ids:
        print(f"INCOMPLETE: trace {tid} not found in any source",
              file=sys.stderr)
        rc = 1
    # a REQUESTED trace must be whole (the unfiltered sweep renders
    # monolithic traces too, which legitimately lack the handoff
    # segments — only --trace selections gate completeness)
    if wanted:
        for t in traces:
            if not t.complete:
                print(f"INCOMPLETE: trace {t.trace_id} missing "
                      + ", ".join(t.missing_segments()),
                      file=sys.stderr)
                rc = 1
    return rc


if __name__ == "__main__":
    sys.exit(main())
