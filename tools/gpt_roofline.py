"""Analytical roofline budget for the GPT-124M single-chip train step.

Computes, from first principles, where the step time HAS to go on a
v5e-class chip (197 TFLOP/s bf16 MXU, ~819 GB/s HBM): dense matmul
FLOPs, attention FLOPs (causal-halved), LM-head cost (fused vs
unfused), optimizer + parameter HBM traffic, and activation traffic.
Pairs with tools/mfu_analysis.py's measured perfetto breakdown: the
measured bucket that most exceeds its roofline line is the next lever.

Usage: python tools/gpt_roofline.py [batch seq] (default 8 1024)
"""
import json
import sys

PEAK_FLOPS = 197e12        # v5e bf16
HBM_BPS = 819e9            # v5e HBM bandwidth

# GPT-124M
L, H, V, HEADS = 12, 768, 50304, 12


def budget(batch, seq, mxu_eff=1.0, hbm_eff=1.0):
    t = batch * seq
    # dense body matmuls: qkv+proj (4H^2/layer) + mlp (8H^2/layer),
    # fwd + 2x bwd
    body_params = L * 12 * H * H
    body_flops = 6.0 * body_params * t
    # attention score+value matmuls: 2 matmuls x 2*T*seq*H per layer
    # fwd, 2x that bwd; causal -> half the blocks are skipped
    attn_flops = 0.5 * 3 * 2 * 2 * t * seq * H * L
    # LM head (tied embedding): fwd logits + bwd dx + bwd dW
    head_flops = 3 * 2.0 * t * H * V
    head_flops_fused_pallas = 5 * 2.0 * t * H * V  # +2 recomputes
    # optimizer/params HBM (O2: bf16 weights, f32 master+moments):
    # fwd read Wbf16, bwd read Wbf16 + write Gbf16, opt reads
    # G+m+v+master, writes m+v+master+Wbf16
    n_params = body_params + V * H + seq * H
    opt_bytes = n_params * (2 + 2 + 2 + 4 * 4 + 4 * 3 + 2)
    # activation traffic: ~10 layer-intermediate [T, H] bf16 tensors
    # per layer written fwd + read bwd
    act_bytes = 2 * 10 * L * t * H * 2
    # unfused head logits traffic: write [T, V] bf16 + read in
    # softmax-CE fwd, dlogits write + 2 reads bwd
    logits_bytes = 5 * t * V * 2

    ms = lambda fl, by: round(max(fl / (PEAK_FLOPS * mxu_eff),
                                  by / (HBM_BPS * hbm_eff)) * 1e3, 2)
    rows = {
        "body_matmuls": ms(body_flops, 0),
        "attention(causal)": ms(attn_flops, 0),
        "head_unfused": ms(head_flops, logits_bytes),
        "head_fused_pallas(2 recomputes)": ms(head_flops_fused_pallas, 0),
        "optimizer+params_hbm": ms(0, opt_bytes),
        "activations_hbm": ms(0, act_bytes),
    }
    floor_unfused = (rows["body_matmuls"] + rows["attention(causal)"]
                     + rows["head_unfused"]
                     + rows["optimizer+params_hbm"])
    model_flops = 6.0 * (n_params) * t + attn_flops
    return {
        "config": {"batch": batch, "seq": seq,
                   "mxu_eff": mxu_eff, "hbm_eff": hbm_eff},
        "per_component_ms": rows,
        "step_floor_ms_unfused_head": round(floor_unfused, 2),
        "mfu_at_floor": round(
            model_flops / (floor_unfused / 1e3) / PEAK_FLOPS, 3),
    }


def main():
    batch = int(sys.argv[1]) if len(sys.argv) > 1 else 8
    seq = int(sys.argv[2]) if len(sys.argv) > 2 else 1024
    # ideal floor and a realistic-efficiency scenario
    for mxu, hbm in ((1.0, 1.0), (0.6, 0.7)):
        print(json.dumps(budget(batch, seq, mxu, hbm)))


if __name__ == "__main__":
    main()
