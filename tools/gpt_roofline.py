"""Analytical roofline budget for the GPT-124M single-chip train step
AND (--decode) the serving decode step.

Computes, from first principles, where the step time HAS to go on a
v5e-class chip (197 TFLOP/s bf16 MXU, ~819 GB/s HBM): dense matmul
FLOPs, attention FLOPs (causal-halved), LM-head cost (fused vs
unfused), optimizer + parameter HBM traffic, and activation traffic.
Pairs with tools/mfu_analysis.py's measured perfetto breakdown: the
measured bucket that most exceeds its roofline line is the next lever.

``--decode`` switches to the serving decode-step HBM model (ROADMAP
direction #2's "roofline first" step, shared with the engine's
snapshot()["perf"] via paddle_tpu/observability/perf/roofline.py,
loaded directly by file so this tool never imports jax): KV-read
bytes per token as a function of batch, context length, heads and
layout (contiguous / paged_xla / paged_pallas), the parameter re-read
every step pays, and the resulting per-step floor — printed for all
THREE layouts so the XLA gather-materialization tax, and what the
Pallas paged-attention kernel (PADDLE_PAGED_ATTN) buys back by
deleting it, are numbers, not vibes.

Usage: python tools/gpt_roofline.py [batch seq]           (train step)
       python tools/gpt_roofline.py --decode [batch ctx]  (decode step)
"""
import importlib.util
import json
import os
import sys

PEAK_FLOPS = 197e12        # v5e bf16
HBM_BPS = 819e9            # v5e HBM bandwidth

# GPT-124M
L, H, V, HEADS = 12, 768, 50304, 12
MAX_SEQ = 1024


def budget(batch, seq, mxu_eff=1.0, hbm_eff=1.0):
    t = batch * seq
    # dense body matmuls: qkv+proj (4H^2/layer) + mlp (8H^2/layer),
    # fwd + 2x bwd
    body_params = L * 12 * H * H
    body_flops = 6.0 * body_params * t
    # attention score+value matmuls: 2 matmuls x 2*T*seq*H per layer
    # fwd, 2x that bwd; causal -> half the blocks are skipped
    attn_flops = 0.5 * 3 * 2 * 2 * t * seq * H * L
    # LM head (tied embedding): fwd logits + bwd dx + bwd dW
    head_flops = 3 * 2.0 * t * H * V
    head_flops_fused_pallas = 5 * 2.0 * t * H * V  # +2 recomputes
    # optimizer/params HBM (O2: bf16 weights, f32 master+moments):
    # fwd read Wbf16, bwd read Wbf16 + write Gbf16, opt reads
    # G+m+v+master, writes m+v+master+Wbf16
    n_params = body_params + V * H + seq * H
    opt_bytes = n_params * (2 + 2 + 2 + 4 * 4 + 4 * 3 + 2)
    # activation traffic: ~10 layer-intermediate [T, H] bf16 tensors
    # per layer written fwd + read bwd
    act_bytes = 2 * 10 * L * t * H * 2
    # unfused head logits traffic: write [T, V] bf16 + read in
    # softmax-CE fwd, dlogits write + 2 reads bwd
    logits_bytes = 5 * t * V * 2

    ms = lambda fl, by: round(max(fl / (PEAK_FLOPS * mxu_eff),
                                  by / (HBM_BPS * hbm_eff)) * 1e3, 2)
    rows = {
        "body_matmuls": ms(body_flops, 0),
        "attention(causal)": ms(attn_flops, 0),
        "head_unfused": ms(head_flops, logits_bytes),
        "head_fused_pallas(2 recomputes)": ms(head_flops_fused_pallas, 0),
        "optimizer+params_hbm": ms(0, opt_bytes),
        "activations_hbm": ms(0, act_bytes),
    }
    floor_unfused = (rows["body_matmuls"] + rows["attention(causal)"]
                     + rows["head_unfused"]
                     + rows["optimizer+params_hbm"])
    model_flops = 6.0 * (n_params) * t + attn_flops
    return {
        "config": {"batch": batch, "seq": seq,
                   "mxu_eff": mxu_eff, "hbm_eff": hbm_eff},
        "per_component_ms": rows,
        "step_floor_ms_unfused_head": round(floor_unfused, 2),
        "mfu_at_floor": round(
            model_flops / (floor_unfused / 1e3) / PEAK_FLOPS, 3),
    }


def _load_roofline_module():
    """Load observability/perf/roofline.py by file path: pure stdlib
    module, no paddle_tpu (= no jax) import at tool startup."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    path = os.path.join(repo, "paddle_tpu", "observability", "perf",
                        "roofline.py")
    spec = importlib.util.spec_from_file_location("_ptpu_roofline",
                                                  path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def decode_budget(batch, ctx):
    """Decode-step HBM model for GPT-124M at (batch slots, ctx cached
    positions), all three KV layouts — contiguous, XLA-composed paged
    gather, and the in-place Pallas paged kernel — bf16 params/KV on
    the v5e reference chip."""
    rf = _load_roofline_module()
    n_params = L * 12 * H * H + V * H + MAX_SEQ * H
    out = {"config": {"batch": batch, "ctx": ctx, "model": "gpt-124m",
                      "peak_flops": PEAK_FLOPS, "hbm_bps": HBM_BPS}}
    for layout in rf.LAYOUTS:
        m = rf.decode_step_model(
            batch=batch, kv_len=ctx, num_layers=L, num_heads=HEADS,
            head_dim=H // HEADS, n_params=n_params, param_bytes=2,
            kv_bytes=2, layout=layout, live_kv_len=ctx,
            peak_flops=PEAK_FLOPS, hbm_bps=HBM_BPS)
        out[layout] = {
            "gather_factor": m["gather_factor"],
            "kv_read_bytes_per_token": m["kv_read_bytes_per_token"],
            "bytes_total": m["bytes_total"],
            "flops": m["flops"],
            "arithmetic_intensity": round(m["arithmetic_intensity"], 4),
            "floor_us_per_step": round(m["floor_s"] * 1e6, 3),
            "tokens_per_sec_at_floor": round(
                batch / m["floor_s"], 1),
            "bound": m["bound"],
        }
    out["paged_gather_tax"] = round(
        out["paged_xla"]["floor_us_per_step"]
        / out["contiguous"]["floor_us_per_step"], 3)
    # what the Pallas kernel buys back at the floor: the whole tax
    out["pallas_vs_paged_xla_x"] = round(
        out["paged_xla"]["floor_us_per_step"]
        / out["paged_pallas"]["floor_us_per_step"], 3)
    return out


def main():
    args = [a for a in sys.argv[1:] if a != "--decode"]
    if "--decode" in sys.argv[1:]:
        batch = int(args[0]) if args else 8
        ctx = int(args[1]) if len(args) > 1 else 1024
        print(json.dumps(decode_budget(batch, ctx)))
        return
    batch = int(args[0]) if args else 8
    seq = int(args[1]) if len(args) > 1 else 1024
    # ideal floor and a realistic-efficiency scenario
    for mxu, hbm in ((1.0, 1.0), (0.6, 0.7)):
        print(json.dumps(budget(batch, seq, mxu, hbm)))


if __name__ == "__main__":
    main()
