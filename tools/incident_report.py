#!/usr/bin/env python
"""Pretty-print a health-observatory incident bundle (or /debug/health
report) and exit nonzero when unhealthy.

The serving engine's health observatory (paddle_tpu.observability.
health) dumps a JSON incident bundle the moment a detector fires —
detector verdict, last-K step-ledger rows, metrics snapshot, active
request traces, host-span tail. This CLI renders the triage view a
human (or a CI gate) wants first:

  * the header: which detector fired, when, on what step, why;
  * the ledger tail as a table — the per-step flight data leading up
    to the anomaly (step id, wall/dispatch/sync ms, queue, slots,
    tokens, compiles), with the firing step marked;
  * top regressed step phases: the tail rows' wall/dispatch/sync
    columns compared, final stretch vs the window median, sorted by
    regression — "sync went 14x" beats eyeballing raw JSON;
  * the engine vitals from the embedded metrics snapshot;
  * the assembled distributed traces of requests in flight at capture
    time — each victim's cross-replica critical path (ISSUE 18);
  * the top tenants by token share at capture time — who was
    hammering the engine when the detector fired (ISSUE 19).

Exit status is the CI contract: an incident bundle is by definition
UNHEALTHY -> exit 1; a ``/debug/health`` body (the ``{healthy, ...}``
shape) exits 0 iff ``healthy`` — so
``python tools/incident_report.py <(curl .../debug/health)`` is a
readiness probe. Wired into tier-1 via tests/test_health.py, which
self-runs it against a synthetic incident.

Usage: python tools/incident_report.py PATH [--tail N]
"""
import argparse
import json
import sys

_TAIL_COLS = (
    ("step", "step", "{:d}"),
    ("wall_ms", "wall_s", None),       # seconds -> ms, special-cased
    ("disp_ms", "dispatch_s", None),
    ("sync_ms", "sync_s", None),
    ("queue", "queue_depth", "{:d}"),
    ("slots", "occupied_slots", "{:d}"),
    ("admit", "admitted", "{:d}"),
    ("toks", "tokens", "{:d}"),
    ("done", "completed", "{:d}"),
    ("shed", "shed", "{:d}"),
    ("compile", "new_compiles", "{:d}"),
    ("thrash", "cache_thrash", "{:d}"),      # cache pressure (PR 13):
    ("evict_d", "pool_evictable_delta", "{:d}"),  # None -> "-" (legacy)
)


def _fmt_cell(key, row):
    v = row.get(key)
    if v is None:
        return "-"
    if key in ("wall_s", "dispatch_s", "sync_s"):
        return f"{float(v) * 1000.0:.2f}"
    try:
        return f"{int(v):d}"
    except (TypeError, ValueError):
        return str(v)


def _median(xs):
    s = sorted(xs)
    n = len(s)
    if not n:
        return 0.0
    mid = n // 2
    return s[mid] if n % 2 else 0.5 * (s[mid - 1] + s[mid])


def render_ledger_table(rows, mark_step=None, out=sys.stdout):
    """Fixed-width table of ledger rows, the anomaly step marked."""
    headers = [h for h, _, _ in _TAIL_COLS]
    table = [[_fmt_cell(key, r) for _, key, _ in _TAIL_COLS]
             for r in rows]
    widths = [max(len(h), *(len(t[i]) for t in table)) if table
              else len(h) for i, h in enumerate(headers)]
    line = "  " + "  ".join(h.rjust(w) for h, w in zip(headers, widths))
    print(line, file=out)
    print("  " + "-" * (len(line) - 2), file=out)
    for r, cells in zip(rows, table):
        mark = "<<" if mark_step is not None \
            and r.get("step") == mark_step else "  "
        print("  " + "  ".join(c.rjust(w) for c, w in
                               zip(cells, widths)) + " " + mark,
              file=out)


def regressed_phases(rows, final_n=3):
    """[(phase, final_avg_s, median_s, ratio)] sorted by ratio desc:
    the tail's last ``final_n`` rows against the whole-tail median per
    timed phase column — which part of the step blew up."""
    out = []
    if len(rows) < 2:
        return out
    final = rows[-final_n:]
    for phase in ("wall_s", "dispatch_s", "sync_s"):
        series = [float(r.get(phase) or 0.0) for r in rows]
        med = _median(series)
        fin = sum(float(r.get(phase) or 0.0)
                  for r in final) / len(final)
        ratio = fin / med if med > 0 else (float("inf") if fin > 0
                                           else 1.0)
        out.append((phase, fin, med, ratio))
    out.sort(key=lambda e: -e[3])
    return out


def report_incident(bundle, tail=None, out=sys.stdout):
    verdict = bundle.get("verdict") or {}
    print(f"INCIDENT  detector={bundle.get('detector')}  "
          f"written_at={bundle.get('written_at')}", file=out)
    print(f"  step:   {verdict.get('step')}", file=out)
    print(f"  reason: {verdict.get('reason')}", file=out)
    extras = {k: v for k, v in verdict.items()
              if k not in ("detector", "step", "reason")}
    if extras:
        print(f"  facts:  {json.dumps(extras, sort_keys=True)}",
              file=out)
    rows = bundle.get("ledger_tail") or []
    if tail is not None:
        rows = rows[-tail:]
    if rows:
        print(f"\nLEDGER TAIL ({len(rows)} steps)", file=out)
        render_ledger_table(rows, mark_step=verdict.get("step"),
                            out=out)
        print("\nTOP REGRESSED STEP PHASES (final 3 steps vs tail "
              "median)", file=out)
        for phase, fin, med, ratio in regressed_phases(rows):
            rtxt = "inf" if ratio == float("inf") else f"{ratio:.2f}x"
            print(f"  {phase:<11} {fin * 1000.0:9.2f}ms vs "
                  f"{med * 1000.0:9.2f}ms  ({rtxt})", file=out)
    snap = bundle.get("metrics") or {}
    if snap:
        print("\nENGINE VITALS", file=out)
        for key in ("tokens_per_sec", "queue_depth", "slot_occupancy",
                    "requests_admitted", "requests_completed",
                    "compiles", "speculative_masked"):
            if key in snap:
                print(f"  {key:<20} {snap[key]}", file=out)
        sched = snap.get("scheduler") or {}
        if sched:
            print(f"  policy               {sched.get('policy')}  "
                  f"shed_total={sched.get('shed_total')}", file=out)
    wd = bundle.get("watchdog") or {}
    if isinstance(wd, dict) and wd.get("steady_state_compiles"):
        print(f"\nWATCHDOG  steady_state_compiles="
              f"{wd['steady_state_compiles']}", file=out)
        for e in (wd.get("steady_state_events") or [])[:3]:
            print(f"  {e.get('key')} at {e.get('call_site')}",
                  file=out)
    reqs = bundle.get("requests") or {}
    active = reqs.get("active") if isinstance(reqs, dict) else None
    if active:
        print(f"\nACTIVE REQUESTS ({len(active)})", file=out)
        for t in active[:8]:
            events = [e.get("event") for e in t.get("events", [])]
            print(f"  rid={t.get('rid')}  last={events[-1] if events else '?'}"
                  f"  events={len(events)}", file=out)
    traces = bundle.get("traces")
    if traces:
        # assembled distributed traces of requests in flight at
        # capture time (ISSUE 18): where each victim's TTFT went,
        # cross-replica, as of the anomaly
        print(f"\nIN-FLIGHT TRACES ({len(traces)})", file=out)
        for t in traces[:4]:
            segs = t.get("segments") or {}
            window = t.get("window_ms")
            gap = t.get("unattributed_ms")
            print(f"  trace={t.get('trace_id')}  "
                  f"replicas={','.join(t.get('replicas') or [])}  "
                  f"window={window}ms  gap={gap}ms", file=out)
            for row in (t.get("timeline") or [])[:12]:
                amb = " ~skew" if row.get("skew_ambiguous") else ""
                print(f"    {row['t_rel_ms']:9.3f}  "
                      f"{row['dur_ms']:9.3f}  "
                      f"{row['replica']:<10} {row['name']}{amb}",
                      file=out)
    tenants = bundle.get("tenants")
    if tenants:
        # top tenants by token share at capture time (ISSUE 19): who
        # was hammering us when the detector fired
        print(f"\nTOP TENANTS ({len(tenants)})", file=out)
        for row in tenants[:8]:
            share = row.get("token_share")
            share = "-" if share is None else f"{share:.3f}"
            print(f"  {str(row.get('tenant'))[:20]:<20} "
                  f"tokens={row.get('tokens_out')}  share={share}  "
                  f"requests={row.get('requests')}  "
                  f"completed={row.get('completed')}", file=out)
    chaos = bundle.get("chaos")
    if isinstance(chaos, dict) and chaos.get("enabled"):
        # the replay recipe: this incident was found under the fault-
        # injection harness and reproduces from the plan's seed alone
        plan = chaos.get("plan") or {}
        print(f"\nCHAOS  seed={plan.get('seed')}  "
              f"fires_total={chaos.get('fires_total')}", file=out)
        for site, st in sorted((chaos.get("sites") or {}).items()):
            if st.get("fires"):
                print(f"  {site:<18} fires={st['fires']}"
                      f"  checks={st['checks']}", file=out)
        tail = chaos.get("fault_log_tail") or []
        if tail:
            print(f"  last fires: " + ", ".join(
                f"{e.get('site')}@check{e.get('check')}"
                for e in tail[-6:]), file=out)
        print(f"  replay: FaultPlan(seed={plan.get('seed')}, "
              f"faults=<plan.faults>) on the same workload", file=out)
    return 1    # an incident bundle is unhealthy by definition


def report_health(body, out=sys.stdout):
    healthy = bool(body.get("healthy"))
    print(f"HEALTH  healthy={healthy}  "
          f"anomalies_total={body.get('anomalies_total')}", file=out)
    if body.get("degraded") or body.get("draining") \
            or body.get("restarts"):
        print(f"  degraded={bool(body.get('degraded'))}  "
              f"draining={bool(body.get('draining'))}  "
              f"restarts={body.get('restarts', 0)}", file=out)
    for name, st in sorted((body.get("detectors") or {}).items()):
        if isinstance(st, dict):
            fired = st.get("fired", 0)
            extra = f"  last_step={st.get('last_step')}" if fired else ""
        else:
            fired, extra = st, ""
        print(f"  {name:<22} fired={fired}{extra}", file=out)
    if body.get("last_incident"):
        print(f"  last_incident: {body['last_incident']}", file=out)
    return 0 if healthy else 1


def main(argv=None):
    parser = argparse.ArgumentParser(
        description=__doc__.splitlines()[0])
    parser.add_argument("path", help="incident bundle or /debug/health"
                        " JSON file")
    parser.add_argument("--tail", type=int, default=None,
                        help="show only the last N ledger rows")
    args = parser.parse_args(argv)
    with open(args.path) as fh:
        body = json.load(fh)
    if isinstance(body, dict) and str(body.get("schema", "")) \
            .startswith("paddle_tpu.health.incident"):
        return report_incident(body, tail=args.tail)
    if isinstance(body, dict) and "healthy" in body:
        return report_health(body)
    print(f"unrecognized document: {args.path} (neither an incident "
          f"bundle nor a /debug/health body)", file=sys.stderr)
    return 2


if __name__ == "__main__":
    sys.exit(main())
