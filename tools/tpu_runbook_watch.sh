#!/bin/bash
# Round-5 armed runbook (VERDICT r4 "Next round" item 1).
#
# Probes the tunneled TPU every PROBE_INTERVAL seconds; each time the
# tunnel is healthy it advances through the runbook stages IN ORDER,
# one stage per healthy window, re-probing between stages (a wedge
# kills only the stage in flight, never the watcher):
#   1. bench  : python bench.py               (live driver-contract line
#               — FIRST: healthy windows have been as short as ~20 min,
#               and the live bench line is the round's #1 artifact)
#   2. smoke  : bash tools/tpu_smoke.sh        (green on-hardware sweep)
#   3. mfu    : python tools/gpt_mfu_sweep.py full
#   4. baseline: python tools/baseline_bench.py all  (refresh BASELINE
#               rows 1 and 3 — LeNet lazy-engine + BERT — live this round)
# Completed stages are recorded in bench_artifacts/runbook_r05_state
# so a restarted watcher resumes where it left off. All tunnel use in
# the round goes through this script — concurrent tunnel processes
# corrupt each other's timings (BASELINE.md measurement notes).
set -u
cd "$(dirname "$0")/.."
ART=bench_artifacts
STATE="$ART/runbook_r05_state"
PROBE_LOG="$ART/probe_log_r05.txt"
PROBE_INTERVAL=${PROBE_INTERVAL:-240}
touch "$STATE"

probe() {
    timeout 90 python -c "
import jax, jax.numpy as jnp
d = jax.devices()
assert d[0].platform == 'tpu', d
x = jnp.ones((256, 256))
print(float((x @ x).sum()))
" >/dev/null 2>&1
}

stage_done() { grep -qx "$1" "$STATE"; }
mark_done()  { echo "$1" >> "$STATE"; }

run_stage() {
    local name=$1 cap=$2; shift 2
    local ts=$(date -u +%Y%m%dT%H%M%SZ)
    local log="$ART/runbook_${name}_${ts}.log"
    echo "[$ts] stage $name: starting (cap ${cap}s)" | tee -a "$PROBE_LOG"
    timeout "$cap" "$@" > "$log" 2>&1
    local rc=$?
    # bench.py exits 0 even when it could only emit the CACHED line
    # (driver contract); the stage is only done once a LIVE line exists
    if [ "$name" = bench ] && [ $rc -eq 0 ] \
            && ! grep -q '"source": "live"' "$log"; then
        rc=99
    fi
    echo "[$(date -u +%Y%m%dT%H%M%SZ)] stage $name: rc=$rc" | tee -a "$PROBE_LOG"
    if [ $rc -eq 0 ]; then mark_done "$name"; return 0; fi
    return 1
}

# hard deadline: stand down WELL before the driver's own end-of-round
# bench run — concurrent tunnel users corrupt each other's timings and
# can wedge each other (BASELINE.md measurement notes). Anchored to the
# FIRST launch's wall clock (persisted), so a restarted watcher does not
# get a fresh window; a stage whose cap would overrun the deadline is
# not started at all.
DEADLINE_S=${DEADLINE_S:-32400}   # 9 h from first launch
EPOCH_FILE="$ART/runbook_r05_epoch"
[ -f "$EPOCH_FILE" ] || date +%s > "$EPOCH_FILE"
T0=$(cat "$EPOCH_FILE")
DEADLINE_AT=$((T0 + DEADLINE_S))

past_deadline() {   # $1 = seconds of headroom needed
    [ $(( $(date +%s) + ${1:-0} )) -ge "$DEADLINE_AT" ]
}

while true; do
    if past_deadline 0; then
        echo "[$(date -u +%Y%m%dT%H%M%SZ)] watcher deadline reached;" \
             "standing down for the driver's end-of-round run" \
             | tee -a "$PROBE_LOG"
        exit 0
    fi
    if stage_done smoke && stage_done bench && stage_done mfu \
            && stage_done baseline; then
        echo "[$(date -u +%Y%m%dT%H%M%SZ)] runbook complete" | tee -a "$PROBE_LOG"
        exit 0
    fi
    if probe; then
        echo "[$(date -u +%Y%m%dT%H%M%SZ)] probe OK" >> "$PROBE_LOG"
        if ! stage_done bench; then
            past_deadline 1500 || run_stage bench 1500 python bench.py
        elif ! stage_done smoke; then
            past_deadline 3600 || run_stage smoke 3600 bash tools/tpu_smoke.sh
        elif ! stage_done mfu; then
            past_deadline 5400 || run_stage mfu 5400 \
                python tools/gpt_mfu_sweep.py full
        else
            # rows 1+3 only — 'all' would re-run the GPT config the mfu
            # stage just measured
            past_deadline 2400 || run_stage baseline 2400 bash -c \
                "python tools/baseline_bench.py lenet && python tools/baseline_bench.py bert"
        fi
    else
        echo "[$(date -u +%Y%m%dT%H%M%SZ)] probe FAIL (wedged)" >> "$PROBE_LOG"
    fi
    sleep "$PROBE_INTERVAL"
done
