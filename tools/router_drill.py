#!/usr/bin/env python
"""router_drill — the kill-a-replica gate for the fleet router.

Spawns N replica subprocesses (tests/router_replica_worker.py: same
seeded tiny GPT each, EngineGateway + ``POST /v1/generate``), routes
seeded traffic over the wire, and proves the router's failover
promise the hard way:

  1. **reference wave** — all replicas up; every request completes;
     its greedy streams are the parity oracle;
  2. **failover wave** — identical traffic with seeded PR-9
     ``router_dispatch`` faults armed, and one replica SIGKILLed the
     moment it has requests in flight. PASS iff 100% of admitted,
     non-shed requests complete, every stream is bit-exact vs the
     reference, the survivors end with zero queued requests / zero
     occupied slots, and their compile counters did not move (zero
     steady-state compiles under failover). The wave also audits the
     distributed traces (ISSUE 18): every failed-over request must
     remain ONE trace — the replay's survivor-side spans land under
     the ORIGINAL trace id with a router/failover annotation;
  3. **no-failover baseline** — the same kill against a
     ``max_retries=0`` router: the drill DEMANDS lost requests here
     (if losing a replica were free, the failover machinery would be
     dead weight) and names the lost rids.

Exit 0 iff completion 100% + parity + no leaks (and the baseline
demonstrably lost the dead replica's in-flight work); exit 1 names
the lost/mismatched rids. One JSON line per wave on stdout, RESULT
line last — the same scriptable-gate discipline as chaos_sweep.py.

``--kill prefill`` runs the DISAGGREGATED flavor: replica 0 comes up
as the prefill tier, the rest as decode (paged pools + warmed KV
export/import programs), wave 1 must complete through real handoffs
(``disagg.handoffs > 0``), and wave 2 SIGKILLs the PREFILL replica
mid-handoff — every request must still complete bit-exact via the
journaled first token (or a full monolithic replay on a decode
survivor when hop 1 never finished), with zero leaked blocks and
zero steady-state compiles on both tiers.

    python tools/router_drill.py              # 3 replicas, 12 reqs
    python tools/router_drill.py --fast       # the tier-1 cell
    python tools/router_drill.py --fast --kill prefill   # disagg cell
"""
import argparse
import json
import os
import signal
import subprocess
import sys
import threading
import time
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

_WORKER = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "tests", "router_replica_worker.py")


def _spawn(idx, role=None):
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    env["ROUTER_REPLICA_ID"] = f"dr{idx}"
    env.setdefault("ROUTER_PORT", "0")
    if role is not None:
        env["ROUTER_ROLE"] = role
    proc = subprocess.Popen(
        [sys.executable, _WORKER], env=env, stdout=subprocess.PIPE,
        stderr=subprocess.PIPE, text=True)
    return proc


def _ready(proc, timeout=180.0):
    box = {}

    def read():
        box["line"] = proc.stdout.readline()

    t = threading.Thread(target=read, daemon=True)
    t.start()
    t.join(timeout)
    line = box.get("line")
    if not line:
        proc.kill()
        err = proc.stderr.read()[-2000:] if proc.stderr else ""
        raise RuntimeError(
            f"replica worker never became ready:\n{err}")
    return json.loads(line)


def _get(url, path, timeout=3.0):
    with urllib.request.urlopen(url + path, timeout=timeout) as resp:
        return json.loads(resp.read().decode("utf-8"))


def _compiles(url):
    """Sum of the replica's ``serving_compiles_total`` series from its
    /metrics.json (``{name: {values: {labelkey: value}}}`` shape)."""
    fam = _get(url, "/metrics.json").get("serving_compiles_total")
    if fam is None:
        raise RuntimeError(
            "replica exposes no serving_compiles_total — the "
            "steady-state compile audit has nothing to audit")
    return sum(fam["values"].values())


def _prompts(seed, n, vocab=97):
    import numpy as np
    rs = np.random.RandomState(seed)
    return [rs.randint(0, vocab, (int(rs.randint(4, 8)),))
            .astype(int).tolist() for _ in range(n)]


def _route_wave(router, prompts, max_new, timeout=600.0):
    tickets = [router.submit(p, max_new) for p in prompts]
    return [t.result(timeout=timeout) for t in tickets]


def _wait_inflight(urls, deadline_s=30.0):
    """Block until SOME replica has occupied slots — the moment a
    SIGKILL is guaranteed to strand in-flight requests. Returns its
    url."""
    t_end = time.monotonic() + deadline_s
    while time.monotonic() < t_end:
        for u in urls:
            try:
                st = _get(u, "/debug/state", timeout=1.0)
            except Exception:   # noqa: BLE001 - replica mid-warmup
                continue
            if st.get("slot_occupancy", 0) > 0 \
                    or st.get("queue_depth", 0) > 0:
                return u
        time.sleep(0.01)
    return None


def _leak_audit(url, rid, paged, failures):
    st = _get(url, "/debug/state")
    if st.get("queue_depth", 0) != 0 \
            or st.get("slot_occupancy", 0) != 0 \
            or st.get("held_exports", 0) != 0:
        failures.append(
            f"leak on {rid}: queue_depth={st.get('queue_depth')} "
            f"slot_occupancy={st.get('slot_occupancy')} "
            f"held_exports={st.get('held_exports')}")
    if paged:
        pool = (st.get("prefix_cache") or {}).get("pool") or {}
        # indexed prefix blocks are CACHE, not leaks — live counts
        # only blocks some slot still references
        if pool.get("live_blocks", 0) != 0:
            failures.append(
                f"leaked blocks on {rid}: "
                f"live_blocks={pool.get('live_blocks')}")


def run_drill(replicas=3, requests=12, max_new=16, seed=5,
              fault_rate=0.1, kill="replica", out=sys.stdout):
    from paddle_tpu.serving.resilience.chaos import (FaultPlan,
                                                     FaultSpec)
    from paddle_tpu.serving.router import (HTTPTransport, Router,
                                           RouterConfig)

    disagg = kill == "prefill"
    roles = (["prefill"] + ["decode"] * (replicas - 1)) if disagg \
        else [None] * replicas
    procs = [_spawn(i, role=r) for i, r in enumerate(roles)]
    failures = []
    try:
        infos = [_ready(p) for p in procs]
        urls = [f"http://127.0.0.1:{i['port']}" for i in infos]
        rids = [i["replica_id"] for i in infos]
        by_url = dict(zip(urls, rids))
        prompts = _prompts(seed, requests)

        def transports(active_urls):
            return [HTTPTransport(u, replica_id=by_url[u],
                                  timeout_s=120.0)
                    for u in active_urls]

        def cfg(max_retries):
            return RouterConfig(max_retries=max_retries,
                                refresh_s=0.1, backoff_base_s=0.05,
                                backoff_max_s=0.5, seed=seed)

        # in disagg mode the steady-state compile audit covers the
        # HANDOFF traffic too: baseline every replica before wave 1
        compiles_w0 = {u: _compiles(u) for u in urls} if disagg \
            else {}

        # ---- wave 1: reference (no kill) — the parity oracle
        router = Router(transports(urls), config=cfg(max_retries=3))
        ref = _route_wave(router, prompts, max_new)
        w1_state = router.state()
        router.close()
        ref_ok = sum(1 for r in ref if r["ok"])
        w1_line = {"wave": "reference", "ok": ref_ok,
                   "total": requests}
        if disagg:
            w1_line["handoffs"] = w1_state["disagg"]["handoffs"]
            w1_line["wire_bytes"] = w1_state["disagg"]["wire_bytes"]
        print(json.dumps(w1_line), file=out, flush=True)
        if ref_ok != requests:
            bad = [(r["rid"], r.get("reason")) for r in ref
                   if not r["ok"]]
            failures.append(
                f"reference wave incomplete: {ref_ok}/{requests} "
                f"{bad}")
            return failures
        if disagg:
            if w1_state["disagg"]["handoffs"] == 0:
                failures.append(
                    "disagg reference wave completed without a "
                    "single KV handoff — the two-hop path never ran")
            # the prefill tier is about to die: audit it NOW (zero
            # leaked blocks, zero steady-state compiles under
            # handoff traffic)
            _leak_audit(urls[0], rids[0], True, failures)
            after0 = _compiles(urls[0])
            if after0 != compiles_w0[urls[0]]:
                failures.append(
                    f"steady-state compiles on prefill tier "
                    f"{rids[0]}: {compiles_w0[urls[0]]} -> {after0}")
        ref_streams = [r["tokens"] for r in ref]

        # ---- wave 2: failover — SIGKILL mid-traffic + seeded
        # router_dispatch faults; identical prompts, 100% + parity
        # demanded
        survivors = urls[1:]
        compiles_before = {u: compiles_w0[u] for u in survivors} \
            if disagg else {u: _compiles(u) for u in survivors}
        plan = FaultPlan(seed=seed, faults={
            "router_dispatch": FaultSpec(rate=fault_rate)})
        router = Router(transports(urls), config=cfg(max_retries=4),
                        chaos=plan)
        tickets = [router.submit(p, max_new) for p in prompts]
        victim = urls[0]
        # kill the victim the moment it holds in-flight work (it is
        # a placement target like any other; if traffic hasn't hit
        # it yet, wait for the router to load-balance onto it)
        _wait_inflight([victim], deadline_s=30.0)
        procs[0].send_signal(signal.SIGKILL)
        procs[0].wait(timeout=30)
        res = [t.result(timeout=600.0) for t in tickets]
        state = router.state()
        router.close()
        ok = [r for r in res if r["ok"]]
        shed = [r for r in res if r.get("shed")]
        lost = [r["rid"] for r in res
                if not r["ok"] and not r.get("shed")]
        mismatch = [r["rid"] for i, r in enumerate(res)
                    if r["ok"] and r["tokens"] != ref_streams[i]]
        failmoves = state["counters"]["failovers"]
        w2_line = {
            "wave": "failover", "ok": len(ok), "shed": len(shed),
            "lost": lost, "parity_mismatch": mismatch,
            "failovers": failmoves,
            "retries": state["counters"]["retries"],
            "killed": by_url[victim]}
        if disagg:
            w2_line["handoffs"] = state["disagg"]["handoffs"]
            w2_line["handoff_failures"] = \
                state["disagg"]["handoff_failures"]
        # distributed-trace audit (ISSUE 18): a failed-over request
        # must remain ONE trace — the replay's spans land under the
        # ORIGINAL trace id (minted at admission, carried by the
        # journal through every dispatch attempt), annotated with a
        # router/failover span. The victim's ring died with it, so
        # assembly joins the router's recorder with the SURVIVORS'
        # /debug/traces — the replayed attempt's replica-side spans
        # must appear under the same id.
        from paddle_tpu.observability.trace import TraceAssembler
        asm = TraceAssembler()
        asm.add_recorder(router.trace)
        for u in survivors:
            try:
                asm.scrape(u, timeout=3.0)
            except Exception:   # noqa: BLE001 - audit is best-effort
                pass
        failed_over = [t for t in asm.assemble_all()
                       if any(s["name"] == "router/failover"
                              for s in t.spans)]
        w2_line["traced_failovers"] = len(failed_over)
        if failmoves and not failed_over:
            failures.append(
                f"router counted {failmoves} failovers but no "
                f"assembled trace carries a router/failover span")
        survivor_rids = {by_url[u] for u in survivors}
        for t in failed_over:
            if not ({s["replica"] for s in t.spans} & survivor_rids):
                failures.append(
                    f"failed-over trace {t.trace_id} has no "
                    f"survivor-side spans under the original trace "
                    f"id — the replay forked the trace")
        print(json.dumps(w2_line), file=out, flush=True)
        if lost:
            failures.append(f"failover wave lost rids: {lost}")
        if mismatch:
            failures.append(
                f"greedy parity broken for rids: {mismatch}")
        if len(ok) + len(shed) != requests:
            failures.append("failover wave accounting does not add up")
        # leak + steady-state-compile audit on the survivors
        for u in survivors:
            _leak_audit(u, by_url[u], disagg, failures)
            after = _compiles(u)
            if after != compiles_before[u]:
                failures.append(
                    f"steady-state compiles on {by_url[u]}: "
                    f"{compiles_before[u]} -> {after}")

        # ---- wave 3: no-failover baseline — the kill MUST hurt
        base_urls = survivors
        router = Router(transports(base_urls),
                        config=cfg(max_retries=0))
        tickets = [router.submit(p, max_new) for p in prompts]
        victim = base_urls[0]
        _wait_inflight([victim], deadline_s=30.0)
        procs[1].send_signal(signal.SIGKILL)
        procs[1].wait(timeout=30)
        res = [t.result(timeout=600.0) for t in tickets]
        router.close()
        base_lost = [r["rid"] for r in res
                     if not r["ok"] and not r.get("shed")]
        print(json.dumps({
            "wave": "baseline_no_failover",
            "ok": sum(1 for r in res if r["ok"]),
            "shed": sum(1 for r in res if r.get("shed")),
            "lost": base_lost, "killed": by_url[victim]}),
            file=out, flush=True)
        if not base_lost:
            failures.append(
                "baseline (max_retries=0) lost nothing — the kill "
                "was not observed mid-flight; drill inconclusive")
        return failures
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
        for p in procs:
            try:
                p.wait(timeout=10)
            except Exception:   # noqa: BLE001 - teardown
                pass


def main(argv=None):
    parser = argparse.ArgumentParser(
        description="kill-a-replica drill: exit 0 iff 100% "
                    "completion + greedy parity + no leaks")
    parser.add_argument("--replicas", type=int, default=3)
    parser.add_argument("--requests", type=int, default=12)
    parser.add_argument("--max-new", type=int, default=16)
    parser.add_argument("--seed", type=int, default=5)
    parser.add_argument("--fault-rate", type=float, default=0.1,
                        help="seeded router_dispatch fault rate for "
                             "the failover wave")
    parser.add_argument("--kill", choices=("replica", "prefill"),
                        default="replica",
                        help="replica: SIGKILL a monolithic replica "
                             "(the classic drill); prefill: 1P+ND "
                             "disaggregated topology, SIGKILL the "
                             "prefill tier mid-handoff")
    parser.add_argument("--fast", action="store_true",
                        help="the tier-1 cell: 3 replicas, fewer/"
                             "shorter requests")
    args = parser.parse_args(argv)
    if args.fast:
        args.requests = min(args.requests, 8)
        args.max_new = min(args.max_new, 12)
    if args.replicas < 3:
        parser.error("the drill needs >= 3 replicas (one killed per "
                     "chaos wave, one survivor to finish the work)")
    t0 = time.monotonic()
    failures = run_drill(replicas=args.replicas,
                         requests=args.requests,
                         max_new=args.max_new, seed=args.seed,
                         fault_rate=args.fault_rate, kill=args.kill)
    verdict = "PASS" if not failures else "FAIL"
    print(json.dumps({"result": verdict,
                      "failures": failures,
                      "wall_s": round(time.monotonic() - t0, 1)}),
          flush=True)
    if failures:
        for f in failures:
            print(f"FAIL: {f}", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
