#!/usr/bin/env python
"""tenant_report — the per-tenant attribution table and the
noisy-neighbor verdict, off live surfaces or saved bodies.

Inputs, mixed freely:

  * URLs — an engine's ``host:port`` (scrapes ``/debug/tenants``) or
    a full path like ``http://host:port/fleet/tenants`` (the fleet
    federation's rollup + fairness-detector state);
  * Files — saved ``/debug/tenants`` / ``/fleet/tenants`` JSON bodies
    (``-`` reads one from stdin).

Counters from multiple sources SUM (the same exact-merge rule the
fleet rollup applies — never averaged ratios); ``token_share`` and
``attainment`` are derived from the merged sums. The table is one row
per tenant, biggest token consumer first, plus the overflow-fold
line when the bounded ledger folded ids into ``~other``.

Exit code is the fairness gate: 1 when a noisy tenant is detected —
either a scraped ``/fleet/tenants`` body carries a live
``noisy_neighbor`` / ``tenant_starvation`` verdict, or the merged
totals themselves show one tenant holding >= ``--share-threshold``
of all generated tokens while the OTHER tenants' SLO attainment sits
below ``--attain-floor`` — naming the tenant on stderr. 0 when the
tenancy looks fair; 2 on unreadable input / no tenant data. Tier-1
self-runs this against a live engine (tests/test_tenant.py), the
same discipline as trace_report / incident_report / fleet_top.

Stdlib-only, zero heavy imports: starts in milliseconds against a
live fleet.

Usage: python tools/tenant_report.py SOURCE [SOURCE...]
           [--share-threshold F] [--attain-floor F] [--min-tokens N]
           [--json] [--timeout S]
"""
import argparse
import json
import os
import sys
import urllib.request

# counters that sum exactly across sources (engine-report entries and
# fleet-rollup rows both carry these names)
_SUM_KEYS = ("requests", "completed", "tokens_in", "tokens_out",
             "goodput_tokens", "attained", "timeouts", "aborts",
             "cache_saved_tokens", "queued")


def fetch(src, timeout=5.0):
    """One source -> parsed JSON body. URL forms: ``host:port``
    scrapes ``/debug/tenants``; anything with a path is used as-is."""
    if src == "-":
        return json.load(sys.stdin)
    if os.path.exists(src):
        with open(src, encoding="utf-8") as fh:
            return json.load(fh)
    url = src if "://" in src else "http://" + src
    if url.count("/") <= 2:              # bare host:port
        url += "/debug/tenants"
    with urllib.request.urlopen(url, timeout=timeout) as resp:
        return json.loads(resp.read().decode("utf-8"))


def _violations_count(entry):
    v = entry.get("violations")
    if isinstance(v, dict):
        return sum(v.values())
    return v or 0


def merge(bodies):
    """Fold engine-report and fleet-rollup bodies into one
    ``{tenants, folded, verdicts, sources_with_data}`` view, counters
    summed exactly."""
    rows, folded, verdicts = {}, 0, []
    seen = 0
    for body in bodies:
        if not isinstance(body, dict):
            continue
        fleet = body.get("fleet")
        if fleet is not None or "last_verdicts" in body:
            # /fleet/tenants shape
            for name, v in sorted((body.get("last_verdicts")
                                   or {}).items()):
                verdicts.append((name, v))
            if not fleet:
                continue
            seen += 1
            folded += fleet.get("overflow_folded") or 0
            entries = fleet.get("tenants") or {}
        elif "tenants" in body:
            # /debug/tenants (engine report) shape
            if not body.get("enabled", True):
                continue
            seen += 1
            folded += (body.get("overflow")
                       or {}).get("folded_events") or 0
            entries = body.get("tenants") or {}
        else:
            continue
        for t, entry in entries.items():
            row = rows.setdefault(
                t, dict({k: 0 for k in _SUM_KEYS},
                        violations=0, shed=0))
            for k in _SUM_KEYS:
                row[k] += entry.get(k) or 0
            row["violations"] += _violations_count(entry)
            shed = entry.get("shed")
            row["shed"] += sum(shed.values()) \
                if isinstance(shed, dict) else (shed or 0)
    total_out = sum(r["tokens_out"] for r in rows.values())
    for row in rows.values():
        row["token_share"] = row["tokens_out"] / total_out \
            if total_out else None
        row["attainment"] = row["attained"] / row["completed"] \
            if row["completed"] else None
    ordered = dict(sorted(rows.items(),
                          key=lambda kv: (-kv[1]["tokens_out"],
                                          kv[0])))
    return {"tenants": ordered, "folded": folded,
            "verdicts": verdicts, "sources_with_data": seen}


def judge(merged, share_threshold=0.6, attain_floor=0.5,
          min_tokens=100):
    """(tenant, reason) when the merged totals show a noisy neighbor;
    None when the tenancy looks fair. Mirrors the fleet
    ``noisy_neighbor`` detector's BOTH-halves rule on cumulative
    sums: dominance alone is just the biggest customer."""
    for name, verdict in merged["verdicts"]:
        t = verdict.get("tenant")
        if t:
            return t, f"live {name} verdict: {verdict.get('reason')}"
    rows = merged["tenants"]
    total = sum(r["tokens_out"] for r in rows.values())
    if len(rows) < 2 or total < min_tokens:
        return None
    top = max(rows, key=lambda t: (rows[t]["tokens_out"], t))
    share = rows[top]["tokens_out"] / total
    victim_done = sum(r["completed"] + r["violations"]
                     for t, r in rows.items() if t != top)
    victim_att = sum(r["attained"] for t, r in rows.items()
                     if t != top) / victim_done if victim_done else None
    if (share >= share_threshold and victim_att is not None
            and victim_att < attain_floor):
        return top, (f"{share:.0%} of {total:.0f} tokens while other "
                     f"tenants attain {victim_att:.0%}")
    return None


def _fmt(v, nd=3):
    if v is None:
        return "-"
    if isinstance(v, float):
        return f"{v:.{nd}f}"
    return str(v)


def _table(headers, rows, out):
    widths = [max(len(h), *(len(r[i]) for r in rows)) if rows
              else len(h) for i, h in enumerate(headers)]
    print("  ".join(h.ljust(w) for h, w in zip(headers, widths)),
          file=out)
    print("  ".join("-" * w for w in widths), file=out)
    for r in rows:
        print("  ".join(c.ljust(w) for c, w in zip(r, widths)),
              file=out)


def render(merged, out=sys.stdout):
    rows = []
    for t, r in merged["tenants"].items():
        rows.append((
            t[:24], _fmt(int(r["requests"])), _fmt(int(r["completed"])),
            _fmt(int(r["tokens_in"])), _fmt(int(r["tokens_out"])),
            _fmt(r["token_share"]), _fmt(r["attainment"]),
            _fmt(int(r["violations"])), _fmt(int(r["shed"])),
            _fmt(int(r["queued"])),
            _fmt(int(r["cache_saved_tokens"])),
        ))
    _table(("TENANT", "REQ", "DONE", "TOK_IN", "TOK_OUT", "SHARE",
            "ATTAIN", "VIOL", "SHED", "QUEUED", "CACHE_SAVED"),
           rows, out)
    if merged["folded"]:
        print(f"overflow: {merged['folded']} unique tenant id(s) "
              f"folded into ~other (bounded ledger)", file=out)
    for name, verdict in merged["verdicts"]:
        print(f"! {name}: {verdict.get('reason', '?')}", file=out)


def main(argv=None):
    parser = argparse.ArgumentParser(
        description="render the per-tenant attribution table; exit 1 "
                    "naming the noisy tenant when one is detected")
    parser.add_argument("sources", nargs="+",
                        help="tenant surfaces: URLs (host:port or "
                             "http://.../fleet/tenants) and/or saved "
                             "JSON bodies ('-' = stdin)")
    parser.add_argument("--share-threshold", type=float, default=0.6,
                        help="token share above which a dominant "
                             "tenant CAN be judged noisy")
    parser.add_argument("--attain-floor", type=float, default=0.5,
                        help="other tenants' attainment below which "
                             "the dominant tenant IS judged noisy")
    parser.add_argument("--min-tokens", type=float, default=100,
                        help="minimum merged generated tokens before "
                             "judging at all (cold surfaces are fair)")
    parser.add_argument("--json", action="store_true",
                        help="dump the merged view as JSON instead of "
                             "the table")
    parser.add_argument("--timeout", type=float, default=5.0,
                        help="per-URL scrape timeout seconds")
    args = parser.parse_args(argv)

    bodies = []
    for src in args.sources:
        try:
            bodies.append(fetch(src, timeout=args.timeout))
        except Exception as e:   # noqa: BLE001 - CLI verdict, exit 2
            print(f"ERROR: cannot read {src}: "
                  f"{type(e).__name__}: {e}", file=sys.stderr)
            return 2
    merged = merge(bodies)
    if not merged["sources_with_data"]:
        print(f"ERROR: no tenant data in {len(bodies)} source(s) "
              f"(ledger disabled everywhere?)", file=sys.stderr)
        return 2
    noisy = judge(merged, share_threshold=args.share_threshold,
                  attain_floor=args.attain_floor,
                  min_tokens=args.min_tokens)
    if args.json:
        print(json.dumps({
            "tenants": merged["tenants"],
            "overflow_folded": merged["folded"],
            "verdicts": [{"detector": n, **v}
                         for n, v in merged["verdicts"]],
            "noisy_tenant": noisy[0] if noisy else None,
        }, indent=1, sort_keys=True))
    else:
        render(merged)
    if noisy:
        print(f"NOISY: tenant {noisy[0]} — {noisy[1]}",
              file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
