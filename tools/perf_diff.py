#!/usr/bin/env python
"""Cross-run perf regression gate: compare the latest bench run
against the perf ledger's baseline and exit nonzero on regression.

``bench_serving.py`` appends one normalized row per (scenario, metric)
to ``bench_artifacts/perf_ledger.jsonl`` on every run; this CLI reads
the whole ledger, judges the LAST row of every (scenario, metric,
config_digest) group against the MEDIAN of its history with robust
thresholds (relative delta gated by a MAD noise estimate — see
paddle_tpu/observability/perf/ledger.py, loaded directly by file so
the gate starts in milliseconds without importing jax), prints the
trajectory table, and exits:

  * 0 — no regressions (clean, improvements, or first-run baselines);
  * 1 — at least one regression, each named as scenario/metric with
        its baseline, current value and threshold;
  * 2 — an explicitly given ledger path does not exist / has no rows.

A missing DEFAULT ledger exits 0 with a note: the gate must not fail
the build before the first bench run ever lands. Wired into tier-1
via tests/test_perf.py, which self-runs it against synthetic ledgers
(clean two-run → 0, planted 2x decode slowdown → 1) — the same
self-run discipline as tools/incident_report.py and
tools/chaos_sweep.py --fast.

``--prune-run RUN_ID`` / ``--prune-series SCENARIO/METRIC``
(repeatable) rewrite the ledger first, dropping a poisoned run's rows
or retiring a stale metric series (ledger.prune — the recorded triage
operation; compare() judges each series' LAST row, so a bad trailing
run keeps the gate red until triaged or outrun), then judge what's
left.

Usage: python tools/perf_diff.py [LEDGER] [--threshold F] [--mad-k K]
                                 [--scenario S] [--history N]
                                 [--prune-run R]... [--prune-series S/M]...
"""
import argparse
import importlib.util
import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_DEFAULT_LEDGER = os.path.join(_REPO, "bench_artifacts",
                               "perf_ledger.jsonl")


def _load_ledger_module():
    path = os.path.join(_REPO, "paddle_tpu", "observability", "perf",
                        "ledger.py")
    spec = importlib.util.spec_from_file_location("_perf_ledger", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _fmt(v):
    if v is None:
        return "-"
    if isinstance(v, float):
        return f"{v:.4g}"
    return str(v)


def render_table(results, history_n=5, out=sys.stdout):
    """Fixed-width trajectory table: recent history -> current, with
    the verdict per (scenario, metric)."""
    headers = ["scenario", "metric", "runs", "trajectory", "baseline",
               "current", "worse_by", "verdict"]
    rows = []
    for r in results:
        traj = " ".join(_fmt(v) for v in r["history"][-history_n:])
        worse = "-" if r["worse_by"] is None \
            else f"{r['worse_by'] * 100.0:+.1f}%"
        rows.append([r["scenario"], r["metric"], str(r["runs"]),
                     traj or "-", _fmt(r["baseline"]),
                     _fmt(r["current"]), worse, r["verdict"]])
    widths = [max(len(h), *(len(row[i]) for row in rows)) if rows
              else len(h) for i, h in enumerate(headers)]
    print("  ".join(h.ljust(w) for h, w in zip(headers, widths)),
          file=out)
    print("  ".join("-" * w for w in widths), file=out)
    for row in rows:
        print("  ".join(c.ljust(w) for c, w in zip(row, widths)),
              file=out)


def main(argv=None):
    parser = argparse.ArgumentParser(
        description=__doc__.splitlines()[0])
    parser.add_argument("ledger", nargs="?", default=None,
                        help="perf ledger JSONL (default: "
                             "bench_artifacts/perf_ledger.jsonl)")
    parser.add_argument("--threshold", type=float, default=0.35,
                        help="default relative-worsening threshold "
                             "(rows may carry their own)")
    parser.add_argument("--mad-k", type=float, default=3.0,
                        help="MAD multiplier of the noise gate")
    parser.add_argument("--scenario", default=None,
                        help="only judge this scenario")
    parser.add_argument("--history", type=int, default=5,
                        help="trajectory points shown per metric")
    parser.add_argument("--prune-run", action="append", default=[],
                        metavar="RUN_ID",
                        help="drop every ledger row from this run_id "
                             "before judging (triage a poisoned run, "
                             "e.g. a host-overloaded smoke run); "
                             "repeatable")
    parser.add_argument("--prune-series", action="append", default=[],
                        metavar="SCENARIO/METRIC",
                        help="drop this whole (scenario, metric) "
                             "series before judging (retire a stale "
                             "metric name); repeatable")
    args = parser.parse_args(argv)

    explicit = args.ledger is not None
    path = args.ledger or _DEFAULT_LEDGER
    if not os.path.exists(path):
        if explicit:
            print(f"perf_diff: no such ledger: {path}",
                  file=sys.stderr)
            return 2
        print(f"perf_diff: no ledger yet at {path} — nothing to "
              f"judge (run bench_serving.py first)")
        return 0

    ledger = _load_ledger_module()
    if args.prune_run or args.prune_series:
        kept, dropped = ledger.prune(path, run_ids=args.prune_run,
                                     series=args.prune_series)
        print(f"perf_diff: pruned {dropped} row(s) from {path} "
              f"({kept} kept)")
    rows, skipped = ledger.read_rows(path)
    if args.scenario:
        rows = [r for r in rows if r["scenario"] == args.scenario]
    if not rows:
        if explicit:
            print(f"perf_diff: no ledger rows in {path}",
                  file=sys.stderr)
            return 2
        print(f"perf_diff: no rows in {path} — nothing to judge")
        return 0

    results = ledger.compare(rows,
                             default_rel_threshold=args.threshold,
                             mad_k=args.mad_k)
    print(f"perf ledger: {path}  rows={len(rows)}"
          + (f"  skipped={skipped}" if skipped else ""))
    render_table(results, history_n=args.history)

    baselines = [r for r in results if r["verdict"] == "baseline"]
    if baselines and len(baselines) == len(results):
        print(f"\nbaseline established for {len(baselines)} "
              f"(scenario, metric) series — nothing to compare yet")
    regressions = [r for r in results if r["verdict"] == "regression"]
    if regressions:
        print(f"\nREGRESSION in {len(regressions)} metric(s):")
        for r in regressions:
            worse = "-" if r["worse_by"] is None \
                else f"{r['worse_by'] * 100.0:.1f}%"
            print(f"  {r['scenario']}/{r['metric']}: "
                  f"{_fmt(r['current'])} vs baseline "
                  f"{_fmt(r['baseline'])} ({worse} worse, threshold "
                  f"{r['threshold'] * 100.0:.0f}%) "
                  f"run={r['current_run']}")
        return 1
    improved = sum(1 for r in results if r["verdict"] == "improvement")
    print(f"\nno regressions across {len(results)} series"
          + (f" ({improved} improved)" if improved else ""))
    return 0


if __name__ == "__main__":
    sys.exit(main())
