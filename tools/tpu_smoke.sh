#!/bin/bash
# On-hardware validation sweep: run the single-chip-safe slice of the
# test suite against the REAL TPU (PADDLE_TPU_TEST_BACKEND=tpu skips
# mesh-dependent modules via conftest). This is correctness evidence —
# the CPU suite can't see TPU-only behavior (bf16 matmul passes, Mosaic
# compilation of the Pallas flash kernels, tunnel D2H semantics).
#
# Never run concurrently with a bench (shared tunnel). Output goes to
# bench_artifacts/tpu_smoke_<ts>.log for the evidence trail.
set -u -o pipefail
cd "$(dirname "$0")/.."
ts=$(date -u +%Y%m%dT%H%M%SZ)
out="bench_artifacts/tpu_smoke_${ts}.log"

echo "== probing backend (90s cap)..."
timeout 90 python -c "
import sys
import jax
d = jax.devices()
print(d[0].platform, d[0].device_kind)
sys.exit(0 if d[0].platform == 'tpu' else 1)  # CPU fallback is NOT evidence
" || { echo 'no TPU (wedged tunnel or CPU fallback); aborting'; exit 1; }

# Curated single-chip slice: core numerics, autograd, layers,
# optimizers, AMP, and the Pallas flash kernels compiled for real (the
# CPU suite only exercises them in interpret mode).
#
# NOT in the slice: test_to_static / test_models — their eager
# discovery passes are per-op ~65ms tunnel round trips, so each test
# runs for minutes-to-tens-of-minutes on the tunneled chip (observed
# 36+ min on one model-scale parity test). Their compiled paths ARE
# exercised on hardware by the benches (bench.py ResNet-50,
# tools/baseline_bench.py BERT/GPT are whole to_static train steps).
FILES="tests/test_tensor.py tests/test_autograd.py tests/test_ops.py \
tests/test_nn_layers.py tests/test_optimizer.py tests/test_amp.py \
tests/test_flash_backward.py tests/test_generation.py \
tests/test_fused_ce.py tests/test_dy2static_loops.py \
tests/test_dy2static_returns.py tests/test_advice_round5.py \
tests/test_checkpoint.py"

PADDLE_TPU_TEST_BACKEND=tpu timeout 5400 \
    python -m pytest $FILES -q -p no:cacheprovider \
    2>&1 | tee "$out"
rc=$?
echo "rc=$rc (log: $out)"
exit $rc
