#!/usr/bin/env python
"""Cache observatory report: render a ``/debug/cache`` body (or a full
``snapshot()`` / bench artifact containing one) as the operator-facing
cache story — measured hit rate, the miss-ratio curve ("what would
0.5x/2x/4x capacity do"), the hot-prefix digest, savings attribution,
eviction churn — and judge THRASH:

  * 0 — healthy (no thrash signature, or cache telemetry disabled);
  * 1 — THRASHING: evictions >= --min-evictions AND thrash reinserts /
        evictions >= --thrash-ratio — the pool keeps evicting paths it
        immediately recomputes, i.e. capacity is below the live
        working set (the MRC table above names what more would buy);
  * 2 — input missing or not recognizable as a cache report.

Input shapes accepted (auto-detected): the ``/debug/cache`` body
itself, any dict with a ``"cache"`` section (``/debug/state``,
``snapshot()``), or a bench artifact whose scenario section carries
one (``shared_prefix.cache``). Reads a file path or stdin (``-``).

Zero heavy imports (no jax, no paddle_tpu) — starts in milliseconds,
usable against a live engine:
``curl :8000/debug/cache | python tools/cache_report.py -``.
Self-run by tier-1 (tests/test_cache.py) on a healthy shared-prefix
drain (exit 0) and a planted thrash workload (exit 1), the same
discipline as tools/incident_report.py and tools/perf_diff.py.

Usage: python tools/cache_report.py [REPORT.json|-]
           [--thrash-ratio F] [--min-evictions N] [--top K]
"""
import argparse
import json
import sys


def find_cache_report(doc):
    """Locate the cache-report dict inside ``doc`` (see module doc for
    accepted shapes); None when nothing recognizable is present."""
    if not isinstance(doc, dict):
        return None
    if "enabled" in doc and "churn" in doc and "mrc" in doc:
        return doc
    cache = doc.get("cache")
    if isinstance(cache, dict) and "enabled" in cache:
        return cache
    # bench artifact: {"scenarios": {"shared_prefix": {"cache": ...}}}
    scenarios = doc.get("scenarios")
    if isinstance(scenarios, dict):
        for sec in scenarios.values():
            found = find_cache_report(sec)
            if found is not None:
                return found
    return None


def _fmt(v, spec="{}"):
    return "-" if v is None else spec.format(v)


def _table(headers, rows, out):
    widths = [max(len(h), *(len(r[i]) for r in rows)) if rows
              else len(h) for i, h in enumerate(headers)]
    print("  ".join(h.ljust(w) for h, w in zip(headers, widths)),
          file=out)
    print("  ".join("-" * w for w in widths), file=out)
    for r in rows:
        print("  ".join(c.ljust(w) for c, w in zip(r, widths)),
              file=out)


def render(report, top=8, out=sys.stdout):
    """Print the human-readable cache story."""
    hr = report.get("hit_rate")
    print(f"cache: accesses={report.get('accesses')} "
          f"hits={report.get('hits')} "
          f"hit_rate={_fmt(hr, '{:.2%}')} "
          f"capacity={report.get('capacity_blocks')} blocks",
          file=out)

    sampled = report.get("sampled") or {}
    if sampled:
        print(f"sampler: rate={sampled.get('rate')} "
              f"sampled_accesses={sampled.get('accesses')} "
              f"tracked={sampled.get('tracked')} "
              f"dropped={sampled.get('dropped')}", file=out)

    mrc = report.get("mrc")
    if mrc:
        print("\nmiss-ratio curve (estimated LRU hit rate by "
              "capacity):", file=out)
        rows = [[_fmt(p.get("factor"), "{}x"), str(p["blocks"]),
                 _fmt(p.get("est_hit_rate"), "{:.2%}")] for p in mrc]
        _table(["factor", "blocks", "est_hit_rate"], rows, out)

    heat = report.get("heat") or {}
    entries = (heat.get("top") or [])[:top]
    if entries:
        print(f"\nhot prefixes (top {len(entries)} of "
              f"{heat.get('indexed_blocks')} indexed blocks, "
              f"{heat.get('total_hits')} total hits):", file=out)
        rows = [[e["fp"], str(e["depth"]), str(e["hits"]),
                 str(e["tokens_saved"]), str(e["last_tick"])]
                for e in entries]
        _table(["fingerprint", "depth", "hits", "tokens_saved",
                "last_tick"], rows, out)

    savings = report.get("savings") or {}
    if savings:
        print(f"\nsavings: saved_tokens={savings.get('saved_tokens')} "
              f"est_ttft_saved_ms="
              f"{_fmt(savings.get('saved_ttft_ms'), '{:.1f}')} "
              f"per_token_prefill_ms="
              f"{_fmt(savings.get('per_token_prefill_ms'), '{:.4f}')}",
              file=out)

    churn = report.get("churn") or {}
    if churn:
        life = churn.get("block_lifetime_ms") or {}
        print(f"churn: evictions={churn.get('evictions')} "
              f"thrash_reinserts={churn.get('thrash_reinserts')} "
              f"block_lifetime_ms p50={_fmt(life.get('p50_ms'))} "
              f"p90={_fmt(life.get('p90_ms'))} "
              f"p99={_fmt(life.get('p99_ms'))}", file=out)


def thrash_verdict(report, ratio=0.5, min_evictions=8):
    """(is_thrashing, reason). Conservative: needs BOTH real eviction
    volume and a high reinsert fraction — a busy cache evicting cold
    tails is healthy."""
    churn = report.get("churn") or {}
    evictions = churn.get("evictions") or 0
    thrash = churn.get("thrash_reinserts") or 0
    if evictions >= min_evictions and thrash / evictions >= ratio:
        return True, (
            f"THRASHING: {thrash} of {evictions} evictions were "
            f"reinserted ({thrash / evictions:.0%} >= {ratio:.0%}) — "
            f"KV pool capacity is below the live prefix working set")
    return False, (
        f"healthy: {thrash} reinsert(s) over {evictions} eviction(s)")


def main(argv=None):
    parser = argparse.ArgumentParser(
        description=__doc__.splitlines()[0])
    parser.add_argument("report", nargs="?", default="-",
                        help="cache report JSON path, or - for stdin")
    parser.add_argument("--thrash-ratio", type=float, default=0.5,
                        help="reinserts/evictions fraction that "
                             "means thrash (default 0.5)")
    parser.add_argument("--min-evictions", type=int, default=8,
                        help="eviction floor below which no thrash "
                             "verdict fires (default 8)")
    parser.add_argument("--top", type=int, default=8,
                        help="hot prefixes shown (default 8)")
    args = parser.parse_args(argv)

    try:
        if args.report == "-":
            doc = json.load(sys.stdin)
        else:
            with open(args.report) as f:
                doc = json.load(f)
    except (OSError, ValueError) as e:
        print(f"cache_report: cannot read {args.report}: {e}",
              file=sys.stderr)
        return 2

    report = find_cache_report(doc)
    if report is None:
        print("cache_report: no cache section found in input",
              file=sys.stderr)
        return 2
    if not report.get("enabled"):
        print("cache observatory disabled on this engine — "
              "nothing to judge")
        return 0

    render(report, top=args.top)
    thrashing, reason = thrash_verdict(
        report, ratio=args.thrash_ratio,
        min_evictions=args.min_evictions)
    print(f"\n{reason}")
    return 1 if thrashing else 0


if __name__ == "__main__":
    sys.exit(main())
