"""GPT-124M MFU sweep (VERDICT r3 item 2: push 31.6% MFU toward 45%).

Runs tools/baseline_bench.py's GPT config across the tuning axes that
matter on one chip — AMP level (O1 per-op autocast vs O2 pure-bf16),
flash-attention tile sizes (fwd and bwd independently), and the
seq 2048/4096 extension points BASELINE.md names — each in a FRESH
SUBPROCESS (a tunnel wedge dies with its attempt; JAX backend state
never leaks between configs). Every result line is appended to a
timestamped artifact in bench_artifacts/ for BASELINE.md citation.

Usage:  python tools/gpt_mfu_sweep.py [quick|full]
  quick: amp sweep + best-guess block sweep at seq 1024 (~6 configs)
  full:  + seq 2048/4096 points and the full block grid
"""
import json
import os
import subprocess
import sys
import time

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_ART = os.path.join(_ROOT, "bench_artifacts")


def run_config(tag, batch, seq, env_extra, timeout=900):
    env = dict(os.environ)
    env.update(env_extra)
    cmd = [sys.executable, os.path.join(_ROOT, "tools",
                                        "baseline_bench.py"),
           "gpt", str(batch), str(seq)]
    t0 = time.time()
    try:
        res = subprocess.run(cmd, env=env, capture_output=True,
                             text=True, timeout=timeout)
        stdout, stderr, rc = res.stdout, res.stderr, res.returncode
        hung = None
    except subprocess.TimeoutExpired as e:
        # the measurement JSON may already be out (e.g. a wedge during
        # the post-measurement profile capture) — salvage it
        stdout = e.stdout or ""
        if isinstance(stdout, bytes):
            stdout = stdout.decode("utf-8", "replace")
        stderr, rc = "", -1
        hung = f"hung >{timeout}s (tunnel wedge?)"
    line = None
    for ln in stdout.splitlines():
        ln = ln.strip()
        if ln.startswith("{"):
            line = ln
    if line is None:
        return {"tag": tag,
                "error": hung or (stderr or "no output")[-400:],
                "rc": rc}
    out = json.loads(line)
    out["tag"] = tag
    out["wall_s"] = round(time.time() - t0, 1)
    if hung:
        out["note"] = ("measurement line salvaged; process " + hung)
    return out


def main():
    mode = sys.argv[1] if len(sys.argv) > 1 else "quick"
    os.makedirs(_ART, exist_ok=True)
    # FIXED per-mode artifact so a watcher retry after a mid-sweep wedge
    # resumes at the first config with no successful line instead of
    # restarting from config 1 (wedges are the norm, not the exception)
    art = os.path.join(_ART, f"gpt_mfu_sweep_{mode}_r05.jsonl")
    done = set()
    prior_best = None
    if os.path.exists(art):
        with open(art) as f:
            for ln in f:
                try:
                    rec = json.loads(ln)
                except ValueError:
                    continue
                if "tokens_per_sec" in rec:
                    done.add(rec["tag"])
                    if rec.get("seq") == 1024 and (
                            prior_best is None or rec["tokens_per_sec"]
                            > prior_best["tokens_per_sec"]):
                        prior_best = rec
                elif rec.get("rc", -1) != -1:
                    # a real exit code = deterministic failure (compile
                    # error, OOM at every batch) — reproduces on retry,
                    # skip it; a hang (rc -1) is a wedge, retry it
                    done.add(rec["tag"])

    configs = [
        ("baseline_O1", 8, 1024, {"GPT_AMP_LEVEL": "O1"}),
        ("O2_pure_bf16", 8, 1024, {"GPT_AMP_LEVEL": "O2"}),
        # ablation: the fused linear+CE head OFF (logits round-trip
        # HBM) — the delta vs O2_pure_bf16 is the fused-CE win
        ("O2_unfused_ce", 8, 1024, {"GPT_AMP_LEVEL": "O2",
                                    "PADDLE_FUSED_CE_DISABLE": "1"}),
        # hybrid: Pallas fused fwd (no logits in HBM) + XLA-composed
        # bwd (one recompute at XLA matmul efficiency instead of the
        # Pallas bwd's two hand-rolled ones)
        ("O2_ce_bwd_xla", 8, 1024, {"GPT_AMP_LEVEL": "O2",
                                    "PADDLE_FUSED_CE": "1",
                                    "PADDLE_FUSED_CE_BWD": "xla"}),
        # bigger token tile: halves the per-token-block W streaming
        ("O2_ce_bt512", 8, 1024, {"GPT_AMP_LEVEL": "O2",
                                  "PADDLE_FUSED_CE": "1",
                                  "PADDLE_FUSED_CE_BLOCK_T": "512"}),
        # the ceiling-analysis capture runs right after the head
        # decision configs — it is the "45% MFU or a profile-backed
        # ceiling analysis" deliverable and must not sit behind the
        # block sweep on a short window
        ("O2_nf_profiled", 8, 1024,
         {"GPT_AMP_LEVEL": "O2",
          "PADDLE_FUSED_CE_DISABLE": "1",
          "GPT_PROFILE_DIR": os.path.join(_ART, "gpt_profile_r05")}),
        # attention-axis configs run UNFUSED (nf): the 2026-08-02 window
        # showed the fused head costs ~46 ms/step, which would drown the
        # flash-tile deltas these configs exist to measure
        ("O2_nf_blk256_bwd", 8, 1024, {"GPT_AMP_LEVEL": "O2",
                                       "PADDLE_FUSED_CE_DISABLE": "1",
                                       "PADDLE_FLASH_BLOCK_BWD": "256"}),
        ("O2_nf_blk1024", 8, 1024, {"GPT_AMP_LEVEL": "O2",
                                    "PADDLE_FUSED_CE_DISABLE": "1",
                                    "PADDLE_FLASH_BLOCK_Q": "1024",
                                    "PADDLE_FLASH_BLOCK_K": "1024"}),
        ("O2_nf_blk1024_bwd", 8, 1024, {"GPT_AMP_LEVEL": "O2",
                                        "PADDLE_FUSED_CE_DISABLE": "1",
                                        "PADDLE_FLASH_BLOCK_BWD": "1024"}),
        # LAST in the quick list: hung >900s in the 2026-08-02 window
        # (wedge or compile churn) — must not block the ablation configs
        # on a short healthy window; unfused so the batch-scaling axis
        # is clean of the head question
        ("O2_nf_batch16", 16, 1024, {"GPT_AMP_LEVEL": "O2",
                                     "PADDLE_FUSED_CE_DISABLE": "1"}),
    ]
    if mode == "full":
        configs += [
            ("O1_nf_blk256_bwd", 8, 1024, {"GPT_AMP_LEVEL": "O1",
                                           "PADDLE_FUSED_CE_DISABLE": "1",
                                           "PADDLE_FLASH_BLOCK_BWD": "256"}),
            ("O2_nf_seq2048", 4, 2048, {"GPT_AMP_LEVEL": "O2",
                                        "PADDLE_FUSED_CE_DISABLE": "1"}),
            ("O2_nf_seq4096", 2, 4096, {"GPT_AMP_LEVEL": "O2",
                                        "PADDLE_FUSED_CE_DISABLE": "1"}),
            # fused head at seq 4096: the memory-bound config where
            # not materializing [T, V] logits should actually matter
            ("O2_seq4096_fused", 2, 4096, {"GPT_AMP_LEVEL": "O2",
                                           "PADDLE_FUSED_CE": "1"}),
            ("O2_nf_seq4096_rc_b4", 4, 4096, {"GPT_AMP_LEVEL": "O2",
                                              "PADDLE_FUSED_CE_DISABLE": "1",
                                              "GPT_RECOMPUTE": "1"}),
            # fused head at batch 16: if nf_batch16 OOMs back to batch
            # 8, this measures whether the no-logits-in-HBM head buys
            # the batch the unfused one can't fit
            ("O2_batch16_fused", 16, 1024, {"GPT_AMP_LEVEL": "O2",
                                            "PADDLE_FUSED_CE": "1"}),
        ]

    best = prior_best
    with open(art, "a") as f:
        for tag, batch, seq, env in configs:
            if tag in done:
                print(f"# {tag}: done in a previous attempt, skipping",
                      file=sys.stderr)
                continue
            print(f"# running {tag} (batch {batch} seq {seq}) ...",
                  file=sys.stderr)
            out = run_config(tag, batch, seq, env)
            f.write(json.dumps(out) + "\n")
            f.flush()
            print(json.dumps(out), flush=True)
            if "error" in out:
                # a wedge poisons the tunnel for every subsequent
                # config too — bail and let the watcher re-enter the
                # sweep (resume skips the finished tags)
                print("# config failed; exiting for watcher re-entry",
                      file=sys.stderr)
                sys.exit(1)
            if "tokens_per_sec" in out and (
                    best is None
                    or out["tokens_per_sec"] > best["tokens_per_sec"]):
                if out.get("seq") == 1024:
                    best = out
    if best:
        print(json.dumps({"best_1024": best,
                          "artifact": os.path.relpath(art, _ROOT)}),
              flush=True)


if __name__ == "__main__":
    main()
