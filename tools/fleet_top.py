#!/usr/bin/env python
"""fleet_top — the fleet table, one shot or watched.

Polls N serving replicas (their ``serve_metrics()`` surfaces) through
``paddle_tpu.observability.fleet.FleetPoller`` and renders one row per
replica: availability verdict, health posture, queue depth, step
rate, goodput tokens, decode roofline fraction, staleness — plus the
fleet rollup line (census, bucket-wise-merged latency percentiles,
fleet-detector firings).

    python tools/fleet_top.py 127.0.0.1:9100 127.0.0.1:9101
    python tools/fleet_top.py --registry fleet.json --watch 2

Exit code: 0 iff EVERY replica is up and healthy (the scriptable
all-clear a deploy gate wants); 1 otherwise, naming the offending
replicas on stderr. ``--json`` dumps the pinned-schema FleetSnapshot
instead of the table. ``--router URL`` additionally scrapes a serving
router's ``/router/state`` and stamps a router line under the fleet
line (journal depth, shed/retry/failover/hedge totals, per-replica
breaker states). ``--traces`` additionally scrapes each target's
``/debug/traces`` ring (and the router's ``/router/trace``),
assembles the distributed traces, and renders one line per trace
(window, unattributed gap, completeness). ``--tenants`` additionally
renders the federated per-tenant attribution table (exact counter
sums across replicas) plus the noisy_neighbor / tenant_starvation
detector state. Tier-1 self-runs this
against two in-process
engines (tests/test_fleet.py), the same discipline as
incident_report / chaos_sweep / perf_diff.
"""
import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

_COLS = (
    ("REPLICA", 18), ("VERDICT", 8), ("POSTURE", 9), ("RESTARTS", 9),
    ("QUEUE", 6), ("STEP/S", 8), ("GOODPUT", 9), ("ROOFLINE", 9),
    ("AGE_S", 7), ("UPTIME_S", 9),
)


def _fmt(v, nd=1):
    if v is None:
        return "-"
    if isinstance(v, float):
        return f"{v:.{nd}f}"
    return str(v)


def _posture(e):
    if e["verdict"] != "up":
        return e["verdict"]
    if e["draining"]:
        return "draining"
    if e["degraded"]:
        return "degraded"
    if e["healthy"] is False:
        return "unhealthy"
    return "healthy" if e["healthy"] else "?"


def render(snap, out=sys.stdout):
    line = "  ".join(f"{name:<{w}}" for name, w in _COLS)
    print(line, file=out)
    print("-" * len(line), file=out)
    for rid, e in sorted(snap["replicas"].items()):
        cells = (
            rid[:18], e["verdict"], _posture(e),
            _fmt(e["restarts"]), _fmt(e["queue_depth"]),
            _fmt(e["step_rate"]), _fmt(e["goodput_tokens"], 0),
            _fmt(e["roofline_fraction"], 3), _fmt(e["age_s"]),
            _fmt(e["uptime_s"]),
        )
        print("  ".join(f"{str(c):<{w}}" for c, (_, w)
                        in zip(cells, _COLS)), file=out)
    f = snap["fleet"]
    lat = f["latency"]["ttft"]
    print(f"fleet: {f['up']}/{f['size']} up ({f['stale']} stale, "
          f"{f['down']} down)  queue={_fmt(f['queue_depth'], 0)}  "
          f"step_rate={_fmt(f['step_rate'])}/s  "
          f"goodput_tokens={_fmt(f['goodput_tokens'], 0)}  "
          f"ttft_p50={_fmt(lat['p50_ms'])}ms "
          f"p99={_fmt(lat['p99_ms'])}ms  "
          f"anomalies={snap['health']['anomalies_total']}", file=out)


def fetch_router_state(url, timeout=2.0):
    """GET ``/router/state`` off a router's metrics server; None when
    unreachable (the fleet table still renders)."""
    import urllib.request
    url = url.rstrip("/")
    if "://" not in url:
        url = "http://" + url
    try:
        with urllib.request.urlopen(url + "/router/state",
                                    timeout=timeout) as resp:
            return json.loads(resp.read().decode("utf-8"))
    except Exception:   # noqa: BLE001 - best-effort stamp
        return None


def render_router(state, out=sys.stdout):
    if state is None:
        print("router: unreachable", file=out)
        return
    c = state["counters"]
    breakers = ", ".join(
        f"{r['replica_id']}={r['breaker']['state']}"
        for r in state["replicas"])
    print(f"router: journal={state['journal_depth']}  "
          f"ok={c['ok']} err={c['error']} shed={c['shed']}  "
          f"retries={c['retries']} failovers={c['failovers']} "
          f"hedges={c['hedges']}  breakers[{breakers}]", file=out)


def fetch_fleet_traces(targets, router=None, timeout=2.0):
    """Assemble distributed traces off the fleet's ``/debug/traces``
    rings (plus the router's ``/router/trace``) — best-effort; an
    unreachable replica just contributes no spans, so a partial trace
    renders with its missing segments named instead of hiding."""
    from paddle_tpu.observability.trace import TraceAssembler
    asm = TraceAssembler()
    scraped = 0
    urls = list(targets)
    if router:
        url = router.rstrip("/")
        if "://" not in url:
            url = "http://" + url
        urls.append(url + "/router/trace")
    for u in urls:
        try:
            asm.scrape(u, timeout=timeout)
            scraped += 1
        except Exception:   # noqa: BLE001 - best-effort stamp
            pass
    return asm.assemble_all() if scraped else []


def render_traces(traces, out=sys.stdout, limit=8):
    if not traces:
        print("traces: none assembled", file=out)
        return
    print(f"traces: {len(traces)} assembled "
          f"(newest {min(limit, len(traces))})", file=out)
    for t in traces[-limit:]:
        status = "complete" if t.complete else \
            "missing:" + ",".join(t.missing_segments())
        print(f"  {t.trace_id[:16]}  "
              f"replicas={','.join(t.replicas)}  "
              f"window={_fmt(t.window_ms())}ms  "
              f"gap={_fmt(t.unattributed_ms())}ms  {status}",
              file=out)


def render_tenants(doc, out=sys.stdout, limit=8):
    """One line per tenant off the poller's federated rollup, biggest
    token consumer first, plus the fairness detectors' verdicts."""
    fleet = (doc or {}).get("fleet")
    if not fleet:
        print("tenants: no tenant series reported", file=out)
        return
    rows = fleet["tenants"]
    print(f"tenants: {fleet['tenant_count']} "
          f"(folded={fleet['overflow_folded']}, showing "
          f"{min(limit, len(rows))})", file=out)
    for name, e in list(rows.items())[:limit]:
        print(f"  {name[:20]:<20} tokens={_fmt(e['tokens_out'], 0)}  "
              f"share={_fmt(e['token_share'], 3)}  "
              f"req={_fmt(e['requests'], 0)}  "
              f"attain={_fmt(e['attainment'], 3)}  "
              f"queued={_fmt(e['queued'], 0)}", file=out)
    for name, verdict in sorted((doc.get("last_verdicts")
                                 or {}).items()):
        print(f"  ! {name}: {verdict.get('reason', '?')}", file=out)


def verdict_exit(snap, out=sys.stderr):
    """0 iff all replicas up and healthy; else 1, naming offenders."""
    bad = {rid: e for rid, e in snap["replicas"].items()
           if e["verdict"] != "up" or e["healthy"] is not True
           or e["degraded"] or e["draining"]}
    if not bad and snap["fleet"]["healthy"]:
        return 0
    for rid, e in sorted(bad.items()):
        print(f"UNHEALTHY: {rid} verdict={e['verdict']} "
              f"posture={_posture(e)} "
              f"last_error={e['last_error'] or '-'}", file=out)
    if not bad:
        print("UNHEALTHY: fleet-level verdict false", file=out)
    return 1


def main(argv=None):
    parser = argparse.ArgumentParser(
        description="render the serving-fleet table; exit 0 iff all "
                    "replicas are up and healthy")
    parser.add_argument("targets", nargs="*",
                        help="replica scrape targets (host:port or "
                             "http://host:port)")
    parser.add_argument("--registry", default=None,
                        help="JSON registry file ({'replicas': "
                             "[{'id','url'}|'host:port', ...]})")
    parser.add_argument("--interval", type=float, default=1.0,
                        help="poll interval seconds (watch mode; also "
                             "spaces the two one-shot polls)")
    parser.add_argument("--timeout", type=float, default=1.0,
                        help="per-replica scrape timeout seconds")
    parser.add_argument("--down-after", type=int, default=1,
                        help="consecutive failures before a replica "
                             "is marked down (one-shot default 1: an "
                             "unreachable replica IS down)")
    parser.add_argument("--polls", type=int, default=2,
                        help="one-shot poll count (>=2 gives step "
                             "rates)")
    parser.add_argument("--watch", type=float, default=None,
                        metavar="SECS",
                        help="keep polling and re-rendering every "
                             "SECS until interrupted")
    parser.add_argument("--json", action="store_true",
                        help="dump the FleetSnapshot JSON instead of "
                             "the table")
    parser.add_argument("--router", default=None, metavar="URL",
                        help="also scrape a router's /router/state "
                             "and stamp its line (journal, breaker "
                             "states, dispatch counters)")
    parser.add_argument("--traces", action="store_true",
                        help="also assemble distributed traces off "
                             "the targets' /debug/traces rings (and "
                             "the router's /router/trace when "
                             "--router is given) and render one line "
                             "per trace")
    parser.add_argument("--tenants", action="store_true",
                        help="also render the federated per-tenant "
                             "attribution table and the fairness "
                             "detectors' state")
    args = parser.parse_args(argv)
    if not args.targets and not args.registry:
        parser.error("give targets or --registry")

    from paddle_tpu.observability.fleet import FleetPoller
    kw = dict(interval_s=args.interval, timeout_s=args.timeout,
              down_after=args.down_after)
    poller = FleetPoller.from_registry(args.registry, **kw) \
        if args.registry else FleetPoller(args.targets, **kw)

    if args.watch:
        try:
            while True:
                poller.poll_once()
                snap = poller.snapshot()
                print(f"\n== fleet_top {time.strftime('%H:%M:%S')} ==")
                render(snap)
                if args.router:
                    render_router(fetch_router_state(args.router))
                if args.traces:
                    render_traces(fetch_fleet_traces(
                        args.targets, router=args.router,
                        timeout=args.timeout))
                if args.tenants:
                    render_tenants(poller.fleet_tenants())
                time.sleep(args.watch)
        except KeyboardInterrupt:
            return verdict_exit(poller.snapshot())

    for i in range(max(1, args.polls)):
        if i:
            time.sleep(min(args.interval, 0.5))
        poller.poll_once()
    snap = poller.snapshot()
    router_state = fetch_router_state(args.router) \
        if args.router else None
    traces = fetch_fleet_traces(args.targets, router=args.router,
                                timeout=args.timeout) \
        if args.traces else None
    tenants = poller.fleet_tenants() if args.tenants else None
    if args.json:
        if args.router:
            snap = dict(snap, router=router_state)
        if traces is not None:
            snap = dict(snap, traces=[t.as_dict() for t in traces])
        if tenants is not None:
            snap = dict(snap, tenants=tenants)
        print(json.dumps(snap, indent=1, sort_keys=True, default=str))
    else:
        render(snap)
        if args.router:
            render_router(router_state)
        if traces is not None:
            render_traces(traces)
        if tenants is not None:
            render_tenants(tenants)
    return verdict_exit(snap)


if __name__ == "__main__":
    sys.exit(main())
