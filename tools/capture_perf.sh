#!/bin/bash
# One-command perf evidence capture (run when the TPU tunnel is healthy;
# never run two TPU processes at once — they corrupt each other's
# timings over the tunnel). Produces committed-able artifacts:
#   bench_artifacts/resnet50_<ts>.json      (bench.py worker evidence)
#   bench_artifacts/baseline_<ts>.log       (LeNet eager/lazy/compiled +
#                                            BERT MFU lines)
set -u
cd "$(dirname "$0")/.."
ts=$(date -u +%Y%m%dT%H%M%SZ)

echo "== probing backend (90s cap)..."
timeout 90 python -c "
import jax; d = jax.devices(); print(d[0].platform, len(d))
" || { echo 'tunnel wedged; aborting'; exit 1; }

echo "== bench.py worker (ResNet-50)..."
timeout 900 python bench.py --worker 128 20 \
    "bench_artifacts/resnet50_${ts}.json" \
    2> "bench_artifacts/resnet50_${ts}.stderr.log"
echo "rc=$?"

echo "== baseline_bench (LeNet + BERT)..."
timeout 1200 python tools/baseline_bench.py all \
    > "bench_artifacts/baseline_${ts}.log" 2>&1
echo "rc=$?"
ls -la bench_artifacts/ | tail -5
echo "commit these artifacts + update BASELINE.md citations"
