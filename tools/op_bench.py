"""Per-op micro-benchmark harness.

Reference parity: paddle/fluid/operators/benchmark/op_tester.cc (runs a
single op from a config and times it) + tools/check_op_benchmark_result.py
(CI regression compare). Usage:

  python tools/op_bench.py                    # built-in op set
  python tools/op_bench.py matmul softmax     # subset
  python tools/op_bench.py --compare old.json # regression check (>10% slow)

Prints one JSON line per op: {"op": ..., "shape": ..., "us": ...}.
Times the jit-compiled executable (the eager dispatch path) after warmup.
"""
import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

_CONFIGS = {
    "matmul": lambda paddle: (paddle.matmul,
                              [np.random.randn(1024, 1024).astype("float32"),
                               np.random.randn(1024, 1024).astype("float32")]),
    "bmm": lambda paddle: (paddle.bmm,
                           [np.random.randn(32, 256, 256).astype("float32"),
                            np.random.randn(32, 256, 256).astype("float32")]),
    "softmax": lambda paddle: (paddle.nn.functional.softmax,
                               [np.random.randn(64, 4096).astype("float32")]),
    "layer_norm": lambda paddle: (
        lambda x: paddle.nn.functional.layer_norm(
            x, x.shape[-1:],
            paddle.to_tensor(np.ones(1024, "float32")),
            paddle.to_tensor(np.zeros(1024, "float32"))),
        [np.random.randn(64, 1024).astype("float32")]),
    "relu": lambda paddle: (paddle.nn.functional.relu,
                            [np.random.randn(1024, 1024).astype("float32")]),
    "add": lambda paddle: (paddle.add,
                           [np.random.randn(1024, 1024).astype("float32"),
                            np.random.randn(1024, 1024).astype("float32")]),
    "conv2d": lambda paddle: (
        lambda x, w: paddle.nn.functional.conv2d(x, w, None, 1, 1),
        [np.random.randn(16, 64, 56, 56).astype("float32"),
         np.random.randn(64, 64, 3, 3).astype("float32")]),
    "reduce_sum": lambda paddle: (paddle.sum,
                                  [np.random.randn(2048, 2048)
                                   .astype("float32")]),
    "transpose": lambda paddle: (
        lambda x: paddle.transpose(x, [1, 0]),
        [np.random.randn(2048, 2048).astype("float32")]),
    "embedding": lambda paddle: (
        lambda ids, w: paddle.nn.functional.embedding(ids, w),
        [np.random.randint(0, 30000, (64, 512)).astype("int64"),
         np.random.randn(30000, 256).astype("float32")]),
}


def bench_one(paddle, name, warmup=5, iters=50):
    fn, arrays = _CONFIGS[name](paddle)
    tensors = [paddle.to_tensor(a) for a in arrays]
    out = None
    for _ in range(warmup):
        out = fn(*tensors)
    _sync(out)
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(*tensors)
    _sync(out)
    us = (time.perf_counter() - t0) / iters * 1e6
    return {"op": name, "shape": [list(a.shape) for a in arrays],
            "us": round(us, 2)}


def _sync(out):
    if isinstance(out, (tuple, list)):
        out = out[0]
    out.numpy()


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("ops", nargs="*", default=None)
    ap.add_argument("--compare", help="baseline json-lines file")
    ap.add_argument("--threshold", type=float, default=1.10,
                    help="fail if new/old exceeds this ratio")
    ap.add_argument("--cpu", action="store_true",
                    help="force CPU backend (for wedged TPU tunnels)")
    args = ap.parse_args()

    if args.cpu:
        import jax
        jax.config.update("jax_platforms", "cpu")
    import paddle_tpu as paddle

    names = args.ops or sorted(_CONFIGS)
    results = []
    for n in names:
        r = bench_one(paddle, n)
        results.append(r)
        print(json.dumps(r))

    if args.compare:
        old = {}
        with open(args.compare) as f:
            for line in f:
                d = json.loads(line)
                old[d["op"]] = d["us"]
        regressed = [(r["op"], old[r["op"]], r["us"]) for r in results
                     if r["op"] in old and r["us"] > old[r["op"]]
                     * args.threshold]
        for op, was, now in regressed:
            print(f"REGRESSION {op}: {was}us -> {now}us", file=sys.stderr)
        if regressed:
            sys.exit(1)


if __name__ == "__main__":
    main()
