#!/usr/bin/env python
"""Seeded chaos sweep over the serving engine: N seeds x fault kinds,
exit nonzero on any leak / hang / parity break.

Every cell of the matrix runs the SAME smoke workload on a hardened
engine (bounded retry + supervisor) under one armed fault site (plus
an "all" cell arming the default mix), then verifies the contract the
resilience layer owes:

  * **no hang** — the drain finishes within a step budget;
  * **no leak** — every slot free afterwards, and on the paged pool a
    full ``check_conservation()`` audit passes;
  * **parity** — every completed request's token stream is bit-exact
    with the unfaulted reference drain (greedy replay correctness
    through rollback, retry and supervisor restart);
  * **determinism** — the cell is re-run at the same seed and must
    reproduce the identical fault log and streams.

Output: one JSON line per cell plus a summary line; exit 1 on any
failure (the CI gate). Tier-1 self-runs ``--fast`` (one seed, both
pools) via tests/test_resilience.py; a nightly can widen ``--seeds``.

Usage: python tools/chaos_sweep.py [--seeds N] [--fast] [--paged 0|1]
"""
import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

# the per-site arming each cell uses: rates high enough that every
# recovery path actually runs during a ~40-request smoke drain
_SITE_RATES = {
    "prefill_dispatch": 0.25,
    "chunk_dispatch": 0.25,
    "decode_dispatch": 0.10,
    "transfer": 0.10,
    "block_exhaustion": 0.15,
    "callback": 0.30,
    "step_latency": {"rate": 0.05, "latency_s": 0.001},
}
_MAX_STEPS = 3000      # hang budget: a clean drain needs ~100 steps


def _build_model():
    import paddle_tpu as paddle
    from paddle_tpu.text.models import (GPTForCausalLM,
                                        TransformerLMConfig)
    paddle.seed(11)
    cfg = TransformerLMConfig(vocab_size=97, hidden_size=32,
                              num_layers=2, num_heads=4,
                              max_seq_len=64, dropout=0.0)
    m = GPTForCausalLM(cfg)
    m.eval()
    return m


def _workload(n_requests=16):
    import numpy as np
    rs = np.random.RandomState(5)
    lengths = rs.randint(3, 20, n_requests)
    return [(rs.randint(0, 97, (int(n),)).astype(np.int64),
             int(rs.randint(3, 8))) for n in lengths]


def _drain(model, specs, paged, chaos=None, chunk=None,
           paged_attn=False, spec=False):
    """One engine drain; returns (streams, engine, steps, fault_log)."""
    from paddle_tpu.serving import ServingEngine
    eng = ServingEngine(
        model, num_slots=4, bucket_min=8, paged=paged,
        paged_attn=paged_attn, speculative=spec,
        prefill_chunk=chunk, chaos=chaos, max_dispatch_retries=3,
        supervisor_cooldown_s=0.0, health_audit_every=8)
    reqs = [eng.add_request(p, max_new_tokens=k,
                            on_token=lambda r, t: None)
            for p, k in specs]
    steps = 0
    while eng.step():
        steps += 1
        if steps > _MAX_STEPS:
            return None, eng, steps, None   # hang
    streams = [list(r.generated) for r in reqs]
    log = eng.chaos.fault_log() if eng.chaos is not None else None
    return streams, eng, steps, log


def _check_cell(site, seed, model, specs, reference, paged, chunk,
                paged_attn=False, spec=False):
    """Run one (site, seed) cell twice; returns a result dict with
    ok=False and a reason on any contract break."""
    from paddle_tpu.serving.resilience import FaultPlan
    faults = dict(_SITE_RATES) if site == "all" \
        else {site: _SITE_RATES[site]}

    def plan():
        return FaultPlan(seed=seed, faults=faults)

    out = {"site": site, "seed": seed, "paged": paged,
           "paged_attn": paged_attn, "spec": spec, "ok": True}
    streams, eng, steps, log = _drain(model, specs, paged,
                                      chaos=plan(), chunk=chunk,
                                      paged_attn=paged_attn, spec=spec)
    out["steps"] = steps
    if streams is None:
        return dict(out, ok=False, reason=f"hang: > {_MAX_STEPS} steps")
    res = eng.metrics.snapshot()["resilience"]
    out["faults"] = res["faults_injected"]
    out["retries"] = res["dispatch_retries"]
    out["restarts"] = res["supervisor_restarts"]
    # leak checks: every slot free, paged block conservation intact
    if eng.pool.free_count + len(eng.pool.quarantined) \
            != eng.pool.num_slots:
        return dict(out, ok=False, reason="slot leak after drain")
    if paged:
        try:
            eng.pool.check_conservation()
        except AssertionError as e:
            return dict(out, ok=False,
                        reason=f"block conservation: {e}")
        if eng.pool.live_blocks > 0:
            return dict(out, ok=False, reason="live blocks at idle")
    # parity: completed requests match the unfaulted reference
    bad = [i for i, (got, want) in enumerate(zip(streams, reference))
           if got and got != want]
    if bad:
        return dict(out, ok=False,
                    reason=f"parity break on requests {bad}")
    incomplete = sum(1 for got, want in zip(streams, reference)
                     if got != want)
    out["incomplete"] = incomplete   # aborted-after-retries allowed,
    if incomplete > len(specs) // 4:  # but not wholesale failure
        return dict(out, ok=False,
                    reason=f"{incomplete}/{len(specs)} incomplete")
    # determinism: same seed => identical fault log and streams
    streams2, _, _, log2 = _drain(model, specs, paged, chaos=plan(),
                                  chunk=chunk, paged_attn=paged_attn,
                                  spec=spec)
    if log2 != log:
        return dict(out, ok=False, reason="fault log not deterministic")
    if streams2 != streams:
        return dict(out, ok=False, reason="streams not deterministic")
    return out


def _check_handoff_cell(seed, model, specs, reference):
    """Disaggregated KV-handoff cell (ISSUE 17): every request
    prefills on a prefill-role engine, crosses the wire as a
    serialized block payload, and decodes on a decode-role engine —
    with a seeded fraction of payloads corrupted in flight (digest
    flip / dropped frame / garbled base64). The contract: corruption
    raises the TYPED wire error and never poisons the decode pool (a
    clean retry of the same handoff must succeed and stay bit-exact
    with the monolithic reference), both tiers end block-clean, and
    the same seed reproduces the same corruption schedule and
    streams."""
    import copy

    import numpy as np

    from paddle_tpu.serving import ServingEngine
    from paddle_tpu.serving.kv_wire import KVWireError

    def corrupt(rs, payload):
        bad = copy.deepcopy(payload)
        kind = int(rs.randint(3))
        if kind == 0:
            f = bad["frames"][int(rs.randint(len(bad["frames"])))]
            f["digest"] = (f["digest"] + 1) % (1 << 32)
        elif kind == 1:
            bad["frames"].pop()
        else:
            bad["frames"][0]["k"] = "!!notb64"
        return bad

    def run_once():
        pe = ServingEngine(model, num_slots=4, bucket_min=8,
                           paged=True, role="prefill")
        de = ServingEngine(model, num_slots=4, bucket_min=8,
                           paged=True, role="decode")
        rs = np.random.RandomState(seed)
        streams, faults = [], 0
        try:
            for p, k in specs:
                req = pe.add_request(p, max_new_tokens=1, hold_kv=True)
                pe.run()
                payload = pe.export_kv(req.rid)
                if rs.rand() < 0.4:
                    faults += 1
                    try:
                        de.import_kv(corrupt(rs, payload),
                                     max_new_tokens=int(k))
                    except KVWireError:
                        pass
                    else:
                        return None, faults, \
                            "corrupted import did not raise KVWireError"
                dreq = de.import_kv(payload, max_new_tokens=int(k))
                de.run()
                streams.append(list(dreq.generated))
            for eng, tier in ((pe, "prefill"), (de, "decode")):
                if eng._held_exports:
                    return None, faults, f"held-export leak: {tier}"
                try:
                    eng.pool.check_conservation()
                except AssertionError as e:
                    return None, faults, \
                        f"{tier} block conservation: {e}"
                if eng.pool.live_blocks > 0:
                    return None, faults, f"live blocks at idle: {tier}"
        finally:
            pe.close()
            de.close()
        return streams, faults, None

    out = {"site": "kv_handoff", "seed": seed, "paged": True, "ok": True}
    streams, faults, reason = run_once()
    out["faults"] = {"kv_wire_corruption": faults}
    if reason:
        return dict(out, ok=False, reason=reason)
    bad = [i for i, (got, want) in enumerate(zip(streams, reference))
           if got != want]
    if bad:
        return dict(out, ok=False,
                    reason=f"handoff parity break on requests {bad}")
    streams2, faults2, reason2 = run_once()
    if reason2:
        return dict(out, ok=False, reason=f"rerun: {reason2}")
    if faults2 != faults:
        return dict(out, ok=False,
                    reason="corruption schedule not deterministic")
    if streams2 != streams:
        return dict(out, ok=False, reason="streams not deterministic")
    return out


def _patrolled(check, *args, **kwargs):
    """Run one cell with the lock patrol armed: every seeded fault
    schedule doubles as a race/deadlock drill. A lock-order or
    held-across-dispatch finding fails the cell with the finding JSON
    in the cell row."""
    from paddle_tpu.analysis import lock_patrol

    with lock_patrol() as patrol:
        result = check(*args, **kwargs)
        findings = patrol.findings()
    if findings:
        patrol_json = [f.to_dict() for f in findings]
        if result.get("ok"):
            result = dict(result, ok=False,
                          reason="lock patrol findings",
                          patrol=patrol_json)
        else:   # keep the cell's own failure reason, attach the drill
            result = dict(result, patrol=patrol_json)
    return result


def main(argv=None):
    parser = argparse.ArgumentParser(
        description=__doc__.splitlines()[0])
    parser.add_argument("--seeds", type=int, default=3)
    parser.add_argument("--fast", action="store_true",
                        help="one seed, reduced site matrix (tier-1)")
    parser.add_argument("--paged", type=int, choices=(0, 1),
                        default=None,
                        help="restrict to one pool flavor")
    args = parser.parse_args(argv)

    sites = ["prefill_dispatch", "decode_dispatch", "transfer",
             "callback", "block_exhaustion", "chunk_dispatch", "all"]
    seeds = [1] if args.fast else list(range(1, args.seeds + 1))
    if args.fast:
        sites = ["prefill_dispatch", "decode_dispatch", "chunk_dispatch",
                 "all"]
    pools = [False, True] if args.paged is None else [bool(args.paged)]

    model = _build_model()
    specs = _workload(12 if args.fast else 16)
    # one long prompt so chunk_dispatch cells exercise real chunking
    chunk = 8
    import numpy as np
    rs = np.random.RandomState(9)
    specs = specs + [(rs.randint(0, 97, (28,)).astype(np.int64), 4)]

    failures = 0
    cells = 0
    for paged in pools:
        reference, ref_eng, _, _ = _drain(model, specs, paged,
                                          chunk=chunk)
        assert reference is not None, "reference drain hung"
        for seed in seeds:
            for site in sites:
                if site == "block_exhaustion" and not paged:
                    continue   # legacy pool has no block economy
                cells += 1
                result = _patrolled(_check_cell, site, seed, model,
                                    specs, reference, paged, chunk)
                print(json.dumps(result), flush=True)
                if not result["ok"]:
                    failures += 1
    # one decode-faulted cell per seed with the Pallas paged decode
    # kernel gate on (interpret mode on CPU): retry/restart replay
    # must stay bit-exact through the kernel path too, against a
    # kernel-enabled unfaulted reference
    from paddle_tpu.ops import paged_attention as paged_attn_mod
    paged_attn_mod._FORCE_INTERPRET[0] = True
    try:
        reference, _, _, _ = _drain(model, specs, True, chunk=chunk,
                                    paged_attn=True)
        assert reference is not None, "pallas reference drain hung"
        for seed in seeds:
            cells += 1
            result = _patrolled(_check_cell, "decode_dispatch", seed,
                                model, specs, reference, True, chunk,
                                paged_attn=True)
            print(json.dumps(result), flush=True)
            if not result["ok"]:
                failures += 1
    finally:
        paged_attn_mod._FORCE_INTERPRET[0] = False
    # speculation-enabled cells per seed, both pools: decode faults now
    # hit k-token verify dispatches too (same "decode_dispatch" site),
    # and retry / supervisor-restart replay must stay bit-exact against
    # a SPEC-ENABLED unfaulted reference (which itself is bit-exact
    # with the plain reference by the acceptance construction — both
    # invariants break loudly here if either drifts). Longer
    # generations so the n-gram drafter actually proposes and verify
    # dispatches really carry drafts when the faults land.
    spec_specs = [(p, k + 8) for p, k in specs]
    for paged in pools:
        reference, _, _, _ = _drain(model, spec_specs, paged,
                                    chunk=chunk, spec=True)
        assert reference is not None, "spec reference drain hung"
        for seed in seeds:
            cells += 1
            result = _patrolled(_check_cell, "decode_dispatch", seed,
                                model, spec_specs, reference, paged,
                                chunk, spec=True)
            print(json.dumps(result), flush=True)
            if not result["ok"]:
                failures += 1
    # disaggregated KV-handoff cells (ISSUE 17), paged pool only (the
    # wire unit IS the paged block): seeded in-flight corruption must
    # surface as the typed wire error without poisoning the decode
    # pool, clean retries stay bit-exact with a monolithic reference,
    # and both tiers end block-clean
    if True in pools:
        reference, _, _, _ = _drain(model, specs, True, chunk=chunk)
        assert reference is not None, "handoff reference drain hung"
        for seed in seeds:
            cells += 1
            result = _patrolled(_check_handoff_cell, seed, model,
                                specs, reference)
            print(json.dumps(result), flush=True)
            if not result["ok"]:
                failures += 1
    print(json.dumps({"summary": True, "cells": cells,
                      "failures": failures}), flush=True)
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
