"""MFU ceiling analysis from a perfetto trace + sweep artifact.

Digests the XPlane/perfetto capture that `GPT_PROFILE_DIR` (see
tools/baseline_bench.py, emitted by the O2_nf_profiled config of
tools/gpt_mfu_sweep.py) writes, into the per-step device-time breakdown
the round-5 deliverable asks for ("profile-backed ceiling analysis"):
which fraction of the step is MXU matmul work vs Pallas kernels vs
data movement vs host gaps — i.e. where the non-MFU time actually goes.

Usage: python tools/mfu_analysis.py [profile_dir] [n_steps]
  profile_dir defaults to bench_artifacts/gpt_profile_r05, n_steps 5.
"""
import glob
import gzip
import json
import os
import re
import sys

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_BUCKETS = [
    ("matmul (MXU)", re.compile(r"dot|conv|einsum|gemm|matmul", re.I)),
    ("pallas/mosaic kernels", re.compile(
        r"custom.?call|mosaic|flash|fused_ce|pallas", re.I)),
    ("collectives", re.compile(
        r"all.?reduce|all.?gather|reduce.?scatter|collective|permute",
        re.I)),
    ("data movement", re.compile(
        r"copy|transpose|reshape|broadcast|concat|slice|gather|scatter|"
        r"pad|convert|bitcast", re.I)),
    ("elementwise/fusion", re.compile(r"fusion|loop|add|mul|select", re.I)),
]


def load_events(profile_dir):
    files = sorted(glob.glob(os.path.join(
        profile_dir, "**", "perfetto_trace.json.gz"), recursive=True))
    if not files:
        raise SystemExit(f"no perfetto_trace.json.gz under {profile_dir}")
    with gzip.open(files[-1]) as f:
        data = json.load(f)
    return data["traceEvents"] if isinstance(data, dict) else data


def main():
    profile_dir = sys.argv[1] if len(sys.argv) > 1 else os.path.join(
        _ROOT, "bench_artifacts", "gpt_profile_r05")
    n_steps = int(sys.argv[2]) if len(sys.argv) > 2 else 5
    evs = load_events(profile_dir)

    # thread/process name tables
    names = {}
    for e in evs:
        if e.get("ph") == "M" and e.get("name") in ("thread_name",
                                                    "process_name"):
            key = (e.get("pid"), e.get("tid"), e["name"])
            names[key] = e.get("args", {}).get("name", "")

    # aggregate complete events per thread
    per_thread = {}
    for e in evs:
        if e.get("ph") != "X" or "dur" not in e:
            continue
        k = (e.get("pid"), e.get("tid"))
        agg = per_thread.setdefault(k, {"total": 0.0, "ops": {}})
        agg["total"] += e["dur"]
        agg["ops"][e["name"]] = agg["ops"].get(e["name"], 0.0) + e["dur"]

    if not per_thread:
        raise SystemExit("no complete events in trace")

    # device lanes: prefer threads whose process/thread name mentions
    # TPU/device; fall back to the busiest thread
    def lane_name(k):
        return (names.get((k[0], k[1], "thread_name"), "") + " / "
                + names.get((k[0], None, "process_name"),
                            names.get((k[0], 0, "process_name"), "")))

    device = {k: v for k, v in per_thread.items()
              if re.search(r"tpu|device|xla", lane_name(k), re.I)}
    if not device:
        busiest = max(per_thread, key=lambda k: per_thread[k]["total"])
        device = {busiest: per_thread[busiest]}

    ops = {}
    for v in device.values():
        for name, dur in v["ops"].items():
            ops[name] = ops.get(name, 0.0) + dur
    total_us = sum(ops.values())

    buckets = {label: 0.0 for label, _ in _BUCKETS}
    buckets["other"] = 0.0
    for name, dur in ops.items():
        for label, pat in _BUCKETS:
            if pat.search(name):
                buckets[label] += dur
                break
        else:
            buckets["other"] += dur

    print(json.dumps({
        "profile_dir": os.path.relpath(profile_dir, _ROOT),
        "device_lanes": [lane_name(k) for k in device],
        "device_time_ms_per_step": round(total_us / 1e3 / n_steps, 3),
        "breakdown_ms_per_step": {
            k: round(v / 1e3 / n_steps, 3)
            for k, v in sorted(buckets.items(), key=lambda x: -x[1])},
        "top_ops_ms_per_step": {
            k: round(v / 1e3 / n_steps, 3)
            for k, v in sorted(ops.items(), key=lambda x: -x[1])[:15]},
    }, indent=1))


if __name__ == "__main__":
    main()
