"""Measure BASELINE.md configs on the real chip.

Config 1: LeNet/MNIST dygraph — eager step time AND to_static step time
          (the eager-vs-compiled gap is SURVEY §7 hard-part 1).
Config 3: BERT-base pretraining (MLM+NSP), bf16 AMP, to_static.

Prints one JSON line per measurement. Run: python tools/baseline_bench.py
"""
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def _sync(t):
    # force a device->host read: on the tunneled axon backend
    # block_until_ready can return before the computation retires, but a
    # D2H materialization cannot
    return float(np.asarray(t.value).reshape(-1)[0])


def bench_lenet():
    import paddle_tpu as paddle
    import paddle_tpu.nn as nn
    from paddle_tpu.vision.models import LeNet

    paddle.seed(0)
    net = LeNet()
    opt = paddle.optimizer.Adam(1e-3, parameters=net.parameters())
    loss_fn = nn.CrossEntropyLoss()
    batch = 64
    x = paddle.to_tensor(
        np.random.randn(batch, 1, 28, 28).astype("float32"))
    y = paddle.to_tensor(np.random.randint(0, 10, (batch,)).astype("int64"))

    def step():
        loss = loss_fn(net(x), y)
        loss.backward()
        opt.step()
        opt.clear_grad()
        return loss

    n = 20

    def time_eager():
        for _ in range(3):
            _sync(step())  # warm executable caches
        t0 = time.perf_counter()
        for _ in range(n):
            loss = step()
        _sync(loss)
        return (time.perf_counter() - t0) / n * 1000

    # eager with lazy micro-tracing (the default: core/lazy.py defers
    # ops and flushes each step as one cached executable)
    paddle.set_flags({"FLAGS_lazy_eager": True})
    eager_lazy_ms = time_eager()
    # eager immediate (per-op dispatch — the r2 baseline mode)
    paddle.set_flags({"FLAGS_lazy_eager": False})
    eager_imm_ms = time_eager()
    paddle.set_flags({"FLAGS_lazy_eager": True})

    compiled = paddle.jit.to_static(step)
    for _ in range(3):
        _sync(compiled())
    t0 = time.perf_counter()
    for _ in range(n):
        loss = compiled()
    _sync(loss)
    comp_ms = (time.perf_counter() - t0) / n * 1000

    print(json.dumps({
        "config": 1, "model": "LeNet/MNIST", "batch": batch,
        "eager_step_ms": round(eager_lazy_ms, 3),
        "eager_immediate_step_ms": round(eager_imm_ms, 3),
        "to_static_step_ms": round(comp_ms, 3),
        "eager_over_compiled": round(eager_lazy_ms / comp_ms, 1),
        "samples_per_sec_compiled": round(batch / comp_ms * 1000, 1),
    }), flush=True)


def bench_bert(batch=32, seq=128, steps=20):
    import paddle_tpu as paddle
    import paddle_tpu.nn as nn  # noqa: F401
    from paddle_tpu.text.models import bert_base

    paddle.seed(0)
    model = bert_base(max_seq_len=seq, dropout=0.0)
    n_params = sum(int(np.prod(p.aval_shape()))
                   for p in model.parameters())
    opt = paddle.optimizer.AdamW(1e-4, parameters=model.parameters(),
                                 weight_decay=0.01)

    def step_fn(ids, tok, mlm, nsp):
        with paddle.amp.auto_cast(level="O1", dtype="bfloat16"):
            loss = model(ids, tok, mlm, nsp)
        loss.backward()
        opt.step()
        opt.clear_grad()
        return loss

    train_step = paddle.jit.to_static(step_fn)

    def data(b):
        rs = np.random.RandomState(0)
        ids = rs.randint(0, 30522, (b, seq)).astype("int64")
        tok = np.zeros((b, seq), "int64")
        mlm = np.where(rs.rand(b, seq) < 0.15,
                       rs.randint(0, 30522, (b, seq)), -1).astype("int64")
        nsp = rs.randint(0, 2, (b, 1)).astype("int64")
        return tuple(paddle.to_tensor(a) for a in (ids, tok, mlm, nsp))

    # discovery at tiny batch, then shape-polymorphic compile at target
    small = data(2)
    for _ in range(3):
        _sync(train_step(*small))
    for b in (batch, batch // 2, batch // 4):
        try:
            args = data(b)
            t0 = time.perf_counter()
            _sync(train_step(*args))
            print(f"# bert compile (batch {b}): "
                  f"{time.perf_counter() - t0:.1f}s", file=sys.stderr)
            # chained steps with ONE final D2H sync: per-step syncing
            # adds the ~65ms tunnel round-trip to every step, while the
            # final materialization provably waits for the whole
            # dependency chain (params thread step-to-step)
            t0 = time.perf_counter()
            for _ in range(steps):
                loss = train_step(*args)
            _sync(loss)
            dt = (time.perf_counter() - t0) / steps
            step_ms = dt * 1000
            sps = b / dt
            tokens_per_sec = sps * seq
            # training FLOPs ~ 6 * params per token
            mfu = 6.0 * n_params * tokens_per_sec / 197e12
            print(json.dumps({
                "config": 3, "model": "BERT-base pretrain",
                "batch": b, "seq": seq,
                "params_m": round(n_params / 1e6, 1),
                "step_ms": round(step_ms, 2),
                "samples_per_sec": round(sps, 1),
                "tokens_per_sec": round(tokens_per_sec, 0),
                "mfu_vs_v5e_peak_bf16": round(mfu, 3),
                "final_loss": round(float(loss.numpy()), 4),
            }), flush=True)
            return
        except Exception as e:
            if "RESOURCE_EXHAUSTED" not in str(e) \
                    and "ResourceExhausted" not in str(e):
                raise
            print(f"# bert batch {b} OOM, retrying", file=sys.stderr)
    print(json.dumps({"config": 3, "model": "BERT-base pretrain",
                      "error": "all batch sizes OOMed"}), flush=True)


def bench_gpt(batch=8, seq=1024, steps=20, amp_level=None):
    """GPT-2-small-scale (124M) causal-LM training on one chip: the
    flagship LLM path — Pallas flash attention fwd+bwd, AdamW, bf16.
    Reference flagship analogue: GPT pretraining under hybrid_parallel
    (the single-chip slice of BASELINE.md config 5).

    Knobs (also see tools/gpt_mfu_sweep.py): batch/seq from argv,
    GPT_AMP_LEVEL=O1|O2 (O2 = pure-bf16 compute, fp32 master weights in
    the optimizer — halves the cast traffic), PADDLE_FLASH_BLOCK_* for
    the attention kernel tile sweep."""
    import paddle_tpu as paddle
    from paddle_tpu.text.models import TransformerLMConfig, GPTForCausalLM

    amp_level = amp_level or os.environ.get("GPT_AMP_LEVEL", "O1")
    paddle.seed(0)
    cfg = TransformerLMConfig(
        vocab_size=50304, hidden_size=768,
        num_layers=12, num_heads=12,
        max_seq_len=seq, dropout=0.0, use_flash_attention=True,
        recompute=os.environ.get("GPT_RECOMPUTE", "0") == "1")
    model = GPTForCausalLM(cfg)
    n_params = sum(int(np.prod(p.aval_shape()))
                   for p in model.parameters())
    opt = paddle.optimizer.AdamW(1e-4, parameters=model.parameters(),
                                 weight_decay=0.01)

    def step_fn(ids, labels):
        with paddle.amp.auto_cast(level=amp_level, dtype="bfloat16"):
            loss = model(ids, labels=labels)
        loss.backward()
        opt.step()
        opt.clear_grad()
        return loss

    train_step = paddle.jit.to_static(step_fn)

    def data(b):
        rs = np.random.RandomState(0)
        ids = rs.randint(0, 50304, (b, seq)).astype("int64")
        return (paddle.to_tensor(ids), paddle.to_tensor(ids.copy()))

    small = data(1)
    for _ in range(3):
        _sync(train_step(*small))
    for b in (batch, batch // 2, batch // 4):
        if b < 1:
            continue  # caller-chosen small batches: never "train" on b=0
        try:
            args = data(b)
            t0 = time.perf_counter()
            _sync(train_step(*args))
            print(f"# gpt compile (batch {b}): "
                  f"{time.perf_counter() - t0:.1f}s", file=sys.stderr)
            t0 = time.perf_counter()
            for _ in range(steps):
                loss = train_step(*args)
            _sync(loss)  # ONE final D2H sync (see bench_bert note)
            dt = (time.perf_counter() - t0) / steps
            tokens_per_sec = b * seq / dt
            mfu = 6.0 * n_params * tokens_per_sec / 197e12
            # true-FLOPs MFU as well: 6N ignores the attention
            # quadratic. Causal fwd score+value matmuls are 2*s*d
            # FLOPs/token/layer; fwd+bwd ~3x that -> 6*L*s*d extra,
            # no longer negligible at seq >= 1024
            attn_extra = 6.0 * cfg.num_layers * seq * cfg.hidden_size
            mfu_true = ((6.0 * n_params + attn_extra)
                        * tokens_per_sec / 197e12)
            print(json.dumps({
                "config": 5, "model": "GPT-124M causal LM (flash attn)",
                "batch": b, "seq": seq, "amp": amp_level,
                "params_m": round(n_params / 1e6, 1),
                "step_ms": round(dt * 1000, 2),
                "tokens_per_sec": round(tokens_per_sec, 0),
                "mfu_vs_v5e_peak_bf16": round(mfu, 3),
                "mfu_incl_attention_flops": round(mfu_true, 3),
                "final_loss": round(float(loss.numpy()), 4),
            }), flush=True)
            prof_dir = os.environ.get("GPT_PROFILE_DIR")
            if prof_dir and b != batch:
                # an OOM fallback batch is NOT the headline workload —
                # a ceiling analysis on it would be misattributed
                print(f"# skipping profile: measured batch {b} != "
                      f"requested {batch}", file=sys.stderr)
                prof_dir = None
            if prof_dir:
                # XPlane capture of 5 steady-state steps for the MFU
                # ceiling analysis (VERDICT r4 item 1); best-effort —
                # a failed capture must not sink the measurement above
                try:
                    import jax
                    # perfetto trace = gzipped JSON, parseable without
                    # the TF profiler stack (XPlane .pb is not)
                    with jax.profiler.trace(prof_dir,
                                            create_perfetto_trace=True):
                        for _ in range(5):
                            loss = train_step(*args)
                        _sync(loss)
                    print(f"# profile captured to {prof_dir}",
                          file=sys.stderr)
                except Exception as pe:  # noqa: BLE001
                    print(f"# profile capture failed: {pe}",
                          file=sys.stderr)
            return
        except Exception as e:
            if "RESOURCE_EXHAUSTED" not in str(e) \
                    and "ResourceExhausted" not in str(e):
                raise
            print(f"# gpt batch {b} OOM, retrying", file=sys.stderr)
    print(json.dumps({"config": 5, "model": "GPT-124M causal LM",
                      "error": "all batch sizes OOMed"}), flush=True)


def main():
    which = sys.argv[1] if len(sys.argv) > 1 else "all"
    if which in ("all", "lenet"):
        bench_lenet()
    if which in ("all", "bert"):
        bench_bert()
    if which in ("all", "gpt"):
        kw = {}
        if len(sys.argv) > 2:
            kw["batch"] = int(sys.argv[2])
        if len(sys.argv) > 3:
            kw["seq"] = int(sys.argv[3])
        bench_gpt(**kw)


if __name__ == "__main__":
    main()
