#!/usr/bin/env python
"""Self-lint the repo's jitted entry points (paddle_tpu.analysis).

Builds the three kinds of compiled programs this framework ships —

  * ``serving_decode``   — a ServingEngine on a tiny GPT, drained once
    and warm-declared, linted via ``engine.lint()`` (f64-upcast /
    host-callback / donation over the decode jaxpr, dynamic-shape-risk
    over the engine's compile watchdog);
  * ``paged_decode``     — the same engine with the paged KV pool
    (``paged=True``): the decode jaxpr now threads the int32 block
    table, and the f64-upcast + donation passes must stay clean with
    that argument (the table is small and host-authored — donating it
    would be noise, and the donation pass's size floor keeps it
    silent);
  * ``paged_decode_pallas`` — the paged engine again with the Pallas
    paged decode-attention kernel enabled (``paged_attn=True``,
    interpret mode forced so the kernel traces on this CPU lint run):
    the decode jaxpr now embeds the ``pallas_call`` and the f64-upcast
    + donation passes must stay clean across its boundary (the kernel
    traces in 32-bit mode — pallas_compat — so an f64 leak here is a
    real finding, not noise);
  * ``chunked_prefill``  — a chunked-prefill + per-slot-sampling
    engine (``prefill_chunk=``, ``sampling=True``): the chunk program
    (traced start/len/slot/final scalars + sampling params) and the
    sampling decode linted via ``engine.lint(program="chunk")`` /
    ``engine.lint()`` — both must stay f64/donation clean;
  * ``spec_verify``      — speculative-decoding engines on BOTH pools
    (``speculative=True``): the k-token verify program
    (``engine.lint(program="spec_verify")``) and the plain decode it
    falls back to must all stay f64/donation clean — the verify
    flavor donates kc/vc/pos exactly like decode, shifted past the
    drafts/dlen host inputs;
  * ``kv_wire``          — a disaggregated KV handoff between a
    prefill-role and a decode-role paged engine: the ``kv_import``
    program is linted like any other jitted entry point, a SECOND
    handoff after ``declare_warmup`` must not compile (export/import
    are dispatch-only on the steady-state hot path), and the export
    program's device->host transfer must stay per-slot sized — an
    export whose outputs approach the full pool is a ``device_get``
    of the whole KV cache wearing a trench coat (error severity);
  * ``hapi_train_step``  — a hapi.Model static-adapter train step
    (forward + loss + backward + optimizer captured as ONE to_static
    program), linted via ``TracedFunction.lint()``;
  * ``to_static_sample`` — a @to_static function with tensor-bound
    control flow (the dy2static while/cond lowering path), linted the
    same way —

and prints every finding as JSON on stdout. Exit status: 0 when no
error-severity findings (warnings are reported but don't fail),
1 otherwise — wired into tier-1 via tests/test_analysis.py so the repo
stays self-clean.

Usage: python tools/lint_graft.py [--pretty]
"""
import argparse
import json
import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402


def lint_serving_decode():
    import paddle_tpu as paddle
    from paddle_tpu.serving import ServingEngine
    from paddle_tpu.text.models import GPTForCausalLM, TransformerLMConfig

    paddle.seed(7)
    cfg = TransformerLMConfig(vocab_size=97, hidden_size=32, num_layers=2,
                              num_heads=4, max_seq_len=64, dropout=0.0)
    model = GPTForCausalLM(cfg)
    model.eval()
    engine = ServingEngine(model, num_slots=4)
    rs = np.random.RandomState(0)
    for n in (5, 9, 17):
        engine.add_request(rs.randint(0, 97, (n,)).astype(np.int64),
                           max_new_tokens=4)
    engine.run()
    engine.declare_warmup()
    return engine.lint()


def lint_paged_decode():
    import paddle_tpu as paddle
    from paddle_tpu.serving import ServingEngine
    from paddle_tpu.text.models import GPTForCausalLM, TransformerLMConfig

    paddle.seed(7)
    cfg = TransformerLMConfig(vocab_size=97, hidden_size=32, num_layers=2,
                              num_heads=4, max_seq_len=64, dropout=0.0)
    model = GPTForCausalLM(cfg)
    model.eval()
    engine = ServingEngine(model, num_slots=4, paged=True, block_size=8)
    rs = np.random.RandomState(0)
    shared = rs.randint(0, 97, (16,)).astype(np.int64)
    for n in (5, 9):
        engine.add_request(
            np.concatenate([shared,
                            rs.randint(0, 97, (n,)).astype(np.int64)]),
            max_new_tokens=4)
    engine.run()
    engine.declare_warmup()
    assert engine.metrics.snapshot()["prefix_cache"]["hits"] >= 1, \
        "paged lint target never exercised the prefix cache"
    return engine.lint()


def lint_paged_decode_pallas():
    import paddle_tpu as paddle
    from paddle_tpu.ops import paged_attention as paged_attn
    from paddle_tpu.serving import ServingEngine
    from paddle_tpu.text.models import GPTForCausalLM, TransformerLMConfig

    paddle.seed(7)
    cfg = TransformerLMConfig(vocab_size=97, hidden_size=32, num_layers=2,
                              num_heads=4, max_seq_len=64, dropout=0.0)
    model = GPTForCausalLM(cfg)
    model.eval()
    # force interpret so the kernel_viable guard admits the kernel on
    # this CPU run and the decode program embeds the real pallas_call
    paged_attn._FORCE_INTERPRET[0] = True
    try:
        engine = ServingEngine(model, num_slots=4, paged=True,
                               block_size=8, paged_attn=True)
        rs = np.random.RandomState(0)
        for n in (5, 9):
            engine.add_request(rs.randint(0, 97, (n,)).astype(np.int64),
                               max_new_tokens=4)
        engine.run()
        engine.declare_warmup()
        assert engine.decode_layout == "paged_pallas", \
            "pallas lint target fell back to the XLA gather path"
        return engine.lint()
    finally:
        paged_attn._FORCE_INTERPRET[0] = False


def lint_chunked_prefill():
    import paddle_tpu as paddle
    from paddle_tpu.serving import ServingEngine
    from paddle_tpu.text.models import GPTForCausalLM, TransformerLMConfig

    paddle.seed(7)
    cfg = TransformerLMConfig(vocab_size=97, hidden_size=32, num_layers=2,
                              num_heads=4, max_seq_len=64, dropout=0.0)
    model = GPTForCausalLM(cfg)
    model.eval()
    engine = ServingEngine(model, num_slots=4, prefill_chunk=8,
                           sampling=True)
    rs = np.random.RandomState(0)
    for n in (5, 23, 40):       # two chunked, one grouped
        engine.add_request(rs.randint(0, 97, (n,)).astype(np.int64),
                           max_new_tokens=4)
    engine.add_request(rs.randint(0, 97, (30,)).astype(np.int64),
                       max_new_tokens=4, temperature=0.8, top_k=10)
    engine.run()
    engine.declare_warmup()
    sched = engine.metrics.snapshot()["scheduler"]
    assert sched["prefill_chunks"] >= 4, \
        "chunked-prefill lint target never actually chunked"
    # the chunk program (traced start/len/slot/final + sampling args)
    # AND the sampling decode must both stay f64/donation clean
    return engine.lint(program="chunk") + engine.lint()


def lint_spec_verify():
    import paddle_tpu as paddle
    from paddle_tpu.serving import ServingEngine
    from paddle_tpu.text.models import GPTForCausalLM, TransformerLMConfig

    paddle.seed(7)
    cfg = TransformerLMConfig(vocab_size=97, hidden_size=32, num_layers=2,
                              num_heads=4, max_seq_len=64, dropout=0.0)
    model = GPTForCausalLM(cfg)
    model.eval()
    findings = []
    for paged in (False, True):
        engine = ServingEngine(model, num_slots=4, paged=paged,
                               block_size=8, speculative=True, spec_k=4)
        rs = np.random.RandomState(0)
        for n in (5, 9, 17):
            # greedy tiny-model decoding locks into cycles within a
            # few tokens — 16 new tokens reliably gives the n-gram
            # drafter self-matches, so verify steps actually dispatch
            engine.add_request(rs.randint(0, 97, (n,)).astype(np.int64),
                               max_new_tokens=16)
        engine.run()
        engine.declare_warmup()
        spec = engine.metrics.snapshot()["perf"]["spec"]
        assert spec["verify_steps"] >= 1, \
            "spec lint target never dispatched a verify step"
        # the verify flavor AND the plain-decode fallback it shares the
        # steady state with must both stay f64/donation clean
        findings += engine.lint(program="spec_verify") + engine.lint()
    return findings


def lint_kv_wire():
    import jax

    import paddle_tpu as paddle
    from paddle_tpu.analysis import Finding
    from paddle_tpu.serving import ServingEngine
    from paddle_tpu.text.models import GPTForCausalLM, TransformerLMConfig

    def build(role):
        paddle.seed(7)
        cfg = TransformerLMConfig(vocab_size=97, hidden_size=32,
                                  num_layers=2, num_heads=4,
                                  max_seq_len=64, dropout=0.0)
        model = GPTForCausalLM(cfg)
        model.eval()
        return ServingEngine(model, num_slots=4, bucket_min=8,
                             paged=True, block_size=8, role=role)

    pe, de = build("prefill"), build("decode")
    rs = np.random.RandomState(0)

    def handoff(n):
        req = pe.add_request(rs.randint(0, 97, (n,)).astype(np.int64),
                             max_new_tokens=1, hold_kv=True)
        pe.run()
        payload = pe.export_kv(req.rid)
        dreq = de.import_kv(payload, max_new_tokens=4)
        de.run()
        assert dreq.state == "done" and len(dreq.generated) == 4, \
            "kv_wire lint target never completed an imported decode"
        return payload

    handoff(13)                 # warm both tiers' handoff programs
    pe.warmup_kv_handoff()
    de.warmup_kv_handoff()
    pe.declare_warmup()
    de.declare_warmup()
    findings = []
    c0 = (pe.metrics.compiles, de.metrics.compiles)
    handoff(14)                 # different length, same prefill bucket
    c1 = (pe.metrics.compiles, de.metrics.compiles)
    if c1 != c0:
        findings.append(Finding(
            "kv_wire_steady_state", "error",
            "ServingEngine.export_kv/import_kv",
            f"a steady-state handoff compiled (prefill {c0[0]}->{c1[0]}, "
            f"decode {c0[1]}->{c1[1]}) — the KV wire path must be "
            f"dispatch-only after warmup_kv_handoff"))
    # the export program's device->host transfer must be ONE slot's
    # blocks, never the pool: abstract-eval the export and compare its
    # output bytes against the pool it reads from
    pool = pe.pool
    idx = np.zeros((pool.blocks_per_slot,), np.int32)
    out = jax.eval_shape(pe._kv_export_fn, pool.kc, pool.vc, idx)
    out_bytes = sum(int(np.prod(o.shape)) * o.dtype.itemsize
                    for o in jax.tree_util.tree_leaves(out))
    pool_bytes = pool.kc.nbytes + pool.vc.nbytes
    if out_bytes * 2 > pool_bytes:
        findings.append(Finding(
            "kv_wire_transfer", "error",
            "ServingEngine._kv_export_fn",
            f"export fetches {out_bytes} bytes against a "
            f"{pool_bytes}-byte pool — a per-slot slice should be a "
            f"small fraction; this is a device_get of the pool"))
    # the import program is a jitted entry point like any other: the
    # f64-upcast / host-callback / donation passes must stay clean
    findings += de.lint(program="kv_import")
    pe.close()
    de.close()
    return findings


def lint_hapi_train_step():
    import paddle_tpu as paddle
    import paddle_tpu.nn as nn

    paddle.seed(7)
    net = nn.Sequential(nn.Linear(16, 32), nn.ReLU(), nn.Linear(32, 10))
    model = paddle.Model(net)
    model.prepare(
        paddle.optimizer.Adam(1e-3, parameters=net.parameters()),
        nn.CrossEntropyLoss())
    paddle.enable_static()
    try:
        rs = np.random.RandomState(7)
        for _ in range(3):  # eager -> record -> compiled
            x = rs.randn(8, 16).astype("float32")
            y = rs.randint(0, 10, (8, 1)).astype("int64")
            model.train_batch([x], [y])
        step = model._static_steps["train"]
        assert any(e["compiled"] is not None
                   for e in step.entries.values()), \
            "hapi train step never reached the compiled phase"
        return step.lint()
    finally:
        paddle.disable_static()


def lint_to_static_sample():
    import paddle_tpu as paddle

    @paddle.jit.to_static
    def sample(x, n):
        s = x * 0.0
        for _ in range(n):  # tensor bound -> ONE lax.while_loop program
            if s.sum() < 100.0:  # tensor pred -> lax.cond
                s = s + x
        return s

    xp = paddle.to_tensor(np.full((8,), 0.5, np.float32))
    for _ in range(3):  # eager -> record -> compiled
        sample(xp, paddle.to_tensor(np.int64(6)))
    assert any(e["compiled"] is not None
               for e in sample.entries.values()), \
        "to_static sample never reached the compiled phase"
    return sample.lint()


def lint_concurrency():
    """Static concurrency audit over the serving stack: cross-role
    unlocked writes (thread-role auditor) + live-buffer-to-dispatch
    (snapshot discipline, the PR-6 bug class). Pure AST — no engine
    builds, no jax dispatches."""
    from paddle_tpu.analysis import concurrency as cc

    return cc.audit_default()


TARGETS = {
    "serving_decode": lint_serving_decode,
    "paged_decode": lint_paged_decode,
    "paged_decode_pallas": lint_paged_decode_pallas,
    "chunked_prefill": lint_chunked_prefill,
    "spec_verify": lint_spec_verify,
    "kv_wire": lint_kv_wire,
    "hapi_train_step": lint_hapi_train_step,
    "to_static_sample": lint_to_static_sample,
    "concurrency": lint_concurrency,
}


def main(argv=None):
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument("--pretty", action="store_true",
                        help="indent the JSON report")
    parser.add_argument("--targets", nargs="*", choices=sorted(TARGETS),
                        default=sorted(TARGETS),
                        help="subset of entry points to lint")
    args = parser.parse_args(argv)

    from paddle_tpu.analysis import SEVERITIES, lint_passes

    findings = []
    for name in args.targets:
        for f in TARGETS[name]():
            d = f.to_dict()
            d["target"] = name
            findings.append(d)
    counts = {sev: sum(1 for f in findings if f["severity"] == sev)
              for sev in SEVERITIES}
    report = {
        "targets": list(args.targets),
        "passes": lint_passes(),
        "findings": findings,
        "counts": counts,
        "ok": counts.get("error", 0) == 0,
    }
    print(json.dumps(report, indent=2 if args.pretty else None))
    return 0 if report["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
