"""paddle.metric equivalent (reference: python/paddle/metric/metrics.py:
Metric base, Accuracy, Precision, Recall, Auc)."""
import numpy as np

from ..core.tensor import Tensor


class Metric:
    def reset(self):
        raise NotImplementedError

    def update(self, *args):
        raise NotImplementedError

    def accumulate(self):
        raise NotImplementedError

    def name(self):
        raise NotImplementedError

    def compute(self, *args):
        return args


class Accuracy(Metric):
    def __init__(self, topk=(1,), name=None):
        self.topk = topk if isinstance(topk, (list, tuple)) else (topk,)
        self.maxk = max(self.topk)
        self._name = name or "acc"
        self.reset()

    def compute(self, pred, label, *args):
        pv = pred.numpy() if isinstance(pred, Tensor) else np.asarray(pred)
        lv = label.numpy() if isinstance(label, Tensor) else np.asarray(label)
        if lv.ndim == pv.ndim and lv.shape[-1] == 1:
            lv = lv.squeeze(-1)
        idx = np.argsort(-pv, axis=-1)[..., :self.maxk]
        correct = (idx == lv[..., None])
        return Tensor(correct.astype(np.float32))

    def update(self, correct, *args):
        cv = correct.numpy() if isinstance(correct, Tensor) else np.asarray(correct)
        num = cv.shape[0] if cv.ndim > 0 else 1
        res = []
        for k in self.topk:
            c = cv[..., :k].sum()
            self.total[self.topk.index(k)] += c
            self.count[self.topk.index(k)] += num
            res.append(float(c) / num if num else 0.0)
        return res[0] if len(res) == 1 else res

    def reset(self):
        self.total = [0.0] * len(self.topk)
        self.count = [0] * len(self.topk)

    def accumulate(self):
        res = [t / c if c else 0.0 for t, c in zip(self.total, self.count)]
        return res[0] if len(res) == 1 else res

    def name(self):
        if len(self.topk) == 1:
            return self._name
        return [f"{self._name}_top{k}" for k in self.topk]


class Precision(Metric):
    def __init__(self, name=None):
        self._name = name or "precision"
        self.reset()

    def update(self, preds, labels):
        pv = preds.numpy() if isinstance(preds, Tensor) else np.asarray(preds)
        lv = labels.numpy() if isinstance(labels, Tensor) else np.asarray(labels)
        pred_pos = (pv > 0.5).reshape(-1)
        lab = lv.reshape(-1).astype(bool)
        self.tp += int((pred_pos & lab).sum())
        self.fp += int((pred_pos & ~lab).sum())

    def reset(self):
        self.tp = 0
        self.fp = 0

    def accumulate(self):
        den = self.tp + self.fp
        return float(self.tp) / den if den else 0.0

    def name(self):
        return self._name


class Recall(Metric):
    def __init__(self, name=None):
        self._name = name or "recall"
        self.reset()

    def update(self, preds, labels):
        pv = preds.numpy() if isinstance(preds, Tensor) else np.asarray(preds)
        lv = labels.numpy() if isinstance(labels, Tensor) else np.asarray(labels)
        pred_pos = (pv > 0.5).reshape(-1)
        lab = lv.reshape(-1).astype(bool)
        self.tp += int((pred_pos & lab).sum())
        self.fn += int((~pred_pos & lab).sum())

    def reset(self):
        self.tp = 0
        self.fn = 0

    def accumulate(self):
        den = self.tp + self.fn
        return float(self.tp) / den if den else 0.0

    def name(self):
        return self._name


class Auc(Metric):
    def __init__(self, curve="ROC", num_thresholds=4095, name=None):
        self._name = name or "auc"
        self.num_thresholds = num_thresholds
        self.reset()

    def update(self, preds, labels):
        pv = preds.numpy() if isinstance(preds, Tensor) else np.asarray(preds)
        lv = labels.numpy() if isinstance(labels, Tensor) else np.asarray(labels)
        if pv.ndim == 2 and pv.shape[1] == 2:
            pv = pv[:, 1]
        pv = pv.reshape(-1)
        lv = lv.reshape(-1)
        bins = np.minimum((pv * self.num_thresholds).astype(np.int64),
                          self.num_thresholds)
        for b, l in zip(bins, lv):
            if l:
                self._stat_pos[b] += 1
            else:
                self._stat_neg[b] += 1

    def reset(self):
        self._stat_pos = np.zeros(self.num_thresholds + 1, np.int64)
        self._stat_neg = np.zeros(self.num_thresholds + 1, np.int64)

    def accumulate(self):
        tot_pos = float(self._stat_pos.sum())
        tot_neg = float(self._stat_neg.sum())
        if tot_pos == 0 or tot_neg == 0:
            return 0.0
        # trapezoid over thresholds from high to low
        tp = np.cumsum(self._stat_pos[::-1])
        fp = np.cumsum(self._stat_neg[::-1])
        tpr = tp / tot_pos
        fpr = fp / tot_neg
        return float(np.trapezoid(tpr, fpr))

    def name(self):
        return self._name


def accuracy(input, label, k=1, correct=None, total=None, name=None):  # noqa: A002
    """Functional accuracy (reference: python/paddle/metric/metrics.py:accuracy)."""
    from .. import ops
    topk_vals, topk_idx = ops.search.topk(input, k)
    lv = label
    if lv.ndim == 1:
        lv = ops.manipulation.unsqueeze(lv, axis=-1)
    correct_mat = ops.logic.equal(topk_idx, ops.math.cast(lv, topk_idx.value.dtype))
    acc = ops.reduction.mean(
        ops.reduction.max(ops.math.cast(correct_mat, "float32"), axis=-1))
    return acc
