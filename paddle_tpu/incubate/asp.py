"""ASP — automatic structured (n:m, default 2:4) sparsity.

Reference parity: python/paddle/fluid/contrib/sparsity/{asp.py, utils.py}
(ASPHelper.decorate/prune_model, get_mask_1d/get_mask_2d_greedy,
check_mask_1d, calculate_density). The reference rewrites the static
program to multiply masks after each optimizer op; here `decorate` wraps
the dygraph optimizer and re-applies the masks after every step — on TPU
the mask multiply fuses into the update kernel under jit.
"""
import numpy as np
import jax.numpy as jnp

from ..core.dispatch import no_grad
from ..optimizer.optimizer import WrappedOptimizer


def calculate_density(x):
    """Fraction of nonzeros (reference: utils.py:86)."""
    x = np.asarray(x)
    return float(np.count_nonzero(x)) / max(1, x.size)


def _reshape_1d(mat, m):
    """Pad cols to a multiple of m and view as rows of m (utils.py:108)."""
    mat = np.asarray(mat)
    if mat.shape[1] % m != 0:
        pad = m - mat.shape[1] % m
        mat = np.concatenate(
            [mat, np.zeros((mat.shape[0], pad), mat.dtype)], axis=1)
    return mat.reshape(-1, m), mat.shape


def get_mask_1d(mat, n, m):
    """Keep the n largest |values| in every group of m consecutive
    elements along rows (reference: utils.py:180)."""
    mat = np.asarray(mat)
    orig_shape = mat.shape
    mat2d = mat.reshape(orig_shape[0], -1) if mat.ndim > 1 else \
        mat.reshape(1, -1)
    groups, padded_shape = _reshape_1d(mat2d, m)
    idx = np.argsort(np.abs(groups), axis=1)[:, : m - n]
    mask = np.ones_like(groups)
    np.put_along_axis(mask, idx, 0.0, axis=1)
    mask = mask.reshape(padded_shape)[:, : mat2d.shape[1]]
    return mask.reshape(orig_shape)


def check_mask_1d(mat, n, m):
    """True iff every m-group has at most n nonzeros (utils.py:136)."""
    mat2d = np.asarray(mat)
    mat2d = mat2d.reshape(mat2d.shape[0], -1) if mat2d.ndim > 1 else \
        mat2d.reshape(1, -1)
    groups, _ = _reshape_1d(mat2d, m)
    return bool(np.all(np.count_nonzero(groups, axis=1) <= n))


def get_mask_2d_greedy(mat, n, m):
    """Greedy m x m block mask keeping n per row and column
    (reference: utils.py:313)."""
    mat = np.asarray(mat)
    h, w = mat.shape
    ph, pw = (-h) % m, (-w) % m
    padded = np.pad(np.abs(mat), ((0, ph), (0, pw)))
    mask = np.zeros_like(padded)
    for bi in range(0, padded.shape[0], m):
        for bj in range(0, padded.shape[1], m):
            block = padded[bi:bi + m, bj:bj + m]
            bmask = np.zeros((m, m))
            order = np.argsort(-block.ravel())
            rows = np.zeros(m, np.int64)
            cols = np.zeros(m, np.int64)
            for f in order:
                r, c = divmod(int(f), m)
                if rows[r] < n and cols[c] < n:
                    bmask[r, c] = 1.0
                    rows[r] += 1
                    cols[c] += 1
            mask[bi:bi + m, bj:bj + m] = bmask
    return mask[:h, :w]


_MASK_ALGOS = {"mask_1d": get_mask_1d, "mask_2d_greedy": get_mask_2d_greedy}

# per-model mask registry: param name -> numpy mask
_asp_state = {"masks": {}, "excluded": set()}


def set_excluded_layers(param_names, main_program=None):
    _asp_state["excluded"].update(param_names)


def reset_excluded_layers(main_program=None):
    _asp_state["excluded"].clear()


def _supported(param):
    shape = tuple(param.aval_shape())
    if len(shape) < 2:
        return False
    if param.name in _asp_state["excluded"]:
        return False
    # reference ASPHelper supports fc/conv weights with inner dims % 4 == 0
    flat_cols = int(np.prod(shape[1:]))
    return shape[0] % 4 == 0 or flat_cols % 4 == 0


@no_grad()
def prune_model(model, n=2, m=4, mask_algo="mask_1d", with_mask=True):
    """Prune supported weights to n:m sparsity and register the masks
    (reference: asp.py prune_model:95)."""
    algo = _MASK_ALGOS[mask_algo]
    masks = {}
    for name, p in model.named_parameters():
        if not p.trainable or not _supported(p):
            continue
        w = np.asarray(p.numpy(), np.float32)
        mat = w.reshape(w.shape[0], -1)
        mask = algo(mat, n, m).reshape(w.shape).astype(w.dtype)
        p.value = jnp.asarray(w * mask)
        if with_mask:
            masks[name] = mask
            # keyed by the parameter's unique framework name (reference
            # ASPHelper keys masks by param name too); no id() reuse hazard
            _asp_state["masks"][p.name] = jnp.asarray(mask)
    return masks


class OptimizerWithSparsityGuarantee(WrappedOptimizer):
    """Reference: asp.py decorate:55 — after every optimizer step,
    multiply masked params by their masks so pruned weights stay zero."""

    @no_grad()
    def step(self):
        self._inner_opt.step()
        for p in self._inner_opt._parameter_list():
            mask = _asp_state["masks"].get(p.name)
            if mask is not None and tuple(mask.shape) == tuple(p.aval_shape()):
                p.value = p.value * mask.astype(p.value.dtype)


def decorate(optimizer):
    return OptimizerWithSparsityGuarantee(optimizer)
