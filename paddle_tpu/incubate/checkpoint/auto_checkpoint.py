"""Auto checkpoint for train-loop resumability.

Reference parity: python/paddle/fluid/incubate/checkpoint/
auto_checkpoint.py:265 TrainEpochRange — an epoch-range context that
snapshots training state keyed by job id so a relaunched job resumes at
the last completed epoch (:598 save logic; reference stores to HDFS, we
store to a local/shared directory).
"""
import json
import os
import time

from ...framework.io_utils import save as psave, load as pload

_job_id = os.environ.get("PADDLE_JOB_ID", "default_job")
_root = os.environ.get("PADDLE_CHECKPOINT_DIR", "/tmp/paddle_tpu_auto_ckpt")


def set_checkpoint_dir(path):
    global _root
    _root = path


class TrainEpochRange:
    """for epoch in TrainEpochRange(n, name).get(): train(...)

    Register model/optimizer with .add(); each completed epoch snapshots
    their state; on restart, iteration resumes after the last completed
    epoch with states restored."""

    def __init__(self, max_epoch_num, name, checkpoint_inter=None,
                 save_checkpoint=True):
        self.max_epoch_num = max_epoch_num
        self.name = name
        self.save_checkpoint = save_checkpoint
        self._dir = os.path.join(_root, _job_id, name)
        os.makedirs(self._dir, exist_ok=True)
        self._saveables = {}
        self._meta_path = os.path.join(self._dir, "meta.json")
        self._start_epoch = 0
        if os.path.exists(self._meta_path):
            try:
                with open(self._meta_path) as f:
                    meta = json.load(f)
                self._start_epoch = meta.get("last_completed", -1) + 1
            except (OSError, ValueError):
                self._start_epoch = 0

    def add(self, name, obj):
        """Register anything with state_dict()/set_state_dict()."""
        self._saveables[name] = obj
        state_path = os.path.join(self._dir, f"{name}.pdparams")
        if self._start_epoch > 0 and os.path.exists(state_path):
            obj.set_state_dict(pload(state_path))
        return self

    @property
    def restored_from(self):
        return self._start_epoch

    def get(self):
        for epoch in range(self._start_epoch, self.max_epoch_num):
            yield epoch
            if self.save_checkpoint:
                self._snapshot(epoch)

    def _snapshot(self, epoch):
        for name, obj in self._saveables.items():
            psave(obj.state_dict(),
                  os.path.join(self._dir, f"{name}.pdparams"))
        with open(self._meta_path, "w") as f:
            json.dump({"last_completed": epoch, "ts": time.time()}, f)

    def clean(self):
        import shutil
        shutil.rmtree(self._dir, ignore_errors=True)
