"""Sharded (multichip) checkpointing over orbax (TPU-native analogue of
the reference's sharded-aware fleet save: fleet_base.py
save_persistables + dist_sharding_save.py test semantics — each rank
persists its own shard; restore re-places shards onto the mesh).

On TPU the idiomatic mechanism is orbax's OCDBT checkpointer: every
host writes only the array shards it owns (no gather to host 0 —
gathering a ZeRO/TP-sharded model would OOM a single host by design),
and restore places each shard straight onto its mesh position from the
restore-time shardings. Async save overlaps serialization with the
next training steps.
"""
import jax

__all__ = ["save_sharded", "load_sharded", "save_sharded_train_state",
           "load_sharded_train_state", "AsyncShardedSaver"]


def _checkpointer():
    import orbax.checkpoint as ocp
    return ocp.StandardCheckpointer()


def _to_arrays(state_dict):
    """paddle state_dict (name -> Tensor) -> name -> jax array."""
    import numpy as np

    from ...core.lazy import concrete
    out = {}
    for k, v in state_dict.items():
        val = concrete(getattr(v, "value", v))  # flush LazyArrays
        if isinstance(val, (int, float, np.ndarray)):
            val = jax.numpy.asarray(val)
        out[k] = val
    return out


def save_sharded(state_dict, path):
    """Persist a (possibly mesh-sharded) state dict; each process
    writes only its own shards. Overwrites an existing checkpoint at
    `path` (save-latest-every-epoch loops, matching paddle.save)."""
    import os
    ckptr = _checkpointer()
    ckptr.save(os.path.abspath(str(path)), _to_arrays(state_dict),
               force=True)
    ckptr.wait_until_finished()


def load_sharded(path, target=None, shardings=None):
    """Restore a state dict saved by save_sharded.

    target: optional state dict (name -> Tensor) restored INTO (values
    are replaced in place, preserving the model's Tensor objects).
    shardings: optional name -> jax.sharding.Sharding placing each
    restored array onto the mesh (defaults to the saved layout).
    Returns the name -> array dict.
    """
    import os

    import numpy as np

    import orbax.checkpoint as ocp
    ckptr = _checkpointer()
    apath = os.path.abspath(str(path))
    if target is not None or shardings is not None:
        ref = {}
        src = target if target is not None else {}
        md = ckptr.metadata(apath)
        # newer orbax wraps the tree in CheckpointMetadata.item_metadata;
        # older releases return the metadata tree directly
        tree = md.item_metadata.tree if hasattr(md, "item_metadata") \
            else md
        if target is not None:
            # validate BEFORE the restore reads anything from disk: a
            # mismatch on a multi-GB checkpoint must not cost the full
            # restore I/O (or die inside orbax with an opaque
            # incompatible-sharding error) before the friendly
            # per-parameter message fires
            missing = [k for k in target if k not in tree]
            if missing:
                raise KeyError(
                    f"checkpoint at {path} has no entries for target "
                    f"keys {sorted(missing)} — a silently half-restored "
                    f"model would compute with its random init for "
                    f"those parameters (reference set_state_dict "
                    f"surfaces missing keys the same way)")
            for k, t in target.items():
                m = tree[k]
                cur = getattr(t, "value", t)
                cur_shape = tuple(getattr(cur, "shape", ()) or ())
                if tuple(m.shape) != cur_shape:
                    raise ValueError(
                        f"checkpoint parameter {k!r} has shape "
                        f"{tuple(m.shape)} but the target expects "
                        f"{cur_shape} — restoring it would defer the "
                        f"failure to a confusing downstream shape "
                        f"error")
                if (hasattr(cur, "dtype") and
                        np.dtype(m.dtype) != np.dtype(cur.dtype)):
                    raise ValueError(
                        f"checkpoint parameter {k!r} has dtype "
                        f"{m.dtype} but the target expects {cur.dtype}")
        for k, m in tree.items():
            sh = (shardings or {}).get(k)
            if sh is None and target is not None and k in src:
                v = getattr(src[k], "value", src[k])
                sh = getattr(v, "sharding", None)
            ref[k] = jax.ShapeDtypeStruct(tuple(m.shape), m.dtype,
                                          sharding=sh)
        restored = ckptr.restore(apath, ref)
    else:
        restored = ckptr.restore(apath)
    if target is not None:
        for k, t in target.items():
            if hasattr(t, "value"):
                t.value = restored[k]
    return dict(restored)


def save_sharded_train_state(model_state, optimizer, path):
    """Persist the FULL training state — model parameters AND optimizer
    accumulators (Adam moments, beta powers, ...) AND LR-scheduler
    metadata — as one sharded checkpoint (the reference's
    save_persistables semantics: fleet_base.py:732 persists optimizer
    accumulator Variables alongside parameters; dist_sharding_save.py
    asserts they round-trip).

    Array state goes through orbax (each process writes only its own
    shards — ZeRO-sharded moments stay sharded on disk); the
    non-array LR/scheduler metadata goes to a process-0 JSON sidecar
    `<path>_meta.json` (atomic rename, so a kill mid-write leaves no
    torn sidecar).
    """
    import json
    import os
    opt_sd = dict(optimizer.state_dict())
    meta = opt_sd.pop("LR_Scheduler", {})
    tree = {"model": _to_arrays(model_state), "opt": _to_arrays(opt_sd)}
    ckptr = _checkpointer()
    apath = os.path.abspath(str(path))
    ckptr.save(apath, tree, force=True)
    ckptr.wait_until_finished()
    if jax.process_index() == 0:
        tmp = apath + "_meta.json.tmp"
        with open(tmp, "w") as f:
            json.dump({"LR_Scheduler": meta}, f)
        os.replace(tmp, apath + "_meta.json")


def load_sharded_train_state(path, model_target, optimizer,
                             sharding=None):
    """Restore a checkpoint written by save_sharded_train_state:
    parameters into `model_target` (a name -> Tensor state dict, values
    replaced in place) and accumulators + LR metadata into `optimizer`
    via set_state_dict — so a resumed Adam continues with its moments
    instead of silently restarting them (the reference's resume:
    fleet_base.py:732 + dist_sharding_save.py round-trip).

    sharding: optional single jax.sharding.Sharding applied to EVERY
    restored array — the reshard-onto-a-different-mesh (elastic) case.
    None keeps each model param on its target's current placement and
    the optimizer arrays on their saved layout.
    """
    import json
    import os

    import numpy as np
    ckptr = _checkpointer()
    apath = os.path.abspath(str(path))
    md = ckptr.metadata(apath)
    tree = md.item_metadata.tree if hasattr(md, "item_metadata") else md
    if model_target is not None:
        # validate BEFORE the restore reads anything from disk (same
        # contract as load_sharded): a mismatch on a multi-GB
        # checkpoint must not cost the full restore I/O or surface as
        # a confusing downstream shape error
        missing = [k for k in model_target if k not in tree["model"]]
        if missing:
            raise KeyError(
                f"train-state checkpoint at {path} has no model entries "
                f"for {sorted(missing)}")
        for k, t in model_target.items():
            m = tree["model"][k]
            cur = getattr(t, "value", t)
            cur_shape = tuple(getattr(cur, "shape", ()) or ())
            if tuple(m.shape) != cur_shape:
                raise ValueError(
                    f"checkpoint parameter {k!r} has shape "
                    f"{tuple(m.shape)} but the target expects "
                    f"{cur_shape}")
            if (hasattr(cur, "dtype")
                    and np.dtype(m.dtype) != np.dtype(cur.dtype)):
                raise ValueError(
                    f"checkpoint parameter {k!r} has dtype {m.dtype} "
                    f"but the target expects {cur.dtype}")
    mpath = apath + "_meta.json"
    if optimizer is not None and not os.path.exists(mpath):
        # the orbax tree becomes durable before process 0 writes the
        # sidecar; a kill in that window leaves a complete-looking
        # checkpoint whose LR/param-order metadata is gone. Restoring
        # it silently would resume at the wrong LR (and positional
        # accumulator matching could not engage) — exactly the
        # moment-less resume this API exists to prevent.
        raise FileNotFoundError(
            f"train-state checkpoint at {path} has no {mpath} sidecar "
            f"(killed between the array save and the metadata write?) "
            f"— treat this checkpoint as incomplete and resume from "
            f"the previous one")
    ref = {}
    for sect, entries in tree.items():
        ref[sect] = {}
        for k, m in entries.items():
            sh = sharding
            if (sh is None and sect == "model"
                    and model_target is not None and k in model_target):
                v = getattr(model_target[k], "value", model_target[k])
                sh = getattr(v, "sharding", None)
            ref[sect][k] = jax.ShapeDtypeStruct(tuple(m.shape), m.dtype,
                                                sharding=sh)
    restored = ckptr.restore(apath, ref)
    if model_target is not None:
        for k, t in model_target.items():
            if hasattr(t, "value"):
                t.value = restored["model"][k]
    if optimizer is not None:
        with open(mpath) as f:
            meta = json.load(f)
        opt_sd = dict(restored["opt"])
        opt_sd["LR_Scheduler"] = meta.get(
            "LR_Scheduler", {"last_lr": optimizer.get_lr()})
        optimizer.set_state_dict(opt_sd)
    return restored


class AsyncShardedSaver:
    """Async variant: save() returns immediately (serialization runs in
    the background, overlapping the next train steps — the reference's
    trainer threads persist PS tables asynchronously the same way);
    wait() (or the next save) joins it."""

    def __init__(self):
        import orbax.checkpoint as ocp
        self._ckptr = ocp.AsyncCheckpointer(
            ocp.StandardCheckpointHandler())

    def save(self, state_dict, path):
        import os
        self._ckptr.save(os.path.abspath(str(path)),
                         args=_std_save_args(_to_arrays(state_dict)),
                         force=True)

    def wait(self):
        self._ckptr.wait_until_finished()

    def close(self):
        self._ckptr.close()


def _std_save_args(tree):
    import orbax.checkpoint as ocp
    return ocp.args.StandardSave(tree)
