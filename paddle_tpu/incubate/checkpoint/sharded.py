"""Sharded (multichip) checkpointing over orbax (TPU-native analogue of
the reference's sharded-aware fleet save: fleet_base.py
save_persistables + dist_sharding_save.py test semantics — each rank
persists its own shard; restore re-places shards onto the mesh).

On TPU the idiomatic mechanism is orbax's OCDBT checkpointer: every
host writes only the array shards it owns (no gather to host 0 —
gathering a ZeRO/TP-sharded model would OOM a single host by design),
and restore places each shard straight onto its mesh position from the
restore-time shardings. Async save overlaps serialization with the
next training steps.
"""
import jax

__all__ = ["save_sharded", "load_sharded", "AsyncShardedSaver"]


def _checkpointer():
    import orbax.checkpoint as ocp
    return ocp.StandardCheckpointer()


def _to_arrays(state_dict):
    """paddle state_dict (name -> Tensor) -> name -> jax array."""
    import numpy as np

    from ...core.lazy import concrete
    out = {}
    for k, v in state_dict.items():
        val = concrete(getattr(v, "value", v))  # flush LazyArrays
        if isinstance(val, (int, float, np.ndarray)):
            val = jax.numpy.asarray(val)
        out[k] = val
    return out


def save_sharded(state_dict, path):
    """Persist a (possibly mesh-sharded) state dict; each process
    writes only its own shards. Overwrites an existing checkpoint at
    `path` (save-latest-every-epoch loops, matching paddle.save)."""
    import os
    ckptr = _checkpointer()
    ckptr.save(os.path.abspath(str(path)), _to_arrays(state_dict),
               force=True)
    ckptr.wait_until_finished()


def load_sharded(path, target=None, shardings=None):
    """Restore a state dict saved by save_sharded.

    target: optional state dict (name -> Tensor) restored INTO (values
    are replaced in place, preserving the model's Tensor objects).
    shardings: optional name -> jax.sharding.Sharding placing each
    restored array onto the mesh (defaults to the saved layout).
    Returns the name -> array dict.
    """
    import os

    import numpy as np

    import orbax.checkpoint as ocp
    ckptr = _checkpointer()
    apath = os.path.abspath(str(path))
    if target is not None or shardings is not None:
        ref = {}
        src = target if target is not None else {}
        tree = ckptr.metadata(apath).item_metadata.tree
        if target is not None:
            # validate BEFORE the restore reads anything from disk: a
            # mismatch on a multi-GB checkpoint must not cost the full
            # restore I/O (or die inside orbax with an opaque
            # incompatible-sharding error) before the friendly
            # per-parameter message fires
            missing = [k for k in target if k not in tree]
            if missing:
                raise KeyError(
                    f"checkpoint at {path} has no entries for target "
                    f"keys {sorted(missing)} — a silently half-restored "
                    f"model would compute with its random init for "
                    f"those parameters (reference set_state_dict "
                    f"surfaces missing keys the same way)")
            for k, t in target.items():
                m = tree[k]
                cur = getattr(t, "value", t)
                cur_shape = tuple(getattr(cur, "shape", ()) or ())
                if tuple(m.shape) != cur_shape:
                    raise ValueError(
                        f"checkpoint parameter {k!r} has shape "
                        f"{tuple(m.shape)} but the target expects "
                        f"{cur_shape} — restoring it would defer the "
                        f"failure to a confusing downstream shape "
                        f"error")
                if (hasattr(cur, "dtype") and
                        np.dtype(m.dtype) != np.dtype(cur.dtype)):
                    raise ValueError(
                        f"checkpoint parameter {k!r} has dtype "
                        f"{m.dtype} but the target expects {cur.dtype}")
        for k, m in tree.items():
            sh = (shardings or {}).get(k)
            if sh is None and target is not None and k in src:
                v = getattr(src[k], "value", src[k])
                sh = getattr(v, "sharding", None)
            ref[k] = jax.ShapeDtypeStruct(tuple(m.shape), m.dtype,
                                          sharding=sh)
        restored = ckptr.restore(apath, ref)
    else:
        restored = ckptr.restore(apath)
    if target is not None:
        for k, t in target.items():
            if hasattr(t, "value"):
                t.value = restored[k]
    return dict(restored)


class AsyncShardedSaver:
    """Async variant: save() returns immediately (serialization runs in
    the background, overlapping the next train steps — the reference's
    trainer threads persist PS tables asynchronously the same way);
    wait() (or the next save) joins it."""

    def __init__(self):
        import orbax.checkpoint as ocp
        self._ckptr = ocp.AsyncCheckpointer(
            ocp.StandardCheckpointHandler())

    def save(self, state_dict, path):
        import os
        self._ckptr.save(os.path.abspath(str(path)),
                         args=_std_save_args(_to_arrays(state_dict)),
                         force=True)

    def wait(self):
        self._ckptr.wait_until_finished()

    def close(self):
        self._ckptr.close()


def _std_save_args(tree):
    import orbax.checkpoint as ocp
    return ocp.args.StandardSave(tree)
