from . import auto_checkpoint  # noqa: F401
from . import sharded  # noqa: F401
from .sharded import (AsyncShardedSaver, load_sharded,  # noqa: F401
                      save_sharded)
