"""paddle.incubate equivalents: MoE, ASP sparsity, auto-checkpoint."""
from . import asp  # noqa: F401
from . import moe  # noqa: F401
from . import checkpoint  # noqa: F401


class LookAhead:
    """Reference: incubate/optimizer/lookahead.py — k fast steps, then
    interpolate slow weights toward fast weights by alpha."""

    def __init__(self, inner_optimizer, alpha=0.5, k=5, name=None):
        self.inner_optimizer = inner_optimizer
        self.alpha = float(alpha)
        self.k = int(k)
        self._step = 0
        self._slow = None

    def _params(self):
        return self.inner_optimizer._parameter_list()

    def step(self):
        import jax.numpy as jnp
        self.inner_optimizer.step()
        if self._slow is None:
            self._slow = [jnp.array(p.value) for p in self._params()]
        self._step += 1
        if self._step % self.k == 0:
            for p, s in zip(self._params(), self._slow):
                new_slow = s + self.alpha * (p.value - s)
                p.value = new_slow
            self._slow = [jnp.array(p.value) for p in self._params()]

    def clear_grad(self):
        self.inner_optimizer.clear_grad()

    def minimize(self, loss, **kw):
        loss.backward()
        self.step()
        return None, None

    def __getattr__(self, item):
        return getattr(self.inner_optimizer, item)


class ModelAverage:
    """Reference: incubate/optimizer/modelaverage.py — maintains a
    running average of parameters; apply()/restore() swap it in and out
    for evaluation."""

    def __init__(self, average_window_rate=0.15, parameters=None,
                 min_average_window=10000, max_average_window=10000,
                 name=None):
        if parameters is None:
            raise ValueError("ModelAverage needs parameters")
        self._params = list(parameters)
        self._sum = None
        self._count = 0
        self._backup = None

    def step(self):
        import jax.numpy as jnp
        if self._sum is None:
            self._sum = [jnp.zeros_like(p.value) for p in self._params]
        self._sum = [s + p.value for s, p in zip(self._sum, self._params)]
        self._count += 1

    def apply(self, executor=None, need_restore=True):
        import jax.numpy as jnp
        if not self._count:
            return
        self._backup = [jnp.array(p.value) for p in self._params]
        for p, s in zip(self._params, self._sum):
            p.value = s / self._count
        if not need_restore:
            self._backup = None

    def restore(self, executor=None):
        if self._backup is None:
            return
        for p, b in zip(self._params, self._backup):
            p.value = b
        self._backup = None


def softmax_mask_fuse(x, mask, name=None):
    """Reference: incubate softmax_mask_fuse op — softmax(x + mask) in
    one fused kernel (XLA fuses the chain)."""
    from ..ops import nn_ops, math as m
    return nn_ops.softmax(m.add(x, m.cast(mask, x.dtype)), axis=-1)


def softmax_mask_fuse_upper_triangle(x):
    """Reference: softmax over causal (upper-triangle-masked) scores."""
    import jax.numpy as jnp
    from ..core.tensor import Tensor
    from ..core.dispatch import register_op
    return _softmax_causal(x)


from ..core.dispatch import register_op as _rop


@_rop("softmax_mask_fuse_upper_triangle")
def _softmax_causal(x):
    import jax
    import jax.numpy as jnp
    s = x.shape[-1]
    mask = jnp.tril(jnp.ones((s, s), bool))
    scores = jnp.where(mask, x, jnp.full_like(x, -1e9))
    return jax.nn.softmax(scores, axis=-1)
