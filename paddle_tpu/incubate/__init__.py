"""paddle.incubate equivalents: MoE, ASP sparsity, auto-checkpoint."""
from . import asp  # noqa: F401
from . import moe  # noqa: F401
from . import checkpoint  # noqa: F401
