"""Mixture-of-Experts with expert parallelism.

The reference has NO MoE framework support — only the raw alltoall
collective primitive exists (SURVEY §2.9 EP row:
operators/collective/alltoall_op.cc). This is the greenfield capability
built on it, TPU-native:

- MoELayer: top-k gating + expert FFNs. Experts are stacked on a leading
  axis sharded over a mesh axis ('mp' by default — expert parallelism);
  tokens route to experts with a capacity-bounded dense dispatch (static
  shapes for XLA: einsum with a one-hot dispatch mask, the standard TPU
  MoE formulation) and GSPMD turns the dispatch/combine einsums into the
  all_to_all traffic over ICI.
"""
import numpy as np
import jax
import jax.numpy as jnp

from ..core.dispatch import register_op
from ..nn.layer_base import Layer
from ..nn import initializer as init_mod
from ..distributed.fleet.meta_parallel.mp_layers import shard_constraint
from ..distributed import topology


@register_op("moe_forward")
def _moe_forward(x, gate_w, w1, b1, w2, b2, *, top_k, capacity_factor,
                 activation):
    """x: [tokens, d]; gate_w: [d, E]; w1: [E, d, hidden]; b1: [E, hidden];
    w2: [E, hidden, d]; b2: [E, d]."""
    tokens, d = x.shape
    e = gate_w.shape[1]
    capacity = int(max(1, capacity_factor * tokens * top_k / e))

    logits = x @ gate_w                                   # [T, E]
    probs = jax.nn.softmax(logits, axis=-1)
    gate_vals, gate_idx = jax.lax.top_k(probs, top_k)     # [T, K]
    # renormalize selected gates
    gate_vals = gate_vals / jnp.sum(gate_vals, axis=-1, keepdims=True)

    # capacity-bounded dispatch mask [T, E, C]
    dispatch = jnp.zeros((tokens, e, capacity), x.dtype)
    combine = jnp.zeros((tokens, e, capacity), x.dtype)
    # position of each token within its expert's buffer, per k choice
    for k in range(top_k):
        idx_k = gate_idx[:, k]                            # [T]
        onehot = jax.nn.one_hot(idx_k, e, dtype=jnp.int32)  # [T, E]
        pos = jnp.cumsum(onehot, axis=0) * onehot - 1     # [T, E] slot or -1
        pos_tok = jnp.sum(pos * onehot, axis=1)           # [T]
        keep = (pos_tok >= 0) & (pos_tok < capacity)
        pos_c = jnp.clip(pos_tok, 0, capacity - 1)
        sel = jax.nn.one_hot(pos_c, capacity, dtype=x.dtype) * \
            keep[:, None].astype(x.dtype)                 # [T, C]
        d_k = onehot.astype(x.dtype)[:, :, None] * sel[:, None, :]
        dispatch = dispatch + d_k
        combine = combine + d_k * gate_vals[:, k][:, None, None]

    # dispatch tokens to expert buffers: [E, C, d]
    expert_in = jnp.einsum("tec,td->ecd", dispatch, x)
    h = jnp.einsum("ecd,edh->ech", expert_in, w1) + b1[:, None, :]
    h = jax.nn.gelu(h) if activation == "gelu" else jax.nn.relu(h)
    expert_out = jnp.einsum("ech,ehd->ecd", h, w2) + b2[:, None, :]
    out = jnp.einsum("tec,ecd->td", combine, expert_out)

    # load-balancing aux loss (Switch-style): E * sum(frac_tokens * frac_prob)
    me = jnp.mean(probs, axis=0)
    ce = jnp.mean(jax.nn.one_hot(gate_idx[:, 0], e, dtype=x.dtype), axis=0)
    aux = e * jnp.sum(me * ce)
    return out, aux


class MoELayer(Layer):
    """Expert-parallel FFN block. Use inside a transformer in place of the
    MLP; add `layer.aux_loss` to the training loss."""

    def __init__(self, d_model, d_hidden, num_experts, top_k=2,
                 capacity_factor=1.25, activation="gelu", ep_axis="mp",
                 gate_attr=None, name=None):
        super().__init__()
        self.num_experts = num_experts
        self.top_k = top_k
        self.capacity_factor = capacity_factor
        self.activation = activation
        self.gate = self.create_parameter(
            (d_model, num_experts),
            attr=init_mod.ParamAttr._to_attr(gate_attr))
        self.w1 = self.create_parameter(
            (num_experts, d_model, d_hidden),
            default_initializer=init_mod.XavierNormal())
        self.b1 = self.create_parameter((num_experts, d_hidden), is_bias=True)
        self.w2 = self.create_parameter(
            (num_experts, d_hidden, d_model),
            default_initializer=init_mod.XavierNormal())
        self.b2 = self.create_parameter((num_experts, d_model), is_bias=True)
        # expert-parallel placement: experts sharded over the ep axis
        mesh = topology.get_mesh()
        if mesh is not None and int(mesh.shape.get(ep_axis, 1)) > 1 and \
                num_experts % int(mesh.shape[ep_axis]) == 0:
            for p in (self.w1, self.b1, self.w2, self.b2):
                p.tp_spec = (ep_axis,) + (None,) * (p.ndim - 1)
        self.aux_loss = None

    def forward(self, x):
        from ..ops import manipulation
        orig_shape = list(x.shape)
        d = orig_shape[-1]
        flat = manipulation.reshape(x, (-1, d))
        w1, b1, w2, b2 = self.w1, self.b1, self.w2, self.b2
        if self.w1.tp_spec is not None:
            w1 = shard_constraint(w1, self.w1.tp_spec)
            b1 = shard_constraint(b1, self.b1.tp_spec)
            w2 = shard_constraint(w2, self.w2.tp_spec)
            b2 = shard_constraint(b2, self.b2.tp_spec)
        out, aux = _moe_forward(flat, self.gate, w1, b1, w2, b2,
                                top_k=self.top_k,
                                capacity_factor=float(self.capacity_factor),
                                activation=self.activation)
        self.aux_loss = aux
        return manipulation.reshape(out, orig_shape)
