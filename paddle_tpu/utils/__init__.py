"""paddle.utils equivalent."""
from . import download  # noqa: F401


def try_import(name):
    import importlib
    try:
        return importlib.import_module(name)
    except ImportError as e:
        raise ImportError(f"optional dependency {name} is unavailable") from e


def run_check():
    """paddle.utils.run_check analogue: verify the runtime works."""
    import jax
    import jax.numpy as jnp
    from .. import to_tensor, matmul
    x = to_tensor([[1.0, 2.0], [3.0, 4.0]])
    y = matmul(x, x)
    assert y.shape == [2, 2]
    print(f"paddle_tpu runs on {jax.default_backend()} "
          f"({jax.device_count()} device(s)). All checks passed.")


def deprecated(since=None, update_to=None, reason=None):
    def deco(fn):
        return fn
    return deco
from . import unique_name  # noqa: F401


def require_version(min_version, max_version=None):
    """Reference: paddle.utils.require_version — version gate against
    this build's __version__."""
    from .. import __version__

    def parse(v):
        return tuple(int(x) for x in str(v).split(".")[:3] if x.isdigit())

    cur = parse(__version__)
    if parse(min_version) > cur:
        raise Exception(
            f"installed version {__version__} < required {min_version}")
    if max_version is not None and parse(max_version) < cur:
        raise Exception(
            f"installed version {__version__} > allowed {max_version}")
