"""paddle.utils.unique_name equivalent (reference:
python/paddle/fluid/unique_name.py: generate, guard, switch)."""
import contextlib

_generators = [{}]


def generate(key):
    """Return key_N with a per-generator increasing N."""
    counters = _generators[-1]
    n = counters.get(key, 0)
    counters[key] = n + 1
    return f"{key}_{n}"


def generate_with_ignorable_key(key):
    return generate(key)


def switch(new_generator=None):
    old = _generators[-1]
    _generators[-1] = new_generator if new_generator is not None else {}
    return old


@contextlib.contextmanager
def guard(new_generator=None):
    """Fresh name scope; restores the previous one on exit."""
    _generators.append(new_generator if isinstance(new_generator, dict)
                       else {})
    try:
        yield
    finally:
        _generators.pop()
