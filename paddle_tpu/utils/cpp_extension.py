"""Custom op extension API.

Reference parity: paddle/fluid/extension/ (PD_BUILD_OP stable ABI) +
python/paddle/utils/cpp_extension/cpp_extension.py (JIT `load`).

TPU-native design: custom DEVICE kernels are written in Python as jax/
Pallas functions and registered with `register_op` — no ABI needed, they
compile into the same XLA program as built-in ops. Custom HOST ops (C++
CPU code: tokenizers, samplers, feature extractors) compile via this
module into a shared library and run inside the graph through
jax.pure_callback — the host-side analogue of the reference's custom CPU
kernels.

C++ contract (C ABI): void op(const float** ins, const int64_t* in_sizes,
int n_in, float* out, int64_t out_size).
"""
import ctypes
import hashlib
import os
import subprocess

import numpy as np
import jax
import jax.numpy as jnp

from ..core.dispatch import register_op as _register_op
from ..core.tensor import Tensor

_BUILD_ROOT = os.path.expanduser("~/.cache/paddle_tpu/extensions")


def register_custom_op(name, fn, differentiable=True):
    """Register a pure jax/Pallas function as a framework op (device path).
    Returns a callable taking/returning Tensors."""
    return _register_op(name, differentiable=differentiable)(fn)


def load(name, sources, extra_cxx_cflags=None, build_directory=None,
         verbose=False, **kwargs):
    """JIT-compile C++ sources into a host-op library (reference:
    cpp_extension.load). Returns a module-like object whose attribute
    lookups resolve exported op symbols as python callables."""
    build_dir = build_directory or _BUILD_ROOT
    os.makedirs(build_dir, exist_ok=True)
    tag = hashlib.md5("".join(sources).encode()).hexdigest()[:12]
    so_path = os.path.join(build_dir, f"{name}_{tag}.so")
    if not os.path.exists(so_path):
        cmd = ["g++", "-O2", "-std=c++17", "-fPIC", "-shared",
               "-o", so_path] + list(sources) + (extra_cxx_cflags or [])
        res = subprocess.run(cmd, capture_output=True, text=True)
        if res.returncode != 0:
            raise RuntimeError(f"extension build failed:\n{res.stderr}")
        if verbose:
            print(f"built {so_path}")
    lib = ctypes.CDLL(so_path)

    class _Module:
        def __getattr__(self, sym):
            cfn = getattr(lib, sym)
            cfn.restype = None
            cfn.argtypes = [
                ctypes.POINTER(ctypes.POINTER(ctypes.c_float)),
                ctypes.POINTER(ctypes.c_int64), ctypes.c_int,
                ctypes.POINTER(ctypes.c_float), ctypes.c_int64]

            def host_call(*arrays):
                arrs = [np.ascontiguousarray(a, np.float32) for a in arrays]
                out = np.empty_like(arrs[0])
                ptrs = (ctypes.POINTER(ctypes.c_float) * len(arrs))(
                    *[a.ctypes.data_as(ctypes.POINTER(ctypes.c_float))
                      for a in arrs])
                sizes = (ctypes.c_int64 * len(arrs))(*[a.size for a in arrs])
                cfn(ptrs, sizes, len(arrs),
                    out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
                    out.size)
                return out

            def op_fn(*xs):
                shape_dtype = jax.ShapeDtypeStruct(xs[0].shape, jnp.float32)
                return jax.pure_callback(
                    host_call, shape_dtype,
                    *[x.astype(jnp.float32) for x in xs])

            wrapped = _register_op(f"custom_{name}_{sym}",
                                   differentiable=False)(op_fn)

            def api(*tensors):
                return wrapped(*tensors)
            api.__name__ = sym
            return api

    return _Module()


class CppExtension:
    """setup()-style descriptor (reference CppExtension); consumed by
    `load` in this runtime."""

    def __init__(self, sources, **kwargs):
        self.sources = sources
        self.kwargs = kwargs


def CUDAExtension(*args, **kwargs):
    raise RuntimeError(
        "CUDA extensions do not exist on TPU; write device kernels as "
        "jax/Pallas functions and register with register_custom_op, or "
        "host C++ ops via cpp_extension.load")


def get_build_directory(verbose=False):
    """Reference: cpp_extension/extension_utils.py get_build_directory —
    where JIT-built extensions land (PADDLE_EXTENSION_DIR overrides)."""
    import os
    path = os.environ.get("PADDLE_EXTENSION_DIR") or os.path.join(
        os.path.expanduser("~"), ".cache", "paddle_tpu", "extensions")
    os.makedirs(path, exist_ok=True)
    return path


def setup(name=None, ext_modules=None, **kwargs):
    """Reference: cpp_extension.setup — setuptools-style build entry for
    custom ops. Here extensions JIT-compile straight into the build
    directory via load() (no egg/install step: import side effects
    register the ops)."""
    mods = ext_modules if isinstance(ext_modules, (list, tuple)) \
        else ([ext_modules] if ext_modules is not None else [])
    built = []
    for ext in mods:
        srcs = getattr(ext, "sources", None) or []
        ext_name = getattr(ext, "name", None) or name
        built.append(load(ext_name, srcs,
                          build_directory=get_build_directory()))
    return built
