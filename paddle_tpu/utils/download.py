"""Dataset/weight download helper (reference:
python/paddle/dataset/common.py + utils/download.py). Zero-egress
environment: downloads are disabled; files must exist locally."""
import hashlib
import os

DATA_HOME = os.path.expanduser("~/.cache/paddle_tpu/dataset")
WEIGHTS_HOME = os.path.expanduser("~/.cache/paddle_tpu/weights")


def md5file(fname):
    h = hashlib.md5()
    with open(fname, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


def get_path_from_url(url, root_dir=None, md5sum=None, check_exist=True):
    root_dir = root_dir or DATA_HOME
    fname = os.path.join(root_dir, os.path.basename(url))
    if os.path.exists(fname):
        return fname
    raise RuntimeError(
        f"network access is disabled; place {os.path.basename(url)} under "
        f"{root_dir} manually (wanted from {url})")


def get_weights_path_from_url(url, md5sum=None):
    return get_path_from_url(url, WEIGHTS_HOME, md5sum)
