"""Minimal TensorBoard events-file scalar writer, dependency-free.

TPU-native stand-in for the reference's VisualDL integration
(reference: python/paddle/hapi/callbacks.py VisualDL callback writing
scalars via visualdl.LogWriter). The image has no visualdl/tensorboard
package, so this emits the TensorBoard wire format directly: TFRecord
framing (length + masked-crc32c) around hand-encoded tensorflow.Event
protobufs carrying Summary/simple_value scalars — readable by a stock
TensorBoard.
"""
import os
import socket
import struct
import time

# ---- crc32c (Castagnoli), table-driven -------------------------------------

_CRC_TABLE = []


def _crc_table():
    if not _CRC_TABLE:
        poly = 0x82F63B78
        for n in range(256):
            c = n
            for _ in range(8):
                c = (c >> 1) ^ poly if c & 1 else c >> 1
            _CRC_TABLE.append(c)
    return _CRC_TABLE


def _crc32c(data):
    table = _crc_table()
    crc = 0xFFFFFFFF
    for b in data:
        crc = table[(crc ^ b) & 0xFF] ^ (crc >> 8)
    return crc ^ 0xFFFFFFFF


def _masked_crc(data):
    crc = _crc32c(data)
    return ((crc >> 15) | (crc << 17)) + 0xA282EAD8 & 0xFFFFFFFF


# ---- protobuf wire encoding (the 4 shapes we need) -------------------------

def _varint(n):
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _tag(field, wire):
    return _varint((field << 3) | wire)


def _pb_double(field, v):
    return _tag(field, 1) + struct.pack("<d", v)


def _pb_float(field, v):
    return _tag(field, 5) + struct.pack("<f", v)


def _pb_int64(field, v):
    return _tag(field, 0) + _varint(v & 0xFFFFFFFFFFFFFFFF)


def _pb_bytes(field, data):
    if isinstance(data, str):
        data = data.encode("utf-8")
    return _tag(field, 2) + _varint(len(data)) + data


def _event(wall_time, step=None, file_version=None, summary=None):
    """tensorflow.Event: wall_time=1 double, step=2 int64,
    file_version=3 string, summary=5 message."""
    buf = _pb_double(1, wall_time)
    if step is not None:
        buf += _pb_int64(2, step)
    if file_version is not None:
        buf += _pb_bytes(3, file_version)
    if summary is not None:
        buf += _pb_bytes(5, summary)
    return buf


def _scalar_summary(tag, value):
    """tensorflow.Summary{ value=1: { tag=1 string, simple_value=2 }}"""
    val = _pb_bytes(1, tag) + _pb_float(2, float(value))
    return _pb_bytes(1, val)


class SummaryWriter:
    """Append-only scalars writer producing a TensorBoard events file.

    API subset of visualdl.LogWriter / torch SummaryWriter:
    add_scalar(tag, value, step), flush(), close().
    """

    def __init__(self, logdir):
        self.logdir = logdir
        os.makedirs(logdir, exist_ok=True)
        fname = (f"events.out.tfevents.{int(time.time())}."
                 f"{socket.gethostname()}")
        self.path = os.path.join(logdir, fname)
        self._f = open(self.path, "ab")
        self._write_event(_event(time.time(),
                                 file_version="brain.Event:2"))

    def _write_event(self, payload):
        header = struct.pack("<Q", len(payload))
        self._f.write(header)
        self._f.write(struct.pack("<I", _masked_crc(header)))
        self._f.write(payload)
        self._f.write(struct.pack("<I", _masked_crc(payload)))

    def add_scalar(self, tag, value, step):
        self._write_event(_event(time.time(), step=int(step),
                                 summary=_scalar_summary(tag, value)))

    def flush(self):
        self._f.flush()

    def close(self):
        if not self._f.closed:
            self._f.flush()
            self._f.close()


def read_scalars(path):
    """Parse an events file back into {tag: [(step, value), ...]} —
    verification-grade decoder (crc-checked) used by tests."""
    out = {}
    with open(path, "rb") as f:
        data = f.read()
    pos = 0
    while pos < len(data):
        (ln,) = struct.unpack_from("<Q", data, pos)
        (hcrc,) = struct.unpack_from("<I", data, pos + 8)
        assert hcrc == _masked_crc(data[pos:pos + 8]), "header crc"
        payload = data[pos + 12:pos + 12 + ln]
        (pcrc,) = struct.unpack_from("<I", data, pos + 12 + ln)
        assert pcrc == _masked_crc(payload), "payload crc"
        pos += 12 + ln + 4
        step, summary = 0, None
        p = 0
        while p < len(payload):
            key, p = _read_varint(payload, p)
            field, wire = key >> 3, key & 7
            if wire == 0:
                v, p = _read_varint(payload, p)
                if field == 2:
                    step = v
            elif wire == 1:
                p += 8
            elif wire == 5:
                p += 4
            elif wire == 2:
                ln2, p = _read_varint(payload, p)
                if field == 5:
                    summary = payload[p:p + ln2]
                p += ln2
        if summary:
            q = 0
            while q < len(summary):
                key, q = _read_varint(summary, q)
                if key >> 3 == 1 and key & 7 == 2:
                    vlen, q = _read_varint(summary, q)
                    val = summary[q:q + vlen]
                    q += vlen
                    tag, sv, r = None, None, 0
                    while r < len(val):
                        k2, r = _read_varint(val, r)
                        if k2 >> 3 == 1 and k2 & 7 == 2:
                            tl, r = _read_varint(val, r)
                            tag = val[r:r + tl].decode()
                            r += tl
                        elif k2 >> 3 == 2 and k2 & 7 == 5:
                            (sv,) = struct.unpack_from("<f", val, r)
                            r += 4
                        else:
                            break
                    if tag is not None:
                        out.setdefault(tag, []).append((step, sv))
    return out


def _read_varint(buf, pos):
    result = shift = 0
    while True:
        b = buf[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, pos
        shift += 7
