"""fluid.dygraph compat (reference: python/paddle/fluid/dygraph/):
guard, to_variable, old-style layer aliases, TracedLayer-ish helpers.
Dygraph is the default (and only) eager mode here, so guard is a no-op
context and enable/disable toggle a flag the modern API also reads.
"""
import contextlib

from ..core.tensor import Tensor
from ..nn.layer_base import Layer  # noqa: F401
from ..nn.layer.common import Linear, Embedding  # noqa: F401
from ..nn.layer.conv import Conv2D  # noqa: F401
from ..nn.layer.norm import BatchNorm2D as BatchNorm  # noqa: F401
from ..nn.layer.pooling import MaxPool2D as Pool2D  # noqa: F401
from ..jit.to_static import to_static as jit_to_static  # noqa: F401


@contextlib.contextmanager
def guard(place=None):
    """Reference: fluid/dygraph/base.py guard — eager mode is always on
    in the TPU build; kept for source compatibility."""
    yield


def to_variable(value, name=None, zero_copy=None, dtype=None):
    return Tensor(value, dtype=dtype, name=name)


def enabled():
    return True


def enable_dygraph(place=None):
    pass


def disable_dygraph():
    pass
