"""paddle.fluid compat namespace.

Reference parity: python/paddle/fluid/ — the pre-2.0 API layer that much
existing user code still imports (fluid.dygraph.guard, fluid.layers.*,
fluid.Executor, fluid.ParamAttr, ...). Everything here forwards to the
modern paddle_tpu modules; it exists so reference-era scripts port
without rewrites. New code should use the top-level API.
"""
from ..core.device import (  # noqa: F401
    CPUPlace, CUDAPlace, CUDAPinnedPlace,
)
from ..nn.initializer import ParamAttr  # noqa: F401
from .. import regularizer  # noqa: F401
from ..static import (  # noqa: F401
    Executor, Program, default_main_program, default_startup_program,
    program_guard, data,
)
from ..core.dispatch import no_grad  # noqa: F401
from ..core.lod import (  # noqa: F401
    LoDTensor, create_lod_tensor, create_random_int_lodtensor,
)
from .. import optimizer  # noqa: F401
from . import dygraph  # noqa: F401
from . import layers  # noqa: F401
from . import io  # noqa: F401
from . import incubate  # noqa: F401
from ..nn import initializer  # noqa: F401
from ..nn.clip import (  # noqa: F401
    ClipGradByValue, ClipGradByNorm, ClipGradByGlobalNorm,
)


def is_compiled_with_cuda():
    return False


from ..core.flags import get_flags, set_flags  # noqa: F401,E402


class CompiledProgram:
    """Reference: fluid/compiler.py CompiledProgram — on TPU every traced
    program is already 'compiled' (XLA); with_data_parallel maps to GSPMD
    batch sharding, so both are identity wrappers."""

    def __init__(self, program_or_graph, build_strategy=None):
        self._program = program_or_graph

    def with_data_parallel(self, loss_name=None, build_strategy=None,
                           exec_strategy=None, places=None):
        return self


class ExecutionStrategy:
    num_threads = 1
    num_iteration_per_drop_scope = 100


class BuildStrategy:
    class ReduceStrategy:
        AllReduce = 0
        Reduce = 1
    reduce_strategy = ReduceStrategy.AllReduce
    fuse_all_reduce_ops = True
    memory_optimize = True
