"""Pre-2.0 incubate namespace (reference: python/paddle/fluid/incubate/).

The TPU build keeps the legacy fleet surface alive as a thin delegation
layer over `paddle.distributed.fleet` (the modern runtime); see
fleet/ subpackage.
"""
from . import fleet  # noqa: F401
