"""Legacy (pre-2.0) fleet API, kept import-compatible.

Reference: python/paddle/fluid/incubate/fleet/ — `base` (Fleet/Mode/
role makers), `collective` (Collective fleet + CollectiveOptimizer),
`parameter_server.distribute_transpiler` (FleetTranspiler + the
Sync/Async/HalfAsync/Geo strategy factory), `parameter_server.pslib`
(binary PSLib — not portable, raises with guidance here).

These all delegate to the modern `paddle.distributed.fleet` runtime:
one PS/collective implementation, two API skins.
"""
from . import base  # noqa: F401
