"""Legacy Fleet base (reference: fluid/incubate/fleet/base/fleet_base.py:42
`Fleet`, :273 `DistributedOptimizer`).

Every query/lifecycle verb delegates to the modern
`paddle.distributed.fleet` module-level API, so a legacy `fleet`
singleton and the modern one observe the same runtime state.
"""
from .....distributed import fleet as _modern
from .mode import Mode


class Fleet:
    """Abstract legacy fleet. Subclasses: Collective (collective mode),
    FleetTranspiler (parameter-server mode)."""

    def __init__(self, mode):
        self._mode = mode
        self._role_maker = None
        self._optimizer = None

    # --- queries (reference fleet_base.py:61-153) ---
    def is_first_worker(self):
        return _modern.is_first_worker()

    def worker_index(self):
        return _modern.worker_index()

    def worker_num(self):
        return _modern.worker_num()

    def is_worker(self):
        return _modern.is_worker()

    def worker_endpoints(self, to_string=False):
        return _modern.worker_endpoints(to_string=to_string)

    def server_num(self):
        return _modern.server_num()

    def server_index(self):
        return _modern.server_index()

    def server_endpoints(self, to_string=False):
        return _modern.server_endpoints(to_string=to_string)

    def is_server(self):
        return _modern.is_server()

    def is_xpu(self):
        return False

    def split_files(self, files):
        """Shard a file list across workers (reference :163)."""
        return _modern.util.get_file_shard(files)

    def barrier_worker(self):
        _modern.barrier_worker()

    def all_reduce_worker(self, input, output=None):  # noqa: A002
        res = _modern.util.all_reduce(input, mode="sum",
                                      comm_world="worker")
        if output is not None:
            # legacy contract: the caller-provided buffer receives the
            # reduction (reference fleet_base.py:222). np.asarray on a
            # list/Tensor would copy, silently dropping the write, so
            # only buffers we can genuinely mutate are accepted.
            import numpy as np
            arr = np.asarray(res)
            if isinstance(output, np.ndarray):
                output[...] = arr
            elif isinstance(output, list):
                output[:] = np.atleast_1d(arr).tolist()
            elif hasattr(output, "set_value"):  # paddle_tpu Tensor —
                # set_value validates shape and goes through the
                # trace-aware value setter (a raw _value write would be
                # invisible to an active trace)
                output.set_value(arr)
            else:
                raise TypeError(
                    "all_reduce_worker: cannot write in place into "
                    f"{type(output).__name__}; pass an ndarray/list/"
                    "Tensor or use the return value")
        return res

    # --- lifecycle ---
    def init(self, role_maker=None):
        # In the legacy API the FLEET INSTANCE determines the mode
        # (Collective vs FleetTranspiler), not the role maker; the
        # modern init branches solely on role_maker._is_collective, so
        # stamp the instance's mode onto the role maker.
        is_coll = self._mode == Mode.COLLECTIVE
        if role_maker is None:
            from .role_maker import PaddleCloudRoleMaker
            role_maker = PaddleCloudRoleMaker(is_collective=is_coll)
        else:
            role_maker._is_collective = is_coll
        self._role_maker = role_maker
        _modern.init(role_maker=role_maker, is_collective=is_coll)
        return self

    def init_worker(self):
        _modern.init_worker()

    def init_server(self, model_dir=None, **kwargs):
        _modern.init_server(model_dir, **kwargs)

    def run_server(self):
        _modern.run_server()

    def stop_worker(self):
        _modern.stop_worker()

    def distributed_optimizer(self, optimizer, strategy=None):
        raise NotImplementedError

    def save_inference_model(self, executor=None, dirname=None,
                             feeded_var_names=None, target_vars=None,
                             main_program=None, export_for_deployment=True):
        return _modern.save_inference_model(
            executor, dirname, feeded_var_names, target_vars,
            main_program, export_for_deployment=export_for_deployment)

    def save_persistables(self, executor=None, dirname=None,
                          main_program=None):
        return _modern.save_persistables(executor, dirname, main_program)


class DistributedOptimizer:
    """Legacy distributed-optimizer wrapper (reference :273): holds the
    inner optimizer; minimize() is the entry point."""

    def __init__(self, optimizer, strategy=None):
        self._optimizer = optimizer
        self._strategy = strategy
        # the modern wrap (meta-optimizers + hybrid clip) is stateful —
        # e.g. GradientMerge accumulation counters — so it must be
        # built ONCE and reused across minimize() calls
        self._modern_opt = None

    def backward(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None, callbacks=None):
        loss.backward()
        return []

    def apply_gradients(self, params_grads):
        self._optimizer.step()

    def _wrapped(self):
        if self._modern_opt is None:
            self._modern_opt = _modern.distributed_optimizer(
                self._optimizer)
        return self._modern_opt

    def minimize(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None):
        return self._wrapped().minimize(loss,
                                        startup_program=startup_program)
