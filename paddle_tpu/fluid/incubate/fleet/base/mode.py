"""Reference: fluid/incubate/fleet/base/mode.py:30 — fleet run modes."""


class Mode:
    TRANSPILER = 1
    PSLIB = 2
    COLLECTIVE = 3
