from . import fleet_base, mode, role_maker  # noqa: F401
from .mode import Mode  # noqa: F401
