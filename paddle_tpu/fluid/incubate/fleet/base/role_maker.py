"""Legacy role makers (reference: fluid/incubate/fleet/base/role_maker.py).

The modern role makers already speak the same env protocol
(PADDLE_TRAINER_ID / TRAINING_ROLE / PADDLE_PSERVERS_IP_PORT_LIST), so
the legacy names re-export them. `Role` keeps the legacy WORKER/SERVER
constants. MPI-based role makers need an MPI runtime the TPU image does
not ship; they raise with the modern replacement named.
"""
from .....distributed.fleet.role_maker import (  # noqa: F401
    PaddleCloudRoleMaker, Role, RoleMakerBase, UserDefinedRoleMaker)


class MPISymetricRoleMaker(RoleMakerBase):  # noqa: N801 (reference name)
    """Reference: role_maker.py MPISymetricRoleMaker (mpi4py-based)."""

    def __init__(self, *a, **k):
        raise NotImplementedError(
            "MPI role makers need an MPI runtime (mpi4py), which this "
            "image does not ship. Use PaddleCloudRoleMaker (env-driven, "
            "works with paddle.distributed.launch) or "
            "UserDefinedRoleMaker instead.")


MPIRoleMaker = MPISymetricRoleMaker
