"""Binary PSLib mode (reference:
fluid/incubate/fleet/parameter_server/pslib/__init__.py).

PSLib is a closed-source baidu PS binary the reference links against
when built WITH_PSLIB; it is not portable to this stack. The public
entry raises and names the working replacement (the transpiler-mode
legacy skin or the modern fleet API, both backed by the TPU-native PS
runtime in paddle_tpu/distributed/ps/).
"""


class PSLib:
    def __init__(self, *a, **k):
        raise NotImplementedError(
            "binary PSLib is not available on this stack; use "
            "fluid.incubate.fleet.parameter_server.distribute_transpiler"
            ".fleet (same API, modern PS runtime underneath) or "
            "paddle.distributed.fleet directly")


def fleet(*a, **k):
    raise NotImplementedError(
        "binary PSLib is not available on this stack; use "
        "fluid.incubate.fleet.parameter_server.distribute_transpiler"
        ".fleet or paddle.distributed.fleet")
