"""Reference: fluid/incubate/fleet/parameter_server/mode.py —
PS communication modes."""


class DistributedMode:
    SYNC = 0
    ASYNC = 1
    HALF_ASYNC = 2
    GEO = 3
