"""Legacy parameter-server fleet namespace (reference:
fluid/incubate/fleet/parameter_server/ — distribute_transpiler mode
delegates to the modern PS runtime; binary PSLib mode is not portable).
"""
from .mode import DistributedMode  # noqa: F401
