"""Legacy PS strategy factory (reference:
fluid/incubate/fleet/parameter_server/distribute_transpiler/
distributed_strategy.py:17 __all__, :26 TrainerRuntimeConfig, :137
DistributedStrategy, :297+ Sync/Async/HalfAsync/Geo strategies).

Each legacy strategy knows how to express itself as the modern
`paddle.distributed.fleet.DistributedStrategy` (`to_modern()`), which
is what FleetTranspiler hands to the modern runtime: sync -> a_sync
off; async/half-async -> a_sync; geo -> a_sync + k_steps.
"""

__all__ = ["TrainerRuntimeConfig", "DistributedStrategy", "SyncStrategy",
           "AsyncStrategy", "HalfAsyncStrategy", "GeoStrategy",
           "StrategyFactory"]


class TrainerRuntimeConfig:
    """Communicator tuning knobs (reference :26 — env-overridable
    max_merge_var_num / send_queue_size etc.)."""

    def __init__(self):
        import os
        self.runtime_configs = {
            "communicator_max_merge_var_num":
                os.getenv("FLAGS_communicator_max_merge_var_num", "20"),
            "communicator_send_queue_size":
                os.getenv("FLAGS_communicator_send_queue_size", "20"),
            "communicator_independent_recv_thread":
                os.getenv("FLAGS_communicator_independent_recv_thread",
                          "1"),
        }

    def get_communicator_flags(self):
        return dict(self.runtime_configs)


class DistributedStrategy:
    def __init__(self):
        self._program_config = {}
        self._trainer_runtime_config = TrainerRuntimeConfig()
        self._server_runtime_config = {}
        self._execute_strategy = None
        self._build_strategy = None

    def get_trainer_runtime_config(self):
        return self._trainer_runtime_config

    def get_program_config(self):
        return self._program_config

    def get_server_runtime_config(self):
        return self._server_runtime_config

    def to_modern(self):
        """Express this legacy strategy as the modern
        fleet.DistributedStrategy."""
        from ......distributed.fleet import DistributedStrategy as Modern
        s = Modern()
        s.a_sync = self._a_sync()
        k = self._k_steps()
        if k:
            s.a_sync_configs = {"k_steps": k}
        return s

    def _a_sync(self):
        return False

    def _k_steps(self):
        return 0


class SyncStrategy(DistributedStrategy):
    """Fully synchronous PS updates (reference :297)."""


class AsyncStrategy(DistributedStrategy):
    """Fire-and-forget gradient push (reference AsyncStrategy)."""

    def _a_sync(self):
        return True


class HalfAsyncStrategy(DistributedStrategy):
    """Async within a barrier epoch (reference HalfAsyncStrategy); the
    modern runtime's a_sync communicator + worker barriers cover it."""

    def _a_sync(self):
        return True


class GeoStrategy(DistributedStrategy):
    """Geo-SGD delta sync every k steps (reference GeoStrategy)."""

    def __init__(self, update_frequency=100):
        super().__init__()
        self._update_frequency = int(update_frequency)

    def _a_sync(self):
        return True

    def _k_steps(self):
        return self._update_frequency


class StrategyFactory:
    """Reference: StrategyFactory.create_*_strategy() classmethods."""

    @staticmethod
    def create_sync_strategy():
        return SyncStrategy()

    @staticmethod
    def create_async_strategy():
        return AsyncStrategy()

    @staticmethod
    def create_half_async_strategy():
        return HalfAsyncStrategy()

    @staticmethod
    def create_geo_strategy(update_frequency=100):
        return GeoStrategy(update_frequency)
