"""Legacy transpiler-mode PS fleet (reference:
fluid/incubate/fleet/parameter_server/distribute_transpiler/
__init__.py:714 `fleet = FleetTranspiler()`).

The reference rewrites the program into trainer/server halves with a
DistTranspiler; the TPU build's modern PS runtime already does the
equivalent split (server-side tables + trainer-side communicator), so
the legacy verbs delegate — legacy strategies are translated via
`to_modern()` at distributed_optimizer time.
"""
from ......distributed import fleet as _modern
from ...base.fleet_base import DistributedOptimizer, Fleet
from ...base.mode import Mode
from .distributed_strategy import (DistributedStrategy, StrategyFactory,
                                   SyncStrategy)


class FleetTranspiler(Fleet):
    def __init__(self):
        super().__init__(Mode.TRANSPILER)

    def distributed_optimizer(self, optimizer, strategy=None):
        if strategy is None:
            strategy = StrategyFactory.create_sync_strategy()
        if isinstance(strategy, DistributedStrategy):
            modern = strategy.to_modern()
        else:
            modern = strategy  # already a modern strategy
        wrapped = _modern.distributed_optimizer(optimizer, strategy=modern)
        self._optimizer = ParameterServerOptimizer(optimizer, strategy)
        # reuse the modern wrap (stateful meta-optimizers) instead of
        # re-wrapping on the first minimize()
        self._optimizer._modern_opt = wrapped
        return self._optimizer


class ParameterServerOptimizer(DistributedOptimizer):
    """Reference: distribute_transpiler/__init__.py
    ParameterServerOptimizer."""


fleet = FleetTranspiler()
