"""Legacy collective fleet (reference:
fluid/incubate/fleet/collective/__init__.py:51 `Collective`, :196
`fleet = Collective()`, :249 `CollectiveOptimizer`).

Delegates to the modern collective runtime (`paddle.distributed.fleet`
with is_collective=True — GSPMD mesh instead of NCCL rings).
"""
from ..base.fleet_base import DistributedOptimizer, Fleet
from ..base.mode import Mode


class DistributedStrategy:
    """Legacy knob bag (reference :199 extends BuildStrategy). All of
    these tune NCCL allreduce scheduling, which GSPMD/XLA absorbs on
    TPU — the knobs are accepted-and-ignored for source compat."""

    def __init__(self):
        self.fuse_all_reduce_ops = True
        self.nccl_comm_num = 1
        self.use_hierarchical_allreduce = False
        self.hierarchical_allreduce_inter_nranks = 0
        self.mode = "collective"
        self.collective_mode = "grad_allreduce"


class LambConfig:
    """Reference :41 — marker config selecting the Lamb optimizer."""


class DistFCConfig:
    """Reference :46 — distributed-FC sharding marker."""


class Collective(Fleet):
    def __init__(self):
        super().__init__(Mode.COLLECTIVE)

    def distributed_optimizer(self, optimizer, strategy=None):
        self._optimizer = CollectiveOptimizer(optimizer, strategy)
        return self._optimizer


class CollectiveOptimizer(DistributedOptimizer):
    """Reference :249 — wraps the inner optimizer for collective
    (allreduce) training; the modern runtime shards via the mesh."""

    def __init__(self, optimizer, strategy=None):
        if strategy is None:
            strategy = DistributedStrategy()
        super().__init__(optimizer, strategy)


fleet = Collective()
