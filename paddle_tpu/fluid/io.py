"""fluid.io compat (reference: python/paddle/fluid/io.py:
save_persistables/save_inference_model/load_inference_model + the
reader decorators re-exported). Forwards to modern save/load and
jit.save/load."""
from ..framework.io_utils import save, load  # noqa: F401
from ..reader import (  # noqa: F401
    map_readers, shuffle, chain, compose, buffered, firstn, cache,
    xmap_readers,
)


def save_persistables(executor=None, dirname=None, main_program=None,
                      filename=None):
    """Reference: fluid/io.py save_persistables — walk the program's
    persistable vars and save them. The static Program tracks its
    persistables (static/program.py register_persist), so this forwards
    to static.save on that program."""
    import os
    from .. import static
    prog = main_program if main_program is not None \
        else static.default_main_program()
    path = os.path.join(dirname or ".", filename or "persistables")
    return static.save(prog, path)


def load_persistables(executor=None, dirname=None, main_program=None,
                      filename=None):
    """Reference: fluid/io.py load_persistables counterpart."""
    import os
    from .. import static
    prog = main_program if main_program is not None \
        else static.default_main_program()
    path = os.path.join(dirname or ".", filename or "persistables")
    return static.load(prog, path, executor)


def save_inference_model(dirname, feeded_var_names=None, target_vars=None,
                         executor=None, main_program=None, model=None,
                         input_spec=None, **kwargs):
    from .. import jit
    if model is None:
        raise NotImplementedError(
            "pass model= (an nn.Layer): the TPU build exports traced "
            "programs via jit.save, not ProgramDesc files")
    return jit.save(model, dirname, input_spec=input_spec)


def load_inference_model(dirname, executor=None, **kwargs):
    from .. import jit
    return jit.load(dirname)
