"""fluid.io compat (reference: python/paddle/fluid/io.py:
save_persistables/save_inference_model/load_inference_model + the
reader decorators re-exported). Forwards to modern save/load and
jit.save/load."""
from ..framework.io_utils import save, load  # noqa: F401
from ..reader import (  # noqa: F401
    map_readers, shuffle, chain, compose, buffered, firstn, cache,
    xmap_readers,
)


def save_persistables(executor=None, dirname=None, main_program=None,
                      filename=None):
    """The reference walks the program's persistable vars; here model/
    optimizer state_dicts are the persistables — use paddle.save on
    state_dict() (this shim exists for source compat)."""
    raise NotImplementedError(
        "save_persistables requires a ProgramDesc; in the TPU build save "
        "state_dicts: paddle.save(model.state_dict(), path)")


def save_inference_model(dirname, feeded_var_names=None, target_vars=None,
                         executor=None, main_program=None, model=None,
                         input_spec=None, **kwargs):
    from .. import jit
    if model is None:
        raise NotImplementedError(
            "pass model= (an nn.Layer): the TPU build exports traced "
            "programs via jit.save, not ProgramDesc files")
    return jit.save(model, dirname, input_spec=input_spec)


def load_inference_model(dirname, executor=None, **kwargs):
    from .. import jit
    return jit.load(dirname)
