"""fluid.layers compat — the op-assembly API (reference:
python/paddle/fluid/layers/nn.py 36k LoC). The heavily-used subset
forwards to the modern functional ops; names keep fluid's signatures
(e.g. fc(input, size), reduce_mean, cross_entropy with soft labels off).
"""
import os as _os

import numpy as np

from ..core.tensor import Tensor

# paddle_tpu package root, for separating user frames from framework
# frames in _reuse_key (trailing sep so a sibling dir sharing the
# prefix, e.g. .../paddle_tpu_examples, is not misclassified)
_PKG_ROOT = _os.path.dirname(
    _os.path.dirname(_os.path.abspath(__file__))) + _os.sep
# the jit/to_static machinery re-invokes the user body once per phase
# (eager/record/compile) from phase-specific lines; frames at or above
# it are phase-variant and must not enter the reuse key
_JIT_DIR = _PKG_ROOT + "jit" + _os.sep

import itertools as _itertools  # noqa: E402
import weakref as _weakref  # noqa: E402

_instance_tokens = _itertools.count()
# identity-keyed side table (NOT an instance attribute: copy.deepcopy
# of a module would carry an attribute over and alias the copy to the
# original's cached parameters; NOT a WeakKeyDictionary: that keys by
# __eq__/__hash__, so a Layer subclass defining __eq__ would crash or
# value-alias). id() keys are guarded against address recycling by a
# liveness check plus a weakref finalizer that evicts dead entries.
_instance_token_map = {}


def _instance_token(slf):
    key = id(slf)
    ent = _instance_token_map.get(key)
    if ent is not None and ent[0]() is slf:
        return ent[1]
    tok = next(_instance_tokens)

    def _evict(_ref, _key=key):
        _instance_token_map.pop(_key, None)

    _instance_token_map[key] = (_weakref.ref(slf, _evict), tok)
    return tok
from ..ops import (creation, linalg, manipulation, math as math_ops,
                   nn_ops, reduction)
from ..static import data  # noqa: F401


_builtin_range = range  # the fluid `range` layer shadows the builtin below

_layer_cache = {}


def clear_layer_cache():
    """Drop all implicitly-created fluid.layers parameters (frees them and
    resets call-site reuse — call between independent model builds)."""
    _layer_cache.clear()


def _reuse_key(name, config):
    """Parameter reuse for the eager replay of fluid code: the reference
    builds each layers.* call ONCE into a program; eager loops re-execute
    the python line each step, so the same call site (or explicit `name`)
    must map to the same parameters or nothing trains. Key: user name if
    given, else the USER portion of the call stack + config — two
    logically distinct layers built through a shared helper differ in an
    outer frame, so they do not alias. Framework-internal frames are
    excluded: under jit/to_static the machinery frames above the user
    body differ per phase (eager/record/compile), and keying on them
    would re-initialize the layer's parameters every pass. Pass `name`
    to share parameters deliberately."""
    if name is not None:
        return ("name", name) + config
    import sys

    from ..nn.layer_base import Layer as _Layer
    frames = []
    f = sys._getframe(2)
    while f is not None:
        fn = f.f_code.co_filename
        if fn.startswith(_JIT_DIR):
            # jit/to_static runner: phase-variant — stop here so the
            # same call site keys identically across eager/record/
            # compile passes
            break
        if not fn.startswith(_PKG_ROOT):
            # keep user frames (outer frames distinguish layers built
            # through shared helpers); skip framework-internal ones
            frames.append((fn, f.f_lineno))
            slf = f.f_locals.get("self")
            if isinstance(slf, _Layer):
                # an nn.Layer method: the INSTANCE identity subsumes
                # everything above it — two module objects sharing
                # forward() code never alias (even called from one
                # line), and repeat calls on one instance from
                # different lines still reuse. A monotonic token in a
                # weak side table (not id(): CPython recycles freed
                # addresses; not an instance attribute: deepcopy would
                # carry it and alias the copy) provides the identity.
                frames.append(("<layer-instance>", _instance_token(slf)))
                break
        f = f.f_back
    return (tuple(frames),) + config


def fc(input, size, num_flatten_dims=1, param_attr=None, bias_attr=None,
       act=None, name=None):
    """Reference: fluid/layers/nn.py fc — creates (or reuses, see
    _reuse_key) a Linear over the flattened trailing dims."""
    from ..nn.layer.common import Linear
    from ..ops.nn_ops import fc_flatten
    x, in_features = fc_flatten(input, num_flatten_dims)
    key = _reuse_key(name, ("fc", in_features, size))
    layer = _layer_cache.get(key)
    if layer is None:
        layer = Linear(in_features, size, weight_attr=param_attr,
                       bias_attr=bias_attr)
        _layer_cache[key] = layer
    out = layer(x)
    if act is not None:
        out = _apply_act(out, act)
    return out


# activation names fluid layers may apply via act= (reference validates
# against the OpMaker activation registry; arbitrary callables like
# dropout must NOT be reachable through act=)
_ACT_NAMES = frozenset({
    "relu", "relu6", "sigmoid", "tanh", "softmax", "log_softmax", "gelu",
    "leaky_relu", "elu", "selu", "celu", "softplus", "softsign", "silu",
    "swish", "mish", "hardswish", "hardsigmoid", "hardtanh", "tanhshrink",
    "softshrink", "hardshrink", "exp", "square", "sqrt", "rsqrt", "abs",
    "reciprocal", "log", "log1p", "sin", "cos",
})


def _apply_act(out, act):
    if act is None:
        return out
    fn = None
    if act in _ACT_NAMES:
        fn = getattr(nn_ops, act, None) or getattr(math_ops, act, None)
    if fn is None or not callable(fn):
        raise ValueError(f"unsupported activation {act!r}")
    return fn(out)


def relu(x, name=None):
    return nn_ops.relu(x)


def softmax(x, axis=-1, name=None):
    return nn_ops.softmax(x, axis=axis)


def matmul(x, y, transpose_x=False, transpose_y=False, alpha=1.0,
           name=None):
    out = linalg.matmul(x, y, transpose_x, transpose_y)
    if alpha != 1.0:
        out = out * alpha
    return out


def reduce_mean(input, dim=None, keep_dim=False, name=None):
    return reduction.mean(input, axis=dim, keepdim=keep_dim)


def reduce_sum(input, dim=None, keep_dim=False, name=None):
    return reduction.sum(input, axis=dim, keepdim=keep_dim)


def reduce_max(input, dim=None, keep_dim=False, name=None):
    return reduction.max(input, axis=dim, keepdim=keep_dim)


def cross_entropy(input, label, soft_label=False, ignore_index=-100):
    return nn_ops.cross_entropy(input, label, soft_label=soft_label,
                                ignore_index=ignore_index,
                                use_softmax=False, reduction="none")


def softmax_with_cross_entropy(logits, label, soft_label=False,
                               ignore_index=-100, axis=-1,
                               return_softmax=False):
    loss = nn_ops.cross_entropy(logits, label, soft_label=soft_label,
                                ignore_index=ignore_index,
                                reduction="none")
    if return_softmax:
        return loss, nn_ops.softmax(logits, axis=axis)
    return loss


def mean(x, name=None):
    return reduction.mean(x)


def concat(input, axis=0, name=None):
    return manipulation.concat(input, axis=axis)


def reshape(x, shape, name=None):
    return manipulation.reshape(x, shape)


def transpose(x, perm, name=None):
    return manipulation.transpose(x, perm)


def fill_constant(shape, dtype, value, name=None):
    from ..static.program import building_program
    prog = building_program()
    if prog is not None:
        # symbolic: the While/StaticRNN patterns build loop state from
        # fill_constant, which must be a PROGRAM variable there
        from ..core.dtype import to_jax_dtype
        import jax.numpy as jnp
        return prog.const_var(
            jnp.full(tuple(int(s) for s in shape), value,
                     to_jax_dtype(dtype)), hint="fill_constant")
    return creation.full(shape, value, dtype=dtype)


def zeros(shape, dtype="float32", name=None):
    return creation.zeros(shape, dtype=dtype)


def ones(shape, dtype="float32", name=None):
    return creation.ones(shape, dtype=dtype)


def assign(input, output=None):
    from ..static.program import building_program, Variable as _SVar
    if isinstance(input, _SVar) or isinstance(output, _SVar):
        prog = building_program()
        src = input if isinstance(input, _SVar) \
            else prog.const_var(np.asarray(
                input.numpy() if isinstance(input, Tensor) else input),
                hint="assign")
        if output is not None:
            return prog.alias(src, output)
        # assign MAKES A COPY: record a fresh variable aliased from src
        # at THIS program position, so a later in-place alias onto src
        # (increment(in_place=True), less_than(cond=...)) is not
        # visible through the returned value — returning src itself
        # would silently share it (fluid assign-copy semantics inside
        # While bodies depend on this)
        name = prog._new_name("assign")
        v = _SVar(name, tuple(src._shape), src._dtype, prog)
        prog.vars[name] = v
        return prog.alias(src, v)
    t = Tensor(np.asarray(input)) if not isinstance(input, Tensor) \
        else input.clone()
    if output is not None:
        output.value = t.value
        return output
    return t


def cast(x, dtype):
    from ..ops.math import cast as _cast
    return _cast(x, dtype)


def embedding(input, size, is_sparse=False, param_attr=None,
              dtype="float32", name=None):
    from ..nn.layer.common import Embedding
    key = _reuse_key(name, ("embedding", int(size[0]), int(size[1]),
                            bool(is_sparse)))
    layer = _layer_cache.get(key)
    if layer is None:
        layer = Embedding(size[0], size[1], weight_attr=param_attr,
                          sparse=is_sparse)
        _layer_cache[key] = layer
    return layer(input)


def dropout(x, dropout_prob, is_test=False,
            dropout_implementation="downgrade_in_infer"):
    mode = ("upscale_in_train"
            if dropout_implementation == "upscale_in_train"
            else "downscale_in_infer")
    return nn_ops.dropout(x, p=dropout_prob, training=not is_test,
                          mode=mode)


def accuracy(input, label, k=1):
    from ..metric import accuracy as _acc
    return _acc(input, label, k=k)


# ---- round-3 surface widening (reference: fluid/layers/nn.py __all__) -----
# Functional names forward to the modern ops with fluid's signatures
# (`dim` instead of `axis`, elementwise_* with the broadcast `axis` arg,
# pool2d with pool_type strings). Parameter-creating layer functions
# (conv2d, batch_norm, ...) reuse the _reuse_key machinery fc uses.

def _paddle():
    import paddle_tpu as _p
    return _p


# -- reductions / logic ------------------------------------------------------

def reduce_min(input, dim=None, keep_dim=False, name=None):  # noqa: A002
    return _paddle().min(input, axis=dim, keepdim=keep_dim)


def reduce_prod(input, dim=None, keep_dim=False, name=None):  # noqa: A002
    return _paddle().prod(input, axis=dim, keepdim=keep_dim)


def reduce_all(input, dim=None, keep_dim=False, name=None):  # noqa: A002
    return _paddle().all(input, axis=dim, keepdim=keep_dim)


def reduce_any(input, dim=None, keep_dim=False, name=None):  # noqa: A002
    return _paddle().any(input, axis=dim, keepdim=keep_dim)


def logical_and(x, y, out=None, name=None):
    return _paddle().logical_and(x, y)


def logical_or(x, y, out=None, name=None):
    return _paddle().logical_or(x, y)


def logical_xor(x, y, out=None, name=None):
    return _paddle().logical_xor(x, y)


def logical_not(x, out=None, name=None):
    return _paddle().logical_not(x)


# -- elementwise with fluid's broadcast `axis` -------------------------------

def _ew(fn, x, y, axis):
    if axis != -1 and hasattr(y, "ndim") and y.ndim < x.ndim:
        # fluid semantics: y's dims align with x starting at `axis`
        from ..ops import manipulation
        for _ in _builtin_range(x.ndim - axis - y.ndim):
            y = manipulation.unsqueeze(y, -1)
    return fn(x, y)


def elementwise_add(x, y, axis=-1, act=None, name=None):
    return _apply_act(_ew(_paddle().add, x, y, axis), act)


def elementwise_sub(x, y, axis=-1, act=None, name=None):
    return _apply_act(_ew(_paddle().subtract, x, y, axis), act)


def elementwise_mul(x, y, axis=-1, act=None, name=None):
    return _apply_act(_ew(_paddle().multiply, x, y, axis), act)


def elementwise_div(x, y, axis=-1, act=None, name=None):
    return _apply_act(_ew(_paddle().divide, x, y, axis), act)


def elementwise_max(x, y, axis=-1, act=None, name=None):
    return _apply_act(_ew(_paddle().maximum, x, y, axis), act)


def elementwise_min(x, y, axis=-1, act=None, name=None):
    return _apply_act(_ew(_paddle().minimum, x, y, axis), act)


def elementwise_pow(x, y, axis=-1, act=None, name=None):
    return _apply_act(_ew(_paddle().pow, x, y, axis), act)


def elementwise_mod(x, y, axis=-1, act=None, name=None):
    return _apply_act(_ew(_paddle().mod, x, y, axis), act)


def elementwise_floordiv(x, y, axis=-1, act=None, name=None):
    return _apply_act(_ew(_paddle().floor_divide, x, y, axis), act)


# -- activations / simple math ----------------------------------------------

def log(x, name=None):
    return _paddle().log(x)


def pow(x, factor=1.0, name=None):  # noqa: A001
    return _paddle().pow(x, factor)


def selu(x, scale=1.0507009873554805, alpha=1.6732632423543772,
         name=None):
    from ..nn import functional as F
    return F.selu(x, scale=scale, alpha=alpha)


def elu(x, alpha=1.0, name=None):
    from ..nn import functional as F
    return F.elu(x, alpha=alpha)


def relu6(x, threshold=6.0, name=None):
    from ..nn import functional as F
    return F.relu6(x)


def leaky_relu(x, alpha=0.02, name=None):
    from ..nn import functional as F
    return F.leaky_relu(x, negative_slope=alpha)


def hard_sigmoid(x, slope=0.2, offset=0.5, name=None):
    return _paddle().clip(x * slope + offset, 0.0, 1.0)


def swish(x, beta=1.0, name=None):
    from ..ops import nn_ops
    return x * nn_ops.sigmoid(x * beta)


def hard_swish(x, threshold=6.0, scale=6.0, offset=3.0, name=None):
    return x * _paddle().clip(x + offset, 0.0, threshold) / scale


def mish(x, name=None):
    from ..nn import functional as F
    return x * _paddle().tanh(F.softplus(x))


def stanh(x, scale_a=0.67, scale_b=1.7159, name=None):
    return scale_b * _paddle().tanh(x * scale_a)


def brelu(x, t_min=0.0, t_max=24.0, name=None):
    return _paddle().clip(x, t_min, t_max)


def soft_relu(x, threshold=40.0, name=None):
    clipped = _paddle().clip(x, -threshold, threshold)
    return _paddle().log(1.0 + _paddle().exp(clipped))


def sign(x, name=None):
    return _paddle().sign(x)


def scale(x, scale=1.0, bias=0.0, bias_after_scale=True,  # noqa: A002
          act=None, name=None):
    out = x * scale + bias if bias_after_scale else (x + bias) * scale
    return _apply_act(out, act)


def clip(x, min, max, name=None):  # noqa: A002
    return _paddle().clip(x, min, max)


def clip_by_norm(x, max_norm, name=None):
    from ..ops import reduction, math as math_ops
    norm = _paddle().sqrt(reduction.sum(math_ops.multiply(x, x)))
    factor = _paddle().minimum(
        _paddle().to_tensor(1.0), max_norm / _paddle().maximum(
            norm, _paddle().to_tensor(1e-12)))
    return x * factor


def mul(x, y, x_num_col_dims=1, y_num_col_dims=1, name=None):
    from ..ops import manipulation, linalg
    import numpy as _np
    xm = manipulation.reshape(
        x, (int(_np.prod(x.shape[:x_num_col_dims])), -1))
    ym = manipulation.reshape(
        y, (int(_np.prod(y.shape[:y_num_col_dims])), -1))
    return linalg.matmul(xm, ym)


# -- shape / manipulation ----------------------------------------------------

def split(input, num_or_sections, dim=-1, name=None):  # noqa: A002
    return _paddle().split(input, num_or_sections, axis=dim)


def squeeze(input, axes=None, name=None):  # noqa: A002
    return _paddle().squeeze(input, axis=axes)


def unsqueeze(input, axes, name=None):  # noqa: A002
    return _paddle().unsqueeze(input, axis=axes)


def flatten(x, axis=1, name=None):
    import numpy as _np
    lead = int(_np.prod(x.shape[:axis])) if axis > 0 else 1
    return _paddle().reshape(x, (lead, -1))


def stack(x, axis=0, name=None):
    return _paddle().stack(x, axis=axis)


def unstack(x, axis=0, num=None, name=None):
    return _paddle().unstack(x, axis=axis, num=num)


def unbind(input, axis=0):  # noqa: A002
    return _paddle().unbind(input, axis=axis)


def expand(x, expand_times, name=None):
    return _paddle().tile(x, expand_times)


def expand_as(x, target_tensor, name=None):
    return _paddle().expand_as(x, target_tensor)


def slice(input, axes, starts, ends):  # noqa: A002
    return _paddle().slice(input, axes, starts, ends)


def strided_slice(input, axes, starts, ends, strides):  # noqa: A002
    return _paddle().strided_slice(input, axes, starts, ends, strides)


def shape(input):  # noqa: A002
    return _paddle().shape(input)


def rank(input):  # noqa: A002
    return _paddle().rank(input)


def size(input):  # noqa: A002
    return _paddle().numel(input)


def gather(input, index, overwrite=True):  # noqa: A002
    return _paddle().gather(input, index)


def gather_nd(input, index, name=None):  # noqa: A002
    return _paddle().gather_nd(input, index)


def scatter(input, index, updates, overwrite=True, name=None):  # noqa: A002
    return _paddle().scatter(input, index, updates, overwrite=overwrite)


def scatter_nd_add(ref, index, updates, name=None):
    return _paddle().scatter_nd_add(ref, index, updates)


def scatter_nd(index, updates, shape, name=None):  # noqa: A002
    return _paddle().scatter_nd(index, updates, shape)


def where(condition):
    return _paddle().nonzero(condition)


def one_hot(input, depth, allow_out_of_range=False):  # noqa: A002
    from ..nn import functional as F
    if input.ndim >= 2 and int(input.shape[-1]) == 1:
        input = input.squeeze(-1)  # fluid replaces the trailing 1-dim
    return F.one_hot(input, depth)


def topk(input, k, name=None):  # noqa: A002
    return _paddle().topk(input, k)


def _unique_appearance(x):
    import numpy as _np
    v = _np.asarray(x.numpy()).reshape(-1)
    sorted_u, first = _np.unique(v, return_index=True)
    order = _np.argsort(first)          # appearance order
    out = sorted_u[order]
    remap = _np.empty(len(sorted_u), _np.int64)
    remap[order] = _np.arange(len(sorted_u))
    inv_sorted = _np.searchsorted(sorted_u, v)
    inverse = remap[inv_sorted]
    counts = _np.bincount(inverse, minlength=len(out))
    return out, inverse, counts


def unique(x, dtype="int32"):
    """fluid semantics: appearance-order uniques + a len(x) index
    mapping every input element into `out`."""
    out, inverse, _ = _unique_appearance(x)
    T = _paddle().to_tensor
    import numpy as _np
    return T(out), T(inverse.astype(_np.dtype(dtype)))


def unique_with_counts(x, dtype="int32"):
    out, inverse, counts = _unique_appearance(x)
    T = _paddle().to_tensor
    import numpy as _np
    return (T(out), T(inverse.astype(_np.dtype(dtype))),
            T(counts.astype(_np.int64)))


def pad(x, paddings, pad_value=0.0, name=None):
    from ..nn import functional as F
    return F.pad(x, paddings, value=pad_value)


def pad2d(input, paddings=(0, 0, 0, 0), mode="constant",  # noqa: A002
          pad_value=0.0, data_format="NCHW", name=None):
    from ..nn import functional as F
    t, b, l, r = paddings  # fluid order: top/bottom/left/right
    return F.pad(input, [l, r, t, b], mode=mode.replace(
        "edge", "replicate"), value=pad_value, data_format=data_format)


def pad_constant_like(x, y, pad_value=0.0, name=None):
    import numpy as _np
    pads = []
    for xa, ya in zip(x.shape, y.shape):
        pads += [0, int(xa - ya)]
    import jax.numpy as _jnp
    arr = _jnp.pad(_paddle().to_tensor(y).value if not isinstance(
        y, Tensor) else y.value,
        [(p0, p1) for p0, p1 in zip(pads[::2], pads[1::2])],
        constant_values=pad_value)
    return Tensor(arr)


def crop_tensor(x, shape=None, offsets=None, name=None):  # noqa: A002
    offs = offsets or [0] * len(shape)
    from ..ops import manipulation
    return manipulation.slice(
        x, list(range(len(shape))), offs,
        [o + s for o, s in zip(offs, shape)])


crop = crop_tensor


def shard_index(input, index_num, nshards, shard_id,  # noqa: A002
                ignore_value=-1):
    return _paddle().shard_index(input, index_num, nshards, shard_id,
                                 ignore_value)


def sum(x):  # noqa: A001
    """fluid.layers.sum IS add_n: elementwise sum of the inputs (a lone
    tensor passes through unchanged — NOT a reduction)."""
    if isinstance(x, (list, tuple)):
        out = x[0]
        for t in x[1:]:
            out = out + t
        return out
    return x


# -- normalization / similarity ---------------------------------------------

def l2_normalize(x, axis, epsilon=1e-12, name=None):
    from ..nn import functional as F
    return F.normalize(x, axis=axis, epsilon=epsilon)


def cos_sim(X, Y):
    from ..nn import functional as F
    return F.cosine_similarity(X, Y, axis=-1).unsqueeze(-1)


def lrn(input, n=5, k=1.0, alpha=1e-4, beta=0.75, name=None,  # noqa: A002
        data_format="NCHW"):
    from ..ops import nn_ops
    return nn_ops.local_response_norm(input, n, alpha, beta, k)


def smooth_l1(x, y, inside_weight=None, outside_weight=None, sigma=None):
    from ..nn import functional as F
    return F.smooth_l1_loss(x, y, reduction="none",
                            delta=1.0 / ((sigma or 1.0) ** 2)) \
        .sum(axis=-1, keepdim=True)


def label_smooth(label, prior_dist=None, epsilon=0.1, dtype="float32",
                 name=None):
    return _paddle().nn.functional.label_smooth(
        label, prior_dist=prior_dist, epsilon=epsilon)


def log_loss(input, label, epsilon=1e-4, name=None):  # noqa: A002
    from ..nn import functional as F
    return F.log_loss(input, label, epsilon)


def dice_loss(input, label, epsilon=1e-5):  # noqa: A002
    from ..nn import functional as F
    return F.dice_loss(input, label, epsilon)


def mean_iou(input, label, num_classes):  # noqa: A002
    from ..metric import mean_iou as _miou
    return _miou(input, label, num_classes)


# -- vision-ish --------------------------------------------------------------

def image_resize(input, out_shape=None, scale=None,  # noqa: A002
                 name=None, resample="BILINEAR", actual_shape=None,
                 align_corners=True, align_mode=1, data_format="NCHW"):
    from ..nn import functional as F
    mode = {"BILINEAR": "bilinear", "NEAREST": "nearest",
            "TRILINEAR": "trilinear", "LINEAR": "linear",
            "BICUBIC": "bicubic"}[resample.upper()]
    return F.interpolate(input, size=out_shape, scale_factor=scale,
                         mode=mode, align_corners=bool(align_corners))


def resize_bilinear(input, out_shape=None, scale=None, name=None,  # noqa: A002
                    actual_shape=None, align_corners=True, align_mode=1,
                    data_format="NCHW"):
    return image_resize(input, out_shape, scale, name, "BILINEAR",
                        align_corners=align_corners)


def resize_nearest(input, out_shape=None, scale=None, name=None,  # noqa: A002
                   actual_shape=None, align_corners=True,
                   data_format="NCHW"):
    return image_resize(input, out_shape, scale, name, "NEAREST",
                        align_corners=align_corners)


def resize_trilinear(input, out_shape=None, scale=None, name=None,  # noqa: A002
                     actual_shape=None, align_corners=True, align_mode=1,
                     data_format="NCDHW"):
    return image_resize(input, out_shape, scale, name, "TRILINEAR",
                        align_corners=align_corners)


def resize_linear(input, out_shape=None, scale=None, name=None,  # noqa: A002
                  actual_shape=None, align_corners=True, align_mode=1,
                  data_format="NCW"):
    return image_resize(input, out_shape, scale, name, "LINEAR",
                        align_corners=align_corners)


def image_resize_short(input, out_short_len, resample="BILINEAR"):  # noqa: A002
    h, w = input.shape[2], input.shape[3]
    short, other = (h, w) if h < w else (w, h)
    ratio = out_short_len / float(short)
    out = (int(round(h * ratio)), int(round(w * ratio)))
    return image_resize(input, out_shape=out, resample=resample)


def roi_align(input, rois, pooled_height=1, pooled_width=1,  # noqa: A002
              spatial_scale=1.0, sampling_ratio=-1, name=None,
              rois_num=None):
    from ..vision.ops import roi_align as _ra
    return _ra(input, rois, rois_num=rois_num,
               output_size=(pooled_height, pooled_width),
               spatial_scale=spatial_scale,
               sampling_ratio=sampling_ratio)


def roi_pool(input, rois, pooled_height=1, pooled_width=1,  # noqa: A002
             spatial_scale=1.0, rois_num=None, name=None):
    # max-pool RoI: reference roi_pool_op; expressed via roi_align with
    # aligned sampling (close TPU-native analogue; exact argmax pooling
    # needs dynamic windows XLA can't tile)
    return roi_align(input, rois, pooled_height, pooled_width,
                     spatial_scale, rois_num=rois_num)


def grid_sampler(x, grid, name=None):
    from ..nn import functional as F
    return F.grid_sample(x, grid)


def affine_grid(theta, out_shape, name=None):
    from ..nn import functional as F
    return F.affine_grid(theta, out_shape)


def affine_channel(x, scale=None, bias=None, data_format="NCHW",
                   act=None, name=None):
    s = scale.reshape((1, -1, 1, 1)) if scale is not None else 1.0
    b = bias.reshape((1, -1, 1, 1)) if bias is not None else 0.0
    return _apply_act(x * s + b, act)


def pixel_shuffle(x, upscale_factor):
    from ..nn import functional as F
    return F.pixel_shuffle(x, upscale_factor)


def space_to_depth(x, blocksize, name=None):
    n, c, h, w = x.shape
    bs = int(blocksize)
    out = _paddle().reshape(x, (n, c, h // bs, bs, w // bs, bs))
    out = _paddle().transpose(out, (0, 3, 5, 1, 2, 4))
    return _paddle().reshape(out, (n, c * bs * bs, h // bs, w // bs))


def shuffle_channel(x, group, name=None):
    n, c, h, w = x.shape
    out = _paddle().reshape(x, (n, group, c // group, h, w))
    out = _paddle().transpose(out, (0, 2, 1, 3, 4))
    return _paddle().reshape(out, (n, c, h, w))


from ..core.dispatch import register_op as _register_op


@_register_op("temporal_shift")
def _temporal_shift_op(x, *, seg_num, shift_ratio):
    import jax.numpy as _jnp
    nt, c, h, w = x.shape
    n = nt // seg_num
    v = x.reshape(n, seg_num, c, h, w)
    fold = int(c * shift_ratio)
    left = _jnp.roll(v[:, :, :fold], -1, axis=1).at[:, -1, :].set(0.0)
    right = _jnp.roll(v[:, :, fold:2 * fold], 1, axis=1) \
        .at[:, 0, :].set(0.0)
    out = _jnp.concatenate([left, right, v[:, :, 2 * fold:]], axis=2)
    return out.reshape(nt, c, h, w)


def temporal_shift(x, seg_num, shift_ratio=0.25, name=None,
                   data_format="NCHW"):
    return _temporal_shift_op(x, seg_num=int(seg_num),
                              shift_ratio=float(shift_ratio))


def maxout(x, groups, name=None, axis=1):
    n, c, h, w = x.shape
    out = _paddle().reshape(x, (n, c // groups, groups, h, w))
    return _paddle().max(out, axis=2)


@_register_op("fsp_matrix")
def _fsp_op(x, y):
    import jax.numpy as _jnp
    n, cx, h, w = x.shape
    cy = y.shape[1]
    xf = x.reshape(n, cx, h * w)
    yf = y.reshape(n, cy, h * w)
    return _jnp.einsum("nch,ndh->ncd", xf, yf) / (h * w)


def fsp_matrix(x, y):
    return _fsp_op(x, y)


@_register_op("add_position_encoding")
def _ape_op(x, *, alpha, beta):
    import jax.numpy as _jnp
    b, t, c = x.shape
    half = c // 2
    pos = _jnp.arange(t, dtype=_jnp.float32)[:, None]
    div = _jnp.power(10000.0, _jnp.arange(half, dtype=_jnp.float32)
                     / half)
    pe = _jnp.concatenate(
        [_jnp.sin(pos / div), _jnp.cos(pos / div)], axis=1)
    return alpha * x + beta * pe[None, :, :c].astype(x.dtype)


def add_position_encoding(input, alpha, beta, name=None):  # noqa: A002
    return _ape_op(input, alpha=float(alpha), beta=float(beta))


def unfold(x, kernel_sizes, strides=1, paddings=0, dilations=1,
           name=None):
    from ..nn import functional as F
    return F.unfold(x, kernel_sizes, strides, paddings, dilations)


@_register_op("multiplex")
def _multiplex_op(index, *inputs):
    import jax.numpy as _jnp
    stacked = _jnp.stack(inputs, axis=0)
    rows = _jnp.arange(stacked.shape[1])
    return stacked[index.reshape(-1), rows]


def multiplex(inputs, index):
    return _multiplex_op(index, *inputs)


def deformable_conv(input, offset, mask, num_filters,  # noqa: A002
                    filter_size, stride=1, padding=0, dilation=1,
                    groups=1, deformable_groups=1, im2col_step=1,
                    param_attr=None, bias_attr=None,
                    modulated=True, name=None):
    from ..vision.ops import deform_conv2d
    key = _reuse_key(name, ("deformable_conv", int(input.shape[1]),
                            num_filters, filter_size))
    w = _layer_cache.get(key)
    if w is None:
        from ..nn import initializer as init_mod
        import jax.numpy as _jnp
        ks = filter_size if isinstance(filter_size, (list, tuple)) \
            else (filter_size, filter_size)
        from ..core.tensor import Parameter
        w = Parameter(init_mod.XavierNormal()(
            (num_filters, int(input.shape[1]) // groups, ks[0], ks[1]),
            _jnp.float32))
        _layer_cache[key] = w
    return deform_conv2d(input, offset, w, mask=mask, stride=stride,
                         padding=padding, dilation=dilation,
                         deformable_groups=deformable_groups,
                         groups=groups)


# -- random ------------------------------------------------------------------

def uniform_random(shape, dtype="float32", min=-1.0, max=1.0,  # noqa: A002
                   seed=0, name=None):
    return _paddle().uniform(shape, dtype, min=min, max=max, seed=seed)


def gaussian_random(shape, mean=0.0, std=1.0, seed=0, dtype="float32",
                    name=None):
    return _paddle().normal(mean=mean, std=std, shape=shape)


def uniform_random_batch_size_like(input, shape, dtype="float32",  # noqa: A002
                                   input_dim_idx=0, output_dim_idx=0,
                                   min=-1.0, max=1.0, seed=0):
    shape = list(shape)
    shape[output_dim_idx] = input.shape[input_dim_idx]
    return uniform_random(shape, dtype, min, max, seed)


def gaussian_random_batch_size_like(input, shape, input_dim_idx=0,  # noqa: A002
                                    output_dim_idx=0, mean=0.0, std=1.0,
                                    seed=0, dtype="float32"):
    shape = list(shape)
    shape[output_dim_idx] = input.shape[input_dim_idx]
    return gaussian_random(shape, mean, std, seed, dtype)


def sampling_id(x, min=0.0, max=1.0, seed=0, dtype="float32"):  # noqa: A002
    return _paddle().multinomial(x, num_samples=1).squeeze(-1)


def random_crop(x, shape, seed=None):  # noqa: A002
    import numpy as _np
    starts = [int(_np.random.randint(0, int(xd) - int(sd) + 1))
              for xd, sd in zip(x.shape[-len(shape):], shape)]
    axes = list(range(x.ndim - len(shape), x.ndim))
    ends = [st + int(sd) for st, sd in zip(starts, shape)]
    from ..ops import manipulation
    return manipulation.slice(x, axes, starts, ends)


# -- sequence / CRF ----------------------------------------------------------

def linear_chain_crf(input, label, param_attr=None, length=None):  # noqa: A002
    """Reference: fluid/layers/nn.py linear_chain_crf — creates the
    [C+2, C] transition parameter and returns per-sequence nll."""
    from ..ops import sequence as seq_ops
    from ..core.tensor import Parameter
    import jax.numpy as _jnp
    c = int(input.shape[-1])
    # shared by design between linear_chain_crf and crf_decoding: key on
    # (name, class-count), never the call stack
    key = ("crf_transition", getattr(param_attr, "name", param_attr), c)
    trans = _layer_cache.get(key)
    if trans is None:
        from ..nn import initializer as init_mod
        trans = Parameter(init_mod.Normal(0.0, 0.1)((c + 2, c),
                                                    _jnp.float32))
        _layer_cache[key] = trans
    if length is None:
        length = _paddle().full([int(input.shape[0])], input.shape[1],
                                "int64")
    if label.ndim == 3:
        label = label.squeeze(-1)
    return seq_ops.linear_chain_crf(input, trans, label, length), trans


def crf_decoding(input, param_attr=None, label=None, length=None):  # noqa: A002
    from ..ops import sequence as seq_ops
    c = int(input.shape[-1])
    key = ("crf_transition", getattr(param_attr, "name", param_attr), c)
    trans = _layer_cache.get(key)
    if trans is None:
        raise ValueError("crf_decoding: no trained transition found — "
                         "call linear_chain_crf first or pass a shared "
                         "param_attr name")
    if length is None:
        length = _paddle().full([int(input.shape[0])], input.shape[1],
                                "int64")
    return seq_ops.crf_decoding(input, trans, length)


def ctc_greedy_decoder(input, blank, input_length=None,  # noqa: A002
                       padding_value=0, name=None):
    """Best-path CTC decode: argmax, merge repeats, drop blanks
    (reference: ctc_align_op)."""
    import numpy as _np
    probs = _np.asarray(input.numpy())
    ids = probs.argmax(-1)
    b, t = ids.shape
    lens = (_np.asarray(input_length.numpy()).reshape(-1)
            if input_length is not None else _np.full(b, t))
    outs = _np.full((b, t), padding_value, _np.int64)
    out_lens = _np.zeros(b, _np.int64)
    for i in _builtin_range(b):
        prev = -1
        k = 0
        for j in _builtin_range(int(lens[i])):
            tok = int(ids[i, j])
            if tok != blank and tok != prev:
                outs[i, k] = tok
                k += 1
            prev = tok
        out_lens[i] = k
    return _paddle().to_tensor(outs), _paddle().to_tensor(out_lens)


def chunk_eval(input, label, chunk_scheme, num_chunk_types,  # noqa: A002
               excluded_chunk_types=None, seq_length=None):
    """IOB/IOE/IOBES chunk P/R/F1 (reference: chunk_eval_op). Host-side
    metric (no gradient)."""
    import numpy as _np

    def _chunks(tags):
        # tag encoding: tag = chunk_type * tag_num + pos; O is any tag
        # outside the range. Positions per scheme (chunk_eval_op.h):
        # IOB: B=0 I=1; IOE: I=0 E=1; IOBES: B=0 I=1 E=2 S=3; plain: 0.
        spans = []
        tag_num = {"IOB": 2, "IOE": 2, "IOBES": 4, "plain": 1}[
            chunk_scheme]
        start = ctype = None
        for i, t in enumerate(list(tags) + [-1]):
            if t < 0 or t >= num_chunk_types * tag_num:
                ty, pos = None, None
            else:
                ty, pos = divmod(int(t), tag_num)
            # does this tag CONTINUE an open chunk of ctype?
            if start is not None:
                cont = (ty == ctype) and (
                    (chunk_scheme == "IOB" and pos == 1)
                    or (chunk_scheme == "IOE" and pos in (0, 1))
                    or (chunk_scheme == "IOBES" and pos in (1, 2))
                    or chunk_scheme == "plain")
                if not cont:
                    spans.append((start, i - 1, ctype))
                    start = ctype = None
            if ty is not None and start is None:
                start, ctype = i, ty
            # immediate enders close INCLUDING this position
            if start is not None and (
                    (chunk_scheme == "IOE" and pos == 1)
                    or (chunk_scheme == "IOBES" and pos in (2, 3))):
                spans.append((start, i, ctype))
                start = ctype = None
        if excluded_chunk_types:
            spans = [s for s in spans if s[2] not in excluded_chunk_types]
        return set(spans)

    inf = _np.asarray(input.numpy()).reshape(input.shape[0], -1)
    lab = _np.asarray(label.numpy()).reshape(label.shape[0], -1)
    lens = (_np.asarray(seq_length.numpy()).reshape(-1)
            if seq_length is not None
            else _np.full(inf.shape[0], inf.shape[1]))
    n_inf = n_lab = n_correct = 0
    for i in _builtin_range(inf.shape[0]):
        ci = _chunks(inf[i, :int(lens[i])])
        cl = _chunks(lab[i, :int(lens[i])])
        n_inf += len(ci)
        n_lab += len(cl)
        n_correct += len(ci & cl)
    p = n_correct / n_inf if n_inf else 0.0
    r = n_correct / n_lab if n_lab else 0.0
    f1 = 2 * p * r / (p + r) if p + r else 0.0
    T = _paddle().to_tensor
    return (T(_np.float32(p)), T(_np.float32(r)), T(_np.float32(f1)),
            T(_np.int64(n_inf)), T(_np.int64(n_lab)),
            T(_np.int64(n_correct)))


# -- parameter-creating layer functions (fc-style _reuse_key reuse) ----------

def _cached_layer(name, config, build):
    key = _reuse_key(name, config)
    layer = _layer_cache.get(key)
    if layer is None:
        layer = build()
        _layer_cache[key] = layer
    return layer


def conv2d(input, num_filters, filter_size, stride=1, padding=0,  # noqa: A002
           dilation=1, groups=1, param_attr=None, bias_attr=None,
           use_cudnn=True, act=None, name=None, data_format="NCHW"):
    from ..nn.layer.conv import Conv2D
    cin = int(input.shape[1])
    layer = _cached_layer(name, ("conv2d", cin, num_filters,
                                 str(filter_size), str(stride),
                                 str(padding), str(dilation), groups),
                          lambda: Conv2D(cin, num_filters, filter_size,
                                         stride=stride, padding=padding,
                                         dilation=dilation, groups=groups,
                                         bias_attr=bias_attr))
    return _apply_act(layer(input), act)


def conv3d(input, num_filters, filter_size, stride=1, padding=0,  # noqa: A002
           dilation=1, groups=1, param_attr=None, bias_attr=None,
           use_cudnn=True, act=None, name=None, data_format="NCDHW"):
    from ..nn.layer.conv import Conv3D
    cin = int(input.shape[1])
    layer = _cached_layer(name, ("conv3d", cin, num_filters,
                                 str(filter_size), str(stride),
                                 str(padding), str(dilation), groups),
                          lambda: Conv3D(cin, num_filters, filter_size,
                                         stride=stride, padding=padding,
                                         dilation=dilation, groups=groups,
                                         bias_attr=bias_attr))
    return _apply_act(layer(input), act)


def conv2d_transpose(input, num_filters, output_size=None,  # noqa: A002
                     filter_size=None, padding=0, stride=1, dilation=1,
                     groups=1, param_attr=None, bias_attr=None,
                     use_cudnn=True, act=None, name=None,
                     data_format="NCHW"):
    from ..nn.layer.conv import Conv2DTranspose
    cin = int(input.shape[1])
    layer = _cached_layer(name, ("conv2dT", cin, num_filters,
                                 str(filter_size), str(stride),
                                 str(padding), groups),
                          lambda: Conv2DTranspose(
                              cin, num_filters, filter_size,
                              stride=stride, padding=padding,
                              groups=groups, bias_attr=bias_attr))
    return _apply_act(layer(input), act)


def conv3d_transpose(input, num_filters, output_size=None,  # noqa: A002
                     filter_size=None, padding=0, stride=1, dilation=1,
                     groups=1, param_attr=None, bias_attr=None,
                     use_cudnn=True, act=None, name=None,
                     data_format="NCDHW"):
    from ..nn.layer.conv import Conv3DTranspose
    cin = int(input.shape[1])
    layer = _cached_layer(name, ("conv3dT", cin, num_filters,
                                 str(filter_size), str(stride),
                                 str(padding), groups),
                          lambda: Conv3DTranspose(
                              cin, num_filters, filter_size,
                              stride=stride, padding=padding,
                              groups=groups, bias_attr=bias_attr))
    return _apply_act(layer(input), act)


def batch_norm(input, act=None, is_test=False, momentum=0.9,  # noqa: A002
               epsilon=1e-5, param_attr=None, bias_attr=None,
               data_layout="NCHW", in_place=False, name=None,
               moving_mean_name=None, moving_variance_name=None,
               do_model_average_for_mean_and_var=True,
               use_global_stats=False):
    from ..nn.layer.norm import BatchNorm2D, BatchNorm1D, BatchNorm3D
    c = int(input.shape[1])
    cls = {2: BatchNorm1D, 3: BatchNorm1D, 4: BatchNorm2D,
           5: BatchNorm3D}[input.ndim]
    layer = _cached_layer(name, ("bn", c, input.ndim),
                          lambda: cls(c, momentum=momentum,
                                      epsilon=epsilon))
    layer.training = not is_test
    return _apply_act(layer(input), act)


def inplace_abn(input, act=None, **kwargs):  # noqa: A002
    # activated batch norm; in-place-ness is an allocator detail the
    # functional runtime absorbs
    return batch_norm(input, act=act or "leaky_relu", **kwargs)


def instance_norm(input, epsilon=1e-5, param_attr=None,  # noqa: A002
                  bias_attr=None, name=None):
    from ..nn.layer.norm import InstanceNorm2D
    c = int(input.shape[1])
    layer = _cached_layer(name, ("in", c),
                          lambda: InstanceNorm2D(c, epsilon=epsilon))
    return layer(input)


def layer_norm(input, scale=True, shift=True,  # noqa: A002
               begin_norm_axis=1, epsilon=1e-5, param_attr=None,
               bias_attr=None, act=None, name=None):
    from ..nn.layer.norm import LayerNorm
    shape = tuple(int(s) for s in input.shape[begin_norm_axis:])
    layer = _cached_layer(name, ("ln", shape),
                          lambda: LayerNorm(list(shape),
                                            epsilon=epsilon))
    return _apply_act(layer(input), act)


def group_norm(input, groups, epsilon=1e-5, param_attr=None,  # noqa: A002
               bias_attr=None, act=None, data_layout="NCHW", name=None):
    from ..nn.layer.norm import GroupNorm
    c = int(input.shape[1])
    layer = _cached_layer(name, ("gn", c, groups),
                          lambda: GroupNorm(groups, c, epsilon=epsilon))
    return _apply_act(layer(input), act)


def spectral_norm(weight, dim=0, power_iters=1, eps=1e-12, name=None):
    from ..nn.layer.norm import SpectralNorm
    layer = _cached_layer(name, ("sn", tuple(weight.shape), dim),
                          lambda: SpectralNorm(weight.shape, dim=dim,
                                               power_iters=power_iters,
                                               eps=eps))
    return layer(weight)


def prelu(x, mode="all", param_attr=None, name=None):
    from ..core.tensor import Parameter
    from ..nn import functional as F
    import jax.numpy as _jnp
    n = {"all": 1, "channel": int(x.shape[1]),
         "element": int(np.prod(x.shape[1:]))}[mode]
    w = _cached_layer(getattr(param_attr, "name", None) or name,
                      ("prelu", mode, n),
                      lambda: Parameter(_jnp.full((n,), 0.25,
                                                  _jnp.float32)))
    if mode == "channel":
        wv = w.reshape((1, -1) + (1,) * (x.ndim - 2))
    elif mode == "element":
        wv = w.reshape((1,) + tuple(x.shape[1:]))
    else:
        wv = w
    return _paddle().maximum(x, x * 0.0) + wv * _paddle().minimum(
        x, x * 0.0)


def bilinear_tensor_product(x, y, size, act=None, name=None,
                            param_attr=None, bias_attr=None):
    from ..core.tensor import Parameter
    import jax.numpy as _jnp
    dx, dy = int(x.shape[-1]), int(y.shape[-1])
    from ..nn import initializer as init_mod
    w = _cached_layer(name, ("bilinear", dx, dy, size),
                      lambda: Parameter(init_mod.XavierNormal()(
                          (size, dx, dy), _jnp.float32)))
    from ..ops import linalg, manipulation
    # out[b, k] = x[b] @ W[k] @ y[b]: Wy = [size*dx, dy] @ y^T ->
    # [size, dx, B] -> [B, size, dx], then row-dot with x
    wy = linalg.matmul(manipulation.reshape(w, (size * dx, dy)),
                       manipulation.transpose(y, (1, 0)))
    wy = manipulation.transpose(
        manipulation.reshape(wy, (size, dx, -1)), (2, 0, 1))
    out = linalg.matmul(wy, manipulation.unsqueeze(x, -1))
    return _apply_act(manipulation.reshape(out, (-1, size)), act)


# -- pooling (fluid signatures) ----------------------------------------------

def pool2d(input, pool_size=-1, pool_type="max", pool_stride=1,  # noqa: A002
           pool_padding=0, global_pooling=False, use_cudnn=True,
           ceil_mode=False, name=None, exclusive=True,
           data_format="NCHW"):
    from ..nn import functional as F
    if global_pooling:
        return (F.adaptive_max_pool2d(input, 1) if pool_type == "max"
                else F.adaptive_avg_pool2d(input, 1))
    if pool_type == "max":
        return F.max_pool2d(input, pool_size, pool_stride, pool_padding,
                            ceil_mode=ceil_mode)
    return F.avg_pool2d(input, pool_size, pool_stride, pool_padding,
                        ceil_mode=ceil_mode, exclusive=exclusive)


def pool3d(input, pool_size=-1, pool_type="max", pool_stride=1,  # noqa: A002
           pool_padding=0, global_pooling=False, use_cudnn=True,
           ceil_mode=False, name=None, exclusive=True,
           data_format="NCDHW"):
    from ..nn import functional as F
    if global_pooling:
        return adaptive_pool3d(input, 1, pool_type)
    if pool_type == "max":
        return F.max_pool3d(input, pool_size, pool_stride, pool_padding,
                            ceil_mode=ceil_mode)
    return F.avg_pool3d(input, pool_size, pool_stride, pool_padding,
                        ceil_mode=ceil_mode, exclusive=exclusive)


def adaptive_pool2d(input, pool_size, pool_type="max",  # noqa: A002
                    require_index=False, name=None):
    from ..nn import functional as F
    if pool_type == "max":
        return F.adaptive_max_pool2d(input, pool_size,
                                     return_mask=require_index)
    return F.adaptive_avg_pool2d(input, pool_size)


def adaptive_pool3d(input, pool_size, pool_type="max",  # noqa: A002
                    require_index=False, name=None):
    from ..nn import functional as F
    if pool_type == "max":
        return F.adaptive_max_pool3d(input, pool_size,
                                     return_mask=require_index)
    return F.adaptive_avg_pool3d(input, pool_size)


# -- misc --------------------------------------------------------------------

_step_counters = {}


def autoincreased_step_counter(counter_name=None, begin=1, step=1):
    """Reference: a persistable int64 counter incremented per call."""
    key = counter_name or "@STEP_COUNTER@"
    t = _step_counters.get(key)
    if t is None:
        t = _paddle().to_tensor(np.asarray([begin], "int64"))
        _step_counters[key] = t
    else:
        t.value = (t + step).value
    return t


def lod_reset(x, y=None, target_lod=None):
    from ..core.lod import LoDTensor
    if isinstance(x, LoDTensor):
        x.set_lod([target_lod] if target_lod is not None else y.lod())
        return x
    return x


def lod_append(x, level):
    return x


def py_func(func, x, out, backward_func=None, skip_vars_in_backward_input=None):
    """Reference: py_func_op — host-python op. The eager runtime IS
    python: call through (backward via PyLayer if needed)."""
    xs = x if isinstance(x, (list, tuple)) else [x]
    res = func(*xs)
    return res


def merge_selected_rows(x, name=None):
    from ..core.sparse_grad import IndexedSlices
    if isinstance(x, IndexedSlices):
        return x.coalesce()
    return x


def get_tensor_from_selected_rows(x, name=None):
    from ..core.sparse_grad import IndexedSlices
    if isinstance(x, IndexedSlices):
        return Tensor(x.to_dense())
    return x


def gather_tree(ids, parents):
    """Beam-search path backtrace (reference: gather_tree_op): ids and
    parents are [T, B, beam]; returns the full paths."""
    import numpy as _np
    idv = _np.asarray(ids.numpy())
    pv = _np.asarray(parents.numpy())
    t_max, b, beam = idv.shape
    out = _np.zeros_like(idv)
    out[-1] = idv[-1]
    par = _np.tile(_np.arange(beam)[None, :], (b, 1))
    for t in _builtin_range(t_max - 2, -1, -1):
        par = _np.take_along_axis(pv[t + 1], par, axis=-1)
        out[t] = _np.take_along_axis(idv[t], par, axis=-1)
    return _paddle().to_tensor(out)


def _fluid_unsupported(name, why):
    def stub(*a, **k):
        from ..core.errors import UnimplementedError
        raise UnimplementedError(
            f"fluid.layers.{name}: {why} (explicitly descoped — see "
            "PARITY.md 'Known descopes')")
    stub.__name__ = name
    return stub


# CTR-pipeline / niche kernels intentionally not rebuilt (documented in
# PARITY.md): each names its modern replacement or rationale.
im2sequence = _fluid_unsupported(
    "im2sequence", "use unfold() (im2col) + sequence ops")
row_conv = _fluid_unsupported(
    "row_conv", "lookahead conv for streaming ASR; use causal conv1d")
data_norm = _fluid_unsupported(
    "data_norm", "CTR summary-stat norm; use batch_norm")
similarity_focus = _fluid_unsupported(
    "similarity_focus", "niche attention mask op")
hash = _fluid_unsupported(  # noqa: A001
    "hash", "CTR feature hashing; hash ids host-side")
psroi_pool = _fluid_unsupported(
    "psroi_pool", "position-sensitive RoI; use roi_align")
prroi_pool = _fluid_unsupported(
    "prroi_pool", "precise RoI; use roi_align")
deformable_roi_pooling = _fluid_unsupported(
    "deformable_roi_pooling", "use deform_conv2d + roi_align")
filter_by_instag = _fluid_unsupported(
    "filter_by_instag", "CTR instance-tag filter; filter host-side")
continuous_value_model = _fluid_unsupported(
    "continuous_value_model", "CTR CVM op; preprocess host-side")


# ---- round-3b: remaining fluid.layers submodule surfaces -------------------
# tensor.py / control_flow.py / loss.py / sequence_lod.py / detection.py /
# rnn.py / metric_op.py (reference fluid/layers/*). Aliases keep fluid
# signatures; LoD-taking sequence ops accept the repo's LoDTensor
# (core/lod.py) or (x, lengths) pairs.

# -- tensor.py ---------------------------------------------------------------

def create_tensor(dtype, name=None, persistable=False):
    return _paddle().to_tensor(np.zeros((0,), dtype))


def create_parameter(shape, dtype, name=None, attr=None,
                     is_bias=False, default_initializer=None):
    from ..core.tensor import Parameter
    from ..nn import initializer as init_mod
    import jax.numpy as _jnp
    init = default_initializer or (
        init_mod.Constant(0.0) if is_bias else init_mod.XavierNormal())
    key = _reuse_key(name, ("create_parameter", tuple(shape), dtype))
    p = _layer_cache.get(key)
    if p is None:
        p = Parameter(init(tuple(int(s) for s in shape),
                           _jnp.dtype(dtype)))
        _layer_cache[key] = p
    return p


def create_global_var(shape, value, dtype, persistable=False,
                      force_cpu=False, name=None):
    key = _reuse_key(name, ("global_var", tuple(shape), float(value)))
    t = _layer_cache.get(key)
    if t is None:
        t = _paddle().full(shape, value, dtype)
        t.persistable = persistable
        _layer_cache[key] = t
    return t


def tensor_array_to_tensor(input, axis=1, use_stack=False):  # noqa: A002
    from ..ops import manipulation
    out = (manipulation.stack(list(input), axis=axis) if use_stack
           else manipulation.concat(list(input), axis=axis))
    sizes = _paddle().to_tensor(np.asarray(
        [int(t.shape[axis]) if not use_stack else 1 for t in input],
        "int32"))
    return out, sizes


def sums(input, out=None):  # noqa: A002
    res = sum(list(input))
    if out is not None:
        out.value = res.value
        return out
    return res


def fill_constant_batch_size_like(input, shape, dtype, value,  # noqa: A002
                                  input_dim_idx=0, output_dim_idx=0,
                                  force_cpu=False):
    shape = list(shape)
    shape[output_dim_idx] = int(input.shape[input_dim_idx])
    return _paddle().full(shape, value, dtype)


def argmin(x, axis=0):
    return _paddle().argmin(x, axis=axis)


def argmax(x, axis=0):
    return _paddle().argmax(x, axis=axis)


def argsort(input, axis=-1, descending=False, name=None):  # noqa: A002
    """fluid returns (sorted_values, indices) — in that order."""
    return (_paddle().sort(input, axis=axis, descending=descending),
            _paddle().argsort(input, axis=axis, descending=descending))


def reverse(x, axis):
    return _paddle().flip(x, axis)


def has_inf(x):
    return _paddle().any(_paddle().isinf(x))


def has_nan(x):
    return _paddle().any(_paddle().isnan(x))


def isfinite(x):
    """fluid semantics: ONE bool — are ALL elements finite."""
    return _paddle().all(_paddle().isfinite(x))


def range(start, end, step, dtype, name=None):  # noqa: A001
    return _paddle().arange(start, end, step, dtype)


def linspace(start, stop, num, dtype="float32", name=None):
    return _paddle().linspace(start, stop, num, dtype)


def zeros_like(x, out=None):
    res = _paddle().zeros_like(x)
    if out is not None:
        out.value = res.value
        return out
    return res


def ones_like(x, out=None):
    res = _paddle().ones_like(x)
    if out is not None:
        out.value = res.value
        return out
    return res


def diag(diagonal):
    return _paddle().diag(diagonal)


def eye(num_rows, num_columns=None, batch_shape=None, dtype="float32",
        name=None):
    out = _paddle().eye(num_rows, num_columns, dtype=dtype)
    if batch_shape:
        for _ in batch_shape:
            out = out.unsqueeze(0)
        out = _paddle().tile(out, list(batch_shape) + [1, 1])
    return out


def triu(input, diagonal=0, name=None):  # noqa: A002
    return _paddle().triu(input, diagonal)


# -- control_flow.py ---------------------------------------------------------

def cond(pred, true_fn=None, false_fn=None, name=None):
    from ..static import nn as static_nn
    return static_nn.cond(pred, true_fn, false_fn)


def while_loop(cond_fn, body, loop_vars, is_test=False, name=None):
    from ..static import nn as static_nn
    return static_nn.while_loop(cond_fn, body, loop_vars)


def case(pred_fn_pairs, default=None, name=None):
    from ..static import nn as static_nn
    return static_nn.case(pred_fn_pairs, default)


def switch_case(branch_index, branch_fns, default=None, name=None):
    from ..static import nn as static_nn
    return static_nn.switch_case(branch_index, branch_fns, default)


def increment(x, value=1.0, in_place=True):
    from ..static.program import building_program, Variable as _SVar
    out = x + value
    if not in_place:
        return out
    if isinstance(x, _SVar):
        return building_program().alias(out, x)
    x.value = out.value
    return x


def less_than(x, y, force_cpu=None, cond=None):  # noqa: A002
    return _binop_cond(_paddle().less_than(x, y), cond)


def less_equal(x, y, cond=None):  # noqa: A002
    return _binop_cond(_paddle().less_equal(x, y), cond)


def greater_than(x, y, cond=None):  # noqa: A002
    return _binop_cond(_paddle().greater_than(x, y), cond)


def greater_equal(x, y, cond=None):  # noqa: A002
    return _binop_cond(_paddle().greater_equal(x, y), cond)


def equal(x, y, cond=None):  # noqa: A002
    return _binop_cond(_paddle().equal(x, y), cond)


def not_equal(x, y, cond=None):  # noqa: A002
    return _binop_cond(_paddle().not_equal(x, y), cond)


def _binop_cond(res, cond):
    if cond is None:
        return res
    from ..static.program import building_program, Variable as _SVar
    if isinstance(cond, _SVar):
        # fluid in-place contract inside a While body: cond reads as
        # res from here on (the loop condition update)
        return building_program().alias(res, cond)
    cond.value = res.value
    return cond


def create_array(dtype):
    return []


def array_write(x, i, array=None):
    if array is None:
        array = []
    idx = int(i.numpy()) if hasattr(i, "numpy") else int(i)
    while len(array) <= idx:
        array.append(None)
    array[idx] = x
    return array


def array_read(array, i):
    return array[int(i.numpy()) if hasattr(i, "numpy") else int(i)]


def array_length(array):
    return _paddle().to_tensor(np.asarray([len(array)], "int64"))


def is_empty(x, name=None):
    return _paddle().to_tensor(np.asarray(
        int(np.prod(x.shape)) == 0))


def Print(input, first_n=-1, message=None, summarize=20,  # noqa: A002
          print_tensor_name=True, print_tensor_type=True,
          print_tensor_shape=True, print_tensor_lod=True,
          print_phase="both"):
    vals = np.asarray(input.numpy()).reshape(-1)
    if summarize is not None and summarize >= 0:
        vals = vals[:summarize]
    print(f"{message or 'Print'}: shape={list(input.shape)} "
          f"values={vals}")
    return input


def Assert(cond, data=None, summarize=20, name=None):  # noqa: A002
    if not bool(np.all(np.asarray(cond.numpy()))):
        raise AssertionError(
            f"fluid.layers.Assert failed"
            + ("" if data is None else
               f": {[np.asarray(d.numpy()) for d in data]}"))
    return cond


class While:
    """fluid-1.x While sub-block (reference: control_flow.py:973).

    TPU-native: ops recorded inside ``block()`` become the body of ONE
    ``lax.while_loop``; the loop state is exactly the pre-existing
    variables the body writes through the fluid in-place contract
    (``increment(in_place=True)``, ``less_than(..., cond=cond)``,
    ``assign(..., output=...)``). Requires static mode — the construct
    IS a program-building construct. Reverse-mode AD through a While is
    a lax limitation; train recurrences with StaticRNN (lax.scan).

    Usage (the reference's canonical counter loop)::

        i = layers.fill_constant([1], 'int64', 0)
        n = layers.fill_constant([1], 'int64', 10)
        cond = layers.less_than(i, n)
        w = layers.While(cond)
        with w.block():
            ...body ops...
            i = layers.increment(i, in_place=True)
            layers.less_than(i, n, cond=cond)
    """

    def __init__(self, cond, is_test=False, name=None):
        from ..static.program import building_program, Variable as _SVar
        prog = building_program()
        if prog is None or not isinstance(cond, _SVar):
            raise TypeError(
                "fluid.layers.While requires static mode with a "
                "program-variable cond (paddle.enable_static(), then "
                "build cond via fill_constant/less_than)")
        self._prog = prog
        self._cond = cond

    def block(self):
        return _WhileBlockGuard(self)


class _WhileBlockGuard:
    def __init__(self, w):
        self._w = w

    def __enter__(self):
        self._start = len(self._w._prog.ops)
        self._pre_vars = set(self._w._prog.vars)
        return self

    def __exit__(self, et, ev, tb):
        if et is not None:
            return False
        from ..static.program import (AliasRecord, ConstRecord, OpRecord,
                                      ScanRecord, WhileRecord)
        prog = self._w._prog
        body = prog.ops[self._start:]
        del prog.ops[self._start:]
        # loop carry = variables that exist BEFORE the block and are
        # written inside it (alias targets); names produced inside the
        # body are per-iteration temporaries
        produced, writes = set(), []

        def collect(records):
            for r in records:
                if isinstance(r, OpRecord):
                    produced.update(r.out_names)
                elif isinstance(r, ConstRecord):
                    produced.add(r.name)
                elif isinstance(r, AliasRecord):
                    if r.dst not in writes:
                        writes.append(r.dst)
                elif isinstance(r, WhileRecord):
                    collect(r.body)
                    for n in r.carry_names:
                        if n not in writes:
                            writes.append(n)
                elif isinstance(r, ScanRecord):
                    collect(r.body)

        collect(body)
        # an alias dst FIRST CREATED inside the block (assign's copy
        # variable) is a per-iteration temporary, not loop state — only
        # pre-existing variables can be carried
        carry = [self._w._cond.name] + [n for n in writes
                                        if n not in produced
                                        and n in self._pre_vars
                                        and n != self._w._cond.name]
        prog.ops.append(WhileRecord(self._w._cond.name, body, carry))
        return False


class StaticRNN:
    """fluid-1.x StaticRNN (reference: control_flow.py:451 -> the
    recurrent_op). TPU-native: the step block becomes the body of ONE
    ``lax.scan`` over the sequence axis — memories are the carry, step
    inputs the xs, step outputs stacked ys. scan is reverse-mode
    differentiable, so ``append_backward`` trains through it (the
    book-era PTB/seq-tagging recipes)."""

    def __init__(self, name=None):
        from ..static.program import building_program
        prog = building_program()
        if prog is None:
            raise TypeError(
                "fluid.layers.StaticRNN requires static mode "
                "(paddle.enable_static())")
        self._prog = prog
        self._seq_inputs = []   # (placeholder_name, src_name)
        self._mems = []         # [mem_name, init_spec, new_name]
        self._out_names = []    # body out names
        self._out_meta = []     # (shape, dtype) per output
        self._seq_len = None
        self._out_vars = []
        self._done = False

    def step(self):
        return _RNNStepGuard(self)

    def step_input(self, x):
        shape = x.shape
        if shape[0] in (-1, None):
            raise ValueError(
                "StaticRNN.step_input needs a static sequence length "
                f"(leading dim of {x.name} is dynamic)")
        if self._seq_len is None:
            self._seq_len = int(shape[0])
        elif int(shape[0]) != self._seq_len:
            raise ValueError("StaticRNN step inputs disagree on "
                             "sequence length")
        ph = self._prog.placeholder_var(shape[1:], x._dtype,
                                        "rnn_step_in")
        self._seq_inputs.append((ph.name, x.name))
        return ph

    def memory(self, init=None, shape=None, batch_ref=None,
               init_value=0.0, init_batch_dim_idx=0,
               ref_batch_dim_idx=1):
        import numpy as _np
        from ..core.tensor import Tensor as _T
        if init is not None:
            if isinstance(init, _T):
                self._prog.register_persist(init)
                name, shp, dt = init.name, init.aval_shape(), \
                    init._value.dtype
            else:
                name, shp, dt = init.name, init.shape, init._dtype
            ph = self._prog.placeholder_var(shp, dt, "rnn_mem")
            spec = name
        else:
            if shape is None:
                raise ValueError("StaticRNN.memory needs init= or shape=")
            dt = (batch_ref._dtype if batch_ref is not None
                  else _np.dtype("float32"))
            ph = self._prog.placeholder_var(shape, dt, "rnn_mem")
            spec = ("zeros", tuple(shape), float(init_value),
                    _np.dtype(dt).name)
        self._mems.append([ph.name, spec, None])
        return ph

    def update_memory(self, mem, x):
        for m in self._mems:
            if m[0] == mem.name:
                m[2] = x.name
                return
        raise ValueError(f"{mem.name} is not a memory of this StaticRNN")

    def step_output(self, o):
        self._out_names.append(o.name)
        self._out_meta.append((o.shape, o._dtype))

    def output(self, *outputs):
        for o in outputs:
            self.step_output(o)

    def __call__(self):
        if not self._done:
            raise RuntimeError("call the StaticRNN after its step() "
                               "block closes")
        if len(self._out_vars) == 1:
            return self._out_vars[0]
        return list(self._out_vars)


class _RNNStepGuard:
    def __init__(self, rnn):
        self._rnn = rnn

    def __enter__(self):
        self._start = len(self._rnn._prog.ops)
        return self

    def __exit__(self, et, ev, tb):
        if et is not None:
            return False
        from ..static.program import ScanRecord, Variable as _SVar
        rnn, prog = self._rnn, self._rnn._prog
        body = prog.ops[self._start:]
        del prog.ops[self._start:]
        if not rnn._seq_inputs:
            raise ValueError("StaticRNN needs at least one step_input")
        missing = [m[0] for m in rnn._mems if m[2] is None]
        if missing:
            raise ValueError(
                f"StaticRNN memories never updated: {missing} — call "
                "update_memory(mem, new_value) inside the step block")
        out_pairs = []
        for bname, (shp, dt) in zip(rnn._out_names, rnn._out_meta):
            name = prog._new_name("rnn_out")
            v = _SVar(name, [rnn._seq_len] + list(shp), dt, prog,
                      stop_gradient=False)
            prog.vars[name] = v
            rnn._out_vars.append(v)
            out_pairs.append((bname, name))
        prog.ops.append(ScanRecord(body, list(rnn._seq_inputs),
                                   [tuple(m) for m in rnn._mems],
                                   out_pairs))
        rnn._done = True
        return False


def _program_construct(name):
    def stub(*a, **k):
        from ..core.errors import UnimplementedError
        raise UnimplementedError(
            f"fluid.layers.{name}: fluid-1.x program-construct class; "
            "write python control flow (dy2static) or use "
            "static.nn.cond/while_loop")
    stub.__name__ = name
    return stub


def _descoped_construct(name, reason):
    def stub(*a, **k):
        from ..core.errors import UnimplementedError
        raise UnimplementedError(
            f"fluid.layers.{name} is explicitly descoped on TPU "
            f"(PARITY.md 'Known descopes'): {reason}")
    stub.__name__ = name
    return stub


Switch = _descoped_construct(
    "Switch", "use static.nn.case/switch_case (lax.switch) — same "
    "semantics, compiler-friendly")
IfElse = _descoped_construct(
    "IfElse", "use static.nn.cond (lax.cond) or dy2static if/else")
DynamicRNN = _descoped_construct(
    "DynamicRNN", "LoD-walking dynamic recurrence needs the fluid "
    "interpreter's dynamic shapes; on XLA use StaticRNN over padded "
    "batches (pad + sequence_mask)")
reorder_lod_tensor_by_rank = _descoped_construct(
    "reorder_lod_tensor_by_rank",
    "DynamicRNN's LoD-rank companion; padded batches make it moot")


# -- loss.py -----------------------------------------------------------------

def square_error_cost(input, label):  # noqa: A002
    from ..nn import functional as F
    return F.square_error_cost(input, label)


def mse_loss(input, label):  # noqa: A002
    from ..nn import functional as F
    return F.mse_loss(input, label)


def kldiv_loss(x, target, reduction="mean", name=None):
    from ..nn import functional as F
    return F.kl_div(x, target, reduction=reduction)


def huber_loss(input, label, delta):  # noqa: A002
    diff = _paddle().abs(input - label)
    quad = 0.5 * diff * diff
    lin = delta * diff - 0.5 * delta * delta
    return _paddle().where(diff <= delta, quad, lin)


def sigmoid_cross_entropy_with_logits(x, label, ignore_index=-100,
                                      name=None, normalize=False):
    from ..nn import functional as F
    loss = F.binary_cross_entropy_with_logits(x, label,
                                              reduction="none")
    mask = (label != float(ignore_index)).astype(x.dtype)
    loss = loss * mask
    if normalize:
        loss = loss / _paddle().maximum(
            mask.sum(), _paddle().to_tensor(1.0))
    return loss


def rank_loss(label, left, right, name=None):
    """Reference rank_loss_op: cross entropy of P(left>right) =
    sigmoid(left-right) against the label:
    loss = log(1 + exp(d)) - label * d, d = left - right."""
    d = left - right
    # log(1+exp(d)) computed stably as softplus
    from ..nn import functional as F
    return F.softplus(d) - label * d


def margin_rank_loss(label, left, right, margin=0.1, name=None):
    act = _paddle().maximum(
        -label * (left - right) + margin,
        _paddle().zeros_like(label))
    return act


from ..core.dispatch import register_op as _register_op2


@_register_op2("bpr_loss")
def _bpr_loss_op(logits, label):
    import jax.numpy as _jnp
    lv = label.reshape(-1)
    pos = _jnp.take_along_axis(logits, lv[:, None], axis=-1)
    diff = pos - logits
    n = logits.shape[-1]
    loss = _jnp.logaddexp(0.0, -diff)      # -log sigmoid(diff), stable
    mask = 1.0 - _jnp.eye(n, dtype=logits.dtype)[lv]
    return (loss * mask).sum(-1, keepdims=True) / (n - 1)


def bpr_loss(input, label, name=None):  # noqa: A002
    """Bayesian personalized ranking (reference bpr_loss_op): mean over
    negatives of -log sigmoid(pos_logit - neg_logit); differentiable."""
    return _bpr_loss_op(input, label)


def hsigmoid(input, label, num_classes, param_attr=None,  # noqa: A002
             bias_attr=None, name=None, path_table=None, path_code=None,
             is_custom=False, is_sparse=False):
    from ..nn import functional as F
    from ..core.tensor import Parameter
    import jax.numpy as _jnp
    from ..nn import initializer as init_mod
    d = int(input.shape[-1])
    key = _reuse_key(name, ("hsigmoid", d, num_classes))
    pw = _layer_cache.get(key)
    if pw is None:
        pw = (Parameter(init_mod.XavierNormal()(
            (num_classes - 1, d), _jnp.float32)),
            Parameter(_jnp.zeros((num_classes - 1,), _jnp.float32)))
        _layer_cache[key] = pw
    return F.hsigmoid_loss(input, label, num_classes, pw[0], pw[1],
                           path_table=path_table, path_code=path_code)


def warpctc(input, label, blank=0, norm_by_times=False,  # noqa: A002
            input_length=None, label_length=None):
    from ..nn import functional as F
    return F.ctc_loss(input, label, input_length, label_length,
                      blank=blank, reduction="none")


def edit_distance(input, label, normalized=True,  # noqa: A002
                  ignored_tokens=None, input_length=None,
                  label_length=None):
    """Levenshtein distance per pair (reference edit_distance_op) —
    host-side DP (metric, no gradient)."""
    a = np.asarray(input.numpy())
    b = np.asarray(label.numpy())
    la = (np.asarray(input_length.numpy()).reshape(-1)
          if input_length is not None else np.full(a.shape[0], a.shape[1]))
    lb = (np.asarray(label_length.numpy()).reshape(-1)
          if label_length is not None else np.full(b.shape[0], b.shape[1]))
    outs = np.zeros((a.shape[0], 1), np.float32)
    for i in _builtin_range(a.shape[0]):
        s1 = [t for t in a[i, :int(la[i])]
              if not ignored_tokens or t not in ignored_tokens]
        s2 = [t for t in b[i, :int(lb[i])]
              if not ignored_tokens or t not in ignored_tokens]
        m, n = len(s1), len(s2)
        dp = np.zeros((m + 1, n + 1), np.int64)
        dp[:, 0] = np.arange(m + 1)
        dp[0, :] = np.arange(n + 1)
        for x_ in _builtin_range(1, m + 1):
            for y_ in _builtin_range(1, n + 1):
                dp[x_, y_] = min(dp[x_ - 1, y_] + 1, dp[x_, y_ - 1] + 1,
                                 dp[x_ - 1, y_ - 1]
                                 + (s1[x_ - 1] != s2[y_ - 1]))
        d = float(dp[m, n])
        outs[i, 0] = d / max(n, 1) if normalized else d
    return (_paddle().to_tensor(outs),
            _paddle().to_tensor(np.asarray([a.shape[0]], "int64")))


def center_loss(input, label, num_classes, alpha, param_attr=None,  # noqa: A002
                update_center=True):
    """Reference center_loss_op: 0.5*||x - c_y||^2 per sample; centers
    are a non-gradient buffer updated by the class-mean residual rule
    (grads flow to the input only, as in the reference kernel)."""
    import jax.numpy as _jnp
    from ..core import lazy as _lazy
    d = int(input.shape[-1])
    key = ("center_loss_centers", num_classes, d)
    centers = _layer_cache.get(key)
    if centers is None:
        centers = Tensor(_jnp.zeros((num_classes, d), _jnp.float32),
                         stop_gradient=True)
        _layer_cache[key] = centers
    lv = _lazy.concrete(label.value if isinstance(label, Tensor)
                        else _jnp.asarray(label)).reshape(-1)
    cv = _lazy.concrete(centers.value)
    sel = Tensor(cv[lv])                       # constant wrt autograd
    diff = input - sel
    if update_center:
        dv = _lazy.concrete(diff.value)
        upd = _jnp.zeros_like(cv).at[lv].add(dv)
        cnt = _jnp.zeros((num_classes, 1)).at[lv].add(1.0) + 1.0
        centers.value = cv + alpha * upd / cnt
    return (0.5 * diff * diff).sum(axis=-1, keepdim=True)


_loss_unsupported_names = ("nce", "sampled_softmax_with_cross_entropy",
                           "teacher_student_sigmoid_loss")
nce = _fluid_unsupported(
    "nce", "negative sampling trains fine as full softmax on TPU (MXU); "
    "use softmax_with_cross_entropy")
sampled_softmax_with_cross_entropy = _fluid_unsupported(
    "sampled_softmax_with_cross_entropy",
    "use full softmax_with_cross_entropy (TPU MXU makes it cheap)")
teacher_student_sigmoid_loss = _fluid_unsupported(
    "teacher_student_sigmoid_loss",
    "CTR distillation loss; compose from sigmoid + log ops")


# -- sequence_lod.py ---------------------------------------------------------
# The repo carries ragged data as LoDTensor (dense + offsets,
# core/lod.py) or (padded, lengths) pairs (ops/sequence.py). Wrappers
# accept LoDTensor like the reference's LoD ops.

def _as_padded(x):
    """LoDTensor -> (padded [B, T, ...], lengths); padded Tensor passes
    through with full lengths."""
    from ..core.lod import LoDTensor
    if isinstance(x, LoDTensor):
        padded, lengths = x.to_padded()
        return padded, lengths
    lens = _paddle().full([int(x.shape[0])], int(x.shape[1]), "int64")
    return x, lens


def sequence_pad(x, pad_value, maxlen=None, name=None):
    from ..ops import sequence as seq_ops
    from ..core.lod import LoDTensor
    if isinstance(x, LoDTensor):
        padded, lengths = x.to_padded(pad_value=float(
            pad_value if not hasattr(pad_value, "numpy")
            else pad_value.numpy()))
        return padded, lengths
    return seq_ops.sequence_pad(x, pad_value=pad_value, maxlen=maxlen)


def sequence_unpad(x, length, name=None):
    from ..ops import sequence as seq_ops
    return seq_ops.sequence_unpad(x, length)


def sequence_pool(input, pool_type, is_test=False, pad_value=0.0):  # noqa: A002
    from ..ops import sequence as seq_ops
    padded, lengths = _as_padded(input)
    return seq_ops.sequence_pool(padded, lengths,
                                 pool_type=pool_type.upper())


def sequence_softmax(input, use_cudnn=False, name=None):  # noqa: A002
    from ..ops import sequence as seq_ops
    padded, lengths = _as_padded(input)
    return seq_ops.sequence_softmax(padded, lengths)


def sequence_first_step(input):  # noqa: A002
    padded, lengths = _as_padded(input)
    return padded[:, 0]


def sequence_last_step(input):  # noqa: A002
    from ..ops import manipulation
    padded, lengths = _as_padded(input)
    idx = (lengths - 1).unsqueeze(-1)
    import jax.numpy as _jnp
    from ..core import lazy as _lazy
    pv = _lazy.concrete(padded.value)
    lv = _lazy.concrete(idx.value).reshape(-1)
    return Tensor(pv[_jnp.arange(pv.shape[0]), lv])


def sequence_reverse(x, name=None):
    from ..ops import sequence as seq_ops
    padded, lengths = _as_padded(x)
    return seq_ops.sequence_reverse(padded, lengths)


def sequence_expand(x, y, ref_level=-1, name=None):
    from ..ops import sequence as seq_ops
    _, y_lens = _as_padded(y)
    return seq_ops.sequence_expand(x, y_lens)


def sequence_expand_as(x, y, name=None):
    return sequence_expand(x, y)


def sequence_concat(input, name=None):  # noqa: A002
    from ..ops import manipulation
    return manipulation.concat(list(input), axis=1)


def sequence_mask(x, maxlen=None, dtype="int64", name=None):
    """lengths -> [B, maxlen] 0/1 mask (reference sequence_mask_op);
    delegates to the functional implementation."""
    from ..nn import functional as F
    return F.sequence_mask(x, maxlen=maxlen, dtype=dtype)


def sequence_reshape(input, new_dim):  # noqa: A002
    from ..ops import manipulation
    return manipulation.reshape(input, (int(input.shape[0]), -1,
                                        int(new_dim)))


def sequence_enumerate(input, win_size, pad_value=0, name=None):  # noqa: A002
    """Sliding windows of ids (reference sequence_enumerate_op)."""
    import jax.numpy as _jnp
    from ..core import lazy as _lazy
    v = _lazy.concrete(input.value if isinstance(input, Tensor)
                       else _jnp.asarray(input))
    b, t = v.shape[0], v.shape[1]
    cols = []
    for w in _builtin_range(win_size):
        shifted = _jnp.concatenate(
            [v[:, int(w):],
             _jnp.full((b, int(w)), pad_value, v.dtype)], axis=1)
        cols.append(shifted)
    return Tensor(_jnp.stack(cols, axis=-1))


def sequence_slice(input, offset, length, name=None):  # noqa: A002
    import jax.numpy as _jnp
    from ..core import lazy as _lazy
    v = _lazy.concrete(input.value)
    off = _lazy.concrete(offset.value
                         if hasattr(offset, "value")
                         else _jnp.asarray(offset)).reshape(-1)
    ln = _lazy.concrete(length.value if hasattr(length, "value")
                        else _jnp.asarray(length)).reshape(-1)
    out = np.zeros((v.shape[0], int(ln.max())) + v.shape[2:],
                   np.asarray(v).dtype)
    vn = np.asarray(v)
    for i in _builtin_range(v.shape[0]):
        out[i, :int(ln[i])] = vn[i, int(off[i]):int(off[i]) + int(ln[i])]
    return Tensor(out), Tensor(np.asarray(ln, "int64"))


def sequence_scatter(input, index, updates, name=None):  # noqa: A002
    return _paddle().scatter(input, index, updates, overwrite=False)


def sequence_conv(input, num_filters, filter_size=3,  # noqa: A002
                  filter_stride=1, padding=True, padding_start=None,
                  bias_attr=None, param_attr=None, act=None, name=None):
    """Context-window conv over time (reference sequence_conv_op) —
    conv1d over the padded representation."""
    from ..nn.layer.conv import Conv1D
    padded, lengths = _as_padded(input)
    d = int(padded.shape[-1])
    layer = _cached_layer(name, ("seq_conv", d, num_filters,
                                 filter_size),
                          lambda: Conv1D(d, num_filters, filter_size,
                                         padding=(filter_size - 1) // 2
                                         if padding else 0,
                                         bias_attr=bias_attr))
    from ..ops import manipulation
    x = manipulation.transpose(padded, (0, 2, 1))   # [B, D, T]
    out = layer(x)
    return _apply_act(manipulation.transpose(out, (0, 2, 1)), act)


# -- detection.py ------------------------------------------------------------

def iou_similarity(x, y, box_normalized=True, name=None):
    """IoU matrix [N, M] (reference iou_similarity_op)."""
    import jax.numpy as _jnp
    from ..core import lazy as _lazy
    a = _lazy.concrete(x.value if isinstance(x, Tensor)
                       else _jnp.asarray(x))
    b = _lazy.concrete(y.value if isinstance(y, Tensor)
                       else _jnp.asarray(y))
    off = 0.0 if box_normalized else 1.0
    area_a = (a[:, 2] - a[:, 0] + off) * (a[:, 3] - a[:, 1] + off)
    area_b = (b[:, 2] - b[:, 0] + off) * (b[:, 3] - b[:, 1] + off)
    lt = _jnp.maximum(a[:, None, :2], b[None, :, :2])
    rb = _jnp.minimum(a[:, None, 2:], b[None, :, 2:])
    wh = _jnp.clip(rb - lt + off, 0.0, None)
    inter = wh[..., 0] * wh[..., 1]
    return Tensor(inter / (area_a[:, None] + area_b[None, :] - inter))


def box_clip(input, im_info, name=None):  # noqa: A002
    import jax.numpy as _jnp
    from ..core import lazy as _lazy
    boxes = _lazy.concrete(input.value)
    info = _lazy.concrete(im_info.value)
    h = info[0, 0] / info[0, 2] - 1.0
    w = info[0, 1] / info[0, 2] - 1.0
    out = _jnp.stack([
        _jnp.clip(boxes[..., 0], 0, w), _jnp.clip(boxes[..., 1], 0, h),
        _jnp.clip(boxes[..., 2], 0, w), _jnp.clip(boxes[..., 3], 0, h),
    ], axis=-1)
    return Tensor(out)


def box_coder(prior_box, prior_box_var, target_box,
              code_type="encode_center_size", box_normalized=True,
              name=None, axis=0):
    """Encode/decode boxes against priors (reference box_coder_op)."""
    import jax.numpy as _jnp
    from ..core import lazy as _lazy
    pb = _lazy.concrete(prior_box.value)
    pbv = (_lazy.concrete(prior_box_var.value)
           if hasattr(prior_box_var, "value")
           else _jnp.asarray(prior_box_var, _jnp.float32))
    tb = _lazy.concrete(target_box.value)
    off = 0.0 if box_normalized else 1.0
    pw = pb[:, 2] - pb[:, 0] + off
    ph = pb[:, 3] - pb[:, 1] + off
    px = (pb[:, 2] + pb[:, 0]) / 2
    py = (pb[:, 3] + pb[:, 1]) / 2
    if pbv.ndim == 1:
        pbv = _jnp.broadcast_to(pbv[None, :], (pb.shape[0], 4))
    if code_type == "encode_center_size":
        tw = tb[:, 2] - tb[:, 0] + off
        th = tb[:, 3] - tb[:, 1] + off
        tx = (tb[:, 2] + tb[:, 0]) / 2
        ty = (tb[:, 3] + tb[:, 1]) / 2
        out = _jnp.stack([
            (tx[:, None] - px[None, :]) / pw[None, :],
            (ty[:, None] - py[None, :]) / ph[None, :],
            _jnp.log(tw[:, None] / pw[None, :]),
            _jnp.log(th[:, None] / ph[None, :]),
        ], axis=-1) / pbv[None, :, :]
        return Tensor(out)
    # decode_center_size: target [N, M, 4] deltas against priors
    if axis == 0:
        pwv, phv, pxv, pyv = (pw[None, :, None], ph[None, :, None],
                              px[None, :], py[None, :])
    else:
        pwv, phv, pxv, pyv = (pw[:, None, None], ph[:, None, None],
                              px[:, None], py[:, None])
    if pbv.ndim == 2:
        d = tb * (pbv[None, :, :] if axis == 0 else pbv[:, None, :])
    else:
        d = tb
    dx, dy, dw, dh = d[..., 0], d[..., 1], d[..., 2], d[..., 3]
    cx = dx * pwv[..., 0] + pxv
    cy = dy * phv[..., 0] + pyv
    w = _jnp.exp(dw) * pwv[..., 0]
    h = _jnp.exp(dh) * phv[..., 0]
    out = _jnp.stack([cx - w / 2 + off / 2, cy - h / 2 + off / 2,
                      cx + w / 2 - off / 2, cy + h / 2 - off / 2],
                     axis=-1)
    return Tensor(out)


def sigmoid_focal_loss(x, label, fg_num, gamma=2.0, alpha=0.25):
    from ..nn import functional as F
    from ..ops import math as math_ops
    num = _paddle().cast(fg_num, "float32")
    oh = one_hot(label, int(x.shape[-1]) + 1)
    target = oh[:, 1:] if oh.shape[-1] == int(x.shape[-1]) + 1 else oh
    loss = F.sigmoid_focal_loss(x, target, reduction="none",
                                gamma=gamma, alpha=alpha)
    return math_ops.divide(loss, _paddle().maximum(
        num, _paddle().to_tensor(1.0)))


def yolov3_loss(x, gt_box, gt_label, anchors, anchor_mask, class_num,
                ignore_thresh, downsample_ratio, gt_score=None,
                use_label_smooth=True, name=None, scale_x_y=1.0):
    from ..vision.ops import yolo_loss as _yl
    return _yl(x, gt_box, gt_label, anchors, anchor_mask, class_num,
               ignore_thresh, downsample_ratio, gt_score=gt_score,
               use_label_smooth=use_label_smooth, scale_x_y=scale_x_y)


def yolo_box(x, img_size, anchors, class_num, conf_thresh,
             downsample_ratio, clip_bbox=True, name=None,
             scale_x_y=1.0):
    from ..vision.ops import yolo_box as _yb
    return _yb(x, img_size, anchors, class_num, conf_thresh,
               downsample_ratio, clip_bbox=clip_bbox,
               scale_x_y=scale_x_y)


def multiclass_nms(bboxes, scores, score_threshold, nms_top_k,
                   keep_top_k, nms_threshold=0.3, normalized=True,
                   nms_eta=1.0, background_label=0, name=None):
    """Per-class NMS + cross-class top-k (reference multiclass_nms_op);
    host-side composition over vision.ops.nms."""
    import jax.numpy as _jnp
    from ..core import lazy as _lazy
    from ..vision.ops import nms as _nms
    bv = np.asarray(_lazy.concrete(bboxes.value))
    sv = np.asarray(_lazy.concrete(scores.value))
    outs = []
    n, c = sv.shape[0], sv.shape[1]
    for b in _builtin_range(n):
        dets = []
        for cls in _builtin_range(c):
            if cls == background_label:
                continue
            sc = sv[b, cls]
            keep = sc > score_threshold
            if not keep.any():
                continue
            boxes_c = bv[b][keep] if bv.ndim == 3 else bv[keep]
            sc = sc[keep]
            order = np.argsort(-sc)[:nms_top_k]
            kept = _nms(_paddle().to_tensor(boxes_c[order]),
                        iou_threshold=nms_threshold)
            kept = np.asarray(kept.numpy())
            for k in kept:
                dets.append([float(cls), float(sc[order][k])]
                            + [float(v) for v in boxes_c[order][k]])
        dets.sort(key=lambda r: -r[1])
        outs.append(np.asarray(dets[:keep_top_k], np.float32)
                    .reshape(-1, 6))
    flat = np.concatenate(outs, 0) if outs else np.zeros((0, 6),
                                                         np.float32)
    lens = np.asarray([len(o) for o in outs], "int64")
    return _paddle().to_tensor(flat), _paddle().to_tensor(lens)


def prior_box(input, image, min_sizes, max_sizes=None,  # noqa: A002
              aspect_ratios=(1.0,), variance=(0.1, 0.1, 0.2, 0.2),
              flip=False, clip=False, steps=(0.0, 0.0), offset=0.5,
              name=None, min_max_aspect_ratios_order=False):
    """SSD prior boxes over the feature-map grid (reference
    prior_box_op); deterministic host-side construction."""
    fh, fw = int(input.shape[2]), int(input.shape[3])
    ih, iw = int(image.shape[2]), int(image.shape[3])
    sw = steps[0] or iw / fw   # reference order: (step_w, step_h)
    sh = steps[1] or ih / fh
    ars = []
    for ar in aspect_ratios:
        ars.append(ar)
        if flip and ar != 1.0:
            ars.append(1.0 / ar)
    per = []
    for ms in min_sizes:
        per.append((ms, ms))
        for ar in ars:
            if ar == 1.0:
                continue
            per.append((ms * np.sqrt(ar), ms / np.sqrt(ar)))
    if max_sizes:
        for ms, mx in zip(min_sizes, max_sizes):
            per.append((np.sqrt(ms * mx), np.sqrt(ms * mx)))
    k = len(per)
    out = np.zeros((fh, fw, k, 4), np.float32)
    for i in _builtin_range(fh):
        for j in _builtin_range(fw):
            cx = (j + offset) * sw
            cy = (i + offset) * sh
            for p, (bw, bh) in enumerate(per):
                out[i, j, p] = [(cx - bw / 2) / iw, (cy - bh / 2) / ih,
                                (cx + bw / 2) / iw, (cy + bh / 2) / ih]
    if clip:
        out = np.clip(out, 0.0, 1.0)
    var = np.broadcast_to(np.asarray(variance, np.float32),
                          out.shape).copy()
    return _paddle().to_tensor(out), _paddle().to_tensor(var)


def anchor_generator(input, anchor_sizes=None, aspect_ratios=None,  # noqa: A002
                     variance=(0.1, 0.1, 0.2, 0.2), stride=None,
                     offset=0.5, name=None):
    """RPN anchors over the grid (reference anchor_generator_op)."""
    fh, fw = int(input.shape[2]), int(input.shape[3])
    sw, sh = stride              # reference order: [stride_w, stride_h]
    per = []
    for size in anchor_sizes:
        area = float(size) * float(size)
        for ar in aspect_ratios:
            w = np.sqrt(area / ar)
            h = w * ar
            per.append((w, h))
    out = np.zeros((fh, fw, len(per), 4), np.float32)
    for i in _builtin_range(fh):
        for j in _builtin_range(fw):
            cx = (j + offset) * sw
            cy = (i + offset) * sh
            for p, (w, h) in enumerate(per):
                out[i, j, p] = [cx - w / 2, cy - h / 2,
                                cx + w / 2, cy + h / 2]
    var = np.broadcast_to(np.asarray(variance, np.float32),
                          out.shape).copy()
    return _paddle().to_tensor(out), _paddle().to_tensor(var)


_det_pipeline = (
    "legacy detection-pipeline kernel; modern pipelines compose these "
    "host-side (PaddleDetection-style python)")
density_prior_box = _fluid_unsupported("density_prior_box", _det_pipeline)
multi_box_head = _fluid_unsupported("multi_box_head", _det_pipeline)
bipartite_match = _fluid_unsupported("bipartite_match", _det_pipeline)
target_assign = _fluid_unsupported("target_assign", _det_pipeline)
detection_output = _fluid_unsupported("detection_output", _det_pipeline)
ssd_loss = _fluid_unsupported("ssd_loss", _det_pipeline)
rpn_target_assign = _fluid_unsupported("rpn_target_assign",
                                       _det_pipeline)
retinanet_target_assign = _fluid_unsupported("retinanet_target_assign",
                                             _det_pipeline)
roi_perspective_transform = _fluid_unsupported(
    "roi_perspective_transform", _det_pipeline)
generate_proposal_labels = _fluid_unsupported(
    "generate_proposal_labels", _det_pipeline)
generate_proposals = _fluid_unsupported("generate_proposals",
                                        _det_pipeline)
generate_mask_labels = _fluid_unsupported("generate_mask_labels",
                                          _det_pipeline)
polygon_box_transform = _fluid_unsupported("polygon_box_transform",
                                           _det_pipeline)
locality_aware_nms = _fluid_unsupported("locality_aware_nms",
                                        _det_pipeline)
matrix_nms = _fluid_unsupported("matrix_nms", _det_pipeline)
retinanet_detection_output = _fluid_unsupported(
    "retinanet_detection_output", _det_pipeline)


# -- rnn.py ------------------------------------------------------------------

def _nn():
    import paddle_tpu.nn as _n
    return _n


from ..nn.layer.rnn import RNNCellBase as RNNCell  # noqa: N812
# (a real base class: fluid user code subclasses fluid.layers.RNNCell)


def GRUCell(hidden_size, *a, **k):  # noqa: N802
    return _nn().GRUCell(hidden_size, hidden_size)


def LSTMCell(hidden_size, *a, **k):  # noqa: N802
    return _nn().LSTMCell(hidden_size, hidden_size)


def rnn(cell, inputs, initial_states=None, sequence_length=None,
        time_major=False, is_reverse=False, **kwargs):
    from ..ops import manipulation
    x = manipulation.transpose(inputs, (1, 0, 2)) if time_major \
        else inputs
    if is_reverse:
        x = _paddle().flip(x, axis=[1])
    layer = _nn().RNN(cell)
    out, state = layer(x, initial_states)
    if is_reverse:
        out = _paddle().flip(out, axis=[1])
    if time_major:
        out = manipulation.transpose(out, (1, 0, 2))
    return out, state


def birnn(cell_fw, cell_bw, inputs, initial_states=None,
          sequence_length=None, time_major=False, **kwargs):
    layer = _nn().BiRNN(cell_fw, cell_bw)
    return layer(inputs, initial_states)


class Decoder:
    """Abstract decode contract (reference fluid/layers/rnn.py Decoder):
    subclass and implement initialize/step/finalize, drive with
    dynamic_decode."""

    def initialize(self, inits):
        raise NotImplementedError

    def step(self, time, inputs, states, **kwargs):
        raise NotImplementedError

    def finalize(self, outputs, final_states, sequence_lengths):
        raise NotImplementedError


def BeamSearchDecoder(*a, **k):  # noqa: N802
    return _nn().BeamSearchDecoder(*a, **k)


def dynamic_decode(decoder, inits=None, max_step_num=32, **kwargs):
    return _nn().dynamic_decode(decoder, inits=inits,
                                max_step_num=max_step_num, **kwargs)


def lstm(input, init_h, init_c, max_len, hidden_size, num_layers,  # noqa: A002
         dropout_prob=0.0, is_bidirec=False, is_test=False, name=None,
         default_initializer=None, seed=-1):
    d = int(input.shape[-1])
    layer = _cached_layer(name, ("lstm", d, hidden_size, num_layers,
                                 is_bidirec),
                          lambda: _nn().LSTM(
                              d, hidden_size, num_layers=num_layers,
                              direction="bidirect" if is_bidirec
                              else "forward"))
    out, (h, c) = layer(input, (init_h, init_c))
    return out, h, c


def dynamic_gru(input, size, param_attr=None, bias_attr=None,  # noqa: A002
                is_reverse=False, gate_activation="sigmoid",
                candidate_activation="tanh", h_0=None, origin_mode=False):
    d = int(input.shape[-1])
    layer = _cached_layer(None, ("dyn_gru", d, size),
                          lambda: _nn().GRU(d, size))
    x = _paddle().flip(input, axis=[1]) if is_reverse else input
    out, _ = layer(x, h_0.unsqueeze(0) if h_0 is not None else None)
    return _paddle().flip(out, axis=[1]) if is_reverse else out


def gru_unit(input, hidden, size, param_attr=None, bias_attr=None,  # noqa: A002
             activation="tanh", gate_activation="sigmoid",
             origin_mode=False):
    d = int(input.shape[-1])
    cell = _cached_layer(None, ("gru_unit", d, size),
                         lambda: _nn().GRUCell(d, size // 3))
    h = cell(input, hidden)[1]
    return h, h, h


def lstm_unit(x_t, hidden_t_prev, cell_t_prev, forget_bias=0.0,
              param_attr=None, bias_attr=None, name=None):
    d = int(x_t.shape[-1])
    hd = int(hidden_t_prev.shape[-1])
    cell = _cached_layer(name, ("lstm_unit", d, hd),
                         lambda: _nn().LSTMCell(d, hd))
    _, (h, c) = cell(x_t, (hidden_t_prev, cell_t_prev))
    return h, c


dynamic_lstm = _fluid_unsupported(
    "dynamic_lstm", "use fluid.layers.lstm or paddle.nn.LSTM")
dynamic_lstmp = _fluid_unsupported(
    "dynamic_lstmp", "projection LSTM; use paddle.nn.LSTM with proj_size")
beam_search = _fluid_unsupported(
    "beam_search", "stepwise beam op; use BeamSearchDecoder + "
    "dynamic_decode")
beam_search_decode = _fluid_unsupported(
    "beam_search_decode", "use gather_tree on dynamic_decode outputs")
DecodeHelper = _program_construct("DecodeHelper")
TrainingHelper = _program_construct("TrainingHelper")
GreedyEmbeddingHelper = _program_construct("GreedyEmbeddingHelper")
SampleEmbeddingHelper = _program_construct("SampleEmbeddingHelper")
BasicDecoder = _program_construct("BasicDecoder")


# -- metric_op.py ------------------------------------------------------------

def auc(input, label, curve="ROC", num_thresholds=4095,  # noqa: A002
        topk=1, slide_steps=1):
    """Streaming-free AUC over this batch (reference auc_op reduced:
    single-shot; use paddle.metric.Auc for streaming)."""
    from ..metric import Auc
    m = Auc(num_thresholds=num_thresholds)
    m.update(np.asarray(input.numpy()), np.asarray(label.numpy()))
    val = m.accumulate()
    T = _paddle().to_tensor
    return (T(np.float32(val)), T(np.float32(val)),
            [T(np.zeros(1, np.int64))] * 4)


def npair_loss(anchor, positive, labels, l2_reg=0.002):
    from ..nn import functional as F
    return F.npair_loss(anchor, positive, labels, l2_reg=l2_reg)


distribute_fpn_proposals = _fluid_unsupported(
    "distribute_fpn_proposals", _det_pipeline)
collect_fpn_proposals = _fluid_unsupported(
    "collect_fpn_proposals", _det_pipeline)
box_decoder_and_assign = _fluid_unsupported(
    "box_decoder_and_assign", _det_pipeline)


# -- learning_rate_scheduler.py ---------------------------------------------
# fluid's decay functions return the CURRENT lr value given the global
# step counter (autoincreased_step_counter); modern code uses
# optimizer.lr schedulers — these forward to the same math.

def _global_step():
    t = _step_counters.get("@LR_DECAY_COUNTER@")
    if t is None:
        t = _paddle().to_tensor(np.asarray([0], "int64"))
        _step_counters["@LR_DECAY_COUNTER@"] = t
    return t


def exponential_decay(learning_rate, decay_steps, decay_rate,
                      staircase=False):
    step = _paddle().cast(_global_step(), "float32")
    exp = step / decay_steps
    if staircase:
        exp = _paddle().floor(exp)
    return learning_rate * (decay_rate ** exp)


def natural_exp_decay(learning_rate, decay_steps, decay_rate,
                      staircase=False):
    step = _paddle().cast(_global_step(), "float32")
    exp = step / decay_steps
    if staircase:
        exp = _paddle().floor(exp)
    return learning_rate * _paddle().exp(-1.0 * decay_rate * exp)


def inverse_time_decay(learning_rate, decay_steps, decay_rate,
                       staircase=False):
    step = _paddle().cast(_global_step(), "float32")
    frac = step / decay_steps
    if staircase:
        frac = _paddle().floor(frac)
    return learning_rate / (1.0 + decay_rate * frac)


def polynomial_decay(learning_rate, decay_steps, end_learning_rate=1e-4,
                     power=1.0, cycle=False):
    step = _paddle().cast(_global_step(), "float32")
    if cycle:
        div = _paddle().ceil(_paddle().maximum(
            step / decay_steps, _paddle().to_tensor(1.0)))
        decay = decay_steps * div
    else:
        decay = float(decay_steps)
        step = _paddle().minimum(step, _paddle().to_tensor(decay))
    return ((learning_rate - end_learning_rate)
            * ((1.0 - step / decay) ** power)) + end_learning_rate


def piecewise_decay(boundaries, values):
    step = int(_global_step().numpy()[0])
    for b, v in zip(boundaries, values):
        if step < b:
            return _paddle().to_tensor(np.float32(v))
    return _paddle().to_tensor(np.float32(values[len(boundaries)]))


def noam_decay(d_model, warmup_steps, learning_rate=1.0):
    step = _paddle().cast(_global_step(), "float32") + 1.0
    return (learning_rate * (d_model ** -0.5)
            * _paddle().minimum(step ** -0.5,
                                step * (warmup_steps ** -1.5)))


def cosine_decay(learning_rate, step_each_epoch, epochs):
    step = _paddle().cast(_global_step(), "float32")
    epoch = _paddle().floor(step / step_each_epoch)
    return learning_rate * 0.5 * (
        _paddle().cos(epoch * float(np.pi) / epochs) + 1.0)


def linear_lr_warmup(learning_rate, warmup_steps, start_lr, end_lr):
    step = _paddle().cast(_global_step(), "float32")
    warm = start_lr + (end_lr - start_lr) * step / warmup_steps
    base = learning_rate if not hasattr(learning_rate, "numpy") \
        else learning_rate
    cond = step < float(warmup_steps)
    return _paddle().where(cond, warm * _paddle().ones_like(step),
                           base * _paddle().ones_like(step))


# -- io.py / distributions re-exports ---------------------------------------

def load(out, file_path, load_as_fp16=None):
    v = _paddle().load(file_path)
    out.value = (v.value if hasattr(v, "value")
                 else _paddle().to_tensor(v).value)
    return out


read_file = _program_construct("read_file")
double_buffer = _program_construct("double_buffer")
py_reader = _program_construct("py_reader")
create_py_reader_by_data = _program_construct("create_py_reader_by_data")

from ..distribution import (  # noqa: E402,F401
    Uniform, Normal, Categorical, MultivariateNormalDiag)
