"""fluid.layers compat — the op-assembly API (reference:
python/paddle/fluid/layers/nn.py 36k LoC). The heavily-used subset
forwards to the modern functional ops; names keep fluid's signatures
(e.g. fc(input, size), reduce_mean, cross_entropy with soft labels off).
"""
import numpy as np

from ..core.tensor import Tensor
from ..ops import (creation, linalg, manipulation, math as math_ops,
                   nn_ops, reduction)
from ..static import data  # noqa: F401


_layer_cache = {}


def clear_layer_cache():
    """Drop all implicitly-created fluid.layers parameters (frees them and
    resets call-site reuse — call between independent model builds)."""
    _layer_cache.clear()


def _reuse_key(name, config):
    """Parameter reuse for the eager replay of fluid code: the reference
    builds each layers.* call ONCE into a program; eager loops re-execute
    the python line each step, so the same call site (or explicit `name`)
    must map to the same parameters or nothing trains. Key: user name if
    given, else the full user call stack + config — two logically distinct
    layers built through a shared helper differ in an outer frame, so they
    do not alias. Pass `name` to share parameters deliberately."""
    if name is not None:
        return ("name", name) + config
    import sys
    frames = []
    f = sys._getframe(2)
    while f is not None:
        frames.append((f.f_code.co_filename, f.f_lineno))
        f = f.f_back
    return (tuple(frames),) + config


def fc(input, size, num_flatten_dims=1, param_attr=None, bias_attr=None,
       act=None, name=None):
    """Reference: fluid/layers/nn.py fc — creates (or reuses, see
    _reuse_key) a Linear over the flattened trailing dims."""
    from ..nn.layer.common import Linear
    in_features = int(np.prod(input.shape[num_flatten_dims:]))
    key = _reuse_key(name, ("fc", in_features, size))
    layer = _layer_cache.get(key)
    if layer is None:
        layer = Linear(in_features, size, weight_attr=param_attr,
                       bias_attr=bias_attr)
        _layer_cache[key] = layer
    x = manipulation.reshape(input, list(input.shape[:num_flatten_dims])
                             + [in_features])
    out = layer(x)
    if act is not None:
        out = _apply_act(out, act)
    return out


# activation names fluid layers may apply via act= (reference validates
# against the OpMaker activation registry; arbitrary callables like
# dropout must NOT be reachable through act=)
_ACT_NAMES = frozenset({
    "relu", "relu6", "sigmoid", "tanh", "softmax", "log_softmax", "gelu",
    "leaky_relu", "elu", "selu", "celu", "softplus", "softsign", "silu",
    "swish", "mish", "hardswish", "hardsigmoid", "hardtanh", "tanhshrink",
    "softshrink", "hardshrink", "exp", "square", "sqrt", "rsqrt", "abs",
    "reciprocal", "log", "log1p", "sin", "cos",
})


def _apply_act(out, act):
    fn = None
    if act in _ACT_NAMES:
        fn = getattr(nn_ops, act, None) or getattr(math_ops, act, None)
    if fn is None or not callable(fn):
        raise ValueError(f"unsupported activation {act!r}")
    return fn(out)


def relu(x, name=None):
    return nn_ops.relu(x)


def softmax(x, axis=-1, name=None):
    return nn_ops.softmax(x, axis=axis)


def matmul(x, y, transpose_x=False, transpose_y=False, alpha=1.0,
           name=None):
    out = linalg.matmul(x, y, transpose_x, transpose_y)
    if alpha != 1.0:
        out = out * alpha
    return out


def reduce_mean(input, dim=None, keep_dim=False, name=None):
    return reduction.mean(input, axis=dim, keepdim=keep_dim)


def reduce_sum(input, dim=None, keep_dim=False, name=None):
    return reduction.sum(input, axis=dim, keepdim=keep_dim)


def reduce_max(input, dim=None, keep_dim=False, name=None):
    return reduction.max(input, axis=dim, keepdim=keep_dim)


def cross_entropy(input, label, soft_label=False, ignore_index=-100):
    return nn_ops.cross_entropy(input, label, soft_label=soft_label,
                                ignore_index=ignore_index,
                                use_softmax=False, reduction="none")


def softmax_with_cross_entropy(logits, label, soft_label=False,
                               ignore_index=-100, axis=-1,
                               return_softmax=False):
    loss = nn_ops.cross_entropy(logits, label, soft_label=soft_label,
                                ignore_index=ignore_index,
                                reduction="none")
    if return_softmax:
        return loss, nn_ops.softmax(logits, axis=axis)
    return loss


def mean(x, name=None):
    return reduction.mean(x)


def concat(input, axis=0, name=None):
    return manipulation.concat(input, axis=axis)


def reshape(x, shape, name=None):
    return manipulation.reshape(x, shape)


def transpose(x, perm, name=None):
    return manipulation.transpose(x, perm)


def fill_constant(shape, dtype, value, name=None):
    return creation.full(shape, value, dtype=dtype)


def zeros(shape, dtype="float32", name=None):
    return creation.zeros(shape, dtype=dtype)


def ones(shape, dtype="float32", name=None):
    return creation.ones(shape, dtype=dtype)


def assign(input, output=None):
    t = Tensor(np.asarray(input)) if not isinstance(input, Tensor) \
        else input.clone()
    if output is not None:
        output.value = t.value
        return output
    return t


def cast(x, dtype):
    from ..ops.math import cast as _cast
    return _cast(x, dtype)


def embedding(input, size, is_sparse=False, param_attr=None,
              dtype="float32", name=None):
    from ..nn.layer.common import Embedding
    key = _reuse_key(name, ("embedding", int(size[0]), int(size[1]),
                            bool(is_sparse)))
    layer = _layer_cache.get(key)
    if layer is None:
        layer = Embedding(size[0], size[1], weight_attr=param_attr,
                          sparse=is_sparse)
        _layer_cache[key] = layer
    return layer(input)


def dropout(x, dropout_prob, is_test=False,
            dropout_implementation="downgrade_in_infer"):
    mode = ("upscale_in_train"
            if dropout_implementation == "upscale_in_train"
            else "downscale_in_infer")
    return nn_ops.dropout(x, p=dropout_prob, training=not is_test,
                          mode=mode)


def accuracy(input, label, k=1):
    from ..metric import accuracy as _acc
    return _acc(input, label, k=k)
