"""paddle.inference equivalent.

Reference parity: paddle/fluid/inference/api/analysis_predictor.h:82
AnalysisPredictor + paddle_infer Python API (Config, create_predictor,
zero-copy input/output handles). TPU-native: a saved model is a serialized
StableHLO program + params (jit.save format); the predictor executes the
deserialized XLA executable — the analysis pass pipeline (fusions, memory
optimize) is XLA compilation itself.
"""
import numpy as np

from ..core.tensor import Tensor
from ..jit.save_load import load as _jit_load


_warned_knobs = set()


def _warn_unsupported(knob, equivalent):
    """One warning per unsupported Config knob per process — these are
    accepted for source compat but MUST not be silent no-ops (a user
    flipping enable_use_gpu deserves to learn what actually runs)."""
    if knob in _warned_knobs:
        return
    _warned_knobs.add(knob)
    import warnings
    warnings.warn(
        f"paddle.inference.Config.{knob} has no effect on TPU: "
        f"{equivalent}", UserWarning, stacklevel=3)


class Config:
    """Reference: AnalysisConfig. Model path + execution knobs; GPU/TRT
    options accepted for source compat but warn once (XLA owns
    optimization; the TPU equivalent is named in the warning)."""

    def __init__(self, prog_file=None, params_file=None):
        if prog_file is not None and prog_file.endswith(".pdmodel"):
            prog_file = prog_file[:-len(".pdmodel")]
        self._model_prefix = prog_file
        self._enable_memory_optim = True

    def set_prog_file(self, path):
        self._model_prefix = path[:-len(".pdmodel")] \
            if path.endswith(".pdmodel") else path

    def model_dir(self):
        return self._model_prefix

    def enable_use_gpu(self, *a, **k):
        _warn_unsupported(
            "enable_use_gpu",
            "the predictor runs on the TPU (or CPU) jax backend; "
            "device selection follows JAX_PLATFORMS")

    def enable_memory_optim(self, flag=True):
        self._enable_memory_optim = flag

    def switch_ir_optim(self, flag=True):
        if not flag:
            _warn_unsupported(
                "switch_ir_optim(False)",
                "XLA compilation IS the IR-optimization pipeline here "
                "and cannot be disabled")

    def enable_tensorrt_engine(self, *a, **k):
        _warn_unsupported(
            "enable_tensorrt_engine",
            "XLA is the execution engine; for int8 use "
            "paddle.quantization PTQ/QAT which runs W8A8 on the int8 "
            "MXU")

    def disable_glog_info(self):
        pass  # genuinely a logging knob; nothing to warn about


class _IOHandle:
    def __init__(self, predictor, name, is_input):
        self._p = predictor
        self.name = name
        self._is_input = is_input

    def reshape(self, shape):
        pass

    def copy_from_cpu(self, arr):
        self._p._inputs[self.name] = np.asarray(arr)

    def copy_to_cpu(self):
        return self._p._outputs[self.name]

    def share_external_data(self, arr):
        self.copy_from_cpu(arr)


class Predictor:
    def __init__(self, config):
        self._layer = _jit_load(config.model_dir())
        n_in = 0
        import pickle
        with open(config.model_dir() + ".pdmeta", "rb") as f:
            meta = pickle.load(f)
        self._input_names = [f"x{i}" for i in range(meta["num_inputs"])]
        self._inputs = {}
        self._outputs = {}
        self._output_names = []
        # memory_optim (reference: AnalysisConfig::EnableMemoryOptim —
        # reuse/free buffers between runs): drop staged host inputs and
        # stale outputs after each run instead of keeping them resident
        self._memory_optim = bool(getattr(config,
                                          "_enable_memory_optim", True))

    def get_input_names(self):
        return list(self._input_names)

    def get_input_handle(self, name):
        return _IOHandle(self, name, True)

    def run(self, inputs=None):
        if inputs is not None:  # direct call style
            arrs = [np.asarray(a) for a in inputs]
        else:
            arrs = [self._inputs[n] for n in self._input_names]
        if self._memory_optim:
            self._outputs = {}          # free previous run's outputs
        out = self._layer(*[Tensor(a) for a in arrs])
        outs = out if isinstance(out, tuple) else (out,)
        self._output_names = [f"out{i}" for i in range(len(outs))]
        self._outputs = {n: o.numpy() for n, o in
                         zip(self._output_names, outs)}
        # staged inputs stay resident (reference AnalysisPredictor
        # semantics: run() is repeatable without re-copying inputs)
        if inputs is not None:
            return [self._outputs[n] for n in self._output_names]
        return True

    def get_output_names(self):
        return list(self._output_names) or ["out0"]

    def get_output_handle(self, name):
        return _IOHandle(self, name, False)


def create_predictor(config):
    return Predictor(config)


PrecisionType = type("PrecisionType", (), {"Float32": 0, "Half": 1,
                                           "Bfloat16": 2, "Int8": 3})
PlaceType = type("PlaceType", (), {"CPU": 0, "GPU": 1, "XPU": 2, "TPU": 4})


class DataType:  # reference: paddle_infer.DataType enum
    FLOAT32 = 0
    INT64 = 1
    INT32 = 2
    UINT8 = 3
    INT8 = 4
    FLOAT16 = 5
    BFLOAT16 = 6


_DTYPE_BYTES = {DataType.FLOAT32: 4, DataType.INT64: 8,
                DataType.INT32: 4, DataType.UINT8: 1, DataType.INT8: 1,
                DataType.FLOAT16: 2, DataType.BFLOAT16: 2}


def get_num_bytes_of_data_type(dtype):
    return _DTYPE_BYTES[dtype]


def get_version():
    from .. import __version__
    return f"paddle_tpu inference {__version__}"


def create_serving_engine(model, **kwargs):
    """Continuous-batching serving entry point — the multi-request
    analogue of create_predictor for autoregressive decode. Takes a
    live GPTForCausalLM (weights snapshotted now) and the
    paddle_tpu.serving knobs (num_slots, max_len, buckets, bucket_min,
    prefill_group_sizes, async_depth, donate_buffers, eos_id); returns
    a paddle_tpu.serving.ServingEngine whose add_request/step/run loop
    serves concurrent generations from a slot-pooled donated KV cache
    with grouped bucketed prefill, one-step-deep async decode
    pipelining and zero steady-state recompiles."""
    from ..serving import ServingEngine
    return ServingEngine(model, **kwargs)


class PredictorPool:
    """Reference: paddle_infer.PredictorPool — N predictors sharing one
    config (thread-per-predictor serving). Programs are jit-compiled
    and shared via the XLA executable cache, so clones are cheap."""

    def __init__(self, config, size=1):
        self._predictors = [Predictor(config) for _ in range(int(size))]

    def retrive(self, idx):  # reference spells it 'retrive'
        return self._predictors[idx]

    retrieve = retrive
