"""Lazy micro-tracing eager executor (SURVEY §7 hard-part 1, second half).

TPU-native answer to the reference's generated fast eager entry points
(reference: paddle/fluid/pybind/op_function_generator.cc:519 — per-op C
functions that bypass python op assembly to make eager dispatch cheap).
On TPU the per-op cost is not python assembly but the PjRt launch round
trip: one executable launch per op. So instead of making each launch
cheaper, consecutive eager ops are DEFERRED into a micro-graph and
flushed as ONE fused XLA executable at materialization points
(`.numpy()`, `float()`, printing, control flow on values) or at a step
boundary (`optimizer.clear_grad`). Steady state, a whole eager train
step becomes a single cached executable launch — the same dispatch
economics as `to_static`, with no user annotation.

Mechanics:
  * `Op.__call__` (core/dispatch.py) calls `dispatch()` instead of
    executing: the op's pure closure becomes a node in the thread-local
    `LazyGraph`; outputs are `LazyArray` placeholders carrying
    shape/dtype from a cached `jax.eval_shape`.
  * backward is lazy too: the autograd engine (core/engine.py) routes
    each node's vjp through `dispatch_vjp`, and gradient accumulation
    through `add`, so fwd+bwd+optimizer of a step accumulate into one
    graph.
  * `flush()` compiles a replay function of the whole graph under
    `jax.jit`, keyed by the graph shape (node keys + wiring + const
    avals + live outputs); repeated steps hit the cache and pay one
    executable launch.
  * Materialization is automatic: `LazyArray.__jax_array__` /
    `__array__` flush on any direct jnp/numpy use, so code that touches
    raw values stays correct (it just fuses less).

Enabled via FLAGS_lazy_eager (core/flags.py).
"""
import threading
import weakref

import numpy as np
import jax
import jax.numpy as jnp

from . import flags as flags_mod
from . import trace as trace_mod


def static_int_exponent(base_dtype, y):
    """Exponent for the exact-multiply-chain pow fast path
    (lax.integer_pow), or None to take the general jnp.power path.
    Guards: bool exponents AND bool bases excluded (integer_pow rejects
    bool; jnp.power promotes it to int32); float exponents only
    promote-safely on float bases (int_array ** 2.0 must yield float
    via jnp.power); negative exponents on integer bases are integer
    division in integer_pow (wrong), so those also fall through."""
    if isinstance(y, bool) or not isinstance(y, (int, float)):
        return None
    if jnp.issubdtype(base_dtype, jnp.bool_):
        return None
    base_is_inexact = jnp.issubdtype(base_dtype, jnp.inexact)
    fy = float(y)
    if not fy.is_integer() or not -64 <= fy <= 64:
        return None
    n = int(fy)
    if not base_is_inexact and (n < 0 or isinstance(y, float)):
        return None
    return n

_MAX_NODES = 4096
_MAX_CACHED_REPLAYS = 64
_state = threading.local()
_ever_enabled = [False]

_replay_cache = {}
_aval_cache = {}
_vjp_fn_cache = {}
_intern_ids = {}


def _intern(key):
    """Map a (hashable) structured key to a process-stable small int."""
    i = _intern_ids.get(key)
    if i is None:
        i = len(_intern_ids)
        _intern_ids[key] = i
    return i


_scalar_cache = {}


def scalar_const(v):
    """Value-keyed python-scalar -> jax array cache: each conversion is
    a device op (a launch per call on TPU); step loops repeat the same
    constants every iteration. Floats key on their sign bit too:
    -0.0 == 0.0 under dict equality but they are different constants
    (1/x, copysign)."""
    if type(v) is float:
        import math
        ck = (float, v, math.copysign(1.0, v))
    else:
        ck = (type(v), v)
    arr = _scalar_cache.get(ck)
    if arr is None:
        arr = jnp.asarray(v)
        if isinstance(arr, jax.core.Tracer):
            return arr  # never cache tracers (leak into later traces)
        if len(_scalar_cache) > 4096:
            _scalar_cache.clear()
        _scalar_cache[ck] = arr
    return arr


class Fallback(Exception):
    """Raised when an op cannot be deferred (exotic outputs); the caller
    executes it eagerly instead."""


def enabled():
    if not flags_mod.get_flag("FLAGS_lazy_eager"):
        return False
    if trace_mod.current_trace() is not None:
        return False
    _ever_enabled[0] = True
    return True


def ever_enabled():
    return _ever_enabled[0]


class LazyArray:
    """Placeholder for a deferred op output. Quacks enough like a
    jax.Array for metadata (shape/dtype/ndim) and converts itself on any
    real use via __jax_array__ / __array__ / attribute fallback."""
    __slots__ = ("_graph", "_aval", "_concrete", "_node_ref",
                 "__weakref__")

    def __init__(self, graph, aval):
        self._graph = graph
        self._aval = aval
        self._concrete = None

    # -- metadata (no materialization) --------------------------------
    @property
    def shape(self):
        return self._aval.shape

    @property
    def dtype(self):
        return self._aval.dtype

    @property
    def ndim(self):
        return len(self._aval.shape)

    @property
    def size(self):
        return int(np.prod(self._aval.shape)) if self._aval.shape else 1

    @property
    def nbytes(self):
        return self.size * jnp.dtype(self._aval.dtype).itemsize

    @property
    def weak_type(self):
        return getattr(self._aval, "weak_type", False)

    # -- materialization ----------------------------------------------
    def materialize(self):
        if self._concrete is None:
            g = self._graph
            if g is None:
                raise RuntimeError("deferred value has no graph and no "
                                   "concrete result (internal error)")
            g.flush()
            if self._concrete is None:
                raise RuntimeError(
                    "deferred value lost: its lazy graph failed to "
                    f"execute ({g.error!r})") from g.error
        return self._concrete

    def __jax_array__(self):
        return self.materialize()

    def __array__(self, dtype=None, copy=None):
        a = np.asarray(self.materialize())
        return a.astype(dtype) if dtype is not None else a

    def block_until_ready(self):
        self.materialize().block_until_ready()
        return self

    def __getattr__(self, item):
        # any attribute beyond the fast-path ones: materialize + delegate
        # (never for private names — those are real missing attributes)
        if item.startswith("_"):
            raise AttributeError(item)
        return getattr(self.materialize(), item)

    def __repr__(self):
        if self._concrete is not None:
            return repr(self._concrete)
        return (f"LazyArray(shape={self._aval.shape}, "
                f"dtype={self._aval.dtype}, deferred)")

    # arithmetic stays lazy (grad accumulation, running-stat updates)
    def __add__(self, other):
        return _binary(jnp.add, "add", self, other)

    def __radd__(self, other):
        return _binary(jnp.add, "add", other, self)

    def __sub__(self, other):
        return _binary(jnp.subtract, "sub", self, other)

    def __rsub__(self, other):
        return _binary(jnp.subtract, "sub", other, self)

    def __mul__(self, other):
        return _binary(jnp.multiply, "mul", self, other)

    def __rmul__(self, other):
        return _binary(jnp.multiply, "mul", other, self)

    def __truediv__(self, other):
        return _binary(jnp.divide, "div", self, other)

    def __rtruediv__(self, other):
        return _binary(jnp.divide, "div", other, self)

    def __neg__(self):
        if enabled():
            try:
                return dispatch(jnp.negative, ("lazy_neg",), [self])
            except Fallback:
                pass
        return jnp.negative(self.materialize())

    def __matmul__(self, other):
        return _binary(jnp.matmul, "matmul", self, other)

    def __pow__(self, other):
        # static integer exponents lower to an exact multiply chain
        # (lax.integer_pow); lax.pow is exp(y*log(x)) whose TPU
        # transcendentals make even x**2 inexact (9.000011 for 3**2)
        n = static_int_exponent(self.dtype, other)
        if n is not None:
            if enabled():
                try:
                    return dispatch(lambda x: jax.lax.integer_pow(x, n),
                                    ("lazy_ipow", n), [self])
                except Fallback:
                    pass
            return jax.lax.integer_pow(self.materialize(), n)
        return _binary(jnp.power, "pow", self, other)

    def __mod__(self, other):
        return _binary(jnp.mod, "mod", self, other)

    def __floordiv__(self, other):
        return _binary(jnp.floor_divide, "floordiv", self, other)

    # comparisons are elementwise (like jax arrays); a missing __eq__
    # would silently fall back to identity and return a python bool
    def __eq__(self, other):
        return _binary(jnp.equal, "eq", self, other)

    def __ne__(self, other):
        return _binary(jnp.not_equal, "ne", self, other)

    def __lt__(self, other):
        return _binary(jnp.less, "lt", self, other)

    def __le__(self, other):
        return _binary(jnp.less_equal, "le", self, other)

    def __gt__(self, other):
        return _binary(jnp.greater, "gt", self, other)

    def __ge__(self, other):
        return _binary(jnp.greater_equal, "ge", self, other)

    __hash__ = None  # unhashable, matching jax.Array

    def __or__(self, other):
        return _binary(jnp.logical_or, "or", self, other)

    def __ror__(self, other):
        return _binary(jnp.logical_or, "or", other, self)

    def __and__(self, other):
        return _binary(jnp.logical_and, "and", self, other)

    def __rand__(self, other):
        return _binary(jnp.logical_and, "and", other, self)

    def __invert__(self):
        if enabled():
            try:
                return dispatch(jnp.logical_not, ("lazy_not",), [self])
            except Fallback:
                pass
        return jnp.logical_not(self.materialize())

    def astype(self, dt):
        return _unary_astype(self, dt)

    def __getitem__(self, idx):
        return self.materialize()[idx]

    def __iter__(self):
        return iter(self.materialize())

    def __float__(self):
        return float(np.asarray(self.materialize()))

    def __int__(self):
        return int(np.asarray(self.materialize()))

    def __bool__(self):
        return bool(np.asarray(self.materialize()))


# Register LazyArray as a pytree whose flatten materializes: jax API
# boundaries (jit args, device_put, shard_map) then accept LazyArrays
# transparently. Direct lax binds on a LazyArray still raise (jax
# removed __jax_array__ abstractification) — framework-internal raw-jax
# sites materialize explicitly via concrete().
jax.tree_util.register_pytree_node(
    LazyArray,
    lambda la: ((la.materialize(),), None),
    lambda _, ch: ch[0])


class _Node:
    __slots__ = ("fn", "fn_key", "args", "treedef", "avals", "out_wrefs",
                 "cache_key")

    def __init__(self, fn, fn_key, args, treedef, avals):
        self.fn = fn
        self.fn_key = fn_key
        self.args = args                  # ("c", i) | ("n", node, out)
        self.treedef = treedef
        self.avals = avals                # flat ShapeDtypeStructs
        self.out_wrefs = []


class LazyGraph:
    def __init__(self):
        self.nodes = []
        self.consts = []
        self._const_ids = {}
        self.flushed = False
        self.error = None

    # -- building ------------------------------------------------------
    def _const_ref(self, arr):
        idx = self._const_ids.get(id(arr))
        if idx is None:
            idx = len(self.consts)
            self.consts.append(arr)
            self._const_ids[id(arr)] = idx
        return ("c", idx)

    def _arg_ref(self, a):
        if isinstance(a, LazyArray):
            if a._concrete is not None:
                return self._const_ref(a._concrete), a._concrete
            if a._graph is not self:
                # a lazy value from an unflushed foreign graph cannot be
                # wired in; materialize it (flushes that graph)
                c = a.materialize()
                return self._const_ref(c), c
            return None, a  # same-graph lazy: resolved by caller
        return self._const_ref(a), a

    def append(self, fn, fn_key, arrays):
        refs = []
        in_avals = []
        for a in arrays:
            ref, val = self._arg_ref(a)
            if ref is None:  # same-graph lazy
                # find its producing slot via the weakref lists
                ref = val._node_ref
                in_avals.append(val._aval)
            else:
                in_avals.append(_aval_of(val))
            refs.append(ref)
        akey = (fn_key,
                tuple((a.shape, a.dtype,
                       bool(getattr(a, "weak_type", False)))
                      for a in in_avals))
        cached = _aval_cache.get(akey)
        if cached is None:
            out_struct = jax.eval_shape(fn, *in_avals)
            flat, treedef = jax.tree.flatten(out_struct)
            for leaf in flat:
                if not hasattr(leaf, "shape") or not hasattr(leaf, "dtype"):
                    raise Fallback(f"non-array output from {fn_key!r}")
                if leaf.dtype == jax.dtypes.float0:
                    raise Fallback(f"float0 output from {fn_key!r}")
            cached = (flat, treedef)
            _aval_cache[akey] = cached
        flat_avals, treedef = cached
        node_idx = len(self.nodes)
        node = _Node(fn, fn_key, tuple(refs), treedef, flat_avals)
        # intern the (fn_key, wiring) pair to a small int: the flush key
        # then hashes a tuple of ints instead of re-hashing every node's
        # nested attr tuples on every step
        node.cache_key = _intern((fn_key, node.args))
        self.nodes.append(node)
        outs = []
        for j, aval in enumerate(flat_avals):
            la = LazyArray(self, aval)
            la._node_ref = ("n", node_idx, j)
            node.out_wrefs.append(weakref.ref(la))
            outs.append(la)
        return jax.tree.unflatten(treedef, outs)

    # -- execution -----------------------------------------------------
    def flush(self):
        if self.flushed:
            return
        self.flushed = True
        if getattr(_state, "graph", None) is self:
            _state.graph = None
        if not self.nodes:
            return
        live = []       # (node_idx, out_idx)
        live_arrays = []  # strong refs so gc can't race the assignment
        for i, n in enumerate(self.nodes):
            for j, w in enumerate(n.out_wrefs):
                la = w()
                if la is not None and la._concrete is None:
                    live.append((i, j))
                    live_arrays.append(la)
        key = (tuple(n.cache_key for n in self.nodes),
               tuple(_intern((np.shape(c), _dtype_of(c),
                              bool(getattr(c, "weak_type", False))))
                     for c in self.consts),
               tuple(live))
        exe = _replay_cache.get(key)
        if exe is None:
            exe = jax.jit(_make_replay(self.nodes, live))
            if len(_replay_cache) >= _MAX_CACHED_REPLAYS:
                # bound compile-cache growth (live-set churn can mint
                # new keys); FIFO eviction of the oldest entry
                _replay_cache.pop(next(iter(_replay_cache)))
            _replay_cache[key] = exe
        try:
            outs = exe(*self.consts)
        except Exception as e:
            # keep the graph object (with .error) so pending LazyArrays
            # raise a diagnostic instead of silently yielding None
            self.error = e
            raise
        for la, val in zip(live_arrays, outs):
            la._concrete = val
            la._graph = None
        self.nodes = None
        self.consts = None
        self._const_ids = None


def _make_replay(nodes, live):
    def replay(*consts):
        vals = []
        for n in nodes:
            args = [consts[r[1]] if r[0] == "c" else vals[r[1]][r[2]]
                    for r in n.args]
            out = n.fn(*args)
            flat, _ = jax.tree.flatten(out)
            vals.append(flat)
        return tuple(vals[i][j] for i, j in live)
    return replay


def _aval_of(x):
    aval = getattr(x, "aval", None)
    if aval is not None:  # jax.Array: reuse its ShapedArray directly
        return aval
    try:
        return jax.ShapeDtypeStruct(
            np.shape(x), _dtype_of(x),
            weak_type=bool(getattr(x, "weak_type", False)))
    except TypeError:  # older jax without weak_type kwarg
        return jax.ShapeDtypeStruct(np.shape(x), _dtype_of(x))


def _dtype_of(x):
    dt = getattr(x, "dtype", None)
    return dt if dt is not None else np.asarray(x).dtype


def _cur():
    g = getattr(_state, "graph", None)
    if g is None:
        g = LazyGraph()
        _state.graph = g
    return g


def flush():
    """Flush the current thread's pending graph (step-boundary hint —
    called by optimizer.clear_grad — or explicit sync)."""
    g = getattr(_state, "graph", None)
    if g is not None:
        g.flush()


def concrete(x):
    return x.materialize() if isinstance(x, LazyArray) else x


def dispatch(fn, fn_key, arrays):
    """Defer `fn(*arrays)` into the current graph; returns the output
    pytree with LazyArray leaves. Raises Fallback for undeferable ops."""
    g = _cur()
    if len(g.nodes) >= _MAX_NODES:
        g.flush()
        g = _cur()
    return g.append(fn, fn_key, arrays)


def _binary(jnp_fn, name, a, b):
    """Lazy-aware elementwise binary (python scalars become consts)."""
    if enabled() and (isinstance(a, LazyArray) or isinstance(b, LazyArray)):
        try:
            aa = scalar_const(a) if isinstance(a, (int, float, bool)) \
                else a
            bb = scalar_const(b) if isinstance(b, (int, float, bool)) \
                else b
            return dispatch(jnp_fn, ("lazy_" + name,), [aa, bb])
        except Fallback:
            pass
    return jnp_fn(concrete(a), concrete(b))


def add(a, b):
    """Lazy-aware addition used by gradient accumulation."""
    return _binary(jnp.add, "add", a, b)


def _unary_astype(a, dt):
    if enabled() and isinstance(a, LazyArray):
        try:
            return dispatch(lambda x: x.astype(dt),
                            ("lazy_astype", str(jnp.dtype(dt))), [a])
        except Fallback:
            pass
    return concrete(a).astype(dt)


def dispatch_vjp(node, cts):
    """Defer a GradNode's backward into the lazy graph. `cts` is the
    list of output cotangents (arrays/LazyArrays, or None/float0 zeros
    for outputs with no incoming gradient). Returns per-input grads
    aligned with node.input_tensors (None for inputs not needing grad).
    Raises Fallback when the vjp can't be deferred."""
    need = tuple(i for i, t in enumerate(node.input_tensors)
                 if t is not None and not t.stop_gradient)
    if not need:
        return [None] * len(node.input_tensors)
    absent = tuple(i for i, c in enumerate(cts)
                   if c is None or getattr(c, "dtype", None)
                   == jax.dtypes.float0)
    fkey = ("lazy_vjp", node.key, need, absent, node.multi_out)
    fn = _vjp_fn_cache.get(fkey)
    if fn is None:
        closure = node.closure
        n_in = len(node.arrays)
        multi = node.multi_out
        absent_set = set(absent)

        def vjp_flat(*flat):
            arrays = flat[:n_in]
            live_cts = list(flat[n_in:])
            primals, vjp = jax.vjp(closure, *arrays)
            plist = list(primals) if isinstance(primals, (tuple, list)) \
                else [primals]
            full_cts = []
            li = 0
            for i, p in enumerate(plist):
                is_float = (jnp.issubdtype(p.dtype, jnp.floating)
                            or jnp.issubdtype(p.dtype,
                                              jnp.complexfloating))
                if i in absent_set:
                    c = (jnp.zeros(np.shape(p), p.dtype) if is_float
                         else np.zeros(np.shape(p), jax.dtypes.float0))
                else:
                    c = live_cts[li]
                    li += 1
                    if not is_float:
                        c = np.zeros(np.shape(p), jax.dtypes.float0)
                    elif c.dtype != p.dtype:
                        c = c.astype(p.dtype)
                full_cts.append(c)
            ct_arg = tuple(full_cts) if multi else full_cts[0]
            grads = vjp(ct_arg)
            return tuple(grads[i] for i in need)

        fn = vjp_flat
        _vjp_fn_cache[fkey] = fn
    args = list(node.arrays) + [c for i, c in enumerate(cts)
                                if i not in set(absent)]
    outs = dispatch(fn, fkey, args)
    outs = list(outs) if isinstance(outs, (tuple, list)) else [outs]
    in_grads = [None] * len(node.input_tensors)
    for j, i in enumerate(need):
        in_grads[i] = outs[j]
    return in_grads
