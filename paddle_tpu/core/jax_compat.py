"""Version-compat wrappers for jax APIs that moved between releases.

The SPMD modules were written against the promoted ``jax.shard_map``
(with ``check_vma`` / ``axis_names``); older releases only ship
``jax.experimental.shard_map.shard_map`` (with ``check_rep`` /
``auto``). One adapter here keeps every call site on the modern
spelling.
"""
import jax


def shard_map(f, mesh=None, in_specs=None, out_specs=None,
              check_vma=None, axis_names=None):
    """jax.shard_map when available, else the experimental fallback.

    check_vma maps to the old check_rep (both toggle the replication
    checker); axis_names={a, ...} maps to auto = mesh axes NOT named
    (manual over the named axes only).
    """
    if hasattr(jax, "shard_map"):
        kw = {}
        if check_vma is not None:
            kw["check_vma"] = check_vma
        if axis_names is not None:
            kw["axis_names"] = axis_names
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, **kw)
    from jax.experimental.shard_map import shard_map as _sm
    kw = {}
    if check_vma is not None:
        kw["check_rep"] = check_vma
    # axis_names (manual-over-these, automatic elsewhere) maps to the
    # old auto= parameter, but partial-auto lowering is unreliable in
    # the experimental versions (PartitionId UNIMPLEMENTED under CPU
    # SPMD) — run full-manual instead: unmentioned axes just see
    # replicated data, which is semantically identical and only costs
    # redundant compute on those axes.
    return _sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
               **kw)
