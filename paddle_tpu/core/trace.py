"""Program-capture (trace) context.

TPU-native replacement for the reference's ProgramDesc+Executor static graph
and the dygraph-to-static ProgramTranslator (reference:
python/paddle/fluid/dygraph/dygraph_to_static/program_translator.py:232,
paddle/fluid/framework/executor.cc:166). Instead of building an op-desc
program and interpreting it, we capture the user's Python step function as a
single XLA computation via jax.jit:

- phase "record": the function runs eagerly while we record which
  pre-existing Tensors it reads (-> compiled-function inputs) and which it
  mutates (-> compiled-function outputs, written back after each call).
  This discovers closure state (parameters, optimizer moments, RNG state)
  without requiring the user to thread it functionally.
- phase "jit": the function runs under jax.jit; reads of captured tensors
  return the corresponding tracer, mutations are collected as extra outputs.

Mutation of a Tensor means assignment to its `.value` — paddle's in-place
ops (optimizer updates, set_value) are expressed that way, which maps
in-place semantics onto XLA's functional model with buffer donation.
"""
import threading
import weakref

_state = threading.local()

# Static-analysis hooks (paddle_tpu.analysis.birth): None by default so
# the untraced hot path pays ONE attribute test. When birth tracking is
# enabled, _birth_hook(tensor) records a birth site for every Tensor
# constructed under a trace, and _capture_hook(ctx, tensor) runs when a
# read is about to CAPTURE a pre-existing tensor (record-mode read /
# jit-mode constant embed) — the escape point of a tracer leak.
_birth_hook = None
_capture_hook = None


def current_trace():
    return getattr(_state, "trace", None)


def adopt(tensor):
    """Register a freshly constructed constant Tensor with the innermost
    active trace when its value is a tracer.

    Constant-creating op paths (scalar wrapping, clip bounds, ...) build
    Tensors directly instead of going through the op dispatcher, which
    registers its outputs. Inside a lax sub-trace (cond/while bodies)
    jnp.asarray of a python scalar yields a TRACER of that sub-trace; if
    the Tensor holding it is not registered as trace-created, the
    TraceContext later classifies it as a pre-existing capture and the
    dead sub-trace tracer escapes into the outer replay
    (UnexpectedTracerError). Concrete values keep today's capture
    semantics untouched — only tracer-valued constants are adopted."""
    ctx = current_trace()
    if ctx is not None:
        import jax.core as jcore
        if isinstance(tensor._value, jcore.Tracer):
            ctx.register_created(tensor)
    return tensor


class TraceContext:
    def __init__(self, mode):
        assert mode in ("record", "jit")
        self.mode = mode
        # id(tensor) -> tensor, for pre-existing tensors read during the run
        self.reads = {}
        # id(tensor) -> tensor, for pre-existing tensors mutated during the run
        self.writes = {}
        # id(tensor) -> weakref, for tensors created during this run
        # (their reads are internal). Membership MUST be checked through
        # is_created(): a dead created tensor's id can be recycled by a
        # later allocation, and a raw id test would silently classify
        # the newcomer as trace-created.
        self.created = {}
        self.created_refs = []
        # jit phase: id(tensor) -> current traced value (tracer)
        self.values = {}
        self.captured_ids = set()

    # -- called from Tensor.value property --------------------------------
    def read(self, tensor):
        tid = id(tensor)
        if tid in self.values:
            return self.values[tid]
        if self.is_created(tensor):
            # created during this very trace but its raw value still set
            return tensor._value
        if self.mode == "record":
            if tensor._value is None:
                raise RuntimeError(
                    f"Tensor {tensor.name!r} read inside a traced function but it "
                    "has no value (it may have escaped a previous trace)")
            if _capture_hook is not None:
                _capture_hook(self, tensor)
            self.reads[tid] = tensor
            return tensor._value
        # jit mode: not captured -> embed as a compile-time constant
        if tensor._value is None:
            raise RuntimeError(
                f"Tensor {tensor.name!r} read inside jit trace has no concrete "
                "value; it likely escaped a previous trace. Make sure the traced "
                "step is self-contained (backward + step + clear_grad inside).")
        if _capture_hook is not None:
            _capture_hook(self, tensor)
        return tensor._value

    def write(self, tensor, value):
        tid = id(tensor)
        if not self.is_created(tensor):
            self.writes[tid] = tensor
        if self.mode == "record":
            tensor._value = value
        else:
            self.values[tid] = value

    def register_created(self, tensor):
        ref = weakref.ref(tensor)
        self.created[id(tensor)] = ref
        self.created_refs.append(ref)

    def is_created(self, tensor):
        """Was THIS tensor (identity, not recycled id) created during
        the trace?"""
        ref = self.created.get(id(tensor))
        return ref is not None and ref() is tensor

    # -- jit phase helpers -------------------------------------------------
    def bind(self, tensor, tracer):
        self.values[id(tensor)] = tracer
        self.captured_ids.add(id(tensor))

    def final_value(self, tensor):
        return self.values.get(id(tensor), tensor._value)


class _Guard:
    def __init__(self, ctx):
        self.ctx = ctx

    def __enter__(self):
        if current_trace() is not None:
            raise RuntimeError("nested traces are not supported")
        _state.trace = self.ctx
        return self.ctx

    def __exit__(self, *exc):
        _state.trace = None
        if self.ctx.mode == "jit":
            # Poison tensors created during the jit trace whose value is a
            # tracer: they must not be read outside the trace.
            import jax.core as jcore
            for ref in self.ctx.created_refs:
                t = ref()
                if t is None:
                    continue
                v = self.ctx.values.get(id(t), t._value)
                if isinstance(v, jcore.Tracer):
                    t._value = None
        return False


def trace_guard(ctx):
    return _Guard(ctx)
