"""Device / Place abstraction.

TPU-native equivalent of the reference Place system (reference:
paddle/fluid/platform/place.h, device_context.h DeviceContextPool,
python/paddle/device/__init__.py:181 set_device). On TPU there is no
per-device stream/handle bundle to manage — PjRt owns the device runtime —
so a Place is simply an identity wrapping a jax.Device.
"""
import jax


class Place:
    """Device identity, paddle-style (CPUPlace / TPUPlace analogues)."""

    __slots__ = ("device_type", "device_id")

    def __init__(self, device_type, device_id=0):
        self.device_type = device_type
        self.device_id = device_id

    def __repr__(self):
        if self.device_type == "cpu":
            return "Place(cpu)"
        return f"Place({self.device_type}:{self.device_id})"

    def __eq__(self, other):
        return (isinstance(other, Place)
                and self.device_type == other.device_type
                and self.device_id == other.device_id)

    def __hash__(self):
        return hash((self.device_type, self.device_id))

    def is_cpu_place(self):
        return self.device_type == "cpu"

    def is_tpu_place(self):
        return self.device_type in ("tpu", "axon")

    def jax_device(self):
        """Resolve to the backing jax.Device."""
        devs = _devices_for(self.device_type)
        return devs[self.device_id]


class CPUPlace(Place):
    def __init__(self):
        super().__init__("cpu", 0)


class TPUPlace(Place):
    def __init__(self, device_id=0):
        super().__init__(_accelerator_platform() or "cpu", device_id)


def _accelerator_platform():
    """Name of the non-cpu platform if one exists (tpu, or 'axon' tunnel)."""
    try:
        platform = jax.default_backend()
    except RuntimeError:
        return None
    return platform if platform != "cpu" else None


def _devices_for(device_type):
    if device_type == "cpu":
        return jax.devices("cpu") if jax.default_backend() == "cpu" else jax.local_devices(backend="cpu")
    return jax.devices()


_current_place = None


def set_device(device):
    """paddle.device.set_device equivalent. Accepts 'cpu', 'tpu', 'tpu:0',
    and for compat 'gpu'/'gpu:0' (mapped to the accelerator)."""
    global _current_place
    dev = device.lower()
    if ":" in dev:
        kind, _, idx = dev.partition(":")
        idx = int(idx)
    else:
        kind, idx = dev, 0
    if kind == "cpu":
        _current_place = CPUPlace()
    elif kind in ("tpu", "gpu", "xpu", "npu", "axon"):
        _current_place = TPUPlace(idx)
    else:
        raise ValueError(f"unsupported device {device!r}")
    return _current_place


def get_device():
    p = get_place()
    if p.is_cpu_place():
        return "cpu"
    return f"tpu:{p.device_id}"


def get_place():
    global _current_place
    if _current_place is None:
        # Default to the accelerator when present, like paddle defaults to GPU.
        _current_place = TPUPlace(0) if _accelerator_platform() else CPUPlace()
    return _current_place


def is_compiled_with_cuda():
    return False


def is_compiled_with_tpu():
    return _accelerator_platform() is not None


def device_count():
    return jax.device_count()


class CUDAPlace(Place):
    """Shim: maps to the accelerator (TPU) device for API parity with the
    reference's CUDAPlace (paddle/fluid/platform/place.h)."""

    def __init__(self, device_id=0):
        super().__init__(_accelerator_platform() or "cpu", device_id)


class CUDAPinnedPlace(Place):
    def __init__(self):
        super().__init__("cpu", 0)


class XPUPlace(Place):
    def __init__(self, device_id=0):
        super().__init__(_accelerator_platform() or "cpu", device_id)


class NPUPlace(Place):
    def __init__(self, device_id=0):
        super().__init__(_accelerator_platform() or "cpu", device_id)


def is_compiled_with_xpu():
    return False


def is_compiled_with_npu():
    return False


def is_compiled_with_rocm():
    return False
