"""ctypes bindings to the native runtime (runtime_cpp/runtime.cc).

Reference analogues: blocking queue (operators/reader/blocking_queue.h),
host arena allocator (memory/allocation/), trace collector
(platform/profiler.h), MultiSlot parser (framework/data_feed.cc).
Builds lazily via make on first use; everything degrades gracefully to
pure-Python fallbacks if a compiler is unavailable.
"""
import ctypes
import os
import subprocess
import threading

import numpy as np

_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
_SO = os.path.join(_ROOT, "runtime_cpp", "build", "libpaddle_tpu_runtime.so")
_lib = None
_lock = threading.Lock()


def _load():
    global _lib
    with _lock:
        if _lib is not None:
            return _lib
        if not os.path.exists(_SO):
            try:
                subprocess.run(["make", "-C",
                                os.path.join(_ROOT, "runtime_cpp")],
                               check=True, capture_output=True)
            except (subprocess.CalledProcessError, FileNotFoundError) as e:
                raise RuntimeError(f"native runtime build failed: {e}")
        lib = ctypes.CDLL(_SO)
        u8p = ctypes.POINTER(ctypes.c_uint8)
        lib.ptq_queue_create.restype = ctypes.c_void_p
        lib.ptq_queue_create.argtypes = [ctypes.c_size_t]
        lib.ptq_queue_put.restype = ctypes.c_int
        lib.ptq_queue_put.argtypes = [ctypes.c_void_p, u8p, ctypes.c_size_t]
        lib.ptq_queue_get.restype = ctypes.c_int64
        lib.ptq_queue_get.argtypes = [ctypes.c_void_p, u8p, ctypes.c_size_t]
        lib.ptq_queue_front_size.restype = ctypes.c_int64
        lib.ptq_queue_front_size.argtypes = [ctypes.c_void_p]
        lib.ptq_queue_size.restype = ctypes.c_size_t
        lib.ptq_queue_size.argtypes = [ctypes.c_void_p]
        lib.ptq_queue_close.argtypes = [ctypes.c_void_p]
        lib.ptq_queue_destroy.argtypes = [ctypes.c_void_p]
        lib.pta_arena_create.restype = ctypes.c_void_p
        lib.pta_arena_alloc.restype = ctypes.c_void_p
        lib.pta_arena_alloc.argtypes = [ctypes.c_void_p, ctypes.c_size_t]
        lib.pta_arena_free.argtypes = [ctypes.c_void_p, ctypes.c_void_p,
                                       ctypes.c_size_t]
        lib.pta_arena_stats.argtypes = [ctypes.c_void_p] + \
            [ctypes.POINTER(ctypes.c_size_t)] * 4
        lib.pta_arena_destroy.argtypes = [ctypes.c_void_p]
        lib.ptt_trace_create.restype = ctypes.c_void_p
        lib.ptt_trace_now_us.restype = ctypes.c_int64
        lib.ptt_trace_now_us.argtypes = [ctypes.c_void_p]
        lib.ptt_trace_record.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                         ctypes.c_int64, ctypes.c_int64,
                                         ctypes.c_int]
        lib.ptt_trace_dump.restype = ctypes.c_int64
        lib.ptt_trace_dump.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        lib.ptt_trace_destroy.argtypes = [ctypes.c_void_p]
        lib.ptd_parse_multislot.restype = ctypes.c_void_p
        lib.ptd_parse_multislot.argtypes = [ctypes.c_char_p, ctypes.c_int64,
                                            ctypes.c_int, ctypes.c_int]
        lib.ptd_slot_num_values.restype = ctypes.c_int64
        lib.ptd_slot_num_values.argtypes = [ctypes.c_void_p, ctypes.c_int]
        lib.ptd_slot_num_samples.restype = ctypes.c_int64
        lib.ptd_slot_num_samples.argtypes = [ctypes.c_void_p, ctypes.c_int]
        lib.ptd_slot_copy.argtypes = [ctypes.c_void_p, ctypes.c_int,
                                      ctypes.POINTER(ctypes.c_float),
                                      ctypes.POINTER(ctypes.c_int64)]
        lib.ptd_parsed_destroy.argtypes = [ctypes.c_void_p]
        _lib = lib
        return lib


def available():
    try:
        _load()
        return True
    except RuntimeError:
        return False


class NativeBlockingQueue:
    """MPMC bounded byte-buffer queue backed by C++ (GIL released during
    blocking waits via ctypes)."""

    def __init__(self, capacity=64):
        self._lib = _load()
        self._q = self._lib.ptq_queue_create(capacity)

    def put_bytes(self, data: bytes):
        buf = (ctypes.c_uint8 * len(data)).from_buffer_copy(data)
        r = self._lib.ptq_queue_put(self._q, buf, len(data))
        if r != 0:
            raise RuntimeError("queue closed")

    def put_array(self, arr: np.ndarray):
        self.put_bytes(np.ascontiguousarray(arr).tobytes())

    def get_bytes(self):
        size = self._lib.ptq_queue_front_size(self._q)
        if size < 0:
            return None
        out = (ctypes.c_uint8 * size)()
        n = self._lib.ptq_queue_get(self._q, out, size)
        if n < 0:
            return None
        return bytes(out[:n])

    def qsize(self):
        return self._lib.ptq_queue_size(self._q)

    def close(self):
        self._lib.ptq_queue_close(self._q)

    def __del__(self):
        try:
            self._lib.ptq_queue_destroy(self._q)
        except Exception:
            pass


class NativeArena:
    """Aligned host slab allocator with stats (reference allocator facade
    semantics for host staging buffers)."""

    def __init__(self):
        self._lib = _load()
        self._a = self._lib.pta_arena_create()

    def buffer(self, nbytes):
        """Allocate and return (numpy uint8 view, release callable)."""
        p = self._lib.pta_arena_alloc(self._a, nbytes)
        if not p:
            raise MemoryError(nbytes)
        arr = np.ctypeslib.as_array(
            ctypes.cast(p, ctypes.POINTER(ctypes.c_uint8)), (nbytes,))

        def release():
            self._lib.pta_arena_free(self._a, p, nbytes)
        return arr, release

    def stats(self):
        vals = [ctypes.c_size_t() for _ in range(4)]
        self._lib.pta_arena_stats(self._a, *[ctypes.byref(v) for v in vals])
        return {"allocated_bytes": vals[0].value,
                "in_use_bytes": vals[1].value,
                "alloc_calls": vals[2].value,
                "cache_hits": vals[3].value}

    def __del__(self):
        try:
            self._lib.pta_arena_destroy(self._a)
        except Exception:
            pass


class NativeTrace:
    """Host event collector -> chrome://tracing JSON."""

    def __init__(self):
        self._lib = _load()
        self._t = self._lib.ptt_trace_create()

    def now_us(self):
        return self._lib.ptt_trace_now_us(self._t)

    def record(self, name, ts_us, dur_us, tid=0):
        self._lib.ptt_trace_record(self._t, name.encode(), ts_us, dur_us, tid)

    def dump(self, path):
        return self._lib.ptt_trace_dump(self._t, path.encode())

    def __del__(self):
        try:
            self._lib.ptt_trace_destroy(self._t)
        except Exception:
            pass


def parse_multislot(text, num_slots, num_threads=4):
    """Parse slot-format text (reference MultiSlotDataFeed format: per line,
    per slot '<n> v1..vn'). Returns list of (values float32 array,
    offsets int64 array) per slot — CSR over samples."""
    lib = _load()
    data = text.encode() if isinstance(text, str) else text
    ps = lib.ptd_parse_multislot(data, len(data), num_slots, num_threads)
    out = []
    try:
        for s in range(num_slots):
            nv = lib.ptd_slot_num_values(ps, s)
            ns = lib.ptd_slot_num_samples(ps, s)
            vals = np.empty(nv, np.float32)
            offs = np.empty(ns + 1, np.int64)
            lib.ptd_slot_copy(
                ps, s, vals.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
                offs.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)))
            out.append((vals, offs))
    finally:
        lib.ptd_parsed_destroy(ps)
    return out
