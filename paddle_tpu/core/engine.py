"""Autograd backward engine.

TPU-native equivalent of the reference dygraph autograd engine (reference:
paddle/fluid/imperative/basic_engine.cc BasicEngine::Execute,
paddle/fluid/imperative/op_base.h:202 GradOpNode,
paddle/fluid/imperative/gradient_accumulator.cc). Differences:

- A GradNode's backward is the jax.vjp of the forward closure (jit-cached),
  rather than a separately-registered grad op; XLA prunes unused primal
  computation from the vjp.
- Gradient accumulation for leaf tensors is an in-place `.value` update so
  the accumulation threads through traced (to_static) steps.
- Topological traversal is an iterative postorder DFS instead of reference
  dependency counting; the visible semantics (sum-accumulation, hooks,
  stop_gradient cuts) match.
"""
import numpy as np
import jax
import jax.numpy as jnp

from . import lazy as lazy_mod

_ones_cache = {}  # backward seed cotangents, keyed by (shape, dtype)


class GradNode:
    __slots__ = ("op", "key", "closure", "arrays", "input_tensors",
                 "out_avals", "out_refs", "pending", "released", "multi_out")

    def __init__(self, op, key, closure, arrays, input_tensors, out_avals):
        self.op = op
        self.key = key
        self.closure = closure
        self.arrays = arrays
        # Tensor owner (or None for raw-array inputs) per array slot, aligned
        # with `arrays` and with jax.vjp's returned gradients.
        self.input_tensors = input_tensors
        self.out_avals = out_avals  # list of (shape, jnp dtype)
        self.out_refs = None
        self.pending = None  # cotangent slots during a backward run
        self.released = False
        self.multi_out = False

    def parents(self):
        seen = []
        for t in self.input_tensors:
            if t is not None and t._grad_node is not None:
                node = t._grad_node[0]
                if node is not self:
                    seen.append(node)
        return seen


def register_tensor_hook(tensor, hook):
    """Hook called with the gradient Tensor when it is computed; may return a
    replacement (reference: VarBase::RegisterGradHook via pybind). Fires for
    both leaf gradients (at accumulation) and non-leaf gradients (on the
    cotangent flowing into the producing node). Hooks live on the Tensor
    itself, so their lifetime matches the tensor's."""
    if tensor._hooks is None:
        tensor._hooks = []
    hooks = tensor._hooks
    hooks.append(hook)

    class _Removable:
        def remove(self_inner):
            try:
                hooks.remove(hook)
            except ValueError:
                pass
    return _Removable()


def _apply_hooks(tensor, grad_array):
    from .tensor import Tensor
    if tensor is None or not tensor._hooks:
        return grad_array
    g = Tensor(grad_array, stop_gradient=True)
    for h in list(tensor._hooks):
        out = h(g)
        if out is not None:
            g = out
    return g.value if isinstance(g, Tensor) else g


def _zero_ct(shape, dt):
    if jnp.issubdtype(dt, jnp.floating) or jnp.issubdtype(dt, jnp.complexfloating):
        return jnp.zeros(shape, dt)
    return np.zeros(shape, jax.dtypes.float0)


def _accumulate_into_leaf(tensor, grad_array, create_graph=False):
    from .tensor import Tensor
    from .sparse_grad import IndexedSlices, SparseGradTensor
    if isinstance(grad_array, IndexedSlices) and not create_graph:
        if tensor._hooks:
            # opaque hooks see dense tensors; correctness over sparsity
            grad_array = grad_array.to_dense()
        elif isinstance(tensor._grad, SparseGradTensor):
            tensor._grad.accumulate(grad_array)
            return
        elif tensor._grad is None:
            tensor._grad = SparseGradTensor(grad_array,
                                            name=tensor.name + "@GRAD")
            from . import trace as trace_mod
            ctx = trace_mod.current_trace()
            if ctx is not None:
                ctx.register_created(tensor._grad)
            return
        else:  # existing dense grad: densify the slices into it
            grad_array = grad_array.to_dense()
    elif isinstance(grad_array, IndexedSlices):
        grad_array = grad_array.to_dense()
    if create_graph:
        # grad_array is a live Tensor; keep its graph so grads of grads work
        g = grad_array
        if tensor._hooks:
            raise NotImplementedError(
                "tensor hooks are not supported together with "
                "create_graph=True (the hook would cut the double-grad "
                "chain)")
        tensor._grad = g if tensor._grad is None else tensor._grad + g
        tensor._grad.name = tensor.name + "@GRAD"
        from . import trace as trace_mod
        ctx = trace_mod.current_trace()
        if ctx is not None:
            ctx.register_created(tensor._grad)
        return
    grad_array = _apply_hooks(tensor, grad_array)
    if tensor._grad is None:
        tensor._grad = Tensor(grad_array, stop_gradient=True,
                              name=tensor.name + "@GRAD")
        from . import trace as trace_mod
        ctx = trace_mod.current_trace()
        if ctx is not None:
            ctx.register_created(tensor._grad)
    else:
        # keep the same Tensor object so traced steps functionalize correctly
        tensor._grad.value = lazy_mod.add(tensor._grad.value, grad_array)


def run_backward(loss, grad_tensor=None, retain_graph=False,
                 create_graph=False):
    from .tensor import Tensor
    if loss.stop_gradient or loss._grad_node is None:
        raise RuntimeError(
            f"Tensor {loss.name!r} has no grad graph (stop_gradient=True or "
            "no recorded ops)")
    root_node, root_idx = loss._grad_node
    if grad_tensor is None:
        shape, dt = root_node.out_avals[root_idx]
        ck = (tuple(shape), str(dt))
        init_ct = _ones_cache.get(ck)
        if init_ct is None:
            init_ct = jnp.ones(shape, dt)
            # under an active jax trace jnp.ones returns a TRACER;
            # caching it would leak it into every later trace as a
            # foreign constant (observed as "+2 buffers" executable
            # mismatches across tests) — cache concrete arrays only
            if not isinstance(init_ct, jax.core.Tracer):
                if len(_ones_cache) > 512:
                    _ones_cache.clear()
                _ones_cache[ck] = init_ct
    else:
        init_ct = grad_tensor.value if isinstance(grad_tensor, Tensor) else jnp.asarray(grad_tensor)
    if create_graph:
        # cotangents flow as live Tensors through differentiable vjp ops
        # (reference: partial_grad_engine.cc create_graph double-grad path);
        # the vjp ops capture closures by value, so the first-order nodes
        # need not be retained unless the caller asks
        if isinstance(grad_tensor, Tensor) and not grad_tensor.stop_gradient:
            init_ct = grad_tensor
        else:
            init_ct = Tensor(init_ct, stop_gradient=True)

    # Postorder DFS for reverse-topological order over reachable nodes.
    order = []
    state = {}  # node -> 0 visiting, 1 done
    stack = [(root_node, False)]
    while stack:
        node, processed = stack.pop()
        if processed:
            state[node] = 1
            order.append(node)
            continue
        if state.get(node) is not None:
            continue
        state[node] = 0
        stack.append((node, True))
        for p in node.parents():
            if state.get(p) is None:
                stack.append((p, False))

    for node in order:
        node.pending = [None] * len(node.out_avals)
    root_node.pending[root_idx] = init_ct

    lazy_bwd = not create_graph and lazy_mod.enabled()
    for node in reversed(order):
        cts = []
        any_ct = False
        for i, (shape, dt) in enumerate(node.out_avals):
            ct = node.pending[i]
            if ct is None:
                if lazy_bwd:
                    # deferred vjp treats None as an absent cotangent
                    # (builds the zeros inside the fused graph); avoids
                    # one eager bind per missing output
                    cts.append(None)
                    continue
                ct = _zero_ct(shape, dt)
                if create_graph:
                    from .tensor import Tensor as _T
                    if not (jnp.issubdtype(dt, jnp.floating)
                            or jnp.issubdtype(dt, jnp.complexfloating)):
                        ct = jnp.zeros(shape, dt)  # placeholder, see vjp_fn
                    ct = _T(ct, stop_gradient=True)
            else:
                any_ct = True
                if node.out_refs is not None and i < len(node.out_refs) \
                        and node.out_refs[i] is not None \
                        and node.out_refs[i]._hooks:
                    if create_graph:
                        # an opaque python hook would detach the cotangent
                        # and silently corrupt higher-order grads
                        raise NotImplementedError(
                            "tensor hooks are not supported together with "
                            "create_graph=True (the hook would cut the "
                            "double-grad chain)")
                    ct = _apply_hooks(node.out_refs[i], ct)
            cts.append(ct)
        node.pending = None
        if not any_ct:
            continue
        if node.released:
            raise RuntimeError(
                "trying to backward through a released graph; pass "
                "retain_graph=True to backward()")
        if create_graph:
            in_grads = _vjp_apply(node, cts)
        else:
            in_grads = None
            # only standard deferrable ops: custom op stand-ins (e.g.
            # _SparseLookupOp) override vjp_fn with semantics autodiff
            # of the closure would not reproduce (IndexedSlices grads)
            if node.closure is not None and getattr(node.op, "defer", False) \
                    and lazy_mod.enabled():
                # lazy micro-tracing: the vjp becomes a deferred node so
                # the whole backward fuses into the step's micro-graph
                try:
                    in_grads = lazy_mod.dispatch_vjp(node, cts)
                except lazy_mod.Fallback:
                    in_grads = None
            if in_grads is None:
                if lazy_mod.ever_enabled():
                    cts_c = [
                        _zero_ct(*node.out_avals[i]) if c is None
                        else lazy_mod.concrete(c)
                        for i, c in enumerate(cts)]
                else:
                    cts_c = cts
                ct_arg = tuple(cts_c) if node.multi_out else cts_c[0]
                bwd = node.op.vjp_fn(node.key, node.closure)
                arrays = node.arrays
                if arrays is not None and lazy_mod.ever_enabled():
                    arrays = [lazy_mod.concrete(a) for a in arrays]
                in_grads = bwd(arrays, ct_arg)
        _distribute(node, in_grads, create_graph)
        if not retain_graph:
            node.released = True
            node.arrays = None
            node.closure = None


_vjp_op_cache = {}


def _vjp_apply(node, ct_tensors):
    """Run a node's backward THROUGH the op dispatcher so the produced
    gradients carry their own grad nodes (double grad; reference:
    partial_grad_engine.cc). The vjp computation itself becomes a
    differentiable op over (original inputs..., cotangents...)."""
    from .tensor import Tensor
    from .dispatch import Op
    if node.closure is None:
        # PyLayer / custom nodes: user backward is opaque python — run it
        # normally; the chain stops there (grads are constants), matching
        # the reference, where PyLayer needs explicit double-grad support
        ct_vals = [c.value if isinstance(c, Tensor) else c
                   for c in ct_tensors]
        ct_arg = tuple(ct_vals) if node.multi_out else ct_vals[0]
        bwd = node.op.vjp_fn(node.key, node.closure)
        grads = bwd(node.arrays, ct_arg)
        return [Tensor(g, stop_gradient=True) if g is not None else None
                for g in grads]
    need = [i for i, t in enumerate(node.input_tensors)
            if t is not None and not t.stop_gradient]
    ckey = ("vjp", node.key, tuple(need))
    op = _vjp_op_cache.get(ckey)
    if op is None:
        closure = node.closure
        n_in = len(node.arrays)
        multi = node.multi_out
        need_c = list(need)

        def vjp_fn(*flat):
            arrays = flat[:n_in]
            cts = list(flat[n_in:])
            primals, vjp = jax.vjp(closure, *arrays)
            plist = list(primals) if isinstance(primals, (tuple, list)) \
                else [primals]
            for i, p in enumerate(plist):
                if not (jnp.issubdtype(p.dtype, jnp.floating)
                        or jnp.issubdtype(p.dtype, jnp.complexfloating)):
                    cts[i] = np.zeros(np.shape(p), jax.dtypes.float0)
                elif cts[i].dtype != p.dtype:
                    cts[i] = cts[i].astype(p.dtype)
            ct_arg = tuple(cts) if multi else cts[0]
            grads = vjp(ct_arg)
            outs = [grads[i] for i in need_c]
            return tuple(outs) if len(outs) != 1 else outs[0]

        # unique name per ckey: the dispatcher's jit cache keys on
        # (name, slots, attrs, cast), and distinct forward attrs (sum
        # axis, transpose perm, ...) produce distinct closures that would
        # otherwise collide under one shared name. A monotonic counter is
        # collision-free and deterministic within the process (a truncated
        # randomized hash would neither be).
        op = Op(f"vjp<{node.op.name}>#{len(_vjp_op_cache)}",
                vjp_fn, differentiable=True)
        _vjp_op_cache[ckey] = op
    # the vjp must see the FORWARD-TIME values (node.arrays), not the
    # tensors' current values (params may have been mutated by opt.step
    # since) — but the Tensor objects themselves must flow into the op so
    # the double-grad graph connects. Temporarily rebind each tensor's
    # value to its saved array around the dispatch (single-threaded eager).
    args = []
    stash = []
    for t, a in zip(node.input_tensors, node.arrays):
        if t is not None:
            stash.append((t, t._value))
            t._value = a
            args.append(t)
        else:
            args.append(a)
    try:
        outs = op(*args, *ct_tensors)
    finally:
        for t, v in stash:
            t._value = v
    outs = list(outs) if isinstance(outs, tuple) else [outs]
    in_grads = [None] * len(node.input_tensors)
    for j, i in enumerate(need):
        in_grads[i] = outs[j]
    return in_grads


def _distribute(node, in_grads, create_graph=False):
    from .sparse_grad import IndexedSlices
    # in_grads aligns with closure's positional arrays (= input_tensors slots)
    for t, g in zip(node.input_tensors, in_grads):
        if t is None or t.stop_gradient:
            continue
        if g is None or (hasattr(g, "dtype") and g.dtype == jax.dtypes.float0):
            continue
        if isinstance(g, IndexedSlices) and t._grad_node is not None:
            # non-leaf consumer: cotangent must be a dense array for the
            # upstream vjp
            g = g.to_dense()
        if t._grad_node is not None:
            pnode, pidx = t._grad_node
            if pnode.released:
                raise RuntimeError(
                    "trying to backward through a released graph; pass "
                    "retain_graph=True to backward()")
            if pnode.pending is None:
                pnode.pending = [None] * len(pnode.out_avals)
            if pnode.pending[pidx] is None:
                pnode.pending[pidx] = g
            elif create_graph:
                pnode.pending[pidx] = pnode.pending[pidx] + g
            else:
                pnode.pending[pidx] = lazy_mod.add(pnode.pending[pidx], g)
        else:
            _accumulate_into_leaf(t, g, create_graph)
