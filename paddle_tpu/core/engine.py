"""Autograd backward engine.

TPU-native equivalent of the reference dygraph autograd engine (reference:
paddle/fluid/imperative/basic_engine.cc BasicEngine::Execute,
paddle/fluid/imperative/op_base.h:202 GradOpNode,
paddle/fluid/imperative/gradient_accumulator.cc). Differences:

- A GradNode's backward is the jax.vjp of the forward closure (jit-cached),
  rather than a separately-registered grad op; XLA prunes unused primal
  computation from the vjp.
- Gradient accumulation for leaf tensors is an in-place `.value` update so
  the accumulation threads through traced (to_static) steps.
- Topological traversal is an iterative postorder DFS instead of reference
  dependency counting; the visible semantics (sum-accumulation, hooks,
  stop_gradient cuts) match.
"""
import numpy as np
import jax
import jax.numpy as jnp


class GradNode:
    __slots__ = ("op", "key", "closure", "arrays", "input_tensors",
                 "out_avals", "out_refs", "pending", "released", "multi_out")

    def __init__(self, op, key, closure, arrays, input_tensors, out_avals):
        self.op = op
        self.key = key
        self.closure = closure
        self.arrays = arrays
        # Tensor owner (or None for raw-array inputs) per array slot, aligned
        # with `arrays` and with jax.vjp's returned gradients.
        self.input_tensors = input_tensors
        self.out_avals = out_avals  # list of (shape, jnp dtype)
        self.out_refs = None
        self.pending = None  # cotangent slots during a backward run
        self.released = False
        self.multi_out = False

    def parents(self):
        seen = []
        for t in self.input_tensors:
            if t is not None and t._grad_node is not None:
                node = t._grad_node[0]
                if node is not self:
                    seen.append(node)
        return seen


def register_tensor_hook(tensor, hook):
    """Hook called with the gradient Tensor when it is computed; may return a
    replacement (reference: VarBase::RegisterGradHook via pybind). Fires for
    both leaf gradients (at accumulation) and non-leaf gradients (on the
    cotangent flowing into the producing node). Hooks live on the Tensor
    itself, so their lifetime matches the tensor's."""
    if tensor._hooks is None:
        tensor._hooks = []
    hooks = tensor._hooks
    hooks.append(hook)

    class _Removable:
        def remove(self_inner):
            try:
                hooks.remove(hook)
            except ValueError:
                pass
    return _Removable()


def _apply_hooks(tensor, grad_array):
    from .tensor import Tensor
    if tensor is None or not tensor._hooks:
        return grad_array
    g = Tensor(grad_array, stop_gradient=True)
    for h in list(tensor._hooks):
        out = h(g)
        if out is not None:
            g = out
    return g.value if isinstance(g, Tensor) else g


def _zero_ct(shape, dt):
    if jnp.issubdtype(dt, jnp.floating) or jnp.issubdtype(dt, jnp.complexfloating):
        return jnp.zeros(shape, dt)
    return np.zeros(shape, jax.dtypes.float0)


def _accumulate_into_leaf(tensor, grad_array):
    from .tensor import Tensor
    grad_array = _apply_hooks(tensor, grad_array)
    if tensor._grad is None:
        tensor._grad = Tensor(grad_array, stop_gradient=True,
                              name=tensor.name + "@GRAD")
        from . import trace as trace_mod
        ctx = trace_mod.current_trace()
        if ctx is not None:
            ctx.register_created(tensor._grad)
    else:
        # keep the same Tensor object so traced steps functionalize correctly
        tensor._grad.value = tensor._grad.value + grad_array


def run_backward(loss, grad_tensor=None, retain_graph=False):
    from .tensor import Tensor
    if loss.stop_gradient or loss._grad_node is None:
        raise RuntimeError(
            f"Tensor {loss.name!r} has no grad graph (stop_gradient=True or "
            "no recorded ops)")
    root_node, root_idx = loss._grad_node
    if grad_tensor is None:
        shape, dt = root_node.out_avals[root_idx]
        init_ct = jnp.ones(shape, dt)
    else:
        init_ct = grad_tensor.value if isinstance(grad_tensor, Tensor) else jnp.asarray(grad_tensor)

    # Postorder DFS for reverse-topological order over reachable nodes.
    order = []
    state = {}  # node -> 0 visiting, 1 done
    stack = [(root_node, False)]
    while stack:
        node, processed = stack.pop()
        if processed:
            state[node] = 1
            order.append(node)
            continue
        if state.get(node) is not None:
            continue
        state[node] = 0
        stack.append((node, True))
        for p in node.parents():
            if state.get(p) is None:
                stack.append((p, False))

    for node in order:
        node.pending = [None] * len(node.out_avals)
    root_node.pending[root_idx] = init_ct

    for node in reversed(order):
        cts = []
        any_ct = False
        for i, (shape, dt) in enumerate(node.out_avals):
            ct = node.pending[i]
            if ct is None:
                ct = _zero_ct(shape, dt)
            else:
                any_ct = True
                if node.out_refs is not None and i < len(node.out_refs):
                    ct = _apply_hooks(node.out_refs[i], ct)
            cts.append(ct)
        node.pending = None
        if not any_ct:
            continue
        if node.released:
            raise RuntimeError(
                "trying to backward through a released graph; pass "
                "retain_graph=True to backward()")
        ct_arg = tuple(cts) if node.multi_out else cts[0]
        bwd = node.op.vjp_fn(node.key, node.closure)
        in_grads = bwd(node.arrays, ct_arg)
        _distribute(node, in_grads)
        if not retain_graph:
            node.released = True
            node.arrays = None
            node.closure = None


def _distribute(node, in_grads):
    # in_grads aligns with closure's positional arrays (= input_tensors slots)
    for t, g in zip(node.input_tensors, in_grads):
        if t is None or t.stop_gradient:
            continue
        if g is None or (hasattr(g, "dtype") and g.dtype == jax.dtypes.float0):
            continue
        if t._grad_node is not None:
            pnode, pidx = t._grad_node
            if pnode.released:
                raise RuntimeError(
                    "trying to backward through a released graph; pass "
                    "retain_graph=True to backward()")
            if pnode.pending is None:
                pnode.pending = [None] * len(pnode.out_avals)
            if pnode.pending[pidx] is None:
                pnode.pending[pidx] = g
            else:
                pnode.pending[pidx] = pnode.pending[pidx] + g
        else:
            _accumulate_into_leaf(t, g)
