"""Sparse (row-slice) gradients for embedding tables.

TPU-native equivalent of the reference's SelectedRows gradient
representation (reference: paddle/fluid/framework/selected_rows.h:41,
paddle/fluid/imperative/gradient_accumulator.cc SelectedRows paths,
paddle/fluid/operators/optimizers/adam_op.h lazy_mode sparse update).

Design: eager-mode embedding lookups with sparse=True produce an
IndexedSlices gradient — {indices, values rows, full dense shape} — so a
large-vocab table never materializes a [vocab, dim] dense gradient on the
host-visible path. Accumulation merges slices; the optimizers' sparse
paths update only the touched rows (scatter ops XLA executes in O(rows)).
Inside a compiled (to_static) step the dense vjp path is used instead:
XLA fuses the one-hot scatter-add and the update into the program, which
is already the memory-optimal form under jit.

A SparseGradTensor is a Tensor whose dense value materializes lazily: any
consumer that reads `.value` (hooks, user numpy access, unaware
optimizers) transparently gets the dense array; sparse-aware consumers
check `.is_sparse()` first and read `.slices`.
"""
import numpy as np
import jax
import jax.numpy as jnp

from .tensor import Tensor


class IndexedSlices:
    """Rows `values[k]` sit at row `indices[k]` of a dense tensor of shape
    `full_shape`; unlisted rows are zero. Duplicate indices mean
    sum-accumulation (same as SelectedRows)."""

    __slots__ = ("indices", "values", "full_shape", "coalesced")

    def __init__(self, indices, values, full_shape, coalesced=False):
        self.indices = indices
        self.values = values
        self.full_shape = tuple(full_shape)
        self.coalesced = coalesced

    @property
    def nbytes(self):
        return self.values.nbytes + self.indices.nbytes

    @property
    def dtype(self):
        return self.values.dtype

    def merge(self, other):
        """Concatenate slice sets (sum semantics via duplicate indices)."""
        assert self.full_shape == other.full_shape
        return IndexedSlices(
            jnp.concatenate([self.indices, other.indices], axis=0),
            jnp.concatenate([self.values, other.values], axis=0),
            self.full_shape)

    def coalesce(self):
        """Sum duplicate rows -> unique, sorted indices (reference:
        scatter::MergeAdd on SelectedRows). Eager-only (dynamic shape)."""
        if self.coalesced:
            return self
        uniq, inv = jnp.unique(self.indices, return_inverse=True)
        summed = jax.ops.segment_sum(self.values, inv.reshape(-1),
                                     num_segments=int(uniq.shape[0]))
        return IndexedSlices(uniq, summed, self.full_shape, coalesced=True)

    def to_dense(self):
        dense = jnp.zeros(self.full_shape, self.values.dtype)
        return dense.at[self.indices].add(self.values)

    def scale(self, factor):
        return IndexedSlices(self.indices, self.values * factor,
                             self.full_shape, coalesced=self.coalesced)

    def __repr__(self):
        return (f"IndexedSlices(rows={int(self.indices.shape[0])}, "
                f"full_shape={self.full_shape})")


class SparseGradTensor(Tensor):
    """Gradient tensor backed by IndexedSlices; densifies lazily on
    `.value` access (paddle analogue: a Variable holding SelectedRows that
    unaware ops see through a to-dense cast)."""

    __slots__ = ("slices",)

    def __init__(self, slices, name=None):
        # _value stays None until someone asks for the dense view
        super().__init__(jnp.zeros((), slices.values.dtype), name=name,
                         stop_gradient=True)
        self._value = None
        self.slices = slices

    def is_sparse(self):
        return self._value is None and self.slices is not None

    is_selected_rows = is_sparse

    @property
    def value(self):
        if self._value is None and self.slices is not None:
            self._value = self.slices.to_dense()
        return Tensor.value.fget(self)

    @value.setter
    def value(self, v):
        self.slices = None
        Tensor.value.fset(self, v)

    def aval_shape(self):
        if self._value is None and self.slices is not None:
            return self.slices.full_shape
        return super().aval_shape()

    @property
    def dtype(self):
        if self._value is None and self.slices is not None:
            from . import dtype as dtype_mod
            return dtype_mod.to_paddle_dtype(self.slices.values.dtype)
        return Tensor.dtype.fget(self)

    def accumulate(self, other):
        """Sum-accumulate another gradient (IndexedSlices or dense array)
        into this one, staying sparse when possible."""
        if isinstance(other, IndexedSlices) and self.is_sparse():
            self.slices = self.slices.merge(other)
            return self
        if isinstance(other, IndexedSlices):
            other = other.to_dense()
        self.value = self.value + other
        return self
