"""Global random state.

TPU-native equivalent of the reference's global Generator / seed system
(reference: paddle/fluid/framework/generator.cc, python paddle.seed).
Design: the generator state is itself a framework Tensor holding a JAX PRNG
key. Every random op splits the key through the normal op dispatcher, so
the state mutation is observed by the trace context — a compiled training
step automatically threads RNG state in and out, giving different dropout
masks per step (the reference achieves this with a stateful cuRAND
generator; we get it functionally).
"""
import jax
import jax.numpy as jnp

from .dispatch import register_op
from .tensor import Tensor


@register_op("rng_split", differentiable=False, defer=False)
def _rng_split(state):
    k1, k2 = jax.random.split(state)
    return k1, k2


class Generator:
    """Stateful generator; key creation is lazy so importing the package
    does not touch the device runtime."""

    def __init__(self, seed=0):
        self._state = None
        self._seed = seed

    @property
    def state(self):
        if self._state is None:
            self._state = Tensor(jax.random.PRNGKey(self._seed),
                                 stop_gradient=True, name="rng_state",
                                 persistable=True)
        return self._state

    def manual_seed(self, seed):
        self._seed = seed
        self.state.value = jax.random.PRNGKey(seed)
        return self

    def initial_seed(self):
        return self._seed

    def next_key(self):
        """Returns a fresh PRNG key Tensor and advances the state in place."""
        new_state, key = _rng_split(self.state)
        self.state.value = new_state.value
        return key


default_generator = Generator(0)


def seed(s):
    """paddle.seed equivalent."""
    default_generator.manual_seed(int(s))
    return default_generator


def next_key():
    return default_generator.next_key()


def get_state():
    """Snapshot of the default generator state (for checkpoint/RNG-state
    save parity with get_cuda_rng_state)."""
    import numpy as np
    return np.asarray(default_generator.state.value).copy()


def set_state(state):
    import jax.numpy as jnp
    default_generator.state.value = jnp.asarray(state)
