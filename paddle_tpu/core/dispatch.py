"""Op registry and eager dispatcher.

TPU-native equivalent of the reference op system + dygraph tracer
(reference: paddle/fluid/framework/operator.h:138,466 OperatorWithKernel,
paddle/fluid/imperative/tracer.cc:144 Tracer::TraceOp,
paddle/fluid/pybind/op_function_generator.cc:519 generated _C_ops entry
points). Design differences, deliberate and TPU-first:

- An op "kernel" is a pure jax function building XLA HLO, not a CUDA kernel.
  Eager dispatch executes it through a jit-compiled executable cached per
  (op, static attrs, amp-state, input avals) — jax.jit provides the
  aval-level cache; we cache the jitted callable per (op, attrs).
- The backward kernel is derived automatically via jax.vjp of the same
  function (reference analogue: per-op GradOpMaker,
  paddle/fluid/framework/grad_op_desc_maker.h:61) and jit-cached the same
  way. XLA dead-code-eliminates any forward recomputation the vjp does not
  need.
- Under a TraceContext (to_static / jit capture) ops apply the raw jax
  function so tracers flow through and the whole step fuses into one XLA
  program — the analogue of running a ProgramDesc through the Executor,
  minus the interpreter.
- AMP autocast is applied inside the jitted closure (reference:
  paddle/fluid/imperative/amp_auto_cast.h:85 AutoCastInputs) so the cast
  fuses with the op.
"""
import functools
import threading

import jax
import jax.numpy as jnp
import numpy as np

from . import trace as trace_mod
from . import flags as flags_mod
from . import lazy as lazy_mod

_grad_state = threading.local()


def is_grad_enabled():
    return getattr(_grad_state, "enabled", True)


class no_grad:
    """paddle.no_grad: context manager + decorator disabling tape recording."""

    def __enter__(self):
        self._prev = is_grad_enabled()
        _grad_state.enabled = False
        return self

    def __exit__(self, *exc):
        _grad_state.enabled = self._prev
        return False

    def __call__(self, fn):
        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            with no_grad():
                return fn(*args, **kwargs)
        return wrapper


class enable_grad:
    def __enter__(self):
        self._prev = is_grad_enabled()
        _grad_state.enabled = True
        return self

    def __exit__(self, *exc):
        _grad_state.enabled = self._prev
        return False


_REGISTRY = {}
_jit_cache = {}

# set by paddle_tpu.static.program: the Variable class, plus an
# is-anyone-building flag maintained by _set_building so the eager hot
# path pays one boolean test, not a per-arg isinstance scan
_static_variable_cls = None
_static_active = False


def get_op(name):
    return _REGISTRY[name]


def _hashable(x):
    if isinstance(x, (list, tuple)):
        return tuple(_hashable(v) for v in x)
    if isinstance(x, dict):
        return tuple(sorted((k, _hashable(v)) for k, v in x.items()))
    if isinstance(x, np.ndarray):
        return (x.shape, str(x.dtype), x.tobytes())
    return x


class Op:
    """A differentiable primitive: a pure jax function over arrays.

    `fn(*arrays, **attrs)` where every positional arg is an array and every
    keyword arg is a static attribute. The public wrapper accepts Tensors in
    positional slots (None allowed for optional tensors) and plain python
    values as attrs.
    """

    def __init__(self, name, fn, differentiable=True, defer=True):
        self.name = name
        self.fn = fn
        self.differentiable = differentiable
        # defer=False opts out of lazy micro-tracing (e.g. RNG key
        # splitting, whose outputs feed raw jax.random calls that cannot
        # abstractify a LazyArray)
        self.defer = defer
        _REGISTRY[name] = self

    def __repr__(self):
        return f"<op {self.name}>"

    def __call__(self, *args, **attrs):
        from .tensor import Tensor
        from .engine import GradNode

        if _static_active \
                and any(isinstance(a, _static_variable_cls) for a in args):
            # static-graph building (paddle.enable_static): record the op
            # into the current Program instead of executing (reference:
            # framework.py append_op path of every layer/op helper). The
            # active AMP autocast list is captured per op record — the
            # reference's static-AMP program rewrite
            # (fluid/contrib/mixed_precision/decorator.py)
            from ..static.program import building_program
            from ..amp.auto_cast import _cast_dtype_for
            prog = building_program()
            if prog is None:
                raise RuntimeError(
                    f"op {self.name!r} called on a static Variable outside "
                    "a program_guard / enable_static context")
            return prog.append_op(self, args, attrs,
                                  cast_dtype=_cast_dtype_for(self.name))

        tensor_args = []   # Tensor (or None) owner per *array slot*
        arrays = []
        slots = []  # index into arrays per positional slot, or None
        for a in args:
            if isinstance(a, Tensor):
                slots.append(len(arrays))
                tensor_args.append(a)
                arrays.append(a.value)  # may notify trace ctx
            elif a is None:
                slots.append(None)
            else:
                # allow raw arrays / numpy / python scalars as dynamic inputs
                slots.append(len(arrays))
                tensor_args.append(None)
                if isinstance(a, jax.Array):
                    arr = a
                elif type(a) in (int, float, bool):
                    arr = lazy_mod.scalar_const(a)
                else:
                    arr = jnp.asarray(a)
                arrays.append(arr)

        from ..amp.auto_cast import _cast_dtype_for
        cast_dtype = _cast_dtype_for(self.name)

        attr_key = _hashable(attrs)
        key = (self.name, tuple(slots), attr_key, cast_dtype)
        closure = self._closure(key, tuple(slots), attrs, cast_dtype)

        ctx = trace_mod.current_trace()
        if ctx is not None and ctx.mode == "jit":
            outs = closure(*arrays)
        else:
            outs = None
            if ctx is None and self.defer and lazy_mod.enabled():
                # lazy micro-tracing (SURVEY §7 hard-part 1): defer the
                # op into the thread's micro-graph; a whole eager step
                # flushes as ONE cached executable at the next
                # materialization / step boundary
                try:
                    outs = lazy_mod.dispatch(closure, key, arrays)
                except lazy_mod.Fallback:
                    outs = None
            if outs is None:
                if lazy_mod.ever_enabled():
                    arrays = [lazy_mod.concrete(a) for a in arrays]
                jitted = _jit_cache.get(key)
                if jitted is None:
                    jitted = jax.jit(closure)
                    _jit_cache[key] = jitted
                outs = jitted(*arrays)

        multi = isinstance(outs, (tuple, list))
        out_list = list(outs) if multi else [outs]

        if flags_mod.get_flag("FLAGS_check_nan_inf") and ctx is None:
            _check_finite(self.name, out_list)

        record = (self.differentiable and is_grad_enabled()
                  and any(t is not None and not t.stop_gradient
                          for t in tensor_args))

        out_tensors = []
        for o in out_list:
            t = Tensor(o, stop_gradient=not (record and _is_float(o)))
            if ctx is not None:
                ctx.register_created(t)
            out_tensors.append(t)

        if record:
            node = GradNode(self, key, closure, arrays, tensor_args,
                            [ (o.shape, o.dtype) for o in out_list ])
            node.multi_out = multi
            for i, t in enumerate(out_tensors):
                if not t.stop_gradient:
                    t._grad_node = (node, i)
            node.out_refs = out_tensors  # strong refs OK; graph freed after bwd

        return tuple(out_tensors) if multi else out_tensors[0]

    def _closure(self, key, slots, attrs, cast_dtype):
        fn = self.fn

        def closure(*arrays):
            call_args = []
            for s in slots:
                if s is None:
                    call_args.append(None)
                else:
                    a = arrays[s]
                    if cast_dtype is not None and jnp.issubdtype(a.dtype, jnp.floating):
                        a = a.astype(cast_dtype)
                    call_args.append(a)
            return fn(*call_args, **attrs)
        closure.__name__ = self.name
        return closure

    def vjp_fn(self, key, closure):
        def bwd_impl(arrays, cts):
            primals, vjp = jax.vjp(closure, *arrays)
            # under AMP the closure's outputs may be bf16/fp16 while the
            # downstream cotangent is fp32 (a later op ran in fp32, e.g.
            # blacklisted reductions); align ct dtype with the primal out
            # or the transpose rules see mixed dtypes
            def _align(ct, p):
                if hasattr(ct, "dtype") and hasattr(p, "dtype") \
                        and ct.dtype != p.dtype:
                    return ct.astype(p.dtype)
                return ct
            if isinstance(primals, (tuple, list)):
                cts = type(cts)(_align(c, p) for c, p in zip(cts, primals))
            else:
                cts = _align(cts, primals)
            return vjp(cts)
        ctx = trace_mod.current_trace()
        if ctx is not None and ctx.mode == "jit":
            return bwd_impl
        bkey = key + ("<vjp>",)
        bwd = _jit_cache.get(bkey)
        if bwd is None:
            bwd = jax.jit(bwd_impl)
            _jit_cache[bkey] = bwd
        return bwd


_FLOAT_DTYPE_CACHE = {}


def _is_float(arr):
    dt = arr.dtype
    hit = _FLOAT_DTYPE_CACHE.get(dt)
    if hit is None:
        hit = bool(jnp.issubdtype(dt, jnp.floating)
                   or jnp.issubdtype(dt, jnp.complexfloating))
        _FLOAT_DTYPE_CACHE[dt] = hit
    return hit


def _check_finite(op_name, out_list):
    for o in out_list:
        if _is_float(o) and not bool(jnp.all(jnp.isfinite(o))):
            raise FloatingPointError(
                f"Operator {op_name} output contains NaN or Inf "
                f"(FLAGS_check_nan_inf is set)")


def register_op(name, differentiable=True, defer=True):
    """Decorator: register a pure jax function as a framework op."""
    def deco(fn):
        return Op(name, fn, differentiable=differentiable, defer=defer)
    return deco
