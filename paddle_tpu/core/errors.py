"""Error-code taxonomy + enforce helpers.

Reference parity: paddle/fluid/platform/enforce.h:427 (PADDLE_ENFORCE*
macros), paddle/fluid/platform/errors.h + error_codes.proto (LEGACY,
INVALID_ARGUMENT, NOT_FOUND, OUT_OF_RANGE, ALREADY_EXISTS,
RESOURCE_EXHAUSTED, PRECONDITION_NOT_MET, PERMISSION_DENIED,
EXECUTION_TIMEOUT, UNIMPLEMENTED, UNAVAILABLE, FATAL, EXTERNAL) and
python/paddle/fluid/core error mapping (each code raises a dedicated
Python exception type that ALSO subclasses the natural builtin, so
except ValueError-style user code keeps working).
"""


class OutOfRangeError(IndexError):
    code = "OUT_OF_RANGE"


class AlreadyExistsError(ValueError):
    code = "ALREADY_EXISTS"


class ResourceExhaustedError(MemoryError):
    code = "RESOURCE_EXHAUSTED"


class PreconditionNotMetError(RuntimeError):
    code = "PRECONDITION_NOT_MET"


class PermissionDeniedError(PermissionError):
    code = "PERMISSION_DENIED"


class ExecutionTimeoutError(TimeoutError):
    code = "EXECUTION_TIMEOUT"


class UnimplementedError(NotImplementedError):
    code = "UNIMPLEMENTED"


class UnavailableError(RuntimeError):
    code = "UNAVAILABLE"


class FatalError(RuntimeError):
    code = "FATAL"


class ExternalError(OSError):
    code = "EXTERNAL"


class InvalidArgumentError(ValueError):
    code = "INVALID_ARGUMENT"


class NotFoundError(FileNotFoundError):
    code = "NOT_FOUND"


_ALL = (OutOfRangeError, AlreadyExistsError, ResourceExhaustedError,
        PreconditionNotMetError, PermissionDeniedError,
        ExecutionTimeoutError, UnimplementedError, UnavailableError,
        FatalError, ExternalError, InvalidArgumentError, NotFoundError)


def error_for_code(code):
    for cls in _ALL:
        if cls.code == code:
            return cls
    return FatalError


# -- enforce helpers (reference: enforce.h PADDLE_ENFORCE_* macros) -------

def enforce(cond, msg, exc=InvalidArgumentError):
    if not cond:
        raise exc(msg)


def enforce_eq(a, b, msg=None, exc=InvalidArgumentError):
    if a != b:
        raise exc(msg or f"expected equality, got {a!r} != {b!r}")


def enforce_ne(a, b, msg=None, exc=InvalidArgumentError):
    if a == b:
        raise exc(msg or f"expected inequality, got {a!r} == {b!r}")


def enforce_gt(a, b, msg=None, exc=InvalidArgumentError):
    if not a > b:
        raise exc(msg or f"expected {a!r} > {b!r}")


def enforce_ge(a, b, msg=None, exc=InvalidArgumentError):
    if not a >= b:
        raise exc(msg or f"expected {a!r} >= {b!r}")


def enforce_lt(a, b, msg=None, exc=InvalidArgumentError):
    if not a < b:
        raise exc(msg or f"expected {a!r} < {b!r}")


def enforce_le(a, b, msg=None, exc=InvalidArgumentError):
    if not a <= b:
        raise exc(msg or f"expected {a!r} <= {b!r}")


def enforce_not_none(v, msg=None, exc=NotFoundError):
    if v is None:
        raise exc(msg or "expected a value, got None")
    return v
