"""Eager Tensor.

TPU-native equivalent of the reference eager tensor stack (reference:
paddle/fluid/imperative/layer.h:66 VarBase + paddle/fluid/framework/tensor.h:89
Tensor + pybind tensor_py.h numpy interop). A Tensor wraps an immutable
jax.Array; paddle's in-place mutation semantics (optimizer updates,
set_value) are expressed by swapping the wrapped array, which the trace
context observes to functionalize compiled steps (see core/trace.py).
Autograd metadata (grad tensor, producing GradNode, stop_gradient) lives
here, mirroring VarBase's autograd fields.
"""
import numpy as np
import jax
import jax.numpy as jnp

from . import dtype as dtype_mod
from . import device as device_mod
from . import trace as trace_mod
from .lazy import LazyArray as _LazyArray

_name_counter = [0]


def _auto_name(prefix="tensor"):
    _name_counter[0] += 1
    return f"{prefix}_{_name_counter[0]}"


class Tensor:
    __slots__ = ("_value", "name", "stop_gradient", "persistable",
                 "_grad", "_grad_node", "trainable", "_hooks", "tp_spec",
                 "__weakref__")

    def __init__(self, value, dtype=None, place=None, stop_gradient=True,
                 name=None, persistable=False):
        if isinstance(value, Tensor):
            value = value.value
        if isinstance(value, _LazyArray) and dtype is None:
            pass  # keep the deferred value — no materialization
        elif not isinstance(value, jax.Array) or dtype is not None:
            jdt = dtype_mod.to_jax_dtype(dtype) if dtype is not None else None
            value = jnp.asarray(value, dtype=jdt)
        if place is not None and not isinstance(value, jax.core.Tracer):
            value = jax.device_put(value, place.jax_device())
        self._value = value
        self.name = name or _auto_name()
        self.stop_gradient = stop_gradient
        self.persistable = persistable
        self.trainable = True
        self._grad = None
        self._grad_node = None
        self._hooks = None
        self.tp_spec = None
        if trace_mod._birth_hook is not None:
            trace_mod._birth_hook(self)

    # ---- value plumbing (trace-aware) -----------------------------------
    @property
    def value(self):
        ctx = trace_mod.current_trace()
        if ctx is not None:
            return ctx.read(self)
        if self._value is None:
            raise RuntimeError(
                f"Tensor {self.name!r} has no value; it escaped a jit trace. "
                "Keep backward/step/clear_grad inside the traced function.")
        return self._value

    @value.setter
    def value(self, v):
        from . import dispatch as _d
        if _d._static_active and isinstance(v, _d._static_variable_cls):
            # static building: `param.value = new_param.value` in an
            # optimizer's _apply_one records an in-place write-back of
            # the producing op's output onto this persistable tensor
            v.program.mark_writeback(v, self)
            return
        ctx = trace_mod.current_trace()
        if ctx is not None:
            ctx.write(self, v)
        else:
            self._value = v

    def set_value(self, value):
        """In-place assignment (reference: paddle.Tensor.set_value)."""
        if isinstance(value, Tensor):
            value = value.value
        arr = jnp.asarray(value, dtype=self.value.dtype)
        if tuple(arr.shape) != tuple(self.shape):
            from .errors import InvalidArgumentError
            raise InvalidArgumentError(
                f"set_value shape mismatch {arr.shape} vs {tuple(self.shape)}")
        self.value = arr
        return self

    # ---- metadata --------------------------------------------------------
    @property
    def shape(self):
        return list(self.aval_shape())

    def aval_shape(self):
        v = self._value
        if v is None:
            ctx = trace_mod.current_trace()
            if ctx is not None:
                v = ctx.final_value(self)
        return tuple(v.shape)

    @property
    def ndim(self):
        return len(self.aval_shape())

    @property
    def dtype(self):
        v = self._value
        if v is None:
            ctx = trace_mod.current_trace()
            if ctx is not None:
                v = ctx.final_value(self)
        return dtype_mod.to_paddle_dtype(v.dtype)

    @property
    def place(self):
        return device_mod.get_place()

    @property
    def size(self):
        return int(np.prod(self.aval_shape())) if self.aval_shape() else 1

    def numel(self):
        return self.size

    def dim(self):
        return self.ndim

    @property
    def T(self):
        from .. import ops
        return ops.manipulation.t(self)

    @property
    def grad(self):
        return self._grad

    @grad.setter
    def grad(self, g):
        self._grad = g

    @property
    def is_leaf(self):
        return self._grad_node is None

    # ---- host interop ----------------------------------------------------
    def numpy(self):
        v = self.value
        if isinstance(v, jax.core.Tracer):
            raise RuntimeError("cannot call .numpy() inside a jit trace")
        if v.dtype == jnp.bfloat16:
            return np.asarray(v)
        return np.asarray(v)

    def item(self, *args):
        return self.numpy().item(*args)

    def tolist(self):
        return self.numpy().tolist()

    def __float__(self):
        return float(self.item())

    def __int__(self):
        return int(self.item())

    def __bool__(self):
        if self.size != 1:
            raise ValueError("truth value of multi-element Tensor is ambiguous")
        return bool(self.numpy())

    def __len__(self):
        s = self.aval_shape()
        if not s:
            raise TypeError("len() of a 0-d tensor")
        return s[0]

    def __repr__(self):
        try:
            data = self.numpy()
            body = np.array2string(np.asarray(data, dtype=np.float32)
                                   if self.dtype.name == "bfloat16" else data,
                                   precision=6, threshold=64)
        except RuntimeError:
            body = "<traced>"
        return (f"Tensor(shape={self.shape}, dtype={self.dtype.name}, "
                f"place={self.place}, stop_gradient={self.stop_gradient},\n"
                f"       {body})")

    # ---- autograd --------------------------------------------------------
    def backward(self, grad_tensor=None, retain_graph=False):
        from .engine import run_backward
        run_backward(self, grad_tensor, retain_graph)

    def clear_grad(self):
        self._grad = None

    clear_gradient = clear_grad

    def detach(self):
        t = Tensor(self.value, stop_gradient=True, name=self.name + ".detach")
        return t

    def detach_(self):
        self.stop_gradient = True
        self._grad_node = None
        return self

    def clone(self):
        from .. import ops
        return ops.math.clone(self)

    def register_hook(self, hook):
        from .engine import register_tensor_hook
        return register_tensor_hook(self, hook)

    # ---- conversion ------------------------------------------------------
    def astype(self, dtype):
        from .. import ops
        return ops.math.cast(self, dtype=dtype_mod.to_jax_dtype(dtype))

    def cast(self, dtype):
        return self.astype(dtype)

    def cpu(self):
        return self

    def cuda(self, *a, **k):
        return self

    def to(self, *args, **kwargs):
        for a in args:
            if isinstance(a, (str, dtype_mod.DType)):
                try:
                    return self.astype(a)
                except ValueError:
                    pass
        return self

    # ---- operators: patched in ops/__init__.py ---------------------------

    def __hash__(self):
        return id(self)


class Parameter(Tensor):
    """Trainable tensor (reference: python/paddle/fluid/framework.py Parameter).
    Defaults stop_gradient=False and persistable=True."""
    __slots__ = ("optimize_attr", "regularizer", "need_clip", "is_distributed")

    def __init__(self, value, dtype=None, name=None, trainable=True):
        super().__init__(value, dtype=dtype, stop_gradient=not trainable,
                         name=name or _auto_name("param"), persistable=True)
        self.trainable = trainable
        self.optimize_attr = {"learning_rate": 1.0}
        self.regularizer = None
        self.need_clip = True
        self.is_distributed = False

    def __repr__(self):
        return "Parameter " + super().__repr__()
