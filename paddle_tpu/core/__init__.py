"""Core runtime: dtype/device/dispatch/tensor/autograd/trace/flags/rng."""
import jax

# Match reference dtype semantics (int64 / float64 tensors exist as real
# dtypes; reference framework.proto VarType supports FP64/INT64). TPU work
# should use float32/bfloat16 explicitly — creation APIs default to float32.
jax.config.update("jax_enable_x64", True)

from . import dtype, device, flags, trace, dispatch, tensor, engine, rng  # noqa: E402,F401
from .tensor import Tensor, Parameter  # noqa: E402,F401
from .dispatch import no_grad, enable_grad, is_grad_enabled, register_op  # noqa: E402,F401

flags.init_compilation_cache()
