"""Dtype system for paddle_tpu.

TPU-native equivalent of the reference dtype enum (reference:
paddle/fluid/framework/framework.proto VarType.Type and
paddle/fluid/platform/float16.h / bfloat16.h). On TPU the portable scalar
types are provided by XLA itself, so this module is a thin mapping layer
between paddle-style dtype names and numpy/jax dtypes.
"""
import numpy as np
import jax.numpy as jnp


class DType:
    """A paddle-style dtype handle wrapping a jax/numpy dtype."""

    __slots__ = ("name", "np_dtype")

    def __init__(self, name, np_dtype):
        self.name = name
        self.np_dtype = np.dtype(np_dtype) if name != "bfloat16" else jnp.bfloat16

    def __repr__(self):
        return f"paddle_tpu.{self.name}"

    def __eq__(self, other):
        if isinstance(other, DType):
            return self.name == other.name
        try:
            return to_paddle_dtype(other).name == self.name
        except (TypeError, ValueError):
            return NotImplemented

    def __hash__(self):
        return hash(self.name)

    @property
    def is_floating(self):
        return self.name in ("float16", "bfloat16", "float32", "float64")

    @property
    def is_complex(self):
        return self.name in ("complex64", "complex128")

    @property
    def is_integer(self):
        return self.name in ("int8", "uint8", "int16", "int32", "int64")


bool_ = DType("bool", np.bool_)
int8 = DType("int8", np.int8)
uint8 = DType("uint8", np.uint8)
int16 = DType("int16", np.int16)
int32 = DType("int32", np.int32)
int64 = DType("int64", np.int64)
float16 = DType("float16", np.float16)
bfloat16 = DType("bfloat16", jnp.bfloat16)
float32 = DType("float32", np.float32)
float64 = DType("float64", np.float64)
complex64 = DType("complex64", np.complex64)
complex128 = DType("complex128", np.complex128)

_ALL = [bool_, int8, uint8, int16, int32, int64, float16, bfloat16,
        float32, float64, complex64, complex128]
_BY_NAME = {d.name: d for d in _ALL}
_BY_NAME["bool"] = bool_
_ALIASES = {"float": "float32", "double": "float64", "half": "float16",
            "int": "int32", "long": "int64", "bf16": "bfloat16",
            "fp16": "float16", "fp32": "float32", "fp64": "float64"}


def to_paddle_dtype(dtype):
    """Normalize any dtype spec (str, numpy dtype, jnp dtype, DType) to DType."""
    if isinstance(dtype, DType):
        return dtype
    if isinstance(dtype, str):
        name = _ALIASES.get(dtype, dtype)
        if name in _BY_NAME:
            return _BY_NAME[name]
        raise ValueError(f"unknown dtype {dtype!r}")
    # numpy / jax dtypes
    name = np.dtype(dtype).name if dtype is not jnp.bfloat16 else "bfloat16"
    if dtype == jnp.bfloat16:
        name = "bfloat16"
    if name in _BY_NAME:
        return _BY_NAME[name]
    raise ValueError(f"unknown dtype {dtype!r}")


def to_jax_dtype(dtype):
    """Normalize any dtype spec to the jax/numpy dtype object."""
    d = to_paddle_dtype(dtype)
    return jnp.bfloat16 if d.name == "bfloat16" else d.np_dtype


# Default dtype management (reference: paddle.set_default_dtype,
# python/paddle/framework/framework.py).
_default_dtype = float32


def set_default_dtype(dtype):
    global _default_dtype
    d = to_paddle_dtype(dtype)
    if not d.is_floating:
        raise TypeError("default dtype must be floating point")
    _default_dtype = d


def get_default_dtype():
    return _default_dtype.name
