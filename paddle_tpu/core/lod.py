"""LoDTensor: level-of-detail (ragged) tensors.

Reference parity: paddle/fluid/framework/lod_tensor.h:109 (LoDTensor =
dense Tensor + LoD offset levels), python/paddle/fluid/lod_tensor.py
(create_lod_tensor, create_random_int_lodtensor), lod_tensor.cc
(ConvertToLengthBasedLoD etc.).

TPU-native design (SURVEY §7 hard-part 3): the DATA stays one dense
concatenated array on device — XLA-friendly, no ragged device type. The
raggedness (LoD offsets) is host metadata carried by the tensor. The
boundary conversions to the compute-friendly forms are explicit:
  - to_padded(): (padded [N, L, ...], lengths) for masked dense ops
  - segment_ids(): row->sequence map for jax segment reductions
  - sequence_list(): python list of per-sequence arrays (host)
Multi-level LoD composes offsets the same way the reference does (outer
levels index into the next level).
"""
import numpy as np
import jax.numpy as jnp

from .tensor import Tensor


def _lengths_to_offsets(lengths):
    off = [0]
    for n in lengths:
        off.append(off[-1] + int(n))
    return off


class LoDTensor(Tensor):
    """Dense data + LoD offsets. lod is a list of offset lists; the last
    level indexes rows of `data` (reference lod_tensor.h:109: 'LoD' =
    vector<vector<size_t>> of offsets)."""

    __slots__ = ("_lod",)

    def __init__(self, data, lod=None, **kw):
        super().__init__(data, **kw)
        self._lod = [list(map(int, lv)) for lv in (lod or [])]
        self._check()

    def _check(self):
        n = self.aval_shape()[0] if self.aval_shape() else 0
        for i, lv in enumerate(self._lod):
            if lv and lv[0] != 0:
                raise ValueError(f"LoD level {i} must start at 0: {lv}")
            if any(a > b for a, b in zip(lv, lv[1:])):
                raise ValueError(f"LoD level {i} not non-decreasing: {lv}")
        if self._lod and self._lod[-1] and self._lod[-1][-1] != n:
            raise ValueError(
                f"last LoD offset {self._lod[-1][-1]} != rows {n}")
        for outer, inner in zip(self._lod, self._lod[1:]):
            if outer and outer[-1] != len(inner) - 1:
                raise ValueError(
                    "outer LoD level must index into the inner level")

    # -- reference API -----------------------------------------------------
    def lod(self):
        return [list(lv) for lv in self._lod]

    def set_lod(self, lod):
        new = [list(map(int, lv)) for lv in lod]
        old, self._lod = self._lod, new
        try:
            self._check()
        except ValueError:
            self._lod = old  # reject without corrupting the tensor
            raise

    def recursive_sequence_lengths(self):
        """Offsets -> nested lengths (reference:
        LoDTensor.recursive_sequence_lengths)."""
        return [[b - a for a, b in zip(lv, lv[1:])] for lv in self._lod]

    def has_valid_recursive_sequence_lengths(self):
        try:
            self._check()
            return True
        except ValueError:
            return False

    # -- TPU-native conversions -------------------------------------------
    def nseq(self, level=-1):
        return len(self._lod[level]) - 1

    def lengths(self, level=-1):
        lv = self._lod[level]
        return np.asarray([b - a for a, b in zip(lv, lv[1:])], "int64")

    def segment_ids(self, level=-1):
        """Row -> sequence index map for jax segment reductions."""
        return np.repeat(np.arange(self.nseq(level)), self.lengths(level))

    def to_padded(self, pad_value=0.0, level=-1):
        """(padded [N, L, ...], lengths Tensor) — the masked-dense form
        every TPU sequence op consumes (ops/sequence.py)."""
        data = np.asarray(self.numpy())
        lv = self._lod[level]
        lens = self.lengths(level)
        L = int(lens.max()) if len(lens) else 0
        out = np.full((len(lens), L) + data.shape[1:], pad_value,
                      data.dtype)
        for i, (a, b) in enumerate(zip(lv, lv[1:])):
            out[i, :b - a] = data[a:b]
        return Tensor(out), Tensor(np.asarray(lens))

    def sequence_list(self, level=-1):
        data = np.asarray(self.numpy())
        lv = self._lod[level]
        return [data[a:b] for a, b in zip(lv, lv[1:])]

    def __repr__(self):
        return (f"LoDTensor(shape={self.shape}, "
                f"lod={self._lod})")


def create_lod_tensor(data, recursive_seq_lens, place=None):
    """Reference: python/paddle/fluid/lod_tensor.py create_lod_tensor —
    data is a numpy array / list whose rows concatenate all sequences;
    recursive_seq_lens is nested LENGTHS (converted to offsets)."""
    if isinstance(data, list) and data and isinstance(
            data[0], (list, np.ndarray)) and np.asarray(data[0]).ndim >= 1:
        flat = np.concatenate([np.asarray(d) for d in data], axis=0)
    else:
        flat = np.asarray(data)
    lod = [_lengths_to_offsets(lv) for lv in recursive_seq_lens]
    return LoDTensor(flat, lod=lod)


def create_random_int_lodtensor(recursive_seq_lens, base_shape, place=None,
                                low=0, high=1):
    total = sum(recursive_seq_lens[-1])
    data = np.random.randint(low, high + 1,
                             (total,) + tuple(base_shape)).astype("int64")
    lod = [_lengths_to_offsets(lv) for lv in recursive_seq_lens]
    return LoDTensor(data, lod=lod)


# -- LoD-aware sequence reductions (segment form, XLA-friendly) -----------

def lod_sequence_pool(t, pool_type="SUM"):
    """sequence_pool over a LoDTensor via segment reduction (reference:
    sequence_pool_op over LoD offsets). Returns a dense [nseq, ...]
    Tensor."""
    import jax
    seg = jnp.asarray(t.segment_ids())
    data = t.value
    n = t.nseq()
    pt = pool_type.upper()
    if pt == "SUM":
        out = jax.ops.segment_sum(data, seg, num_segments=n)
    elif pt == "AVERAGE":
        s = jax.ops.segment_sum(data, seg, num_segments=n)
        cnt = jax.ops.segment_sum(jnp.ones((data.shape[0],), data.dtype),
                                  seg, num_segments=n)
        out = s / jnp.maximum(cnt, 1).reshape((-1,) + (1,) * (s.ndim - 1))
    elif pt == "MAX":
        out = jax.ops.segment_max(data, seg, num_segments=n)
    elif pt == "MIN":
        out = jax.ops.segment_min(data, seg, num_segments=n)
    elif pt in ("FIRST", "LAST"):
        lv = t._lod[-1]
        if pt == "FIRST":
            idx = jnp.asarray([min(a, data.shape[0] - 1) for a in lv[:-1]])
        else:
            idx = jnp.asarray([max(b - 1, 0) for b in lv[1:]])
        out = jnp.take(data, idx, axis=0)
        # an empty sequence has no first/last row: yield zeros, not a
        # neighboring sequence's row
        lens = jnp.asarray(t.lengths())
        mask = (lens > 0).reshape((-1,) + (1,) * (out.ndim - 1))
        out = jnp.where(mask, out, jnp.zeros_like(out))
    else:
        raise ValueError(f"unknown pool_type {pool_type!r}")
    return Tensor(out)


def lod_sequence_expand(x, ref):
    """Repeat each row of x by the ref LoDTensor's sequence lengths
    (reference: sequence_expand_op)."""
    lens = ref.lengths()
    data = x.value if isinstance(x, Tensor) else jnp.asarray(x)
    rep = jnp.asarray(np.repeat(np.arange(len(lens)), lens))
    return LoDTensor(jnp.take(data, rep, axis=0), lod=[ref._lod[-1]])
