"""Process-level flags, paddle.set_flags / get_flags style.

TPU-native equivalent of the reference gflags registry (reference:
paddle/fluid/platform/flags.cc:33-353 and
paddle/fluid/pybind/global_value_getter_setter.cc). Flags can be set via
environment (FLAGS_xxx=...) or paddle_tpu.set_flags({...}).
Only flags meaningful on the XLA/PjRt runtime are kept; CUDA-specific ones
are accepted but ignored for compatibility.
"""
import os

_DEFAULTS = {
    # debugging: scan op outputs for NaN/Inf (flags.cc:44 FLAGS_check_nan_inf)
    "FLAGS_check_nan_inf": False,
    # deterministic execution (flags.cc:108 FLAGS_cudnn_deterministic analogue):
    # on TPU, XLA is deterministic by default; flag kept for API parity.
    "FLAGS_deterministic": True,
    # eager op dispatch: log compiles (debugging aid, no reference analogue)
    "FLAGS_log_compiles": False,
    # DDP/DP gradient fusion bucket size in MB (reference reducer.h:84
    # group_size_limits ~25MB)
    "FLAGS_fuse_parameter_memory_size": 25.0,
}

_flags = {}


def _coerce(default, v):
    if isinstance(default, bool):
        if isinstance(v, str):
            return v.lower() in ("1", "true", "yes", "on")
        return bool(v)
    if isinstance(default, float):
        return float(v)
    if isinstance(default, int):
        return int(v)
    return v


def get_flag(name):
    if name in _flags:
        return _flags[name]
    env = os.environ.get(name)
    default = _DEFAULTS.get(name)
    if env is not None:
        return _coerce(default if default is not None else env, env)
    return default


def set_flags(flags):
    """paddle.set_flags({'FLAGS_check_nan_inf': 1})"""
    for k, v in flags.items():
        default = _DEFAULTS.get(k)
        _flags[k] = _coerce(default, v) if default is not None else v


def get_flags(names):
    if isinstance(names, str):
        names = [names]
    return {n: get_flag(n) for n in names}
