"""Process-level flags, paddle.set_flags / get_flags style.

TPU-native equivalent of the reference gflags registry (reference:
paddle/fluid/platform/flags.cc:33-353 and
paddle/fluid/pybind/global_value_getter_setter.cc). Flags can be set via
environment (FLAGS_xxx=...) or paddle_tpu.set_flags({...}).
Only flags meaningful on the XLA/PjRt runtime are kept; CUDA-specific ones
are accepted but ignored for compatibility.
"""
import os

_DEFAULTS = {
    # debugging: scan op outputs for NaN/Inf (flags.cc:44 FLAGS_check_nan_inf)
    "FLAGS_check_nan_inf": False,
    # deterministic execution (flags.cc:108 FLAGS_cudnn_deterministic analogue):
    # on TPU, XLA is deterministic by default; flag kept for API parity.
    "FLAGS_deterministic": True,
    # eager op dispatch: log compiles (debugging aid, no reference analogue)
    "FLAGS_log_compiles": False,
    # DDP/DP gradient fusion bucket size in MB (reference reducer.h:84
    # group_size_limits ~25MB)
    "FLAGS_fuse_parameter_memory_size": 25.0,
    # persistent XLA compilation cache directory ("" disables). Eager
    # dispatch compiles one executable per (op, shape); on TPU those
    # compiles dominate warmup (SURVEY §7 hard-part 1) — the disk cache
    # amortizes them across processes/runs. Per-user path: cache entries
    # are executed code, so a world-shared /tmp dir would let another
    # local user poison them.
    "FLAGS_compilation_cache_dir": os.path.join(
        os.path.expanduser("~"), ".cache", "paddle_tpu", "xla"),
    # only cache compiles slower than this (seconds)
    "FLAGS_compilation_cache_min_compile_secs": 0.3,
    # lazy micro-tracing eager executor (core/lazy.py): defer eager ops
    # into a micro-graph flushed as one cached XLA executable at
    # materialization/step boundaries. The TPU answer to the reference's
    # generated fast eager entry points (op_function_generator.cc:519).
    "FLAGS_lazy_eager": True,
}


def init_compilation_cache():
    """Apply FLAGS_compilation_cache_dir to jax (called at import and
    whenever set_flags changes the cache flags)."""
    path = get_flag("FLAGS_compilation_cache_dir")
    if not path:
        try:
            import jax
            jax.config.update("jax_compilation_cache_dir", None)
        except Exception:
            pass
        return
    try:
        os.makedirs(path, exist_ok=True)
        import jax
        jax.config.update("jax_compilation_cache_dir", path)
        jax.config.update(
            "jax_persistent_cache_min_compile_time_secs",
            float(get_flag("FLAGS_compilation_cache_min_compile_secs")))
    except Exception:  # unwritable dir/old jax: run without the cache
        pass

_flags = {}


def _coerce(default, v):
    if isinstance(default, bool):
        if isinstance(v, str):
            return v.lower() in ("1", "true", "yes", "on")
        return bool(v)
    if isinstance(default, float):
        return float(v)
    if isinstance(default, int):
        return int(v)
    return v


def get_flag(name):
    if name in _flags:
        return _flags[name]
    env = os.environ.get(name)
    default = _DEFAULTS.get(name)
    if env is not None:
        return _coerce(default if default is not None else env, env)
    return default


_CACHE_FLAGS = ("FLAGS_compilation_cache_dir",
                "FLAGS_compilation_cache_min_compile_secs")


def set_flags(flags):
    """paddle.set_flags({'FLAGS_check_nan_inf': 1})"""
    reinit_cache = any(k in _CACHE_FLAGS for k in flags)
    for k, v in flags.items():
        default = _DEFAULTS.get(k)
        _flags[k] = _coerce(default, v) if default is not None else v
    if reinit_cache:
        init_compilation_cache()


def get_flags(names):
    if isinstance(names, str):
        names = [names]
    return {n: get_flag(n) for n in names}
