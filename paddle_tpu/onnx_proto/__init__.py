"""Self-contained ONNX protobuf bindings.

The onnx python package is not available in this image;
`paddle_tpu_onnx_pb2` is generated (protoc) from the bundled
`paddle_tpu_onnx.proto`, a subset of the official schema with upstream
field numbers/enums, so serialized models are valid ONNX files. The
proto file and package are deliberately NOT named `onnx`: the real onnx
package registers `onnx.proto` into protobuf's default descriptor pool,
and a second registration with different bytes raises — the rename
keeps both importable in one process (wire format depends only on
field numbers). Regenerate with:
    protoc --python_out=. paddle_tpu_onnx.proto
"""
from . import paddle_tpu_onnx_pb2 as onnx_pb2  # noqa: F401
