"""Self-contained ONNX protobuf bindings.

The onnx python package is not available in this image; `onnx_pb2` is
generated (protoc) from the bundled `onnx.proto`, a subset of the
official schema with upstream field numbers/enums, so serialized models
are valid ONNX files. Regenerate with:
    protoc --python_out=. onnx.proto
"""
from . import onnx_pb2  # noqa: F401
