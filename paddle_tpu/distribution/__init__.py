"""paddle.distribution equivalent (reference: python/paddle/distribution.py
— Distribution, Uniform, Normal, Categorical). Sampling draws from the
global generator; math is pure jax."""
import math

import numpy as np
import jax
import jax.numpy as jnp

from ..core.tensor import Tensor
from ..core import rng as rng_mod
from ..core.dispatch import register_op
from ..ops.creation import _register_created


def _arr(x, dtype=jnp.float32):
    if isinstance(x, Tensor):
        return x.value.astype(dtype)
    return jnp.asarray(x, dtype)


class Distribution:
    def sample(self, shape=()):
        raise NotImplementedError

    def entropy(self):
        raise NotImplementedError

    def log_prob(self, value):
        raise NotImplementedError

    def probs(self, value):
        from ..ops import math as math_ops
        return math_ops.exp(self.log_prob(value))

    def kl_divergence(self, other):
        raise NotImplementedError


@register_op("dist_normal_sample", differentiable=False)
def _normal_sample(loc, scale, key, *, shape):
    return loc + scale * jax.random.normal(key, shape, loc.dtype)


@register_op("dist_uniform_sample", differentiable=False)
def _uniform_sample(low, high, key, *, shape):
    return low + (high - low) * jax.random.uniform(key, shape, low.dtype)


class Normal(Distribution):
    def __init__(self, loc, scale, name=None):
        self.loc = Tensor(_arr(loc))
        self.scale = Tensor(_arr(scale))

    def sample(self, shape=(), seed=0):
        shape = tuple(shape) + tuple(np.broadcast_shapes(
            self.loc.aval_shape(), self.scale.aval_shape()))
        key = rng_mod.next_key()
        return _normal_sample(self.loc, self.scale, key, shape=shape)

    def log_prob(self, value):
        from ..ops import math as math_ops
        var = math_ops.multiply(self.scale, self.scale)
        diff = math_ops.subtract(value, self.loc)
        t1 = math_ops.divide(math_ops.multiply(diff, diff),
                             math_ops.scale(var, 2.0))
        return math_ops.scale(
            math_ops.add(t1, math_ops.log(
                math_ops.scale(self.scale, math.sqrt(2 * math.pi)))), -1.0)

    def entropy(self):
        from ..ops import math as math_ops
        return math_ops.add(
            math_ops.log(self.scale),
            float(0.5 * math.log(2 * math.pi) + 0.5))

    def kl_divergence(self, other):
        from ..ops import math as math_ops
        var_ratio = math_ops.divide(self.scale, other.scale)
        var_ratio = math_ops.multiply(var_ratio, var_ratio)
        t1 = math_ops.divide(math_ops.subtract(self.loc, other.loc),
                             other.scale)
        t1 = math_ops.multiply(t1, t1)
        return math_ops.scale(
            math_ops.subtract(
                math_ops.add(var_ratio, t1),
                math_ops.add(math_ops.log(var_ratio), 1.0)), 0.5)


class Uniform(Distribution):
    def __init__(self, low, high, name=None):
        self.low = Tensor(_arr(low))
        self.high = Tensor(_arr(high))

    def sample(self, shape=(), seed=0):
        shape = tuple(shape) + tuple(np.broadcast_shapes(
            self.low.aval_shape(), self.high.aval_shape()))
        key = rng_mod.next_key()
        return _uniform_sample(self.low, self.high, key, shape=shape)

    def log_prob(self, value):
        from ..ops import math as math_ops, logic
        span = math_ops.subtract(self.high, self.low)
        inside = logic.logical_and(logic.greater_equal(value, self.low),
                                   logic.less_than(value, self.high))
        from ..ops import manipulation
        lp = math_ops.scale(math_ops.log(span), -1.0)
        neg_inf = Tensor(jnp.full(np.broadcast_shapes(
            tuple(value.aval_shape()), tuple(lp.aval_shape())), -np.inf,
            jnp.float32))
        return manipulation.where(inside, lp, neg_inf)

    def entropy(self):
        from ..ops import math as math_ops
        return math_ops.log(math_ops.subtract(self.high, self.low))


class Categorical(Distribution):
    def __init__(self, logits, name=None):
        self.logits = logits if isinstance(logits, Tensor) else \
            Tensor(_arr(logits))

    def sample(self, shape=(), seed=0):
        key = rng_mod.next_key()
        return _categorical_sample(self.logits, key, shape=tuple(shape))

    def log_prob(self, value):
        from ..ops import nn_ops, manipulation, math as math_ops
        logp = nn_ops.log_softmax(self.logits, axis=-1)
        idx = math_ops.cast(value, "int32")
        if logp.ndim == 1:
            return manipulation.gather(logp, idx)
        return manipulation.take_along_axis(
            logp, manipulation.unsqueeze(idx, axis=-1), axis=-1)

    def entropy(self):
        from ..ops import nn_ops, math as math_ops, reduction
        logp = nn_ops.log_softmax(self.logits, axis=-1)
        p = nn_ops.softmax(self.logits, axis=-1)
        return math_ops.scale(
            reduction.sum(math_ops.multiply(p, logp), axis=-1), -1.0)


@register_op("dist_categorical_sample", differentiable=False)
def _categorical_sample(logits, key, *, shape):
    return jax.random.categorical(key, logits, axis=-1,
                                  shape=shape + logits.shape[:-1])


def kl_divergence(p, q):
    return p.kl_divergence(q)


class MultivariateNormalDiag(Distribution):
    """Reference: fluid/layers/distributions.py MultivariateNormalDiag —
    diagonal-covariance multivariate normal."""

    def __init__(self, loc, scale):
        self.loc = _arr(loc)
        self.scale = _arr(scale)  # diagonal covariance matrix

    def _diag(self):
        import jax.numpy as jnp
        return jnp.diagonal(self.scale, axis1=-2, axis2=-1)

    def sample(self, shape=()):
        import jax.numpy as jnp
        from ..core import rng as rng_mod
        import jax
        key = rng_mod.next_key().value
        d = self._diag()
        eps = jax.random.normal(key, tuple(shape) + self.loc.shape,
                                self.loc.dtype)
        return Tensor(self.loc + eps * jnp.sqrt(d))

    def entropy(self):
        import jax.numpy as jnp
        d = self._diag()
        k = self.loc.shape[-1]
        ent = 0.5 * (k * (1.0 + jnp.log(2 * jnp.pi))
                     + jnp.sum(jnp.log(d), axis=-1))
        return Tensor(ent)

    def log_prob(self, value):
        import jax.numpy as jnp
        v = _arr(value)
        d = self._diag()
        k = self.loc.shape[-1]
        return Tensor(-0.5 * (jnp.sum((v - self.loc) ** 2 / d, axis=-1)
                              + k * jnp.log(2 * jnp.pi)
                              + jnp.sum(jnp.log(d), axis=-1)))

    def kl_divergence(self, other):
        import jax.numpy as jnp
        d0, d1 = self._diag(), other._diag()
        k = self.loc.shape[-1]
        t = (jnp.sum(d0 / d1, axis=-1)
             + jnp.sum((other.loc - self.loc) ** 2 / d1, axis=-1) - k
             + jnp.sum(jnp.log(d1), axis=-1)
             - jnp.sum(jnp.log(d0), axis=-1))
        return Tensor(0.5 * t)


def sampling_id(x, min=0.0, max=1.0, seed=0, dtype="int64"):  # noqa: A002
    """Reference: layers.sampling_id — sample a category index per row
    of a probability matrix."""
    import jax
    from ..core import rng as rng_mod
    key = rng_mod.next_key().value
    import jax.numpy as jnp
    from ..core import dtype as dtype_mod
    idx = jax.random.categorical(key, jnp.log(jnp.maximum(
        _arr(x), 1e-12)), axis=-1)
    return Tensor(idx.astype(dtype_mod.to_jax_dtype(dtype)))
