"""Comparison / logical ops (reference: python/paddle/tensor/logic.py)."""
import jax.numpy as jnp

from ..core.dispatch import register_op
from ..core.tensor import Tensor
from .math import _wrap_scalar


def _cmp(name, fn):
    op = register_op(name, differentiable=False)(fn)

    def api(x, y, name=None):
        x = _wrap_scalar(x, y)
        y = _wrap_scalar(y, x)
        return op(x, y)
    api.__name__ = name
    return api


equal = _cmp("equal", lambda x, y: jnp.equal(x, y))
not_equal = _cmp("not_equal", lambda x, y: jnp.not_equal(x, y))
greater_than = _cmp("greater_than", lambda x, y: jnp.greater(x, y))
greater_equal = _cmp("greater_equal", lambda x, y: jnp.greater_equal(x, y))
less_than = _cmp("less_than", lambda x, y: jnp.less(x, y))
less_equal = _cmp("less_equal", lambda x, y: jnp.less_equal(x, y))
logical_and = _cmp("logical_and", lambda x, y: jnp.logical_and(x, y))
logical_or = _cmp("logical_or", lambda x, y: jnp.logical_or(x, y))
logical_xor = _cmp("logical_xor", lambda x, y: jnp.logical_xor(x, y))
bitwise_and = _cmp("bitwise_and", lambda x, y: jnp.bitwise_and(x, y))
bitwise_or = _cmp("bitwise_or", lambda x, y: jnp.bitwise_or(x, y))
bitwise_xor = _cmp("bitwise_xor", lambda x, y: jnp.bitwise_xor(x, y))


@register_op("logical_not", differentiable=False)
def _logical_not(x):
    return jnp.logical_not(x)


def logical_not(x, name=None):
    return _logical_not(x)


@register_op("bitwise_not", differentiable=False)
def _bitwise_not(x):
    return jnp.bitwise_not(x)


def bitwise_not(x, name=None):
    return _bitwise_not(x)


@register_op("isclose", differentiable=False)
def _isclose(x, y, *, rtol, atol, equal_nan):
    return jnp.isclose(x, y, rtol=rtol, atol=atol, equal_nan=equal_nan)


def isclose(x, y, rtol=1e-5, atol=1e-8, equal_nan=False, name=None):
    return _isclose(x, y, rtol=float(rtol), atol=float(atol),
                    equal_nan=bool(equal_nan))


def allclose(x, y, rtol=1e-5, atol=1e-8, equal_nan=False, name=None):
    from . import reduction
    return reduction.all(isclose(x, y, rtol, atol, equal_nan))


def equal_all(x, y, name=None):
    if tuple(x.shape) != tuple(y.shape):
        return Tensor(jnp.asarray(False))
    from . import reduction
    return reduction.all(equal(x, y))


def is_empty(x, name=None):
    return Tensor(jnp.asarray(x.size == 0))


def is_tensor(x):
    return isinstance(x, Tensor)
