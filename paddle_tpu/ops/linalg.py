"""Linear algebra ops (reference: python/paddle/tensor/linalg.py)."""
import functools

import jax
import jax.numpy as jnp

from ..core.dispatch import register_op
from .math import matmul, bmm, dot, mv  # noqa: F401  re-export
from .reduction import norm, dist  # noqa: F401


def _f32_on_tpu(fn):
    """TPU linear-algebra custom-calls implement only f32/c64 (the
    compiler rejects f64, e.g. "Only F32 and C64 types are implemented
    in LuDecomposition") — there is no f64 hardware path. On the TPU
    backend, compute f64/c128 inputs in f32/c64 and cast results back,
    keeping the reference dtype contract (f64 in -> f64 out)."""

    @functools.wraps(fn)
    def wrapped(*args, **kwargs):
        if jax.default_backend() != "tpu":
            return fn(*args, **kwargs)
        demoted = [False]

        def dem(a):
            dt = getattr(a, "dtype", None)
            if dt == jnp.float64:
                demoted[0] = True
                return a.astype(jnp.float32)
            if dt == jnp.complex128:
                demoted[0] = True
                return a.astype(jnp.complex64)
            return a

        args = jax.tree_util.tree_map(dem, args)
        out = fn(*args, **kwargs)
        if not demoted[0]:
            return out

        def prom(a):
            dt = getattr(a, "dtype", None)
            if dt == jnp.float32:
                return a.astype(jnp.float64)
            if dt == jnp.complex64:
                return a.astype(jnp.complex128)
            return a  # integer outputs (pivots, infos) pass through

        return jax.tree_util.tree_map(prom, out)

    return wrapped


@register_op("cholesky")
@_f32_on_tpu
def _cholesky(x, *, upper):
    L = jnp.linalg.cholesky(x)
    return jnp.swapaxes(L, -1, -2) if upper else L


def cholesky(x, upper=False, name=None):
    return _cholesky(x, upper=bool(upper))


@register_op("inverse")
@_f32_on_tpu
def _inv(x):
    return jnp.linalg.inv(x)


def inv(x, name=None):
    return _inv(x)


inverse = inv


@register_op("matrix_power")
@_f32_on_tpu
def _matrix_power(x, *, n):
    return jnp.linalg.matrix_power(x, n)


def matrix_power(x, n, name=None):
    return _matrix_power(x, n=int(n))


@register_op("det")
@_f32_on_tpu
def _det(x):
    return jnp.linalg.det(x)


def det(x, name=None):
    return _det(x)


@register_op("slogdet")
@_f32_on_tpu
def _slogdet(x):
    sign, logdet = jnp.linalg.slogdet(x)
    return sign, logdet


def slogdet(x, name=None):
    return _slogdet(x)


@register_op("solve")
@_f32_on_tpu
def _solve(a, b):
    return jnp.linalg.solve(a, b)


def solve(x, y, name=None):
    return _solve(x, y)


@register_op("triangular_solve")
@_f32_on_tpu
def _triangular_solve(a, b, *, upper, transpose, unitriangular):
    return jax.scipy.linalg.solve_triangular(
        a, b, lower=not upper, trans=1 if transpose else 0,
        unit_diagonal=unitriangular)


def triangular_solve(x, y, upper=True, transpose=False, unitriangular=False,
                     name=None):
    return _triangular_solve(x, y, upper=bool(upper), transpose=bool(transpose),
                             unitriangular=bool(unitriangular))


@register_op("svd", differentiable=False)
@_f32_on_tpu
def _svd(x, *, full_matrices):
    return jnp.linalg.svd(x, full_matrices=full_matrices)


def svd(x, full_matrices=False, name=None):
    return _svd(x, full_matrices=bool(full_matrices))


@register_op("qr", differentiable=False)
@_f32_on_tpu
def _qr(x, *, mode):
    return jnp.linalg.qr(x, mode=mode)


def qr(x, mode="reduced", name=None):
    return _qr(x, mode=mode)


@register_op("eigh", differentiable=False)
@_f32_on_tpu
def _eigh(x, *, uplo):
    return jnp.linalg.eigh(x, UPLO=uplo)


def eigh(x, UPLO="L", name=None):
    return _eigh(x, uplo=UPLO)


@register_op("eigvalsh", differentiable=False)
@_f32_on_tpu
def _eigvalsh(x, *, uplo):
    return jnp.linalg.eigvalsh(x, UPLO=uplo)


def eigvalsh(x, UPLO="L", name=None):
    return _eigvalsh(x, uplo=UPLO)


@register_op("pinv", differentiable=False)
@_f32_on_tpu
def _pinv(x, *, rcond):
    return jnp.linalg.pinv(x, rtol=rcond)


def pinv(x, rcond=1e-15, hermitian=False, name=None):
    return _pinv(x, rcond=float(rcond))


@register_op("matrix_rank", differentiable=False)
@_f32_on_tpu
def _matrix_rank(x, *, tol):
    return jnp.linalg.matrix_rank(x, rtol=tol)


def matrix_rank(x, tol=None, hermitian=False, name=None):
    return _matrix_rank(x, tol=tol)


@register_op("lstsq", differentiable=False)
@_f32_on_tpu
def _lstsq(a, b):
    sol, res, rank, sv = jnp.linalg.lstsq(a, b)
    return sol, res, rank, sv


def lstsq(x, y, rcond=None, driver=None, name=None):
    return _lstsq(x, y)


@register_op("multi_dot")
def _multi_dot(*xs):
    return jnp.linalg.multi_dot(xs)


def multi_dot(x, name=None):
    return _multi_dot(*x)


@register_op("cond_number", differentiable=False)
@_f32_on_tpu
def _cond(x, *, p):
    return jnp.linalg.cond(x, p=p)


def cond(x, p=None, name=None):
    return _cond(x, p=p)


@register_op("lu", differentiable=False)
@_f32_on_tpu
def _lu(x):
    lu, pivots, _ = jax.lax.linalg.lu(x)
    return lu, pivots + 1  # paddle pivots are 1-based (reference lu_op)


def lu(x, pivot=True, get_infos=False, name=None):
    """Reference: paddle.linalg.lu (operators/lu_op). Returns (LU,
    pivots[, infos]); infos are always 0 here (XLA LU does not report
    singularity)."""
    res, piv = _lu(x)
    if get_infos:
        from .creation import zeros
        info = zeros(list(x.aval_shape()[:-2]) or [1], dtype="int32")
        return res, piv, info
    return res, piv


@register_op("cholesky_solve")
@_f32_on_tpu
def _cholesky_solve(y, x, *, upper):
    return jax.scipy.linalg.cho_solve((x, not upper), y)


def cholesky_solve(x, y, upper=False, name=None):
    """Reference: operators/cholesky_solve_op — solves A @ out = x given
    the Cholesky factor y of A."""
    return _cholesky_solve(x, y, upper=bool(upper))


@register_op("householder_product", differentiable=False)
@_f32_on_tpu
def _householder_product(x, tau):
    return jax.lax.linalg.householder_product(x, tau)


def householder_product(x, tau, name=None):
    return _householder_product(x, tau)


@register_op("eig", differentiable=False)
@_f32_on_tpu
def _eig(x):
    return jnp.linalg.eig(x)


def eig(x, name=None):
    """Reference: operators/eig_op (CPU-only there too; XLA lowers eig on
    the host)."""
    return _eig(x)


@register_op("corrcoef", differentiable=False)
def _corrcoef(x, *, rowvar):
    return jnp.corrcoef(x, rowvar=rowvar)


def corrcoef(x, rowvar=True, name=None):
    return _corrcoef(x, rowvar=bool(rowvar))


@register_op("cov", differentiable=False)
def _cov(x, fweights, aweights, *, rowvar, ddof):
    return jnp.cov(x, rowvar=rowvar, ddof=1 if ddof else 0,
                   fweights=fweights, aweights=aweights)


def cov(x, rowvar=True, ddof=True, fweights=None, aweights=None, name=None):
    return _cov(x, fweights, aweights, rowvar=bool(rowvar), ddof=bool(ddof))
