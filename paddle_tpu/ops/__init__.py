"""Op library + Tensor method/operator patching.

Reference parity: python/paddle/tensor/__init__.py attaches ~300 methods to
the Tensor type via monkey patch (reference:
python/paddle/fluid/dygraph/math_op_patch.py for operators). We do the same
so `x.sum()`, `x + y`, `x.reshape(...)` all work on eager Tensors.
"""
from . import creation, math, reduction, manipulation, logic, search, \
    nn_ops, linalg, indexing  # noqa: F401
from ..core.tensor import Tensor


def _patch():
    T = Tensor
    m, r, mp, lg, s = math, reduction, manipulation, logic, search

    # arithmetic operators
    T.__add__ = lambda self, o: m.add(self, o)
    T.__radd__ = lambda self, o: m.add(o, self)
    T.__sub__ = lambda self, o: m.subtract(self, o)
    T.__rsub__ = lambda self, o: m.subtract(o, self)
    T.__mul__ = lambda self, o: m.multiply(self, o)
    T.__rmul__ = lambda self, o: m.multiply(o, self)
    def _true_div(a, b):
        # reference math_op_patch.py:190: the / OPERATOR casts int
        # tensors to float32 before elementwise_div (true division),
        # while the divide() API keeps the kernel's integer division
        def _c(t):
            if isinstance(t, Tensor) and "int" in str(t.dtype):
                return t.astype("float32")
            return t
        return m.divide(_c(a), _c(b))

    T.__truediv__ = lambda self, o: _true_div(self, o)
    T.__rtruediv__ = lambda self, o: _true_div(o, self)
    T.__floordiv__ = lambda self, o: m.floor_divide(self, o)
    T.__mod__ = lambda self, o: m.remainder(self, o)
    T.__pow__ = lambda self, o: m.pow(self, o)
    T.__rpow__ = lambda self, o: m.pow(o, self)
    T.__neg__ = lambda self: m.neg(self)
    T.__abs__ = lambda self: m.abs(self)
    T.__matmul__ = lambda self, o: m.matmul(self, o)
    T.__rmatmul__ = lambda self, o: m.matmul(o, self)
    # comparisons
    T.__eq__ = lambda self, o: lg.equal(self, o)
    T.__ne__ = lambda self, o: lg.not_equal(self, o)
    T.__lt__ = lambda self, o: lg.less_than(self, o)
    T.__le__ = lambda self, o: lg.less_equal(self, o)
    T.__gt__ = lambda self, o: lg.greater_than(self, o)
    T.__ge__ = lambda self, o: lg.greater_equal(self, o)
    T.__invert__ = lambda self: lg.logical_not(self)
    T.__and__ = lambda self, o: lg.logical_and(self, o)
    T.__or__ = lambda self, o: lg.logical_or(self, o)
    T.__xor__ = lambda self, o: lg.logical_xor(self, o)
    # indexing
    T.__getitem__ = lambda self, idx: indexing.getitem(self, idx)
    T.__setitem__ = lambda self, idx, v: indexing.setitem(self, idx, v)

    def meth(fn):
        def _m(self, *a, **k):
            return fn(self, *a, **k)
        return _m

    methods = {
        # math
        "add": m.add, "subtract": m.subtract, "multiply": m.multiply,
        "divide": m.divide, "matmul": m.matmul, "mm": m.matmul, "bmm": m.bmm,
        "dot": m.dot, "mv": m.mv, "pow": m.pow, "abs": m.abs, "exp": m.exp,
        "log": m.log, "log2": m.log2, "log10": m.log10, "log1p": m.log1p,
        "sqrt": m.sqrt, "rsqrt": m.rsqrt, "square": m.square, "sin": m.sin,
        "cos": m.cos, "tan": m.tan, "asin": m.asin, "acos": m.acos,
        "atan": m.atan, "sinh": m.sinh, "cosh": m.cosh, "tanh": m.tanh,
        "floor": m.floor, "ceil": m.ceil, "round": m.round, "trunc": m.trunc,
        "sign": m.sign, "reciprocal": m.reciprocal, "erf": m.erf,
        "sigmoid": m.sigmoid, "clip": m.clip, "lerp": m.lerp, "scale": m.scale,
        "maximum": m.maximum, "minimum": m.minimum, "remainder": m.remainder,
        "mod": m.mod, "floor_divide": m.floor_divide, "neg": m.neg,
        "cumsum": m.cumsum, "cumprod": m.cumprod, "isnan": m.isnan,
        "isinf": m.isinf, "isfinite": m.isfinite, "addmm": m.addmm,
        "trace": m.trace, "diff": m.diff, "kron": m.kron, "outer": m.outer,
        "inner": m.inner, "atan2": m.atan2, "logit": m.logit,
        "nan_to_num": m.nan_to_num, "increment": m.increment,
        "stanh": m.stanh, "expm1": m.expm1, "angle": m.angle, "conj": m.conj,
        # reduction
        "sum": r.sum, "mean": r.mean, "max": r.max, "min": r.min,
        "prod": r.prod, "all": r.all, "any": r.any, "std": r.std,
        "var": r.var, "median": r.median, "logsumexp": r.logsumexp,
        "norm": r.norm, "dist": r.dist, "amax": r.max, "amin": r.min,
        "count_nonzero": r.count_nonzero, "nansum": r.nansum,
        "nanmean": r.nanmean, "quantile": r.quantile,
        # manipulation
        "reshape": mp.reshape, "reshape_": mp.reshape_,
        "transpose": mp.transpose, "flatten": mp.flatten,
        "squeeze": mp.squeeze, "unsqueeze": mp.unsqueeze, "tile": mp.tile,
        "expand": mp.expand, "expand_as": mp.expand_as,
        "broadcast_to": mp.broadcast_to, "flip": mp.flip, "roll": mp.roll,
        "gather": mp.gather, "gather_nd": mp.gather_nd,
        "scatter": mp.scatter, "scatter_nd_add": mp.scatter_nd_add,
        "index_select": mp.index_select, "index_sample": mp.index_sample,
        "masked_select": mp.masked_select, "masked_fill": mp.masked_fill,
        "split": mp.split, "chunk": mp.chunk, "unbind": mp.unbind,
        "slice": mp.slice, "take_along_axis": mp.take_along_axis,
        "put_along_axis": mp.put_along_axis, "unstack": mp.unstack,
        "repeat_interleave": mp.repeat_interleave, "pad": mp.pad,
        "where": mp.where, "rot90": mp.rot90, "tril": creation.tril,
        "triu": creation.triu, "diag": creation.diag,
        # logic
        "equal": lg.equal, "not_equal": lg.not_equal,
        "greater_than": lg.greater_than, "greater_equal": lg.greater_equal,
        "less_than": lg.less_than, "less_equal": lg.less_equal,
        "logical_and": lg.logical_and, "logical_or": lg.logical_or,
        "logical_not": lg.logical_not, "logical_xor": lg.logical_xor,
        "isclose": lg.isclose, "allclose": lg.allclose,
        "equal_all": lg.equal_all, "bitwise_and": lg.bitwise_and,
        "bitwise_or": lg.bitwise_or, "bitwise_xor": lg.bitwise_xor,
        "bitwise_not": lg.bitwise_not,
        # search
        "argmax": s.argmax, "argmin": s.argmin, "argsort": s.argsort,
        "sort": s.sort, "topk": s.topk, "nonzero": s.nonzero,
        "unique": s.unique, "kthvalue": s.kthvalue, "mode": s.mode,
        "searchsorted": s.searchsorted,
        # linalg
        "cholesky": linalg.cholesky, "inverse": linalg.inv,
        "matrix_power": linalg.matrix_power, "det": linalg.det,
        # nn
        "softmax": nn_ops.softmax,
        # creation-ish
        "zeros_like": creation.zeros_like, "ones_like": creation.ones_like,
        "full_like": creation.full_like,
        # round-2 additions (reference tensor/__init__.py method list)
        "concat": mp.concat, "stack": mp.stack,
        "strided_slice": mp.strided_slice, "shard_index": mp.shard_index,
        "multiplex": mp.multiplex, "reverse": mp.reverse,
        "broadcast_tensors": mp.broadcast_tensors,
        "moveaxis": mp.moveaxis, "index_add": mp.index_add,
        "index_fill": mp.index_fill, "tensordot": mp.tensordot,
        "as_real": mp.as_real, "as_complex": mp.as_complex,
        "add_n": m.add_n, "cross": m.cross, "histogram": m.histogram,
        "digamma": m.digamma, "lgamma": m.lgamma, "real": m.real,
        "imag": m.imag, "floor_mod": m.floor_mod,
        "broadcast_shape": mp.broadcast_shape,
        "is_empty": lg.is_empty, "is_tensor": lg.is_tensor,
        "t": mp.t, "bincount": s.bincount, "bucketize": s.bucketize,
        "nanmedian": r.nanmedian, "nanquantile": r.nanquantile,
        "renorm": m.renorm, "logcumsumexp": m.logcumsumexp,
        "trapezoid": m.trapezoid, "vander": m.vander,
    }
    for name, fn in methods.items():
        setattr(T, name, meth(fn))

    # exported for patch_symbolic (static Variable gets the same
    # method surface — reference: fluid/layers/math_op_patch.py
    # monkey_patch_variable)
    global _METHOD_TABLE
    _METHOD_TABLE = dict(methods)

    def rank_m(self):
        return creation.to_tensor(self.ndim)
    T.rank = rank_m

    def _iter(self):
        # without __iter__, python's getitem-protocol fallback loops
        # forever (our indexing clamps instead of raising IndexError).
        # NOT a generator: the 0-d check must fire at iter() time.
        if self.ndim == 0:
            raise TypeError("iteration over a 0-d tensor")
        return (self[i] for i in range(self.aval_shape()[0]))
    T.__iter__ = _iter
    T.__len__ = lambda self: (self.aval_shape()[0] if self.ndim
                              else (_ for _ in ()).throw(
                                  TypeError("len() of a 0-d tensor")))
    from ..core import dtype as _dtype_mod
    import numpy as _np

    def _element_size(self):
        # via the dtype property (trace-aware) rather than raw _value
        return int(_np.dtype(_dtype_mod.to_jax_dtype(self.dtype)).itemsize)
    T.element_size = _element_size
    T.ndimension = lambda self: self.ndim
    T.pin_memory = lambda self: self  # host staging is PjRt's job here
    T.scatter_nd = staticmethod(mp.scatter_nd)

    # in-place variants (reference: tensor method list *_ entries) — the
    # functional result is swapped into the tensor's buffer slot
    def inplace(fn):
        def _m(self, *a, **k):
            out = fn(self, *a, **k)
            self.value = out.value
            return self
        return _m

    for base_name, fn in {
        "add_": m.add, "subtract_": m.subtract, "ceil_": m.ceil,
        "floor_": m.floor, "clip_": m.clip, "exp_": m.exp,
        "reciprocal_": m.reciprocal, "round_": m.round,
        "rsqrt_": m.rsqrt, "sqrt_": m.sqrt, "scale_": m.scale,
        "squeeze_": mp.squeeze, "unsqueeze_": mp.unsqueeze,
        "flatten_": mp.flatten, "scatter_": mp.scatter,
        "tanh_": m.tanh,
    }.items():
        setattr(T, base_name, inplace(fn))


_patch()


def patch_symbolic(V):
    """Attach the Tensor method surface to the static Variable class
    (reference: fluid/layers/math_op_patch.py monkey_patch_variable —
    the method-style API works identically on symbolic variables; the
    op layer records instead of executing). Arithmetic dunders are
    Variable's own; comparison dunders are deliberately NOT attached
    (an elementwise __eq__ would null Variable's hashability)."""

    for name, fn in _METHOD_TABLE.items():
        if name.endswith("_"):
            continue  # in-place mutators bypass the recording op layer
        if not hasattr(V, name):
            # plain functions bind self when assigned as class attrs
            setattr(V, name, fn)
