"""Fused linear + softmax-cross-entropy Pallas kernel (the LM head).

The GPT head computes logits = x @ W^T over a ~50k vocab and immediately
reduces them to one scalar per token. Unfused, the [tokens, vocab]
logits tensor (1.6 GB f32 at batch 8 x seq 1024) round-trips HBM several
times (write logits, read for log-softmax, read again for d_logits,
write d_logits, read twice for dx/dW) — pure bandwidth, no reuse. This
kernel streams vocab TILES through VMEM with an online logsumexp
(the flash-attention trick applied to the classifier), so the full
logits tensor never exists in HBM in either direction. Backward splits
into two pallas_calls (dx accumulates over the vocab grid dim, dW over
the token grid dim — each accumulator needs ITS dim innermost), so each
recomputes the logits tiles: TWO extra x@W matmul passes total. FLOPs
are cheap here — the unfused path's MXU sits idle on the ~5 HBM passes
over the logits tensor these kernels delete.

Reference analogue: the reference fuses this pair as
softmax_with_cross_entropy_op on the [T, V] logits its matmul wrote
(paddle/fluid/operators/softmax_with_cross_entropy_op.cu) — on TPU the
win is fusing the MATMUL too, which XLA will not do across a reduction.

Weight layout is [V, H] (paddle embedding layout), so tied-embedding
heads pass word_embeddings.weight with no transpose.
"""
import functools
import math
import os as _os

import jax
import jax.numpy as jnp

from ..core.dispatch import register_op
from ..core.jax_compat import shard_map as _shard_map
from .pallas_compat import trace_32bit as _trace_32bit

_BLOCK_T = int(_os.environ.get("PADDLE_FUSED_CE_BLOCK_T", "256"))
_BLOCK_V = int(_os.environ.get("PADDLE_FUSED_CE_BLOCK_V", "1024"))
_FORCE_INTERPRET = [False]


def _interpret():
    return _FORCE_INTERPRET[0]


def _dot_f32(a, b, dims):
    return jax.lax.dot_general(a, b, (dims, ((), ())),
                               preferred_element_type=jnp.float32)


def _use_pallas(x, w_vh, tp=False):
    if _os.environ.get("PADDLE_FUSED_CE_DISABLE") == "1":
        return False  # perf-ablation knob (tools/gpt_mfu_sweep.py)
    t, h = x.shape
    v = w_vh.shape[0]
    if x.dtype not in (jnp.float32, jnp.bfloat16, jnp.float16):
        return False
    ok = (t % 128 == 0 and h % 128 == 0 and v % 128 == 0
          and t >= 128 and v >= 1024)
    if _FORCE_INTERPRET[0]:
        return ok
    if jax.default_backend() == "cpu":
        return False
    if tp:
        # Vocab-sharded TP path: Pallas ON by default (ADVICE r5). The
        # single-chip opt-in below exists because the 2026-08-02 sweep
        # showed XLA wins on SPEED there — but the TP kernel's point is
        # that the per-shard [T, V/mp] logits tensor never exists in
        # HBM, the memory property the path is chosen for, so it keeps
        # its own gate: PADDLE_FUSED_CE_TP=0 opts out (the global
        # PADDLE_FUSED_CE_DISABLE kill switch above still wins).
        return ok and _os.environ.get("PADDLE_FUSED_CE_TP", "1") != "0"
    # Default OFF on real hardware since the 2026-08-02 on-chip sweep:
    # the Pallas kernels cost ~46 ms/step on GPT-124M vs the XLA
    # composition (the bwd recomputes the 633-GFLOP head matmul in both
    # dx and dw kernels at below-XLA MXU efficiency; tools/
    # gpt_roofline.py shows fused cannot beat unfused on speed even at
    # equal kernel efficiency — its win is logits-tensor MEMORY, which
    # matters for big-batch/long-seq configs). PADDLE_FUSED_CE=1 opts
    # in; the vocab-sharded TP path has its own default-on gate above
    # (PADDLE_FUSED_CE_TP).
    return ok and _os.environ.get("PADDLE_FUSED_CE") == "1"


def _block_for(n, want):
    b = 128
    while b * 2 <= want and n % (b * 2) == 0:
        b *= 2
    return b if n % b == 0 else n


# ---- forward: online logsumexp over vocab tiles ----------------------------

def _fwd_kernel(x_ref, w_ref, lab_ref, loss_ref, lse_ref,
                m_sc, s_sc, ll_sc, *, block_t, block_v, nv,
                ignore_index):
    from jax.experimental import pallas as pl
    vi = pl.program_id(1)

    @pl.when(vi == 0)
    def _init():
        m_sc[...] = jnp.full_like(m_sc, -1e30)
        s_sc[...] = jnp.zeros_like(s_sc)
        ll_sc[...] = jnp.zeros_like(ll_sc)

    x = x_ref[...]                       # [bt, H]
    w = w_ref[...]                       # [bv, H]
    tile = _dot_f32(x, w, ((1,), (1,)))  # [bt, bv] logits tile

    labels = lab_ref[...][0]             # [bt] int32
    local = labels - vi * jnp.int32(block_v)
    col = jax.lax.broadcasted_iota(jnp.int32, (block_t, block_v), 1)
    hit = col == local[:, None]          # out-of-tile labels never match
    ll_sc[...] += jnp.sum(jnp.where(hit, tile, 0.0),
                          axis=1)[None, :]

    m = m_sc[...][0]
    new_m = jnp.maximum(m, jnp.max(tile, axis=1))
    s_sc[...] = (s_sc[...][0] * jnp.exp(m - new_m)
                 + jnp.sum(jnp.exp(tile - new_m[:, None]),
                           axis=1))[None, :]
    m_sc[...] = new_m[None, :]

    @pl.when(vi == nv - 1)
    def _store():
        lse = m_sc[...][0] + jnp.log(s_sc[...][0])
        valid = labels != jnp.int32(ignore_index)
        loss_ref[...] = jnp.where(valid, lse - ll_sc[...][0],
                                  0.0)[None, :]
        lse_ref[...] = lse[None, :]


@_trace_32bit
def _pallas_fwd(x, w_vh, labels, ignore_index):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu
    t, h = x.shape
    v = w_vh.shape[0]
    bt = _block_for(t, _BLOCK_T)
    bv = _block_for(v, _BLOCK_V)
    nt, nv = t // bt, v // bv
    lab2 = labels.astype(jnp.int32)[None, :]          # [1, T]
    kernel = functools.partial(_fwd_kernel, block_t=bt, block_v=bv,
                               nv=nv, ignore_index=ignore_index)
    loss, lse = pl.pallas_call(
        kernel,
        grid=(nt, nv),
        in_specs=[
            pl.BlockSpec((bt, h), lambda ti, vi: (ti, 0)),
            pl.BlockSpec((bv, h), lambda ti, vi: (vi, 0)),
            pl.BlockSpec((1, bt), lambda ti, vi: (0, ti)),
        ],
        out_specs=[
            pl.BlockSpec((1, bt), lambda ti, vi: (0, ti)),
            pl.BlockSpec((1, bt), lambda ti, vi: (0, ti)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((1, t), jnp.float32),
            jax.ShapeDtypeStruct((1, t), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((1, bt), jnp.float32),
            pltpu.VMEM((1, bt), jnp.float32),
            pltpu.VMEM((1, bt), jnp.float32),
        ],
        interpret=_interpret(),
    )(x, w_vh, lab2)
    return loss[0], lse[0]


# ---- backward: recompute tiles, never materialize d_logits ------------------

def _dtile(x, w, labels, lse, g, vi, block_t, block_v, ignore_index):
    """d_logits tile = (softmax - onehot) * g, recomputed in VMEM."""
    tile = _dot_f32(x, w, ((1,), (1,)))
    p = jnp.exp(tile - lse[:, None])
    local = labels - vi * jnp.int32(block_v)
    col = jax.lax.broadcasted_iota(jnp.int32, (block_t, block_v), 1)
    onehot = (col == local[:, None]).astype(jnp.float32)
    valid = (labels != jnp.int32(ignore_index)).astype(jnp.float32)
    return (p - onehot) * (g * valid)[:, None]


def _bwd_dx_kernel(x_ref, w_ref, lab_ref, lse_ref, g_ref, dx_ref, *,
                   block_t, block_v, ignore_index):
    from jax.experimental import pallas as pl
    vi = pl.program_id(1)

    @pl.when(vi == 0)
    def _init():
        dx_ref[...] = jnp.zeros_like(dx_ref)

    d = _dtile(x_ref[...], w_ref[...], lab_ref[...][0], lse_ref[...][0],
               g_ref[...][0], vi, block_t, block_v, ignore_index)
    w = w_ref[...]
    dx_ref[...] += _dot_f32(d.astype(w.dtype), w, ((1,), (0,)))


def _bwd_dw_kernel(x_ref, w_ref, lab_ref, lse_ref, g_ref, dw_ref, *,
                   block_t, block_v, ignore_index):
    from jax.experimental import pallas as pl
    ti = pl.program_id(1)
    vi = pl.program_id(0)

    @pl.when(ti == 0)
    def _init():
        dw_ref[...] = jnp.zeros_like(dw_ref)

    x = x_ref[...]
    d = _dtile(x, w_ref[...], lab_ref[...][0], lse_ref[...][0],
               g_ref[...][0], vi, block_t, block_v, ignore_index)
    dw_ref[...] += _dot_f32(d.astype(x.dtype), x, ((0,), (0,)))


@_trace_32bit
def _pallas_bwd(x, w_vh, labels, lse, g, ignore_index):
    from jax.experimental import pallas as pl
    t, h = x.shape
    v = w_vh.shape[0]
    bt = _block_for(t, _BLOCK_T)
    bv = _block_for(v, _BLOCK_V)
    nt, nv = t // bt, v // bv
    lab2 = labels.astype(jnp.int32)[None, :]
    lse2 = lse[None, :]
    g2 = g.astype(jnp.float32)[None, :]

    dx = pl.pallas_call(
        functools.partial(_bwd_dx_kernel, block_t=bt, block_v=bv,
                          ignore_index=ignore_index),
        grid=(nt, nv),
        in_specs=[
            pl.BlockSpec((bt, h), lambda ti, vi: (ti, 0)),
            pl.BlockSpec((bv, h), lambda ti, vi: (vi, 0)),
            pl.BlockSpec((1, bt), lambda ti, vi: (0, ti)),
            pl.BlockSpec((1, bt), lambda ti, vi: (0, ti)),
            pl.BlockSpec((1, bt), lambda ti, vi: (0, ti)),
        ],
        out_specs=pl.BlockSpec((bt, h), lambda ti, vi: (ti, 0)),
        out_shape=jax.ShapeDtypeStruct((t, h), jnp.float32),
        interpret=_interpret(),
    )(x, w_vh, lab2, lse2, g2)

    dw = pl.pallas_call(
        functools.partial(_bwd_dw_kernel, block_t=bt, block_v=bv,
                          ignore_index=ignore_index),
        grid=(nv, nt),
        in_specs=[
            pl.BlockSpec((bt, h), lambda vi, ti: (ti, 0)),
            pl.BlockSpec((bv, h), lambda vi, ti: (vi, 0)),
            pl.BlockSpec((1, bt), lambda vi, ti: (0, ti)),
            pl.BlockSpec((1, bt), lambda vi, ti: (0, ti)),
            pl.BlockSpec((1, bt), lambda vi, ti: (0, ti)),
        ],
        out_specs=pl.BlockSpec((bv, h), lambda vi, ti: (vi, 0)),
        out_shape=jax.ShapeDtypeStruct((v, h), jnp.float32),
        interpret=_interpret(),
    )(x, w_vh, lab2, lse2, g2)
    return dx.astype(x.dtype), dw.astype(w_vh.dtype)


# ---- reference composition + custom vjp ------------------------------------

def _reference(x, w_vh, labels, ignore_index):
    logits = _dot_f32(x, w_vh, ((1,), (1,)))
    lse = jax.scipy.special.logsumexp(logits, axis=-1)
    ll = jnp.take_along_axis(
        logits, jnp.clip(labels, 0, w_vh.shape[0] - 1)[:, None].astype(
            jnp.int32), axis=-1)[:, 0]
    valid = labels != ignore_index
    return jnp.where(valid, lse - ll, 0.0)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def _fused_core(x, w_vh, labels, ignore_index):
    if _use_pallas(x, w_vh):
        return _pallas_fwd(x, w_vh, labels, ignore_index)[0]
    return _reference(x, w_vh, labels, ignore_index)


def _fused_fwd(x, w_vh, labels, ignore_index):
    if _use_pallas(x, w_vh):
        loss, lse = _pallas_fwd(x, w_vh, labels, ignore_index)
        return loss, (x, w_vh, labels, lse)
    return (_reference(x, w_vh, labels, ignore_index),
            (x, w_vh, labels, None))


def _xla_bwd(x, w_vh, labels, lse, g, ignore_index):
    """Backward as plain XLA ops from the saved lse: ONE logits
    recompute at XLA matmul efficiency, d_logits = (softmax-onehot)*g
    fused into its consumers by XLA, dx/dW as two MXU matmuls. Trades
    the Pallas bwd's zero-materialization for d_logits round-tripping
    HBM once in bf16 — but deletes the second logits recompute and runs
    every matmul at XLA's MXU scheduling, not a hand-rolled kernel's.
    Selected by PADDLE_FUSED_CE_BWD=xla (perf sweep axis)."""
    logits = _dot_f32(x, w_vh, ((1,), (1,)))
    p = jnp.exp(logits - lse[:, None])
    col = jax.lax.broadcasted_iota(jnp.int32, logits.shape, 1)
    onehot = (col == labels.astype(jnp.int32)[:, None]).astype(
        jnp.float32)
    valid = (labels != ignore_index).astype(jnp.float32)
    # d_logits stays f32 through BOTH matmuls (ADVICE r5): casting to
    # bf16 first would quantize the gradient signal the Pallas backward
    # keeps at f32 tile precision; only the final outputs narrow.
    # dot_general accepts the mixed f32/bf16 operands and accumulates
    # f32 (preferred_element_type in _dot_f32).
    d = (p - onehot) * (g.astype(jnp.float32) * valid)[:, None]
    dx = _dot_f32(d, w_vh, ((1,), (0,))).astype(x.dtype)
    dw = _dot_f32(d, x, ((0,), (0,))).astype(w_vh.dtype)
    return dx, dw


def _fused_bwd(ignore_index, res, g):
    x, w_vh, labels, lse = res
    if lse is None:  # reference path: differentiate the composition
        _, vjp = jax.vjp(
            lambda x_, w_: _reference(x_, w_, labels, ignore_index),
            x, w_vh)
        dx, dw = vjp(g)
        return dx, dw, None
    if _os.environ.get("PADDLE_FUSED_CE_BWD") == "xla":
        dx, dw = _xla_bwd(x, w_vh, labels, lse, g, ignore_index)
        return dx, dw, None
    dx, dw = _pallas_bwd(x, w_vh, labels, lse, g, ignore_index)
    return dx, dw, None


_fused_core.defvjp(_fused_fwd, _fused_bwd)


@register_op("fused_linear_cross_entropy")
def _fused_op(x, w_vh, labels, *, ignore_index):
    """Per-token loss [T] for logits = x @ w_vh.T, labels [T] int.
    ignore_index rows contribute 0 loss and 0 gradient."""
    return _fused_core(x, w_vh, labels, ignore_index)


def fused_linear_cross_entropy(x, weight_vh, labels, ignore_index=-100):
    """Public wrapper over Tensors: x [T, H], weight_vh [V, H] (paddle
    embedding layout — tied heads pass the embedding table directly),
    labels [T]. Returns per-token loss [T] (reduce outside)."""
    return _fused_op(x, weight_vh, labels,
                     ignore_index=int(ignore_index))


# ---- tensor-parallel (vocab-sharded) variant --------------------------------
#
# The reference's TP loss IS a fused vocab-sharded kernel:
# paddle/fluid/operators/collective/c_softmax_with_cross_entropy_op.cu:1
# — each rank computes its local logits shard's max / sum-exp / label
# hit, then combines with cross-rank allreduce(max) + allreduce(sum).
# TPU-native translation: shard_map over the 'mp' mesh axis; each shard
# runs the SAME single-chip Pallas streaming kernel on its local
# [V/mp, H] vocab tile, then lax.pmax/psum over 'mp' combine the
# per-shard logsumexp and label log-likelihood. The [tokens, vocab]
# logits tensor never exists in HBM on ANY shard, in either direction.

# out-of-vocab sentinel: never equals any (shifted) label, so the local
# kernels treat every row as "valid" and validity is applied OUTSIDE
# (ignore_index handling must be global, not per-shard: a shifted
# ignore label could alias a real local id on shard 0 otherwise)
_NEVER = -(2 ** 31 - 123)

# mesh registry keyed by CONTENT (axis names + device ids + shape), not
# id(): id-keyed entries pinned meshes forever and a recycled id could
# have mapped a jit-cached mesh key onto the wrong mesh. Equal meshes
# share one entry, so the registry is bounded by the number of distinct
# topologies in the process.
_TP_MESHES = {}


def _register_mesh(mesh):
    key = (tuple(mesh.axis_names),
           tuple(int(d.id) for d in mesh.devices.flat),
           tuple(mesh.devices.shape))
    _TP_MESHES[key] = mesh
    return key


def _local_fwd(x_l, w_l, lab_local):
    """(per-token local loss, local lse) for ONE vocab shard; labels
    already shifted to local coords, out-of-shard labels miss (ll=0,
    so local loss == local lse for them)."""
    if _use_pallas(x_l, w_l, tp=True):
        return _pallas_fwd(x_l, w_l, lab_local, _NEVER)
    logits = _dot_f32(x_l, w_l, ((1,), (1,)))
    lse = jax.scipy.special.logsumexp(logits, axis=-1)
    v_l = w_l.shape[0]
    hit = (lab_local >= 0) & (lab_local < v_l)
    ll = jnp.where(
        hit,
        jnp.take_along_axis(
            logits, jnp.clip(lab_local, 0, v_l - 1)[:, None].astype(
                jnp.int32), axis=-1)[:, 0],
        0.0)
    return lse - ll, lse


def _tp_specs(mesh, P):
    tok = "dp" if "dp" in mesh.axis_names else None
    return P(tok, None), P("mp", None), P(tok)


def _tp_fwd_impl(x, w_vh, labels, mesh_id, ignore_index):
    from jax.sharding import PartitionSpec as P
    mesh = _TP_MESHES[mesh_id]
    v_local = w_vh.shape[0] // mesh.shape["mp"]
    x_spec, w_spec, t_spec = _tp_specs(mesh, P)

    def body(x_l, w_l, lab_l):
        lab = lab_l.astype(jnp.int32)
        valid = lab != jnp.int32(ignore_index)
        shifted = (jnp.where(valid, lab, jnp.int32(_NEVER))
                   - jax.lax.axis_index("mp") * jnp.int32(v_local))
        loss_l, lse_l = _local_fwd(x_l, w_l, shifted)
        ll_l = lse_l - loss_l           # local label log-likelihood
        # distributed logsumexp: allreduce(max) + allreduce(sum), the
        # c_softmax_with_cross_entropy combine, on ICI via GSPMD
        m = jax.lax.pmax(lse_l, "mp")
        lse_g = m + jnp.log(jax.lax.psum(jnp.exp(lse_l - m), "mp"))
        ll_g = jax.lax.psum(ll_l, "mp")
        loss = jnp.where(valid, lse_g - ll_g, 0.0)
        return loss, lse_g

    return _shard_map(
        body, mesh=mesh, in_specs=(x_spec, w_spec, t_spec),
        out_specs=(t_spec, t_spec), check_vma=False)(x, w_vh, labels)


def _tp_bwd_impl(x, w_vh, labels, lse_g, g, mesh_id, ignore_index):
    from jax.sharding import PartitionSpec as P
    mesh = _TP_MESHES[mesh_id]
    v_local = w_vh.shape[0] // mesh.shape["mp"]
    x_spec, w_spec, t_spec = _tp_specs(mesh, P)

    def body(x_l, w_l, lab_l, lse_l, g_l):
        lab = lab_l.astype(jnp.int32)
        valid = lab != jnp.int32(ignore_index)
        shifted = (jnp.where(valid, lab, jnp.int32(_NEVER))
                   - jax.lax.axis_index("mp") * jnp.int32(v_local))
        # validity zeroes the cotangent (the kernels' sentinel
        # ignore_index treats every row as valid)
        g_eff = g_l * valid.astype(g_l.dtype)
        if _use_pallas(x_l, w_l, tp=True):
            # global lse → each shard's recomputed tile exponentiates
            # to the GLOBAL softmax slice; dx partial-sums over shards
            dx_l, dw_l = _pallas_bwd(x_l, w_l, shifted, lse_l, g_eff,
                                     _NEVER)
        else:
            logits = _dot_f32(x_l, w_l, ((1,), (1,)))
            p = jnp.exp(logits - lse_l[:, None])
            col = jax.lax.broadcasted_iota(
                jnp.int32, logits.shape, 1)
            onehot = (col == shifted[:, None]).astype(jnp.float32)
            d = (p - onehot) * g_eff.astype(jnp.float32)[:, None]
            dx_l = _dot_f32(d.astype(w_l.dtype), w_l, ((1,), (0,)))
            dw_l = _dot_f32(d.astype(x_l.dtype), x_l, ((0,), (0,)))
        # dx partial-sums over the vocab ('mp') shards; dw over the
        # token ('dp') shards — each axis reduces the dim it splits
        dx = jax.lax.psum(dx_l.astype(x_l.dtype), "mp")
        dw = dw_l.astype(w_l.dtype)
        if "dp" in mesh.axis_names:
            dw = jax.lax.psum(dw, "dp")
        return dx, dw

    return _shard_map(
        body, mesh=mesh,
        in_specs=(x_spec, w_spec, t_spec, t_spec, t_spec),
        out_specs=(x_spec, w_spec), check_vma=False)(
            x, w_vh, labels, lse_g, g)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def _fused_tp_core(x, w_vh, labels, mesh_id, ignore_index):
    return _tp_fwd_impl(x, w_vh, labels, mesh_id, ignore_index)[0]


def _fused_tp_fwd(x, w_vh, labels, mesh_id, ignore_index):
    loss, lse_g = _tp_fwd_impl(x, w_vh, labels, mesh_id, ignore_index)
    return loss, (x, w_vh, labels, lse_g)


def _fused_tp_bwd(mesh_id, ignore_index, res, g):
    x, w_vh, labels, lse_g = res
    dx, dw = _tp_bwd_impl(x, w_vh, labels, lse_g, g, mesh_id,
                          ignore_index)
    return dx, dw, None


_fused_tp_core.defvjp(_fused_tp_fwd, _fused_tp_bwd)


@register_op("fused_linear_cross_entropy_tp")
def _fused_tp_op(x, w_vh, labels, *, mesh_id, ignore_index):
    return _fused_tp_core(x, w_vh, labels, mesh_id, ignore_index)


def tp_fused_applicable(mesh, t, h, v):
    """The fused TP head handles meshes whose parallel axes are
    dp/mp/sharding (pp stages slice the program before the head; the
    pipelined loss keeps the composition) with the vocab and token dims
    dividing evenly over their axes."""
    if mesh is None or "mp" not in mesh.axis_names:
        return False
    mp = int(mesh.shape["mp"])
    if mp <= 1 or v % mp != 0:
        return False
    if int(mesh.shape.get("pp", 1)) != 1:
        return False
    dp = int(mesh.shape.get("dp", 1))
    return t % max(dp, 1) == 0


def fused_linear_cross_entropy_tp(x, weight_vh, labels, mesh,
                                  ignore_index=-100):
    """Vocab-sharded fused linear+CE: weight_vh [V, H] sharded over the
    'mp' mesh axis, x [T, H] (tokens dp-sharded when the mesh has a dp
    axis), labels [T]. Per-token loss [T]. Reference:
    c_softmax_with_cross_entropy_op.cu (allreduce-max/sum combine)."""
    return _fused_tp_op(x, weight_vh, labels,
                        mesh_id=_register_mesh(mesh),
                        ignore_index=int(ignore_index))
