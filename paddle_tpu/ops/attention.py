"""Fused attention.

TPU-native: flash attention as a Pallas kernel for the hot path
(reference analogue: paddle/fluid/operators/math/bert_encoder_functor.cu
and fused multihead-matmul passes — here it's one fused VMEM-resident
kernel instead of a fusion pass). Falls back to the XLA softmax(QK^T)V
composition for small shapes or on CPU where Pallas TPU kernels are
unavailable.

Layout: [batch, num_heads, seq, head_dim].
"""
import functools
import math

import jax
import jax.numpy as jnp

from ..core.dispatch import register_op


def _reference_attention(q, k, v, mask, scale, causal):
    qk = jnp.einsum("bhsd,bhtd->bhst", q, k) * scale
    if causal:
        s, t = qk.shape[-2], qk.shape[-1]
        causal_mask = jnp.tril(jnp.ones((s, t), bool), k=t - s)
        qk = jnp.where(causal_mask, qk, jnp.asarray(-1e30, qk.dtype))
    if mask is not None:
        qk = qk + mask
    w = jax.nn.softmax(qk.astype(jnp.float32), axis=-1).astype(q.dtype)
    return jnp.einsum("bhst,bhtd->bhsd", w, v)


def _use_pallas(q):
    if jax.default_backend() == "cpu":
        return False
    b, h, s, d = q.shape
    return s >= 256 and d in (64, 128, 256) and s % 128 == 0


def _flash_fwd_kernel(q_ref, k_ref, v_ref, o_ref, *, scale, causal,
                      block_k, seq_len):
    from jax.experimental import pallas as pl
    q = q_ref[...].astype(jnp.float32) * scale
    block_q = q.shape[0]
    qi = pl.program_id(2)

    def body(start, carry):
        acc, m_prev, l_prev = carry
        k = pl.load(k_ref, (pl.ds(start * block_k, block_k), slice(None))
                    ).astype(jnp.float32)
        v = pl.load(v_ref, (pl.ds(start * block_k, block_k), slice(None))
                    ).astype(jnp.float32)
        s = q @ k.T  # [block_q, block_k]
        if causal:
            q_pos = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            k_pos = start * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, -1e30)
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1))
        p = jnp.exp(s - m_new[:, None])
        alpha = jnp.exp(m_prev - m_new)
        l_new = alpha * l_prev + jnp.sum(p, axis=1)
        acc = acc * alpha[:, None] + p @ v
        return acc, m_new, l_new

    block_q_sz = q.shape[0]
    d = v_ref.shape[-1]
    acc0 = jnp.zeros((block_q_sz, d), jnp.float32)
    m0 = jnp.full((block_q_sz,), -1e30, jnp.float32)
    l0 = jnp.zeros((block_q_sz,), jnp.float32)
    num_k_blocks = seq_len // block_k
    if causal:
        # only blocks up to the diagonal contribute
        max_block = (qi + 1) * block_q  # exclusive end position
        nkb = jax.lax.div(max_block + block_k - 1, block_k)
    else:
        nkb = num_k_blocks
    acc, m, l = jax.lax.fori_loop(0, nkb, body, (acc0, m0, l0))
    o_ref[...] = (acc / l[:, None]).astype(o_ref.dtype)


def _pallas_flash(q, k, v, scale, causal):
    from jax.experimental import pallas as pl
    b, h, s, d = q.shape
    block_q = min(128, s)
    block_k = min(128, s)
    kernel = functools.partial(_flash_fwd_kernel, scale=scale, causal=causal,
                               block_k=block_k, seq_len=s)
    out = pl.pallas_call(
        kernel,
        grid=(b, h, s // block_q),
        in_specs=[
            pl.BlockSpec((None, None, block_q, d),
                         lambda bi, hi, qi: (bi, hi, qi, 0)),
            pl.BlockSpec((None, None, s, d), lambda bi, hi, qi: (bi, hi, 0, 0)),
            pl.BlockSpec((None, None, s, d), lambda bi, hi, qi: (bi, hi, 0, 0)),
        ],
        out_specs=pl.BlockSpec((None, None, block_q, d),
                               lambda bi, hi, qi: (bi, hi, qi, 0)),
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
    )(q, k, v)
    return out


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def _flash_attention_core(q, k, v, scale, causal):
    if _use_pallas(q):
        return _pallas_flash(q, k, v, scale, causal)
    return _reference_attention(q, k, v, None, scale, causal)


def _flash_fwd(q, k, v, scale, causal):
    return _flash_attention_core(q, k, v, scale, causal), (q, k, v)


def _flash_bwd(scale, causal, res, g):
    q, k, v = res
    # recompute-based backward through the reference composition: XLA fuses
    # this well; a Pallas backward kernel is a later optimization.
    _, vjp = jax.vjp(lambda q_, k_, v_: _reference_attention(
        q_, k_, v_, None, scale, causal), q, k, v)
    return vjp(g)


_flash_attention_core.defvjp(_flash_fwd, _flash_bwd)


@register_op("flash_attention")
def _flash_op(q, k, v, mask, *, scale, causal):
    if mask is not None:
        return _reference_attention(q, k, v, mask, scale, causal)
    return _flash_attention_core(q, k, v, scale, causal)


def scaled_dot_product_attention(query, key, value, attn_mask=None,
                                 dropout_p=0.0, is_causal=False,
                                 training=True, scale=None, name=None):
    """Inputs [batch, heads, seq, head_dim] (or [b, s, h, d] paddle-style
    is accepted via transpose by callers). Dropout inside attention is not
    fused; applied to weights only in the fallback path when requested."""
    sc = scale if scale is not None else 1.0 / math.sqrt(query.shape[-1])
    return _flash_op(query, key, value, attn_mask, scale=float(sc),
                     causal=bool(is_causal))


def flash_attention(query, key, value, dropout=0.0, causal=False,
                    return_softmax=False, name=None):
    out = scaled_dot_product_attention(query, key, value, is_causal=causal)
    if return_softmax:
        return out, None
    return out
