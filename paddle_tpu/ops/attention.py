"""Fused attention.

TPU-native: flash attention as Pallas kernels for the hot path —
FORWARD (online-softmax, VMEM-resident) and BACKWARD (recompute-based,
O(seq) memory: the full [s, t] score matrix is never materialized),
the greenfield requirement SURVEY §5 sets for long-context. Reference
analogue: paddle/fluid/operators/math/bert_encoder_functor.cu and the
fused multihead-matmul passes — here it's fused kernels instead of
fusion passes. Falls back to the XLA softmax(QK^T)V composition for
small shapes or on CPU where Pallas TPU kernels are unavailable
(interpret mode exercises the kernels in CPU tests).

Layout: [batch, num_heads, seq, head_dim].
"""
import functools
import math

import jax
import jax.numpy as jnp

from ..core.dispatch import register_op
from .pallas_compat import trace_32bit as _trace_32bit

# tests flip this to run the Pallas kernels in interpret mode on CPU
_FORCE_INTERPRET = [False]


def _dot_f32(a, b, dims):
    """MXU matmul in the operands' native dtype (bf16 runs at full MXU
    rate — casting to f32 first would cut throughput 4-8x on v5e) with
    float32 accumulation. dims = ((a_contract,), (b_contract,))."""
    return jax.lax.dot_general(a, b, (dims, ((), ())),
                               preferred_element_type=jnp.float32)


def _reference_attention(q, k, v, mask, scale, causal):
    qk = jnp.einsum("bhsd,bhtd->bhst", q, k) * scale
    if causal:
        s, t = qk.shape[-2], qk.shape[-1]
        causal_mask = jnp.tril(jnp.ones((s, t), bool), k=t - s)
        qk = jnp.where(causal_mask, qk, jnp.asarray(-1e30, qk.dtype))
    if mask is not None:
        qk = qk + mask
    w = jax.nn.softmax(qk.astype(jnp.float32), axis=-1).astype(q.dtype)
    return jnp.einsum("bhst,bhtd->bhsd", w, v)


def _use_pallas(q):
    b, h, s, d = q.shape
    # f64 cannot lower on Mosaic (and the kernels trace in 32-bit mode)
    if q.dtype not in (jnp.float32, jnp.bfloat16, jnp.float16):
        return False
    shape_ok = s >= 256 and d in (64, 128, 256) and s % 128 == 0
    if _FORCE_INTERPRET[0]:
        return s % 128 == 0 and s >= 128
    if jax.default_backend() == "cpu":
        return False
    return shape_ok


def _interpret():
    return _FORCE_INTERPRET[0]


# ---- forward kernel --------------------------------------------------------

def _flash_fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref,
                      acc_ref, m_ref, l_ref, *, scale, causal,
                      block_q, block_k, nk):
    """Grid (b, h, nq, nk): K/V stream through VMEM one block at a
    time, so VMEM use is O(block) — independent of seq length (a
    full-seq-resident K/V caps out near seq 16k on the 16MB budget).
    The online-softmax state (acc, m, l) lives in VMEM scratch, which
    persists across the sequentially-executed inner ki grid steps; the
    o/lse output blocks are revisited and written once at the last ki."""
    from jax.experimental import pallas as pl
    qi = pl.program_id(2)
    ki = pl.program_id(3)

    @pl.when(ki == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, -1e30)
        l_ref[...] = jnp.zeros_like(l_ref)

    def _compute():
        q = q_ref[...]
        k = k_ref[...]
        v = v_ref[...]
        # [block_q, block_k] = q @ k.T, f32 accumulation
        s = _dot_f32(q, k, ((1,), (1,))) * jnp.float32(scale)
        if causal:
            q_pos = qi * jnp.int32(block_q) + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            k_pos = ki * jnp.int32(block_k) + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, jnp.float32(-1e30))
        m_prev = m_ref[...][0]
        l_prev = l_ref[...][0]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1))
        p = jnp.exp(s - m_new[:, None])
        alpha = jnp.exp(m_prev - m_new)
        l_ref[...] = (alpha * l_prev + jnp.sum(p, axis=1))[None, :]
        m_ref[...] = m_new[None, :]
        pv = _dot_f32(p.astype(v.dtype), v, ((1,), (0,)))
        acc_ref[...] = acc_ref[...] * alpha[:, None] + pv

    if causal:
        # fully-future K blocks contribute nothing: skip their matmuls
        pl.when(ki * block_k <= qi * block_q + block_q - 1)(_compute)
    else:
        _compute()

    @pl.when(ki == nk - 1)
    def _store():
        l = l_ref[...][0]
        o_ref[...] = (acc_ref[...] / l[:, None]).astype(o_ref.dtype)
        lse_ref[...] = m_ref[...] + jnp.log(l)[None, :]


def _pallas_flash_fwd(q, k, v, scale, causal):
    # x64 guard shared by every Pallas entry point (pallas_compat)
    return _trace_32bit(_pallas_flash_fwd_32)(q, k, v, scale, causal)


import os as _os

# Block sizes: 128-row blocks leave the MXU underfed (64-deep contractions
# on 128x128 tiles) and pay per-grid-cell DMA/semaphore overhead; 512
# amortizes both while staying well inside the 16MB VMEM budget at
# d=64..256. Measured on v5e at [8,12,1024,64] bf16 causal: grad
# 7.4ms (block 128) -> 4.7ms (block 512), 1.9x faster than
# jax.experimental.pallas.ops.tpu.flash_attention on the same shape.
_BLOCK_Q = int(_os.environ.get("PADDLE_FLASH_BLOCK_Q", "512"))
_BLOCK_K = int(_os.environ.get("PADDLE_FLASH_BLOCK_K", "512"))
_BLOCK_BWD = int(_os.environ.get("PADDLE_FLASH_BLOCK_BWD", "512"))


def _block_for(s, want):
    """Largest power-of-two block <= want that divides s (s is a
    multiple of 128 per the _use_pallas gate, so the halving loop
    terminates by 128; non-power-of-two env overrides are rounded down
    so it cannot degenerate below that)."""
    want = max(128, 1 << (max(want, 1).bit_length() - 1))
    blk = min(want, s)
    while s % blk:
        blk //= 2
    return blk


def _pallas_flash_fwd_32(q, k, v, scale, causal):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu
    b, h, s, d = q.shape
    block_q = _block_for(s, _BLOCK_Q)
    block_k = _block_for(s, _BLOCK_K)
    nq, nk = s // block_q, s // block_k
    kernel = functools.partial(_flash_fwd_kernel, scale=scale,
                               causal=causal, block_q=block_q,
                               block_k=block_k, nk=nk)
    out, lse = pl.pallas_call(
        kernel,
        grid=(b, h, nq, nk),
        in_specs=[
            pl.BlockSpec((None, None, block_q, d),
                         lambda bi, hi, qi, ki: (bi, hi, qi, 0)),
            pl.BlockSpec((None, None, block_k, d),
                         lambda bi, hi, qi, ki: (bi, hi, ki, 0)),
            pl.BlockSpec((None, None, block_k, d),
                         lambda bi, hi, qi, ki: (bi, hi, ki, 0)),
        ],
        out_specs=[
            pl.BlockSpec((None, None, block_q, d),
                         lambda bi, hi, qi, ki: (bi, hi, qi, 0)),
            # mosaic needs the last two block dims ~(8,128)-aligned or
            # full; a [b,h,1,s] layout makes the lse block (1, block_q)
            pl.BlockSpec((None, None, 1, block_q),
                         lambda bi, hi, qi, ki: (bi, hi, 0, qi)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct(q.shape, q.dtype),
            jax.ShapeDtypeStruct((b, h, 1, s), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, d), jnp.float32),
            pltpu.VMEM((1, block_q), jnp.float32),
            pltpu.VMEM((1, block_q), jnp.float32),
        ],
        interpret=_interpret(),
    )(q, k, v)
    return out, lse


# ---- backward kernels (flash-attention-2 style, O(seq) memory) -------------
# 4D grid (b, h, outer, inner): the inner loop is a GRID dimension, so
# only block-sized tiles live in VMEM at a time (full-seq tiles blew the
# 16MB scoped-vmem budget at seq 16k); the output block is revisited
# across inner steps and accumulated (TPU grids execute sequentially).

def _flash_bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                         dq_ref, *, scale, causal, block_q, block_k):
    from jax.experimental import pallas as pl
    qi = pl.program_id(2)
    ki = pl.program_id(3)

    @pl.when(ki == 0)
    def _init():
        dq_ref[...] = jnp.zeros_like(dq_ref)

    def _compute():
        q = q_ref[...]
        do = do_ref[...]
        lse = lse_ref[...][0]
        delta = delta_ref[...][0]
        k = k_ref[...]
        v = v_ref[...]
        s = _dot_f32(q, k, ((1,), (1,))) * jnp.float32(scale)
        if causal:
            q_pos = qi * jnp.int32(block_q) + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            k_pos = ki * jnp.int32(block_k) + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, jnp.float32(-1e30))
        p = jnp.exp(s - lse[:, None])
        dp = _dot_f32(do, v, ((1,), (1,)))
        ds = p * (dp - delta[:, None])
        dq_ref[...] += _dot_f32(ds.astype(k.dtype), k,
                                ((1,), (0,))) * jnp.float32(scale)

    if causal:
        pl.when(qi >= ki)(_compute)  # fully-future blocks contribute 0
    else:
        _compute()


def _flash_bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                          dk_ref, dv_ref, *, scale, causal, block_q,
                          block_k):
    from jax.experimental import pallas as pl
    ki = pl.program_id(2)
    qi = pl.program_id(3)

    @pl.when(qi == 0)
    def _init():
        dk_ref[...] = jnp.zeros_like(dk_ref)
        dv_ref[...] = jnp.zeros_like(dv_ref)

    def _compute():
        k = k_ref[...]
        v = v_ref[...]
        q = q_ref[...]
        do = do_ref[...]
        lse = lse_ref[...][0]
        delta = delta_ref[...][0]
        s = _dot_f32(q, k, ((1,), (1,))) * jnp.float32(scale)
        if causal:
            q_pos = qi * jnp.int32(block_q) + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            k_pos = ki * jnp.int32(block_k) + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, jnp.float32(-1e30))
        p = jnp.exp(s - lse[:, None])
        # p.T @ do and ds.T @ q, contracting over the block_q axis
        dv_ref[...] += _dot_f32(p.astype(do.dtype), do, ((0,), (0,)))
        dp = _dot_f32(do, v, ((1,), (1,)))
        ds = p * (dp - delta[:, None])
        dk_ref[...] += _dot_f32(ds.astype(q.dtype), q,
                                ((0,), (0,))) * jnp.float32(scale)

    if causal:
        pl.when(qi >= ki)(_compute)
    else:
        _compute()


def _pallas_flash_bwd(q, k, v, out, lse, g, scale, causal):
    return _trace_32bit(_pallas_flash_bwd_32)(q, k, v, out, lse, g,
                                              scale, causal)


def _pallas_flash_bwd_32(q, k, v, out, lse, g, scale, causal):
    from jax.experimental import pallas as pl
    b, h, s, d = q.shape
    block = _block_for(s, _BLOCK_BWD)
    n = s // block
    # delta = rowsum(dO * O): O(s d) precompute outside the kernels
    delta = jnp.sum(g.astype(jnp.float32) * out.astype(jnp.float32),
                    axis=-1)[:, :, None, :]  # [b, h, 1, s]

    def blk(which):  # index by grid dim 2 or 3
        return pl.BlockSpec(
            (None, None, block, d),
            (lambda bi, hi, i, j: (bi, hi, i, 0)) if which == 2
            else (lambda bi, hi, i, j: (bi, hi, j, 0)))

    def vec(which):
        return pl.BlockSpec(
            (None, None, 1, block),
            (lambda bi, hi, i, j: (bi, hi, 0, i)) if which == 2
            else (lambda bi, hi, i, j: (bi, hi, 0, j)))

    f32 = jnp.float32
    # dq: grid (b, h, nq, nk); dq block revisited across nk
    dq = pl.pallas_call(
        functools.partial(_flash_bwd_dq_kernel, scale=scale,
                          causal=causal, block_q=block, block_k=block),
        grid=(b, h, n, n),
        in_specs=[blk(2), blk(3), blk(3), blk(2), vec(2), vec(2)],
        out_specs=blk(2),
        out_shape=jax.ShapeDtypeStruct(q.shape, f32),
        interpret=_interpret(),
    )(q, k, v, g, lse, delta)

    # dk/dv: grid (b, h, nk, nq); dk/dv blocks revisited across nq
    dk, dv = pl.pallas_call(
        functools.partial(_flash_bwd_dkv_kernel, scale=scale,
                          causal=causal, block_q=block, block_k=block),
        grid=(b, h, n, n),
        in_specs=[blk(3), blk(2), blk(2), blk(3), vec(3), vec(3)],
        out_specs=[blk(2), blk(2)],
        out_shape=[jax.ShapeDtypeStruct(k.shape, f32),
                   jax.ShapeDtypeStruct(v.shape, f32)],
        interpret=_interpret(),
    )(q, k, v, g, lse, delta)
    return (dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype))


# ---- custom-vjp wrapper ----------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def _flash_attention_core(q, k, v, scale, causal):
    if _use_pallas(q):
        return _pallas_flash_fwd(q, k, v, scale, causal)[0]
    return _reference_attention(q, k, v, None, scale, causal)


def _flash_fwd(q, k, v, scale, causal):
    if _use_pallas(q):
        out, lse = _pallas_flash_fwd(q, k, v, scale, causal)
        return out, (q, k, v, out, lse)
    out = _reference_attention(q, k, v, None, scale, causal)
    return out, (q, k, v, None, None)


def _flash_bwd(scale, causal, res, g):
    q, k, v, out, lse = res
    if lse is not None and _use_pallas(q):
        return _pallas_flash_bwd(q, k, v, out, lse, g, scale, causal)
    # small-shape / CPU fallback: recompute through the reference
    # composition (XLA fuses it; memory is O(s^2), fine at these sizes)
    _, vjp = jax.vjp(lambda q_, k_, v_: _reference_attention(
        q_, k_, v_, None, scale, causal), q, k, v)
    return vjp(g)


_flash_attention_core.defvjp(_flash_fwd, _flash_bwd)


@register_op("flash_attention")
def _flash_op(q, k, v, mask, *, scale, causal):
    if mask is not None:
        return _reference_attention(q, k, v, mask, scale, causal)
    return _flash_attention_core(q, k, v, scale, causal)


def scaled_dot_product_attention(query, key, value, attn_mask=None,
                                 dropout_p=0.0, is_causal=False,
                                 training=True, scale=None, name=None):
    """Inputs [batch, heads, seq, head_dim] (or [b, s, h, d] paddle-style
    is accepted via transpose by callers). Dropout inside attention is not
    fused; applied to weights only in the fallback path when requested."""
    sc = scale if scale is not None else 1.0 / math.sqrt(query.shape[-1])
    return _flash_op(query, key, value, attn_mask, scale=float(sc),
                     causal=bool(is_causal))


def cached_slot_attention(q, k_cache, v_cache, lengths):
    """Single-token decode attention over a slot-pooled static cache
    with per-slot cache-length masking (the serving decode step,
    text.models.GPTForCausalLM.build_serving_fns).

    q [S, nh, hd] — one new-token query per slot;
    k_cache/v_cache [S, nh, C, hd] — each slot's full static cache;
    lengths [S] int — live prefix length per slot (prompt + generated
    so far, INCLUDING the row just written for this step).

    Key positions >= lengths[s] get -1e30 before the f32 softmax, so
    stale K/V from a slot's previous occupant (and prefill pad rows)
    carry exactly-zero weight — a recycled slot is bit-identical to a
    fresh one. Same score scale / mask value / softmax as the causal
    decode in generate(): for lengths = pos + 1 this IS its mask,
    vectorized over slots."""
    hd = q.shape[-1]
    cache_len = k_cache.shape[2]
    # f32 score accumulation (the _dot_f32 discipline): bf16 caches
    # keep full MXU rate but never sum scores in bf16; a no-op for f32
    s = jnp.einsum("shd,shkd->shk", q, k_cache,
                   preferred_element_type=jnp.float32) / jnp.sqrt(
        jnp.float32(hd))
    kpos = jnp.arange(cache_len)[None, None, :]
    s = jnp.where(kpos < lengths[:, None, None], s,
                  jnp.float32(-1e30))
    return jnp.einsum("shk,shkd->shd", jax.nn.softmax(s, axis=-1),
                      v_cache, preferred_element_type=jnp.float32)


def cached_paged_attention(q, k_cache, v_cache, block_tables, lengths):
    """Single-token decode attention over a PAGED cache addressed
    through a fixed-shape block table (the serving paged decode step,
    serving.paged.programs.build_paged_fns).

    q [S, nh, hd] — one new-token query per slot;
    k_cache/v_cache [num_blocks, nh, block_size, hd] — one layer's
    pooled block arrays;
    block_tables [S, max_blocks] int — each slot's logical->physical
    block row (padding/released entries point at the trash block);
    lengths [S] int — live prefix length per slot, INCLUDING the row
    just written for this step.

    Gathers each slot's blocks into a position-ordered contiguous view
    [S, nh, max_blocks*block_size, hd] (view index block*BS + offset IS
    the cache position) and defers to cached_slot_attention's length
    masking — positions >= lengths[s], which includes every trash-block
    row a padding entry gathered, get -1e30 before the f32 softmax and
    carry exactly-zero weight. For block tables describing the same
    live prefixes this computes bit-for-bit what the slot-contiguous
    path computes; it is the XLA-composed gather baseline — and the
    parity oracle / fallback — for the Pallas paged decode kernel
    (ops.paged_attention, PADDLE_PAGED_ATTN) that reads the blocks in
    place instead."""
    S, nh, hd = q.shape
    k = jnp.take(k_cache, block_tables, axis=0)  # [S, MB, nh, BS, hd]
    v = jnp.take(v_cache, block_tables, axis=0)
    k = k.transpose(0, 2, 1, 3, 4).reshape(S, nh, -1, hd)
    v = v.transpose(0, 2, 1, 3, 4).reshape(S, nh, -1, hd)
    return cached_slot_attention(q, k, v, lengths)


def cached_slot_block_attention(q, k_cache, v_cache, qpos):
    """Multi-query decode attention over a slot-pooled static cache:
    the t-token generalization of cached_slot_attention, used by the
    speculative k-token verify program (serving.spec.programs) where
    every slot scores t = k+1 candidate positions in one dispatch.

    q [S, nh, t, hd] — t new-token queries per slot (the slot's last
    accepted token plus its k drafted continuations);
    k_cache/v_cache [S, nh, C, hd] — each slot's full static cache,
    INCLUDING the t candidate rows this dispatch just wrote;
    qpos [S, t] int — the cache position of each query.

    Per-query causal masking ``kpos <= qpos[s, i]`` makes query i see
    exactly the slot's live prefix plus candidates 0..i — so logits at
    position i are conditioned only on the (accepted-by-construction)
    prefix of the draft, which is what makes longest-accepted-prefix
    harvest bit-exact with one-token-at-a-time greedy decode. For
    t = 1 and qpos = lengths - 1 this IS cached_slot_attention's mask;
    stale rows beyond qpos (a recycled slot's previous occupant, or a
    rejected draft tail from a previous verify step) carry exactly-zero
    softmax weight."""
    hd = q.shape[-1]
    cache_len = k_cache.shape[2]
    s = jnp.einsum("shtd,shkd->shtk", q, k_cache,
                   preferred_element_type=jnp.float32) / jnp.sqrt(
        jnp.float32(hd))
    kpos = jnp.arange(cache_len)[None, None, None, :]
    s = jnp.where(kpos <= qpos[:, None, :, None], s,
                  jnp.float32(-1e30))
    return jnp.einsum("shtk,shkd->shtd", jax.nn.softmax(s, axis=-1),
                      v_cache, preferred_element_type=jnp.float32)


def cached_paged_block_attention(q, k_cache, v_cache, block_tables,
                                 qpos):
    """Multi-query decode attention over a PAGED cache: the t-token
    generalization of cached_paged_attention for the speculative
    verify program on the paged pool. Same gather-to-contiguous
    baseline (view index block*BS + offset IS the cache position),
    then cached_slot_block_attention's per-query causal mask — trash-
    block rows a padding table entry gathered sit beyond every qpos
    and carry exactly-zero weight."""
    S, nh, t, hd = q.shape
    k = jnp.take(k_cache, block_tables, axis=0)  # [S, MB, nh, BS, hd]
    v = jnp.take(v_cache, block_tables, axis=0)
    k = k.transpose(0, 2, 1, 3, 4).reshape(S, nh, -1, hd)
    v = v.transpose(0, 2, 1, 3, 4).reshape(S, nh, -1, hd)
    return cached_slot_block_attention(q, k, v, qpos)


def flash_attention(query, key, value, dropout=0.0, causal=False,
                    return_softmax=False, name=None):
    out = scaled_dot_product_attention(query, key, value, is_causal=causal)
    if return_softmax:
        return out, None
    return out
