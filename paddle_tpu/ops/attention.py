"""Fused attention.

TPU-native: flash attention as Pallas kernels for the hot path —
FORWARD (online-softmax, VMEM-resident) and BACKWARD (recompute-based,
O(seq) memory: the full [s, t] score matrix is never materialized),
the greenfield requirement SURVEY §5 sets for long-context. Reference
analogue: paddle/fluid/operators/math/bert_encoder_functor.cu and the
fused multihead-matmul passes — here it's fused kernels instead of
fusion passes. Falls back to the XLA softmax(QK^T)V composition for
small shapes or on CPU where Pallas TPU kernels are unavailable
(interpret mode exercises the kernels in CPU tests).

Layout: [batch, num_heads, seq, head_dim].
"""
import functools
import math

import jax
import jax.numpy as jnp

from ..core.dispatch import register_op

# tests flip this to run the Pallas kernels in interpret mode on CPU
_FORCE_INTERPRET = [False]


def _reference_attention(q, k, v, mask, scale, causal):
    qk = jnp.einsum("bhsd,bhtd->bhst", q, k) * scale
    if causal:
        s, t = qk.shape[-2], qk.shape[-1]
        causal_mask = jnp.tril(jnp.ones((s, t), bool), k=t - s)
        qk = jnp.where(causal_mask, qk, jnp.asarray(-1e30, qk.dtype))
    if mask is not None:
        qk = qk + mask
    w = jax.nn.softmax(qk.astype(jnp.float32), axis=-1).astype(q.dtype)
    return jnp.einsum("bhst,bhtd->bhsd", w, v)


def _use_pallas(q):
    b, h, s, d = q.shape
    # f64 cannot lower on Mosaic (and the kernels trace in 32-bit mode)
    if q.dtype not in (jnp.float32, jnp.bfloat16, jnp.float16):
        return False
    shape_ok = s >= 256 and d in (64, 128, 256) and s % 128 == 0
    if _FORCE_INTERPRET[0]:
        return s % 128 == 0 and s >= 128
    if jax.default_backend() == "cpu":
        return False
    return shape_ok


def _interpret():
    return _FORCE_INTERPRET[0]


# ---- forward kernel --------------------------------------------------------

def _flash_fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *, scale,
                      causal, block_k, seq_len):
    from jax.experimental import pallas as pl
    q = q_ref[...].astype(jnp.float32) * jnp.float32(scale)
    block_q = q.shape[0]
    qi = pl.program_id(2)

    def body(start, carry):
        acc, m_prev, l_prev = carry
        k = k_ref[pl.ds(start * jnp.int32(block_k), block_k), :].astype(jnp.float32)
        v = v_ref[pl.ds(start * jnp.int32(block_k), block_k), :].astype(jnp.float32)
        s = q @ k.T  # [block_q, block_k]
        if causal:
            q_pos = qi * jnp.int32(block_q) + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            k_pos = start * jnp.int32(block_k) + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, jnp.float32(-1e30))
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1))
        p = jnp.exp(s - m_new[:, None])
        alpha = jnp.exp(m_prev - m_new)
        l_new = alpha * l_prev + jnp.sum(p, axis=1)
        acc = acc * alpha[:, None] + p @ v
        return acc, m_new, l_new

    d = v_ref.shape[-1]
    acc0 = jnp.zeros((block_q, d), jnp.float32)
    m0 = jnp.full((block_q,), -1e30, jnp.float32)
    l0 = jnp.zeros((block_q,), jnp.float32)
    # NOTE: full-range loop even for causal — a program-id-dependent
    # trip count does not lower on Mosaic; instead each body invocation
    # branches on the block index, so future blocks cost a predicate,
    # not three matmuls
    nkb = seq_len // block_k
    if causal:
        inner = body

        def body(start, carry):  # noqa: F811
            return jax.lax.cond(
                start * jnp.int32(block_k) <= qi * jnp.int32(block_q)
                + jnp.int32(block_q - 1),
                lambda c: inner(start, c), lambda c: c, carry)
    acc, m, l = jax.lax.fori_loop(0, nkb, body, (acc0, m0, l0))
    o_ref[...] = (acc / l[:, None]).astype(o_ref.dtype)
    lse_ref[...] = (m + jnp.log(l))[None, :]


def _pallas_flash_fwd(q, k, v, scale, causal):
    from jax.experimental import pallas as pl
    # the framework enables jax_enable_x64 globally (paddle int64/float64
    # dtypes); inside the kernels python literals would become i64/f64,
    # which Mosaic cannot lower — trace the kernels in 32-bit mode
    with jax.enable_x64(False):
        return _pallas_flash_fwd_32(q, k, v, scale, causal)


def _pallas_flash_fwd_32(q, k, v, scale, causal):
    from jax.experimental import pallas as pl
    b, h, s, d = q.shape
    block_q = min(128, s)
    block_k = min(128, s)
    kernel = functools.partial(_flash_fwd_kernel, scale=scale,
                               causal=causal, block_k=block_k, seq_len=s)
    out, lse = pl.pallas_call(
        kernel,
        grid=(b, h, s // block_q),
        in_specs=[
            pl.BlockSpec((None, None, block_q, d),
                         lambda bi, hi, qi: (bi, hi, qi, 0)),
            pl.BlockSpec((None, None, s, d),
                         lambda bi, hi, qi: (bi, hi, 0, 0)),
            pl.BlockSpec((None, None, s, d),
                         lambda bi, hi, qi: (bi, hi, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((None, None, block_q, d),
                         lambda bi, hi, qi: (bi, hi, qi, 0)),
            # mosaic needs the last two block dims ~(8,128)-aligned or
            # full; a [b,h,1,s] layout makes the lse block (1, block_q)
            pl.BlockSpec((None, None, 1, block_q),
                         lambda bi, hi, qi: (bi, hi, 0, qi)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct(q.shape, q.dtype),
            jax.ShapeDtypeStruct((b, h, 1, s), jnp.float32),
        ],
        interpret=_interpret(),
    )(q, k, v)
    return out, lse


# ---- backward kernels (flash-attention-2 style, O(seq) memory) -------------
# 4D grid (b, h, outer, inner): the inner loop is a GRID dimension, so
# only block-sized tiles live in VMEM at a time (full-seq tiles blew the
# 16MB scoped-vmem budget at seq 16k); the output block is revisited
# across inner steps and accumulated (TPU grids execute sequentially).

def _flash_bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                         dq_ref, *, scale, causal, block_q, block_k):
    from jax.experimental import pallas as pl
    qi = pl.program_id(2)
    ki = pl.program_id(3)

    @pl.when(ki == 0)
    def _init():
        dq_ref[...] = jnp.zeros_like(dq_ref)

    def _compute():
        q = q_ref[...].astype(jnp.float32)
        do = do_ref[...].astype(jnp.float32)
        lse = lse_ref[...][0]
        delta = delta_ref[...][0]
        k = k_ref[...].astype(jnp.float32)
        v = v_ref[...].astype(jnp.float32)
        s = (q @ k.T) * jnp.float32(scale)
        if causal:
            q_pos = qi * jnp.int32(block_q) + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            k_pos = ki * jnp.int32(block_k) + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, jnp.float32(-1e30))
        p = jnp.exp(s - lse[:, None])
        dp = do @ v.T
        ds = p * (dp - delta[:, None])
        dq_ref[...] += (ds @ k) * jnp.float32(scale)

    if causal:
        pl.when(qi >= ki)(_compute)  # fully-future blocks contribute 0
    else:
        _compute()


def _flash_bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                          dk_ref, dv_ref, *, scale, causal, block_q,
                          block_k):
    from jax.experimental import pallas as pl
    ki = pl.program_id(2)
    qi = pl.program_id(3)

    @pl.when(qi == 0)
    def _init():
        dk_ref[...] = jnp.zeros_like(dk_ref)
        dv_ref[...] = jnp.zeros_like(dv_ref)

    def _compute():
        k = k_ref[...].astype(jnp.float32)
        v = v_ref[...].astype(jnp.float32)
        q = q_ref[...].astype(jnp.float32)
        do = do_ref[...].astype(jnp.float32)
        lse = lse_ref[...][0]
        delta = delta_ref[...][0]
        s = (q @ k.T) * jnp.float32(scale)
        if causal:
            q_pos = qi * jnp.int32(block_q) + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            k_pos = ki * jnp.int32(block_k) + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, jnp.float32(-1e30))
        p = jnp.exp(s - lse[:, None])
        dv_ref[...] += p.T @ do
        dp = do @ v.T
        ds = p * (dp - delta[:, None])
        dk_ref[...] += (ds.T @ q) * jnp.float32(scale)

    if causal:
        pl.when(qi >= ki)(_compute)
    else:
        _compute()


def _pallas_flash_bwd(q, k, v, out, lse, g, scale, causal):
    with jax.enable_x64(False):
        return _pallas_flash_bwd_32(q, k, v, out, lse, g, scale, causal)


def _pallas_flash_bwd_32(q, k, v, out, lse, g, scale, causal):
    from jax.experimental import pallas as pl
    b, h, s, d = q.shape
    block = min(128, s)
    n = s // block
    # delta = rowsum(dO * O): O(s d) precompute outside the kernels
    delta = jnp.sum(g.astype(jnp.float32) * out.astype(jnp.float32),
                    axis=-1)[:, :, None, :]  # [b, h, 1, s]

    def blk(which):  # index by grid dim 2 or 3
        return pl.BlockSpec(
            (None, None, block, d),
            (lambda bi, hi, i, j: (bi, hi, i, 0)) if which == 2
            else (lambda bi, hi, i, j: (bi, hi, j, 0)))

    def vec(which):
        return pl.BlockSpec(
            (None, None, 1, block),
            (lambda bi, hi, i, j: (bi, hi, 0, i)) if which == 2
            else (lambda bi, hi, i, j: (bi, hi, 0, j)))

    f32 = jnp.float32
    # dq: grid (b, h, nq, nk); dq block revisited across nk
    dq = pl.pallas_call(
        functools.partial(_flash_bwd_dq_kernel, scale=scale,
                          causal=causal, block_q=block, block_k=block),
        grid=(b, h, n, n),
        in_specs=[blk(2), blk(3), blk(3), blk(2), vec(2), vec(2)],
        out_specs=blk(2),
        out_shape=jax.ShapeDtypeStruct(q.shape, f32),
        interpret=_interpret(),
    )(q, k, v, g, lse, delta)

    # dk/dv: grid (b, h, nk, nq); dk/dv blocks revisited across nq
    dk, dv = pl.pallas_call(
        functools.partial(_flash_bwd_dkv_kernel, scale=scale,
                          causal=causal, block_q=block, block_k=block),
        grid=(b, h, n, n),
        in_specs=[blk(3), blk(2), blk(2), blk(3), vec(3), vec(3)],
        out_specs=[blk(2), blk(2)],
        out_shape=[jax.ShapeDtypeStruct(k.shape, f32),
                   jax.ShapeDtypeStruct(v.shape, f32)],
        interpret=_interpret(),
    )(q, k, v, g, lse, delta)
    return (dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype))


# ---- custom-vjp wrapper ----------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def _flash_attention_core(q, k, v, scale, causal):
    if _use_pallas(q):
        return _pallas_flash_fwd(q, k, v, scale, causal)[0]
    return _reference_attention(q, k, v, None, scale, causal)


def _flash_fwd(q, k, v, scale, causal):
    if _use_pallas(q):
        out, lse = _pallas_flash_fwd(q, k, v, scale, causal)
        return out, (q, k, v, out, lse)
    out = _reference_attention(q, k, v, None, scale, causal)
    return out, (q, k, v, None, None)


def _flash_bwd(scale, causal, res, g):
    q, k, v, out, lse = res
    if lse is not None and _use_pallas(q):
        return _pallas_flash_bwd(q, k, v, out, lse, g, scale, causal)
    # small-shape / CPU fallback: recompute through the reference
    # composition (XLA fuses it; memory is O(s^2), fine at these sizes)
    _, vjp = jax.vjp(lambda q_, k_, v_: _reference_attention(
        q_, k_, v_, None, scale, causal), q, k, v)
    return vjp(g)


_flash_attention_core.defvjp(_flash_fwd, _flash_bwd)


@register_op("flash_attention")
def _flash_op(q, k, v, mask, *, scale, causal):
    if mask is not None:
        return _reference_attention(q, k, v, mask, scale, causal)
    return _flash_attention_core(q, k, v, scale, causal)


def scaled_dot_product_attention(query, key, value, attn_mask=None,
                                 dropout_p=0.0, is_causal=False,
                                 training=True, scale=None, name=None):
    """Inputs [batch, heads, seq, head_dim] (or [b, s, h, d] paddle-style
    is accepted via transpose by callers). Dropout inside attention is not
    fused; applied to weights only in the fallback path when requested."""
    sc = scale if scale is not None else 1.0 / math.sqrt(query.shape[-1])
    return _flash_op(query, key, value, attn_mask, scale=float(sc),
                     causal=bool(is_causal))


def flash_attention(query, key, value, dropout=0.0, causal=False,
                    return_softmax=False, name=None):
    out = scaled_dot_product_attention(query, key, value, is_causal=causal)
    if return_softmax:
        return out, None
    return out
