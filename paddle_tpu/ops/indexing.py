"""Tensor __getitem__ / __setitem__.

Reference parity: pybind/imperative.cc VarBase __getitem__ slicing +
set_value op. Static (int/slice/None/Ellipsis) index components are jit
cache keys; Tensor index components are dynamic gather inputs.
"""
import numpy as np
import jax.numpy as jnp

from ..core.dispatch import register_op
from ..core.tensor import Tensor


def _split_index(index):
    """Returns (static_spec, dynamic_tensors). static_spec mirrors the index
    structure with placeholders where dynamic tensors go."""
    if not isinstance(index, tuple):
        index = (index,)
    spec = []
    dyn = []
    for it in index:
        if isinstance(it, Tensor):
            spec.append(("dyn", len(dyn)))
            dyn.append(it)
        elif isinstance(it, slice):
            spec.append(("slice", it.start, it.stop, it.step))
        elif it is None:
            spec.append(("none",))
        elif it is Ellipsis:
            spec.append(("ellipsis",))
        elif isinstance(it, (int, np.integer)):
            spec.append(("int", int(it)))
        elif isinstance(it, (list, np.ndarray)):
            arr = np.asarray(it)
            if arr.dtype == bool:
                spec.append(("dyn", len(dyn)))
                dyn.append(Tensor(jnp.asarray(arr)))
            else:
                spec.append(("dyn", len(dyn)))
                dyn.append(Tensor(jnp.asarray(arr)))
        else:
            raise TypeError(f"unsupported index component {it!r}")
    return tuple(spec), dyn


def _rebuild_index(spec, dyn_arrays):
    idx = []
    for s in spec:
        kind = s[0]
        if kind == "dyn":
            idx.append(dyn_arrays[s[1]])
        elif kind == "slice":
            idx.append(slice(s[1], s[2], s[3]))
        elif kind == "none":
            idx.append(None)
        elif kind == "ellipsis":
            idx.append(Ellipsis)
        elif kind == "int":
            idx.append(s[1])
    return tuple(idx)


@register_op("getitem")
def _getitem(x, *dyn, spec):
    idx = _rebuild_index(spec, dyn)
    return x[idx]


@register_op("setitem")
def _setitem(x, v, *dyn, spec):
    idx = _rebuild_index(spec, dyn)
    return x.at[idx].set(v.astype(x.dtype))


def getitem(x, index):
    # bool mask over whole tensor -> dynamic shape, eager only
    if isinstance(index, Tensor) and index.value.dtype == jnp.bool_:
        from . import manipulation
        return manipulation.masked_select(x, index)
    spec, dyn = _split_index(index)
    return _getitem(x, *dyn, spec=spec)


def setitem(x, index, value):
    if not isinstance(value, Tensor):
        value = Tensor(jnp.asarray(value, x.value.dtype))
    spec, dyn = _split_index(index)
    out = _setitem(x, value, *dyn, spec=spec)
    x.value = out.value if isinstance(out, Tensor) else out
    # __setitem__ is in-place: autograd through it is not tracked for the
    # overwritten slots (reference set_value op behaves the same for leaf).
    return x
