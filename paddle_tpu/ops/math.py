"""Elementwise & general math ops.

Reference parity: python/paddle/tensor/math.py and the reference C++
elementwise/activation op family (paddle/fluid/operators/elementwise/,
activation_op.cc). Each op is a pure jax function; broadcasting follows
numpy semantics like the reference's elementwise ops with axis=-1.
"""
import numpy as np
import jax
import jax.numpy as jnp

from ..core.dispatch import register_op
from ..core.tensor import Tensor
from ..core import dtype as dtype_mod
from ..core import trace as trace_mod


def _wrap_scalar(x, other):
    """Convert python scalar to the dtype of the other operand (paddle
    semantics: scalar adopts the tensor's dtype).

    The wrapped constant is ADOPTED by the innermost active trace
    (trace_mod.adopt): inside a lax sub-trace (while_cond / cond
    branches) jnp.asarray yields a sub-trace tracer, and an unregistered
    Tensor holding one would be mis-classified as a pre-existing capture
    — the dy2static while/cond tracer leak this fix closes (see
    paddle_tpu.analysis tracer-leak detector, which attributes exactly
    this escape shape)."""
    if isinstance(x, Tensor):
        return x
    from ..core import dispatch as _d
    if _d._static_variable_cls is not None \
            and isinstance(x, _d._static_variable_cls):
        return x  # static program Variable: the op layer records it
    if isinstance(other, Tensor):
        dt = other.value.dtype
    elif _d._static_variable_cls is not None \
            and isinstance(other, _d._static_variable_cls):
        dt = other._dtype
    else:
        dt = None
    arr = jnp.asarray(x, dtype=dt)
    return trace_mod.adopt(Tensor(arr))


def _binary(name, fn, differentiable=True):
    op = register_op(name, differentiable=differentiable)(fn)

    def api(x, y, name=None):
        x = _wrap_scalar(x, y)
        y = _wrap_scalar(y, x)
        return op(x, y)
    api.__name__ = name
    return api


def _unary(name, fn, differentiable=True):
    op = register_op(name, differentiable=differentiable)(fn)

    def api(x, name=None):
        return op(x)
    api.__name__ = name
    return api


add = _binary("elementwise_add", lambda x, y: jnp.add(x, y))
subtract = _binary("elementwise_sub", lambda x, y: jnp.subtract(x, y))
multiply = _binary("elementwise_mul", lambda x, y: jnp.multiply(x, y))
def _ref_divide(x, y):
    # reference DivFunctor is plain C a/b per dtype: INTEGER division
    # for int tensors (test_elementwise_div_op.py:203 expects X // Y),
    # true division for floats
    if jnp.issubdtype(jnp.result_type(x, y), jnp.integer):
        return _trunc_div(x, y)
    return jnp.divide(x, y)


divide = _binary("elementwise_div", _ref_divide)
def _trunc_div(x, y):
    # reference FloorDivFunctor is std::trunc(a/b) — toward-ZERO
    # division despite the name (elementwise_floordiv_op.h:42), not
    # python floor division. lax.div IS C trunc division for ints
    # (abs-based formulations overflow on INT_MIN).
    rt = jnp.result_type(x, y)
    if jnp.issubdtype(rt, jnp.integer):
        x, y = jnp.broadcast_arrays(jnp.asarray(x, rt),
                                    jnp.asarray(y, rt))
        return jax.lax.div(x, y)
    return jnp.trunc(jnp.divide(x, y))


floor_divide = _binary("elementwise_floordiv", _trunc_div,
                       differentiable=False)
remainder = _binary("elementwise_mod", lambda x, y: jnp.mod(x, y),
                    differentiable=False)
mod = remainder
floor_mod = remainder
maximum = _binary("elementwise_max", lambda x, y: jnp.maximum(x, y))
minimum = _binary("elementwise_min", lambda x, y: jnp.minimum(x, y))
fmax = _binary("elementwise_fmax", lambda x, y: jnp.fmax(x, y))
fmin = _binary("elementwise_fmin", lambda x, y: jnp.fmin(x, y))
pow_ = _binary("elementwise_pow", lambda x, y: jnp.power(x, y))
atan2 = _binary("atan2", lambda x, y: jnp.arctan2(x, y))
hypot = _binary("hypot", lambda x, y: jnp.hypot(x, y))
logaddexp = _binary("logaddexp", lambda x, y: jnp.logaddexp(x, y))
heaviside = _binary("heaviside", lambda x, y: jnp.heaviside(x, y),
                    differentiable=False)
inner = _binary("inner_product", lambda x, y: jnp.inner(x, y))
outer = _binary("outer", lambda x, y: jnp.outer(x, y))
kron = _binary("kron", lambda x, y: jnp.kron(x, y))


@register_op("pow_int")
def _pow_int(x, *, n):
    return jax.lax.integer_pow(x, n)


def pow(x, y, name=None):  # noqa: A001
    # static integer exponents lower to an exact multiply chain
    # (lax.integer_pow, matching the reference pow kernel's repeated
    # multiply); lax.pow is exp(y*log(x)) whose TPU transcendentals make
    # even x**2 inexact
    from ..core.dtype import to_jax_dtype
    from ..core.lazy import static_int_exponent
    n = static_int_exponent(
        to_jax_dtype(getattr(x, "dtype", "float32")), y)
    if n is not None:
        return _pow_int(x, n=n)
    return pow_(x, y)


def divide_no_nan(x, y):
    return _divide_no_nan(x, y)


_divide_no_nan = register_op("divide_no_nan")(
    lambda x, y: jnp.where(y == 0, jnp.zeros_like(x), x / jnp.where(y == 0, jnp.ones_like(y), y)))


abs = _unary("abs", lambda x: jnp.abs(x))  # noqa: A001
neg = _unary("neg", lambda x: jnp.negative(x))
negative = neg
exp = _unary("exp", lambda x: jnp.exp(x))
expm1 = _unary("expm1", lambda x: jnp.expm1(x))
log = _unary("log", lambda x: jnp.log(x))
log2 = _unary("log2", lambda x: jnp.log2(x))
log10 = _unary("log10", lambda x: jnp.log10(x))
log1p = _unary("log1p", lambda x: jnp.log1p(x))
sqrt = _unary("sqrt", lambda x: jnp.sqrt(x))
rsqrt = _unary("rsqrt", lambda x: jax.lax.rsqrt(x))
square = _unary("square", lambda x: jnp.square(x))
sin = _unary("sin", lambda x: jnp.sin(x))
cos = _unary("cos", lambda x: jnp.cos(x))
tan = _unary("tan", lambda x: jnp.tan(x))
asin = _unary("asin", lambda x: jnp.arcsin(x))
acos = _unary("acos", lambda x: jnp.arccos(x))
atan = _unary("atan", lambda x: jnp.arctan(x))
sinh = _unary("sinh", lambda x: jnp.sinh(x))
cosh = _unary("cosh", lambda x: jnp.cosh(x))
tanh = _unary("tanh", lambda x: jnp.tanh(x))
asinh = _unary("asinh", lambda x: jnp.arcsinh(x))
acosh = _unary("acosh", lambda x: jnp.arccosh(x))
atanh = _unary("atanh", lambda x: jnp.arctanh(x))
floor = _unary("floor", lambda x: jnp.floor(x), differentiable=False)
ceil = _unary("ceil", lambda x: jnp.ceil(x), differentiable=False)
def _round_half_away(x):
    # Eigen x.round() = std::round = half AWAY from zero; jnp.round is
    # banker's half-to-even (2.5 -> 2). Only exact halves may differ,
    # so override just those (floor(|x|+0.5) would corrupt values near
    # the .5 boundary and large exact integers via fp addition).
    frac = x - jnp.trunc(x)
    return jnp.where(jnp.abs(frac) == 0.5,
                     jnp.trunc(x) + jnp.sign(x), jnp.round(x))


round = _unary("round", _round_half_away, differentiable=False)  # noqa: A001
trunc = _unary("trunc", lambda x: jnp.trunc(x), differentiable=False)
frac = _unary("frac", lambda x: x - jnp.trunc(x))
sign = _unary("sign", lambda x: jnp.sign(x), differentiable=False)
reciprocal = _unary("reciprocal", lambda x: jnp.reciprocal(x))
erf = _unary("erf", lambda x: jax.scipy.special.erf(x))
erfinv = _unary("erfinv", lambda x: jax.scipy.special.erfinv(x))
lgamma = _unary("lgamma", lambda x: jax.scipy.special.gammaln(x))
digamma = _unary("digamma", lambda x: jax.scipy.special.digamma(x))
sigmoid = _unary("sigmoid", lambda x: jax.nn.sigmoid(x))
i0 = _unary("i0", lambda x: jax.scipy.special.i0(x))
angle = _unary("angle", lambda x: jnp.angle(x))
conj = _unary("conj", lambda x: jnp.conjugate(x))
real = _unary("real", lambda x: jnp.real(x))
imag = _unary("imag", lambda x: jnp.imag(x))
deg2rad = _unary("deg2rad", lambda x: jnp.deg2rad(x))
rad2deg = _unary("rad2deg", lambda x: jnp.rad2deg(x))
logit = _unary("logit", lambda x: jnp.log(x / (1 - x)))
nan_to_num = _unary("nan_to_num", lambda x: jnp.nan_to_num(x))

isnan = _unary("isnan", lambda x: jnp.isnan(x), differentiable=False)
isinf = _unary("isinf", lambda x: jnp.isinf(x), differentiable=False)
isfinite = _unary("isfinite", lambda x: jnp.isfinite(x), differentiable=False)


@register_op("clone")
def _clone(x):
    return x + jnp.zeros((), x.dtype)


def clone(x, name=None):
    return _clone(x)


@register_op("cast")
def _cast(x, *, dtype):
    return x.astype(dtype)


def cast(x, dtype):
    return _cast(x, dtype=dtype_mod.to_jax_dtype(dtype))


@register_op("scale")
def _scale(x, *, scale, bias, bias_after_scale):
    s = jnp.asarray(scale, x.dtype)
    b = jnp.asarray(bias, x.dtype)
    if bias_after_scale:
        return x * s + b
    return (x + b) * s


def scale(x, scale=1.0, bias=0.0, bias_after_scale=True, act=None, name=None):
    """Reference: paddle.scale (operators/scale_op.cc)."""
    if isinstance(scale, Tensor):
        scale = float(scale.item())
    out = _scale(x, scale=float(scale), bias=float(bias),
                 bias_after_scale=bool(bias_after_scale))
    if act is not None:
        from . import nn_ops
        out = getattr(nn_ops, act)(out)
    return out


@register_op("clip")
def _clip(x, mn, mx):
    return jnp.clip(x, mn, mx)


def clip(x, min=None, max=None, name=None):  # noqa: A002
    mn = min.value if isinstance(min, Tensor) else (min if min is not None else -np.inf)
    mx = max.value if isinstance(max, Tensor) else (max if max is not None else np.inf)
    mn = jnp.asarray(mn, x.value.dtype)
    mx = jnp.asarray(mx, x.value.dtype)
    return _clip(x, trace_mod.adopt(Tensor(mn)),
                 trace_mod.adopt(Tensor(mx)))


@register_op("lerp")
def _lerp(x, y, w):
    return x + w * (y - x)


def lerp(x, y, weight, name=None):
    if not isinstance(weight, Tensor):
        weight = trace_mod.adopt(Tensor(jnp.asarray(weight, x.value.dtype)))
    return _lerp(x, y, weight)


@register_op("matmul_v2")
def _matmul(x, y, *, transpose_x, transpose_y):
    if transpose_x:
        x = jnp.swapaxes(x, -1, -2) if x.ndim > 1 else x
    if transpose_y:
        y = jnp.swapaxes(y, -1, -2) if y.ndim > 1 else y
    return jnp.matmul(x, y)


def matmul(x, y, transpose_x=False, transpose_y=False, name=None):
    return _matmul(x, y, transpose_x=bool(transpose_x),
                   transpose_y=bool(transpose_y))


mm = matmul


@register_op("bmm")
def _bmm(x, y):
    return jnp.matmul(x, y)


def bmm(x, y, name=None):
    return _bmm(x, y)


@register_op("dot")
def _dot(x, y):
    return jnp.sum(x * y, axis=-1)


def dot(x, y, name=None):
    return _dot(x, y)


@register_op("addmm")
def _addmm(inp, x, y, *, beta, alpha):
    return beta * inp + alpha * jnp.matmul(x, y)


def addmm(input, x, y, beta=1.0, alpha=1.0, name=None):  # noqa: A002
    return _addmm(input, x, y, beta=float(beta), alpha=float(alpha))


@register_op("mv")
def _mv(x, vec):
    return jnp.matmul(x, vec)


def mv(x, vec, name=None):
    return _mv(x, vec)


@register_op("cumsum")
def _cumsum(x, *, axis):
    if axis is None:
        return jnp.cumsum(x.reshape(-1))
    return jnp.cumsum(x, axis=axis)


def cumsum(x, axis=None, dtype=None, name=None):
    out = _cumsum(x, axis=axis if axis is None else int(axis))
    if dtype is not None:
        out = cast(out, dtype)
    return out


@register_op("cumprod")
def _cumprod(x, *, dim):
    return jnp.cumprod(x, axis=dim)


def cumprod(x, dim=None, dtype=None, name=None):
    out = _cumprod(x, dim=int(dim))
    if dtype is not None:
        out = cast(out, dtype)
    return out


@register_op("cummax", differentiable=False)
def _cummax(x, *, axis):
    return jax.lax.cummax(x, axis=axis)


def cummax(x, axis=-1):
    return _cummax(x, axis=int(axis))


@register_op("stanh")
def _stanh(x, *, scale_a, scale_b):
    return scale_b * jnp.tanh(scale_a * x)


def stanh(x, scale_a=0.67, scale_b=1.7159, name=None):
    return _stanh(x, scale_a=float(scale_a), scale_b=float(scale_b))


def increment(x, value=1.0, name=None):
    """In-place increment (reference: operators/increment_op)."""
    x.value = x.value + jnp.asarray(value, x.value.dtype)
    return x


@register_op("einsum")
def _einsum(*arrays, equation):
    return jnp.einsum(equation, *arrays)


def einsum(equation, *operands):
    return _einsum(*operands, equation=equation)


@register_op("trace_op")
def _trace(x, *, offset, axis1, axis2):
    return jnp.trace(x, offset=offset, axis1=axis1, axis2=axis2)


def trace(x, offset=0, axis1=0, axis2=1, name=None):
    return _trace(x, offset=int(offset), axis1=int(axis1), axis2=int(axis2))


@register_op("diff")
def _diff(x, *, n, axis):
    return jnp.diff(x, n=n, axis=axis)


def diff(x, n=1, axis=-1, name=None):
    return _diff(x, n=int(n), axis=int(axis))


def rsqrt_(x):
    x.value = jax.lax.rsqrt(x.value)
    return x


@register_op("sum_op_n")
def _add_n(*xs):
    out = xs[0]
    for x in xs[1:]:
        out = out + x
    return out


def add_n(inputs, name=None):
    """Sum a list of tensors (reference: paddle/fluid/operators/sum_op.cc)."""
    if isinstance(inputs, Tensor):
        return inputs
    return _add_n(*inputs)


@register_op("cross")
def _cross(x, y, *, axis):
    return jnp.cross(x, y, axis=axis)


def cross(x, y, axis=None, name=None):
    """Reference: paddle/fluid/operators/cross_op.cc (default: first axis
    with dim 3)."""
    if axis is None:
        axis = next((i for i, s in enumerate(x.shape) if s == 3), None)
        if axis is None:
            raise ValueError(
                f"cross: no dimension of size 3 in input shape {x.shape}")
    return _cross(x, y, axis=int(axis))


@register_op("histogram", differentiable=False)
def _histogram(x, *, bins, min, max):
    lo, hi = float(min), float(max)
    if lo == 0.0 and hi == 0.0:
        lo, hi = jnp.min(x).astype(jnp.float32), jnp.max(x).astype(jnp.float32)
        hi = jnp.where(hi > lo, hi, lo + 1.0)
    h, _ = jnp.histogram(x.astype(jnp.float32).reshape(-1), bins=bins,
                         range=(lo, hi))
    return h.astype(jnp.int64)


def histogram(input, bins=100, min=0, max=0, name=None):  # noqa: A002
    return _histogram(input, bins=int(bins), min=min, max=max)


def tanh_(x, name=None):
    x.value = jnp.tanh(x.value)
    return x


# ---- round-2 op additions (reference: python/paddle/tensor/math.py) -------

@register_op("renorm")
def _renorm(x, *, p, axis, max_norm):
    moved = jnp.moveaxis(x, axis, 0)
    flat = moved.reshape(moved.shape[0], -1)
    norms = jnp.sum(jnp.abs(flat) ** p, axis=1) ** (1.0 / p)
    factor = jnp.where(norms > max_norm,
                       max_norm / jnp.maximum(norms, 1e-12),
                       jnp.ones_like(norms))
    shaped = factor.reshape((-1,) + (1,) * (moved.ndim - 1))
    return jnp.moveaxis(moved * shaped, 0, axis)


def renorm(x, p, axis, max_norm, name=None):
    """Reference: operators/renorm_op — clamp each slice along `axis` to
    p-norm <= max_norm."""
    return _renorm(x, p=float(p), axis=int(axis), max_norm=float(max_norm))


@register_op("vander", differentiable=False)
def _vander(x, *, n, increasing):
    return jnp.vander(x, N=n, increasing=increasing)


def vander(x, n=None, increasing=False, name=None):
    return _vander(x, n=None if n is None else int(n),
                   increasing=bool(increasing))


@register_op("logcumsumexp")
def _logcumsumexp(x, *, axis):
    if axis is None:
        return jax.lax.associative_scan(jnp.logaddexp, x.reshape(-1))
    return jax.lax.associative_scan(jnp.logaddexp, x, axis=axis)


def logcumsumexp(x, axis=None, dtype=None, name=None):
    out = _logcumsumexp(x, axis=None if axis is None else int(axis))
    if dtype is not None:
        return cast(out, dtype)
    return out


@register_op("trapezoid_op")
def _trapezoid(y, x, *, dx, axis):
    if x is not None:
        return jnp.trapezoid(y, x=x, axis=axis)
    return jnp.trapezoid(y, dx=1.0 if dx is None else dx, axis=axis)


def trapezoid(y, x=None, dx=None, axis=-1, name=None):
    return _trapezoid(y, x, dx=dx, axis=int(axis))


@register_op("cumulative_trapezoid_op")
def _cumulative_trapezoid(y, x, *, dx, axis):
    y1 = jnp.moveaxis(y, axis, -1)
    if x is not None:
        xm = jnp.moveaxis(x, axis, -1) if x.ndim == y.ndim else x
        d = jnp.diff(xm, axis=-1)
    else:
        d = jnp.full((y1.shape[-1] - 1,), 1.0 if dx is None else dx,
                     y1.dtype)
    seg = (y1[..., 1:] + y1[..., :-1]) * 0.5 * d
    return jnp.moveaxis(jnp.cumsum(seg, axis=-1), -1, axis)


def cumulative_trapezoid(y, x=None, dx=None, axis=-1, name=None):
    return _cumulative_trapezoid(y, x, dx=dx, axis=int(axis))


@register_op("polygamma_op", differentiable=False)
def _polygamma(x, *, n):
    return jax.scipy.special.polygamma(n, x)


def polygamma(x, n, name=None):
    return _polygamma(x, n=int(n))


@register_op("igamma_op", differentiable=False)
def _igamma(x, a):
    return jax.scipy.special.gammainc(a, x)


def igamma(x, a, name=None):
    """Reference: paddle.igamma (regularized lower incomplete gamma)."""
    return _igamma(x, a)
