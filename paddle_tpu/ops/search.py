"""Search / sort ops (reference: python/paddle/tensor/search.py)."""
import jax
import jax.numpy as jnp

from ..core.dispatch import register_op
from ..core.tensor import Tensor


@register_op("arg_max", differentiable=False)
def _argmax(x, *, axis, keepdim, flatten):
    if flatten:
        return jnp.argmax(x.reshape(-1))
    out = jnp.argmax(x, axis=axis)
    if keepdim:
        out = jnp.expand_dims(out, axis)
    return out


def argmax(x, axis=None, keepdim=False, dtype="int64", name=None):
    out = _argmax(x, axis=axis if axis is None else int(axis),
                  keepdim=bool(keepdim), flatten=axis is None)
    from .math import cast
    return out if dtype in ("int64", None) else cast(out, dtype)


@register_op("arg_min", differentiable=False)
def _argmin(x, *, axis, keepdim, flatten):
    if flatten:
        return jnp.argmin(x.reshape(-1))
    out = jnp.argmin(x, axis=axis)
    if keepdim:
        out = jnp.expand_dims(out, axis)
    return out


def argmin(x, axis=None, keepdim=False, dtype="int64", name=None):
    out = _argmin(x, axis=axis if axis is None else int(axis),
                  keepdim=bool(keepdim), flatten=axis is None)
    from .math import cast
    return out if dtype in ("int64", None) else cast(out, dtype)


@register_op("top_k_v2")
def _topk(x, *, k, axis, largest, sorted_):
    if not largest:
        neg_vals, idx = jax.lax.top_k(jnp.moveaxis(-x, axis, -1), k)
        vals = -neg_vals
    else:
        vals, idx = jax.lax.top_k(jnp.moveaxis(x, axis, -1), k)
    # reference top_k_v2 emits int64 indices
    return (jnp.moveaxis(vals, -1, axis),
            jnp.moveaxis(idx, -1, axis).astype(jnp.int64))


def topk(x, k, axis=-1, largest=True, sorted=True, name=None):  # noqa: A002
    if isinstance(k, Tensor):
        k = int(k.item())
    vals, idx = _topk(x, k=int(k), axis=int(axis), largest=bool(largest),
                      sorted_=bool(sorted))
    return vals, idx


@register_op("argsort", differentiable=False)
def _argsort(x, *, axis, descending):
    idx = jnp.argsort(x, axis=axis, descending=descending)
    return idx


def argsort(x, axis=-1, descending=False, name=None):
    return _argsort(x, axis=int(axis), descending=bool(descending))


@register_op("sort")
def _sort(x, *, axis, descending):
    out = jnp.sort(x, axis=axis, descending=descending)
    return out


def sort(x, axis=-1, descending=False, name=None):
    return _sort(x, axis=int(axis), descending=bool(descending))


def nonzero(x, as_tuple=False):
    """Data-dependent output shape: eager-only (sync point), like the
    reference's dynamic-shape where_index op."""
    import jax.core as jcore
    if isinstance(x.value, jcore.Tracer):
        raise RuntimeError("nonzero has data-dependent shape; not usable in jit")
    idx = jnp.nonzero(x.value)
    if as_tuple:
        return tuple(Tensor(i) for i in idx)
    return Tensor(jnp.stack(idx, axis=1))


@register_op("searchsorted", differentiable=False)
def _searchsorted(sorted_seq, values, *, right):
    return jnp.searchsorted(sorted_seq, values, side="right" if right else "left")


def searchsorted(sorted_sequence, values, out_int32=False, right=False):
    out = _searchsorted(sorted_sequence, values, right=bool(right))
    if out_int32:
        from .math import cast
        return cast(out, "int32")
    return out


def unique(x, return_index=False, return_inverse=False, return_counts=False,
           axis=None, dtype="int64", name=None):
    """Eager-only (dynamic output shape), like reference unique op."""
    import jax.core as jcore
    if isinstance(x.value, jcore.Tracer):
        raise RuntimeError("unique has data-dependent shape; not usable in jit")
    res = jnp.unique(x.value, return_index=return_index,
                     return_inverse=return_inverse,
                     return_counts=return_counts, axis=axis)
    if not (return_index or return_inverse or return_counts):
        return Tensor(res)
    return tuple(Tensor(r) for r in res)


@register_op("kthvalue")
def _kthvalue(x, *, k, axis, keepdim):
    vals = jnp.sort(x, axis=axis)
    idxs = jnp.argsort(x, axis=axis)
    take = jax.lax.index_in_dim(vals, k - 1, axis, keepdims=keepdim)
    take_i = jax.lax.index_in_dim(idxs, k - 1, axis, keepdims=keepdim)
    return take, take_i.astype(jnp.int64)  # reference: int64 indices


def kthvalue(x, k, axis=-1, keepdim=False, name=None):
    return _kthvalue(x, k=int(k), axis=int(axis), keepdim=bool(keepdim))


@register_op("mode")
def _mode(x, *, axis, keepdim):
    sorted_x = jnp.sort(x, axis=axis)
    # mode = most frequent; approximate via median of sorted for floats is
    # wrong, so do a proper count along axis using broadcasting
    def mode_1d(v):
        vals, counts = jnp.unique_counts(v, size=v.shape[0])
        return vals[jnp.argmax(counts)]
    moved = jnp.moveaxis(sorted_x, axis, -1)
    flat = moved.reshape(-1, moved.shape[-1])
    modes = jax.vmap(mode_1d)(flat)
    out = modes.reshape(moved.shape[:-1])
    if keepdim:
        out = jnp.expand_dims(out, axis)
    return out


def mode(x, axis=-1, keepdim=False, name=None):
    vals = _mode(x, axis=int(axis), keepdim=bool(keepdim))
    return vals


def masked_select(x, mask, name=None):
    from . import manipulation
    return manipulation.masked_select(x, mask)


def index_sample(x, index):
    from . import manipulation
    return manipulation.index_sample(x, index)


def where(condition, x=None, y=None, name=None):
    from . import manipulation
    return manipulation.where(condition, x, y, name)


@register_op("bincount_op", differentiable=False)
def _bincount(x, weights, *, length):
    return jnp.bincount(x, weights=weights, length=length)


def bincount(x, weights=None, minlength=0, name=None):
    """Reference: operators/bincount_op. The output length is
    data-dependent, so it is resolved eagerly (max(x)+1) and baked as a
    static shape for the XLA kernel."""
    from ..core.tensor import Tensor
    xv = x.value if isinstance(x, Tensor) else jnp.asarray(x)
    n = int(jnp.max(xv)) + 1 if xv.size else 0
    length = max(n, int(minlength))
    return _bincount(x, weights, length=length)


def bucketize(x, sorted_sequence, out_int32=False, right=False, name=None):
    """Reference: paddle.bucketize — index of the bucket each element
    falls into (thin wrapper over searchsorted)."""
    return searchsorted(sorted_sequence, x, out_int32=out_int32,
                        right=right)
