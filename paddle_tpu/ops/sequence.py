"""Sequence (LoD-equivalent) ops.

Reference parity: paddle/fluid/operators/sequence_ops/ (sequence_pad,
sequence_unpad, sequence_pool, sequence_expand, sequence_softmax,
sequence_mask over LoDTensor ragged offsets).

TPU-native design (SURVEY §7 hard-part 3): XLA needs static shapes, so
ragged sequences are carried as (padded_tensor, lengths) pairs — every op
here is a masked dense computation; no LoD offsets exist. sequence_pad
turns a python list of variable-length arrays into that representation at
the host boundary (the only place raggedness can exist).
"""
import numpy as np
import jax.numpy as jnp

from ..core.dispatch import register_op
from ..core.tensor import Tensor


def sequence_pad(x, pad_value=0.0, maxlen=None, dtype="float32"):
    """Host boundary: list of [len_i, ...] arrays -> (padded [N, L, ...],
    lengths [N]) (reference: sequence_pad_op)."""
    arrs = [np.asarray(a.numpy() if isinstance(a, Tensor) else a)
            for a in x]
    lens = np.asarray([len(a) for a in arrs], "int64")
    L = int(maxlen) if maxlen is not None else int(lens.max())
    # truncating pad: returned lengths must match the clipped data, or
    # masked ops downstream index past the pad (reference checks
    # padded_length >= max seq len)
    lens = np.minimum(lens, L)
    tail = arrs[0].shape[1:]
    out = np.full((len(arrs), L) + tail, pad_value,
                  arrs[0].dtype if arrs[0].dtype != np.int64 else "int64")
    for i, a in enumerate(arrs):
        out[i, :min(len(a), L)] = a[:L]
    return Tensor(out), Tensor(lens)


def sequence_unpad(x, length):
    """Padded [N,L,...] + lengths -> list of [len_i, ...] arrays
    (reference: sequence_unpad_op). Host boundary op."""
    arr = x.numpy() if isinstance(x, Tensor) else np.asarray(x)
    lens = np.asarray(length.numpy() if isinstance(length, Tensor)
                      else length).astype("int64")
    return [Tensor(arr[i, :lens[i]].copy()) for i in range(len(lens))]


def _mask(lengths, L):
    return (jnp.arange(L)[None, :] < lengths[:, None])


@register_op("sequence_pool")
def _sequence_pool(x, lengths, *, pool_type):
    """Masked pooling over the time axis (reference: sequence_pool_op
    SUM/AVERAGE/MAX/SQRT/LAST/FIRST)."""
    n, L = x.shape[0], x.shape[1]
    m = _mask(lengths, L)
    shape = (n, L) + (1,) * (x.ndim - 2)
    mf = m.reshape(shape).astype(x.dtype)
    pt = pool_type.upper()
    if pt == "SUM":
        return (x * mf).sum(axis=1)
    if pt == "AVERAGE":
        return (x * mf).sum(axis=1) / jnp.maximum(
            lengths.reshape((n,) + (1,) * (x.ndim - 2)).astype(x.dtype), 1)
    if pt == "SQRT":
        return (x * mf).sum(axis=1) / jnp.sqrt(jnp.maximum(
            lengths.reshape((n,) + (1,) * (x.ndim - 2)).astype(x.dtype), 1))
    if pt == "MAX":
        neg = jnp.where(m.reshape(shape), x,
                        jnp.asarray(-jnp.inf, x.dtype))
        return neg.max(axis=1)
    if pt == "LAST":
        idx = jnp.maximum(lengths - 1, 0).astype(jnp.int32)
        return jnp.take_along_axis(
            x, idx.reshape((n, 1) + (1,) * (x.ndim - 2)), axis=1)[:, 0]
    if pt == "FIRST":
        return x[:, 0]
    raise ValueError(pool_type)


def sequence_pool(x, lengths, pool_type="SUM"):
    return _sequence_pool(x, lengths, pool_type=pool_type)


@register_op("sequence_softmax")
def _sequence_softmax(x, lengths):
    """Masked softmax over time (reference: sequence_softmax_op)."""
    L = x.shape[1]
    m = _mask(lengths, L)
    while m.ndim < x.ndim:
        m = m[..., None]
    z = jnp.where(m, x, -jnp.inf)
    z = z - jnp.max(z, axis=1, keepdims=True)
    e = jnp.exp(z) * m.astype(x.dtype)
    return e / jnp.maximum(e.sum(axis=1, keepdims=True), 1e-9)


def sequence_softmax(x, lengths):
    return _sequence_softmax(x, lengths)


def sequence_expand(x, y_lengths):
    """Repeat each row i of x y_lengths[i] times (reference:
    sequence_expand_op with ref_level LoD). Host-computed repeat counts
    keep the output shape static for XLA."""
    reps = np.asarray(y_lengths.numpy() if isinstance(y_lengths, Tensor)
                      else y_lengths).astype("int32")
    arr = x.value if isinstance(x, Tensor) else jnp.asarray(x)
    out = jnp.repeat(arr, jnp.asarray(reps), axis=0,
                     total_repeat_length=int(reps.sum()))
    return Tensor(out)


def sequence_reverse(x, lengths):
    """Reverse each sequence within its valid prefix (reference:
    sequence_reverse_op)."""
    arr = x.value if isinstance(x, Tensor) else jnp.asarray(x)
    lens = lengths.value if isinstance(lengths, Tensor) else \
        jnp.asarray(lengths)
    L = arr.shape[1]
    pos = jnp.arange(L)[None, :]
    src = jnp.where(pos < lens[:, None], lens[:, None] - 1 - pos, pos)
    out = jnp.take_along_axis(
        arr, src.reshape(src.shape + (1,) * (arr.ndim - 2)).astype(jnp.int32),
        axis=1)
    return Tensor(out)
