"""Sequence (LoD-equivalent) ops.

Reference parity: paddle/fluid/operators/sequence_ops/ (sequence_pad,
sequence_unpad, sequence_pool, sequence_expand, sequence_softmax,
sequence_mask over LoDTensor ragged offsets).

TPU-native design (SURVEY §7 hard-part 3): XLA needs static shapes, so
ragged sequences are carried as (padded_tensor, lengths) pairs — every op
here is a masked dense computation; no LoD offsets exist. sequence_pad
turns a python list of variable-length arrays into that representation at
the host boundary (the only place raggedness can exist).
"""
import numpy as np
import jax
import jax.numpy as jnp

from ..core.dispatch import register_op
from ..core.tensor import Tensor


def sequence_pad(x, pad_value=0.0, maxlen=None, dtype="float32"):
    """Host boundary: list of [len_i, ...] arrays -> (padded [N, L, ...],
    lengths [N]) (reference: sequence_pad_op)."""
    arrs = [np.asarray(a.numpy() if isinstance(a, Tensor) else a)
            for a in x]
    lens = np.asarray([len(a) for a in arrs], "int64")
    L = int(maxlen) if maxlen is not None else int(lens.max())
    # truncating pad: returned lengths must match the clipped data, or
    # masked ops downstream index past the pad (reference checks
    # padded_length >= max seq len)
    lens = np.minimum(lens, L)
    tail = arrs[0].shape[1:]
    out = np.full((len(arrs), L) + tail, pad_value,
                  arrs[0].dtype if arrs[0].dtype != np.int64 else "int64")
    for i, a in enumerate(arrs):
        out[i, :min(len(a), L)] = a[:L]
    return Tensor(out), Tensor(lens)


def sequence_unpad(x, length):
    """Padded [N,L,...] + lengths -> list of [len_i, ...] arrays
    (reference: sequence_unpad_op). Host boundary op."""
    arr = x.numpy() if isinstance(x, Tensor) else np.asarray(x)
    lens = np.asarray(length.numpy() if isinstance(length, Tensor)
                      else length).astype("int64")
    return [Tensor(arr[i, :lens[i]].copy()) for i in range(len(lens))]


def _mask(lengths, L):
    return (jnp.arange(L)[None, :] < lengths[:, None])


@register_op("sequence_pool")
def _sequence_pool(x, lengths, *, pool_type):
    """Masked pooling over the time axis (reference: sequence_pool_op
    SUM/AVERAGE/MAX/SQRT/LAST/FIRST)."""
    n, L = x.shape[0], x.shape[1]
    m = _mask(lengths, L)
    shape = (n, L) + (1,) * (x.ndim - 2)
    mf = m.reshape(shape).astype(x.dtype)
    pt = pool_type.upper()
    if pt == "SUM":
        return (x * mf).sum(axis=1)
    if pt == "AVERAGE":
        return (x * mf).sum(axis=1) / jnp.maximum(
            lengths.reshape((n,) + (1,) * (x.ndim - 2)).astype(x.dtype), 1)
    if pt == "SQRT":
        return (x * mf).sum(axis=1) / jnp.sqrt(jnp.maximum(
            lengths.reshape((n,) + (1,) * (x.ndim - 2)).astype(x.dtype), 1))
    if pt == "MAX":
        neg = jnp.where(m.reshape(shape), x,
                        jnp.asarray(-jnp.inf, x.dtype))
        return neg.max(axis=1)
    if pt == "LAST":
        idx = jnp.maximum(lengths - 1, 0).astype(jnp.int32)
        return jnp.take_along_axis(
            x, idx.reshape((n, 1) + (1,) * (x.ndim - 2)), axis=1)[:, 0]
    if pt == "FIRST":
        return x[:, 0]
    raise ValueError(pool_type)


def sequence_pool(x, lengths, pool_type="SUM"):
    return _sequence_pool(x, lengths, pool_type=pool_type)


@register_op("sequence_softmax")
def _sequence_softmax(x, lengths):
    """Masked softmax over time (reference: sequence_softmax_op)."""
    L = x.shape[1]
    m = _mask(lengths, L)
    while m.ndim < x.ndim:
        m = m[..., None]
    z = jnp.where(m, x, -jnp.inf)
    z = z - jnp.max(z, axis=1, keepdims=True)
    e = jnp.exp(z) * m.astype(x.dtype)
    return e / jnp.maximum(e.sum(axis=1, keepdims=True), 1e-9)


def sequence_softmax(x, lengths):
    return _sequence_softmax(x, lengths)


def sequence_expand(x, y_lengths):
    """Repeat each row i of x y_lengths[i] times (reference:
    sequence_expand_op with ref_level LoD). Host-computed repeat counts
    keep the output shape static for XLA."""
    reps = np.asarray(y_lengths.numpy() if isinstance(y_lengths, Tensor)
                      else y_lengths).astype("int32")
    arr = x.value if isinstance(x, Tensor) else jnp.asarray(x)
    out = jnp.repeat(arr, jnp.asarray(reps), axis=0,
                     total_repeat_length=int(reps.sum()))
    return Tensor(out)


def sequence_reverse(x, lengths):
    """Reverse each sequence within its valid prefix (reference:
    sequence_reverse_op)."""
    arr = x.value if isinstance(x, Tensor) else jnp.asarray(x)
    lens = lengths.value if isinstance(lengths, Tensor) else \
        jnp.asarray(lengths)
    L = arr.shape[1]
    pos = jnp.arange(L)[None, :]
    src = jnp.where(pos < lens[:, None], lens[:, None] - 1 - pos, pos)
    out = jnp.take_along_axis(
        arr, src.reshape(src.shape + (1,) * (arr.ndim - 2)).astype(jnp.int32),
        axis=1)
    return Tensor(out)


# ---- linear-chain CRF (reference: linear_chain_crf_op.h forward
# algorithm; crf_decoding_op.h viterbi) -------------------------------------

@register_op("linear_chain_crf")
def _linear_chain_crf(emission, transition, label, lengths):
    """Negative log-likelihood of label paths under a linear-chain CRF.

    emission: [B, T, C] unary scores; transition: [C+2, C] with row 0 =
    start weights, row 1 = end weights, rows 2.. = pairwise transitions
    (the reference layout, linear_chain_crf_op.h:66); label: [B, T]
    int; lengths: [B] valid steps. Log-domain forward algorithm as a
    lax.scan over time (TPU-friendly: no data-dependent shapes).
    Returns per-sequence nll [B, 1].
    """
    start_w = transition[0]          # [C]
    end_w = transition[1]            # [C]
    trans = transition[2:]           # [C, C] from->to
    b, t_max, c = emission.shape
    steps = jnp.arange(t_max)
    valid = steps[None, :] < lengths[:, None]        # [B, T]

    # ---- log partition: alpha recursion -----------------------------
    alpha0 = start_w[None, :] + emission[:, 0]       # [B, C]

    def fwd(alpha, t):
        nxt = jax.scipy.special.logsumexp(
            alpha[:, :, None] + trans[None], axis=1) + emission[:, t]
        keep = valid[:, t][:, None]
        return jnp.where(keep, nxt, alpha), None

    alpha, _ = jax.lax.scan(fwd, alpha0, jnp.arange(1, t_max))
    log_z = jax.scipy.special.logsumexp(alpha + end_w[None], axis=-1)

    # ---- gold path score --------------------------------------------
    unary = jnp.take_along_axis(emission, label[..., None],
                                axis=-1)[..., 0]     # [B, T]
    unary = jnp.where(valid, unary, 0.0).sum(-1)
    pair = trans[label[:, :-1], label[:, 1:]]        # [B, T-1]
    pair = jnp.where(valid[:, 1:], pair, 0.0).sum(-1)
    last_idx = jnp.clip(lengths - 1, 0, t_max - 1)
    last_lab = jnp.take_along_axis(label, last_idx[:, None], 1)[:, 0]
    score = (unary + pair + start_w[label[:, 0]] + end_w[last_lab])
    return (log_z - score)[:, None]


@register_op("crf_decoding", differentiable=False)
def _crf_decoding(emission, transition, lengths):
    """Viterbi decode (reference: crf_decoding_op.h): returns the argmax
    label path [B, T] (entries past each length are 0)."""
    start_w = transition[0]
    end_w = transition[1]
    trans = transition[2:]
    b, t_max, c = emission.shape
    steps = jnp.arange(t_max)
    valid = steps[None, :] < lengths[:, None]

    delta0 = start_w[None, :] + emission[:, 0]

    def fwd(delta, t):
        cand = delta[:, :, None] + trans[None]       # [B, from, to]
        best = cand.max(axis=1) + emission[:, t]
        arg = cand.argmax(axis=1)                    # [B, C]
        keep = valid[:, t][:, None]
        return jnp.where(keep, best, delta), \
            jnp.where(keep, arg, jnp.arange(c)[None, :])

    delta, back = jax.lax.scan(fwd, delta0, jnp.arange(1, t_max))
    # back: [T-1, B, C] backpointers for steps 1..T-1
    last = jnp.argmax(delta + end_w[None], axis=-1)  # [B]

    def bwd(lab, bp_t):
        # bp_t = backpointers INTO step t (xs index i <-> step i+1):
        # ys[i] = label at step i+1; carry walks to label at step i
        return bp_t[jnp.arange(b), lab], lab

    lab0, path_tail = jax.lax.scan(bwd, last, back, reverse=True)
    path = jnp.concatenate([lab0[None], path_tail], axis=0).T  # [B, T]
    return jnp.where(valid, path, 0)


def linear_chain_crf(emission, transition, label, length):
    """Public fluid-compatible CRF NLL (batched dense form; the
    reference's LoD form maps via sequence_pad)."""
    return _linear_chain_crf(emission, transition, label, length)


def crf_decoding(emission, transition, length):
    return _crf_decoding(emission, transition, length)
