"""Neural-net primitive ops.

Reference parity: paddle/fluid/operators/ conv2d / pool2d / batch_norm /
layer_norm / dropout / softmax_with_cross_entropy / activation families and
python/paddle/nn/functional/. All are pure jax functions lowered through
XLA's convolution/reduce-window/dot primitives, which map directly onto the
TPU MXU / VPU — there is no cuDNN analogue to call; XLA *is* the vendor
library on TPU.

Layout note: paddle defaults to NCHW. XLA TPU internally prefers NHWC but
`jax.lax.conv_general_dilated` takes dimension_numbers, letting XLA pick
the optimal internal layout; we keep the user-visible NCHW contract.
"""
import numpy as np
import jax
import jax.numpy as jnp

from ..core.dispatch import register_op
from ..core.tensor import Tensor
from ..core import rng as rng_mod


def _pair(v, n=2):
    if isinstance(v, (list, tuple)):
        return tuple(int(x) for x in v)
    return (int(v),) * n


# ---- activations -----------------------------------------------------------

def _act(name, fn):
    op = register_op(name)(fn)

    def api(x, name=None):
        return op(x)
    api.__name__ = name
    return api


relu = _act("relu", lambda x: jax.nn.relu(x))
relu6 = _act("relu6", lambda x: jax.nn.relu6(x))
sigmoid = _act("sigmoid_act", lambda x: jax.nn.sigmoid(x))
tanh = _act("tanh_act", lambda x: jnp.tanh(x))
softplus_ = _act("softplus", lambda x: jax.nn.softplus(x))
softsign = _act("softsign", lambda x: jax.nn.soft_sign(x))
silu = _act("silu", lambda x: jax.nn.silu(x))
swish = silu
mish = _act("mish", lambda x: jax.nn.mish(x))
hardswish = _act("hard_swish", lambda x: jax.nn.hard_swish(x))
hardsigmoid = _act("hard_sigmoid", lambda x: jnp.clip(x / 6.0 + 0.5, 0.0, 1.0))
tanhshrink = _act("tanh_shrink", lambda x: x - jnp.tanh(x))
log_sigmoid = _act("logsigmoid", lambda x: jax.nn.log_sigmoid(x))


@register_op("gelu")
def _gelu(x, *, approximate):
    return jax.nn.gelu(x, approximate=approximate)


def gelu(x, approximate=False, name=None):
    return _gelu(x, approximate=bool(approximate))


@register_op("leaky_relu")
def _leaky_relu(x, *, alpha):
    return jax.nn.leaky_relu(x, negative_slope=alpha)


def leaky_relu(x, negative_slope=0.01, name=None):
    return _leaky_relu(x, alpha=float(negative_slope))


@register_op("elu")
def _elu(x, *, alpha):
    return jax.nn.elu(x, alpha=alpha)


def elu(x, alpha=1.0, name=None):
    return _elu(x, alpha=float(alpha))


@register_op("selu")
def _selu(x, *, scale, alpha):
    return scale * jnp.where(x > 0, x, alpha * jnp.expm1(x))


def selu(x, scale=1.0507009873554805, alpha=1.6732632423543772, name=None):
    return _selu(x, scale=float(scale), alpha=float(alpha))


@register_op("celu")
def _celu(x, *, alpha):
    return jax.nn.celu(x, alpha=alpha)


def celu(x, alpha=1.0, name=None):
    return _celu(x, alpha=float(alpha))


@register_op("hardtanh")
def _hardtanh(x, *, mn, mx):
    return jnp.clip(x, mn, mx)


def hardtanh(x, min=-1.0, max=1.0, name=None):  # noqa: A002
    return _hardtanh(x, mn=float(min), mx=float(max))


@register_op("hard_shrink")
def _hardshrink(x, *, threshold):
    return jnp.where(jnp.abs(x) > threshold, x, jnp.zeros_like(x))


def hardshrink(x, threshold=0.5, name=None):
    return _hardshrink(x, threshold=float(threshold))


@register_op("soft_shrink")
def _softshrink(x, *, threshold):
    return jnp.where(x > threshold, x - threshold,
                     jnp.where(x < -threshold, x + threshold, jnp.zeros_like(x)))


def softshrink(x, threshold=0.5, name=None):
    return _softshrink(x, threshold=float(threshold))


def softplus(x, beta=1.0, threshold=20.0, name=None):
    return _softplus_full(x, beta=float(beta), threshold=float(threshold))


@register_op("softplus_full")
def _softplus_full(x, *, beta, threshold):
    scaled = beta * x
    return jnp.where(scaled > threshold, x, jax.nn.softplus(scaled) / beta)


@register_op("thresholded_relu")
def _thresholded_relu(x, *, threshold):
    return jnp.where(x > threshold, x, jnp.zeros_like(x))


def thresholded_relu(x, threshold=1.0, name=None):
    return _thresholded_relu(x, threshold=float(threshold))


@register_op("prelu")
def _prelu(x, weight, *, channel_axis):
    shape = [1] * x.ndim
    if weight.size > 1:
        shape[channel_axis] = weight.size
    w = weight.reshape(shape)
    return jnp.where(x > 0, x, w * x)


def prelu(x, weight, data_format="NCHW", name=None):
    axis = 1 if data_format[1] == "C" else x.ndim - 1
    return _prelu(x, weight, channel_axis=axis)


@register_op("softmax")
def _softmax(x, *, axis):
    return jax.nn.softmax(x, axis=axis)


def softmax(x, axis=-1, dtype=None, name=None):
    out = _softmax(x, axis=int(axis))
    if dtype is not None:
        from . import math as math_ops
        out = math_ops.cast(out, dtype)
    return out


@register_op("log_softmax")
def _log_softmax(x, *, axis):
    return jax.nn.log_softmax(x, axis=axis)


def log_softmax(x, axis=-1, dtype=None, name=None):
    return _log_softmax(x, axis=int(axis))


@register_op("glu")
def _glu(x, *, axis):
    a, b = jnp.split(x, 2, axis=axis)
    return a * jax.nn.sigmoid(b)


def glu(x, axis=-1, name=None):
    return _glu(x, axis=int(axis))


# ---- linear / conv ---------------------------------------------------------

@register_op("linear")
def _linear(x, w, b):
    out = jnp.matmul(x, w)
    if b is not None:
        out = out + b
    return out


def fc_flatten(x, num_flatten_dims):
    """Shared fc input normalization (reference paddle.static.nn.fc /
    fluid.layers.fc): trailing dims from num_flatten_dims flatten into
    the feature axis. Validates 1 <= num_flatten_dims <= rank-1 and
    demands concrete non-batch leading dims (one -1 covers the batch).
    Returns (flattened_x, in_features)."""
    rank = len(x.shape)
    if not 1 <= num_flatten_dims <= rank - 1:
        raise ValueError(
            f"fc: num_flatten_dims must be in [1, {rank - 1}] for a "
            f"rank-{rank} input, got {num_flatten_dims}")
    trailing = [int(s) for s in x.shape[num_flatten_dims:]]
    if any(d < 0 for d in trailing):
        raise ValueError(
            "fc: trailing (feature) dims must be concrete, got "
            f"{tuple(x.shape)}")
    in_dim = int(np.prod(trailing))
    if rank == num_flatten_dims + 1:
        return x, in_dim
    lead = [int(s) for s in x.shape[1:num_flatten_dims]]
    if any(d < 0 for d in lead):
        raise ValueError(
            "fc: leading dims beyond the batch must be concrete when "
            f"num_flatten_dims > 1, got {tuple(x.shape)}")
    from . import manipulation
    return manipulation.reshape(x, (-1, *lead, in_dim)), in_dim


def linear(x, weight, bias=None, name=None):
    """Reference: python/paddle/nn/functional/common.py:1398 (weight is
    [in_features, out_features], NOT transposed — paddle convention)."""
    return _linear(x, weight, bias)


@register_op("conv2d")
def _conv2d(x, w, b, *, strides, paddings, dilations, groups, data_format):
    # the layer stores weights OIHW for BOTH data formats (conv.py
    # _ConvNd); only the feature layout changes with data_format
    dn = jax.lax.conv_dimension_numbers(
        x.shape, w.shape,
        ("NCHW", "OIHW", "NCHW") if data_format == "NCHW"
        else ("NHWC", "OIHW", "NHWC"))
    if isinstance(paddings, str):
        pad = paddings  # SAME / VALID
    else:
        pad = tuple((p, p) for p in paddings) if len(paddings) == 2 else \
            tuple((paddings[2 * i], paddings[2 * i + 1]) for i in range(2))
    # no preferred_element_type: the TPU MXU accumulates bf16 convs in
    # f32 in hardware already, and an f32-output annotation makes the
    # conv transpose rule see mixed bf16/f32 operands in the vjp
    out = jax.lax.conv_general_dilated(
        x, w, window_strides=strides, padding=pad,
        rhs_dilation=dilations, dimension_numbers=dn,
        feature_group_count=groups)
    if b is not None:
        bshape = (1, -1, 1, 1) if data_format == "NCHW" else (1, 1, 1, -1)
        out = out + b.reshape(bshape)
    return out


def conv2d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCHW", name=None):
    """Reference: operators/conv_op.cc semantics; weight OIHW."""
    if isinstance(padding, str):
        pad = padding.upper()
    else:
        pad = _pair(padding) if not (isinstance(padding, (list, tuple)) and len(padding) == 4) \
            else tuple(int(p) for p in padding)
    return _conv2d(x, weight, bias, strides=_pair(stride), paddings=pad,
                   dilations=_pair(dilation), groups=int(groups),
                   data_format=data_format)


@register_op("conv1d")
def _conv1d(x, w, b, *, stride, padding, dilation, groups):
    dn = jax.lax.conv_dimension_numbers(x.shape, w.shape, ("NCH", "OIH", "NCH"))
    pad = padding if isinstance(padding, str) else ((padding, padding),)
    out = jax.lax.conv_general_dilated(
        x, w, window_strides=(stride,), padding=pad, rhs_dilation=(dilation,),
        dimension_numbers=dn, feature_group_count=groups)
    if b is not None:
        out = out + b.reshape(1, -1, 1)
    return out


def conv1d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCL", name=None):
    pad = padding.upper() if isinstance(padding, str) else int(padding)
    return _conv1d(x, weight, bias, stride=int(stride), padding=pad,
                   dilation=int(dilation), groups=int(groups))


@register_op("conv3d")
def _conv3d(x, w, b, *, strides, paddings, dilations, groups):
    dn = jax.lax.conv_dimension_numbers(x.shape, w.shape,
                                        ("NCDHW", "OIDHW", "NCDHW"))
    pad = paddings if isinstance(paddings, str) else tuple((p, p) for p in paddings)
    out = jax.lax.conv_general_dilated(
        x, w, window_strides=strides, padding=pad, rhs_dilation=dilations,
        dimension_numbers=dn, feature_group_count=groups)
    if b is not None:
        out = out + b.reshape(1, -1, 1, 1, 1)
    return out


def conv3d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCDHW", name=None):
    pad = padding.upper() if isinstance(padding, str) else _pair(padding, 3)
    x = _to_ncdhw(x, data_format)   # NDHWC handled by transposition
    out = _conv3d(x, weight, bias, strides=_pair(stride, 3), paddings=pad,
                  dilations=_pair(dilation, 3), groups=int(groups))
    return _from_ncdhw(out, data_format)


@register_op("conv2d_transpose")
def _conv2d_transpose(x, w, b, *, strides, paddings, output_padding, dilations,
                      groups):
    # paddle weight layout for transpose conv: [in, out/groups, kh, kw].
    # Express as a fractionally-strided conv: spatially flip the kernel and
    # swap I/O (per group) to OIHW, then conv with lhs_dilation=stride.
    in_c, out_pg, kh_, kw_ = w.shape
    wf = jnp.flip(w, axis=(2, 3))
    wf = wf.reshape(groups, in_c // groups, out_pg, kh_, kw_)
    wf = wf.transpose(0, 2, 1, 3, 4).reshape(
        groups * out_pg, in_c // groups, kh_, kw_)
    dn = jax.lax.conv_dimension_numbers(x.shape, wf.shape,
                                        ("NCHW", "OIHW", "NCHW"))
    kh = (kh_ - 1) * dilations[0] + 1
    kw = (kw_ - 1) * dilations[1] + 1
    ph, pw = paddings
    pad = ((kh - 1 - ph, kh - 1 - ph + output_padding[0]),
           (kw - 1 - pw, kw - 1 - pw + output_padding[1]))
    out = jax.lax.conv_general_dilated(
        x, wf, window_strides=(1, 1), padding=pad,
        lhs_dilation=strides, rhs_dilation=dilations, dimension_numbers=dn,
        feature_group_count=groups)
    if b is not None:
        out = out + b.reshape(1, -1, 1, 1)
    return out


def conv2d_transpose(x, weight, bias=None, stride=1, padding=0,
                     output_padding=0, dilation=1, groups=1,
                     output_size=None, data_format="NCHW", name=None):
    return _conv2d_transpose(x, weight, bias, strides=_pair(stride),
                             paddings=_pair(padding),
                             output_padding=_pair(output_padding),
                             dilations=_pair(dilation), groups=int(groups))


# ---- pooling ---------------------------------------------------------------

def _pool_windows(x, ksize, strides, paddings, pad_value, ceil_mode=False):
    """Yield the kh*kw strided window slices of x (differentiable pooling
    building block: slice + elementwise reduce only — fuses well on TPU and
    avoids reduce_window, whose vjp does not lower under jit on this
    backend). ceil_mode right-pads so the partial windows exist."""
    kh, kw = ksize
    sh, sw = strides
    ph, pw = paddings
    h0, w0 = x.shape[2], x.shape[3]
    if ceil_mode:
        oh = -(-(h0 + 2 * ph - kh) // sh) + 1
        ow = -(-(w0 + 2 * pw - kw) // sw) + 1
    else:
        oh = (h0 + 2 * ph - kh) // sh + 1
        ow = (w0 + 2 * pw - kw) // sw + 1
    need_h = max(0, (oh - 1) * sh + kh - (h0 + 2 * ph))
    need_w = max(0, (ow - 1) * sw + kw - (w0 + 2 * pw))
    if ph or pw or need_h or need_w:
        x = jnp.pad(x, ((0, 0), (0, 0), (ph, ph + need_h),
                        (pw, pw + need_w)), constant_values=pad_value)
    for i in range(kh):
        for j in range(kw):
            yield x[:, :, i:i + (oh - 1) * sh + 1:sh,
                    j:j + (ow - 1) * sw + 1:sw]


@register_op("pool2d_max")
def _max_pool2d(x, *, ksize, strides, paddings, ceil_mode):
    out = None
    for win in _pool_windows(x, ksize, strides, paddings,
                             _neg_min(x.dtype), ceil_mode):
        out = win if out is None else jnp.maximum(out, win)
    return out


@register_op("pool2d_max_with_index")
def _max_pool2d_with_index(x, *, ksize, strides, paddings,
                           ceil_mode=False):
    """Reference: max_pool2d_with_index op (pool_with_index_op.cc) — the
    mask is each max's flat position in the INPUT feature map (h*w),
    first-max-wins on ties."""
    wins = jnp.stack(
        list(_pool_windows(x, ksize, strides, paddings,
                           _neg_min(x.dtype), ceil_mode)), axis=0)
    out = jnp.max(wins, axis=0)
    amax = jnp.argmax(wins, axis=0)        # row-major window slot
    kh, kw = ksize
    sh, sw = strides
    ph, pw = paddings
    di, dj = amax // kw, amax % kw
    oh, ow = out.shape[2], out.shape[3]
    r = jnp.arange(oh)[:, None] * sh - ph + di
    c = jnp.arange(ow)[None, :] * sw - pw + dj
    mask = (r * x.shape[3] + c).astype(jnp.int32)
    return out, mask


def max_pool2d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               return_mask=False, data_format="NCHW", name=None):
    ks = _pair(kernel_size)
    st = _pair(stride) if stride is not None else ks
    if return_mask:
        return _max_pool2d_with_index(x, ksize=ks, strides=st,
                                      paddings=_pair(padding),
                                      ceil_mode=bool(ceil_mode))
    return _max_pool2d(x, ksize=ks, strides=st, paddings=_pair(padding),
                       ceil_mode=bool(ceil_mode))


@register_op("pool2d_avg")
def _avg_pool2d(x, *, ksize, strides, paddings, exclusive):
    summed = None
    for win in _pool_windows(x, ksize, strides, paddings, 0):
        summed = win if summed is None else summed + win
    if exclusive and (paddings[0] or paddings[1]):
        # per-position valid-element counts are static: compute with numpy
        kh, kw = ksize
        sh, sw = strides
        ph, pw = paddings
        h, w = x.shape[2], x.shape[3]
        ones = np.ones((1, 1, h, w), np.float32)
        ones = np.pad(ones, ((0, 0), (0, 0), (ph, ph), (pw, pw)))
        oh = (h + 2 * ph - kh) // sh + 1
        ow = (w + 2 * pw - kw) // sw + 1
        counts = np.zeros((1, 1, oh, ow), np.float32)
        for i in range(kh):
            for j in range(kw):
                counts += ones[:, :, i:i + (oh - 1) * sh + 1:sh,
                               j:j + (ow - 1) * sw + 1:sw]
        return summed / jnp.asarray(counts, x.dtype)
    return summed / (ksize[0] * ksize[1])


def avg_pool2d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               exclusive=True, divisor_override=None, data_format="NCHW",
               name=None):
    ks = _pair(kernel_size)
    st = _pair(stride) if stride is not None else ks
    return _avg_pool2d(x, ksize=ks, strides=st, paddings=_pair(padding),
                       exclusive=bool(exclusive))


@register_op("adaptive_avg_pool2d")
def _adaptive_avg_pool2d(x, *, output_size):
    n, c, h, w = x.shape
    oh, ow = output_size
    if h % oh == 0 and w % ow == 0:
        x4 = x.reshape(n, c, oh, h // oh, ow, w // ow)
        return x4.mean(axis=(3, 5))
    # general case: interpolate-style pooling
    out = jax.image.resize(x, (n, c, oh, ow), method="linear")
    return out


def adaptive_avg_pool2d(x, output_size, data_format="NCHW", name=None):
    return _adaptive_avg_pool2d(x, output_size=_pair(output_size))


@register_op("adaptive_max_pool2d")
def _adaptive_max_pool2d(x, *, output_size):
    n, c, h, w = x.shape
    oh, ow = output_size
    assert h % oh == 0 and w % ow == 0, "adaptive_max_pool needs divisible sizes"
    x4 = x.reshape(n, c, oh, h // oh, ow, w // ow)
    return x4.max(axis=(3, 5))


@register_op("adaptive_max_pool2d_with_index")
def _adaptive_max_pool2d_with_index(x, *, output_size):
    n, c, h, w = x.shape
    oh, ow = output_size
    assert h % oh == 0 and w % ow == 0, "adaptive_max_pool needs divisible sizes"
    bh, bw = h // oh, w // ow
    x4 = x.reshape(n, c, oh, bh, ow, bw)
    blocks = x4.transpose(0, 1, 2, 4, 3, 5).reshape(n, c, oh, ow, bh * bw)
    amax = jnp.argmax(blocks, axis=-1)
    di, dj = amax // bw, amax % bw
    r = jnp.arange(oh)[:, None] * bh + di
    col = jnp.arange(ow)[None, :] * bw + dj
    mask = (r * w + col).astype(jnp.int32)
    return blocks.max(axis=-1), mask


def adaptive_max_pool2d(x, output_size, return_mask=False, name=None):
    if return_mask:
        return _adaptive_max_pool2d_with_index(
            x, output_size=_pair(output_size))
    return _adaptive_max_pool2d(x, output_size=_pair(output_size))


def max_pool1d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               return_mask=False, name=None):
    from . import manipulation
    x4 = manipulation.unsqueeze(x, axis=2)
    out = max_pool2d(x4, (1, kernel_size), (1, stride or kernel_size),
                     (0, padding if isinstance(padding, int)
                      else padding[0]),
                     return_mask=return_mask)
    if return_mask:
        # the [1, L] feature map's flat index IS the index in L
        return (manipulation.squeeze(out[0], axis=2),
                manipulation.squeeze(out[1], axis=2))
    return manipulation.squeeze(out, axis=2)


def avg_pool1d(x, kernel_size, stride=None, padding=0, exclusive=True,
               ceil_mode=False, name=None):
    from . import manipulation
    x4 = manipulation.unsqueeze(x, axis=2)
    out = avg_pool2d(x4, (1, kernel_size), (1, stride or kernel_size),
                     (0, padding if isinstance(padding, int) else padding[0]),
                     exclusive=exclusive)
    return manipulation.squeeze(out, axis=2)


# ---- normalization ---------------------------------------------------------


@register_op("spectral_norm_op")
def _spectral_norm(weight, u, v, *, dim, power_iters, eps):
    """Reference: spectral_norm_op.cc — power iteration for the largest
    singular value; u/v are carried state, constant for the gradient
    (lax.stop_gradient), exactly the reference kernel's treatment."""
    perm = (dim,) + tuple(i for i in range(weight.ndim) if i != dim)
    mat = jnp.transpose(weight, perm).reshape(weight.shape[dim], -1)

    def _l2(x):
        return x / (jnp.linalg.norm(x) + eps)

    uu, vv = u, v
    for _ in range(max(1, power_iters)):
        vv = _l2(mat.T @ uu)
        uu = _l2(mat @ vv)
    uu = jax.lax.stop_gradient(uu)
    vv = jax.lax.stop_gradient(vv)
    sigma = uu @ (mat @ vv)
    return weight / sigma, uu, vv


def spectral_norm(weight, u, v, dim=0, power_iters=1, eps=1e-12,
                  name=None):
    return _spectral_norm(weight, u, v, dim=int(dim),
                          power_iters=int(power_iters), eps=float(eps))

@register_op("layer_norm")
def _layer_norm(x, scale, bias, *, epsilon, begin_norm_axis):
    axes = tuple(range(begin_norm_axis, x.ndim))
    mean = jnp.mean(x, axis=axes, keepdims=True)
    var = jnp.var(x, axis=axes, keepdims=True)
    inv = jax.lax.rsqrt(var + epsilon)
    out = (x - mean) * inv
    if scale is not None:
        out = out * scale.reshape(x.shape[begin_norm_axis:])
    if bias is not None:
        out = out + bias.reshape(x.shape[begin_norm_axis:])
    return out


def layer_norm(x, normalized_shape=None, weight=None, bias=None, epsilon=1e-5,
               name=None):
    """Reference: operators/layer_norm_op.cc; normalizes trailing dims."""
    if isinstance(normalized_shape, int):
        normalized_shape = (normalized_shape,)
    n_norm = len(normalized_shape) if normalized_shape else 1
    begin = x.ndim - n_norm
    return _layer_norm(x, weight, bias, epsilon=float(epsilon),
                       begin_norm_axis=int(begin))


@register_op("batch_norm_infer")
def _batch_norm_infer(x, mean, var, scale, bias, *, epsilon, channel_axis):
    shape = [1] * x.ndim
    shape[channel_axis] = x.shape[channel_axis]
    inv = jax.lax.rsqrt(var.reshape(shape) + epsilon)
    out = (x - mean.reshape(shape)) * inv
    if scale is not None:
        out = out * scale.reshape(shape)
    if bias is not None:
        out = out + bias.reshape(shape)
    return out


@register_op("batch_norm_train")
def _batch_norm_train(x, scale, bias, *, epsilon, channel_axis):
    axes = tuple(i for i in range(x.ndim) if i != channel_axis)
    mean = jnp.mean(x, axis=axes)
    var = jnp.var(x, axis=axes)
    shape = [1] * x.ndim
    shape[channel_axis] = x.shape[channel_axis]
    inv = jax.lax.rsqrt(var.reshape(shape) + epsilon)
    out = (x - mean.reshape(shape)) * inv
    if scale is not None:
        out = out * scale.reshape(shape)
    if bias is not None:
        out = out + bias.reshape(shape)
    return out, mean, var


def batch_norm(x, running_mean, running_var, weight=None, bias=None,
               training=False, momentum=0.9, epsilon=1e-5,
               data_format="NCHW", use_global_stats=None, name=None):
    """Reference: operators/batch_norm_op.cc. In training mode the running
    stats tensors are updated in place (observable by the trace context)."""
    ch_axis = 1 if data_format[1] == "C" or data_format == "NCL" else x.ndim - 1
    if use_global_stats is None:
        use_global_stats = not training
    if not training or use_global_stats:
        return _batch_norm_infer(x, running_mean, running_var, weight, bias,
                                 epsilon=float(epsilon), channel_axis=ch_axis)
    out, batch_mean, batch_var = _batch_norm_train(
        x, weight, bias, epsilon=float(epsilon), channel_axis=ch_axis)
    if running_mean is not None:
        m = float(momentum)
        running_mean.value = running_mean.value * m + batch_mean.value * (1 - m)
        running_var.value = running_var.value * m + batch_var.value * (1 - m)
    return out


@register_op("group_norm")
def _group_norm(x, scale, bias, *, groups, epsilon):
    n, c = x.shape[0], x.shape[1]
    spatial = x.shape[2:]
    xg = x.reshape((n, groups, c // groups) + spatial)
    axes = tuple(range(2, xg.ndim))
    mean = jnp.mean(xg, axis=axes, keepdims=True)
    var = jnp.var(xg, axis=axes, keepdims=True)
    out = ((xg - mean) * jax.lax.rsqrt(var + epsilon)).reshape(x.shape)
    shape = (1, c) + (1,) * len(spatial)
    if scale is not None:
        out = out * scale.reshape(shape)
    if bias is not None:
        out = out + bias.reshape(shape)
    return out


def group_norm(x, num_groups, weight=None, bias=None, epsilon=1e-5,
               data_format="NCHW", name=None):
    return _group_norm(x, weight, bias, groups=int(num_groups),
                       epsilon=float(epsilon))


@register_op("instance_norm")
def _instance_norm(x, scale, bias, *, epsilon):
    axes = tuple(range(2, x.ndim))
    mean = jnp.mean(x, axis=axes, keepdims=True)
    var = jnp.var(x, axis=axes, keepdims=True)
    out = (x - mean) * jax.lax.rsqrt(var + epsilon)
    shape = (1, x.shape[1]) + (1,) * (x.ndim - 2)
    if scale is not None:
        out = out * scale.reshape(shape)
    if bias is not None:
        out = out + bias.reshape(shape)
    return out


def instance_norm(x, running_mean=None, running_var=None, weight=None,
                  bias=None, training=True, momentum=0.9, epsilon=1e-5,
                  data_format="NCHW", name=None):
    return _instance_norm(x, weight, bias, epsilon=float(epsilon))


@register_op("l2_normalize")
def _normalize(x, *, p, axis, epsilon):
    if p == 2.0:
        nrm = jnp.sqrt(jnp.sum(jnp.square(x), axis=axis, keepdims=True))
    else:
        nrm = jnp.sum(jnp.abs(x) ** p, axis=axis, keepdims=True) ** (1.0 / p)
    return x / jnp.maximum(nrm, epsilon)


def normalize(x, p=2, axis=1, epsilon=1e-12, name=None):
    return _normalize(x, p=float(p), axis=int(axis), epsilon=float(epsilon))


@register_op("local_response_norm")
def _lrn(x, *, size, alpha, beta, k):
    sq = jnp.square(x)
    half = size // 2
    pad = [(0, 0)] * x.ndim
    pad[1] = (half, size - half - 1)
    sq = jnp.pad(sq, pad)
    acc = jnp.zeros_like(x)
    for i in range(size):
        acc = acc + jax.lax.dynamic_slice_in_dim(sq, i, x.shape[1], axis=1)
    return x / jnp.power(k + alpha * acc, beta)


def local_response_norm(x, size, alpha=1e-4, beta=0.75, k=1.0,
                        data_format="NCHW", name=None):
    return _lrn(x, size=int(size), alpha=float(alpha), beta=float(beta),
                k=float(k))


# ---- dropout / embedding ---------------------------------------------------

@register_op("dropout")
def _dropout(x, key, *, p, upscale):
    keep = 1.0 - p
    mask = jax.random.bernoulli(key, keep, x.shape)
    if upscale:
        return jnp.where(mask, x / keep, jnp.zeros_like(x))
    return jnp.where(mask, x, jnp.zeros_like(x))


def dropout(x, p=0.5, axis=None, training=True, mode="upscale_in_train",
            name=None):
    """Reference: operators/dropout_op.cc; default mode upscale_in_train."""
    if not training or p == 0.0:
        if mode == "downscale_in_infer" and not training:
            from . import math as math_ops
            return math_ops.scale(x, scale=1.0 - p)
        return x
    key = rng_mod.next_key()
    return _dropout(x, key, p=float(p), upscale=(mode == "upscale_in_train"))


def dropout2d(x, p=0.5, training=True, data_format="NCHW", name=None):
    if not training or p == 0.0:
        return x
    key = rng_mod.next_key()
    return _dropout2d(x, key, p=float(p))


@register_op("dropout2d")
def _dropout2d(x, key, *, p):
    keep = 1.0 - p
    mask = jax.random.bernoulli(key, keep, x.shape[:2] + (1, 1))
    return jnp.where(mask, x / keep, jnp.zeros_like(x))


@register_op("lookup_table_v2")
def _embedding(ids, weight, *, padding_idx):
    out = jnp.take(weight, ids, axis=0)
    if padding_idx is not None and padding_idx >= 0:
        mask = (ids != padding_idx)[..., None]
        out = jnp.where(mask, out, jnp.zeros_like(out))
    return out


class _SparseLookupOp:
    """Op stand-in for the engine: backward emits an IndexedSlices grad
    for the table instead of a dense [vocab, dim] scatter-add (reference:
    lookup_table_v2_op grad with is_sparse=True -> SelectedRows,
    selected_rows.h:41)."""

    name = "lookup_table_v2_sparse"
    differentiable = True

    def __init__(self, padding_idx):
        self._padding_idx = padding_idx

    def vjp_fn(self, key, closure):
        pi = self._padding_idx

        def bwd(arrays, ct):
            ids, weight = arrays
            idx = ids.reshape(-1)
            vals = ct.reshape(-1, weight.shape[-1])
            if pi is not None and pi >= 0:
                vals = jnp.where((idx != pi)[:, None], vals,
                                 jnp.zeros_like(vals))
            from ..core.sparse_grad import IndexedSlices
            return (np.zeros(ids.shape, jax.dtypes.float0),
                    IndexedSlices(idx, vals, weight.shape))
        return bwd


def _embedding_sparse_grad(x, weight, pi):
    """Eager sparse-grad lookup: forward via the normal op with autograd
    suppressed, then a hand-built grad node whose backward produces
    IndexedSlices. create_graph falls back to the dense closure."""
    from ..core.engine import GradNode
    from ..core.dispatch import no_grad
    from ..core.tensor import Tensor

    with no_grad():
        out = _embedding(x, weight, padding_idx=pi)
    op = _SparseLookupOp(pi)
    arrays = [x.value if isinstance(x, Tensor) else jnp.asarray(x),
              weight.value]

    def closure(ids, w):  # dense fallback for double-grad (_vjp_apply)
        return _embedding.fn(ids, w, padding_idx=pi)

    node = GradNode(op, ("lookup_table_v2_sparse", pi), closure, arrays,
                    [None, weight], [(out.value.shape, out.value.dtype)])
    out.stop_gradient = False
    out._grad_node = (node, 0)
    node.out_refs = [out]
    return out


def embedding(x, weight, padding_idx=None, sparse=False, name=None):
    """Reference: operators/lookup_table_v2_op. sparse=True produces
    IndexedSlices gradients for the table in eager mode (reference
    SelectedRows, selected_rows.h:41); under a compiled step the dense
    vjp is used — XLA fuses the scatter-add into the program, which is
    already the memory-optimal jit form."""
    from ..core import trace as trace_mod
    from ..core import dispatch as _d
    from ..core.dispatch import is_grad_enabled
    pi = -1 if padding_idx is None else int(padding_idx)
    if pi < 0 and padding_idx is not None:
        pi = weight.shape[0] + int(padding_idx)
    pi = pi if padding_idx is not None else None
    if (sparse and trace_mod.current_trace() is None
            and is_grad_enabled() and hasattr(weight, "stop_gradient")
            and not weight.stop_gradient):
        in_static = False
        if _d._static_variable_cls is not None:
            from ..static.program import building_program
            in_static = building_program() is not None
        if not in_static:
            return _embedding_sparse_grad(x, weight, pi)
    # static / traced: dense record path (XLA fuses the scatter-add)
    return _embedding(x, weight, padding_idx=pi)


@register_op("one_hot_v2", differentiable=False)
def _one_hot(x, *, num_classes):
    return jax.nn.one_hot(x, num_classes, dtype=jnp.float32)


def one_hot(x, num_classes, name=None):
    return _one_hot(x, num_classes=int(num_classes))


# ---- losses ----------------------------------------------------------------

@register_op("softmax_with_cross_entropy")
def _softmax_with_ce(logits, label, *, soft_label, axis, ignore_index):
    logp = jax.nn.log_softmax(logits, axis=axis)
    if soft_label:
        loss = -jnp.sum(label * logp, axis=axis, keepdims=True)
    else:
        lab = label
        if lab.ndim == logits.ndim:
            lab = jnp.squeeze(lab, axis=axis)
        safe_lab = jnp.where(lab == ignore_index, jnp.zeros_like(lab), lab)
        gathered = jnp.take_along_axis(
            logp, jnp.expand_dims(safe_lab, axis).astype(jnp.int32), axis=axis)
        loss = -gathered
        mask = jnp.expand_dims(lab, axis) != ignore_index
        loss = jnp.where(mask, loss, jnp.zeros_like(loss))
    return loss


def softmax_with_cross_entropy(logits, label, soft_label=False,
                               ignore_index=-100, axis=-1,
                               return_softmax=False, name=None):
    loss = _softmax_with_ce(logits, label, soft_label=bool(soft_label),
                            axis=int(axis), ignore_index=int(ignore_index))
    if return_softmax:
        return loss, softmax(logits, axis=axis)
    return loss


def cross_entropy(input, label, weight=None, ignore_index=-100,  # noqa: A002
                  reduction="mean", soft_label=False, axis=-1,
                  use_softmax=True, name=None):
    """Reference: python/paddle/nn/functional/loss.py cross_entropy."""
    from . import math as math_ops, reduction as red_ops
    if use_softmax:
        loss = softmax_with_cross_entropy(input, label, soft_label=soft_label,
                                          ignore_index=ignore_index, axis=axis)
    else:
        loss = _nll_from_probs(input, label, axis=int(axis))
    from . import manipulation
    loss = manipulation.squeeze(loss, axis=int(axis))
    if weight is not None:
        w = _gather_weight(weight, label, soft_label, axis)
        loss = math_ops.multiply(loss, w)
    if reduction == "mean":
        if not soft_label:
            # mean over non-ignored positions; weighted mean divides by the
            # sum of gathered weights (reference: nn/functional/loss.py)
            valid = _valid_mask(label, ignore_index, axis)
            s = red_ops.sum(loss)
            if weight is not None:
                w = math_ops.multiply(
                    _gather_weight(weight, label, soft_label, axis), valid)
                n = red_ops.sum(w)
            else:
                n = red_ops.sum(valid)
            return math_ops.divide(s, math_ops.maximum(n, 1e-12))
        if weight is not None:
            # soft labels: reference divides by the summed per-sample
            # weights <label, w> too (loss.py weighted mean)
            wsum = red_ops.sum(_gather_weight(weight, label, soft_label,
                                              axis))
            return math_ops.divide(red_ops.sum(loss),
                                   math_ops.maximum(wsum, 1e-12))
        return red_ops.mean(loss)
    if reduction == "sum":
        return red_ops.sum(loss)
    return loss


@register_op("nll_from_probs")
def _nll_from_probs(probs, label, *, axis):
    logp = jnp.log(jnp.maximum(probs, 1e-30))
    lab = label
    if lab.ndim == probs.ndim:
        lab = jnp.squeeze(lab, axis=axis)
    return -jnp.take_along_axis(logp, jnp.expand_dims(lab, axis).astype(jnp.int32),
                                axis=axis)


@register_op("valid_mask", differentiable=False)
def _valid_mask_op(label, *, ignore_index):
    return (label != ignore_index).astype(jnp.float32)


def _valid_mask(label, ignore_index, axis):
    return _valid_mask_op(label, ignore_index=int(ignore_index))


def _gather_weight(weight, label, soft_label, axis):
    from . import manipulation
    if soft_label:
        # reference semantics (loss.py cross_entropy soft_label=True,
        # weight given): per-sample weight = <soft label, class weight>
        from . import math as math_ops, reduction as red_ops
        return red_ops.sum(math_ops.multiply(label, weight),
                           axis=int(axis))
    return manipulation.gather(weight, label)


@register_op("mse_loss")
def _mse(x, y):
    return jnp.square(x - y)


def mse_loss(input, label, reduction="mean", name=None):  # noqa: A002
    from . import reduction as red_ops
    loss = _mse(input, label)
    if reduction == "mean":
        return red_ops.mean(loss)
    if reduction == "sum":
        return red_ops.sum(loss)
    return loss


@register_op("l1_loss")
def _l1(x, y):
    return jnp.abs(x - y)


def l1_loss(input, label, reduction="mean", name=None):  # noqa: A002
    from . import reduction as red_ops
    loss = _l1(input, label)
    if reduction == "mean":
        return red_ops.mean(loss)
    if reduction == "sum":
        return red_ops.sum(loss)
    return loss


@register_op("smooth_l1_loss")
def _smooth_l1(x, y, *, delta):
    diff = jnp.abs(x - y)
    return jnp.where(diff < delta, 0.5 * diff * diff / delta,
                     diff - 0.5 * delta)


def smooth_l1_loss(input, label, reduction="mean", delta=1.0, name=None):  # noqa: A002
    from . import reduction as red_ops
    loss = _smooth_l1(input, label, delta=float(delta))
    if reduction == "mean":
        return red_ops.mean(loss)
    if reduction == "sum":
        return red_ops.sum(loss)
    return loss


@register_op("bce_with_logits")
def _bce_logits(logits, label, pos_weight):
    # stable: max(x,0) - x*z + log(1 + exp(-|x|)), with optional pos_weight
    softplus_term = jnp.maximum(logits, 0.0) - logits * label + \
        jnp.log1p(jnp.exp(-jnp.abs(logits)))
    if pos_weight is None:
        return softplus_term
    log_weight = (pos_weight - 1.0) * label + 1.0
    return (1.0 - label) * logits + log_weight * (
        jnp.log1p(jnp.exp(-jnp.abs(logits))) + jnp.maximum(-logits, 0.0))


def binary_cross_entropy_with_logits(logit, label, weight=None,
                                     reduction="mean", pos_weight=None,
                                     name=None):
    from . import math as math_ops, reduction as red_ops
    loss = _bce_logits(logit, label, pos_weight)
    if weight is not None:
        loss = math_ops.multiply(loss, weight)
    if reduction == "mean":
        return red_ops.mean(loss)
    if reduction == "sum":
        return red_ops.sum(loss)
    return loss


@register_op("bce")
def _bce(x, label):
    x = jnp.clip(x, 1e-12, 1.0 - 1e-12)
    return -(label * jnp.log(x) + (1.0 - label) * jnp.log1p(-x))


def binary_cross_entropy(input, label, weight=None, reduction="mean",  # noqa: A002
                         name=None):
    from . import math as math_ops, reduction as red_ops
    loss = _bce(input, label)
    if weight is not None:
        loss = math_ops.multiply(loss, weight)
    if reduction == "mean":
        return red_ops.mean(loss)
    if reduction == "sum":
        return red_ops.sum(loss)
    return loss


@register_op("nll_loss")
def _nll_loss(logp, label, *, ignore_index):
    safe = jnp.where(label == ignore_index, jnp.zeros_like(label), label)
    g = jnp.take_along_axis(logp, safe[:, None].astype(jnp.int32), axis=1)
    loss = -jnp.squeeze(g, axis=1)
    loss = jnp.where(label != ignore_index, loss, jnp.zeros_like(loss))
    return loss


def nll_loss(input, label, weight=None, ignore_index=-100, reduction="mean",  # noqa: A002
             name=None):
    from . import reduction as red_ops
    loss = _nll_loss(input, label, ignore_index=int(ignore_index))
    if reduction == "mean":
        return red_ops.mean(loss)
    if reduction == "sum":
        return red_ops.sum(loss)
    return loss


@register_op("kldiv_loss")
def _kl_div(x, label):
    return label * (jnp.log(jnp.maximum(label, 1e-30)) - x)


def kl_div(input, label, reduction="mean", name=None):  # noqa: A002
    from . import reduction as red_ops
    loss = _kl_div(input, label)
    if reduction == "mean":
        return red_ops.mean(loss)
    if reduction == "sum":
        return red_ops.sum(loss)
    if reduction == "batchmean":
        from . import math as math_ops
        return math_ops.divide(red_ops.sum(loss), float(input.shape[0]))
    return loss


@register_op("square_error_cost")
def _square_error(x, y):
    return jnp.square(x - y)


def square_error_cost(input, label):  # noqa: A002
    return _square_error(input, label)


@register_op("margin_ranking_loss")
def _margin_rank(x, y, label, *, margin):
    return jnp.maximum(-label * (x - y) + margin, 0.0)


def margin_ranking_loss(input, other, label, margin=0.0, reduction="mean",  # noqa: A002
                        name=None):
    from . import reduction as red_ops
    loss = _margin_rank(input, other, label, margin=float(margin))
    if reduction == "mean":
        return red_ops.mean(loss)
    if reduction == "sum":
        return red_ops.sum(loss)
    return loss


@register_op("cosine_similarity")
def _cos_sim(x1, x2, *, axis, eps):
    dot = jnp.sum(x1 * x2, axis=axis)
    n1 = jnp.sqrt(jnp.sum(jnp.square(x1), axis=axis))
    n2 = jnp.sqrt(jnp.sum(jnp.square(x2), axis=axis))
    return dot / jnp.maximum(n1 * n2, eps)


def cosine_similarity(x1, x2, axis=1, eps=1e-8):
    return _cos_sim(x1, x2, axis=int(axis), eps=float(eps))


# ---- misc ------------------------------------------------------------------

def _axis_resize(x, axis, out_len, kind, align_corners):
    """Separable 1-axis resize. align_corners=True samples the corner
    grid pos = i*(in-1)/(out-1) (reference interpolate_op.h); False is
    half-pixel (what jax.image.resize implements)."""
    in_len = x.shape[axis]
    if out_len == in_len:
        return x
    if align_corners:
        # reference: ratio = (in-1)/(out-1), and 0 when out == 1
        ratio = (in_len - 1) / (out_len - 1) if out_len > 1 else 0.0
        pos = jnp.arange(out_len) * ratio
    else:
        pos = (jnp.arange(out_len) + 0.5) * (in_len / out_len) - 0.5
    if kind == "nearest":
        if align_corners:
            # reference kernel: static_cast<int>(ratio*i + 0.5) — half UP
            idx = jnp.clip(jnp.floor(pos + 0.5), 0,
                           in_len - 1).astype(jnp.int32)
        else:
            # reference non-aligned nearest: floor(i * in/out)
            idx = jnp.clip(
                jnp.floor(jnp.arange(out_len) * (in_len / out_len)),
                0, in_len - 1).astype(jnp.int32)
        return jnp.take(x, idx, axis=axis)
    base = jnp.floor(pos)
    frac = (pos - base).astype(x.dtype)
    shape = [1] * x.ndim
    shape[axis] = out_len
    frac = frac.reshape(shape)
    if kind == "linear":
        i0 = jnp.clip(base, 0, in_len - 1).astype(jnp.int32)
        i1 = jnp.clip(base + 1, 0, in_len - 1).astype(jnp.int32)
        return (jnp.take(x, i0, axis=axis) * (1 - frac)
                + jnp.take(x, i1, axis=axis) * frac)
    # cubic convolution, a=-0.75 (the reference's bicubic kernel)
    a = -0.75

    def w0(t):
        return ((a + 2) * t - (a + 3)) * t * t + 1

    def w1(t):
        return ((a * t - 5 * a) * t + 8 * a) * t - 4 * a

    taps = []
    weights = [w1(frac + 1), w0(frac), w0(1 - frac), w1(2 - frac)]
    for off in (-1, 0, 1, 2):
        idx = jnp.clip(base + off, 0, in_len - 1).astype(jnp.int32)
        taps.append(jnp.take(x, idx, axis=axis))
    return sum(t * w for t, w in zip(taps, weights))


@register_op("interpolate")
def _interp(x, *, size, method, align_corners):
    n, c = x.shape[:2]
    out_shape = (n, c) + size
    kind = {"nearest": "nearest", "bilinear": "linear",
            "linear": "linear", "trilinear": "linear",
            "bicubic": "cubic"}[method]
    if align_corners or method == "nearest":
        # nearest also needs the reference's asymmetric floor(i*in/out)
        # indexing, which jax.image.resize does not implement
        out = x
        for i, s in enumerate(size):
            out = _axis_resize(out, 2 + i, int(s), kind,
                               bool(align_corners))
        return out
    jmethod = {"nearest": "nearest", "bilinear": "linear", "bicubic": "cubic",
               "trilinear": "linear", "linear": "linear"}[method]
    return jax.image.resize(x, out_shape, method=jmethod)


def interpolate(x, size=None, scale_factor=None, mode="nearest",
                align_corners=False, align_mode=0, data_format="NCHW",
                name=None):
    if size is None:
        spatial = x.shape[2:]
        if isinstance(scale_factor, (int, float)):
            scale_factor = [scale_factor] * len(spatial)
        size = tuple(int(s * f) for s, f in zip(spatial, scale_factor))
    else:
        if isinstance(size, Tensor):
            size = size.tolist()
        size = tuple(int(s) for s in size)
    return _interp(x, size=size, method=mode, align_corners=bool(align_corners))


def upsample(x, size=None, scale_factor=None, mode="nearest",
             align_corners=False, name=None):
    return interpolate(x, size, scale_factor, mode, align_corners)


@register_op("pixel_shuffle")
def _pixel_shuffle(x, *, upscale_factor):
    n, c, h, w = x.shape
    r = upscale_factor
    x = x.reshape(n, c // (r * r), r, r, h, w)
    x = x.transpose(0, 1, 4, 2, 5, 3)
    return x.reshape(n, c // (r * r), h * r, w * r)


def pixel_shuffle(x, upscale_factor, data_format="NCHW", name=None):
    return _pixel_shuffle(x, upscale_factor=int(upscale_factor))


@register_op("label_smooth")
def _label_smooth(label, *, epsilon):
    n = label.shape[-1]
    return label * (1.0 - epsilon) + epsilon / n


def label_smooth(label, prior_dist=None, epsilon=0.1, name=None):
    return _label_smooth(label, epsilon=float(epsilon))


@register_op("temporal_shift")
def _temporal_shift(x, *, seg_num, shift_ratio):
    nt, c, h, w = x.shape
    n = nt // seg_num
    xr = x.reshape(n, seg_num, c, h, w)
    fold = int(c * shift_ratio)
    left = jnp.concatenate([xr[:, 1:, :fold], jnp.zeros_like(xr[:, :1, :fold])], axis=1)
    right = jnp.concatenate([jnp.zeros_like(xr[:, :1, fold:2 * fold]),
                             xr[:, :-1, fold:2 * fold]], axis=1)
    rest = xr[:, :, 2 * fold:]
    return jnp.concatenate([left, right, rest], axis=2).reshape(nt, c, h, w)


def temporal_shift(x, seg_num, shift_ratio=0.25, name=None):
    return _temporal_shift(x, seg_num=int(seg_num), shift_ratio=float(shift_ratio))


# ---- round-2 functional additions (reference: python/paddle/nn/
# functional/{pooling,conv,loss,vision,extension}.py) -----------------------

def _triple(v):
    if isinstance(v, (list, tuple)):
        return tuple(int(x) for x in v)
    return (int(v),) * 3


def _to_ncdhw(x, data_format):
    from . import manipulation
    if data_format == "NDHWC":
        return manipulation.transpose(x, (0, 4, 1, 2, 3))
    if data_format != "NCDHW":
        raise ValueError(f"pool3d: unknown data_format {data_format!r}")
    return x


def _from_ncdhw(x, data_format):
    from . import manipulation
    if data_format == "NDHWC":
        return manipulation.transpose(x, (0, 2, 3, 4, 1))
    return x


def _neg_min(dtype):
    """Most-negative value for max-pool padding, dtype-aware (shared by
    the 2d and 3d with-index kernels so they cannot drift)."""
    return (-jnp.inf if jnp.issubdtype(dtype, jnp.floating)
            else jnp.iinfo(dtype).min)


def _pool_windows3d(x, ksize, strides, paddings, pad_value,
                    ceil_mode=False):
    """3d counterpart of _pool_windows: yield the kd*kh*kw strided
    window slices (same slice-only building block)."""
    kd, kh, kw = ksize
    sd, sh, sw = strides
    pd, ph, pw = paddings
    d0, h0, w0 = x.shape[2:]

    def out_len(sz, k, s, p):
        if ceil_mode:
            return -(-(sz + 2 * p - k) // s) + 1
        return (sz + 2 * p - k) // s + 1

    od = out_len(d0, kd, sd, pd)
    oh = out_len(h0, kh, sh, ph)
    ow = out_len(w0, kw, sw, pw)
    need = [max(0, (o - 1) * s + k - (sz + 2 * p))
            for o, s, k, sz, p in zip((od, oh, ow), strides, ksize,
                                      (d0, h0, w0), paddings)]
    if pd or ph or pw or any(need):
        x = jnp.pad(x, ((0, 0), (0, 0), (pd, pd + need[0]),
                        (ph, ph + need[1]), (pw, pw + need[2])),
                    constant_values=pad_value)
    for i in range(kd):
        for j in range(kh):
            for k in range(kw):
                yield x[:, :, i:i + (od - 1) * sd + 1:sd,
                        j:j + (oh - 1) * sh + 1:sh,
                        k:k + (ow - 1) * sw + 1:sw]


@register_op("pool3d_max_with_index")
def _max_pool3d_with_index(x, *, ksize, strides, paddings,
                           ceil_mode=False):
    """Reference: max_pool3d_with_index (pool_with_index_op) — mask is
    the max's flat position in the input d*h*w volume."""
    kd, kh, kw = ksize
    sd, sh, sw = strides
    pd, ph, pw = paddings
    d0, h0, w0 = x.shape[2:]
    wins = jnp.stack(
        list(_pool_windows3d(x, ksize, strides, paddings,
                             _neg_min(x.dtype), ceil_mode)), axis=0)
    out = jnp.max(wins, axis=0)
    od, oh, ow = out.shape[2:]
    amax = jnp.argmax(wins, axis=0)
    di = amax // (kh * kw)
    dj = (amax // kw) % kh
    dk = amax % kw
    zd = jnp.arange(od)[:, None, None] * sd - pd + di
    zh = jnp.arange(oh)[None, :, None] * sh - ph + dj
    zw = jnp.arange(ow)[None, None, :] * sw - pw + dk
    mask = ((zd * h0 + zh) * w0 + zw).astype(jnp.int32)
    return out, mask


def max_pool3d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               return_mask=False, data_format="NCDHW", name=None):
    """Reference: pool3d_op; NDHWC handled by transposing around the
    NCDHW kernel (TPU-native layout choice: XLA re-lays-out anyway)."""
    x = _to_ncdhw(x, data_format)
    ks = _triple(kernel_size)
    st = _triple(stride) if stride is not None else ks
    pad3 = _triple(padding)
    if return_mask:
        out, mask = _max_pool3d_with_index(x, ksize=ks, strides=st,
                                           paddings=pad3,
                                           ceil_mode=bool(ceil_mode))
        return _from_ncdhw(out, data_format), _from_ncdhw(mask,
                                                          data_format)
    out = _pool3d(x, ksize=ks, strides=st, paddings=pad3, mode="max",
                  ceil_mode=bool(ceil_mode), exclusive=True,
                  divisor=None)
    return _from_ncdhw(out, data_format)


def avg_pool3d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               exclusive=True, divisor_override=None, data_format="NCDHW",
               name=None):
    x = _to_ncdhw(x, data_format)
    ks = _triple(kernel_size)
    st = _triple(stride) if stride is not None else ks
    out = _pool3d(x, ksize=ks, strides=st, paddings=_triple(padding),
                  mode="avg", ceil_mode=bool(ceil_mode),
                  exclusive=bool(exclusive),
                  divisor=None if divisor_override is None
                  else float(divisor_override))
    return _from_ncdhw(out, data_format)


@register_op("pool3d")
def _pool3d(x, *, ksize, strides, paddings, mode, ceil_mode, exclusive,
            divisor):
    kd, kh, kw = ksize
    sd, sh, sw = strides
    pd, ph, pw = paddings

    def out_len(size, k, s, p):
        if ceil_mode:
            return -(-(size + 2 * p - k) // s) + 1
        return (size + 2 * p - k) // s + 1

    d0, h0, w0 = x.shape[2:]
    od, oh, ow = (out_len(d0, kd, sd, pd), out_len(h0, kh, sh, ph),
                  out_len(w0, kw, sw, pw))
    # right-pad enough that every (possibly ceil-extended) window exists
    need = [max(0, (o - 1) * s + k - (sz + 2 * p))
            for o, s, k, sz, p in zip((od, oh, ow), strides, ksize,
                                      (d0, h0, w0), paddings)]
    pad_v = (-jnp.inf if mode == "max" else 0.0)
    if pd or ph or pw or any(need):
        x = jnp.pad(x, ((0, 0), (0, 0), (pd, pd + need[0]),
                        (ph, ph + need[1]), (pw, pw + need[2])),
                    constant_values=pad_v)
    out = None
    for i in range(kd):
        for j in range(kh):
            for k in range(kw):
                win = x[:, :, i:i + (od - 1) * sd + 1:sd,
                        j:j + (oh - 1) * sh + 1:sh,
                        k:k + (ow - 1) * sw + 1:sw]
                if out is None:
                    out = win
                elif mode == "max":
                    out = jnp.maximum(out, win)
                else:
                    out = out + win
    if mode != "avg":
        return out
    if divisor is not None:
        return out / divisor
    if exclusive and (pd or ph or pw or any(need)):
        # count only in-bounds cells per window (paddle exclusive=True);
        # counts are static -> numpy
        ones = np.zeros((1, 1, d0 + 2 * pd + need[0],
                         h0 + 2 * ph + need[1], w0 + 2 * pw + need[2]),
                        np.float32)
        ones[:, :, pd:pd + d0, ph:ph + h0, pw:pw + w0] = 1.0
        counts = np.zeros((1, 1, od, oh, ow), np.float32)
        for i in range(kd):
            for j in range(kh):
                for k in range(kw):
                    counts += ones[:, :, i:i + (od - 1) * sd + 1:sd,
                                   j:j + (oh - 1) * sh + 1:sh,
                                   k:k + (ow - 1) * sw + 1:sw]
        # padded cells contributed -inf/0; zero them out of the sum for
        # avg by re-summing with 0 pad value happened above (pad_v=0)
        return out / jnp.asarray(np.maximum(counts, 1.0), x.dtype)
    return out / (kd * kh * kw)


@register_op("adaptive_pool3d")
def _adaptive_pool3d(x, *, output_size, mode):
    n, c, d, h, w = x.shape
    od, oh, ow = output_size
    assert d % od == 0 and h % oh == 0 and w % ow == 0, \
        "adaptive 3d pooling needs divisible sizes"
    x6 = x.reshape(n, c, od, d // od, oh, h // oh, ow, w // ow)
    if mode == "max":
        return x6.max(axis=(3, 5, 7))
    return x6.mean(axis=(3, 5, 7))


def adaptive_avg_pool3d(x, output_size, data_format="NCDHW", name=None):
    return _adaptive_pool3d(x, output_size=_triple(output_size),
                            mode="avg")


@register_op("adaptive_max_pool3d_with_index")
def _adaptive_max_pool3d_with_index(x, *, output_size):
    n, c, d, h, w = x.shape
    od, oh, ow = output_size
    assert d % od == 0 and h % oh == 0 and w % ow == 0, \
        "adaptive 3d pooling needs divisible sizes"
    bd, bh, bw = d // od, h // oh, w // ow
    x6 = x.reshape(n, c, od, bd, oh, bh, ow, bw)
    blocks = x6.transpose(0, 1, 2, 4, 6, 3, 5, 7).reshape(
        n, c, od, oh, ow, bd * bh * bw)
    amax = jnp.argmax(blocks, axis=-1)
    di = amax // (bh * bw)
    dj = (amax // bw) % bh
    dk = amax % bw
    zd = jnp.arange(od)[:, None, None] * bd + di
    zh = jnp.arange(oh)[None, :, None] * bh + dj
    zw = jnp.arange(ow)[None, None, :] * bw + dk
    mask = ((zd * h + zh) * w + zw).astype(jnp.int32)
    return blocks.max(axis=-1), mask


def adaptive_max_pool3d(x, output_size, return_mask=False, name=None):
    if return_mask:
        return _adaptive_max_pool3d_with_index(
            x, output_size=_triple(output_size))
    return _adaptive_pool3d(x, output_size=_triple(output_size),
                            mode="max")


def adaptive_avg_pool1d(x, output_size, name=None):
    from . import manipulation
    x4 = manipulation.unsqueeze(x, axis=2)
    out = adaptive_avg_pool2d(x4, (1, int(output_size)))
    return manipulation.squeeze(out, axis=2)


def adaptive_max_pool1d(x, output_size, return_mask=False, name=None):
    from . import manipulation
    x4 = manipulation.unsqueeze(x, axis=2)
    out = adaptive_max_pool2d(x4, (1, int(output_size)),
                              return_mask=return_mask)
    if return_mask:
        return (manipulation.squeeze(out[0], axis=2),
                manipulation.squeeze(out[1], axis=2))
    return manipulation.squeeze(out, axis=2)


@register_op("conv_transpose_nd")
def _conv_transpose_nd(x, weight, bias, *, strides, paddings,
                       output_padding, dilations, groups, nd):
    # weight layout [in, out/groups, *k] (paddle transpose-conv
    # convention); expressed as a fractionally-strided conv exactly like
    # _conv2d_transpose: flip spatial axes, swap I/O per group,
    # lhs_dilation=stride
    spatial = tuple(range(2, 2 + nd))
    in_c, out_pg = weight.shape[0], weight.shape[1]
    ks = weight.shape[2:]
    wf = jnp.flip(weight, axis=spatial)
    wf = wf.reshape((groups, in_c // groups, out_pg) + ks)
    wf = jnp.swapaxes(wf, 1, 2).reshape(
        (groups * out_pg, in_c // groups) + ks)
    letters = "DHW"[3 - nd:]
    dn = jax.lax.conv_dimension_numbers(
        x.shape, wf.shape, ("NC" + letters, "OI" + letters,
                            "NC" + letters))
    pad = tuple(
        ((k - 1) * d - p, (k - 1) * d - p + op)
        for k, d, p, op in zip(ks, dilations, paddings, output_padding))
    out = jax.lax.conv_general_dilated(
        x, wf, window_strides=(1,) * nd, padding=pad,
        lhs_dilation=strides, rhs_dilation=dilations,
        dimension_numbers=dn, feature_group_count=groups)
    if bias is not None:
        out = out + bias.reshape((1, -1) + (1,) * nd)
    return out


def conv1d_transpose(x, weight, bias=None, stride=1, padding=0,
                     output_padding=0, groups=1, dilation=1,
                     output_size=None, data_format="NCL", name=None):
    """Reference: conv2d_transpose_op (1D variant)."""
    one = lambda v: (v if isinstance(v, int) else v[0],)  # noqa: E731
    return _conv_transpose_nd(x, weight, bias, strides=one(stride),
                              paddings=one(padding),
                              output_padding=one(output_padding),
                              dilations=one(dilation),
                              groups=int(groups), nd=1)


def conv3d_transpose(x, weight, bias=None, stride=1, padding=0,
                     output_padding=0, groups=1, dilation=1,
                     output_size=None, data_format="NCDHW", name=None):
    return _conv_transpose_nd(x, weight, bias, strides=_triple(stride),
                              paddings=_triple(padding),
                              output_padding=_triple(output_padding),
                              dilations=_triple(dilation),
                              groups=int(groups), nd=3)


def dropout3d(x, p=0.5, training=True, data_format="NCDHW", name=None):
    """Channel-wise dropout for 5-D input (reference: dropout_nd)."""
    if not training or p == 0.0:
        return x
    from ..core import rng as rng_mod
    key = rng_mod.next_key()
    return _dropout_nd(x, key, p=float(p), nd=3)


@register_op("dropout_nd")
def _dropout_nd(x, key, *, p, nd):
    keep = 1.0 - p
    mask_shape = x.shape[:2] + (1,) * nd
    mask = jax.random.bernoulli(key, keep, mask_shape)
    return jnp.where(mask, x / keep, jnp.zeros_like(x))


def alpha_dropout(x, p=0.5, training=True, name=None):
    """SELU-preserving dropout (reference: alpha_dropout in
    nn/functional/common.py): keeps mean/variance of SELU activations."""
    if not training or p == 0.0:
        return x
    from ..core import rng as rng_mod
    key = rng_mod.next_key()
    return _alpha_dropout(x, key, p=float(p))


@register_op("alpha_dropout_op")
def _alpha_dropout(x, key, *, p):
    alpha = 1.6732632423543772
    scale = 1.0507009873554805
    alpha_p = -alpha * scale
    keep = 1.0 - p
    a = (keep + alpha_p ** 2 * keep * (1 - keep)) ** -0.5
    b = -a * alpha_p * (1 - keep)
    mask = jax.random.bernoulli(key, keep, x.shape)
    return a * jnp.where(mask, x, jnp.full_like(x, alpha_p)) + b


def maxout(x, groups, axis=1, name=None):
    """Reference: maxout_op — max over `groups` consecutive channels."""
    return _maxout(x, groups=int(groups), axis=int(axis))


@register_op("maxout_op")
def _maxout(x, *, groups, axis):
    shape = list(x.shape)
    c = shape[axis]
    assert c % groups == 0, "channels must divide groups"
    new = shape[:axis] + [c // groups, groups] + shape[axis + 1:]
    return jnp.max(x.reshape(new), axis=axis + 1)


def bilinear(x1, x2, weight, bias=None, name=None):
    """Reference: bilinear_tensor_product_op: out[b,k] =
    x1[b,:] @ W[k] @ x2[b,:] + bias[k]."""
    return _bilinear(x1, x2, weight, bias)


@register_op("bilinear_op")
def _bilinear(x1, x2, weight, bias):
    out = jnp.einsum("bi,kij,bj->bk", x1, weight, x2)
    if bias is not None:
        out = out + bias
    return out


# -- losses -----------------------------------------------------------------

def log_loss(input, label, epsilon=1e-4, name=None):  # noqa: A002
    """Reference: log_loss_op."""
    return _log_loss(input, label, epsilon=float(epsilon))


@register_op("log_loss_op")
def _log_loss(x, label, *, epsilon):
    return (-label * jnp.log(x + epsilon)
            - (1.0 - label) * jnp.log(1.0 - x + epsilon))


def dice_loss(input, label, epsilon=1e-5, name=None):  # noqa: A002
    """Reference: nn/functional/loss.py dice_loss (segmentation)."""
    return _dice_loss(input, label, epsilon=float(epsilon))


@register_op("dice_loss_op")
def _dice_loss(x, label, *, epsilon):
    lab = label
    if lab.ndim == x.ndim:
        lab = jnp.squeeze(lab, axis=-1)
    oh = jax.nn.one_hot(lab, x.shape[-1], dtype=x.dtype)
    reduce_dims = tuple(range(1, x.ndim))
    inter = jnp.sum(x * oh, axis=reduce_dims)
    union = jnp.sum(x, axis=reduce_dims) + jnp.sum(oh, axis=reduce_dims)
    dice = (2.0 * inter + epsilon) / (union + epsilon)
    return jnp.mean(1.0 - dice)


def npair_loss(anchor, positive, labels, l2_reg=0.002):
    """Reference: nn/functional/loss.py npair_loss."""
    return _npair_loss(anchor, positive, labels, l2_reg=float(l2_reg))


@register_op("npair_loss_op")
def _npair_loss(anchor, positive, labels, *, l2_reg):
    lab = labels.reshape(-1, 1)
    same = (lab == lab.T).astype(anchor.dtype)
    same = same / jnp.maximum(jnp.sum(same, axis=1, keepdims=True), 1e-12)
    logits = anchor @ positive.T
    logp = jax.nn.log_softmax(logits, axis=1)
    xent = -jnp.sum(same * logp, axis=1).mean()
    reg = l2_reg * (jnp.sum(anchor * anchor)
                    + jnp.sum(positive * positive)) / anchor.shape[0]
    return xent + reg


def sigmoid_focal_loss(logit, label, normalizer=None, alpha=0.25,
                       gamma=2.0, reduction="sum", name=None):
    """Reference: sigmoid_focal_loss_op (RetinaNet loss)."""
    out = _sigmoid_focal_loss(logit, label, alpha=float(alpha),
                              gamma=float(gamma))
    from . import reduction as r, math as m
    if normalizer is not None:
        out = m.divide(out, normalizer)
    if reduction == "sum":
        return r.sum(out)
    if reduction == "mean":
        return r.mean(out)
    return out


@register_op("sigmoid_focal_loss_op")
def _sigmoid_focal_loss(logit, label, *, alpha, gamma):
    p = jax.nn.sigmoid(logit)
    ce = -(label * jax.nn.log_sigmoid(logit)
           + (1 - label) * jax.nn.log_sigmoid(-logit))
    p_t = p * label + (1 - p) * (1 - label)
    a_t = alpha * label + (1 - alpha) * (1 - label)
    return a_t * ((1 - p_t) ** gamma) * ce


def ctc_loss(log_probs, labels, input_lengths, label_lengths, blank=0,
             reduction="mean", norm_by_times=False):
    """Reference: warpctc_op / paddle.nn.functional.ctc_loss.
    log_probs: [T, B, C] (paddle layout); labels: [B, L] int32."""
    return _ctc(log_probs, labels, input_lengths, label_lengths,
                blank=int(blank), reduction=reduction)


def _ctc(log_probs, labels, input_lengths, label_lengths, *, blank,
         reduction):
    import optax
    from ..core.tensor import Tensor
    lp = log_probs.value if isinstance(log_probs, Tensor) else log_probs
    lab = labels.value if isinstance(labels, Tensor) else labels
    il = (input_lengths.value if isinstance(input_lengths, Tensor)
          else input_lengths)
    ll = (label_lengths.value if isinstance(label_lengths, Tensor)
          else label_lengths)
    out = _ctc_op(Tensor(lp), Tensor(lab), Tensor(il), Tensor(ll),
                  blank=blank)
    from . import reduction as r
    if reduction == "mean":
        from . import math as m
        # paddle normalizes each loss by its label length, then means
        norm = m.divide(out, m.cast(Tensor(jnp.asarray(ll)), out.dtype))
        return r.mean(norm)
    if reduction == "sum":
        return r.sum(out)
    return out


@register_op("warpctc")
def _ctc_op(log_probs, labels, input_lengths, label_lengths, *, blank):
    import optax
    lp = jnp.transpose(log_probs, (1, 0, 2))  # [B, T, C]
    T = lp.shape[1]
    L = labels.shape[1]
    t_idx = jnp.arange(T)[None, :]
    logit_pad = (t_idx >= input_lengths[:, None]).astype(lp.dtype)
    l_idx = jnp.arange(L)[None, :]
    label_pad = (l_idx >= label_lengths[:, None]).astype(lp.dtype)
    return optax.ctc_loss(lp, logit_pad, labels.astype(jnp.int32),
                          label_pad, blank_id=blank)


def hsigmoid_loss(input, label, num_classes, weight, bias=None,  # noqa: A002
                  path_table=None, path_code=None, is_sparse=False,
                  name=None):
    """Reference: hierarchical_sigmoid_op (default complete binary tree;
    custom path_table/path_code not supported — raise clearly)."""
    if path_table is not None or path_code is not None:
        raise NotImplementedError(
            "custom-tree hsigmoid (path_table/path_code) is not supported")
    return _hsigmoid(input, label, weight, bias,
                     num_classes=int(num_classes))


@register_op("hsigmoid_op")
def _hsigmoid(x, label, weight, bias, *, num_classes):
    # complete-binary-tree codes: internal nodes = num_classes - 1.
    # class c's path visits nodes derived from (c + num_classes) >> k.
    code_len = int(np.ceil(np.log2(num_classes)))
    lab = label.reshape(-1)
    c = lab + num_classes
    losses = jnp.zeros(lab.shape, x.dtype)
    for k in range(code_len, 0, -1):
        node = c >> k
        bit = ((c >> (k - 1)) & 1).astype(x.dtype)
        active = (node >= 1) & (node - 1 < num_classes - 1)
        nidx = jnp.clip(node - 1, 0, num_classes - 2)
        w_row = jnp.take(weight, nidx, axis=0)
        logit = jnp.sum(x * w_row, axis=-1)
        if bias is not None:
            logit = logit + jnp.take(bias.reshape(-1), nidx)
        # bit==1 -> right child: sigmoid target 1
        ce = -(bit * jax.nn.log_sigmoid(logit)
               + (1 - bit) * jax.nn.log_sigmoid(-logit))
        losses = losses + jnp.where(active, ce, 0.0)
    return losses.reshape(label.shape[:1] + (1,))


# -- vision sampling --------------------------------------------------------

def affine_grid(theta, out_shape, align_corners=True, name=None):
    """Reference: affine_grid_op — sampling grid [N, H, W, 2] from 2x3
    affine matrices."""
    sh = [int(s) for s in (out_shape.numpy().tolist()
                           if hasattr(out_shape, "numpy") else out_shape)]
    return _affine_grid(theta, out_shape=tuple(sh),
                        align_corners=bool(align_corners))


@register_op("affine_grid_op")
def _affine_grid(theta, *, out_shape, align_corners):
    n, c, h, w = out_shape
    if align_corners:
        ys = jnp.linspace(-1.0, 1.0, h)
        xs = jnp.linspace(-1.0, 1.0, w)
    else:
        ys = (jnp.arange(h) + 0.5) * 2.0 / h - 1.0
        xs = (jnp.arange(w) + 0.5) * 2.0 / w - 1.0
    gy, gx = jnp.meshgrid(ys, xs, indexing="ij")
    ones = jnp.ones_like(gx)
    base = jnp.stack([gx, gy, ones], axis=-1).reshape(1, h * w, 3)
    base = jnp.broadcast_to(base, (n, h * w, 3)).astype(theta.dtype)
    out = jnp.einsum("nhk,nck->nhc", base, theta)  # [N, H*W, 2]
    return out.reshape(n, h, w, 2)


def grid_sample(x, grid, mode="bilinear", padding_mode="zeros",
                align_corners=True, name=None):
    """Reference: grid_sampler_op — bilinear/nearest sampling of x
    [N,C,H,W] at grid [N,Ho,Wo,2] normalized coords."""
    return _grid_sample(x, grid, mode=mode, padding_mode=padding_mode,
                        align_corners=bool(align_corners))


@register_op("grid_sampler")
def _grid_sample(x, grid, *, mode, padding_mode, align_corners):
    n, c, h, w = x.shape
    gx = grid[..., 0]
    gy = grid[..., 1]
    if align_corners:
        fx = (gx + 1.0) * (w - 1) / 2.0
        fy = (gy + 1.0) * (h - 1) / 2.0
    else:
        fx = ((gx + 1.0) * w - 1.0) / 2.0
        fy = ((gy + 1.0) * h - 1.0) / 2.0

    nd_mode = {"zeros": "constant", "border": "nearest",
               "reflection": "mirror"}.get(padding_mode)
    if nd_mode is None:
        raise ValueError(f"unknown padding_mode {padding_mode!r}")

    def sample_one(img, cx, cy):
        # img [C,H,W]; cx/cy [Ho,Wo]
        coords = jnp.stack([cy.reshape(-1), cx.reshape(-1)], axis=0)
        order = 1 if mode == "bilinear" else 0
        out = jax.vmap(lambda ch: jax.scipy.ndimage.map_coordinates(
            ch, list(coords), order=order, mode=nd_mode, cval=0.0))(img)
        return out.reshape(img.shape[0], *cx.shape)

    return jax.vmap(sample_one)(x, fx, fy)


def gather_tree(ids, parents):
    """Reference: gather_tree_op — back-trace beam-search parent pointers
    into full sequences. ids/parents: [T, B, beam]."""
    return _gather_tree(ids, parents)


@register_op("gather_tree_op", differentiable=False)
def _gather_tree(ids, parents):
    T = ids.shape[0]

    def step(beams, t):
        # beams: current beam index per [B, beam]
        idx = T - 1 - t
        out = jnp.take_along_axis(ids[idx], beams, axis=-1)
        beams = jnp.take_along_axis(parents[idx], beams, axis=-1)
        return beams, out

    init = jnp.broadcast_to(jnp.arange(ids.shape[2]), ids.shape[1:])
    _, rev = jax.lax.scan(step, init, jnp.arange(T))
    return rev[::-1]


# -- inplace activation variants -------------------------------------------

def relu_(x, name=None):
    x.value = jax.nn.relu(x.value)
    return x


def elu_(x, alpha=1.0, name=None):
    x.value = jax.nn.elu(x.value, alpha)
    return x


def softmax_(x, axis=-1, name=None):
    x.value = jax.nn.softmax(x.value, axis=axis)
    return x
