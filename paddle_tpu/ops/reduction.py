"""Reduction ops.

Reference parity: paddle/fluid/operators/reduce_ops/ and
python/paddle/tensor/math.py sum/mean/... + stat.py std/var/median.
"""
import jax
import jax.numpy as jnp

from ..core.dispatch import register_op
from ..core import dtype as dtype_mod


def _norm_axis(axis):
    if axis is None:
        return None
    if isinstance(axis, (list, tuple)):
        return tuple(int(a) for a in axis)
    return int(axis)


def _make_reduce(name, jfn, differentiable=True):
    @register_op(name, differentiable=differentiable)
    def _op(x, *, axis, keepdim):
        return jfn(x, axis=axis, keepdims=keepdim)

    def api(x, axis=None, keepdim=False, name=None, dtype=None):
        out = _op(x, axis=_norm_axis(axis), keepdim=bool(keepdim))
        if dtype is not None:
            from . import math as math_ops
            out = math_ops.cast(out, dtype)
        return out
    api.__name__ = name
    return api


sum = _make_reduce("reduce_sum", jnp.sum)  # noqa: A001
mean = _make_reduce("reduce_mean", jnp.mean)
max = _make_reduce("reduce_max", jnp.max)  # noqa: A001
min = _make_reduce("reduce_min", jnp.min)  # noqa: A001
prod = _make_reduce("reduce_prod", jnp.prod)
all = _make_reduce("reduce_all", jnp.all, differentiable=False)  # noqa: A001
any = _make_reduce("reduce_any", jnp.any, differentiable=False)  # noqa: A001
amax = max
amin = min
nansum = _make_reduce("reduce_nansum", jnp.nansum)
nanmean = _make_reduce("reduce_nanmean", jnp.nanmean)


@register_op("reduce_std")
def _std(x, *, axis, keepdim, unbiased):
    return jnp.std(x, axis=axis, keepdims=keepdim, ddof=1 if unbiased else 0)


def std(x, axis=None, unbiased=True, keepdim=False, name=None):
    return _std(x, axis=_norm_axis(axis), keepdim=bool(keepdim),
                unbiased=bool(unbiased))


@register_op("reduce_var")
def _var(x, *, axis, keepdim, unbiased):
    return jnp.var(x, axis=axis, keepdims=keepdim, ddof=1 if unbiased else 0)


def var(x, axis=None, unbiased=True, keepdim=False, name=None):
    return _var(x, axis=_norm_axis(axis), keepdim=bool(keepdim),
                unbiased=bool(unbiased))


@register_op("median")
def _median(x, *, axis, keepdim):
    return jnp.median(x, axis=axis, keepdims=keepdim)


def median(x, axis=None, keepdim=False, name=None):
    return _median(x, axis=_norm_axis(axis), keepdim=bool(keepdim))


@register_op("quantile")
def _quantile(x, *, q, axis, keepdim):
    return jnp.quantile(x, jnp.asarray(q), axis=axis, keepdims=keepdim)


def quantile(x, q, axis=None, keepdim=False):
    return _quantile(x, q=float(q) if not isinstance(q, (list, tuple)) else tuple(q),
                     axis=_norm_axis(axis), keepdim=bool(keepdim))


@register_op("logsumexp")
def _logsumexp(x, *, axis, keepdim):
    return jax.scipy.special.logsumexp(x, axis=axis, keepdims=keepdim)


def logsumexp(x, axis=None, keepdim=False, name=None):
    return _logsumexp(x, axis=_norm_axis(axis), keepdim=bool(keepdim))


@register_op("count_nonzero", differentiable=False)
def _count_nonzero(x, *, axis, keepdim):
    return jnp.count_nonzero(x, axis=axis, keepdims=keepdim)


def count_nonzero(x, axis=None, keepdim=False, name=None):
    return _count_nonzero(x, axis=_norm_axis(axis), keepdim=bool(keepdim))


@register_op("p_norm")
def _p_norm(x, *, p, axis, keepdim):
    if p == float("inf"):
        return jnp.max(jnp.abs(x), axis=axis, keepdims=keepdim)
    if p == float("-inf"):
        return jnp.min(jnp.abs(x), axis=axis, keepdims=keepdim)
    return jnp.sum(jnp.abs(x) ** p, axis=axis, keepdims=keepdim) ** (1.0 / p)


@register_op("frobenius_norm")
def _fro_norm(x, *, axis, keepdim):
    return jnp.sqrt(jnp.sum(jnp.square(x), axis=axis, keepdims=keepdim))


def norm(x, p="fro", axis=None, keepdim=False, name=None):
    """paddle.linalg.norm subset: fro, p-norms along axis."""
    if p == "fro":
        ax = _norm_axis(axis)
        if isinstance(ax, int):
            ax = (ax,)
        return _fro_norm(x, axis=ax, keepdim=bool(keepdim))
    return _p_norm(x, p=float(p), axis=_norm_axis(axis), keepdim=bool(keepdim))


def dist(x, y, p=2.0):
    from . import math as math_ops
    return norm(math_ops.subtract(x, y), p=float(p))


@register_op("nanmedian_op", differentiable=False)
def _nanmedian(x, *, axis, keepdim):
    return jnp.nanmedian(x, axis=axis, keepdims=keepdim)


def nanmedian(x, axis=None, keepdim=False, name=None):
    ax = tuple(axis) if isinstance(axis, (list, tuple)) \
        else (None if axis is None else int(axis))
    return _nanmedian(x, axis=ax, keepdim=bool(keepdim))


@register_op("nanquantile_op", differentiable=False)
def _nanquantile(x, *, q, axis, keepdim):
    return jnp.nanquantile(x, jnp.asarray(q), axis=axis, keepdims=keepdim)


def nanquantile(x, q, axis=None, keepdim=False, name=None):
    ax = None if axis is None else int(axis)
    return _nanquantile(x, q=tuple(q) if isinstance(q, (list, tuple))
                        else float(q), axis=ax, keepdim=bool(keepdim))
