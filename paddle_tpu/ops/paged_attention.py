"""Pallas TPU paged decode-attention kernel.

Single-token decode attention computed DIRECTLY over the paged pool
layout (vLLM's PagedAttention idea, SOSP'23, done TPU-natively): q
``[S, nh, hd]``, pooled ``k_cache``/``v_cache``
``[num_blocks, nh, BS, hd]``, fixed-shape ``block_tables [S, MB]``,
per-slot ``lengths``. Each slot's physical blocks stream through VMEM
one at a time under an online softmax — the ``[S, nh, MB*BS, hd]``
gathered view the XLA composition (``ops.attention.
cached_paged_attention``) materializes is never built, which deletes
the ~3x gather traffic the roofline model prices as
``PAGED_GATHER_FACTOR``.

How the table drives the DMA schedule: grid ``(S, MB)`` with
``PrefetchScalarGridSpec(num_scalar_prefetch=2)`` — ``block_tables``
and ``lengths`` arrive ahead of the kernel body as scalar-prefetch
refs, and the K/V BlockSpec index maps read ``bt_ref[s, ...]`` to
return PHYSICAL block ids, so Pallas' pipelining fetches exactly the
blocks the table names. The index map clamps the logical block index
to the slot's last LIVE block (``(lengths[s]-1) // BS``): grid steps
beyond the live length re-present the previous block index, and
Pallas elides the re-DMA for an unchanged block — the kernel never
over-reads past a slot's live length, the fixed-shape over-read the
roofline's ``paged_pallas`` layout (gather factor 1.0) models away.

In-kernel masking mirrors the fallback exactly: key positions
``>= lengths[s]`` (trash-block padding rows, a recycled slot's stale
rows, the tail of a partially-filled block) get ``-1e30`` before the
f32 online softmax, so they carry exactly-zero weight. Scores and the
output accumulator are f32 (the ``_dot_f32`` discipline); scores are
computed as a VPU multiply-reduce over ``hd`` — per (slot, head) the
contraction is ``[1, hd] x [hd, BS]``, far too skinny to feed the MXU,
and the whole op is HBM-bound anyway.

Gating follows the fused-CE playbook: ``PADDLE_PAGED_ATTN=1`` env
opt-in (or the ``ServingConfig(paged_attn=...)`` knob), a
``kernel_viable`` shape/dtype/backend guard, and interpret mode on CPU
(tests flip ``_FORCE_INTERPRET``) so tier-1 exercises the real kernel
while the XLA composition stays the default measured fallback.
"""
import functools
import os

import jax
import jax.numpy as jnp

from .pallas_compat import trace_32bit as _trace_32bit

# tests flip this to run the kernel in interpret mode on CPU
_FORCE_INTERPRET = [False]


def _interpret():
    return _FORCE_INTERPRET[0]


def kernel_requested(override=None):
    """The gate: ``ServingConfig(paged_attn=...)`` when set, else the
    PADDLE_PAGED_ATTN env var. Default OFF — the XLA gather
    composition stays the measured fallback until the kernel is
    explicitly enabled (mirroring PADDLE_FUSED_CE)."""
    if override is not None:
        return bool(override)
    return os.environ.get("PADDLE_PAGED_ATTN", "0") == "1"


def kernel_viable(num_heads, head_dim, block_size, dtype):
    """Shape/dtype/backend guard (the ``_use_pallas`` discipline).
    Static facts only, so the engine can resolve the active decode
    layout once at build time and bind it to the roofline."""
    dtype = jnp.dtype(dtype)
    if dtype not in (jnp.dtype(jnp.float32), jnp.dtype(jnp.bfloat16),
                     jnp.dtype(jnp.float16)):
        return False  # f64 cannot lower on Mosaic
    if _FORCE_INTERPRET[0]:
        return True   # interpret mode handles any shape
    if jax.default_backend() == "cpu":
        return False
    # Mosaic wants the K/V block's sublane dim (BS) tiling-aligned;
    # nh and hd ride in full so they only need the lane minimum
    sub = 8 if dtype == jnp.dtype(jnp.float32) else 16
    return block_size % sub == 0 and head_dim % 8 == 0


def use_paged_kernel(q, k_cache):
    """Trace-time guard over the actual operands (programs.py calls
    this on the traced q/k so a dtype surprise falls back cleanly)."""
    _, nh, hd = q.shape
    return kernel_viable(nh, hd, k_cache.shape[2], q.dtype)


def _paged_decode_kernel(bt_ref, len_ref, q_ref, k_ref, v_ref, o_ref,
                         acc_ref, m_ref, l_ref, *, block_size,
                         max_blocks):
    """Grid (S, MB), MB innermost: one slot's blocks arrive
    sequentially, so the online-softmax state (acc, m, l) lives in
    VMEM scratch across the inner steps and the o block is revisited
    and written once at the last step — the flash-forward idiom, per
    slot instead of per query-block."""
    from jax.experimental import pallas as pl
    si = pl.program_id(0)
    bi = pl.program_id(1)

    @pl.when(bi == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, -1e30)
        l_ref[...] = jnp.zeros_like(l_ref)

    length = len_ref[si]

    def _compute():
        q = q_ref[...]   # [nh, hd]
        k = k_ref[...]   # [nh, BS, hd]
        v = v_ref[...]
        hd = q.shape[-1]
        # scores [nh, BS] in f32; same scale and mask value as the
        # fallback so masked softmax terms agree exactly
        s = jnp.sum(q[:, None, :].astype(jnp.float32)
                    * k.astype(jnp.float32), axis=-1)
        s = s / jnp.sqrt(jnp.float32(hd))
        kpos = bi * jnp.int32(block_size) + jax.lax.broadcasted_iota(
            jnp.int32, s.shape, 1)
        s = jnp.where(kpos < length, s, jnp.float32(-1e30))
        m_prev = m_ref[...]          # [nh, 1]
        l_prev = l_ref[...]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m_prev - m_new)
        l_ref[...] = alpha * l_prev + jnp.sum(p, axis=1, keepdims=True)
        m_ref[...] = m_new
        pv = jnp.sum(p[:, :, None] * v.astype(jnp.float32), axis=1)
        acc_ref[...] = acc_ref[...] * alpha + pv

    # blocks entirely beyond the live length contribute zero weight:
    # skip the math (their DMA is already elided by the index-map
    # clamp re-presenting the previous block)
    pl.when(bi * jnp.int32(block_size) < length)(_compute)

    @pl.when(bi == max_blocks - 1)
    def _store():
        # l >= 1 whenever any block computed (the max's own exp term);
        # the floor only guards a length<=0 slot, whose output is
        # as-unused as the fallback's uniform-over-garbage row
        l = jnp.maximum(l_ref[...], jnp.float32(1e-37))
        o_ref[...] = (acc_ref[...] / l).astype(o_ref.dtype)


def _paged_decode_32(q, k_cache, v_cache, block_tables, lengths):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu
    S, nh, hd = q.shape
    BS = k_cache.shape[2]
    MB = block_tables.shape[1]
    block_tables = block_tables.astype(jnp.int32)
    lengths = lengths.astype(jnp.int32)

    def q_index(si, bi, bt_ref, len_ref):
        return (si, 0, 0)

    def kv_index(si, bi, bt_ref, len_ref):
        # physical block id straight from the prefetched table; clamp
        # to the slot's last live block so beyond-length grid steps
        # repeat an index and their DMA is elided (no over-read)
        last = jnp.minimum(jnp.maximum(len_ref[si] - 1, 0)
                           // jnp.int32(BS), MB - 1)
        return (bt_ref[si, jnp.minimum(bi, last)], 0, 0, 0)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(S, MB),
        in_specs=[
            pl.BlockSpec((None, nh, hd), q_index),
            pl.BlockSpec((None, nh, BS, hd), kv_index),
            pl.BlockSpec((None, nh, BS, hd), kv_index),
        ],
        out_specs=pl.BlockSpec((None, nh, hd), q_index),
        scratch_shapes=[
            pltpu.VMEM((nh, hd), jnp.float32),
            pltpu.VMEM((nh, 1), jnp.float32),
            pltpu.VMEM((nh, 1), jnp.float32),
        ],
    )
    kernel = functools.partial(_paged_decode_kernel, block_size=BS,
                               max_blocks=MB)
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((S, nh, hd), q.dtype),
        interpret=_interpret(),
    )(block_tables, lengths, q, k_cache, v_cache)


def paged_decode_attention(q, k_cache, v_cache, block_tables, lengths):
    """Drop-in for ``ops.attention.cached_paged_attention`` (same
    signature, same semantics) reading K/V blocks in place. Callers
    check ``use_paged_kernel`` first; ``cached_paged_attention`` is
    the bit-exact-fallback parity oracle."""
    # x64 guard shared by every Pallas entry point (pallas_compat)
    return _trace_32bit(_paged_decode_32)(q, k_cache, v_cache,
                                          block_tables, lengths)
