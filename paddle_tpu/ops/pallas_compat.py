"""Shared Pallas/Mosaic compatibility helpers.

The framework enables jax_enable_x64 globally (paddle int64/float64
dtype semantics, core/__init__.py); inside Pallas kernels and their
BlockSpec index maps python literals would then become i64/f64, which
Mosaic cannot lower ("failed to legalize operation 'func.return'",
observed on the real chip). Every Pallas entry point traces in 32-bit
mode via this decorator.
"""
import functools

import jax

try:  # the jax.enable_x64 alias was removed from newer jax releases
    _enable_x64 = jax.enable_x64
except AttributeError:
    from jax.experimental import enable_x64 as _enable_x64


def trace_32bit(fn):
    """Run `fn` (a pallas_call builder) with x64 disabled."""
    @functools.wraps(fn)
    def wrapper(*args, **kwargs):
        with _enable_x64(False):
            return fn(*args, **kwargs)
    return wrapper
