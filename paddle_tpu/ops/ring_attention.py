"""Sequence/context parallelism: ring attention and Ulysses all-to-all.

Greenfield (SURVEY §5: the reference has NO sequence-parallel support —
`ring_attention|ulysses|context_parallel` absent from its tree). Design:

- ring_attention: shard_map over the 'sp' mesh axis. Each device holds
  q/k/v chunks [B, H, S/sp, D]. K/V blocks rotate around the ring with
  lax.ppermute while each device accumulates online-softmax partial
  attention of its local Q against every block — compute overlaps the
  ICI transfer (the Ring Attention construction, Liu et al. 2023).
  HBM footprint per chip stays O(S/sp), enabling sequences sp x longer.
- ulysses_attention: all_to_all re-shard seq->heads, full-sequence
  attention per head subset, all_to_all back (DeepSpeed Ulysses).
  Cheaper comms for moderate S, needs num_heads % sp == 0.

Both are differentiable (built from jax primitives; autodiff of ppermute /
all_to_all yields the reversed collectives).
"""
import functools
import math

import jax
from ..core.jax_compat import shard_map as _shard_map
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P


def _online_block(q, k, v, acc, m_prev, l_prev, mask=None):
    """One online-softmax accumulation step. q:[B,H,Sq,D] k/v:[B,H,Sk,D],
    acc:[B,H,Sq,D] accumulates unnormalized output; m,l:[B,H,Sq]."""
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k).astype(jnp.float32)
    if mask is not None:
        s = jnp.where(mask, s, -1e30)
    m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1))
    p = jnp.exp(s - m_new[..., None])
    alpha = jnp.exp(m_prev - m_new)
    l_new = alpha * l_prev + jnp.sum(p, axis=-1)
    acc_new = acc * alpha[..., None] + jnp.einsum(
        "bhqk,bhkd->bhqd", p, v.astype(jnp.float32))
    return acc_new, m_new, l_new


def _ring_attention_sharded(q, k, v, *, axis_name, sp, scale, causal):
    """Per-device body under shard_map. q/k/v: local [B, H, S/sp, D]."""
    my = jax.lax.axis_index(axis_name)
    q = q.astype(jnp.float32) * scale
    b, h, sq, d = q.shape
    acc0 = jnp.zeros((b, h, sq, d), jnp.float32)
    m0 = jnp.full((b, h, sq), -1e30, jnp.float32)
    l0 = jnp.zeros((b, h, sq), jnp.float32)
    perm = [(i, (i + 1) % sp) for i in range(sp)]

    def body(step, carry):
        acc, m, l, kb, vb = carry
        # block currently held came from device (my - step) mod sp
        src = (my - step) % sp
        if causal:
            # query position i (global: my*sq + i) attends key j
            # (global: src*sq + j) iff qpos >= kpos
            qpos = my * sq + jax.lax.broadcasted_iota(jnp.int32, (sq, sq), 0)
            kpos = src * sq + jax.lax.broadcasted_iota(jnp.int32, (sq, sq), 1)
            mask = (qpos >= kpos)[None, None]
        else:
            mask = None
        acc, m, l = _online_block(q, kb, vb, acc, m, l, mask)
        kb = jax.lax.ppermute(kb, axis_name, perm)
        vb = jax.lax.ppermute(vb, axis_name, perm)
        return acc, m, l, kb, vb

    acc, m, l, _, _ = jax.lax.fori_loop(0, sp, body, (acc0, m0, l0, k, v))
    return (acc / l[..., None]).astype(v.dtype)


def _bh_specs(mesh, q, axis_name, heads_groups=1):
    """Batch/head placements for the sp shard_map: keep the batch on
    'dp' and the heads on 'mp' when the mesh has those axes (Megatron-SP
    composition — attention is head- and batch-independent, so each
    dp x mp shard runs its own ring on its slice; an unmentioned axis
    would force an all-gather instead). heads_groups: extra divisibility
    the body needs on the per-mp-shard head count (Ulysses sp groups).
    axis_name (the ring/a2a axis) must not repeat in the spec, so a ring
    run over 'dp' or 'mp' itself keeps that dim replicated as before."""
    b, h = q.shape[0], q.shape[1]
    bspec = "dp" if ("dp" in mesh.axis_names and axis_name != "dp"
                     and b % int(mesh.shape["dp"]) == 0) else None
    mp = int(mesh.shape.get("mp", 1))
    hspec = "mp" if (mp > 1 and axis_name != "mp" and h % mp == 0
                     and (h // mp) % heads_groups == 0) else None
    return bspec, hspec


def ring_attention(q, k, v, mesh, axis_name="sp", causal=True, scale=None):
    """q/k/v: GLOBAL [B, H, S, D] arrays (sharded or not) — runs the ring
    over mesh[axis_name], sequence dimension sharded sp-ways; batch and
    heads stay dp-/mp-sharded when those axes exist."""
    sp = int(mesh.shape[axis_name])
    sc = scale if scale is not None else 1.0 / math.sqrt(q.shape[-1])
    if sp == 1:
        from .attention import _flash_attention_core
        return _flash_attention_core(q, k, v, sc, causal)
    body = functools.partial(_ring_attention_sharded, axis_name=axis_name,
                             sp=sp, scale=sc, causal=causal)
    bspec, hspec = _bh_specs(mesh, q, axis_name)
    spec = P(bspec, hspec, axis_name, None)
    fn = _shard_map(body, mesh=mesh, in_specs=(spec, spec, spec),
                       out_specs=spec, check_vma=False)
    return fn(q, k, v)


def _ulysses_sharded(q, k, v, *, axis_name, sp, scale, causal):
    """Per-device: [B, H, S/sp, D] -> all_to_all -> [B, H/sp, S, D] ->
    attention -> all_to_all back."""
    def seq_to_heads(x):
        # split heads into sp groups, exchange so each device gets full seq
        # for its head group: all_to_all over the head axis
        return jax.lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2,
                                  tiled=True)

    def heads_to_seq(x):
        return jax.lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1,
                                  tiled=True)

    qh = seq_to_heads(q)
    kh = seq_to_heads(k)
    vh = seq_to_heads(v)
    # full-sequence attention per head group through the flash core: at
    # long S the dense [S, S] score matrix this used to build is exactly
    # what Ulysses + flash avoids (the core self-falls-back to the dense
    # composition for small shapes / CPU)
    from .attention import _flash_attention_core
    out = _flash_attention_core(qh, kh, vh, scale, causal)
    return heads_to_seq(out)


def ulysses_attention(q, k, v, mesh, axis_name="sp", causal=True, scale=None):
    sp = int(mesh.shape[axis_name])
    sc = scale if scale is not None else 1.0 / math.sqrt(q.shape[-1])
    if sp == 1:
        from .attention import _flash_attention_core
        return _flash_attention_core(q, k, v, sc, causal)
    assert q.shape[1] % sp == 0, "num_heads must divide sp for Ulysses"
    body = functools.partial(_ulysses_sharded, axis_name=axis_name, sp=sp,
                             scale=sc, causal=causal)
    bspec, hspec = _bh_specs(mesh, q, axis_name, heads_groups=sp)
    spec = P(bspec, hspec, axis_name, None)
    fn = _shard_map(body, mesh=mesh, in_specs=(spec, spec, spec),
                       out_specs=spec, check_vma=False)
    return fn(q, k, v)
