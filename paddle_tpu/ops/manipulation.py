"""Shape / layout manipulation ops.

Reference parity: python/paddle/tensor/manipulation.py (reshape, transpose,
concat, split, gather, scatter, ...) over the reference C++ ops
(reshape_op, transpose_op, concat_op, gather_op, ...).
"""
import numpy as np
import jax
import jax.numpy as jnp

from ..core.dispatch import register_op
from ..core.tensor import Tensor


def _shape_tuple(shape):
    if isinstance(shape, Tensor):
        shape = shape.tolist()
    return tuple(int(s) for s in shape)


@register_op("reshape")
def _reshape(x, *, shape):
    return jnp.reshape(x, shape)


def _resolve_reshape(x, shape):
    """Reference reshape_op semantics: a 0 entry copies the
    corresponding input dim (position-wise); -1 infers as usual."""
    tgt = list(_shape_tuple(shape))
    in_shape = tuple(int(s) for s in x.shape)
    for i, d in enumerate(tgt):
        if d == 0:
            if i >= len(in_shape):
                from ..core.errors import InvalidArgumentError
                raise InvalidArgumentError(
                    f"reshape: 0 at position {i} has no corresponding "
                    f"input dim (input rank {len(in_shape)})")
            tgt[i] = in_shape[i]
    return tuple(tgt)


def reshape(x, shape, name=None):
    return _reshape(x, shape=_resolve_reshape(x, shape))


def reshape_(x, shape, name=None):
    x.value = jnp.reshape(x.value, _resolve_reshape(x, shape))
    return x


@register_op("transpose2")
def _transpose(x, *, perm):
    return jnp.transpose(x, perm)


def transpose(x, perm, name=None):
    return _transpose(x, perm=tuple(int(p) for p in perm))


@register_op("t_op")
def _t(x):
    return x.T


def t(x, name=None):
    return _t(x)


@register_op("flatten2")
def _flatten(x, *, start_axis, stop_axis):
    shape = x.shape
    nd = x.ndim
    sa = start_axis % nd if nd else 0
    so = stop_axis % nd if nd else 0
    new_shape = shape[:sa] + (-1,) + shape[so + 1:]
    return jnp.reshape(x, new_shape)


def flatten(x, start_axis=0, stop_axis=-1, name=None):
    return _flatten(x, start_axis=int(start_axis), stop_axis=int(stop_axis))


@register_op("squeeze2")
def _squeeze(x, *, axes):
    if not axes:
        return jnp.squeeze(x)
    axes = tuple(a for a in axes if x.shape[a] == 1)
    return jnp.squeeze(x, axis=axes) if axes else x


def squeeze(x, axis=None, name=None):
    if axis is None:
        return _squeeze(x, axes=())
    if isinstance(axis, int):
        axis = [axis]
    return _squeeze(x, axes=tuple(int(a) for a in axis))


@register_op("unsqueeze2")
def _unsqueeze(x, *, axes):
    for a in sorted(axes):
        x = jnp.expand_dims(x, a)
    return x


def unsqueeze(x, axis, name=None):
    if isinstance(axis, int):
        axis = [axis]
    return _unsqueeze(x, axes=tuple(int(a) for a in axis))


def unsqueeze_(x, axis, name=None):
    x.value = _unsqueeze(
        x, axes=tuple(axis) if isinstance(axis, (list, tuple)) else (int(axis),)
    ).value
    return x


@register_op("concat")
def _concat(*xs, axis):
    return jnp.concatenate(xs, axis=axis)


def concat(x, axis=0, name=None):
    if isinstance(axis, Tensor):
        axis = int(axis.item())
    return _concat(*x, axis=int(axis))


@register_op("stack")
def _stack(*xs, axis):
    return jnp.stack(xs, axis=axis)


def stack(x, axis=0, name=None):
    return _stack(*x, axis=int(axis))


@register_op("split")
def _split(x, *, sections, axis):
    if isinstance(sections, int):
        return tuple(jnp.split(x, sections, axis=axis))
    idx = np.cumsum(sections)[:-1].tolist()
    return tuple(jnp.split(x, idx, axis=axis))


def split(x, num_or_sections, axis=0, name=None):
    if isinstance(axis, Tensor):
        axis = int(axis.item())
    if isinstance(num_or_sections, int):
        sections = int(num_or_sections)
    else:
        secs = [int(s) for s in num_or_sections]
        total = x.shape[int(axis)]
        neg = [i for i, s in enumerate(secs) if s < 0]
        if neg:
            known = sum(s for s in secs if s >= 0)
            secs[neg[0]] = total - known
        sections = tuple(secs)
    out = _split(x, sections=sections, axis=int(axis))
    return list(out)


def chunk(x, chunks, axis=0, name=None):
    return split(x, int(chunks), axis)


def unbind(x, axis=0):
    n = x.shape[int(axis)]
    outs = split(x, n, axis)
    return [squeeze(o, axis=int(axis)) for o in outs]


@register_op("slice")
def _slice(x, *, axes, starts, ends, strides):
    idx = [slice(None)] * x.ndim
    for ax, st, en, sd in zip(axes, starts, ends, strides):
        idx[ax] = slice(st, en, sd)
    return x[tuple(idx)]


def slice(x, axes, starts, ends, name=None):  # noqa: A001
    starts = [int(s.item()) if isinstance(s, Tensor) else int(s) for s in starts]
    ends = [int(e.item()) if isinstance(e, Tensor) else int(e) for e in ends]
    return _slice(x, axes=tuple(int(a) for a in axes), starts=tuple(starts),
                  ends=tuple(ends), strides=(1,) * len(axes))


def strided_slice(x, axes, starts, ends, strides, name=None):
    return _slice(x, axes=tuple(int(a) for a in axes),
                  starts=tuple(int(s) for s in starts),
                  ends=tuple(int(e) for e in ends),
                  strides=tuple(int(s) for s in strides))


@register_op("gather")
def _gather(x, index, *, axis):
    if index.ndim == 0:
        index = index[None]
    return jnp.take(x, index, axis=axis)


def gather(x, index, axis=0, name=None):
    if isinstance(axis, Tensor):
        axis = int(axis.item())
    return _gather(x, index, axis=int(axis))


@register_op("gather_nd")
def _gather_nd(x, index):
    idx = tuple(jnp.moveaxis(index, -1, 0))
    return x[idx]


def gather_nd(x, index, name=None):
    return _gather_nd(x, index)


@register_op("take_along_axis")
def _take_along_axis(x, index, *, axis):
    return jnp.take_along_axis(x, index, axis=axis)


def take_along_axis(x, indices, axis, name=None):
    return _take_along_axis(x, indices, axis=int(axis))


@register_op("put_along_axis")
def _put_along_axis(x, index, value, *, axis, reduce):
    value_b = jnp.broadcast_to(value, index.shape).astype(x.dtype)
    idxs = list(jnp.indices(index.shape, sparse=True))
    idxs[axis] = index
    if reduce == "assign":
        return x.at[tuple(idxs)].set(value_b)
    if reduce == "add":
        return x.at[tuple(idxs)].add(value_b)
    if reduce in ("mul", "multiply"):
        return x.at[tuple(idxs)].multiply(value_b)
    raise ValueError(reduce)


def put_along_axis(x, indices, values, axis, reduce="assign"):
    if not isinstance(values, Tensor):
        values = Tensor(jnp.asarray(values, x.value.dtype))
    return _put_along_axis(x, indices, values, axis=int(axis), reduce=reduce)


@register_op("index_select")
def _index_select(x, index, *, axis):
    return jnp.take(x, index, axis=axis)


def index_select(x, index, axis=0, name=None):
    return _index_select(x, index, axis=int(axis))


@register_op("index_sample")
def _index_sample(x, index):
    return jnp.take_along_axis(x, index, axis=1)


def index_sample(x, index):
    return _index_sample(x, index)


@register_op("scatter")
def _scatter(x, index, updates, *, overwrite):
    if index.ndim == 2:
        index = index[:, 0]
    if overwrite:
        return x.at[index].set(updates)
    # paddle scatter with overwrite=False sums duplicates after zeroing
    zeroed = x.at[index].set(jnp.zeros_like(updates))
    return zeroed.at[index].add(updates)


def scatter(x, index, updates, overwrite=True, name=None):
    return _scatter(x, index, updates, overwrite=bool(overwrite))


@register_op("scatter_nd_add")
def _scatter_nd_add(x, index, updates):
    idx = tuple(jnp.moveaxis(index, -1, 0))
    return x.at[idx].add(updates)


def scatter_nd_add(x, index, updates, name=None):
    return _scatter_nd_add(x, index, updates)


@register_op("tile")
def _tile(x, *, repeat_times):
    return jnp.tile(x, repeat_times)


def tile(x, repeat_times, name=None):
    return _tile(x, repeat_times=_shape_tuple(repeat_times))


@register_op("expand_v2")
def _expand(x, *, shape):
    offset = len(shape) - x.ndim
    full = []
    for i, s in enumerate(shape):
        if s == -1:
            full.append(x.shape[i - offset] if i >= offset else 1)
        else:
            full.append(s)
    return jnp.broadcast_to(x, tuple(full))


def expand(x, shape, name=None):
    return _expand(x, shape=_shape_tuple(shape))


def expand_as(x, y, name=None):
    return _expand(x, shape=tuple(y.shape))


def broadcast_to(x, shape, name=None):
    return expand(x, shape)


@register_op("broadcast_tensors")
def _broadcast_tensors(*xs):
    return tuple(jnp.broadcast_arrays(*xs))


def broadcast_tensors(inputs, name=None):
    return list(_broadcast_tensors(*inputs))


@register_op("flip")
def _flip(x, *, axis):
    return jnp.flip(x, axis=axis)


def flip(x, axis, name=None):
    if isinstance(axis, int):
        axis = [axis]
    return _flip(x, axis=tuple(int(a) for a in axis))


@register_op("roll")
def _roll(x, *, shifts, axis):
    return jnp.roll(x, shifts, axis=axis)


def roll(x, shifts, axis=None, name=None):
    if isinstance(shifts, (list, tuple)):
        shifts = tuple(int(s) for s in shifts)
    else:
        shifts = int(shifts)
    if axis is not None:
        axis = tuple(int(a) for a in axis) if isinstance(axis, (list, tuple)) else int(axis)
    return _roll(x, shifts=shifts, axis=axis)


@register_op("rot90")
def _rot90(x, *, k, axes):
    return jnp.rot90(x, k=k, axes=axes)


def rot90(x, k=1, axes=(0, 1)):
    return _rot90(x, k=int(k), axes=tuple(axes))


@register_op("repeat_interleave")
def _repeat_interleave(x, *, repeats, axis):
    return jnp.repeat(x, repeats, axis=axis)


def repeat_interleave(x, repeats, axis=None, name=None):
    return _repeat_interleave(x, repeats=int(repeats),
                              axis=None if axis is None else int(axis))


@register_op("pad3d")
def _pad(x, *, paddings, mode, value):
    jmode = {"constant": "constant", "reflect": "reflect",
             "replicate": "edge", "circular": "wrap"}[mode]
    if jmode == "constant":
        return jnp.pad(x, paddings, mode=jmode, constant_values=value)
    return jnp.pad(x, paddings, mode=jmode)


def pad(x, pad, mode="constant", value=0.0, data_format="NCHW", name=None):  # noqa: A002
    """paddle.nn.functional.pad. `pad` is [left,right,top,bottom,...] pairs on
    trailing dims (paddle convention) or full per-dim list."""
    if isinstance(pad, int):  # scalar: pad every spatial dim (Pad1/2/3D)
        pad = [pad] * (2 * max(x.ndim - 2, 1))
    pad = [int(p) for p in (pad.tolist() if isinstance(pad, Tensor) else pad)]
    nd = x.ndim
    if len(pad) == 2 * nd:
        paddings = tuple((pad[2 * i], pad[2 * i + 1]) for i in range(nd))
    else:
        npairs = len(pad) // 2
        paddings = [(0, 0)] * nd
        if data_format.endswith("C") and nd >= 3:  # NHWC-style: pad spatial dims
            dims = range(1, 1 + npairs)
        else:  # NCHW-style: pad trailing dims, reversed pair order
            dims = range(nd - 1, nd - 1 - npairs, -1)
        for i, d in enumerate(dims):
            paddings[d] = (pad[2 * i], pad[2 * i + 1])
        paddings = tuple(paddings)
    return _pad(x, paddings=paddings, mode=mode, value=float(value))


@register_op("where_op")
def _where(cond, x, y):
    return jnp.where(cond, x, y)


def where(condition, x=None, y=None, name=None):
    if x is None and y is None:
        from . import search
        return search.nonzero(condition, as_tuple=True)
    return _where(condition, x, y)


@register_op("masked_select")
def _masked_select(x, mask):
    # dynamic-size output: fall back to host (reference returns dynamic shape;
    # on XLA this is inherently a sync point)
    return x[mask]


def masked_select(x, mask, name=None):
    import jax.core as jcore
    if isinstance(x.value, jcore.Tracer):
        raise RuntimeError("masked_select has data-dependent shape and cannot "
                           "be used inside jit; use paddle.where instead")
    return Tensor(x.value[mask.value])


def masked_fill(x, mask, value, name=None):
    if isinstance(value, Tensor):
        value = value.item()
    vals = jnp.asarray(value, x.value.dtype)
    return _where(mask, Tensor(jnp.broadcast_to(vals, ())), x)


@register_op("meshgrid")
def _meshgrid(*xs):
    return tuple(jnp.meshgrid(*xs, indexing="ij"))


def meshgrid(*args, **kwargs):
    if len(args) == 1 and isinstance(args[0], (list, tuple)):
        args = args[0]
    return list(_meshgrid(*args))


@register_op("shard_index", differentiable=False)
def _shard_index(x, *, index_num, nshards, shard_id, ignore_value):
    shard_size = (index_num + nshards - 1) // nshards
    lo = shard_id * shard_size
    hi = lo + shard_size
    in_shard = (x >= lo) & (x < hi)
    return jnp.where(in_shard, x - lo, ignore_value)


def shard_index(input, index_num, nshards, shard_id, ignore_value=-1):  # noqa: A002
    """Reference: operators/shard_index_op (used by TP vocab sharding)."""
    return _shard_index(input, index_num=int(index_num), nshards=int(nshards),
                        shard_id=int(shard_id), ignore_value=int(ignore_value))


def numel(x):
    return Tensor(jnp.asarray(x.size, jnp.int64))


def shape(x):
    return Tensor(jnp.asarray(x.aval_shape(), jnp.int32))


@register_op("unstack")
def _unstack(x, *, axis, num):
    return tuple(jnp.squeeze(s, axis) for s in jnp.split(x, num, axis=axis))


def unstack(x, axis=0, num=None):
    num = num or x.shape[axis]
    return list(_unstack(x, axis=int(axis), num=int(num)))


@register_op("unfold")
def _unfold(x, *, kernel_sizes, strides, paddings, dilations):
    n, c, h, w = x.shape
    kh, kw = kernel_sizes
    sh, sw = strides
    ph, pw = paddings
    dh, dw = dilations
    x = jnp.pad(x, ((0, 0), (0, 0), (ph, ph), (pw, pw)))
    oh = (h + 2 * ph - dh * (kh - 1) - 1) // sh + 1
    ow = (w + 2 * pw - dw * (kw - 1) - 1) // sw + 1
    cols = []
    for i in range(kh):
        for j in range(kw):
            patch = x[:, :, i * dh:i * dh + oh * sh:sh, j * dw:j * dw + ow * sw:sw]
            cols.append(patch)
    out = jnp.stack(cols, axis=2)  # N, C, kh*kw, oh, ow
    return out.reshape(n, c * kh * kw, oh * ow)


def unfold(x, kernel_sizes, strides=1, paddings=0, dilations=1, name=None):
    def _pair(v):
        return (v, v) if isinstance(v, int) else tuple(v)
    return _unfold(x, kernel_sizes=_pair(kernel_sizes), strides=_pair(strides),
                   paddings=_pair(paddings), dilations=_pair(dilations))


@register_op("diagonal")
def _diagonal(x, *, offset, axis1, axis2):
    return jnp.diagonal(x, offset=offset, axis1=axis1, axis2=axis2)


def diagonal(x, offset=0, axis1=0, axis2=1, name=None):
    """Reference: python/paddle/tensor/math.py diagonal op."""
    return _diagonal(x, offset=int(offset), axis1=int(axis1), axis2=int(axis2))


@register_op("multiplex")
def _multiplex(index, *xs):
    stacked = jnp.stack(xs, axis=0)  # [num_candidates, batch, ...]
    idx = index.reshape(-1).astype(jnp.int32)
    rows = jnp.arange(stacked.shape[1])
    return stacked[idx, rows]


def multiplex(inputs, index, name=None):
    """Row-wise select among candidate tensors (reference:
    paddle/fluid/operators/multiplex_op.cc)."""
    return _multiplex(index, *inputs)


@register_op("reverse")
def _reverse(x, *, axis):
    return jnp.flip(x, axis=axis)


def reverse(x, axis, name=None):
    if isinstance(axis, int):
        axis = [axis]
    return _reverse(x, axis=tuple(int(a) for a in axis))


@register_op("crop_tensor")
def _crop(x, *, offsets, shape):
    return jax.lax.dynamic_slice(x, offsets, shape)


def crop(x, shape=None, offsets=None, name=None):
    """Reference: paddle/fluid/operators/crop_tensor_op.cc. A shape entry of
    -1 means "everything from the offset to the end of that dim"."""
    off = list(_shape_tuple(offsets)) if offsets is not None else [0] * x.ndim
    shp = list(shape) if shape is not None else [-1] * x.ndim
    shp = [x.shape[i] - off[i] if s in (-1, None) else int(s)
           for i, s in enumerate(shp)]
    return _crop(x, offsets=tuple(off), shape=tuple(shp))


crop_tensor = crop


@register_op("scatter_nd")
def _scatter_nd(index, updates, *, shape):
    zeros = jnp.zeros(shape, updates.dtype)
    return zeros.at[tuple(jnp.moveaxis(index, -1, 0))].add(updates)


def scatter_nd(index, updates, shape, name=None):
    """Reference: paddle/fluid/operators/scatter_nd_add_op.cc (zero base)."""
    return _scatter_nd(index, updates, shape=_shape_tuple(shape))


def scatter_(x, index, updates, overwrite=True, name=None):
    x.value = scatter(x, index, updates, overwrite=overwrite).value
    return x


def squeeze_(x, axis=None, name=None):
    x.value = squeeze(x, axis=axis).value
    return x


def tolist(x):
    return x.value.tolist() if hasattr(x, "value") else list(x)


def broadcast_shape(x_shape, y_shape):
    import numpy as np
    return list(np.broadcast_shapes(tuple(x_shape), tuple(y_shape)))


# ---- round-2 op additions (reference: python/paddle/tensor/manipulation.py)

@register_op("moveaxis_op")
def _moveaxis(x, *, source, destination):
    return jnp.moveaxis(x, source, destination)


def moveaxis(x, source, destination, name=None):
    src = tuple(source) if isinstance(source, (list, tuple)) else int(source)
    dst = tuple(destination) if isinstance(destination, (list, tuple)) \
        else int(destination)
    return _moveaxis(x, source=src, destination=dst)


@register_op("index_add_op")
def _index_add(x, index, value, *, axis):
    moved = jnp.moveaxis(x, axis, 0)
    vmoved = jnp.moveaxis(value, axis, 0)
    out = moved.at[index].add(vmoved)
    return jnp.moveaxis(out, 0, axis)


def index_add(x, index, axis, value, name=None):
    return _index_add(x, index, value, axis=int(axis))


def index_add_(x, index, axis, value, name=None):
    out = index_add(x, index, axis, value)
    x.value = out.value
    return x


@register_op("index_fill_op")
def _index_fill(x, index, *, axis, fill_value):
    moved = jnp.moveaxis(x, axis, 0)
    out = moved.at[index].set(fill_value)
    return jnp.moveaxis(out, 0, axis)


def index_fill(x, index, axis, value, name=None):
    from ..core.tensor import Tensor
    if isinstance(value, Tensor):
        value = float(value.numpy())
    return _index_fill(x, index, axis=int(axis), fill_value=value)


def index_fill_(x, index, axis, value, name=None):
    out = index_fill(x, index, axis, value)
    x.value = out.value
    return x


@register_op("tensordot_op")
def _tensordot(x, y, *, axes):
    return jnp.tensordot(x, y, axes=axes)


def tensordot(x, y, axes=2, name=None):
    if isinstance(axes, (list, tuple)):
        a, b = axes
        axes = (tuple(a) if isinstance(a, (list, tuple)) else (a,),
                tuple(b) if isinstance(b, (list, tuple)) else (b,))
    else:
        axes = int(axes)
    return _tensordot(x, y, axes=axes)


@register_op("as_real")
def _as_real(x):
    return jnp.stack([jnp.real(x), jnp.imag(x)], axis=-1)


def as_real(x, name=None):
    """Reference: paddle.as_real — complex [..] -> float [.., 2]."""
    return _as_real(x)


view_as_real = as_real


@register_op("as_complex")
def _as_complex(x):
    return jax.lax.complex(x[..., 0], x[..., 1])


def as_complex(x, name=None):
    return _as_complex(x)


view_as_complex = as_complex
