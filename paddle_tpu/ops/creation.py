"""Tensor creation ops.

Reference parity: python/paddle/tensor/creation.py (to_tensor, zeros, ones,
full, arange, eye, ...) and python/paddle/tensor/random.py. Random ops draw
keys from the global Generator (core/rng.py) so they are trace-safe.
"""
import numpy as np
import jax
import jax.numpy as jnp

from ..core.dispatch import register_op
from ..core.tensor import Tensor
from ..core import dtype as dtype_mod
from ..core import rng as rng_mod
from ..core import trace as trace_mod


def _norm_shape(shape):
    if isinstance(shape, Tensor):
        shape = shape.tolist()
    if isinstance(shape, (int, np.integer)):
        return (int(shape),)
    return tuple(int(s) for s in shape)


def _jdt(dtype, default="float32"):
    return dtype_mod.to_jax_dtype(dtype if dtype is not None else default)


def _register_created(t):
    ctx = trace_mod.current_trace()
    if ctx is not None:
        ctx.register_created(t)
    return t


def to_tensor(data, dtype=None, place=None, stop_gradient=True):
    """paddle.to_tensor. Python floats/lists default to float32 (reference
    behavior); numpy arrays keep their dtype."""
    if isinstance(data, Tensor):
        out = Tensor(data.value, dtype=dtype, stop_gradient=stop_gradient)
        return _register_created(out)
    if dtype is None:
        if isinstance(data, (bool, np.bool_)):
            pass
        elif isinstance(data, (int, np.integer)):
            dtype = "int64"
        elif isinstance(data, float):
            dtype = "float32"
        elif isinstance(data, (list, tuple)):
            a = np.asarray(data)
            if a.dtype == np.float64:
                dtype = "float32"
        elif isinstance(data, np.ndarray) and data.dtype == np.float64:
            dtype = None  # numpy keeps dtype, paddle-style
    out = Tensor(data, dtype=dtype, place=place, stop_gradient=stop_gradient)
    return _register_created(out)


def zeros(shape, dtype=None, name=None):
    return _register_created(Tensor(jnp.zeros(_norm_shape(shape), _jdt(dtype))))


def ones(shape, dtype=None, name=None):
    return _register_created(Tensor(jnp.ones(_norm_shape(shape), _jdt(dtype))))


def full(shape, fill_value, dtype=None, name=None):
    if isinstance(fill_value, Tensor):
        fill_value = fill_value.item()
    return _register_created(
        Tensor(jnp.full(_norm_shape(shape), fill_value, _jdt(dtype))))


def empty(shape, dtype=None, name=None):
    return zeros(shape, dtype)


@register_op("zeros_like", differentiable=False)
def _zeros_like(x, *, dtype):
    return jnp.zeros(x.shape, dtype if dtype is not None else x.dtype)


@register_op("ones_like", differentiable=False)
def _ones_like(x, *, dtype):
    return jnp.ones(x.shape, dtype if dtype is not None else x.dtype)


@register_op("full_like", differentiable=False)
def _full_like(x, *, fill_value, dtype):
    return jnp.full(x.shape, fill_value, dtype if dtype is not None else x.dtype)


def zeros_like(x, dtype=None, name=None):
    return _zeros_like(x, dtype=_jdt(dtype, None) if dtype else None)


def ones_like(x, dtype=None, name=None):
    return _ones_like(x, dtype=_jdt(dtype, None) if dtype else None)


def full_like(x, fill_value, dtype=None, name=None):
    return _full_like(x, fill_value=float(fill_value),
                      dtype=_jdt(dtype, None) if dtype else None)


def empty_like(x, dtype=None, name=None):
    return zeros_like(x, dtype)


def arange(start=0, end=None, step=1, dtype=None, name=None):
    if end is None:
        start, end = 0, start
    for v in (start, end, step):
        if isinstance(v, Tensor):
            raise TypeError("arange with Tensor bounds not supported")
    if dtype is None:
        dtype = ("float32" if any(isinstance(v, float) for v in (start, end, step))
                 else "int64")
    return _register_created(
        Tensor(jnp.arange(start, end, step, dtype=_jdt(dtype))))


def linspace(start, stop, num, dtype=None, name=None):
    return _register_created(
        Tensor(jnp.linspace(float(start), float(stop), int(num),
                            dtype=_jdt(dtype))))


def logspace(start, stop, num, base=10.0, dtype=None, name=None):
    return _register_created(
        Tensor(jnp.logspace(float(start), float(stop), int(num),
                            base=float(base), dtype=_jdt(dtype))))


def eye(num_rows, num_columns=None, dtype=None, name=None):
    return _register_created(
        Tensor(jnp.eye(int(num_rows),
                       int(num_columns) if num_columns is not None else None,
                       dtype=_jdt(dtype))))


@register_op("tril")
def _tril(x, *, diagonal):
    return jnp.tril(x, k=diagonal)


@register_op("triu")
def _triu(x, *, diagonal):
    return jnp.triu(x, k=diagonal)


def tril(x, diagonal=0, name=None):
    return _tril(x, diagonal=int(diagonal))


def triu(x, diagonal=0, name=None):
    return _triu(x, diagonal=int(diagonal))


@register_op("diag")
def _diag(x, *, offset, padding_value):
    if x.ndim == 1:
        out = jnp.diag(x, k=offset)
        if padding_value != 0:
            mask = jnp.eye(*out.shape, k=offset, dtype=bool)
            out = jnp.where(mask, out, jnp.asarray(padding_value, out.dtype))
        return out
    return jnp.diagonal(x, offset=offset)


def diag(x, offset=0, padding_value=0, name=None):
    return _diag(x, offset=int(offset), padding_value=padding_value)


def diagflat(x, offset=0, name=None):
    from . import manipulation
    return diag(manipulation.flatten(x), offset=offset)


def assign(x, output=None):
    """paddle.assign (reference: python/paddle/tensor/creation.py assign)."""
    src = x.value if isinstance(x, Tensor) else jnp.asarray(x)
    if output is None:
        return _register_created(Tensor(src))
    output.value = src
    return output


def clone(x, name=None):
    from . import math as math_ops
    return math_ops.clone(x)


# ---- random ---------------------------------------------------------------

@register_op("uniform_random", differentiable=False)
def _uniform(key, *, shape, dtype, minv, maxv):
    return jax.random.uniform(key, shape, dtype=dtype, minval=minv, maxval=maxv)


@register_op("gaussian_random", differentiable=False)
def _normal(key, *, shape, dtype, mean, std):
    return jax.random.normal(key, shape, dtype=dtype) * std + mean


@register_op("randint", differentiable=False)
def _randint(key, *, low, high, shape, dtype):
    return jax.random.randint(key, shape, low, high, dtype=dtype)


@register_op("randperm", differentiable=False)
def _randperm(key, *, n, dtype):
    return jax.random.permutation(key, n).astype(dtype)


@register_op("bernoulli", differentiable=False)
def _bernoulli(x, key):
    return jax.random.bernoulli(key, x).astype(x.dtype)


@register_op("multinomial", differentiable=False)
def _multinomial(x, key, *, num_samples, replacement):
    logits = jnp.log(jnp.maximum(x, 1e-30))
    if replacement:
        return jax.random.categorical(key, logits, axis=-1,
                                      shape=x.shape[:-1] + (num_samples,))
    # without replacement: gumbel top-k
    g = jax.random.gumbel(key, x.shape, dtype=logits.dtype)
    _, idx = jax.lax.top_k(logits + g, num_samples)
    return idx


def uniform(shape, dtype=None, min=-1.0, max=1.0, seed=0, name=None):
    key = rng_mod.next_key()
    return _uniform(key, shape=_norm_shape(shape), dtype=_jdt(dtype),
                    minv=float(min), maxv=float(max))


def rand(shape, dtype=None, name=None):
    return uniform(shape, dtype, 0.0, 1.0)


def normal(mean=0.0, std=1.0, shape=None, name=None):
    key = rng_mod.next_key()
    return _normal(key, shape=_norm_shape(shape), dtype=_jdt(None),
                   mean=float(mean), std=float(std))


def randn(shape, dtype=None, name=None):
    key = rng_mod.next_key()
    return _normal(key, shape=_norm_shape(shape), dtype=_jdt(dtype),
                   mean=0.0, std=1.0)


def randint(low=0, high=None, shape=(1,), dtype=None, name=None):
    if high is None:
        low, high = 0, low
    key = rng_mod.next_key()
    return _randint(key, low=int(low), high=int(high),
                    shape=_norm_shape(shape), dtype=_jdt(dtype, "int64"))


def randperm(n, dtype="int64", name=None):
    key = rng_mod.next_key()
    return _randperm(key, n=int(n), dtype=_jdt(dtype, "int64"))


def bernoulli(x, name=None):
    return _bernoulli(x, rng_mod.next_key())


def multinomial(x, num_samples=1, replacement=False, name=None):
    return _multinomial(x, rng_mod.next_key(), num_samples=int(num_samples),
                        replacement=bool(replacement))


def rand_like(x, dtype=None):
    return uniform(tuple(x.shape), dtype or x.value.dtype, 0.0, 1.0)


def standard_normal(shape, dtype=None, name=None):
    """randn alias with paddle's standard_normal name."""
    return randn(shape, dtype=dtype)


def create_parameter(shape, dtype, name=None, attr=None,
                     is_bias=False, default_initializer=None):
    """Reference: python/paddle/fluid/layers/tensor.py create_parameter."""
    from ..core.tensor import Parameter
    from ..nn import initializer as I
    init = default_initializer
    if init is None and attr is not None and getattr(attr, "initializer", None):
        init = attr.initializer
    if init is None:
        init = I.Constant(0.0) if is_bias else I.XavierUniform()
    val = init(tuple(int(s) for s in shape), dtype or "float32")
    return Parameter(val, name=name)
