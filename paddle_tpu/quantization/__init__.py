"""Quantization: QAT fake-quant layers + post-training calibration.

Reference parity: python/paddle/fluid/contrib/slim/quantization/ —
ImperativeQuantAware (imperative/qat.py:42, dygraph QAT swapping
Conv2D/Linear for quantized wrappers), fake_quantize ops
(paddle/fluid/operators/fake_quantize_op.cc: abs_max,
moving_average_abs_max, channel_wise_abs_max) and
PostTrainingQuantization (post_training_quantization.py).

TPU-native design: fake quant-dequant is a pure jax op with a
straight-through-estimator custom VJP; under jit the q/dq chain fuses
into the surrounding matmul, and on TPU the int8 simulation runs in the
MXU-friendly fp domain (scale * round(x/scale)).
"""
import jax
import jax.numpy as jnp

from ..core.dispatch import register_op
from ..core.tensor import Tensor
from ..nn.layer_base import Layer
from ..nn.layer.common import Linear
from ..nn.layer.conv import Conv2D
from ..ops import nn_ops


# ---------------------------------------------------------------------------
# fake quant-dequant primitives (STE gradient)
# ---------------------------------------------------------------------------

@jax.custom_vjp
def _qdq_ste(x, scale, qmax):
    s = jnp.maximum(scale, 1e-8)
    q = jnp.clip(jnp.round(x / s * qmax), -qmax, qmax)
    return q * s / qmax


def _qdq_fwd(x, scale, qmax):
    return _qdq_ste(x, scale, qmax), None


def _qdq_bwd(_, g):
    return (g, None, None)  # straight-through: pass grad, no scale grad


_qdq_ste.defvjp(_qdq_fwd, _qdq_bwd)


@register_op("fake_quantize_dequantize_abs_max")
def _fake_qdq_abs_max(x, *, bits):
    """Reference: fake_quantize_dequantize_abs_max op — per-tensor scale
    from the current batch's abs-max."""
    qmax = float(2 ** (bits - 1) - 1)
    scale = jnp.max(jnp.abs(x))
    return _qdq_ste(x, scale, qmax)


@register_op("fake_channel_wise_quantize_dequantize_abs_max")
def _fake_qdq_channel(x, *, bits, axis):
    """Reference: fake_channel_wise_quantize_dequantize_abs_max — one
    scale per output channel (weights)."""
    qmax = float(2 ** (bits - 1) - 1)
    red = tuple(i for i in range(x.ndim) if i != axis)
    scale = jnp.max(jnp.abs(x), axis=red, keepdims=True)
    return _qdq_ste(x, scale, qmax)


@register_op("fake_quantize_dequantize_moving_average_abs_max")
def _fake_qdq_moving(x, in_scale, *, bits):
    qmax = float(2 ** (bits - 1) - 1)
    return _qdq_ste(x, in_scale, qmax)


@register_op("moving_average_scale_update", differentiable=False)
def _ma_update(x, scale, accum, state, *, rate, algo):
    """Reference: moving_average_abs_max_scale op (EMA of batch abs-max);
    algo="abs_max" keeps the running max instead — the PTQ calibration
    rule (post_training_quantization.py abs_max algo)."""
    cur = jnp.max(jnp.abs(x)).astype(jnp.float32)
    state_n = rate * state + 1.0
    if algo == "abs_max":
        scale_n = jnp.maximum(scale, cur)
        accum_n = scale_n
    else:
        accum_n = rate * accum + cur
        scale_n = accum_n / state_n
    return scale_n, accum_n, state_n


def quant_dequant_abs_max(x, bits=8):
    return _fake_qdq_abs_max(x, bits=bits)


def quant_dequant_channel_wise(x, bits=8, axis=0):
    return _fake_qdq_channel(x, bits=bits, axis=axis)


# ---------------------------------------------------------------------------
# QAT layers (reference: python/paddle/nn/quant/quant_layers.py)
# ---------------------------------------------------------------------------

class FakeQuantMovingAverageAbsMax(Layer):
    """Activation quantizer: EMA abs-max scale updated in training,
    frozen in eval (reference: quant_layers.FakeQuantMovingAverageAbsMax)."""

    def __init__(self, bits=8, moving_rate=0.9, algo="ema", name=None):
        super().__init__()
        self._bits = bits
        self._rate = float(moving_rate)
        self._algo = algo
        # python-side flag, NOT a device read: eval-mode forward must stay
        # traceable (jit.save) and free of per-layer host syncs
        self._calibrated = False
        self.register_buffer("scale", Tensor(jnp.zeros((), jnp.float32)))
        self.register_buffer("accum", Tensor(jnp.zeros((), jnp.float32)))
        self.register_buffer("state", Tensor(jnp.zeros((), jnp.float32)))

    def forward(self, x):
        if self.training:
            s, a, st = _ma_update(x, self.scale, self.accum, self.state,
                                  rate=self._rate, algo=self._algo)
            self.scale.value = s.value
            self.accum.value = a.value
            self.state.value = st.value
            self._calibrated = True
        elif not self._calibrated:
            # never calibrated: dynamic per-batch scale instead of the
            # uninitialized observer (which would collapse activations)
            return _fake_qdq_abs_max(x, bits=self._bits)
        return _fake_qdq_moving(x, self.scale, bits=self._bits)

    def _after_load_state_dict(self):
        # calibration state is derivable from the persisted buffers: any
        # training step leaves scale>0 (abs_max) or state>0 (ema). Loading
        # an uncalibrated (all-zero) checkpoint must also CLEAR the flag,
        # or eval would quantize through scale=0 and collapse activations.
        try:
            self._calibrated = bool(float(self.scale.numpy()) > 0
                                    or float(self.state.numpy()) > 0)
        except Exception:
            pass  # traced/abstract buffers: leave the flag unchanged


class QuantizedLinear(Layer):
    """Linear with fake-quantized weight (channel-wise abs-max) and
    activation (moving-average abs-max)."""

    def __init__(self, layer, weight_bits=8, activation_bits=8,
                 moving_rate=0.9, weight_quantize_type="channel_wise_abs_max",
                 act_algo="ema"):
        super().__init__()
        self.weight = layer.weight
        self.bias = layer.bias
        self._wbits = weight_bits
        self._wtype = weight_quantize_type
        self._act_quant = FakeQuantMovingAverageAbsMax(activation_bits,
                                                       moving_rate, act_algo)

    def forward(self, x):
        x = self._act_quant(x)
        if self._wtype == "abs_max":
            w = _fake_qdq_abs_max(self.weight, bits=self._wbits)
        else:
            w = _fake_qdq_channel(self.weight, bits=self._wbits, axis=1)
        return nn_ops.linear(x, w, self.bias)


class QuantizedConv2D(Layer):
    def __init__(self, layer, weight_bits=8, activation_bits=8,
                 moving_rate=0.9, weight_quantize_type="channel_wise_abs_max",
                 act_algo="ema"):
        super().__init__()
        self.weight = layer.weight
        self.bias = layer.bias
        self._stride = layer._stride
        self._padding = layer._padding
        self._dilation = layer._dilation
        self._groups = layer._groups
        self._data_format = layer._data_format
        self._wbits = weight_bits
        self._wtype = weight_quantize_type
        self._act_quant = FakeQuantMovingAverageAbsMax(activation_bits,
                                                       moving_rate, act_algo)

    def forward(self, x):
        x = self._act_quant(x)
        if self._wtype == "abs_max":
            w = _fake_qdq_abs_max(self.weight, bits=self._wbits)
        else:
            w = _fake_qdq_channel(self.weight, bits=self._wbits, axis=0)
        return nn_ops.conv2d(x, w, self.bias, self._stride, self._padding,
                             self._dilation, self._groups, self._data_format)


_QUANT_WRAPPERS = {"Linear": (Linear, QuantizedLinear),
                   "Conv2D": (Conv2D, QuantizedConv2D)}


class ImperativeQuantAware:
    """Dygraph QAT driver (reference: imperative/qat.py:42): walks the
    model, swaps quantizable layers for quantized wrappers in place."""

    def __init__(self, quantizable_layer_type=("Conv2D", "Linear"),
                 weight_quantize_type="channel_wise_abs_max",
                 activation_quantize_type="moving_average_abs_max",
                 weight_bits=8, activation_bits=8, moving_rate=0.9):
        unsupported = [t for t in quantizable_layer_type
                       if t not in _QUANT_WRAPPERS]
        if unsupported:
            raise ValueError(
                f"unsupported quantizable_layer_type {unsupported}; "
                f"supported: {sorted(_QUANT_WRAPPERS)}")
        self._types = tuple(quantizable_layer_type)
        self._wtype = weight_quantize_type
        self._wbits = weight_bits
        self._abits = activation_bits
        self._rate = moving_rate
        self._act_algo = ("abs_max"
                          if activation_quantize_type == "abs_max" else "ema")

    def quantize(self, model):
        self._quantize_sublayers(model)
        return model

    def _quantize_sublayers(self, layer):
        for name, sub in list(layer._sub_layers.items()):
            replaced = False
            for tname in self._types:
                base, wrapper = _QUANT_WRAPPERS[tname]
                if isinstance(sub, base):
                    layer._sub_layers[name] = wrapper(
                        sub, self._wbits, self._abits, self._rate,
                        self._wtype, self._act_algo)
                    replaced = True
                    break
            if not replaced:
                self._quantize_sublayers(sub)

    def save_quantized_model(self, model, path, input_spec=None):
        from .. import jit
        model.eval()
        jit.save(model, path, input_spec=input_spec)


class PostTrainingQuantization:
    """PTQ calibration (reference: post_training_quantization.py, abs-max
    algo): feed calibration batches, collect per-layer activation scales,
    then freeze them into quantized wrappers."""

    def __init__(self, model, quantizable_layer_type=("Conv2D", "Linear"),
                 weight_bits=8, activation_bits=8, algo="abs_max"):
        self._model = model
        self._types = tuple(quantizable_layer_type)
        self._wbits = weight_bits
        self._abits = activation_bits
        self._algo = algo
        self._qat = ImperativeQuantAware(
            quantizable_layer_type=quantizable_layer_type,
            activation_quantize_type=("abs_max" if algo == "abs_max"
                                      else "moving_average_abs_max"),
            weight_bits=weight_bits, activation_bits=activation_bits)

    def sample(self, *batches):
        """Run calibration forwards with the MODEL in inference mode
        (dropout off, batch-norm frozen — reference PTQ runs inference
        passes) while only the quant observers update."""
        if not getattr(self, "_quantized", False):
            self._qat.quantize(self._model)
            self._quantized = True
        self._model.eval()
        for obs in self._observers(self._model):
            obs.training = True
        try:
            outs = [self._model(b) for b in batches]
        finally:
            for obs in self._observers(self._model):
                obs.training = False
        return outs

    @staticmethod
    def _observers(layer):
        found = []
        for sub in layer._sub_layers.values():
            if isinstance(sub, FakeQuantMovingAverageAbsMax):
                found.append(sub)
            found.extend(PostTrainingQuantization._observers(sub))
        return found

    def convert(self):
        """Freeze observers: eval mode stops scale updates."""
        self._model.eval()
        return self._model


# ---------------------------------------------------------------------------
# TRUE int8 inference execution (round 3)
# ---------------------------------------------------------------------------
# The QAT/PTQ wrappers above SIMULATE int8 in fp (reference parity); the
# converters below EXECUTE in int8: weights are stored as int8 with
# per-out-channel scales, activations quantize dynamically per tensor,
# and the matmul runs int8 x int8 -> int32 on the MXU
# (preferred_element_type) — v5e int8 peak is ~2x bf16. Reference
# analogue: the slim int8 inference passes
# (quantization/quantization_pass.py conversions to INT8 kernels).

@register_op("int8_linear", differentiable=False)
def _int8_linear_op(x, w_q, w_scale, bias):
    """x fp -> dynamic per-tensor int8; w_q int8 [in, out] with
    per-out-channel scales; accumulate in int32, rescale to fp32."""
    sx = jnp.maximum(jnp.max(jnp.abs(x)) / 127.0, 1e-8)
    x_q = jnp.clip(jnp.round(x / sx), -127, 127).astype(jnp.int8)
    acc = jax.lax.dot_general(
        x_q, w_q, (((x_q.ndim - 1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32)
    out = acc.astype(jnp.float32) * (sx * w_scale)
    if bias is not None:
        out = out + bias
    return out.astype(x.dtype)  # keep the pipeline's compute dtype


@register_op("int8_dequant_weight_oihw", differentiable=False)
def _int8_dequant_w(w_q, w_scale):
    """Weight-only dequant (per-out-channel, OIHW); XLA fuses it into
    the consuming conv so the HBM read stays int8."""
    return w_q.astype(jnp.float32) * w_scale[:, None, None, None]


class Int8Linear(Layer):
    """W8A8 linear for inference (int8 MXU path)."""

    def __init__(self, layer):
        super().__init__()
        import numpy as np
        w = np.asarray(layer.weight.numpy())        # [in, out]
        scale = np.maximum(np.abs(w).max(axis=0), 1e-8) / 127.0
        self.register_buffer("w_q", Tensor(jnp.asarray(
            np.clip(np.round(w / scale[None, :]), -127, 127)
            .astype(np.int8)), persistable=True))
        self.register_buffer("w_scale", Tensor(jnp.asarray(
            scale.astype(np.float32)), persistable=True))
        self.bias = layer.bias

    def forward(self, x):
        return _int8_linear_op(x, self.w_q, self.w_scale, self.bias)


class Int8Conv2D(Layer):
    """Weight-only-int8 conv for inference: dequant op + the normal
    conv2d path (padding/data_format semantics stay in ONE place)."""

    def __init__(self, layer):
        super().__init__()
        import numpy as np
        w = np.asarray(layer.weight.numpy())        # [out, in, kh, kw]
        scale = np.maximum(np.abs(w).reshape(w.shape[0], -1)
                           .max(axis=1), 1e-8) / 127.0
        self.register_buffer("w_q", Tensor(jnp.asarray(
            np.clip(np.round(w / scale[:, None, None, None]), -127, 127)
            .astype(np.int8)), persistable=True))
        self.register_buffer("w_scale", Tensor(jnp.asarray(
            scale.astype(np.float32)), persistable=True))
        self.bias = layer.bias
        self._cfg = dict(stride=layer._stride, padding=layer._padding,
                         dilation=layer._dilation, groups=layer._groups,
                         data_format=layer._data_format)

    def forward(self, x):
        w = _int8_dequant_w(self.w_q, self.w_scale)
        return nn_ops.conv2d(x, w, self.bias, **self._cfg)


def convert_to_int8(model, layer_types=("Linear", "Conv2D")):
    """Swap Linear->Int8Linear (W8A8) and Conv2D->Int8Conv2D
    (weight-only) in place for inference; returns the model. Run AFTER
    training/PTQ. The swap halves weight HBM and puts linears on the
    int8 MXU path."""
    for name, sub in list(model._sub_layers.items()):
        if "Linear" in layer_types and isinstance(
                sub, (Linear, QuantizedLinear)):
            if isinstance(sub, QuantizedLinear):
                # QAT/PTQ wrapper: reuse its (fake-quant-trained) weight
                lin = Linear.__new__(Linear)
                Layer.__init__(lin)
                lin.weight, lin.bias = sub.weight, sub.bias
                sub = lin
            model._sub_layers[name] = Int8Linear(sub)
        elif "Conv2D" in layer_types and isinstance(
                sub, (Conv2D, QuantizedConv2D)):
            if isinstance(sub, QuantizedConv2D):
                conv = Conv2D.__new__(Conv2D)
                Layer.__init__(conv)
                conv.weight, conv.bias = sub.weight, sub.bias
                for a in ("_stride", "_padding", "_dilation", "_groups",
                          "_data_format"):
                    setattr(conv, a, getattr(sub, a))
                sub = conv
            model._sub_layers[name] = Int8Conv2D(sub)
        else:
            convert_to_int8(sub, layer_types)
    return model
