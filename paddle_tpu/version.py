"""Reference: python/paddle/version.py (generated at build time there;
static here). `paddle.version.full_version` / `paddle.__version__`.
"""
full_version = "2.1.0+tpu.0.1.0"
major = "2"
minor = "1"
patch = "0"
rc = "0"
commit = "tpu-native"
istaged = False

__all__ = ["full_version", "major", "minor", "patch", "rc", "commit",
           "show"]


def show():
    print(f"full_version: {full_version}")
    print(f"major: {major}")
    print(f"minor: {minor}")
    print(f"patch: {patch}")
    print(f"rc: {rc}")
    print(f"commit: {commit}")
