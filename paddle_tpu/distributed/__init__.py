"""paddle.distributed equivalent (reference: python/paddle/distributed/).

TPU-native model: single-controller SPMD over a jax.sharding.Mesh. NCCL
ring groups map to mesh axes; collectives map to XLA collectives (SURVEY
§5 mapping table). Multi-host uses jax.distributed coordination instead of
TCP ncclUniqueId broadcast.
"""
from . import env  # noqa: F401
from .env import get_rank, get_world_size, ParallelEnv  # noqa: F401
from .parallel import init_parallel_env, DataParallel  # noqa: F401
from .collective import (  # noqa: F401
    all_reduce, all_gather, broadcast, reduce, scatter, alltoall,
    reduce_scatter, barrier, wait, new_group, get_group, Group, ReduceOp,
    is_initialized, _c_identity, _mp_allreduce,
)
from . import topology  # noqa: F401
from . import fleet  # noqa: F401
from .launch_mod import spawn, launch  # noqa: F401
from . import sharding  # noqa: F401
from .collective import send, recv, split  # noqa: F401,E402
from .fleet.dataset import InMemoryDataset, QueueDataset  # noqa: F401,E402


class ProbabilityEntry:
    """Reference: distributed/entry_attr.py — sparse-table entry admission
    by show probability."""

    def __init__(self, probability):
        self.probability = float(probability)

    def _to_attr(self):
        return f"probability_entry:{self.probability}"


class CountFilterEntry:
    """Reference: distributed/entry_attr.py — admission after N shows."""

    def __init__(self, count_filter):
        self.count_filter = int(count_filter)

    def _to_attr(self):
        return f"count_filter_entry:{self.count_filter}"
