"""Filesystem clients for checkpoint/dataset plumbing.

Reference parity: python/paddle/distributed/fleet/utils/fs.py:423 —
FS interface, LocalFS, HDFSClient (reference shells out to `hadoop fs`).
HDFSClient here keeps the same surface and raises a clear error when no
hadoop binary exists (zero-egress TPU hosts); auto_checkpoint and dataset
code paths accept any FS implementation.
"""
import os
import shutil
import subprocess


class ExecuteError(Exception):
    pass


class FSFileExistsError(Exception):
    pass


class FSFileNotExistsError(Exception):
    pass


class FS:
    def ls_dir(self, fs_path):
        raise NotImplementedError

    def is_file(self, fs_path):
        raise NotImplementedError

    def is_dir(self, fs_path):
        raise NotImplementedError

    def is_exist(self, fs_path):
        raise NotImplementedError

    def mkdirs(self, fs_path):
        raise NotImplementedError

    def delete(self, fs_path):
        raise NotImplementedError

    def need_upload_download(self):
        return False

    def rename(self, src, dst):
        raise NotImplementedError

    def touch(self, fs_path, exist_ok=True):
        raise NotImplementedError

    def cat(self, fs_path):
        raise NotImplementedError

    def upload(self, local_path, fs_path):
        raise NotImplementedError

    def download(self, fs_path, local_path):
        raise NotImplementedError


class LocalFS(FS):
    """Reference: fs.py:119."""

    def ls_dir(self, fs_path):
        if not self.is_exist(fs_path):
            return [], []
        dirs, files = [], []
        for name in sorted(os.listdir(fs_path)):
            (dirs if os.path.isdir(os.path.join(fs_path, name))
             else files).append(name)
        return dirs, files

    def is_file(self, fs_path):
        return os.path.isfile(fs_path)

    def is_dir(self, fs_path):
        return os.path.isdir(fs_path)

    def is_exist(self, fs_path):
        return os.path.exists(fs_path)

    def mkdirs(self, fs_path):
        os.makedirs(fs_path, exist_ok=True)

    def delete(self, fs_path):
        if os.path.isdir(fs_path):
            shutil.rmtree(fs_path)
        elif os.path.exists(fs_path):
            os.remove(fs_path)

    def rename(self, src, dst):
        os.rename(src, dst)

    def mv(self, src, dst, overwrite=False, test_exists=False):
        if test_exists and not self.is_exist(src):
            raise FSFileNotExistsError(src)
        if not overwrite and self.is_exist(dst):
            raise FSFileExistsError(dst)
        shutil.move(src, dst)

    def touch(self, fs_path, exist_ok=True):
        if self.is_exist(fs_path):
            if not exist_ok:
                raise FSFileExistsError(fs_path)
            return
        open(fs_path, "a").close()

    def cat(self, fs_path):
        with open(fs_path) as f:
            return f.read()

    def upload(self, local_path, fs_path):
        shutil.copy(local_path, fs_path)

    def download(self, fs_path, local_path):
        shutil.copy(fs_path, local_path)

    def list_dirs(self, fs_path):
        return self.ls_dir(fs_path)[0]


class HDFSClient(FS):
    """Reference: fs.py:423 — shells out to `hadoop fs`. Surfaces the
    same API; requires a hadoop binary on PATH."""

    def __init__(self, hadoop_home=None, configs=None, time_out=300000,
                 sleep_inter=1000):
        self._hadoop = (os.path.join(hadoop_home, "bin", "hadoop")
                        if hadoop_home else shutil.which("hadoop"))
        self._configs = configs or {}
        if self._hadoop is None or not os.path.exists(self._hadoop):
            raise ExecuteError(
                "no hadoop binary available on this host; pass hadoop_home "
                "or use LocalFS (TPU hosts checkpoint to local/NFS paths)")

    def _run(self, *args):
        cmd = [self._hadoop, "fs"]
        for k, v in self._configs.items():
            cmd += ["-D", f"{k}={v}"]
        cmd += list(args)
        proc = subprocess.run(cmd, capture_output=True, text=True)
        if proc.returncode != 0:
            raise ExecuteError(f"{' '.join(cmd)}: {proc.stderr}")
        return proc.stdout

    def ls_dir(self, fs_path):
        out = self._run("-ls", fs_path)
        dirs, files = [], []
        for line in out.splitlines():
            parts = line.split()
            if len(parts) < 8:
                continue
            name = os.path.basename(parts[-1])
            (dirs if parts[0].startswith("d") else files).append(name)
        return dirs, files

    def is_exist(self, fs_path):
        try:
            self._run("-test", "-e", fs_path)
            return True
        except ExecuteError:
            return False

    def is_file(self, fs_path):
        try:
            self._run("-test", "-f", fs_path)
            return True
        except ExecuteError:
            return False

    def is_dir(self, fs_path):
        try:
            self._run("-test", "-d", fs_path)
            return True
        except ExecuteError:
            return False

    def mkdirs(self, fs_path):
        self._run("-mkdir", "-p", fs_path)

    def delete(self, fs_path):
        self._run("-rm", "-r", "-f", fs_path)

    def rename(self, src, dst):
        self._run("-mv", src, dst)

    def touch(self, fs_path, exist_ok=True):
        if self.is_exist(fs_path):
            if not exist_ok:
                raise FSFileExistsError(fs_path)
            return  # -touchz would truncate the existing file
        self._run("-touchz", fs_path)

    def cat(self, fs_path):
        return self._run("-cat", fs_path)

    def upload(self, local_path, fs_path):
        self._run("-put", local_path, fs_path)

    def download(self, fs_path, local_path):
        self._run("-get", fs_path, local_path)

    def need_upload_download(self):
        return True
