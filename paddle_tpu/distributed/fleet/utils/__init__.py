"""paddle.distributed.fleet.utils (reference:
python/paddle/distributed/fleet/utils/__init__.py __all__ =
[LocalFS, recompute, DistributedInfer, HDFSClient])."""
from ..utils_fs import LocalFS, HDFSClient  # noqa: F401
from ...utils_recompute import recompute  # noqa: F401


class DistributedInfer:
    """Reference: fleet/utils/ps_util.py DistributedInfer — pulls the
    latest sparse params from the PS before inference. Reduced: with the
    TCP PS, init_distributed_infer_env warms the local cache by pulling
    the listed tables; get_dist_infer_program is the identity (the jit
    program already contains the dense part)."""

    def __init__(self, main_program=None, startup_program=None):
        self._main = main_program

    def init_distributed_infer_env(self, exe=None, loss=None,
                                   role_maker=None, dirname=None):
        from ..fleet_base import ps_client
        client = ps_client()
        if client is not None and dirname:
            client.load(dirname)
        return exe

    def get_dist_infer_program(self):
        return self._main
