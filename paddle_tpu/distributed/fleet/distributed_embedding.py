"""Distributed (parameter-server-equivalent) embedding tables.

Reference: the brpc parameter server's sparse tables
(paddle/fluid/distributed/table/common_sparse_table.h, SSDSparseTable) +
distributed_lookup_table op (operators/pscore/distributed_lookup_table_op)
serve huge embeddings from CPU-cluster RAM with pull/push RPC.

TPU-native design (SURVEY §7 hard part 7 — reduced-scope equivalent):
- DistributedEmbedding: table row-sharded over a mesh axis (HBM across
  chips); lookup is a GSPMD-sharded gather — XLA emits the all-to-all the
  PS pull performed explicitly. Scales table size with chip count.
- HostEmbeddingTable: table lives in host RAM as numpy (the "CPU parameter
  server" role on one host); pull gathers rows to device, push applies
  sparse SGD updates host-side. For tables larger than HBM.
"""
import numpy as np
import jax.numpy as jnp

from ...core.dispatch import register_op, no_grad
from ...core.tensor import Tensor, Parameter
from ...nn.layer_base import Layer
from ...nn import initializer as init_mod
from ...ops import nn_ops
from .meta_parallel.mp_layers import shard_constraint


class DistributedEmbedding(Layer):
    """HBM-sharded embedding: rows sharded over the 'mp' axis (or a given
    axis); gradient is a dense scatter-add XLA handles sharded."""

    def __init__(self, num_embeddings, embedding_dim, axis="mp",
                 weight_attr=None, name=None):
        super().__init__()
        self._axis = axis
        self.weight = self.create_parameter(
            (num_embeddings, embedding_dim),
            attr=init_mod.ParamAttr._to_attr(weight_attr),
            default_initializer=init_mod.Normal(0.0, 0.01))
        self.weight.tp_spec = (axis, None)

    def forward(self, ids):
        w = shard_constraint(self.weight, self.weight.tp_spec)
        return nn_ops.embedding(ids, w)


class HostEmbeddingTable:
    """Host-RAM table with pull/push API (the PS worker's view).

    pull(ids)  -> device Tensor of rows (forward)
    push(ids, grads, lr) -> sparse host-side update (backward apply)
    The (pull, autograd-cut, push) pattern matches the reference's
    DownpourWorker pull/push cycle (framework/fleet/fleet_wrapper.h:69).
    """

    def __init__(self, num_embeddings, embedding_dim, init_std=0.01,
                 optimizer="sgd", seed=0):
        rs = np.random.RandomState(seed)
        self.table = (rs.randn(num_embeddings, embedding_dim)
                      .astype(np.float32) * init_std)
        self.embedding_dim = embedding_dim
        self.optimizer = optimizer
        self._adagrad_acc = None
        if optimizer == "adagrad":
            self._adagrad_acc = np.zeros(num_embeddings, np.float32)

    def pull(self, ids):
        ids_np = ids.numpy() if isinstance(ids, Tensor) else np.asarray(ids)
        rows = self.table[ids_np.reshape(-1)].reshape(
            ids_np.shape + (self.embedding_dim,))
        return Tensor(jnp.asarray(rows))

    @no_grad()
    def push(self, ids, grads, lr=0.01):
        ids_np = (ids.numpy() if isinstance(ids, Tensor)
                  else np.asarray(ids)).reshape(-1)
        g = (grads.numpy() if isinstance(grads, Tensor)
             else np.asarray(grads)).reshape(-1, self.embedding_dim)
        if self.optimizer == "adagrad":
            sq = (g * g).mean(axis=1)
            np.add.at(self._adagrad_acc, ids_np, sq)
            scale = lr / (np.sqrt(self._adagrad_acc[ids_np]) + 1e-6)
            np.subtract.at(self.table, ids_np, g * scale[:, None])
        else:
            np.subtract.at(self.table, ids_np, lr * g)

    def save(self, path):
        np.save(path, self.table)

    def load(self, path):
        self.table = np.load(path)


class HostEmbedding(Layer):
    """Layer wrapper over HostEmbeddingTable: forward pulls rows; backward
    grads accumulate on the pulled Tensor and `apply_push(lr)` pushes them
    back — one pull/push round per step, like the reference's async PS
    worker loop."""

    def __init__(self, num_embeddings, embedding_dim, **kwargs):
        super().__init__()
        self.table = HostEmbeddingTable(num_embeddings, embedding_dim,
                                        **kwargs)
        self._last = None  # (ids, pulled tensor)

    def forward(self, ids):
        pulled = self.table.pull(ids)
        pulled.stop_gradient = False
        self._last = (ids, pulled)
        return pulled

    def apply_push(self, lr=0.01):
        if self._last is None:
            return
        ids, pulled = self._last
        if pulled._grad is not None:
            self.table.push(ids, pulled._grad, lr)
        self._last = None
