"""Distributed (parameter-server-equivalent) embedding tables.

Reference: the brpc parameter server's sparse tables
(paddle/fluid/distributed/table/common_sparse_table.h, SSDSparseTable) +
distributed_lookup_table op (operators/pscore/distributed_lookup_table_op)
serve huge embeddings from CPU-cluster RAM with pull/push RPC.

TPU-native design (SURVEY §7 hard part 7 — reduced-scope equivalent):
- DistributedEmbedding: table row-sharded over a mesh axis (HBM across
  chips); lookup is a GSPMD-sharded gather — XLA emits the all-to-all the
  PS pull performed explicitly. Scales table size with chip count.
- HostEmbeddingTable: table lives in host RAM as numpy (the "CPU parameter
  server" role on one host); pull gathers rows to device, push applies
  sparse SGD updates host-side. For tables larger than HBM.
"""
import numpy as np
import jax.numpy as jnp

from ...core.dispatch import register_op, no_grad
from ...core.tensor import Tensor, Parameter
from ...nn.layer_base import Layer
from ...nn import initializer as init_mod
from ...ops import nn_ops
from .meta_parallel.mp_layers import shard_constraint


def c_embedding(ids, local_weight, axis, start_index):
    """SPMD vocab-parallel lookup INSIDE a shard_map manual region: each
    chip holds rows [start_index, start_index + local_rows); out-of-range
    ids contribute zero locally and the psum over `axis` assembles the
    full rows — the explicit form of the PS 'pull' / reference
    c_embedding op (operators/collective/c_embedding_op.cu). The backward
    of this computation is the masked scatter-add, i.e. each chip
    receives exactly its own rows' gradient (the PS 'push')."""
    import jax
    local_rows = local_weight.shape[0]
    local_ids = ids - start_index
    in_range = (local_ids >= 0) & (local_ids < local_rows)
    safe = jnp.where(in_range, local_ids, 0)
    rows = jnp.take(local_weight, safe, axis=0)
    rows = jnp.where(in_range[..., None], rows, jnp.zeros_like(rows))
    return jax.lax.psum(rows, axis)


class DistributedEmbedding(Layer):
    """HBM-sharded embedding: rows sharded over the 'mp' axis (or a given
    axis). Under GSPMD (to_static) the sharded gather emits the same
    collectives automatically; `use_c_embedding` routes through the
    explicit masked-lookup+psum primitive inside manual regions."""

    def __init__(self, num_embeddings, embedding_dim, axis="mp",
                 weight_attr=None, sparse=False, name=None):
        super().__init__()
        self._axis = axis
        self._sparse = bool(sparse)
        self.weight = self.create_parameter(
            (num_embeddings, embedding_dim),
            attr=init_mod.ParamAttr._to_attr(weight_attr),
            default_initializer=init_mod.Normal(0.0, 0.01))
        self.weight.tp_spec = (axis, None)

    def forward(self, ids):
        w = shard_constraint(self.weight, self.weight.tp_spec)
        return nn_ops.embedding(ids, w, sparse=self._sparse)


class HostEmbeddingTable:
    """Host-RAM table with pull/push API (the PS worker's view).

    pull(ids)  -> device Tensor of rows (forward)
    push(ids, grads, lr) -> sparse host-side update (backward apply)
    The (pull, autograd-cut, push) pattern matches the reference's
    DownpourWorker pull/push cycle (framework/fleet/fleet_wrapper.h:69).
    """

    def __init__(self, num_embeddings, embedding_dim, init_std=0.01,
                 optimizer="sgd", seed=0):
        rs = np.random.RandomState(seed)
        self.table = (rs.randn(num_embeddings, embedding_dim)
                      .astype(np.float32) * init_std)
        self.embedding_dim = embedding_dim
        self.optimizer = optimizer
        self._adagrad_acc = None
        if optimizer == "adagrad":
            self._adagrad_acc = np.zeros(num_embeddings, np.float32)

    def pull(self, ids):
        ids_np = ids.numpy() if isinstance(ids, Tensor) else np.asarray(ids)
        rows = self.table[ids_np.reshape(-1)].reshape(
            ids_np.shape + (self.embedding_dim,))
        return Tensor(jnp.asarray(rows))

    @no_grad()
    def push(self, ids, grads, lr=0.01):
        ids_np = (ids.numpy() if isinstance(ids, Tensor)
                  else np.asarray(ids)).reshape(-1)
        g = (grads.numpy() if isinstance(grads, Tensor)
             else np.asarray(grads)).reshape(-1, self.embedding_dim)
        if self.optimizer == "adagrad":
            sq = (g * g).mean(axis=1)
            np.add.at(self._adagrad_acc, ids_np, sq)
            scale = lr / (np.sqrt(self._adagrad_acc[ids_np]) + 1e-6)
            np.subtract.at(self.table, ids_np, g * scale[:, None])
        else:
            np.subtract.at(self.table, ids_np, lr * g)

    def push_sparse(self, slices, lr=0.01):
        """Apply an IndexedSlices gradient (core/sparse_grad.py) directly
        — the SelectedRows push the reference Communicator sends
        (distributed/service/communicator.h:348). Duplicates are merged
        first (reference scatter::MergeAdd) so adagrad scaling sees one
        summed row per id."""
        slices = slices.coalesce()
        ids = np.asarray(slices.indices).reshape(-1)
        g = np.asarray(slices.values).reshape(-1, self.embedding_dim)
        self.push(ids, g, lr)

    def save(self, path):
        """Persist full server state (table + optimizer accumulators) —
        reference: sparse table save/load
        (distributed/table/common_sparse_table.h Save/Load)."""
        state = {"table": self.table, "optimizer": self.optimizer}
        if self._adagrad_acc is not None:
            state["adagrad_acc"] = self._adagrad_acc
        np.savez(path, **state)

    def load(self, path):
        import os
        if not os.path.exists(path) and not str(path).endswith(".npz"):
            path = str(path) + ".npz"
        data = np.load(path, allow_pickle=False)
        if hasattr(data, "files"):  # npz: full server state
            self.table = data["table"]
            if "optimizer" in data.files:
                self.optimizer = str(data["optimizer"])
            if "adagrad_acc" in data.files:
                self._adagrad_acc = data["adagrad_acc"]
            elif self.optimizer == "adagrad":
                self._adagrad_acc = np.zeros(self.table.shape[0],
                                             np.float32)
            else:
                self._adagrad_acc = None
        else:  # legacy single-array .npy format
            self.table = data


class HostEmbedding(Layer):
    """Layer wrapper over HostEmbeddingTable: forward pulls rows; backward
    grads accumulate on the pulled Tensor and `apply_push(lr)` pushes them
    back — one pull/push round per step, like the reference's async PS
    worker loop."""

    def __init__(self, num_embeddings, embedding_dim, **kwargs):
        super().__init__()
        self.table = HostEmbeddingTable(num_embeddings, embedding_dim,
                                        **kwargs)
        self._last = None  # (ids, pulled tensor)

    def forward(self, ids):
        pulled = self.table.pull(ids)
        pulled.stop_gradient = False
        self._last = (ids, pulled)
        return pulled

    def apply_push(self, lr=0.01):
        if self._last is None:
            return
        ids, pulled = self._last
        if pulled._grad is not None:
            self.table.push(ids, pulled._grad, lr)
        self._last = None
