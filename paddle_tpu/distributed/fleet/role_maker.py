"""Role makers: derive this process's role in a PS/collective cluster.

Reference parity: python/paddle/distributed/fleet/base/role_maker.py
(PaddleCloudRoleMaker reads the launcher's env: TRAINING_ROLE,
PADDLE_PSERVERS_IP_PORT_LIST, PADDLE_TRAINER_ENDPOINTS,
PADDLE_TRAINER_ID, PADDLE_PORT/POD_IP; UserDefinedRoleMaker takes
explicit values).
"""
import os


class Role:
    WORKER = 1
    SERVER = 2
    HETER_WORKER = 3
    ALL = 4


class RoleMakerBase:
    def _is_worker(self):
        return self._role == Role.WORKER

    def _is_server(self):
        return self._role == Role.SERVER

    def _worker_index(self):
        return self._current_id if self._role == Role.WORKER else -1

    def _server_index(self):
        return self._current_id if self._role == Role.SERVER else -1

    def _worker_num(self):
        return len(self._worker_endpoints)

    def _server_num(self):
        return len(self._server_endpoints)

    def _get_trainer_endpoints(self):
        return list(self._worker_endpoints)

    def _get_pserver_endpoints(self):
        return list(self._server_endpoints)


class PaddleCloudRoleMaker(RoleMakerBase):
    """Reference: role_maker.py PaddleCloudRoleMaker — env-driven."""

    def __init__(self, is_collective=False, **kwargs):
        self._is_collective = is_collective
        self._server_endpoints = [
            e for e in os.environ.get("PADDLE_PSERVERS_IP_PORT_LIST",
                                      "").split(",") if e]
        self._worker_endpoints = [
            e for e in os.environ.get("PADDLE_TRAINER_ENDPOINTS",
                                      "").split(",") if e]
        role = os.environ.get("TRAINING_ROLE", "TRAINER").upper()
        if is_collective or role in ("TRAINER", "WORKER"):
            self._role = Role.WORKER
            self._current_id = int(os.environ.get("PADDLE_TRAINER_ID", 0))
        else:
            self._role = Role.SERVER
            ip = os.environ.get("POD_IP", "127.0.0.1")
            port = os.environ.get("PADDLE_PORT", "0")
            ep = f"{ip}:{port}"
            if self._server_endpoints and ep not in self._server_endpoints:
                raise ValueError(
                    f"current endpoint {ep} (POD_IP:PADDLE_PORT) is not "
                    f"in PADDLE_PSERVERS_IP_PORT_LIST "
                    f"{self._server_endpoints}")
            self._current_id = (self._server_endpoints.index(ep)
                                if self._server_endpoints else 0)


class UserDefinedRoleMaker(RoleMakerBase):
    """Reference: role_maker.py UserDefinedRoleMaker — explicit args."""

    def __init__(self, is_collective=False, current_id=0, role=Role.WORKER,
                 worker_num=None, worker_endpoints=None,
                 server_endpoints=None, **kwargs):
        self._is_collective = is_collective
        self._role = role
        self._current_id = int(current_id)
        self._worker_endpoints = list(worker_endpoints or [])
        if worker_num and not self._worker_endpoints:
            self._worker_endpoints = [""] * int(worker_num)
        self._server_endpoints = list(server_endpoints or [])
