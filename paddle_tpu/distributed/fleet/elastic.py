"""Elastic training manager.

Reference parity: python/paddle/distributed/fleet/elastic.py:101
ElasticManager — etcd3 node registry (:144-147), membership watchers
(:173-206), relaunch of local procs with updated endpoints; launcher child
monitoring (LauncherInterface._check_procs :75).

TPU-native reduction: coordination runs over a shared-filesystem heartbeat
store (a directory visible to all hosts — on cloud TPU pods typically GCS
or NFS; etcd is not part of this image). Each node writes a heartbeat file;
the watcher detects joins/leaves by scanning heartbeats; on membership
change the registered callback re-initializes jax.distributed and resumes
from the latest auto-checkpoint. Scale-in/out = world size change between
restarts, which jax.distributed.initialize handles by re-forming the
coordination service.
"""
import json
import os
import signal
import subprocess
import threading
import time


class ElasticStatus:
    COMPLETED = "completed"
    ERROR = "error"
    HOLD = "hold"
    RESTART = "restart"
    EXIT = "exit"


class FileStore:
    """Heartbeat registry on a shared filesystem (etcd stand-in)."""

    def __init__(self, root, ttl=10.0):
        self.root = root
        self.ttl = ttl
        os.makedirs(root, exist_ok=True)

    def register(self, node_id, info=None):
        path = os.path.join(self.root, f"{node_id}.hb")
        with open(path, "w") as f:
            json.dump({"ts": time.time(), "info": info or {}}, f)

    def heartbeat(self, node_id):
        self.register(node_id)

    def alive_nodes(self):
        now = time.time()
        nodes = []
        for fn in os.listdir(self.root):
            if not fn.endswith(".hb"):
                continue
            try:
                with open(os.path.join(self.root, fn)) as f:
                    data = json.load(f)
                if now - data["ts"] <= self.ttl:
                    nodes.append(fn[:-3])
            except (OSError, ValueError):
                continue
        return sorted(nodes)

    def deregister(self, node_id):
        try:
            os.remove(os.path.join(self.root, f"{node_id}.hb"))
        except OSError:
            pass


class ElasticManager:
    def __init__(self, node_id=None, store=None, store_root=None,
                 heartbeat_interval=2.0, on_membership_change=None):
        self.node_id = node_id or f"node-{os.getpid()}"
        self.store = store or FileStore(store_root or "/tmp/paddle_tpu_elastic")
        self.interval = heartbeat_interval
        self.on_membership_change = on_membership_change
        self._members = []
        self._stop = threading.Event()
        self._thread = None
        self._procs = []

    # -- membership --------------------------------------------------------
    def start(self):
        self.store.register(self.node_id)
        self._members = self.store.alive_nodes()
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()
        return self._members

    def _loop(self):
        while not self._stop.is_set():
            self.store.heartbeat(self.node_id)
            current = self.store.alive_nodes()
            if current != self._members:
                old, self._members = self._members, current
                if self.on_membership_change is not None:
                    self.on_membership_change(old, current)
            self._stop.wait(self.interval)

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
        self.store.deregister(self.node_id)

    def world(self):
        return list(self._members)

    # -- child process supervision (launcher role) ------------------------
    def launch(self, cmd, env=None):
        e = dict(os.environ)
        if env:
            e.update(env)
        p = subprocess.Popen(cmd, env=e)
        self._procs.append(p)
        return p

    def check_procs(self):
        """Reference: LauncherInterface._check_procs — returns
        (all_done, failed_list)."""
        failed = []
        alive = False
        for p in self._procs:
            rc = p.poll()
            if rc is None:
                alive = True
            elif rc != 0:
                failed.append((p.pid, rc))
        return (not alive), failed

    def kill_children(self):
        for p in self._procs:
            if p.poll() is None:
                p.send_signal(signal.SIGTERM)
        deadline = time.time() + 10
        for p in self._procs:
            try:
                p.wait(timeout=max(0.1, deadline - time.time()))
            except subprocess.TimeoutExpired:
                p.kill()
        self._procs = []

    def relaunch(self, cmd, env=None):
        """Membership changed: kill current children, restart with updated
        world info (reference relaunch with new DISTRIBUTED_TRAINER_ENDPOINTS)."""
        self.kill_children()
        world = ",".join(self.world())
        e = {"PADDLE_ELASTIC_WORLD": world,
             "PADDLE_TRAINERS_NUM": str(len(self.world()))}
        if env:
            e.update(env)
        return self.launch(cmd, env=e)
