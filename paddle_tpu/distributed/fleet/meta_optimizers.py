"""Fleet meta-optimizers: GradientMerge, LocalSGD, DGC, FP16-allreduce.

Reference parity: python/paddle/distributed/fleet/meta_optimizers/
{gradient_merge_optimizer.py, localsgd_optimizer.py, dgc_optimizer.py,
fp16_allreduce_optimizer.py} and operators/optimizers/dgc_momentum_op.cc.
The reference implements these as ProgramDesc rewrites; here each one is a
gradient/step transform wrapping the inner optimizer — the compiled step
traces through the wrapper, so XLA fuses the extra work into the update
and GSPMD inserts the collective traffic where the mesh requires it.
"""
import jax
import jax.numpy as jnp

from ...core.dispatch import register_op, no_grad
from ...optimizer.optimizers import Momentum
from ...optimizer.optimizer import WrappedOptimizer as _WrappedOptimizer


class GradientMergeOptimizer(_WrappedOptimizer):
    """Accumulate grads for k_steps before one real update (reference:
    gradient_merge_optimizer.py — static rewrite w/ cond block +
    GradMergeAllReduceOpHandle; here: carry a merge buffer per param and
    gate the inner step on step%k)."""

    def __init__(self, inner_opt, k_steps=1, avg=True):
        super().__init__(inner_opt)
        self._k = max(1, int(k_steps))
        self._avg = bool(avg)
        self._step_idx = 0
        self._buffers = {}

    @no_grad()
    def step(self):
        from ...core.tensor import Tensor
        self._step_idx += 1
        params = self._inner_opt._parameter_list()
        final = self._step_idx % self._k == 0
        for p in params:
            if p._grad is None or not p.trainable:
                continue
            g = p._grad.value.astype(jnp.float32)
            acc = self._buffers.get(id(p))
            acc = g if acc is None else acc + g
            if final:
                merged = acc / self._k if self._avg else acc
                p._grad.value = merged.astype(p._grad.value.dtype)
                self._buffers.pop(id(p), None)
            else:
                self._buffers[id(p)] = acc
        if final:
            # flush buffers of params that saw grads earlier in the cycle
            # but have none this step — a leftover buffer must not leak
            # into the next cycle (it would merge a stale cycle's grads)
            if self._buffers:
                by_id = {id(p): p for p in params}
                for pid, acc in list(self._buffers.items()):
                    p = by_id.get(pid)
                    if p is not None:
                        merged = acc / self._k if self._avg else acc
                        p._grad = Tensor(merged)
                self._buffers.clear()
            self._inner_opt.step()


class LocalSGDOptimizer(_WrappedOptimizer):
    """Step locally every iteration; average parameters across the data-
    parallel group every k_steps (reference: localsgd_optimizer.py inserts
    c_allreduce on params inside a cond block). begin_step delays the
    first sync like the reference's `begin_step` config."""

    def __init__(self, inner_opt, k_steps=1, begin_step=1, group=None):
        super().__init__(inner_opt)
        self._k = max(1, int(k_steps))
        self._begin = int(begin_step)
        self._group = group
        self._step_idx = 0

    @no_grad()
    def step(self):
        self._inner_opt.step()
        self._step_idx += 1
        if self._step_idx >= self._begin and self._step_idx % self._k == 0:
            self._sync_params()

    def _sync_params(self):
        """In single-controller SPMD, dp replicas of a parameter are
        bitwise equal by construction (GSPMD psums grads inside the step),
        so the reference's c_allreduce(param)/nranks sync is the identity
        — device-sharded params (ZeRO-3 / expert weights) hold DISTINCT
        logical rows per shard and must never be averaged across them.
        The averaging is only a real operation in multi-process
        (jax.distributed) runs where each process owns an independent
        replica of the addressable values."""
        import jax as _jax
        if _jax.process_count() <= 1:
            return
        from .. import collective
        for p in self._inner_opt._parameter_list():
            if not p.trainable or p._value is None:
                continue
            sharding = getattr(p._value, "sharding", None)
            if sharding is not None and not getattr(
                    sharding, "is_fully_replicated", True):
                continue  # distinct shards per device — never average
            collective.all_reduce(p, op="avg", group=self._group)


class AdaptiveLocalSGDOptimizer(LocalSGDOptimizer):
    """Adaptive sync interval (reference: localsgd_optimizer.py
    AdaptiveLocalSGDOptimizer — interval adapts to training-loss
    progress). minimize() observes the loss: while the loss is still
    improving the interval stays short; when progress stalls relative to
    the best seen, syncing more often cannot help and k grows (capped).
    Plain step() calls (no loss visible) keep the current interval."""

    def __init__(self, inner_opt, init_k_steps=1, begin_step=1, group=None,
                 max_k_steps=16):
        super().__init__(inner_opt, init_k_steps, begin_step, group)
        self._max_k = int(max_k_steps)
        self._best_loss = None

    def minimize(self, loss, startup_program=None, parameters=None,
                 no_grad_set=None):
        loss.backward()
        self.step()
        cur = float(loss.numpy())
        if self._best_loss is None or cur < self._best_loss * 0.999:
            self._best_loss = min(cur, self._best_loss or cur)
        else:  # progress stalled → lengthen the interval
            self._k = min(self._max_k, self._k * 2)
        return None, None


class FP16AllReduceOptimizer(_WrappedOptimizer):
    """Compress gradients to 16-bit before the data-parallel reduction
    (reference: fp16_allreduce_optimizer.py casts grads fp32→fp16 around
    c_allreduce). On TPU the natural wire format is bfloat16."""

    def __init__(self, inner_opt, dtype="bfloat16"):
        super().__init__(inner_opt)
        self._wire_dtype = jnp.bfloat16 if dtype == "bfloat16" else jnp.float16

    @no_grad()
    def step(self):
        for p in self._inner_opt._parameter_list():
            if p._grad is not None and p.trainable:
                g = p._grad.value
                p._grad.value = g.astype(self._wire_dtype).astype(g.dtype)
        self._inner_opt.step()


def _dgc_sparsity(global_step, rampup_begin_step, rampup_step, sparsity):
    """Reference dgc.py get_sparsity: step through the sparsity list over
    the rampup window, then hold the final value."""
    if global_step < rampup_begin_step:
        return 0.0
    progress = global_step - rampup_begin_step
    if rampup_step <= 0 or progress >= rampup_step:
        return float(sparsity[-1])
    idx = int(progress * len(sparsity) / rampup_step)
    return float(sparsity[min(idx, len(sparsity) - 1)])


@register_op("dgc_momentum_update", differentiable=False)
def _dgc_update(param, grad, u, v, lr, *, mu, ratio, wd):
    """DGC: momentum correction + top-k sparsification. The kept top-k
    fraction (`ratio` = 1 - sparsity) is exchanged; the residual stays in
    the local velocity accumulators (reference: dgc_op + dgc_momentum_op).
    Under GSPMD the sparse exchange becomes a dense psum of the masked
    tensor — semantics (residual accumulation / delayed updates) match."""
    g = grad.astype(jnp.float32)
    p32 = param.astype(jnp.float32)
    if wd:
        g = g + wd * p32
    u_new = mu * u + g
    v_new = v + u_new
    flat = jnp.abs(v_new).ravel()
    k = max(1, int(flat.shape[0] * ratio))
    thr = jax.lax.top_k(flat, k)[0][-1]
    mask = (jnp.abs(v_new) >= thr).astype(jnp.float32)
    encoded = v_new * mask
    v_out = v_new * (1.0 - mask)
    u_out = u_new * (1.0 - mask)
    new_p = p32 - lr * encoded
    return new_p.astype(param.dtype), u_out, v_out


class DGCMomentumOptimizer(Momentum):
    """Deep-gradient-compression momentum (reference: dgc_optimizer.py
    swaps user Momentum for DGCMomentumOptimizer when strategy.dgc;
    operators/optimizers/dgc_momentum_op). Before rampup_begin_step it is
    exactly Momentum; after, top-k sparsified updates with residual
    accumulation."""

    def __init__(self, learning_rate, momentum=0.9, parameters=None,
                 use_nesterov=False, weight_decay=None, grad_clip=None,
                 rampup_begin_step=0, rampup_step=1, sparsity=(0.999,),
                 name=None):
        super().__init__(learning_rate, momentum=momentum,
                         parameters=parameters, use_nesterov=use_nesterov,
                         weight_decay=weight_decay, grad_clip=grad_clip,
                         name=name)
        self._rampup_begin = int(rampup_begin_step)
        self._rampup_step = int(rampup_step)
        self._sparsity = list(sparsity)
        self._global_step = 0

    @no_grad()
    def step(self):
        super().step()
        self._global_step += 1

    def _apply_one(self, p, g):
        s = _dgc_sparsity(self._global_step, self._rampup_begin,
                          self._rampup_step, self._sparsity)
        numel = 1
        for d in p.aval_shape():
            numel *= int(d)
        if s <= 0.0 or numel < 16:
            # warmup / tiny params: vanilla momentum (reference keeps
            # small tensors dense too)
            return super()._apply_one(p, g)
        shape = tuple(p.aval_shape())
        u = self._acc("dgc_u", p, shape=shape, dtype=jnp.float32)
        v = self._acc("dgc_v", p, shape=shape, dtype=jnp.float32)
        new_p, u_n, v_n = _dgc_update(p, g, u, v, self._lr_tensor,
                                      mu=self._momentum, ratio=1.0 - s,
                                      wd=self._weight_decay)
        p.value = new_p.value
        u.value = u_n.value
        v.value = v_n.value


def apply_meta_optimizers(optimizer, strategy):
    """StrategyCompiler equivalent (reference:
    fleet/base/strategy_compiler.py): pick and chain the meta-optimizers
    the strategy enables. Order (innermost first): dgc swap → fp16
    allreduce → gradient merge → localsgd."""
    if strategy is None:
        return optimizer
    if getattr(strategy, "dgc", False) and isinstance(optimizer, Momentum) \
            and not isinstance(optimizer, DGCMomentumOptimizer):
        cfg = getattr(strategy, "dgc_configs", {}) or {}
        dgc = DGCMomentumOptimizer(
            optimizer._lr_scheduler or float(optimizer.get_lr()),
            momentum=optimizer._momentum,
            parameters=optimizer._param_groups,
            use_nesterov=optimizer._use_nesterov,
            weight_decay=optimizer._weight_decay or None,
            grad_clip=optimizer._grad_clip,
            rampup_begin_step=cfg.get("rampup_begin_step", 0),
            rampup_step=cfg.get("rampup_step", 1),
            sparsity=cfg.get("sparsity", [0.999]))
        optimizer = dgc
    if getattr(strategy, "fp16_allreduce", False):
        optimizer = FP16AllReduceOptimizer(optimizer)
    if getattr(strategy, "gradient_merge", False):
        cfg = strategy.gradient_merge_configs
        optimizer = GradientMergeOptimizer(optimizer,
                                           k_steps=cfg.get("k_steps", 1),
                                           avg=cfg.get("avg", True))
    if getattr(strategy, "localsgd", False):
        cfg = strategy.localsgd_configs
        optimizer = LocalSGDOptimizer(optimizer,
                                      k_steps=cfg.get("k_steps", 1),
                                      begin_step=cfg.get("begin_step", 1))
    elif getattr(strategy, "adaptive_localsgd", False):
        cfg = getattr(strategy, "adaptive_localsgd_configs", {}) or {}
        optimizer = AdaptiveLocalSGDOptimizer(
            optimizer, init_k_steps=cfg.get("init_k_steps", 1),
            begin_step=cfg.get("begin_step", 1))
    return optimizer
