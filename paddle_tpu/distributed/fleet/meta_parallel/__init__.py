from .mp_layers import (  # noqa: F401
    VocabParallelEmbedding, ColumnParallelLinear, RowParallelLinear,
    ParallelCrossEntropy,
)
from .parallel_wrappers import (  # noqa: F401
    TensorParallel, PipelineParallel, ShardingParallel,
)
from .pp_layers import PipelineLayer, LayerDesc, SharedLayerDesc  # noqa: F401
from .random import RNGStatesTracker, get_rng_state_tracker  # noqa: F401
