"""RNG state tracking for tensor parallelism.

Reference parity: python/paddle/distributed/fleet/meta_parallel/
parallel_layers/random.py:24 RNGStatesTracker — separate RNG streams so
dropout inside TP regions is identical across TP ranks while differing
across DP ranks. TPU-native: a named registry of Generator states; under
the single-controller SPMD model a dropout mask computed from one global
key is already consistent across the mp shards of an activation, so the
tracker mainly provides API + determinism control.
"""
from contextlib import contextmanager

from ....core.rng import Generator

MODEL_PARALLEL_RNG = "model_parallel_rng"


class RNGStatesTracker:
    def __init__(self):
        self.states_ = {}
        self.seeds_ = set()

    def reset(self):
        self.states_ = {}
        self.seeds_ = set()

    def add(self, name, seed):
        if seed in self.seeds_:
            raise ValueError(f"seed {seed} already added")
        if name in self.states_:
            raise ValueError(f"state {name} already added")
        self.seeds_.add(seed)
        self.states_[name] = Generator(seed)

    def get_states_tracker(self):
        return dict(self.states_)

    def set_states_tracker(self, states):
        self.states_ = states

    @contextmanager
    def rng_state(self, name=MODEL_PARALLEL_RNG):
        if name not in self.states_:
            raise ValueError(f"state {name} not added")
        from ....core import rng as rng_mod
        prev = rng_mod.default_generator
        rng_mod.default_generator = self.states_[name]
        try:
            yield
        finally:
            rng_mod.default_generator = prev


_RNG_STATE_TRACKER = RNGStatesTracker()


def get_rng_state_tracker():
    return _RNG_STATE_TRACKER


def model_parallel_random_seed(seed=None):
    import random
    global _RNG_STATE_TRACKER
    seed = seed or (random.randint(0, 1 << 30))
    _RNG_STATE_TRACKER.reset()
    _RNG_STATE_TRACKER.add(MODEL_PARALLEL_RNG, seed)
