"""Sequence-parallel attention layers over the 'sp' mesh axis.

Greenfield (SURVEY §5/§7 step 8): exposes ring attention / Ulysses through
the framework op dispatcher so eager Tensors and to_static traces both
work. `sp_degree` in fleet hybrid_configs sizes the axis.
"""
import jax

from ....core.dispatch import register_op
from ....core.tensor import Tensor
from ....ops import ring_attention as ra
from ... import topology

_MESHES = {}


@register_op("ring_attention")
def _ring_op(q, k, v, *, mesh_id, causal, scale):
    return ra.ring_attention(q, k, v, _MESHES[mesh_id], causal=causal,
                             scale=scale)


@register_op("ulysses_attention")
def _ulysses_op(q, k, v, *, mesh_id, causal, scale):
    return ra.ulysses_attention(q, k, v, _MESHES[mesh_id], causal=causal,
                                scale=scale)


def _dispatch(op, q, k, v, causal, scale, mesh):
    mesh = mesh or topology.get_mesh()
    if mesh is None or int(mesh.shape.get("sp", 1)) == 1:
        from ....ops.attention import scaled_dot_product_attention
        return scaled_dot_product_attention(q, k, v, is_causal=causal,
                                            scale=scale)
    _MESHES[id(mesh)] = mesh
    return op(q, k, v, mesh_id=id(mesh), causal=bool(causal), scale=scale)


def ring_attention(q, k, v, causal=True, scale=None, mesh=None):
    """Context-parallel attention; q/k/v logical [B, H, S, D], sequence
    sharded over 'sp'. O(S/sp) HBM per chip; K/V ride the ICI ring."""
    return _dispatch(_ring_op, q, k, v, causal, scale, mesh)


def ulysses_attention(q, k, v, causal=True, scale=None, mesh=None):
    """All-to-all sequence parallelism (heads must divide sp)."""
    return _dispatch(_ulysses_op, q, k, v, causal, scale, mesh)


class SequenceParallelAttention:
    """Config-selectable SP attention kernel for model code."""

    def __init__(self, mode="ring", causal=True):
        assert mode in ("ring", "ulysses")
        self.mode = mode
        self.causal = causal

    def __call__(self, q, k, v, scale=None):
        fn = ring_attention if self.mode == "ring" else ulysses_attention
        return fn(q, k, v, causal=self.causal, scale=scale)
