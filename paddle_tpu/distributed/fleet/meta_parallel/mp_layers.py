"""Megatron-style tensor-parallel layers.

Reference parity: python/paddle/distributed/fleet/meta_parallel/
parallel_layers/mp_layers.py (VocabParallelEmbedding:30,
ColumnParallelLinear:97, RowParallelLinear:170, ParallelCrossEntropy:249)
over c_embedding / c_identity / c_allreduce_sum ops.

TPU-native design: instead of materializing per-rank weight shards and
inserting explicit collectives, each layer holds the FULL logical weight
annotated with a NamedSharding over the 'mp' mesh axis. Under jit/pjit,
GSPMD partitions the matmuls and inserts the same all-reduce/all-gather
pattern Megatron does (column-parallel: activations sharded on features,
row-parallel: psum on output) — laid out on ICI. The user-visible layer
API matches the reference, and state_dict holds full weights (so
checkpoints are topology-independent, an improvement over per-rank
shards).
"""
import jax
from jax.sharding import NamedSharding, PartitionSpec as P

from ....core.dispatch import register_op
from ....nn.layer_base import Layer
from ....nn import initializer as init_mod
from ....ops import nn_ops
from ... import topology


def _axis_is_manual(name):
    """True when `name` is currently a bound (manual) axis — i.e. we
    are tracing inside a shard_map/pmap body over it. A GSPMD sharding
    constraint over a manual axis is invalid (the data is already
    per-device there), so callers skip the hint."""
    try:
        from jax._src.core import axis_frame
    except ImportError:
        return False
    try:
        axis_frame(name)
        return True
    except NameError:
        return False


@register_op("sharding_constraint")
def _constraint(x, *, spec, mesh_id):
    mesh = _MESH_REGISTRY[mesh_id]
    axes = []
    for ax in spec:  # spec entries: name | tuple of names | None
        if isinstance(ax, str):
            axes.append(ax)
        elif isinstance(ax, (tuple, list)):
            axes.extend(a for a in ax if isinstance(a, str))
    if any(_axis_is_manual(ax) for ax in axes):
        # full-manual shard_map (older jax without partial-auto
        # axis_names): data is per-device; the hint is meaningless —
        # and with_sharding_constraint would reject the spec at
        # lowering time with an opaque manual_axes ValueError
        return x
    try:
        return jax.lax.with_sharding_constraint(
            x, NamedSharding(mesh, P(*spec)))
    except ValueError as e:
        if "manual" in str(e):
            return x
        raise


_MESH_REGISTRY = {}


def shard_constraint(t, spec, mesh=None):
    """Annotate a tensor with a mesh sharding INSIDE a compiled (to_static)
    graph. Outside a jit trace this is a no-op by design: eager phases stay
    single-device so no eager sub-group collectives are ever launched (the
    CPU backend deadlocks on those, and on TPU they would serialize);
    GSPMD materializes all sharding when the step compiles."""
    from ....core import trace as trace_mod
    ctx = trace_mod.current_trace()
    if ctx is None or ctx.mode != "jit":
        return t
    mesh = mesh or topology.get_mesh()
    if mesh is None:
        return t
    mid = id(mesh)
    _MESH_REGISTRY[mid] = mesh
    return _constraint(t, spec=tuple(spec), mesh_id=mid)


def _shard_param(param, spec, mesh=None):
    """Record the parameter's tensor-parallel placement; applied as a
    sharding constraint in the layer's forward when the step compiles."""
    param.tp_spec = tuple(spec)
    return param


class VocabParallelEmbedding(Layer):
    """Reference: mp_layers.py:30 — vocab dimension sharded over mp."""

    def __init__(self, num_embeddings, embedding_dim, weight_attr=None,
                 mp_group=None, name=None):
        super().__init__()
        self._num_embeddings = num_embeddings
        self._embedding_dim = embedding_dim
        self.weight = self.create_parameter(
            (num_embeddings, embedding_dim),
            attr=init_mod.ParamAttr._to_attr(weight_attr),
            default_initializer=init_mod.XavierNormal())
        _shard_param(self.weight, ("mp", None))

    def forward(self, x):
        w = shard_constraint(self.weight, self.weight.tp_spec)
        out = nn_ops.embedding(x, w)
        return out


class ColumnParallelLinear(Layer):
    """Reference: mp_layers.py:97 — output features sharded over mp;
    gather_output=False keeps activations feature-sharded for the following
    RowParallelLinear (the Megatron pattern)."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 has_bias=True, gather_output=True, fuse_matmul_bias=False,
                 mp_group=None, name=None):
        super().__init__()
        self._in_features = in_features
        self._out_features = out_features
        self.gather_output = gather_output
        self.weight = self.create_parameter(
            (in_features, out_features),
            attr=init_mod.ParamAttr._to_attr(weight_attr))
        self.bias = self.create_parameter(
            (out_features,), is_bias=True) if has_bias else None
        _shard_param(self.weight, (None, "mp"))
        if self.bias is not None:
            _shard_param(self.bias, ("mp",))

    def forward(self, x):
        w = shard_constraint(self.weight, self.weight.tp_spec)
        b = None if self.bias is None else \
            shard_constraint(self.bias, self.bias.tp_spec)
        out = nn_ops.linear(x, w, b)
        if self.gather_output:
            out = shard_constraint(out, (None,) * len(out.shape))
        else:
            out = shard_constraint(
                out, (None,) * (len(out.shape) - 1) + ("mp",))
        return out


class RowParallelLinear(Layer):
    """Reference: mp_layers.py:170 — input features sharded over mp; output
    is the psum of partial matmuls (GSPMD inserts it)."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 has_bias=True, input_is_parallel=False,
                 fuse_matmul_bias=False, mp_group=None, name=None):
        super().__init__()
        self._in_features = in_features
        self._out_features = out_features
        self.input_is_parallel = input_is_parallel
        self.weight = self.create_parameter(
            (in_features, out_features),
            attr=init_mod.ParamAttr._to_attr(weight_attr))
        self.bias = self.create_parameter(
            (out_features,), is_bias=True) if has_bias else None
        _shard_param(self.weight, ("mp", None))

    def forward(self, x):
        if self.input_is_parallel:
            x = shard_constraint(x, (None,) * (len(x.shape) - 1) + ("mp",))
        w = shard_constraint(self.weight, self.weight.tp_spec)
        out = nn_ops.linear(x, w, self.bias)
        out = shard_constraint(out, (None,) * len(out.shape))
        return out


class ParallelCrossEntropy(Layer):
    """Reference: mp_layers.py:249 over c_softmax_with_cross_entropy —
    cross entropy on vocab-sharded logits. GSPMD computes the partitioned
    log-softmax reduction without materializing gathered logits when the
    logits carry an mp sharding."""

    def __init__(self, mp_group=None, name=None, ignore_index=-100):
        super().__init__()
        self.ignore_index = ignore_index

    def forward(self, input, label):  # noqa: A002
        return nn_ops.softmax_with_cross_entropy(
            input, label, ignore_index=self.ignore_index)
