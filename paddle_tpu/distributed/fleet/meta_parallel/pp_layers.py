"""Pipeline layer declaration.

Reference parity: python/paddle/distributed/fleet/meta_parallel/
parallel_layers/pp_layers.py (PipelineLayer:23, LayerDesc, SharedLayerDesc:62,
segmentation by layer count or parameter count:76).

TPU-native: PipelineLayer keeps the declarative stage-partition API; the
schedule executes micro-batches through stage segments (see
pipeline_parallel.py). Stage placement is a mesh-axis concern, not a
process concern: stage s parameters are tagged so the runtime can place
them on the pp=s mesh slice.
"""
import numpy as np

from ....nn.layer_base import Layer
from ....nn.layer.container import LayerList


class LayerDesc:
    def __init__(self, layer_cls, *args, **kwargs):
        self.layer_cls = layer_cls
        self.args = args
        self.kwargs = kwargs

    def build_layer(self):
        return self.layer_cls(*self.args, **self.kwargs)

    def __repr__(self):
        return f"LayerDesc({self.layer_cls.__name__})"


class SharedLayerDesc(LayerDesc):
    """Reference: pp_layers.py:62 — layer shared between stages (e.g. tied
    embeddings); in the single-controller model sharing is simply the same
    Layer object appearing in both segments."""

    def __init__(self, key, layer_cls, forward_func=None, shared_weight_attr
                 ="weight", *args, **kwargs):
        super().__init__(layer_cls, *args, **kwargs)
        self.layer_name = key
        self.forward_func = forward_func
        self.shared_weight_attr = shared_weight_attr


class PipelineLayer(Layer):
    def __init__(self, layers, num_stages=None, topology=None,
                 loss_fn=None, seg_method="uniform", recompute_interval=0,
                 recompute_ctx=None, num_virtual_pipeline_stages=None):
        super().__init__()
        self._loss_fn = loss_fn
        self._num_stages = num_stages or 1
        self._seg_method = seg_method
        self._recompute_interval = recompute_interval
        self._shared = {}
        built = []
        for item in layers:
            if isinstance(item, SharedLayerDesc):
                if item.layer_name in self._shared:
                    layer = self._shared[item.layer_name]
                else:
                    layer = item.build_layer()
                    self._shared[item.layer_name] = layer
                built.append((layer, item.forward_func))
            elif isinstance(item, LayerDesc):
                built.append((item.build_layer(), None))
            elif isinstance(item, Layer):
                built.append((item, None))
            elif callable(item):
                built.append((item, "fn"))
            else:
                raise TypeError(f"bad pipeline item {item!r}")
        self.run_function = built
        self._layers_list = LayerList(
            [l for l, tag in built if isinstance(l, Layer)])
        self._segments = self._segment(built, self._num_stages)

    def _segment(self, built, num_stages):
        """Reference: pp_layers.py:76 — uniform or by-parameter-count."""
        n = len(built)
        if self._seg_method == "uniform" or num_stages == 1:
            bounds = np.linspace(0, n, num_stages + 1).astype(int)
        else:  # "layer:param" style: balance by parameter count
            weights = []
            for l, _ in built:
                if isinstance(l, Layer):
                    weights.append(sum(p.size for p in l.parameters()) + 1)
                else:
                    weights.append(1)
            cum = np.cumsum(weights)
            total = cum[-1]
            bounds = [0]
            for s in range(1, num_stages):
                bounds.append(int(np.searchsorted(cum, total * s / num_stages)))
            bounds.append(n)
            bounds = np.asarray(bounds)
        return [(int(bounds[i]), int(bounds[i + 1]))
                for i in range(num_stages)]

    def get_num_stages(self):
        return self._num_stages

    def stage_segments(self):
        return self._segments

    @staticmethod
    def apply_items(items, x):
        """Run a sequence of (layer, tag) items — the single dispatch point
        for stage execution and for PP auto-segmentation."""
        for layer, tag in items:
            if tag == "fn":
                x = layer(x)
            elif tag is not None and callable(tag):
                x = tag(layer, x)
            else:
                x = layer(x)
        return x

    def forward_stage(self, x, stage):
        lo, hi = self._segments[stage]
        return self.apply_items(self.run_function[lo:hi], x)

    def forward(self, x):
        for stage in range(self._num_stages):
            x = self.forward_stage(x, stage)
        return x

    def loss(self, output, label):
        if self._loss_fn is None:
            return output
        return self._loss_fn(output, label)
