"""Parallel model wrappers.

Reference parity: fleet/meta_parallel/tensor_parallel.py (TensorParallel),
pipeline_parallel.py:32 (PipelineParallel.train_batch:114),
sharding_parallel.py (ShardingParallel). See each class for the TPU-native
mapping.
"""
import jax
from jax.sharding import NamedSharding, PartitionSpec as P

from ....core.tensor import Tensor
from ....nn.layer_base import Layer
from ....ops import manipulation, math as math_ops


class _MetaParallelBase(Layer):
    def __init__(self, layers, hcg, strategy=None):
        super().__init__()
        self._layers = layers
        self._hcg = hcg
        self._strategy = strategy

    def forward(self, *inputs, **kwargs):
        return self._layers(*inputs, **kwargs)

    def parameters(self, include_sublayers=True):
        return self._layers.parameters(include_sublayers)

    def named_parameters(self, prefix="", include_sublayers=True):
        return self._layers.named_parameters(prefix, include_sublayers)

    def state_dict(self, *args, **kwargs):
        return self._layers.state_dict(*args, **kwargs)

    def set_state_dict(self, state_dict, *args, **kwargs):
        return self._layers.set_state_dict(state_dict, *args, **kwargs)

    def train(self):
        self._layers.train()
        self.training = True
        return self

    def eval(self):
        self._layers.eval()
        self.training = False
        return self


class TensorParallel(_MetaParallelBase):
    """Reference: meta_parallel/tensor_parallel.py — broadcasts non-TP
    params inside the mp group. TPU-native: in the single-controller model
    all params are already consistent; TP placement comes from the
    mp_layers' sharding constraints when the step compiles, and the batch
    gets a dp constraint here. Eager phases run unsharded by design (see
    mp_layers.shard_constraint)."""

    def forward(self, *inputs, **kwargs):
        from .mp_layers import shard_constraint
        mesh = self._hcg.mesh
        dp = int(mesh.shape["dp"])
        sharded = []
        for x in inputs:
            if isinstance(x, Tensor) and x.ndim >= 1 and x.shape[0] % dp == 0:
                x = shard_constraint(x, ("dp",) + (None,) * (x.ndim - 1))
            sharded.append(x)
        return self._layers(*sharded, **kwargs)


class ShardingParallel(_MetaParallelBase):
    """Reference: meta_parallel/sharding_parallel.py. ZeRO staging happens
    in the sharded optimizer (dygraph_sharding_optimizer); the model wrapper
    just replicates params (stage 1/2) — see sharding/ for the optimizer."""

    def __init__(self, layers, hcg, strategy=None):
        super().__init__(layers, hcg, strategy)


class PipelineParallel(_MetaParallelBase):
    """Reference: meta_parallel/pipeline_parallel.py:32; train_batch(:114)
    runs the 1F1B micro-batch schedule with p2p send/recv.

    TPU-native round-1 design: micro-batches are executed sequentially over
    the stage segments on the controller (gradient accumulation semantics
    identical to 1F1B); stage parameters carry pp-mesh shardings so under
    jit GSPMD maps stage weights onto their pp slice. A shard_map-based
    collective-permute pipeline (compute/transfer overlap on ICI) is the
    planned optimization — see distributed/pipeline.py.
    """

    def __init__(self, layers, hcg, strategy=None):
        super().__init__(layers, hcg, strategy)
        self._acc_steps = 1
        if strategy is not None:
            self._acc_steps = strategy.pipeline_configs.get(
                "accumulate_steps", 1)

    def forward(self, *inputs, **kwargs):
        return self._layers(*inputs, **kwargs)

    def train_batch(self, data, optimizer, lr_scheduler=None, scaler=None):
        """Reference signature: pipeline_parallel.py:114."""
        x, label = data
        micro = self._acc_steps
        n = x.shape[0]
        assert n % micro == 0, "batch must divide accumulate_steps"
        mb = n // micro
        total_loss = None
        optimizer.clear_grad()
        for i in range(micro):
            xs = x[i * mb:(i + 1) * mb]
            ys = label[i * mb:(i + 1) * mb]
            out = self._layers(xs)
            loss = self._layers.loss(out, ys) if hasattr(
                self._layers, "loss") else out
            scaled = math_ops.scale(loss, 1.0 / micro)
            if scaler is not None:
                scaler.scale(scaled).backward()
            else:
                scaled.backward()
            total_loss = scaled if total_loss is None else \
                math_ops.add(total_loss, scaled)
        if scaler is not None:
            scaler.step(optimizer)
        else:
            optimizer.step()
        optimizer.clear_grad()
        if lr_scheduler is not None:
            lr_scheduler.step()
        return total_loss

    def eval_batch(self, data, compute_loss=True):
        x, label = data
        out = self._layers(x)
        if compute_loss and hasattr(self._layers, "loss"):
            return self._layers.loss(out, label)
        return out
