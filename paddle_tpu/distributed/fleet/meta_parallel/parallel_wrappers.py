"""Parallel model wrappers.

Reference parity: fleet/meta_parallel/tensor_parallel.py (TensorParallel),
pipeline_parallel.py:32 (PipelineParallel.train_batch:114),
sharding_parallel.py (ShardingParallel). See each class for the TPU-native
mapping.
"""
import jax
from jax.sharding import NamedSharding, PartitionSpec as P

from ....core.tensor import Tensor
from ....nn.layer_base import Layer
from ....ops import manipulation, math as math_ops


class _MetaParallelBase(Layer):
    def __init__(self, layers, hcg, strategy=None):
        super().__init__()
        self._layers = layers
        self._hcg = hcg
        self._strategy = strategy

    def forward(self, *inputs, **kwargs):
        return self._layers(*inputs, **kwargs)

    def parameters(self, include_sublayers=True):
        return self._layers.parameters(include_sublayers)

    def named_parameters(self, prefix="", include_sublayers=True):
        return self._layers.named_parameters(prefix, include_sublayers)

    def state_dict(self, *args, **kwargs):
        return self._layers.state_dict(*args, **kwargs)

    def set_state_dict(self, state_dict, *args, **kwargs):
        return self._layers.set_state_dict(state_dict, *args, **kwargs)

    def train(self):
        self._layers.train()
        self.training = True
        return self

    def eval(self):
        self._layers.eval()
        self.training = False
        return self


class TensorParallel(_MetaParallelBase):
    """Reference: meta_parallel/tensor_parallel.py — broadcasts non-TP
    params inside the mp group. TPU-native: in the single-controller model
    all params are already consistent; TP placement comes from the
    mp_layers' sharding constraints when the step compiles, and the batch
    gets a dp constraint here. Eager phases run unsharded by design (see
    mp_layers.shard_constraint)."""

    def forward(self, *inputs, **kwargs):
        from .mp_layers import shard_constraint
        mesh = self._hcg.mesh
        dp = int(mesh.shape["dp"])
        sharded = []
        for x in inputs:
            if isinstance(x, Tensor) and x.ndim >= 1 and x.shape[0] % dp == 0:
                x = shard_constraint(x, ("dp",) + (None,) * (x.ndim - 1))
            sharded.append(x)
        return self._layers(*sharded, **kwargs)


class ShardingParallel(_MetaParallelBase):
    """Reference: meta_parallel/sharding_parallel.py. ZeRO staging happens
    in the sharded optimizer (dygraph_sharding_optimizer); the model wrapper
    just replicates params (stage 1/2) — see sharding/ for the optimizer."""

    def __init__(self, layers, hcg, strategy=None):
        super().__init__(layers, hcg, strategy)


def _layer_signature(layer):
    """Structural signature used to find the homogeneous block run: two
    layers pipeline-stack iff class and param (names, shapes, dtypes)
    match."""
    if not isinstance(layer, Layer):
        return None
    sig = tuple(sorted((k, tuple(v.aval_shape()), str(v.value.dtype))
                       for k, v in layer.state_dict().items()))
    return (type(layer).__name__, sig)


def _functional_call(bindings, fn, *arrays, rng=None):
    """Call a Layer-graph function purely: bind param Tensors to traced
    values, wrap jax arrays as fresh Tensors, return the jax output value.
    When `rng` is given, the global generator state is bound to it and the
    advanced state is returned alongside (so dropout differs per step —
    the same threading the to_static machinery does automatically)."""
    from ....core import trace as trace_mod
    from ....core import rng as rng_mod

    ctx = trace_mod.TraceContext("jit")
    rng_t = rng_mod.default_generator.state if rng is not None else None
    with trace_mod.trace_guard(ctx):
        for t, v in bindings:
            ctx.bind(t, v)
        if rng_t is not None:
            ctx.bind(rng_t, rng)
        targs = []
        for a in arrays:
            ta = Tensor(a)
            ctx.register_created(ta)
            targs.append(ta)
        out = fn(*targs)
        out_val = out.value if isinstance(out, Tensor) else out
        new_rng = ctx.final_value(rng_t) if rng_t is not None else None
    if rng is not None:
        return out_val, new_rng
    return out_val


class PipelineParallel(_MetaParallelBase):
    """TPU-native pipeline engine (reference:
    meta_parallel/pipeline_parallel.py:32 train_batch:114 over p2p NCCL;
    framework/section_worker.cc:34 1F1B schedule).

    Instead of per-stage worker processes exchanging activations, the whole
    train step is ONE compiled program:
      - the model's edge segments (embedding / final norm / head / loss)
        run as plain GSPMD ops on the full mesh — so a tied/shared
        embedding (SharedLayerDesc) is literally the same tensor used in
        both places, no cross-stage sync;
      - the repeated blocks are pipelined over the 'pp' mesh axis via
        scan + ppermute (distributed/pipeline.py), manual only over 'pp'
        so TP ('mp') and DP shardings inside blocks still compile via
        GSPMD;
      - backward is jax autodiff of the schedule — the reversed scan with
        reversed ppermute, i.e. 1F1B-equivalent gradient accumulation.

    Models opt in by providing pp_segments() -> {'pre': fn(x)->h,
    'blocks': [Layer...], 'post': fn(h, label)->loss}; PipelineLayer
    containers are segmented automatically (homogeneous middle run).
    Uneven block counts are padded to ceil(n/pp) per stage (padded slots
    masked out).
    """

    def __init__(self, layers, hcg, strategy=None):
        super().__init__(layers, hcg, strategy)
        self._acc_steps = 1
        if strategy is not None:
            self._acc_steps = strategy.pipeline_configs.get(
                "accumulate_steps", 1)
        self._plan = None
        self._jitted = {}

    def forward(self, *inputs, **kwargs):
        return self._layers(*inputs, **kwargs)

    # -- segmentation ------------------------------------------------------
    def _segments(self):
        model = self._layers
        if hasattr(model, "pp_segments"):
            return model.pp_segments()
        from .pp_layers import PipelineLayer
        if isinstance(model, PipelineLayer):
            return self._segments_from_pipeline_layer(model)
        raise TypeError(
            "pipeline parallelism needs a model with pp_segments() or a "
            "PipelineLayer container; got " + type(model).__name__)

    @staticmethod
    def _segments_from_pipeline_layer(model):
        items = model.run_function
        sigs = [_layer_signature(l) for l, tag in items]
        # longest contiguous run of identical non-trivial signatures
        best = (0, 0)
        i = 0
        while i < len(items):
            if sigs[i] is None or not sigs[i][1]:
                i += 1
                continue
            j = i
            while j < len(items) and sigs[j] == sigs[i]:
                j += 1
            if j - i > best[1] - best[0]:
                best = (i, j)
            i = j
        lo, hi = best
        if hi - lo < 2:
            raise ValueError(
                "PipelineLayer has no homogeneous block run to pipeline")
        pre_items, block_items, post_items = \
            items[:lo], items[lo:hi], items[hi:]
        run = type(model).apply_items

        def pre(x):
            return run(pre_items, x)

        def post(h, label):
            out = run(post_items, h)
            return model.loss(out, label)

        return {"pre": pre, "blocks": [l for l, _ in block_items],
                "post": post}

    # -- compiled pipeline step -------------------------------------------
    def _build_plan(self):
        import numpy as np
        segs = self._segments()
        model = self._layers
        import jax.numpy as jnp
        blocks = list(segs["blocks"])
        template = blocks[0]
        block_states = [b.state_dict() for b in blocks]
        # differentiate only float trainable block entries; int/bool
        # buffers (masks, counters) ride along undifferentiated in their
        # own stack (value_and_grad rejects non-float argnums)
        keys = [k for k, t in block_states[0].items()
                if t.trainable and jnp.issubdtype(t.value.dtype,
                                                  jnp.floating)]
        aux_keys = [k for k in block_states[0] if k not in keys]
        block_ids = {id(t) for st in block_states for t in st.values()}
        full = model.state_dict()
        other = {n: t for n, t in full.items() if id(t) not in block_ids}

        # only float trainables are differentiated; buffers/int state are
        # passed through undifferentiated (value_and_grad needs float args)
        diff = {n: t for n, t in other.items()
                if t.trainable and jnp.issubdtype(t.value.dtype,
                                                  jnp.floating)}
        aux = {n: t for n, t in other.items() if n not in diff}

        mesh = self._hcg.mesh
        pp = int(mesh.shape["pp"])
        groups = np.array_split(np.arange(len(blocks)), pp)
        lps = max(len(g) for g in groups)
        # stage-major [pp, lps] block index map; padded slots repeat the
        # stage's last block (real weights -> no NaN hazards) and are
        # masked out of both forward and grads
        idx_map = np.asarray([[g[min(j, len(g) - 1)] for j in range(lps)]
                              for g in groups])
        valid = np.asarray([[j < len(g) for j in range(lps)]
                            for g in groups])
        self._plan = dict(
            segs=segs, blocks=blocks, template=template,
            block_states=block_states, keys=keys, aux_keys=aux_keys,
            diff=diff, aux=aux,
            mesh=mesh, pp=pp, idx_map=idx_map, valid=valid, lps=lps)
        return self._plan

    def _stacked_values(self, plan, which="keys"):
        import jax.numpy as jnp
        stacked = {}
        for k in plan[which]:
            rows = []
            for s in range(plan["pp"]):
                rows.append(jnp.stack(
                    [plan["block_states"][i][k].value
                     for i in plan["idx_map"][s]], axis=0))
            stacked[k] = jnp.stack(rows, axis=0)  # [pp, lps, ...]
        return stacked

    def _make_loss_fn(self, plan, micro):
        from ...pipeline import pipeline_blocks_apply
        import jax.numpy as jnp

        segs, template = plan["segs"], plan["template"]
        tmpl_state = plan["block_states"][0]
        keys, aux_keys, mesh = plan["keys"], plan["aux_keys"], plan["mesh"]
        diff, aux = plan["diff"], plan["aux"]
        tmpl_tensors = [tmpl_state[k] for k in keys]
        tmpl_aux_tensors = [tmpl_state[k] for k in aux_keys]
        valid = jnp.asarray(plan["valid"])
        dp = int(mesh.shape.get("dp", 1))

        def block_fn(sliced, h):
            # sliced: (diff param values, aux values, rng key) for ONE
            # block
            vals, aux_vals_b, key = sliced
            binds = (list(zip(tmpl_tensors, [vals[k] for k in keys])) +
                     list(zip(tmpl_aux_tensors,
                              [aux_vals_b[k] for k in aux_keys])))
            out, _ = _functional_call(binds, template, h, rng=key)
            return out

        def loss_fn(diff_vals, stacked_vals, aux_vals, stacked_aux, x, y,
                    rng, loss_scale):
            binds = ([(diff[n], diff_vals[n]) for n in diff] +
                     [(aux[n], aux_vals[n]) for n in aux])
            if x.ndim >= 1 and x.shape[0] % dp == 0 and dp > 1:
                x = jax.lax.with_sharding_constraint(
                    x, NamedSharding(mesh, P("dp")))
            r_pre, r_blocks, r_post = jax.random.split(rng, 3)
            h, _ = _functional_call(binds, segs["pre"], x, rng=r_pre)
            block_keys = jax.random.split(
                r_blocks, plan["pp"] * plan["lps"]).reshape(
                    plan["pp"], plan["lps"], -1)
            h = pipeline_blocks_apply(
                block_fn, (stacked_vals, stacked_aux, block_keys), valid,
                h, micro, mesh)
            args = (h,) if y is None else (h, y)
            loss, _ = _functional_call(binds, segs["post"], *args,
                                       rng=r_post)
            # grads are taken of the SCALED loss (GradScaler contract:
            # scaler.step later unscales + runs inf detection); the raw
            # loss is returned for reporting
            return loss * loss_scale, loss

        return loss_fn

    def _run_step(self, x, y, micro, training=True, loss_scale=None):
        import jax.numpy as jnp
        plan = self._plan or self._build_plan()
        key = ("train" if training else "eval", micro,
               tuple(x.shape), str(x.value.dtype),
               None if y is None else tuple(y.shape))
        jitted = self._jitted.get(key)
        if jitted is None:
            loss_fn = self._make_loss_fn(plan, micro)
            if training:
                jitted = jax.jit(jax.value_and_grad(
                    loss_fn, argnums=(0, 1), has_aux=True))
            else:
                jitted = jax.jit(lambda *a: loss_fn(*a)[1])
            self._jitted[key] = jitted

        from ....core import rng as rng_mod
        diff_vals = {n: t.value for n, t in plan["diff"].items()}
        aux_vals = {n: t.value for n, t in plan["aux"].items()}
        stacked_vals = self._stacked_values(plan, "keys")
        stacked_aux = self._stacked_values(plan, "aux_keys")
        rng = rng_mod.next_key().value
        yv = None if y is None else y.value
        scale = jnp.asarray(1.0 if loss_scale is None else loss_scale,
                            jnp.float32)
        if not training:
            return jitted(diff_vals, stacked_vals, aux_vals, stacked_aux,
                          x.value, yv, rng, scale)
        (_, loss), (g_diff, g_stacked) = jitted(
            diff_vals, stacked_vals, aux_vals, stacked_aux, x.value, yv,
            rng, scale)
        self._assign_grads(plan, g_diff, g_stacked)
        return loss

    @staticmethod
    def _accum_grad(t, g):
        if t.grad is None:
            t.grad = Tensor(g, stop_gradient=True)
        else:
            t.grad.value = t.grad.value + g

    def _assign_grads(self, plan, g_diff, g_stacked):
        for n, t in plan["diff"].items():
            self._accum_grad(t, g_diff[n])
        for k in plan["keys"]:
            g = g_stacked[k]  # [pp, lps, ...]
            for s in range(plan["pp"]):
                for j, bi in enumerate(plan["idx_map"][s]):
                    if not plan["valid"][s][j]:
                        continue
                    t = plan["block_states"][bi][k]
                    if t.trainable:
                        self._accum_grad(t, g[s, j])

    def train_batch(self, data, optimizer, lr_scheduler=None, scaler=None):
        """Reference signature: pipeline_parallel.py:114. Runs the compiled
        pipelined forward+backward (grads land on param.grad), then the
        optimizer step."""
        x, label = data
        micro = self._acc_steps
        n = x.shape[0]
        assert n % micro == 0, \
            "batch size must be a multiple of accumulate_steps"
        scale = None if scaler is None else float(scaler._scale.numpy())
        loss_val = self._run_step(x, label, micro, training=True,
                                  loss_scale=scale)
        loss = Tensor(loss_val)
        if scaler is not None:
            scaler.step(optimizer)
        else:
            optimizer.step()
        optimizer.clear_grad()
        if lr_scheduler is not None:
            lr_scheduler.step()
        return loss

    def eval_batch(self, data, compute_loss=True):
        x, label = data
        if compute_loss:
            was = self.training
            self.eval()
            out = Tensor(self._run_step(x, label, self._acc_steps,
                                        training=False))
            if was:
                self.train()
            return out
        return self._layers(x)
