from .fleet_base import (  # noqa: F401
    init, distributed_model, distributed_optimizer, get_hybrid_communicate_group,
    worker_num, worker_index, is_first_worker, barrier_worker,
)
from .distributed_strategy import DistributedStrategy  # noqa: F401
from ..topology import HybridCommunicateGroup, CommunicateTopology  # noqa: F401
from . import meta_parallel  # noqa: F401
from . import meta_optimizers  # noqa: F401
from .meta_optimizers import (  # noqa: F401
    GradientMergeOptimizer, LocalSGDOptimizer, AdaptiveLocalSGDOptimizer,
    DGCMomentumOptimizer, FP16AllReduceOptimizer,
)
from ..utils_recompute import recompute  # noqa: F401


from . import utils_fs  # noqa: F401


class utils:
    from ..utils_recompute import recompute  # noqa: F401
    from . import utils_fs as fs  # noqa: F401
    from .utils_fs import LocalFS, HDFSClient  # noqa: F401
