from .fleet_base import (  # noqa: F401
    init, distributed_model, distributed_optimizer, get_hybrid_communicate_group,
    worker_num, worker_index, is_first_worker, barrier_worker,
)
from .distributed_strategy import DistributedStrategy  # noqa: F401
from ..topology import HybridCommunicateGroup, CommunicateTopology  # noqa: F401
from . import meta_parallel  # noqa: F401
from . import meta_optimizers  # noqa: F401
from .meta_optimizers import (  # noqa: F401
    GradientMergeOptimizer, LocalSGDOptimizer, AdaptiveLocalSGDOptimizer,
    DGCMomentumOptimizer, FP16AllReduceOptimizer,
)
from ..utils_recompute import recompute  # noqa: F401


from . import utils_fs  # noqa: F401


class utils:
    from ..utils_recompute import recompute  # noqa: F401
    from . import utils_fs as fs  # noqa: F401
    from .utils_fs import LocalFS, HDFSClient  # noqa: F401
from .fleet_base import (  # noqa: F401,E402
    is_worker, is_server, server_num, server_index, server_endpoints,
    worker_endpoints, init_server, run_server, init_worker, stop_worker,
    minimize, state_dict, save_persistables, save_inference_model,
    ps_client, communicator,
)
from .role_maker import (  # noqa: F401,E402
    Role, PaddleCloudRoleMaker, UserDefinedRoleMaker,
)
from .data_generator import (  # noqa: F401,E402
    DataGenerator, MultiSlotDataGenerator, MultiSlotStringDataGenerator,
)
from .dataset import InMemoryDataset, QueueDataset  # noqa: F401,E402


class UtilBase:
    """Reference: fleet/utils/__init__.py UtilBase (fleet.util) —
    worker-side helpers over the collective/PS backends."""

    _allreduce_round = [0]

    def all_reduce(self, input, mode="sum", comm_world="worker"):  # noqa: A002
        """Reduce across WORKER processes (reference: gloo all_reduce).
        With a PS cluster attached, trainers combine through a fresh
        round-scoped server-side 'sum' scratch table (create is
        first-wins on the server, so the racing trainers share one
        table); a lone worker is the identity. Calls must be collective:
        every worker invokes the same sequence of all_reduce calls."""
        if mode not in ("sum", "min", "max"):
            raise ValueError(f"util.all_reduce mode {mode!r}; expected "
                             "sum/min/max")
        import numpy as np
        from .fleet_base import ps_client, worker_num, worker_index
        arr = np.asarray(getattr(input, "numpy", lambda: input)())
        client = ps_client()
        n = worker_num()
        if client is None or n <= 1:
            return arr  # single worker: reduction of one contribution
        rnd = self._allreduce_round[0]
        self._allreduce_round[0] += 1
        if mode == "sum":
            tid = f"__fleet_util_allreduce__{rnd}"
            client.create_dense_table(tid, shape=arr.shape,
                                      optimizer="sum",
                                      init=np.zeros_like(arr))
            client.push_dense(tid, arr)
            client.barrier(n)
            out = np.asarray(client.pull_dense(tid))
            client.barrier(n)
            return out
        # min/max: the server tables only sum, so exchange contributions
        # all-to-all through the shuffle service and reduce locally
        # (reference gloo supports sum/min/max; same collective
        # contract). Buckets are namespaced per round and rank — the
        # integer buckets belong to InMemoryDataset.global_shuffle and
        # must not be drained or polluted here.
        import pickle
        me = worker_index()
        blob = pickle.dumps(arr)
        for r in range(n):
            client._call(r % client.n_servers,
                         {"cmd": "shuffle_put",
                          "dest": f"__util_allreduce__{rnd}_{r}",
                          "blobs": [blob]})
        client.barrier(n)
        resp = client._call(me % client.n_servers,
                            {"cmd": "shuffle_take",
                             "rank": f"__util_allreduce__{rnd}_{me}"})
        vals = [pickle.loads(b) for b in resp["blobs"]]
        client.barrier(n)
        if len(vals) != n:
            raise RuntimeError(
                f"util.all_reduce({mode}): received {len(vals)} of {n} "
                "contributions — a worker missed the collective")
        red = np.minimum if mode == "min" else np.maximum
        out = vals[0]
        for v in vals[1:]:
            out = red(out, v)
        return out

    def barrier(self, comm_world="worker"):
        from .fleet_base import barrier_worker
        barrier_worker()

    def get_file_shard(self, files):
        """Split a file list evenly over workers (reference:
        UtilBase.get_file_shard)."""
        from .fleet_base import worker_index, worker_num
        n, i = worker_num(), worker_index()
        per = len(files) // n
        rem = len(files) % n
        start = per * i + min(i, rem)
        end = start + per + (1 if i < rem else 0)
        return files[start:end]

    def print_on_rank(self, message, rank_id=0):
        from .fleet_base import worker_index
        if worker_index() == rank_id:
            print(message)


util = UtilBase()


class Fleet:
    """Reference: fleet_base.py:72 Fleet — the module-level functions ARE
    the singleton's methods; this class exposes the same surface for
    code that instantiates/attributes `fleet.Fleet`."""

    def __getattr__(self, item):
        import sys
        mod = sys.modules[__name__]
        return getattr(mod, item)
