"""fleet: unified distributed entry point.

Reference parity: python/paddle/distributed/fleet/base/fleet_base.py
(init:139, distributed_model:836, distributed_optimizer:783). TPU-native:
fleet.init builds the hybrid Mesh from strategy.hybrid_configs;
distributed_model wraps the layer per enabled strategy (DataParallel /
TensorParallel / PipelineParallel); distributed_optimizer composes the
HybridParallelOptimizer (clip + sharding + amp behaviors) — the analogue
of the reference's meta-optimizer StrategyCompiler chain, except each
"meta optimizer" is a sharding/wrapping decision instead of a program
rewrite.
"""
import jax

from .distributed_strategy import DistributedStrategy
from .. import topology
from ..env import get_rank, get_world_size

_fleet_state = {"strategy": None, "hcg": None, "initialized": False}


def init(role_maker=None, is_collective=True, strategy=None):
    if strategy is None:
        strategy = DistributedStrategy()
    strategy.check_conflicts(device_count=jax.device_count())
    hc = strategy.hybrid_configs
    degrees = {k: hc.get(k, 1) for k in
               ("dp_degree", "mp_degree", "pp_degree", "sharding_degree",
                "sp_degree")}
    total = 1
    for v in degrees.values():
        total *= v
    if total == 1:
        degrees["dp_degree"] = jax.device_count()
    hcg = topology.HybridCommunicateGroup(
        dp=degrees["dp_degree"], mp=degrees["mp_degree"],
        pp=degrees["pp_degree"], sharding=degrees["sharding_degree"],
        sp=degrees["sp_degree"])
    _fleet_state.update(strategy=strategy, hcg=hcg, initialized=True)
    return None


def get_hybrid_communicate_group():
    return _fleet_state["hcg"]


def _strategy():
    return _fleet_state["strategy"] or DistributedStrategy()


def distributed_model(model):
    """Reference: fleet_base.py:836-913 — chooses the parallel wrapper."""
    if not _fleet_state["initialized"]:
        init()
    hcg = _fleet_state["hcg"]
    from .meta_parallel.parallel_wrappers import (
        TensorParallel, PipelineParallel, ShardingParallel)
    from ..parallel import DataParallel
    if hcg.get_pipe_parallel_world_size() > 1:
        return PipelineParallel(model, hcg, strategy=_strategy())
    if hcg.get_model_parallel_world_size() > 1:
        return TensorParallel(model, hcg, strategy=_strategy())
    if hcg.get_sharding_parallel_world_size() > 1:
        return ShardingParallel(model, hcg, strategy=_strategy())
    return DataParallel(model)


def distributed_optimizer(optimizer, strategy=None):
    """Reference: fleet_base.py:783 + HybridParallelOptimizer
    (dygraph_optimizer/hybrid_parallel_optimizer.py:89)."""
    if strategy is not None:
        _fleet_state["strategy"] = strategy
    from .hybrid_optimizer import HybridParallelOptimizer
    from .meta_optimizers import apply_meta_optimizers
    optimizer = apply_meta_optimizers(optimizer, _strategy())
    return HybridParallelOptimizer(optimizer, _fleet_state["hcg"],
                                   _strategy())


def worker_num():
    return get_world_size()


def worker_index():
    return get_rank()


def is_first_worker():
    return get_rank() == 0


def barrier_worker():
    jax.effects_barrier()
