"""fleet: unified distributed entry point.

Reference parity: python/paddle/distributed/fleet/base/fleet_base.py
(init:139, distributed_model:836, distributed_optimizer:783). TPU-native:
fleet.init builds the hybrid Mesh from strategy.hybrid_configs;
distributed_model wraps the layer per enabled strategy (DataParallel /
TensorParallel / PipelineParallel); distributed_optimizer composes the
HybridParallelOptimizer (clip + sharding + amp behaviors) — the analogue
of the reference's meta-optimizer StrategyCompiler chain, except each
"meta optimizer" is a sharding/wrapping decision instead of a program
rewrite.
"""
import jax

from .distributed_strategy import DistributedStrategy
from .. import topology
from ..env import get_rank, get_world_size

_fleet_state = {"strategy": None, "hcg": None, "initialized": False}


def init(role_maker=None, is_collective=True, strategy=None):
    if strategy is None:
        strategy = DistributedStrategy()
    _fleet_state["role_maker"] = role_maker
    if role_maker is not None and not getattr(role_maker,
                                              "_is_collective", False):
        # parameter-server mode (reference: fleet.init(role_maker) +
        # a_sync strategy): no device mesh — workers/servers talk over
        # the PS subsystem; a server process must not touch the chips
        _fleet_state.update(strategy=strategy, initialized=True)
        return None
    strategy.check_conflicts(device_count=jax.device_count())
    hc = strategy.hybrid_configs
    degrees = {k: hc.get(k, 1) for k in
               ("dp_degree", "mp_degree", "pp_degree", "sharding_degree",
                "sp_degree")}
    total = 1
    for v in degrees.values():
        total *= v
    if total == 1:
        degrees["dp_degree"] = jax.device_count()
    hcg = topology.HybridCommunicateGroup(
        dp=degrees["dp_degree"], mp=degrees["mp_degree"],
        pp=degrees["pp_degree"], sharding=degrees["sharding_degree"],
        sp=degrees["sp_degree"])
    _fleet_state.update(strategy=strategy, hcg=hcg, initialized=True)
    return None


def get_hybrid_communicate_group():
    return _fleet_state["hcg"]


def _strategy():
    return _fleet_state["strategy"] or DistributedStrategy()


def distributed_model(model):
    """Reference: fleet_base.py:836-913 — chooses the parallel wrapper."""
    if not _fleet_state["initialized"]:
        init()
    if _fleet_state.get("hcg") is None:  # PS mode: model runs as-is
        _fleet_state["dist_model"] = model
        return model
    hcg = _fleet_state["hcg"]
    from .meta_parallel.parallel_wrappers import (
        TensorParallel, PipelineParallel, ShardingParallel)
    from ..parallel import DataParallel
    if hcg.get_pipe_parallel_world_size() > 1:
        wrapped = PipelineParallel(model, hcg, strategy=_strategy())
    elif hcg.get_model_parallel_world_size() > 1:
        wrapped = TensorParallel(model, hcg, strategy=_strategy())
    elif hcg.get_sharding_parallel_world_size() > 1:
        wrapped = ShardingParallel(model, hcg, strategy=_strategy())
    else:
        wrapped = DataParallel(model)
    _fleet_state["dist_model"] = wrapped
    return wrapped


def distributed_optimizer(optimizer, strategy=None):
    """Reference: fleet_base.py:783 + HybridParallelOptimizer
    (dygraph_optimizer/hybrid_parallel_optimizer.py:89)."""
    if strategy is not None:
        _fleet_state["strategy"] = strategy
    from .hybrid_optimizer import HybridParallelOptimizer
    from .meta_optimizers import apply_meta_optimizers
    optimizer = apply_meta_optimizers(optimizer, _strategy())
    if _fleet_state.get("hcg") is None:
        # PS mode: no mesh wrapping — keep the (meta-wrapped) optimizer
        _fleet_state["dist_optimizer"] = optimizer
        return optimizer
    wrapped = HybridParallelOptimizer(optimizer, _fleet_state["hcg"],
                                      _strategy())
    _fleet_state["dist_optimizer"] = wrapped
    return wrapped


def worker_num():
    rm = _fleet_state.get("role_maker")
    if rm is not None and rm._worker_num():
        return rm._worker_num()
    return get_world_size()


def worker_index():
    rm = _fleet_state.get("role_maker")
    if rm is not None and rm._is_worker():
        return rm._worker_index()
    return get_rank()


def is_first_worker():
    return worker_index() == 0


def barrier_worker():
    # PS mode: a REAL rendezvous across trainer processes via the server
    # barrier; collective single-controller mode: device-queue sync
    client = _fleet_state.get("ps_client")
    if client is not None and worker_num() > 1:
        client.barrier(worker_num())
        return
    jax.effects_barrier()


# -- parameter-server mode lifecycle (reference: fleet_base.py
# init_worker:1051 / init_server:1110 / run_server:1129 / stop_worker
# over the_one_ps.py TheOnePSRuntime; here over distributed/ps) ----------

def _role_maker():
    return _fleet_state.get("role_maker")


def is_worker():
    rm = _role_maker()
    return True if rm is None else rm._is_worker()


def is_server():
    rm = _role_maker()
    return False if rm is None else rm._is_server()


def server_num():
    rm = _role_maker()
    return 0 if rm is None else rm._server_num()


def server_index():
    rm = _role_maker()
    return -1 if rm is None else rm._server_index()


def server_endpoints(to_string=False):
    rm = _role_maker()
    eps = [] if rm is None else rm._get_pserver_endpoints()
    return ",".join(eps) if to_string else eps


def worker_endpoints(to_string=False):
    rm = _role_maker()
    eps = [] if rm is None else rm._get_trainer_endpoints()
    return ",".join(eps) if to_string else eps


def init_server(*args, **kwargs):
    """Build this rank's PS server bound to its endpoint (reference:
    init_server loads a saved model into tables; pass model_dir to do
    the same via table load)."""
    rm = _role_maker()
    if rm is None or not rm._is_server():
        raise RuntimeError("init_server called on a non-server role")
    from ..ps import PSServer
    ep = rm._get_pserver_endpoints()[rm._server_index()]
    host, port = ep.rsplit(":", 1)
    srv = PSServer(host, int(port))
    _fleet_state["ps_server"] = srv
    if args and isinstance(args[0], str):
        import os as _os
        path = _os.path.join(args[0], f"ps_state.server"
                             f"{rm._server_index()}")
        if _os.path.exists(path):
            srv._dispatch({"cmd": "load", "path": path})
    return srv


def run_server():
    """Serve until stopped (blocking — reference run_server)."""
    srv = _fleet_state.get("ps_server")
    if srv is None:
        srv = init_server()
    srv.run()


def init_worker():
    """Connect this trainer to the PS cluster and start the communicator
    the strategy asks for (sync / a_sync / geo; reference:
    communicator.h:197,348,497)."""
    rm = _role_maker()
    if rm is None:
        raise RuntimeError("init_worker needs fleet.init(role_maker)")
    from ..ps import PSClient, Communicator, AsyncCommunicator, \
        GeoCommunicator
    client = PSClient(rm._get_pserver_endpoints())
    strategy = _strategy()
    a_sync = bool(getattr(strategy, "a_sync", False))
    k_steps = int(getattr(strategy, "a_sync_configs", {})
                  .get("k_steps", 0) or 0)
    if a_sync and k_steps > 0:
        comm = GeoCommunicator(client, k_steps=k_steps)
    elif a_sync:
        comm = AsyncCommunicator(client).start()
    else:
        comm = Communicator(client)
    _fleet_state.update(ps_client=client, communicator=comm)
    return client


def stop_worker():
    comm = _fleet_state.pop("communicator", None)
    if comm is not None:
        comm.stop()
    client = _fleet_state.pop("ps_client", None)
    if client is not None:
        client.close()


def ps_client():
    return _fleet_state.get("ps_client")


def communicator():
    return _fleet_state.get("communicator")


def minimize(loss, startup_program=None, parameter_list=None,
             no_grad_set=None):
    """Reference: fleet_base.py:1288 — requires distributed_optimizer
    first."""
    opt = _fleet_state.get("dist_optimizer")
    if opt is None:
        raise RuntimeError("call fleet.distributed_optimizer(opt) before "
                           "fleet.minimize")
    return opt.minimize(loss)


def state_dict():
    m = _fleet_state.get("dist_model")
    return {} if m is None else m.state_dict()


def save_persistables(executor=None, dirname=None, main_program=None,
                      **kwargs):
    """PS mode: persist server tables (reference: fleet
    save_persistables via the PS runtime); collective mode: save the
    wrapped model's state_dict."""
    import os as _os
    client = _fleet_state.get("ps_client")
    if client is not None and dirname:
        _os.makedirs(dirname, exist_ok=True)
        client.save(_os.path.join(dirname, "ps_state"))
        return
    m = _fleet_state.get("dist_model")
    if m is not None and dirname:
        from ... import save as _save
        _os.makedirs(dirname, exist_ok=True)
        _save(m.state_dict(), _os.path.join(dirname, "model.pdparams"))


def save_inference_model(executor=None, dirname=None, feeded_var_names=None,
                         target_vars=None, main_program=None, **kwargs):
    from ...static import save_inference_model as _sim
    if main_program is not None and dirname:
        import os as _os
        _os.makedirs(dirname, exist_ok=True)
        return _sim(_os.path.join(dirname, "model"),
                    feeded_var_names or [], target_vars or [],
                    program=main_program)
