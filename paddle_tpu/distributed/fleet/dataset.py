"""Fleet datasets: InMemoryDataset / QueueDataset.

Reference parity: python/paddle/distributed/fleet/dataset/dataset.py:259
(InMemoryDataset) / :1099 (QueueDataset) configuring the C++ Dataset/
DataFeed (framework/data_feed.cc MultiSlotDataFeed, data_set.cc channels +
preload threads, global shuffle via brpc).

TPU-native design: the slot parsing and the sample channel are native C++
(runtime_cpp: ptd_parse_multislot threaded parser + BlockingQueue), driven
by Python file-loader threads; "global shuffle" is an in-memory permutation
(single-controller — no brpc exchange needed). Batches pad ragged sparse
slots to the bucketized max length so shapes stay static for XLA.
"""
import os
import threading

import numpy as np

from ...core import native


class DatasetBase:
    def __init__(self):
        self._use_var = []
        self._pipe_command = None
        self._batch_size = 1
        self._thread_num = 4
        self._filelist = []

    def init(self, batch_size=1, thread_num=4, use_var=None,
             pipe_command=None, input_type=0, fs_name="", fs_ugi="",
             download_cmd="cat", **kwargs):
        self._batch_size = batch_size
        self._thread_num = thread_num
        self._use_var = use_var or []
        self._pipe_command = pipe_command

    def set_filelist(self, filelist):
        self._filelist = list(filelist)

    def set_batch_size(self, batch_size):
        self._batch_size = batch_size

    def set_thread(self, thread_num):
        self._thread_num = thread_num

    def set_use_var(self, use_var):
        self._use_var = use_var


class InMemoryDataset(DatasetBase):
    """Loads slot-format text files into memory with threaded native
    parsing; supports global shuffle and batch iteration with padded
    sparse slots."""

    def __init__(self):
        super().__init__()
        self._slots = None  # list of (values, offsets) per slot
        self._num_samples = 0
        self._num_slots = 0
        self._slot_is_dense = []

    def init(self, batch_size=1, thread_num=4, use_var=None, **kwargs):
        super().init(batch_size, thread_num, use_var, **kwargs)
        self._num_slots = len(self._use_var) if self._use_var else 0

    def load_into_memory(self):
        texts = []
        lock = threading.Lock()

        def read(path):
            with open(path, "r") as f:
                data = f.read()
            with lock:
                texts.append(data)

        threads = [threading.Thread(target=read, args=(p,))
                   for p in self._filelist]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        text = "".join(texts)
        if not self._num_slots:
            # infer from first line: count of "<n> values..." groups
            first = text.split("\n", 1)[0].split()
            i = 0
            n = 0
            while i < len(first):
                cnt = int(first[i])
                i += cnt + 1
                n += 1
            self._num_slots = n
        if native.available():
            self._slots = native.parse_multislot(
                text, self._num_slots, self._thread_num)
        else:
            self._slots = _py_parse_multislot(text, self._num_slots)
        self._num_samples = len(self._slots[0][1]) - 1
        self._slot_is_dense = [
            bool(np.all(np.diff(offs) == (offs[1] - offs[0])))
            for _, offs in self._slots]

    # -- sample (de)serialization for the shuffle exchange ----------------
    def _export_samples(self):
        """Per-sample rows: sample i -> tuple of per-slot value arrays."""
        out = []
        for i in range(self._num_samples):
            row = []
            for vals, offs in self._slots:
                row.append(vals[offs[i]:offs[i + 1]].copy())
            out.append(row)
        return out

    def _import_samples(self, samples):
        nslots = self._num_slots
        new_slots = []
        for s in range(nslots):
            seqs = [row[s] for row in samples]
            offs = np.zeros(len(seqs) + 1, np.int64)
            np.cumsum([len(q) for q in seqs], out=offs[1:])
            vals = (np.concatenate(seqs) if seqs
                    else np.zeros((0,), np.float32))
            new_slots.append((vals, offs))
        self._slots = new_slots
        self._num_samples = len(samples)
        # NOTE: _slot_is_dense is the dataset SCHEMA — invariant under
        # shuffling. Re-deriving it from whichever samples landed here
        # could classify a sparse slot as dense on one rank and not
        # another, desyncing batch structure across data-parallel ranks.

    def global_shuffle(self, fleet=None, thread_num=None,
                       ps_endpoints=None, rank=None, world=None,
                       seed=None):
        """Reference: InMemoryDataset.global_shuffle — samples are
        re-dealt ACROSS workers through a shuffle service
        (data_feed.h:395 InMemoryDataFeed global shuffle over brpc).

        Distributed path (ps_endpoints given): every worker assigns each
        of its samples a uniform destination rank, deposits the blobs in
        the PS shuffle buckets, barriers, then collects its own bucket —
        samples land on random workers. Without endpoints, the
        single-controller reduction: permute in memory."""
        if ps_endpoints:
            from ..ps import PSClient
            from ...core.errors import enforce, enforce_not_none
            import pickle
            enforce_not_none(rank, "global_shuffle(ps_endpoints=...) "
                             "requires rank=")
            enforce_not_none(world, "global_shuffle(ps_endpoints=...) "
                             "requires world=")
            enforce(0 <= rank < world,
                    f"rank {rank} out of range for world {world}")
            client = PSClient(ps_endpoints)
            try:
                # decorrelate destination draws per worker: a shared
                # seed would give every worker the SAME dests sequence
                # (sample i of every worker co-located, not a shuffle)
                rs = np.random.RandomState(
                    None if seed is None else seed + 7919 * (rank + 1))
                samples = self._export_samples()
                dests = rs.randint(0, world, size=len(samples))
                for d in range(world):
                    idx = np.nonzero(dests == d)[0]
                    if len(idx):
                        client.shuffle_put(
                            int(d),
                            [pickle.dumps(samples[i], protocol=4)
                             for i in idx])
                client.barrier(world)  # all deposits visible
                mine = [pickle.loads(b)
                        for b in client.shuffle_take(rank)]
                rs2 = np.random.RandomState(
                    None if seed is None else seed + rank)
                order = rs2.permutation(len(mine))
                self._import_samples([mine[i] for i in order])
                client.barrier(world)  # everyone done taking
            finally:
                client.close()
            return
        perm = np.random.permutation(self._num_samples)
        new_slots = []
        for vals, offs in self._slots:
            counts = np.diff(offs)
            new_counts = counts[perm]
            new_offs = np.zeros(len(offs), np.int64)
            np.cumsum(new_counts, out=new_offs[1:])
            new_vals = np.empty_like(vals)
            pos = 0
            for i, src in enumerate(perm):
                c = counts[src]
                new_vals[pos:pos + c] = vals[offs[src]:offs[src] + c]
                pos += c
            new_slots.append((new_vals, new_offs))
        self._slots = new_slots

    def local_shuffle(self):
        self.global_shuffle()

    def get_memory_data_size(self, fleet=None):
        return self._num_samples

    def get_shuffle_data_size(self, fleet=None):
        return self._num_samples

    def release_memory(self):
        self._slots = None
        self._num_samples = 0

    def __iter__(self):
        """Yields per-batch lists: dense slots -> [B] or [B, k] arrays;
        sparse slots -> (padded [B, maxlen] int64, [B] lengths)."""
        bs = self._batch_size
        for start in range(0, self._num_samples - bs + 1, bs):
            batch = []
            for (vals, offs), dense in zip(self._slots,
                                           self._slot_is_dense):
                counts = np.diff(offs[start:start + bs + 1])
                if dense:
                    k = counts[0]
                    arr = vals[offs[start]:offs[start + bs]].reshape(bs, k)
                    batch.append(arr.copy())
                else:
                    maxlen = int(counts.max())
                    pad = np.zeros((bs, maxlen), np.int64)
                    for i in range(bs):
                        c = counts[i]
                        o = offs[start + i]
                        pad[i, :c] = vals[o:o + c].astype(np.int64)
                    batch.append((pad, counts.astype(np.int64)))
            yield batch


def _py_parse_multislot(text, num_slots):
    values = [[] for _ in range(num_slots)]
    offsets = [[0] for _ in range(num_slots)]
    for line in text.splitlines():
        toks = line.split()
        i = 0
        for s in range(num_slots):
            cnt = int(toks[i])
            i += 1
            values[s].extend(float(t) for t in toks[i:i + cnt])
            i += cnt
            offsets[s].append(offsets[s][-1] + cnt)
    return [(np.asarray(v, np.float32), np.asarray(o, np.int64))
            for v, o in zip(values, offsets)]


class QueueDataset(DatasetBase):
    """Streaming variant: files parsed on the fly through the native
    blocking queue (reference QueueDataset semantics — one pass, no
    global shuffle)."""

    def __iter__(self):
        inner = InMemoryDataset()
        inner._use_var = self._use_var
        inner._batch_size = self._batch_size
        inner._thread_num = self._thread_num
        for path in self._filelist:
            inner.set_filelist([path])
            inner._num_slots = len(self._use_var) if self._use_var else 0
            inner.load_into_memory()
            yield from inner
            inner.release_memory()
