"""Data-generator authoring API for PS datasets.

Reference parity: python/paddle/distributed/fleet/data_generator/
data_generator.py — users subclass and implement generate_sample();
run_from_stdin/run_from_files emit the MultiSlot text format the
InMemoryDataset/DataFeed parser consumes ("<n> v1..vn" per slot).
"""
import sys


class DataGenerator:
    def __init__(self):
        self.batch_size_ = 1

    def set_batch(self, batch_size):
        self.batch_size_ = int(batch_size)

    def generate_sample(self, line):
        """User hook: returns an iterator of [(slot_name, [values]), ...]
        per output sample (reference contract)."""
        raise NotImplementedError(
            "implement generate_sample() in your DataGenerator subclass")

    def generate_batch(self, samples):
        def local_iter():
            for s in samples:
                yield s
        return local_iter

    def _format(self, record):
        return self._gen_str(record)

    def _gen_str(self, record):
        raise NotImplementedError

    def _emit(self, lines, write):
        # every sample flows through generate_batch (reference contract:
        # subclasses may batch/reorder/augment there), collected in
        # batch_size_ groups
        buf = []
        for line in lines:
            for record in self.generate_sample(line)():
                buf.append(record)
                if len(buf) >= self.batch_size_:
                    for rec in self.generate_batch(buf)():
                        write(self._format(rec))
                    buf = []
        if buf:
            for rec in self.generate_batch(buf)():
                write(self._format(rec))

    def run_from_stdin(self):
        self._emit(sys.stdin, sys.stdout.write)

    def run_from_files(self, filelist, output):
        def lines():
            for path in filelist:
                with open(path) as f:
                    yield from f
        with open(output, "w") as out:
            self._emit(lines(), out.write)


class MultiSlotDataGenerator(DataGenerator):
    """Reference: MultiSlotDataGenerator._gen_str — '<n> v1 .. vn' per
    slot, space-joined, newline-terminated."""

    def _gen_str(self, record):
        parts = []
        for _, values in record:
            parts.append(str(len(values)))
            parts.extend(str(v) for v in values)
        return " ".join(parts) + "\n"


class MultiSlotStringDataGenerator(DataGenerator):
    """Reference: MultiSlotStringDataGenerator — values are emitted
    verbatim (already strings)."""

    def _gen_str(self, record):
        parts = []
        for _, values in record:
            parts.append(str(len(values)))
            parts.extend(values)
        return " ".join(parts) + "\n"
