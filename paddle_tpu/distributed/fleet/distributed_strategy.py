"""DistributedStrategy.

Reference parity: python/paddle/distributed/fleet/base/distributed_strategy.py
backed by paddle/fluid/framework/distributed_strategy.proto:159-211. Plain
python properties instead of protobuf; the accepted keys mirror the proto
fields so reference configs port directly.
"""
import copy


class DistributedStrategy:
    def __init__(self):
        # proto defaults (distributed_strategy.proto:159-211)
        self.amp = False
        self.amp_configs = {
            "init_loss_scaling": 32768.0, "incr_every_n_steps": 1000,
            "decr_every_n_nan_or_inf": 2, "incr_ratio": 2.0,
            "decr_ratio": 0.8, "use_dynamic_loss_scaling": True,
            "custom_white_list": [], "custom_black_list": [],
            "use_pure_fp16": False, "use_bf16": True,
        }
        self.recompute = False
        self.recompute_configs = {"checkpoints": []}
        self.pipeline = False
        self.pipeline_configs = {"accumulate_steps": 1, "micro_batch_size": 1,
                                 "schedule_mode": "1F1B"}
        self.tensor_parallel = False
        self.tensor_parallel_configs = {"tensor_parallel_degree": 1}
        self.sharding = False
        self.sharding_configs = {"sharding_degree": 1, "stage": 1,
                                 "offload": False}
        self.gradient_merge = False
        self.gradient_merge_configs = {"k_steps": 1, "avg": True}
        self.lars = False
        self.lars_configs = {}
        self.lamb = False
        self.lamb_configs = {}
        self.dgc = False
        self.dgc_configs = {"rampup_begin_step": 0, "rampup_step": 1,
                            "sparsity": [0.999]}
        self.fp16_allreduce = False
        self.localsgd = False
        self.localsgd_configs = {"k_steps": 1, "begin_step": 1}
        self.adaptive_localsgd = False
        self.adaptive_localsgd_configs = {"init_k_steps": 1, "begin_step": 1}
        self.a_sync = False
        self.a_sync_configs = {}
        self.elastic = False
        self.auto = False
        self.fuse_all_reduce_ops = True
        self.fuse_grad_size_in_MB = 32
        self.nccl_comm_num = 1
        self.hybrid_configs = {
            "dp_degree": 1, "mp_degree": 1, "pp_degree": 1,
            "sharding_degree": 1, "sp_degree": 1,
        }
        self.find_unused_parameters = False
        self.heter_ccl_mode = False

    _DEGREE_KEYS = ("dp_degree", "mp_degree", "pp_degree",
                    "sharding_degree", "sp_degree")

    def __setattr__(self, key, value):
        if key == "hybrid_configs" and hasattr(self, "hybrid_configs"):
            # validate instead of silently absorbing typos: a misspelled
            # degree key would otherwise quietly stay 1 (reference:
            # distributed_strategy.py check_configs_key)
            unknown = set(value) - set(self._DEGREE_KEYS)
            if unknown:
                raise ValueError(
                    f"unknown hybrid_configs keys {sorted(unknown)}; "
                    f"valid keys: {list(self._DEGREE_KEYS)}")
            for k, v in value.items():
                if not isinstance(v, int) or isinstance(v, bool) or v < 1:
                    raise ValueError(
                        f"hybrid_configs[{k!r}] must be a positive int, "
                        f"got {v!r}")
            merged = dict(self.hybrid_configs)
            merged.update(value)
            object.__setattr__(self, key, merged)
            return
        if key.endswith("_configs") and hasattr(self, key) \
                and isinstance(getattr(self, key), dict) \
                and isinstance(value, dict):
            known = set(getattr(self, key))
            unknown = set(value) - known
            if known and unknown:
                raise ValueError(
                    f"unknown {key} keys {sorted(unknown)}; valid: "
                    f"{sorted(known)}")
            merged = dict(getattr(self, key))
            merged.update(value)
            object.__setattr__(self, key, merged)
            return
        if not hasattr(self, key) and hasattr(self, "heter_ccl_mode"):
            # object fully constructed: unknown attribute = typo
            raise AttributeError(
                f"DistributedStrategy has no field {key!r} (reference "
                "proto: distributed_strategy.proto:159-211)")
        object.__setattr__(self, key, value)

    def check_conflicts(self, device_count=None):
        """Minimal strategy-compiler conflict rules (reference:
        fleet/base/strategy_compiler.py + meta-optimizer
        _can_apply/_disable_strategy chains)."""
        errs = []
        if self.a_sync and (self.pipeline or self.tensor_parallel
                            or self.sharding):
            errs.append("a_sync (parameter-server mode) cannot combine "
                        "with pipeline/tensor_parallel/sharding")
        if self.dgc and self.fp16_allreduce:
            errs.append("dgc and fp16_allreduce are mutually exclusive")
        if (self.localsgd or self.adaptive_localsgd) and self.pipeline:
            errs.append("localsgd cannot combine with pipeline")
        if self.localsgd and self.adaptive_localsgd:
            errs.append("localsgd and adaptive_localsgd are exclusive")
        hc = self.hybrid_configs
        total = 1
        for k in self._DEGREE_KEYS:
            total *= hc.get(k, 1)
        if device_count is not None and total not in (1, device_count):
            errs.append(
                f"hybrid degrees multiply to {total} but "
                f"{device_count} devices are available")
        if errs:
            raise ValueError("DistributedStrategy conflicts: "
                             + "; ".join(errs))
        return True

    def __repr__(self):
        flags = [k for k in ("amp", "recompute", "pipeline", "tensor_parallel",
                             "sharding", "gradient_merge", "lars", "lamb",
                             "dgc", "localsgd", "a_sync")
                 if getattr(self, k)]
        return f"DistributedStrategy(enabled={flags}, hybrid={self.hybrid_configs})"

    def copy(self):
        return copy.deepcopy(self)
