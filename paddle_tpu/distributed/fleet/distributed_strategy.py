"""DistributedStrategy.

Reference parity: python/paddle/distributed/fleet/base/distributed_strategy.py
backed by paddle/fluid/framework/distributed_strategy.proto:159-211. Plain
python properties instead of protobuf; the accepted keys mirror the proto
fields so reference configs port directly.
"""
import copy


class DistributedStrategy:
    def __init__(self):
        # proto defaults (distributed_strategy.proto:159-211)
        self.amp = False
        self.amp_configs = {
            "init_loss_scaling": 32768.0, "incr_every_n_steps": 1000,
            "decr_every_n_nan_or_inf": 2, "incr_ratio": 2.0,
            "decr_ratio": 0.8, "use_dynamic_loss_scaling": True,
            "custom_white_list": [], "custom_black_list": [],
            "use_pure_fp16": False, "use_bf16": True,
        }
        self.recompute = False
        self.recompute_configs = {"checkpoints": []}
        self.pipeline = False
        self.pipeline_configs = {"accumulate_steps": 1, "micro_batch_size": 1,
                                 "schedule_mode": "1F1B"}
        self.tensor_parallel = False
        self.tensor_parallel_configs = {"tensor_parallel_degree": 1}
        self.sharding = False
        self.sharding_configs = {"sharding_degree": 1, "stage": 1,
                                 "offload": False}
        self.gradient_merge = False
        self.gradient_merge_configs = {"k_steps": 1, "avg": True}
        self.lars = False
        self.lars_configs = {}
        self.lamb = False
        self.lamb_configs = {}
        self.dgc = False
        self.dgc_configs = {"rampup_begin_step": 0, "rampup_step": 1,
                            "sparsity": [0.999]}
        self.fp16_allreduce = False
        self.localsgd = False
        self.localsgd_configs = {"k_steps": 1, "begin_step": 1}
        self.adaptive_localsgd = False
        self.adaptive_localsgd_configs = {"init_k_steps": 1, "begin_step": 1}
        self.a_sync = False
        self.a_sync_configs = {}
        self.elastic = False
        self.auto = False
        self.fuse_all_reduce_ops = True
        self.fuse_grad_size_in_MB = 32
        self.nccl_comm_num = 1
        self.hybrid_configs = {
            "dp_degree": 1, "mp_degree": 1, "pp_degree": 1,
            "sharding_degree": 1, "sp_degree": 1,
        }
        self.find_unused_parameters = False
        self.heter_ccl_mode = False

    def __setattr__(self, key, value):
        if key == "hybrid_configs" and hasattr(self, "hybrid_configs"):
            merged = dict(self.hybrid_configs)
            merged.update(value)
            object.__setattr__(self, key, merged)
            return
        object.__setattr__(self, key, value)

    def __repr__(self):
        flags = [k for k in ("amp", "recompute", "pipeline", "tensor_parallel",
                             "sharding", "gradient_merge", "lars", "lamb",
                             "dgc", "localsgd", "a_sync")
                 if getattr(self, k)]
        return f"DistributedStrategy(enabled={flags}, hybrid={self.hybrid_configs})"

    def copy(self):
        return copy.deepcopy(self)
