"""HybridParallelOptimizer.

Reference parity: python/paddle/distributed/fleet/meta_parallel/
dygraph_optimizer/hybrid_parallel_optimizer.py:89 — wraps the inner
optimizer with (a) TP-aware global-norm clipping (HybridParallelClipGrad:32
— norm is computed over the full logical params; in our design params are
full logical tensors already, so the standard clip is exactly the hybrid
clip), (b) cross-group grad sync (GSPMD inserts it), (c) optional
ZeRO-style optimizer-state sharding over the 'sharding' axis.
"""
import jax
from jax.sharding import NamedSharding, PartitionSpec as P

from ... import optimizer as opt_mod  # noqa: F401  (type ref)
from .. import topology


class HybridParallelOptimizer:
    def __init__(self, inner_opt, hcg=None, strategy=None):
        self._inner_opt = inner_opt
        self._hcg = hcg
        self._strategy = strategy
        self._shard_states = (hcg is not None and
                              hcg.get_sharding_parallel_world_size() > 1)

    def __getattr__(self, item):
        return getattr(self._inner_opt, item)

    def step(self):
        self._inner_opt.step()
        if self._shard_states:
            self._apply_state_sharding()

    def minimize(self, loss, startup_program=None, parameters=None,
                 no_grad_set=None):
        loss.backward()
        self.step()
        return None, None

    def clear_grad(self):
        self._inner_opt.clear_grad()

    clear_gradients = clear_grad

    def _apply_state_sharding(self):
        """ZeRO-1: shard optimizer moment tensors over the 'sharding' axis.
        Applied as sharding constraints inside the (traced) step, so GSPMD
        generates the reduce-scatter/all-gather traffic when the step
        compiles (reference: sharding_optimizer.py:43 does this with
        explicit c_ops); eager phases stay unsharded."""
        from .meta_parallel.mp_layers import shard_constraint
        mesh = self._hcg.mesh if self._hcg else topology.get_mesh()
        if mesh is None:
            return
        deg = int(mesh.shape["sharding"])
        for kind, store in self._inner_opt._accumulators.items():
            for t in store.values():
                shape = t.aval_shape()
                if not shape:
                    continue
                spec = [None] * len(shape)
                for i, s in enumerate(shape):
                    if s % deg == 0 and s >= deg:
                        spec[i] = "sharding"
                        break
                if any(spec):
                    out = shard_constraint(t, spec)
                    if out is not t:
                        t.value = out.value
