"""Distributed environment.

Reference parity: python/paddle/fluid/dygraph/parallel.py ParallelEnv (env
vars PADDLE_TRAINER_ID / PADDLE_TRAINERS_NUM set per-process by the
launcher, reference fleet/launch_utils.py). TPU-native: a single controller
process drives all local chips (SPMD), so "rank" means host process index
(jax.process_index) and device parallelism is expressed with a Mesh, not
one process per device.
"""
import os

import jax


def get_rank():
    return jax.process_index()


def get_world_size():
    return jax.process_count()


class ParallelEnv:
    @property
    def rank(self):
        return get_rank()

    @property
    def local_rank(self):
        return get_rank()

    @property
    def world_size(self):
        return get_world_size()

    @property
    def nranks(self):
        return get_world_size()

    @property
    def dev_id(self):
        return 0

    @property
    def device_type(self):
        return jax.default_backend()

    @property
    def current_endpoint(self):
        return os.environ.get("PADDLE_CURRENT_ENDPOINT", "127.0.0.1:0")

    @property
    def trainer_endpoints(self):
        eps = os.environ.get("PADDLE_TRAINER_ENDPOINTS", "")
        return eps.split(",") if eps else []
