"""Compiled pipeline parallelism over the 'pp' mesh axis.

TPU-native replacement for the reference 1F1B pipeline engine (reference:
paddle/fluid/framework/section_worker.cc:34 SectionWorker schedule_mode_==1,
fleet/meta_parallel/pp_utils/p2p_communication.py send/recv over NCCL p2p).

Design: instead of per-stage processes exchanging activations with p2p ops,
all pp devices run ONE compiled SPMD program (shard_map over 'pp'). Stage
parameters are stacked on a leading pp-sharded axis so device i holds stage
i's weights. The schedule is a lax.scan over M + P - 1 ticks; each tick
every device runs its stage on the microbatch in flight and the activation
ring advances with lax.ppermute (ICI neighbor transfer, overlapped by XLA's
latency-hiding scheduler). Backward is jax autodiff of the scan — the
reversed scan with reversed ppermute IS the pipeline backward pass, giving
1F1B-equivalent gradient accumulation without hand-written scheduling.
Memory: pass remat=True to checkpoint each tick (recompute in backward),
the analogue of the reference's per-microbatch scope recycling.
"""
import functools

import jax
from ..core.jax_compat import shard_map as _shard_map
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P


def stack_stage_params(per_stage_params):
    """[stage][pytree] -> pytree with leading stage axis (to shard on pp)."""
    return jax.tree.map(lambda *xs: jnp.stack(xs, axis=0), *per_stage_params)


def pipeline_apply(stage_fn, stacked_params, x_microbatches, mesh,
                   axis_name="pp", remat=True):
    """Run the pipelined stack.

    stage_fn(params_slice, x) -> y     homogeneous per-stage computation
    stacked_params: pytree, leading dim P (stage), sharded over axis_name
    x_microbatches: [M, ...mb shape...] microbatched inputs (replicated)

    Returns [M, ...] outputs of the final stage (replicated).
    """
    pp = int(mesh.shape[axis_name])
    m = x_microbatches.shape[0]
    if pp == 1:
        params0 = jax.tree.map(lambda a: a[0], stacked_params)
        return jax.vmap(lambda xb: stage_fn(params0, xb))(x_microbatches)

    def body(local_params, xs):
        # local_params: leading dim 1 (this device's stage); xs: [M, ...]
        params = jax.tree.map(lambda a: a[0], local_params)
        idx = jax.lax.axis_index(axis_name)
        ticks = m + pp - 1
        perm_fwd = [(i, (i + 1) % pp) for i in range(pp)]

        mb_shape = xs.shape[1:]
        state = jnp.zeros(mb_shape, xs.dtype)      # activation arriving
        outs = jnp.zeros((m,) + mb_shape, xs.dtype)

        def tick(carry, t):
            state, outs = carry
            # stage 0 consumes fresh microbatch t (clamped), others consume
            # the activation that just arrived on the ring
            x_in = jnp.where(idx == 0,
                             xs[jnp.clip(t, 0, m - 1)], state)
            fn = jax.checkpoint(stage_fn) if remat else stage_fn
            y = fn(params, x_in)
            # last stage finished microbatch (t - pp + 1) at this tick
            done_idx = t - (pp - 1)
            is_last = idx == pp - 1
            valid = (done_idx >= 0) & (done_idx < m) & is_last
            outs = jax.lax.cond(
                valid,
                lambda o: jax.lax.dynamic_update_index_in_dim(
                    o, y, jnp.clip(done_idx, 0, m - 1), 0),
                lambda o: o, outs)
            state = jax.lax.ppermute(y, axis_name, perm_fwd)
            return (state, outs), None

        (state, outs), _ = jax.lax.scan(tick, (state, outs),
                                        jnp.arange(ticks))
        # outs live on the last stage only; broadcast to every device so the
        # loss is computable SPMD (sum over the one non-zero contribution)
        outs = jax.lax.psum(
            jnp.where(idx == pp - 1, outs, jnp.zeros_like(outs)), axis_name)
        return outs

    fn = _shard_map(
        body, mesh=mesh,
        in_specs=(jax.tree.map(lambda _: P(axis_name), stacked_params),
                  P()),
        out_specs=P(), check_vma=False)
    return fn(stacked_params, x_microbatches)


def pipeline_blocks_apply(block_fn, stacked_params, valid, h, microbatches,
                          mesh, axis_name="pp", remat=True):
    """Heterogeneous-model middle pipeline (reference: SectionWorker 1F1B,
    section_worker.cc:34, but expressed as ONE compiled SPMD program).

    The model's edge stages (embedding / head / loss) run as plain GSPMD
    ops outside this call; only the repeated homogeneous blocks are
    pipelined — the idiomatic TPU split (praxis-style), since the edge
    stages hold almost no FLOPs and the shared/tied embedding then needs
    no cross-stage weight exchange at all.

    block_fn(params_one_block, h_mb) -> h_mb   one block, same signature
    stacked_params: pytree, leaves [pp, L, ...] — stage-major stacking of
        the blocks' params (L = max blocks per stage, padded); sharded on
        axis_name so device s holds only stage s's block weights.
    valid: bool [pp, L] — False marks padded slots (uneven segmentation).
    h: [B, ...] activations entering the first block; any non-pp sharding
        (dp/mp GSPMD) is preserved — shard_map is manual ONLY over
        axis_name, the rest of the mesh stays in auto (GSPMD) mode.
    microbatches: M; B must divide by M.

    Returns [B, ...] activations after the last block. The schedule is a
    lax.scan over M + pp - 1 ticks with lax.ppermute ring transfers;
    backward through it (jax autodiff) IS the reversed pipeline with
    1F1B-equivalent gradient accumulation.
    """
    pp = int(mesh.shape[axis_name])
    b = h.shape[0]
    m = int(microbatches)
    assert b % m == 0, f"batch {b} must divide microbatches {m}"

    def stage_fn(params, flags, x):
        # scan this stage's own blocks (uneven stages: padded slots are
        # computed-and-discarded via where, keeping shapes static)
        def one(carry, sl):
            p, flag = sl
            y = block_fn(p, carry)
            return jnp.where(flag, y, carry), None

        fn = jax.checkpoint(one) if remat else one
        x, _ = jax.lax.scan(fn, x, (params, flags))
        return x

    if pp == 1:
        params0 = jax.tree.map(lambda a: a[0], stacked_params)
        return stage_fn(params0, valid[0], h)

    xs = h.reshape((m, b // m) + h.shape[1:])

    def body(local_params, local_valid, xs):
        params = jax.tree.map(lambda a: a[0], local_params)
        flags = local_valid[0]
        idx = jax.lax.axis_index(axis_name)
        ticks = m + pp - 1
        perm_fwd = [(i, (i + 1) % pp) for i in range(pp)]
        state = jnp.zeros_like(xs[0])
        outs = jnp.zeros_like(xs)

        def tick(carry, t):
            state, outs = carry
            x_in = jnp.where(idx == 0, xs[jnp.clip(t, 0, m - 1)], state)
            y = stage_fn(params, flags, x_in)
            done_idx = t - (pp - 1)
            valid_t = (done_idx >= 0) & (done_idx < m) & (idx == pp - 1)
            outs = jax.lax.cond(
                valid_t,
                lambda o: jax.lax.dynamic_update_index_in_dim(
                    o, y, jnp.clip(done_idx, 0, m - 1), 0),
                lambda o: o, outs)
            state = jax.lax.ppermute(y, axis_name, perm_fwd)
            return (state, outs), None

        (state, outs), _ = jax.lax.scan(tick, (state, outs),
                                        jnp.arange(ticks))
        # outputs live on the last stage; make them SPMD-visible
        outs = jax.lax.psum(
            jnp.where(idx == pp - 1, outs, jnp.zeros_like(outs)), axis_name)
        return outs

    fn = _shard_map(
        body, mesh=mesh,
        in_specs=(jax.tree.map(lambda _: P(axis_name), stacked_params),
                  P(axis_name), P()),
        out_specs=P(), axis_names={axis_name}, check_vma=False)
    outs = fn(stacked_params, valid, xs)
    return outs.reshape((b,) + h.shape[1:])


def pipeline_loss_and_grad(stage_fn, loss_fn, stacked_params,
                           x_microbatches, y_microbatches, mesh,
                           axis_name="pp", remat=True):
    """Mean loss over microbatches + grads wrt stacked params — one compiled
    SPMD program; the backward pipeline emerges from autodiff."""

    def total_loss(params):
        outs = pipeline_apply(stage_fn, params, x_microbatches, mesh,
                              axis_name, remat)
        losses = jax.vmap(loss_fn)(outs, y_microbatches)
        return jnp.mean(losses)

    return jax.value_and_grad(total_loss)(stacked_params)
