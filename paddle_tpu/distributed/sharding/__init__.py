"""ZeRO-style sharded data parallelism.

Reference parity: python/paddle/distributed/fleet/meta_optimizers/
sharding_optimizer.py:43 (static ZeRO-1/2),
sharding_optimizer.py:118-138 (hybrid meshes), and dygraph_optimizer/
dygraph_sharding_optimizer.py:27. TPU-native: sharding is a placement
over the 'sharding' mesh axis — optimizer states (stage 1 / 'os'), plus
gradients (stage 2 / 'os_g'), plus parameters (stage 3 / 'p_g_os') get
NamedShardings; GSPMD emits the reduce-scatter/all-gather traffic, which
is exactly the ZeRO communication pattern.

Placement strategy:
- State arrays are physically placed with their sharded NamedSharding
  ONCE (first step after the accumulators exist). Elementwise optimizer
  math preserves input shardings, so eager steps stay sharded with no
  per-step re-placement, and compiled steps inherit the placement through
  the captured inputs.
- Inside a compiled (to_static) step, gradients (stage >= 2) and updated
  state get with_sharding_constraint annotations so XLA reduce-scatters
  grads and keeps the optimizer update sharded; parameters consumed by
  matmuls are all-gathered on use by GSPMD (stage 3 gather-on-use).
- Eagerly, jax computes directly on sharded committed arrays, so stage-3
  params remain usable outside jit (gather-on-use happens per op).
"""
import jax
from jax.sharding import NamedSharding, PartitionSpec as P

from .. import topology
from ...core import trace as trace_mod


def _shard_spec(shape, deg, axis="sharding"):
    spec = [None] * len(shape)
    for i, s in enumerate(shape):
        if s % deg == 0 and s >= deg:
            spec[i] = axis
            break
    return spec


def _place_once(t, mesh, deg, placed, axis="sharding"):
    """Physically shard a state tensor's array over the sharding axis
    (eager, one-time)."""
    if id(t) in placed:
        return
    v = t._value
    if v is None or getattr(v, "ndim", 0) == 0:
        return
    spec = _shard_spec(v.shape, deg, axis)
    if not any(spec):
        return
    try:
        t._value = jax.device_put(v, NamedSharding(mesh, P(*spec)))
        placed.add(id(t))
    except (ValueError, RuntimeError):
        pass


def group_sharded_parallel(model, optimizer, level="os_g", scaler=None,
                           group=None, offload=False, sync_buffers=False,
                           buffer_max_size=2 ** 23, segment_size=2 ** 20,
                           sync_comm=False, axis="sharding"):
    """Reference: python/paddle/distributed/sharding/group_sharded.py.
    level: 'os' (ZeRO-1), 'os_g' (ZeRO-2), 'p_g_os' (ZeRO-3).

    axis: mesh axis the shards live on. The default is the dedicated
    'sharding' axis; pass "dp" for the reference's standard hybrid where
    ZeRO is folded into data parallelism (sharding_optimizer.py:118-138
    — dp replicas double as shard owners, so dp x mp x pp meshes get
    ZeRO without a fourth axis)."""
    if level not in ("os", "os_g", "p_g_os"):
        raise ValueError(f"unknown sharding level {level!r}")
    mesh = topology.get_mesh()
    if mesh is None or int(mesh.shape.get(axis, 1)) == 1:
        return model, optimizer, scaler
    deg = int(mesh.shape[axis])

    from ..fleet.meta_parallel.mp_layers import shard_constraint
    shard_grads = level in ("os_g", "p_g_os")
    shard_params = level == "p_g_os"
    orig_step = optimizer.step
    placed = set()
    params = list(model.parameters())

    if shard_params:
        for p in params:
            _place_once(p, mesh, deg, placed, axis)

    def sharded_step():
        in_trace = trace_mod.current_trace() is not None
        if shard_grads and in_trace:
            # annotate grads before the optimizer consumes them: GSPMD
            # then reduce-scatters the dp-psum straight into shards
            for p in params:
                g = p.grad
                if g is None:
                    continue
                shape = g.aval_shape()
                spec = _shard_spec(shape, deg, axis) if shape else []
                if any(spec):
                    out = shard_constraint(g, spec, mesh=mesh)
                    if out is not g:
                        g.value = out.value
        orig_step()
        for kind, store in optimizer._accumulators.items():
            for t in store.values():
                shape = t.aval_shape()
                if not shape:
                    continue
                spec = _shard_spec(shape, deg, axis)
                if not any(spec):
                    continue
                if in_trace:
                    out = shard_constraint(t, spec, mesh=mesh)
                    if out is not t:
                        t.value = out.value
                else:
                    _place_once(t, mesh, deg, placed, axis)
        for p in params:
            shape = p.aval_shape()
            if not shape:
                continue
            spec = _shard_spec(shape, deg, axis) if shard_params \
                else [None] * len(shape)
            if shard_params and not any(spec):
                continue
            if in_trace:
                # stage 3: keep params sharded; stage 1/2: pin params
                # REPLICATED or GSPMD would propagate the sharded moment
                # layout into the updated params (that trades per-step
                # all-gathers for memory the level didn't ask to save)
                out = shard_constraint(p, spec, mesh=mesh)
                if out is not p:
                    p.value = out.value
            elif shard_params:
                _place_once(p, mesh, deg, placed, axis)

    optimizer.step = sharded_step
    return model, optimizer, scaler


class DygraphShardingOptimizer:
    """Reference: dygraph_sharding_optimizer.py:27 — rank-wise param group
    sharding. TPU-native: delegates to mesh sharding annotations."""

    def __init__(self, hcg=None, user_defined_strategy=None, params=None,
                 inner_optimizer_class=None, **inner_kw):
        if inner_optimizer_class is not None:
            self._inner = inner_optimizer_class(parameters=params, **inner_kw)
        else:
            self._inner = None
        self._hcg = hcg
        self._placed = set()

    def __getattr__(self, item):
        return getattr(self._inner, item)

    def step(self):
        self._inner.step()
        mesh = self._hcg.mesh if self._hcg else topology.get_mesh()
        if mesh is None:
            return
        deg = int(mesh.shape.get("sharding", 1))
        if deg == 1:
            return
        for kind, store in self._inner._accumulators.items():
            for t in store.values():
                _place_once(t, mesh, deg, self._placed)
