"""ZeRO-style sharded data parallelism.

Reference parity: python/paddle/distributed/fleet/meta_optimizers/
sharding_optimizer.py:43 (static ZeRO-1/2) and dygraph_optimizer/
dygraph_sharding_optimizer.py:27. TPU-native: sharding is a placement
annotation over the 'sharding' mesh axis — optimizer states (stage 1),
plus gradients (stage 2), plus parameters (stage 3) get NamedShardings;
XLA emits the reduce-scatter/all-gather traffic GSPMD-style, which is
exactly the ZeRO communication pattern.
"""
import jax
from jax.sharding import NamedSharding, PartitionSpec as P

from .. import topology


def _shard_spec(shape, deg):
    spec = [None] * len(shape)
    for i, s in enumerate(shape):
        if s % deg == 0 and s >= deg:
            spec[i] = "sharding"
            break
    return spec


def _try_place(arr, mesh, spec):
    try:
        return jax.device_put(arr, NamedSharding(mesh, P(*spec)))
    except (ValueError, RuntimeError):
        return arr


def group_sharded_parallel(model, optimizer, level="os_g", scaler=None,
                           group=None, offload=False, sync_buffers=False,
                           buffer_max_size=2 ** 23, segment_size=2 ** 20,
                           sync_comm=False):
    """Reference: python/paddle/distributed/sharding/group_sharded.py.
    level: 'os' (ZeRO-1), 'os_g' (ZeRO-2), 'p_g_os' (ZeRO-3)."""
    mesh = topology.get_mesh()
    if mesh is None or int(mesh.shape.get("sharding", 1)) == 1:
        return model, optimizer, scaler
    deg = int(mesh.shape["sharding"])

    from ..fleet.meta_parallel.mp_layers import shard_constraint
    shard_params = level == "p_g_os"
    orig_step = optimizer.step

    def sharded_step():
        orig_step()
        # sharding constraints materialize when the step compiles; eager
        # phases stay single-device (see mp_layers.shard_constraint)
        for kind, store in optimizer._accumulators.items():
            for t in store.values():
                shape = t.aval_shape()
                if not shape:
                    continue
                spec = _shard_spec(shape, deg)
                if any(spec):
                    out = shard_constraint(t, spec, mesh=mesh)
                    if out is not t:
                        t.value = out.value
        if shard_params:
            for p in model.parameters():
                spec = _shard_spec(p.aval_shape(), deg)
                if any(spec):
                    out = shard_constraint(p, spec, mesh=mesh)
                    if out is not p:
                        p.value = out.value

    optimizer.step = sharded_step
    return model, optimizer, scaler


class DygraphShardingOptimizer:
    """Reference: dygraph_sharding_optimizer.py:27 — rank-wise param group
    sharding. TPU-native: delegates to mesh sharding annotations."""

    def __init__(self, hcg=None, user_defined_strategy=None, params=None,
                 inner_optimizer_class=None, **inner_kw):
        if inner_optimizer_class is not None:
            self._inner = inner_optimizer_class(parameters=params, **inner_kw)
        else:
            self._inner = None
        self._hcg = hcg

    def __getattr__(self, item):
        return getattr(self._inner, item)

    def step(self):
        self._inner.step()
        mesh = self._hcg.mesh if self._hcg else topology.get_mesh()
        if mesh is None:
            return
        deg = int(mesh.shape.get("sharding", 1))
        if deg == 1:
            return
        for kind, store in self._inner._accumulators.items():
            for t in store.values():
                v = t._value
                if v is None or v.ndim == 0:
                    continue
                spec = _shard_spec(v.shape, deg)
                if any(spec):
                    t._value = _try_place(v, mesh, spec)
