"""Trainer-side communicators: sync / async / geo gradient flow.

Reference: paddle/fluid/distributed/service/communicator.h:197
(Communicator base: send queues + merge), :348 (AsyncCommunicator —
background send thread merging up to max_merge_var_num grads before
pushing), :497 (GeoCommunicator — local SGD with periodic delta sync).
"""
import queue
import threading

import numpy as np


class Communicator:
    """Sync mode: push gradients immediately, callers pull when needed."""

    def __init__(self, client):
        self.client = client

    def send_dense(self, table_id, grad):
        self.client.push_dense(table_id, grad)

    def send_sparse(self, table_id, ids, grads):
        self.client.push_sparse(table_id, ids, grads)

    def recv_dense(self, table_id):
        return self.client.pull_dense(table_id)

    def start(self):
        return self

    def stop(self):
        pass

    def flush(self):
        pass


class AsyncCommunicator(Communicator):
    """Async mode: gradients go to a queue; a background thread merges up
    to `max_merge_var_num` pending grads per table and pushes the sum
    (reference: communicator.h:348, FLAGS_communicator_max_merge_var_num
    platform/flags.cc:210)."""

    def __init__(self, client, max_merge_var_num=20, send_wait_ms=5):
        super().__init__(client)
        self.max_merge = int(max_merge_var_num)
        self.wait_s = send_wait_ms / 1000.0
        self._q = queue.Queue()
        self._stop = threading.Event()
        self._thread = None
        self._inflight = threading.Semaphore(0)
        self._pending = 0
        self._pending_lock = threading.Lock()

    def start(self):
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()
        return self

    def send_dense(self, table_id, grad):
        with self._pending_lock:
            self._pending += 1
        self._q.put(("dense", table_id, np.asarray(grad)))

    def send_sparse(self, table_id, ids, grads):
        with self._pending_lock:
            self._pending += 1
        self._q.put(("sparse", table_id, (np.asarray(ids),
                                          np.asarray(grads))))

    def _drain(self, first):
        """Collect up to max_merge messages for the same (kind, table)."""
        kind, tid, payload = first
        if kind == "dense":
            acc = payload.astype(np.float32)
        else:
            acc_ids = [payload[0]]
            acc_grads = [payload[1]]
        n = 1
        back = []
        while n < self.max_merge:
            try:
                item = self._q.get_nowait()
            except queue.Empty:
                break
            if item[0] == kind and item[1] == tid:
                if kind == "dense":
                    acc = acc + item[2]
                else:
                    acc_ids.append(item[2][0])
                    acc_grads.append(item[2][1])
                n += 1
            else:
                back.append(item)
        for item in back:
            self._q.put(item)
        if kind == "dense":
            return kind, tid, acc, n
        return kind, tid, (np.concatenate(acc_ids),
                           np.concatenate(acc_grads)), n

    def _loop(self):
        while not self._stop.is_set() or not self._q.empty():
            try:
                first = self._q.get(timeout=self.wait_s)
            except queue.Empty:
                continue
            kind, tid, payload, n = self._drain(first)
            try:
                if kind == "dense":
                    self.client.push_dense(tid, payload)
                else:
                    self.client.push_sparse(tid, payload[0], payload[1])
            except Exception as e:  # noqa: BLE001 — surfaced by flush()
                self._error = e
                with self._pending_lock:
                    self._pending -= n
                return  # dead server: stop consuming, flush() re-raises
            with self._pending_lock:
                self._pending -= n

    _error = None

    def flush(self):
        import time
        while True:
            if self._error is not None:
                raise RuntimeError(
                    "AsyncCommunicator send thread failed") from self._error
            if self._thread is not None and not self._thread.is_alive() \
                    and not self._stop.is_set():
                raise RuntimeError("AsyncCommunicator send thread died")
            with self._pending_lock:
                if self._pending == 0 and self._q.empty():
                    return
            time.sleep(0.005)

    def stop(self):
        self.flush()
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10)


class GeoCommunicator(Communicator):
    """Geo-SGD: train on a LOCAL copy; every `k_steps` push the delta
    (local - last_synced) and pull the server's merged state (reference:
    communicator.h:497 GeoCommunicator; the server table uses the 'sum'
    rule so deltas from all trainers accumulate)."""

    def __init__(self, client, k_steps=4):
        super().__init__(client)
        self.k_steps = int(k_steps)
        self._local = {}
        self._synced = {}
        self._steps = {}

    def init_dense(self, table_id):
        v = self.client.pull_dense(table_id)
        self._local[table_id] = np.array(v, np.float32)
        self._synced[table_id] = np.array(v, np.float32)
        self._steps[table_id] = 0
        return self._local[table_id]

    def local_value(self, table_id):
        return self._local[table_id]

    def local_update(self, table_id, grad, lr):
        """One local SGD step; triggers a geo sync every k_steps."""
        self._local[table_id] -= lr * np.asarray(grad, np.float32)
        self._steps[table_id] += 1
        if self._steps[table_id] % self.k_steps == 0:
            self._geo_sync(table_id)

    def _geo_sync(self, table_id):
        delta = self._local[table_id] - self._synced[table_id]
        self.client.push_dense(table_id, delta)  # server rule: 'sum'
        fresh = np.asarray(self.client.pull_dense(table_id), np.float32)
        self._local[table_id] = fresh.copy()
        self._synced[table_id] = fresh.copy()

    def flush(self):
        for tid in list(self._local):
            self._geo_sync(tid)
