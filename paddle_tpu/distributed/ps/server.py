"""PS server: owns tables, serves pull/push over TCP.

Reference: paddle/fluid/distributed/service/brpc_ps_server.h BrpcPsServer
+ distributed/table/common_dense_table.h / common_sparse_table.h (tables
with per-table optimizer rules applied server-side on push).
"""
import os
import socketserver
import threading

import numpy as np

from .rpc import send_msg, recv_msg


class DenseTable:
    """Reference: CommonDenseTable — a flat dense param block updated by
    pushed gradients with a server-side rule (sgd / adam / sum)."""

    def __init__(self, shape, optimizer="sgd", lr=0.01, init=None,
                 seed=0):
        self.lock = threading.Lock()
        if init is not None:
            self.value = np.asarray(init, np.float32).copy()
        else:
            rs = np.random.RandomState(seed)
            self.value = (rs.randn(*shape) * 0.01).astype(np.float32)
        self.optimizer = optimizer
        self.lr = float(lr)
        if optimizer == "adam":
            self._m = np.zeros_like(self.value)
            self._v = np.zeros_like(self.value)
            self._t = 0

    def pull(self):
        with self.lock:
            return self.value.copy()

    def push(self, grad):
        # the TCP server is threaded: concurrent trainer pushes must not
        # interleave the read-modify-write (numpy releases the GIL)
        g = np.asarray(grad, np.float32)
        with self.lock:
            if self.optimizer == "sum":
                self.value += g
            elif self.optimizer == "adam":
                self._t += 1
                self._m = 0.9 * self._m + 0.1 * g
                self._v = 0.999 * self._v + 0.001 * g * g
                mh = self._m / (1 - 0.9 ** self._t)
                vh = self._v / (1 - 0.999 ** self._t)
                self.value -= self.lr * mh / (np.sqrt(vh) + 1e-8)
            else:  # sgd
                self.value -= self.lr * g

    def set(self, value):
        with self.lock:
            self.value = np.asarray(value, np.float32).copy()

    def state(self):
        s = {"value": self.value, "optimizer": self.optimizer,
             "lr": self.lr}
        if self.optimizer == "adam":
            s.update(m=self._m, v=self._v, t=self._t)
        return s

    def load_state(self, s):
        self.value = s["value"]
        self.optimizer = s["optimizer"]
        self.lr = s["lr"]
        if self.optimizer == "adam":
            self._m, self._v, self._t = s["m"], s["v"], s["t"]


class SparseTable:
    """Reference: CommonSparseTable — hash-sparse embedding rows created
    on first access, sparse SGD/adagrad applied on push."""

    def __init__(self, dim, optimizer="sgd", lr=0.01, init_std=0.01,
                 seed=0):
        self.lock = threading.Lock()
        self.dim = int(dim)
        self.optimizer = optimizer
        self.lr = float(lr)
        self.init_std = float(init_std)
        self._rs = np.random.RandomState(seed)
        self.rows = {}
        self._acc = {}

    def _row(self, rid):
        r = self.rows.get(rid)
        if r is None:
            r = (self._rs.randn(self.dim) * self.init_std).astype(
                np.float32)
            self.rows[rid] = r
        return r

    def pull(self, ids):
        ids = np.asarray(ids).reshape(-1)
        with self.lock:
            return np.stack([self._row(int(i)).copy() for i in ids],
                            axis=0)

    def push(self, ids, grads):
        ids = np.asarray(ids).reshape(-1)
        grads = np.asarray(grads, np.float32).reshape(len(ids), self.dim)
        with self.lock:
            for i, g in zip(ids, grads):
                i = int(i)
                row = self._row(i)
                if self.optimizer == "adagrad":
                    acc = self._acc.get(i, 0.0) + float((g * g).mean())
                    self._acc[i] = acc
                    row -= self.lr / (np.sqrt(acc) + 1e-6) * g
                else:
                    row -= self.lr * g

    def state(self):
        with self.lock:
            return {"dim": self.dim, "optimizer": self.optimizer,
                    "lr": self.lr, "rows": dict(self.rows),
                    "acc": dict(self._acc), "init_std": self.init_std,
                    "rs": self._rs.get_state()}

    def load_state(self, s):
        self.dim = s["dim"]
        self.optimizer = s["optimizer"]
        self.lr = s["lr"]
        self.rows = s["rows"]
        self._acc = s["acc"]
        self.init_std = s.get("init_std", 0.01)
        if "rs" in s:
            # restore the row-init RNG stream position: rows created
            # after a restore must not replay pre-save values
            self._rs.set_state(s["rs"])


class SSDSparseTable(SparseTable):
    """Disk-backed sparse table (reference:
    distributed/table/ssd_sparse_table.h — embedding tables larger than
    RAM: a bounded in-memory hot set with LRU eviction, cold rows on
    disk; rocksdb there, an append-log with per-record checksums here).

    Crash durability (r4, the rocksdb-atomicity analogue): spills
    APPEND fixed-size records `[rid int64 | dim+1 float32 | crc32]` —
    the in-memory index advances to a record only after its bytes are
    fully written, and recovery (`recover()` / opening an existing
    path) scans the log keeping the LAST checksum-valid record per rid
    and truncates at the first torn/invalid one. A kill mid-spill
    therefore loses at most the record being written, never corrupts
    older data, and is detected — not silently read back as garbage.
    The log compacts in place (write-temp + atomic rename) when stale
    versions dominate."""

    _MAGIC = b"SSDT\x01"

    def __init__(self, dim, optimizer="sgd", lr=0.01, init_std=0.01,
                 seed=0, cache_rows=4096, path=None):
        super().__init__(dim, optimizer, lr, init_std, seed)
        import collections
        import tempfile
        self.rows = collections.OrderedDict()  # hot set, LRU order
        self.cache_rows = int(cache_rows)
        self._dir = path or tempfile.mkdtemp(prefix="ps_ssd_table_")
        os.makedirs(self._dir, exist_ok=True)
        self._data_path = os.path.join(self._dir, "rows.bin")
        self._rec = 8 + (self.dim + 1) * 4 + 4
        self._slots = {}              # rid -> byte offset of last record
        if os.path.exists(self._data_path):
            self._open_and_recover()
        else:
            self._file = open(self._data_path, "w+b")
            self._file.write(self._MAGIC
                             + np.uint32(self.dim).tobytes())
            self._file.flush()
            self._end = self._file.tell()

    # -- log format -------------------------------------------------------
    def _encode(self, rid, row, acc):
        import zlib
        payload = np.int64(rid).tobytes()
        rec = np.empty(self.dim + 1, np.float32)
        rec[:self.dim] = row
        rec[self.dim] = acc
        payload += rec.tobytes()
        return payload + np.uint32(
            zlib.crc32(payload) & 0xFFFFFFFF).tobytes()

    def _decode(self, buf):
        """(rid, row, acc) or None if torn/corrupt."""
        import zlib
        if len(buf) != self._rec:
            return None
        payload, crc = buf[:-4], buf[-4:]
        if np.frombuffer(crc, np.uint32)[0] != (
                zlib.crc32(payload) & 0xFFFFFFFF):
            return None
        rid = int(np.frombuffer(payload[:8], np.int64)[0])
        vals = np.frombuffer(payload[8:], np.float32)
        return rid, vals[:self.dim].copy(), float(vals[self.dim])

    def _open_and_recover(self):
        """Scan an existing log: keep the last valid record per rid,
        truncate at the first torn/invalid record (everything before it
        was written completely — append-log atomicity)."""
        self._file = open(self._data_path, "r+b")
        head = self._file.read(len(self._MAGIC) + 4)
        if len(head) < len(self._MAGIC) + 4:
            # crash in the window between file creation and the header
            # landing on disk: nothing was ever stored — reinitialize
            # as an empty log rather than refusing to restart
            self._file.seek(0)
            self._file.truncate(0)
            self._file.write(self._MAGIC + np.uint32(self.dim).tobytes())
            self._file.flush()
            self._end = self._file.tell()
            return
        if head[:len(self._MAGIC)] != self._MAGIC:
            raise RuntimeError(
                f"{self._data_path} is not an SSDSparseTable log "
                "(bad magic)")
        fdim = int(np.frombuffer(head[len(self._MAGIC):], np.uint32)[0])
        if fdim != self.dim:
            raise RuntimeError(
                f"SSDSparseTable log at {self._data_path} has dim "
                f"{fdim}, table expects {self.dim}")
        pos = len(head)
        while True:
            buf = self._file.read(self._rec)
            if not buf:
                break
            dec = self._decode(buf)
            if dec is None:
                # torn tail (kill mid-spill): discard it and everything
                # after — records are appended, so nothing valid follows
                self._file.truncate(pos)
                break
            self._slots[dec[0]] = pos
            pos += self._rec
        self._file.seek(0, os.SEEK_END)
        self._end = self._file.tell()

    @classmethod
    def recover(cls, path, dim, **kw):
        """Reopen a table directory after a crash; torn tail records
        from a kill mid-spill are detected (checksum) and dropped."""
        return cls(dim, path=path, **kw)

    def _row(self, rid):
        r = self.rows.get(rid)
        if r is not None:
            self.rows.move_to_end(rid)
            return r
        off = self._slots.get(rid)
        if off is not None:
            self._file.seek(off)
            dec = self._decode(self._file.read(self._rec))
            if dec is None or dec[0] != rid:
                raise RuntimeError(
                    f"SSDSparseTable: corrupt record for row {rid} at "
                    f"offset {off} (checksum mismatch)")
            r, acc = dec[1], dec[2]
            if acc:
                self._acc[rid] = acc
        else:
            r = (self._rs.randn(self.dim) * self.init_std).astype(
                np.float32)
        self.rows[rid] = r
        self._evict()
        return r

    def _spill(self, rid, row):
        buf = self._encode(rid, row, self._acc.pop(rid, 0.0))
        self._file.seek(self._end)
        self._file.write(buf)
        # the index advances ONLY after the full record is written: a
        # crash inside write() leaves the old index target intact
        self._slots[rid] = self._end
        self._end += self._rec

    def _evict(self):
        while len(self.rows) > self.cache_rows:
            rid, row = self.rows.popitem(last=False)  # oldest-touched
            self._spill(rid, row)
        self._maybe_compact()

    def _maybe_compact(self):
        live = max(1, len(self._slots))
        total = (self._end - len(self._MAGIC) - 4) // self._rec
        if total > 2 * live + 64:
            self._compact()

    def _compact(self):
        """Rewrite live records to a temp file and atomically rename —
        a crash mid-compaction leaves the original log untouched."""
        tmp = self._data_path + ".compact"
        with open(tmp, "wb") as f:
            f.write(self._MAGIC + np.uint32(self.dim).tobytes())
            new_slots = {}
            for rid, off in self._slots.items():
                self._file.seek(off)
                new_slots[rid] = f.tell()
                f.write(self._file.read(self._rec))
            f.flush()
            os.fsync(f.fileno())
        self._file.close()
        os.replace(tmp, self._data_path)
        self._file = open(self._data_path, "r+b")
        self._file.seek(0, os.SEEK_END)
        self._end = self._file.tell()
        self._slots = new_slots

    def _flush_locked(self):
        for rid in list(self.rows):
            acc = self._acc.get(rid)  # _spill pops; keep hot copy
            self._spill(rid, self.rows[rid])
            if acc is not None:
                self._acc[rid] = acc
        self._file.flush()
        os.fsync(self._file.fileno())
        # all-hot workloads never reach _evict's compaction check, but
        # every flush appends a fresh record per hot row — compact here
        # too or periodic snapshots grow the log without bound
        self._maybe_compact()

    def flush(self):
        """Spill every hot row to disk (fsynced; rows stay hot); called
        before state snapshots so the file is complete."""
        with self.lock:
            self._flush_locked()

    @property
    def hot_rows(self):
        return len(self.rows)

    @property
    def total_rows(self):
        return len(set(self._slots) | set(self.rows))

    def state(self):
        # point-in-time snapshot: the spill file's CONTENT is copied
        # into the state (referencing the live file would let later
        # evictions mutate the checkpoint, and the path may not exist
        # on a restore host). One lock scope for flush + read: a push
        # landing between them would make blob and acc/hot disagree.
        with self.lock:
            self._flush_locked()
            with open(self._data_path, "rb") as f:
                blob = f.read()
            return {"dim": self.dim, "optimizer": self.optimizer,
                    "lr": self.lr, "init_std": self.init_std,
                    "rs": self._rs.get_state(),
                    "cache_rows": self.cache_rows,
                    "slots": dict(self._slots),
                    "data_blob": blob,
                    "acc": dict(self._acc),
                    "hot_ids": list(self.rows)}

    def load_state(self, s):
        import collections
        import tempfile
        self.dim = s["dim"]
        self.optimizer = s["optimizer"]
        self.lr = s["lr"]
        self.init_std = s["init_std"]
        self._rs.set_state(s["rs"])
        self.cache_rows = s["cache_rows"]
        self._dir = tempfile.mkdtemp(prefix="ps_ssd_table_")
        self._data_path = os.path.join(self._dir, "rows.bin")
        with open(self._data_path, "wb") as f:
            f.write(s["data_blob"])
        self._file = open(self._data_path, "r+b")
        self._file.seek(0, os.SEEK_END)
        self._end = self._file.tell()
        self._slots = dict(s["slots"])
        self._acc = dict(s["acc"])
        self._rec = 8 + (self.dim + 1) * 4 + 4
        self.rows = collections.OrderedDict()
        for rid in s["hot_ids"]:      # rewarm the previously-hot set
            self._row(rid)


class GraphTable:
    """Graph service table for GNN training (reference:
    distributed/table/common_graph_table.h + the graph PS service
    graph_brpc_server.h — node/edge storage with weighted random
    neighbor sampling and node features; reduced: in-memory adjacency,
    same id%n_servers sharding as sparse tables)."""

    def __init__(self, feat_dim=0, seed=0):
        self.lock = threading.Lock()
        self.feat_dim = int(feat_dim)
        self._rs = np.random.RandomState(seed)
        self.adj = {}     # src -> (list of dst, list of weight)
        self.feats = {}   # node -> np.float32[feat_dim]

    def add_edges(self, src, dst, weights=None):
        src = np.asarray(src).reshape(-1)
        dst = np.asarray(dst).reshape(-1)
        w = (np.asarray(weights, np.float32).reshape(-1)
             if weights is not None else np.ones(len(src), np.float32))
        with self.lock:
            for s, d, wt in zip(src, dst, w):
                nbrs = self.adj.setdefault(int(s), ([], []))
                nbrs[0].append(int(d))
                nbrs[1].append(float(wt))

    def set_node_feat(self, ids, feats):
        ids = np.asarray(ids).reshape(-1)
        feats = np.asarray(feats, np.float32).reshape(len(ids),
                                                      self.feat_dim)
        with self.lock:
            for i, f in zip(ids, feats):
                self.feats[int(i)] = f.copy()

    def get_node_feat(self, ids):
        ids = np.asarray(ids).reshape(-1)
        with self.lock:
            return np.stack(
                [self.feats.get(int(i),
                                np.zeros(self.feat_dim, np.float32))
                 for i in ids], axis=0) if len(ids) else \
                np.zeros((0, self.feat_dim), np.float32)

    def sample_neighbors(self, ids, count):
        """Weighted-with-replacement neighbor sampling; nodes without
        edges get -1 padding (reference graph sampling semantics)."""
        ids = np.asarray(ids).reshape(-1)
        out = np.full((len(ids), count), -1, np.int64)
        with self.lock:
            for row, i in enumerate(ids):
                nbrs = self.adj.get(int(i))
                if not nbrs or not nbrs[0]:
                    continue
                d = np.asarray(nbrs[0], np.int64)
                w = np.asarray(nbrs[1], np.float64)
                p = w / w.sum()
                out[row] = self._rs.choice(d, size=count, replace=True,
                                           p=p)
        return out

    def random_nodes(self, count):
        with self.lock:
            pool = np.asarray(sorted(self.adj), np.int64)
        if len(pool) == 0:
            return np.zeros((0,), np.int64)
        return self._rs.choice(pool, size=min(count, len(pool)),
                               replace=False)

    def state(self):
        with self.lock:
            return {"feat_dim": self.feat_dim, "adj": dict(self.adj),
                    "feats": dict(self.feats),
                    "rs": self._rs.get_state()}

    def load_state(self, s):
        self.feat_dim = s["feat_dim"]
        self.adj = s["adj"]
        self.feats = s["feats"]
        self._rs.set_state(s["rs"])


class _Handler(socketserver.BaseRequestHandler):
    def handle(self):
        server = self.server.ps  # type: PSServer
        while True:
            req = recv_msg(self.request)
            if req is None:
                return
            try:
                resp = server._dispatch(req)
            except Exception as e:  # noqa: BLE001 — serve errors to client
                resp = {"ok": False, "error": f"{type(e).__name__}: {e}"}
            send_msg(self.request, resp)
            if req.get("cmd") == "stop":
                return


class _TCP(socketserver.ThreadingTCPServer):
    allow_reuse_address = True
    daemon_threads = True


class PSServer:
    """Reference: BrpcPsServer — start() binds and serves until stop().
    Tables are created by client request or locally."""

    def __init__(self, host="127.0.0.1", port=0):
        self._srv = _TCP((host, port), _Handler)
        self._srv.ps = self
        self.host, self.port = self._srv.server_address
        self.tables = {}
        self._shuffle = {}        # dest rank -> list of sample blobs
        self._shuffle_lock = threading.Lock()
        self._barrier_count = 0
        self._barrier_gen = 0
        self._barrier_cv = threading.Condition()
        self._thread = None

    # -- lifecycle --------------------------------------------------------
    def start(self):
        self._thread = threading.Thread(target=self._srv.serve_forever,
                                        daemon=True)
        self._thread.start()
        return self

    def stop(self):
        self._srv.shutdown()
        self._srv.server_close()

    def run(self):
        """Blocking serve (server-process entry, reference
        run_server)."""
        self._srv.serve_forever()

    # -- dispatch ---------------------------------------------------------
    def _dispatch(self, req):
        cmd = req.get("cmd")
        if cmd == "create_dense":
            # first creation wins: concurrent trainers racing to create
            # the same table must NOT wipe each other's pushes
            if req["table_id"] not in self.tables:
                self.tables[req["table_id"]] = DenseTable(
                    req.get("shape"),
                    optimizer=req.get("optimizer", "sgd"),
                    lr=req.get("lr", 0.01), init=req.get("init"),
                    seed=req.get("seed", 0))
                return {"ok": True, "created": True}
            return {"ok": True, "created": False}
        if cmd == "create_sparse":
            if req["table_id"] not in self.tables:
                if req.get("ssd"):
                    # an explicit path is broadcast to every server:
                    # give each shard its own subdir or they would
                    # overwrite each other's record slots
                    path = req.get("path")
                    if path is not None:
                        path = os.path.join(path, f"shard_{self.port}")
                    self.tables[req["table_id"]] = SSDSparseTable(
                        req["dim"], optimizer=req.get("optimizer", "sgd"),
                        lr=req.get("lr", 0.01), seed=req.get("seed", 0),
                        cache_rows=req.get("cache_rows", 4096),
                        path=path)
                else:
                    self.tables[req["table_id"]] = SparseTable(
                        req["dim"], optimizer=req.get("optimizer", "sgd"),
                        lr=req.get("lr", 0.01), seed=req.get("seed", 0))
                return {"ok": True, "created": True}
            return {"ok": True, "created": False}
        if cmd == "create_graph":
            if req["table_id"] not in self.tables:
                self.tables[req["table_id"]] = GraphTable(
                    feat_dim=req.get("feat_dim", 0),
                    seed=req.get("seed", 0))
                return {"ok": True, "created": True}
            return {"ok": True, "created": False}
        if cmd == "graph_add_edges":
            self.tables[req["table_id"]].add_edges(
                req["src"], req["dst"], req.get("weights"))
            return {"ok": True}
        if cmd == "graph_set_feat":
            self.tables[req["table_id"]].set_node_feat(req["ids"],
                                                       req["feats"])
            return {"ok": True}
        if cmd == "graph_get_feat":
            return {"ok": True,
                    "feats": self.tables[req["table_id"]].get_node_feat(
                        req["ids"])}
        if cmd == "graph_sample":
            return {"ok": True,
                    "neighbors": self.tables[
                        req["table_id"]].sample_neighbors(
                        req["ids"], req["count"])}
        if cmd == "graph_random_nodes":
            return {"ok": True,
                    "nodes": self.tables[req["table_id"]].random_nodes(
                        req["count"])}
        if cmd == "pull_dense":
            return {"ok": True, "value": self.tables[req["table_id"]].pull()}
        if cmd == "push_dense":
            self.tables[req["table_id"]].push(req["grad"])
            return {"ok": True}
        if cmd == "set_dense":
            self.tables[req["table_id"]].set(req["value"])
            return {"ok": True}
        if cmd == "pull_sparse":
            return {"ok": True,
                    "rows": self.tables[req["table_id"]].pull(req["ids"])}
        if cmd == "push_sparse":
            self.tables[req["table_id"]].push(req["ids"], req["grads"])
            return {"ok": True}
        if cmd == "save":
            state = {tid: t.state() for tid, t in self.tables.items()}
            kinds = {tid: type(t).__name__ for tid, t in self.tables.items()}
            import pickle
            with open(req["path"], "wb") as f:
                pickle.dump({"state": state, "kinds": kinds}, f)
            return {"ok": True}
        if cmd == "load":
            import pickle
            with open(req["path"], "rb") as f:
                data = pickle.load(f)
            for tid, s in data["state"].items():
                cls = {"DenseTable": DenseTable,
                       "SparseTable": SparseTable,
                       "SSDSparseTable": SSDSparseTable,
                       "GraphTable": GraphTable}[
                    data["kinds"][tid]]
                t = cls.__new__(cls)
                t.lock = threading.Lock()
                if cls is not DenseTable:
                    t._rs = np.random.RandomState(0)
                t.load_state(s)
                self.tables[tid] = t
            return {"ok": True}
        if cmd == "barrier":
            n = req["trainers"]
            timeout = float(req.get("timeout", 60.0))
            with self._barrier_cv:
                gen = self._barrier_gen
                self._barrier_count += 1
                if self._barrier_count >= n:
                    self._barrier_count = 0
                    self._barrier_gen += 1
                    self._barrier_cv.notify_all()
                    return {"ok": True}
                released = self._barrier_cv.wait_for(
                    lambda: self._barrier_gen != gen, timeout=timeout)
                if not released:
                    # roll back so a retry doesn't count this waiter twice
                    self._barrier_count = max(0, self._barrier_count - 1)
                    return {"ok": False,
                            "error": f"barrier timeout after {timeout}s "
                                     f"waiting for {n} trainers"}
            return {"ok": True}
        if cmd == "shuffle_put":
            # global-shuffle exchange (reference: InMemoryDataFeed
            # GlobalShuffle over brpc channels, data_feed.h:395): workers
            # deposit sample blobs addressed to a destination rank
            with self._shuffle_lock:
                self._shuffle.setdefault(req["dest"], []).extend(
                    req["blobs"])
            return {"ok": True}
        if cmd == "shuffle_take":
            with self._shuffle_lock:
                blobs = self._shuffle.pop(req["rank"], [])
            return {"ok": True, "blobs": blobs}
        if cmd == "stop":
            threading.Thread(target=self.stop, daemon=True).start()
            return {"ok": True}
        if cmd == "ping":
            return {"ok": True, "tables": sorted(self.tables)}
        raise ValueError(f"unknown command {cmd!r}")


def run_server_forever(host="127.0.0.1", port=0, ready_file=None):
    """Server-process entry: binds, optionally writes 'host:port' to
    ready_file, serves until stop (reference: the listen_and_serv op)."""
    srv = PSServer(host, port)
    if ready_file:
        tmp = ready_file + ".tmp"
        with open(tmp, "w") as f:
            f.write(f"{srv.host}:{srv.port}")
        os.rename(tmp, ready_file)
    srv.run()
