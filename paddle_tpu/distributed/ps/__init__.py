"""Parameter-server subsystem with a real remote path.

Reference parity: paddle/fluid/distributed/service/brpc_ps_server.h /
brpc_ps_client.h (pull/push dense & sparse over RPC),
distributed/service/communicator.h:197 (Communicator, AsyncCommunicator
:348, GeoCommunicator :497), distributed/table/ (CommonDenseTable,
CommonSparseTable). SURVEY §7.7 allows a reduced-scope equivalent; this
one is reduced in TRANSPORT (length-prefixed pickle over TCP sockets
instead of baidu-rpc + protobuf) but keeps the architecture: standalone
server processes own sharded tables, trainer clients pull/push over the
network, and sync/async/geo communication modes change when and how
gradients reach the server.

TPU-native division of labor: the dense compute path stays on
XLA devices; the PS serves what does NOT fit or belongs host-side —
huge sparse embeddings — exactly the reference's CPU-parameter-server
role next to GPU trainers.
"""
from .server import PSServer, DenseTable, SparseTable  # noqa: F401
from .client import PSClient  # noqa: F401
from .communicator import (  # noqa: F401
    Communicator, AsyncCommunicator, GeoCommunicator)


class PSEmbedding:
    """Trainer-side embedding over a REMOTE sparse table: forward pulls
    rows (autograd-cut at the pull, like the reference DownpourWorker's
    pull), backward grads on the pulled rows are pushed back via the
    communicator (reference: distributed_lookup_table_op +
    fleet_wrapper.h:69 PullSparse/PushSparseGrad)."""

    def __init__(self, client, table_id, dim, communicator=None):
        self.client = client
        self.table_id = table_id
        self.dim = int(dim)
        self.comm = communicator or Communicator(client)
        self._last = None

    def __call__(self, ids):
        import jax.numpy as jnp
        from ...core.tensor import Tensor
        ids_np = ids.numpy() if isinstance(ids, Tensor) else ids
        rows = self.client.pull_sparse(self.table_id, ids_np)
        rows = rows.reshape(tuple(ids_np.shape) + (self.dim,))
        pulled = Tensor(jnp.asarray(rows))
        pulled.stop_gradient = False
        self._last = (ids_np, pulled)
        return pulled

    def apply_push(self):
        if self._last is None:
            return
        ids_np, pulled = self._last
        if pulled._grad is not None:
            g = pulled._grad.value
            self.comm.send_sparse(
                self.table_id, ids_np.reshape(-1),
                __import__("numpy").asarray(g).reshape(-1, self.dim))
        self._last = None
